"""CandidateCache: the delta-aware candidate structure behind warm solves.

Covers the cache invariants the matcher's correctness rests on: row
stability, departure masking, spec-change retirement, new-provider merge
into cached lists, task deltas, vocab growth, and compaction rebuild
(SURVEY §7 hard part 4; VERDICT r2 item 3).
"""

import numpy as np

from protocol_tpu.models import ComputeRequirements, ComputeSpecs, CpuSpecs, GpuSpecs
from protocol_tpu.ops.cost import CostWeights
from protocol_tpu.ops.encoding import FeatureEncoder
from protocol_tpu.sched.cand_cache import CandidateCache, ProviderItem, TaskItem


def specs(model="H100", price_dummy=0):
    return ComputeSpecs(
        gpu=GpuSpecs(count=8, model=model, memory_mb=80000),
        cpu=CpuSpecs(cores=32),
        ram_mb=65536,
        storage_gb=1000,
    )


def pitem(addr, model="H100", price=0.0, loc=None):
    return ProviderItem(addr=addr, specs=specs(model), location=loc, price=price)


def titem(tid, take, req=""):
    return TaskItem(
        task_id=tid,
        requirement=ComputeRequirements.parse(req) if req else ComputeRequirements(),
        take=take,
    )


def mk_cache(k=8, **kw):
    return CandidateCache(FeatureEncoder(), CostWeights(priority=1.0), k=k, **kw)


class TestProviderRegistry:
    def test_rows_stable_across_solves(self):
        c = mk_cache()
        provs = [pitem(f"0x{i}") for i in range(6)]
        ts = [titem("t1", 4)]
        p1 = c.prepare(provs, ts)
        assert p1.rebuilt
        p2 = c.prepare(provs, ts)
        assert not p2.rebuilt
        assert p2.delta_rows == 0 and p2.delta_tasks == 0
        assert p1.row_of_addr == p2.row_of_addr

    def test_departure_masks_row(self):
        c = mk_cache()
        provs = [pitem(f"0x{i}") for i in range(6)]
        ts = [titem("t1", 4)]
        c.prepare(provs, ts)
        gone = provs[0].addr
        p2 = c.prepare(provs[1:], ts)
        assert gone not in p2.row_of_addr
        # the departed row must not appear in any candidate list
        live_rows = set(p2.row_of_addr.values())
        cand = p2.cand_p[: p2.num_slots]
        assert set(cand[cand >= 0].tolist()) <= live_rows

    def test_spec_change_retires_row(self):
        c = mk_cache()
        provs = [pitem(f"0x{i}") for i in range(4)]
        ts = [titem("t1", 2, req="gpu:model=H100")]
        p1 = c.prepare(provs, ts)
        old_row = p1.row_of_addr["0x0"]
        changed = [pitem("0x0", model="RTX4090")] + provs[1:]
        p2 = c.prepare(changed, ts)
        new_row = p2.row_of_addr["0x0"]
        assert new_row != old_row
        # the retired H100 row is gone from the H100-only candidates, and
        # the RTX row must not enter them
        cand = p2.cand_p[: p2.num_slots]
        assert old_row not in set(cand[cand >= 0].tolist())
        assert new_row not in set(cand[cand >= 0].tolist())

    def test_compaction_rebuild_after_mass_departure(self):
        c = mk_cache(max_invalid_frac=0.25)
        provs = [pitem(f"0x{i}") for i in range(8)]
        ts = [titem("t1", 4)]
        c.prepare(provs, ts)
        p2 = c.prepare(provs[:4], ts)  # 50% departed > 25%
        assert p2.rebuilt
        assert p2.num_rows == 4


class TestCandidateMaintenance:
    def test_new_cheap_provider_merges_into_cached_list(self):
        c = mk_cache(k=4)
        provs = [pitem(f"0x{i}", price=10.0) for i in range(6)]
        ts = [titem("t1", 3)]
        c.prepare(provs, ts)
        cheap = pitem("0xcheap", price=0.5)
        p2 = c.prepare(provs + [cheap], ts)
        assert p2.delta_rows == 1 and p2.delta_tasks == 0
        row = p2.row_of_addr["0xcheap"]
        cand = p2.cand_p[: p2.num_slots]
        assert row in set(cand[cand >= 0].tolist())
        # and it ranks FIRST (cheapest) in every slot's list
        assert (cand[:, 0] == row).all()

    def test_new_task_computed_fresh(self):
        c = mk_cache()
        provs = [pitem(f"0x{i}") for i in range(6)]
        c.prepare(provs, [titem("t1", 2)])
        p2 = c.prepare(provs, [titem("t1", 2), titem("t2", 3)])
        assert p2.delta_tasks == 1
        assert p2.num_slots == 5
        assert (p2.cand_p[:5] >= 0).any(axis=1).all()

    def test_replica_growth_recomputes_task(self):
        c = mk_cache()
        provs = [pitem(f"0x{i}") for i in range(6)]
        c.prepare(provs, [titem("t1", 2)])
        p2 = c.prepare(provs, [titem("t1", 5)])
        assert p2.delta_tasks == 1
        assert p2.num_slots == 5

    def test_requirement_change_recomputes_task(self):
        c = mk_cache()
        provs = [pitem(f"0x{i}") for i in range(6)]
        c.prepare(provs, [titem("t1", 2)])
        p2 = c.prepare(provs, [titem("t1", 2, req="gpu:model=H100")])
        assert p2.delta_tasks == 1

    def test_vocab_growth_invalidates_requirement_masks(self):
        c = mk_cache()
        provs = [pitem(f"0x{i}", model="H100") for i in range(4)]
        ts = [titem("t1", 2, req="gpu:model=A100")]  # no A100 yet
        p1 = c.prepare(provs, ts)
        assert (p1.cand_p[: p1.num_slots] == -1).all()  # nothing compatible
        # an A100 provider arrives: new vocab entry -> cached mask is stale
        # and must be recomputed so the task can now match
        p2 = c.prepare(provs + [pitem("0xa100", model="A100")], ts)
        row = p2.row_of_addr["0xa100"]
        cand = p2.cand_p[: p2.num_slots]
        assert row in set(cand[cand >= 0].tolist())

    def test_price_drift_updates_costs_without_delta(self):
        # trigger disabled: this test isolates the in-place price update
        # mechanism; a 1->5 flip on a 2-row fleet would (correctly) trip
        # the adaptive re-ground otherwise (TestAdaptiveReGround)
        c = mk_cache(max_stale_frac=None)
        provs = [pitem("0xa", price=1.0), pitem("0xb", price=2.0)]
        ts = [titem("t1", 1)]
        p1 = c.prepare(provs, ts)
        # flip prices: no rows re-encoded, but assembled costs reflect it
        p2 = c.prepare([pitem("0xa", price=5.0), pitem("0xb", price=2.0)], ts)
        assert p2.delta_rows == 0
        ra, rb = p2.row_of_addr["0xa"], p2.row_of_addr["0xb"]
        cand = p2.cand_p[0]
        costs = p2.cand_c[0]
        ca = costs[list(cand).index(ra)]
        cb = costs[list(cand).index(rb)]
        assert ca > cb  # 0xa now the pricier option


class TestPrices:
    def test_prices_survive_churn(self):
        c = mk_cache()
        provs = [pitem(f"0x{i}") for i in range(4)]
        ts = [titem("t1", 2)]
        p1 = c.prepare(provs, ts)
        price = np.zeros(p1.p_bucket, np.float32)
        price[p1.row_of_addr["0x1"]] = 3.5
        c.store_prices(price)
        p2 = c.prepare(provs + [pitem("0xnew")], ts)
        assert p2.price0[p2.row_of_addr["0x1"]] == 3.5
        assert p2.price0[p2.row_of_addr["0xnew"]] == 0.0


class TestCoverageRepair:
    """Valid rows absent from every cached top-k list get reverse edges in
    ``extra`` appended candidate columns (stage-B completeness on the warm
    path — the cached lists coverage-cap exactly like the forward-only
    cold generator; see ops/sparse.candidates_topk_reverse)."""

    def test_priced_out_rows_get_reverse_edges(self):
        c = mk_cache(k=2, reverse_r=4, extra=4)
        # identical specs, distinct prices: every task's top-2 is the same
        # two cheapest rows; the other six appear in no list
        provs = [pitem(f"0x{i}", price=float(i)) for i in range(8)]
        tasks = [titem(f"t{i}", 1) for i in range(8)]
        prep = c.prepare(provs, tasks)
        assert prep.uncovered_rows == 6
        assert prep.cand_p.shape[1] == 2 + 4  # k + extra columns
        covered = np.unique(prep.cand_p[prep.cand_p >= 0])
        valid = np.flatnonzero(c.cols["valid"][: c.rows])
        assert set(valid.tolist()) <= set(covered.tolist())

    def test_full_coverage_emits_no_extras(self):
        c = mk_cache(k=8, reverse_r=4, extra=4)
        provs = [pitem(f"0x{i}", price=float(i)) for i in range(6)]
        tasks = [titem("t0", 2)]
        prep = c.prepare(provs, tasks)
        # k=8 >= P: every row is in the task's list already
        assert prep.uncovered_rows == 0
        assert (prep.cand_p[:, 8:] == -1).all()

    def test_repair_costs_are_current_and_priority_adjusted(self):
        c = mk_cache(k=1, reverse_r=2, extra=2)
        provs = [pitem("0xcheap", price=0.0), pitem("0xdear", price=5.0)]
        t = titem("t0", 1)
        t.prio = 2.0
        prep = c.prepare(provs, [t])
        row_dear = c.row_of_addr["0xdear"]
        ex = prep.cand_p[0, 1:]
        pos = np.flatnonzero(ex == row_dear)
        assert pos.size == 1  # the priced-out row arrived via repair
        got = float(prep.cand_c[0, 1 + pos[0]])
        # exact current cost: base(price*w) + static - w_prio * prio,
        # matching the forward column decomposition (jitter is sub-1e-4)
        w = c.weights
        expect = w.price * 5.0 - w.priority * 2.0
        assert abs(got - expect) < 1e-3, (got, expect)

    def test_warm_solve_keeps_coverage_under_churn(self):
        c = mk_cache(k=2, reverse_r=4, extra=4)
        provs = [pitem(f"0x{i}", price=float(i)) for i in range(8)]
        tasks = [titem(f"t{i}", 1) for i in range(8)]
        c.prepare(provs, tasks)
        # churn: one cheap row departs, one expensive row joins
        provs = provs[1:] + [pitem("0xnew", price=9.0)]
        prep = c.prepare(provs, tasks)
        covered = np.unique(prep.cand_p[prep.cand_p >= 0])
        valid = np.flatnonzero(c.cols["valid"][: c.rows])
        assert set(valid.tolist()) <= set(covered.tolist())


class TestAdaptiveReGround:
    """VERDICT r3 item 10: cold re-grounds triggered by MEASURED selection
    staleness (base drift re-ranking the fleet), not only a fixed solve
    counter. Uniform drift (inflation) must NOT trigger; re-ranking drift
    must — and the rebuilt selection must see the re-ranked order."""

    def _fleet(self, c, prices):
        return [
            pitem(f"0x{i}", price=float(p)) for i, p in enumerate(prices)
        ]

    def test_uniform_inflation_does_not_rebuild(self):
        c = mk_cache(k=2, max_stale_frac=0.10)
        tasks = [titem("t0", 1)]
        c.prepare(self._fleet(c, [1, 2, 3, 4, 5, 6]), tasks)
        # +100 on EVERY provider: ranking unchanged, selection still valid
        prep = c.prepare(self._fleet(c, [101, 102, 103, 104, 105, 106]), tasks)
        assert not prep.rebuilt
        assert prep.stale_frac == 0.0

    def test_reranking_drift_rebuilds_and_selection_follows(self):
        c = mk_cache(k=2, max_stale_frac=0.10)
        tasks = [titem("t0", 1)]
        prep0 = c.prepare(self._fleet(c, [1, 2, 3, 4, 5, 6]), tasks)
        # rows 0-1 (the cached top-2) get expensive; row 5 becomes cheapest.
        # In-place price updates alone keep the OLD rows in the list —
        # the re-ranked fleet must trip the drift trigger instead.
        new_prices = [50, 60, 3, 4, 5, 0.5]
        prep = c.prepare(self._fleet(c, new_prices), tasks)
        assert prep.stale_frac > 0.10
        assert prep.rebuilt
        cheap_row = c.row_of_addr["0x5"]
        assert cheap_row in prep.cand_p[0], (
            "rebuilt selection must include the now-cheapest provider"
        )

    def test_staleness_cost_at_the_boundary(self):
        """Quantifies what the trigger buys: with the trigger disabled the
        stale top-k misses the now-cheapest provider entirely (selection
        cost strictly higher); with it enabled the solve sees it."""
        tasks = [titem("t0", 1)]
        prices0 = [1, 2, 3, 4, 5, 6]
        new_prices = [50, 60, 3, 4, 5, 0.5]

        frozen = mk_cache(k=2, max_stale_frac=None)  # trigger disabled
        frozen.prepare(self._fleet(frozen, prices0), tasks)
        prep_frozen = frozen.prepare(self._fleet(frozen, new_prices), tasks)
        adaptive = mk_cache(k=2, max_stale_frac=0.10)
        adaptive.prepare(self._fleet(adaptive, prices0), tasks)
        prep_adapt = adaptive.prepare(self._fleet(adaptive, new_prices), tasks)

        def best_cost(prep):
            cp = prep.cand_p[0]
            return float(np.min(prep.cand_c[0][cp >= 0]))

        assert not prep_frozen.rebuilt and prep_adapt.rebuilt
        # stale list holds rows 0-1 at prices 50/60 (+ coverage-repair
        # extras); adaptive re-selected and found the 0.5 provider
        assert best_cost(prep_adapt) < best_cost(prep_frozen)
        cheap_row = adaptive.row_of_addr["0x5"]
        assert cheap_row in prep_adapt.cand_p[0]

    def test_backstop_counter_still_exists(self):
        from protocol_tpu.sched.tpu_backend import TpuBatchMatcher
        from protocol_tpu.store import StoreContext

        m = TpuBatchMatcher(StoreContext.new_test())
        assert m.cold_every == 256  # schedule is the backstop, not the policy
        assert m._cache.max_stale_frac == 0.10


class TestCandidateMemo:
    """Content-hash memo for the stateless candidate paths (gRPC backend +
    wire-path matcher): exact repeats hit, any byte change misses."""

    def _instance(self, seed=0, P=64, T=64):
        from tests.test_sparse import encode_random_marketplace

        return encode_random_marketplace(seed, P, T)

    def test_repeat_hits_and_changed_input_misses(self):
        import dataclasses

        import jax.numpy as jnp
        import numpy as np

        from protocol_tpu.ops.cost import CostWeights
        from protocol_tpu.sched.cand_cache import CandidateMemo

        memo = CandidateMemo()
        ep, er = self._instance()
        kw = dict(k=8, tile=16, reverse_r=4, extra=4)
        cp1, cc1 = memo.get(ep, er, CostWeights(), **kw)
        cp2, cc2 = memo.get(ep, er, CostWeights(), **kw)
        assert memo.hits == 1 and memo.misses == 1
        assert cp1 is cp2 and cc1 is cc2
        # one changed price byte -> miss, and the result reflects it
        ep2 = dataclasses.replace(
            ep, price=jnp.asarray(np.asarray(ep.price) + 1.0)
        )
        memo.get(ep2, er, CostWeights(), **kw)
        assert memo.misses == 2
        # different generation params are different keys
        memo.get(ep, er, CostWeights(), k=8, tile=16, reverse_r=4, extra=8)
        assert memo.misses == 3

    def test_capacity_evicts_lru(self):
        from protocol_tpu.ops.cost import CostWeights
        from protocol_tpu.sched.cand_cache import CandidateMemo

        memo = CandidateMemo(capacity=2)
        kw = dict(k=8, tile=16, reverse_r=4, extra=4)
        a = self._instance(1)
        b = self._instance(2)
        c = self._instance(3)
        memo.get(*a, CostWeights(), **kw)
        memo.get(*b, CostWeights(), **kw)
        memo.get(*a, CostWeights(), **kw)  # refresh a
        memo.get(*c, CostWeights(), **kw)  # evicts b (LRU)
        memo.get(*a, CostWeights(), **kw)
        assert memo.hits == 2  # a hit twice; b/c were misses
        memo.get(*b, CostWeights(), **kw)  # b was evicted -> miss
        assert memo.misses == 4


class TestDirtySlots:
    """PreparedSolve.dirty_slots: the warm-retirement-carry invalidation
    signal (ADVICE r5 — a task must not stay retired after churn changes
    its candidate list)."""

    def test_first_prepare_reports_none(self):
        cache = mk_cache()
        prep = cache.prepare([pitem("a"), pitem("b")], [titem("t", 2)])
        assert prep.dirty_slots is None  # no reference yet: all-dirty

    def test_unchanged_population_is_clean(self):
        cache = mk_cache()
        providers = [pitem("a"), pitem("b")]
        tasks = [titem("t", 2)]
        cache.prepare(providers, tasks)
        prep = cache.prepare(providers, tasks)
        assert prep.dirty_slots is not None
        assert not prep.dirty_slots.any()

    def test_new_provider_dirties_merged_slots(self):
        cache = mk_cache()
        tasks = [titem("t", 2)]
        cache.prepare([pitem("a"), pitem("b")], tasks)
        prep = cache.prepare([pitem("a"), pitem("b"), pitem("c")], tasks)
        # k=8 > fleet: the newcomer enters every slot's list
        assert prep.dirty_slots is not None
        assert prep.dirty_slots[: prep.num_slots].all()

    def test_departure_dirties_slots(self):
        cache = mk_cache()
        tasks = [titem("t", 2)]
        fleet = [pitem(f"p{i}") for i in range(8)]
        cache.prepare(fleet, tasks)
        # one departure stays under the compaction threshold (no rebuild:
        # dirty_slots must come from the content comparison, not a reset)
        prep = cache.prepare(fleet[:-1], tasks)
        assert not prep.rebuilt
        assert prep.dirty_slots is not None
        assert prep.dirty_slots[: prep.num_slots].all()
