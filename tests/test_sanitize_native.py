"""Sanitizer-build plumbing tests (hermetic — nothing here runs under an
actual sanitizer; the TSan/ASan stress itself is scripts/sanitize_native.py,
gated in CI and too slow for tier-1)."""

import os

import numpy as np
import pytest

from protocol_tpu import native
from scripts.sanitize_native import (
    _REPORT_MARKERS,
    _clang_tidy,
    _synth_marketplace,
)


class TestVariantSelection:
    def test_default_is_plain(self, monkeypatch):
        monkeypatch.delenv("PROTOCOL_TPU_NATIVE_SANITIZE", raising=False)
        assert native.sanitize_variant() == ""

    @pytest.mark.parametrize("value,expect", [
        ("tsan", "tsan"), ("asan", "asan"), ("TSAN", "tsan"),
        ("", ""), ("off", ""), ("none", ""), ("0", ""),
    ])
    def test_env_values(self, monkeypatch, value, expect):
        monkeypatch.setenv("PROTOCOL_TPU_NATIVE_SANITIZE", value)
        assert native.sanitize_variant() == expect

    def test_garbage_value_is_refused(self, monkeypatch):
        monkeypatch.setenv("PROTOCOL_TPU_NATIVE_SANITIZE", "msan")
        with pytest.raises(native.NativeBuildError):
            native.sanitize_variant()

    def test_variant_so_names_are_distinct(self):
        paths = {native.so_path(v) for v in ("", "tsan", "asan")}
        assert len(paths) == 3
        assert all(p.endswith(".so") for p in paths)


class TestBuildFlags:
    def test_production_flags_honor_native_cflags(self, monkeypatch):
        monkeypatch.setenv("NATIVE_CFLAGS", "-O2 -funroll-loops")
        assert native._cflags("") == ["-O2", "-funroll-loops"]

    def test_default_is_portable_not_march_native(self, monkeypatch):
        monkeypatch.delenv("NATIVE_CFLAGS", raising=False)
        flags = native._cflags("")
        assert "-march=native" not in flags
        assert "-march=x86-64-v2" in flags

    @pytest.mark.parametrize("variant,needle", [
        ("tsan", "-fsanitize=thread"),
        ("asan", "-fsanitize=address,undefined"),
    ])
    def test_sanitizer_flags(self, monkeypatch, variant, needle):
        monkeypatch.delenv("NATIVE_CFLAGS", raising=False)
        flags = native._cflags(variant)
        assert needle in flags
        # -O1 -g replaces the production opt level: reports need symbols
        assert "-O1" in flags and "-g" in flags
        assert "-O3" not in flags

    def test_sanitizer_flags_strip_march_native_from_overrides(self, monkeypatch):
        monkeypatch.setenv("NATIVE_CFLAGS", "-O3 -march=native")
        flags = native._cflags("tsan")
        assert "-march=native" not in flags and "-O3" not in flags

    def test_unknown_variant_is_refused(self):
        with pytest.raises(native.NativeBuildError):
            native.build("msan")


class TestStressHarnessInputs:
    def test_synth_marketplace_duck_types_the_encoder_columns(self):
        rng = np.random.default_rng(0)
        ep, er, w = _synth_marketplace(rng, 64, 48)
        # every column the C++ feature structs dereference must exist
        # with population-length leading axes
        from protocol_tpu.native.arena import _P_SPEC, _R_SPEC

        for name, _ in _P_SPEC:
            assert getattr(ep, name).shape[0] == 64, name
        for name, _ in _R_SPEC:
            assert getattr(er, name).shape[0] == 48, name
        assert er.gpu_model_mask.ndim == 3
        for attr in ("price", "load", "proximity", "priority"):
            assert isinstance(getattr(w, attr), float)

    @pytest.mark.skipif(
        not native.available(), reason="no native toolchain in this env"
    )
    def test_synth_marketplace_is_solvable(self):
        """The stress population must be bench-shaped (mostly feasible):
        an accidentally-adversarial population burns the sanitizer budget
        on give-up bidding wars instead of kernel coverage."""
        rng = np.random.default_rng(7)
        ep, er, w = _synth_marketplace(rng, 256, 256)
        cp, cc = native.fused_topk_candidates(ep, er, w, k=24, threads=1)
        p4t, _, _ = native.auction_sparse_mt(cp, cc, num_providers=256, threads=1)
        assert int((p4t >= 0).sum()) >= 250

    def test_report_markers_cover_all_sanitizer_families(self):
        text = "\n".join(_REPORT_MARKERS)
        for fam in ("ThreadSanitizer", "AddressSanitizer", "LeakSanitizer",
                    "runtime error"):
            assert fam in text


class TestClangTidyMandatory:
    """The static pass is pinned and non-optional (ISSUE 10 satellite):
    a missing clang-tidy binary must FAIL the harness, not skip — the
    old behavior let the gate silently rot off-CI."""

    def test_missing_clang_tidy_fails(self, monkeypatch):
        import scripts.sanitize_native as sn

        monkeypatch.setattr(sn.shutil, "which", lambda name: None)
        lines = []
        assert _clang_tidy(lines.append) is False
        assert any("mandatory" in ln for ln in lines)

    def test_ci_installs_and_runs_tidy_as_its_own_step(self):
        wf = open(os.path.join(
            os.path.dirname(__file__), "..",
            ".github", "workflows", "checks.yml",
        )).read()
        assert "clang-tidy" in wf
        # the workflow must INSTALL the toolchain (pinned step), and no
        # job may pass --skip-clang-tidy
        assert "apt-get install" in wf and "clang-tidy" in wf
        assert "--skip-clang-tidy" not in wf


class TestMakefileParity:
    def test_makefile_clean_removes_sanitizer_variants(self):
        mk = open(os.path.join(os.path.dirname(__file__), "..", "Makefile")).read()
        assert "libassign_engine.tsan.so" in mk.split("clean:")[1]
        assert "libassign_engine.asan.so" in mk.split("clean:")[1]

    def test_makefile_native_flags_match_python_builder(self):
        """Makefile and protocol_tpu.native must agree on the portable
        default — a drifted recipe ships a .so the other half would not
        reproduce."""
        mk = open(os.path.join(os.path.dirname(__file__), "..", "Makefile")).read()
        assert "NATIVE_CFLAGS ?= " + native._DEFAULT_CFLAGS in mk
