"""Ledger tests: the economic-substrate lifecycle the reference drives
through its contract wrappers (register -> stake -> add node -> validate ->
create/start pool -> signed invite join -> submit work -> invalidate)."""

import pytest

# Environment guard: this module's import chain reaches
# protocol_tpu.security / protocol_tpu.utils.tls, which need the
# third-party `cryptography` package (wallet signing + TLS material).
# On hosts without it, report the whole module as SKIPPED instead of a
# collection error (tier-1 keeps an honest skip count; CI installs
# cryptography and runs everything).
pytest.importorskip(
    "cryptography", reason="cryptography not installed (signing/TLS dependency)"
)

import time

import pytest

from protocol_tpu.chain import Ledger, LedgerError, PoolStatus
from protocol_tpu.chain.ledger import invite_digest
from protocol_tpu.security import EvmRecoveryWallet, EvmWallet, Wallet


@pytest.fixture(
    params=[Wallet, EvmWallet, EvmRecoveryWallet],
    ids=["ed25519", "evm", "evm-recovery"],
)
def world(request):
    ledger = Ledger(min_stake_per_compute_unit=10)
    provider = request.param.from_seed(b"provider")
    node = request.param.from_seed(b"node")
    manager = request.param.from_seed(b"pool-manager")
    ledger.mint(provider.address, 1000)
    did = ledger.create_domain("synthetic-data", validation_logic="toploc")
    pid = ledger.create_pool(did, provider.address, manager.address, "gpu:count=1")
    return ledger, provider, node, manager, did, pid


def join(ledger, manager, pid, node, provider):
    exp = time.time() + 60
    sig = manager.sign_message(
        invite_digest(ledger.get_pool_info(pid).domain_id, pid, node.address, "n0nce", exp)
    )
    ledger.join_compute_pool(pid, provider.address, node.address, "n0nce", exp, sig)


class TestTokenAndStake:
    def test_mint_transfer(self, world):
        ledger, provider, *_ = world
        assert ledger.balance_of(provider.address) == 1000
        ledger.transfer(provider.address, "0xother", 100)
        assert ledger.balance_of("0xother") == 100

    def test_register_provider_takes_stake(self, world):
        ledger, provider, *_ = world
        ledger.register_provider(provider.address, 100)
        assert ledger.get_stake(provider.address) == 100
        assert ledger.balance_of(provider.address) == 900

    def test_register_requires_balance_and_minimum(self, world):
        ledger, provider, *_ = world
        with pytest.raises(LedgerError):
            ledger.register_provider("0xpoor", 100)
        with pytest.raises(LedgerError):
            ledger.register_provider(provider.address, 5)  # below min

    def test_reclaim_respects_node_requirements(self, world):
        ledger, provider, node, *_ = world
        ledger.register_provider(provider.address, 100)
        ledger.add_compute_node(provider.address, node.address)
        with pytest.raises(LedgerError):
            ledger.reclaim_stake(provider.address, 95)
        ledger.reclaim_stake(provider.address, 80)
        assert ledger.get_stake(provider.address) == 20


class TestNodesAndPools:
    def test_full_join_flow(self, world):
        ledger, provider, node, manager, did, pid = world
        ledger.register_provider(provider.address, 100)
        ledger.add_compute_node(provider.address, node.address)
        ledger.validate_node(node.address)
        ledger.start_pool(pid, provider.address)
        join(ledger, manager, pid, node, provider)
        assert ledger.is_node_in_pool(pid, node.address)

    def test_join_requires_validation(self, world):
        ledger, provider, node, manager, did, pid = world
        ledger.register_provider(provider.address, 100)
        ledger.add_compute_node(provider.address, node.address)
        ledger.start_pool(pid, provider.address)
        with pytest.raises(LedgerError, match="not validated"):
            join(ledger, manager, pid, node, provider)

    def test_join_requires_valid_signature(self, world):
        ledger, provider, node, manager, did, pid = world
        ledger.register_provider(provider.address, 100)
        ledger.add_compute_node(provider.address, node.address)
        ledger.validate_node(node.address)
        ledger.start_pool(pid, provider.address)
        rogue = Wallet.from_seed(b"rogue")
        exp = time.time() + 60
        sig = rogue.sign_message(invite_digest(did, pid, node.address, "n0nce", exp))
        with pytest.raises(LedgerError, match="invalid invite"):
            ledger.join_compute_pool(pid, provider.address, node.address, "n0nce", exp, sig)

    def test_join_rejects_expired_invite(self, world):
        ledger, provider, node, manager, did, pid = world
        ledger.register_provider(provider.address, 100)
        ledger.add_compute_node(provider.address, node.address)
        ledger.validate_node(node.address)
        ledger.start_pool(pid, provider.address)
        exp = time.time() - 1
        sig = manager.sign_message(invite_digest(did, pid, node.address, "n0nce", exp))
        with pytest.raises(LedgerError, match="expired"):
            ledger.join_compute_pool(pid, provider.address, node.address, "n0nce", exp, sig)

    def test_pool_must_be_active(self, world):
        ledger, provider, node, manager, did, pid = world
        ledger.register_provider(provider.address, 100)
        ledger.add_compute_node(provider.address, node.address)
        ledger.validate_node(node.address)
        with pytest.raises(LedgerError, match="not active"):
            join(ledger, manager, pid, node, provider)

    def test_eject_and_blacklist(self, world):
        ledger, provider, node, manager, did, pid = world
        ledger.register_provider(provider.address, 100)
        ledger.add_compute_node(provider.address, node.address)
        ledger.validate_node(node.address)
        ledger.start_pool(pid, provider.address)
        join(ledger, manager, pid, node, provider)

        ledger.eject_node(pid, node.address, manager.address)
        assert not ledger.is_node_in_pool(pid, node.address)

        ledger.blacklist_node(pid, node.address, manager.address)
        with pytest.raises(LedgerError, match="blacklisted"):
            join(ledger, manager, pid, node, provider)

    def test_eject_requires_authority(self, world):
        ledger, provider, node, manager, did, pid = world
        with pytest.raises(LedgerError, match="authorized"):
            ledger.eject_node(pid, node.address, "0xrandom")

    def test_stake_gates_node_count(self, world):
        ledger, provider, node, *_ = world
        ledger.register_provider(provider.address, 10)  # exactly 1 unit
        ledger.add_compute_node(provider.address, node.address)
        with pytest.raises(LedgerError, match="insufficient stake"):
            ledger.add_compute_node(provider.address, "0xsecond")


class TestWork:
    def _join(self, world):
        ledger, provider, node, manager, did, pid = world
        ledger.register_provider(provider.address, 100)
        ledger.add_compute_node(provider.address, node.address)
        ledger.validate_node(node.address)
        ledger.start_pool(pid, provider.address)
        join(ledger, manager, pid, node, provider)
        return ledger, node, pid

    def test_submit_and_query(self, world):
        ledger, node, pid = self._join(world)
        t0 = time.time()
        ledger.submit_work(pid, node.address, "sha-1", 500)
        assert ledger.get_work_keys(pid) == ["sha-1"]
        info = ledger.get_work_info(pid, "sha-1")
        assert info.work_units == 500
        assert ledger.get_rewards(node.address) == 500
        assert [w.work_key for w in ledger.get_work_since(pid, t0 - 1)] == ["sha-1"]

    def test_duplicate_work_key_rejected(self, world):
        ledger, node, pid = self._join(world)
        ledger.submit_work(pid, node.address, "sha-1", 500)
        with pytest.raises(LedgerError, match="already submitted"):
            ledger.submit_work(pid, node.address, "sha-1", 1)

    def test_submit_requires_pool_membership(self, world):
        ledger, provider, node, manager, did, pid = world
        with pytest.raises(LedgerError, match="unknown node|not in pool"):
            ledger.submit_work(pid, node.address, "sha-1", 1)

    def test_hard_invalidate_slashes(self, world):
        ledger, node, pid = self._join(world)
        ledger.submit_work(pid, node.address, "sha-1", 500)
        provider_addr = ledger.get_node(node.address).provider
        stake_before = ledger.get_stake(provider_addr)
        ledger.invalidate_work(pid, "sha-1", penalty=30)
        assert ledger.get_rewards(node.address) == 0
        assert ledger.get_stake(provider_addr) == stake_before - 30
        assert ledger.get_work_info(pid, "sha-1").invalidated

    def test_soft_invalidate_no_slash(self, world):
        ledger, node, pid = self._join(world)
        ledger.submit_work(pid, node.address, "sha-1", 500)
        provider_addr = ledger.get_node(node.address).provider
        stake_before = ledger.get_stake(provider_addr)
        ledger.soft_invalidate_work(pid, "sha-1")
        assert ledger.get_rewards(node.address) == 0
        assert ledger.get_stake(provider_addr) == stake_before
        assert ledger.get_work_info(pid, "sha-1").soft_invalidated


def test_snapshot_restore_round_trip(tmp_path):
    """Ledger state (balances, providers, nodes, pools incl. enum status
    and blacklist, work, roles, id counters) survives snapshot/restore —
    the dev substrate's equivalent of the reference's durable chain."""
    import time as _time

    from protocol_tpu.chain.ledger import PoolStatus, invite_digest
    from protocol_tpu.security import Wallet

    ledger = Ledger()
    creator, manager = Wallet.from_seed(b"sc"), Wallet.from_seed(b"sm")
    provider, node = Wallet.from_seed(b"sp"), Wallet.from_seed(b"sn")
    ledger.mint(provider.address, 500)
    did = ledger.create_domain("snap", validation_logic="toploc")
    pid = ledger.create_pool(did, creator.address, manager.address, "ram_mb=1")
    ledger.start_pool(pid, creator.address)
    ledger.register_provider(provider.address, 100)
    ledger.whitelist_provider(provider.address)
    ledger.add_compute_node(provider.address, node.address)
    ledger.validate_node(node.address)
    ledger.grant_validator_role("0xval")
    exp = _time.time() + 60
    sig = manager.sign_message(invite_digest(did, pid, node.address, "n", exp))
    ledger.join_compute_pool(pid, provider.address, node.address, "n", exp, sig)
    ledger.submit_work(pid, node.address, "ab" * 32, 9)
    ledger.soft_invalidate_work(pid, "ab" * 32)
    ledger.blacklist_node(pid, "0xbad", manager.address)

    path = str(tmp_path / "ledger.json")
    ledger.snapshot(path)
    restored = Ledger.restore(path)

    assert restored.balance_of(provider.address) == ledger.balance_of(provider.address)
    assert restored.get_pool_info(pid).status == PoolStatus.ACTIVE
    assert restored.get_pool_info(pid).blacklist == {"0xbad"}
    assert restored.is_node_in_pool(pid, node.address)
    assert restored.is_provider_whitelisted(provider.address)
    assert restored.is_node_validated(node.address)
    assert restored.get_validator_role() == ["0xval"]
    info = restored.get_work_info(pid, "ab" * 32)
    assert info.work_units == 9 and info.soft_invalidated
    # id counters continue, no collisions
    assert restored.create_domain("next") == did + 1
    assert (
        restored.create_pool(did, creator.address, manager.address, "") == pid + 1
    )
