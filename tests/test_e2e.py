"""End-to-end slice (BASELINE config #1 shape): a full in-process cluster —
ledger + discovery + orchestrator (TPU batch scheduler) + workers +
validator — wired over real localhost HTTP with signed requests.

Covers SURVEY.md §3 call stacks: worker boot/registration (3.1), invite
flow (3.2), discovery sync (3.3), heartbeat+scheduling hot loop (3.4),
work submission (3.5 tail), and validation (3.6).
"""

import pytest

# Environment guard: this module's import chain reaches
# protocol_tpu.security / protocol_tpu.utils.tls, which need the
# third-party `cryptography` package (wallet signing + TLS material).
# On hosts without it, report the whole module as SKIPPED instead of a
# collection error (tier-1 keeps an honest skip count; CI installs
# cryptography and runs everything).
pytest.importorskip(
    "cryptography", reason="cryptography not installed (signing/TLS dependency)"
)

import asyncio

import aiohttp
import pytest
from aiohttp.test_utils import TestServer

from protocol_tpu.chain import Ledger
from protocol_tpu.models import ComputeSpecs, CpuSpecs, GpuSpecs
from protocol_tpu.models.node import DiscoveryNode
from protocol_tpu.sched import Scheduler, TpuBatchMatcher
from protocol_tpu.security import Wallet, sign_request
from protocol_tpu.services.discovery import DiscoveryService
from protocol_tpu.services.orchestrator import OrchestratorService
from protocol_tpu.services.validator import (
    SyntheticDataValidator,
    ToplocClient,
    ValidationResult,
    ValidatorService,
)
from protocol_tpu.services.worker import MockRuntime, WorkerAgent
from protocol_tpu.store import NodeStatus, StoreContext
from protocol_tpu.utils.storage import MockStorageProvider

from tests.test_services import make_toploc_app

N_WORKERS = 8


def specs():
    return ComputeSpecs(
        gpu=GpuSpecs(count=8, model="NVIDIA H100 80GB HBM3", memory_mb=80000),
        cpu=CpuSpecs(cores=64),
        ram_mb=262144,
        storage_gb=4000,
    )


async def build_cluster(session: aiohttp.ClientSession, toploc_results: dict):
    ledger = Ledger()
    creator = Wallet.from_seed(b"creator")
    manager = Wallet.from_seed(b"manager")
    validator_wallet = Wallet.from_seed(b"validator")
    did = ledger.create_domain("synth", validation_logic="toploc")
    pid = ledger.create_pool(
        did, creator.address, manager.address, "gpu:count=8;gpu:model=H100"
    )
    ledger.start_pool(pid, creator.address)

    # ---- discovery
    discovery = DiscoveryService(ledger, pid)
    discovery_server = TestServer(discovery.make_app())
    await discovery_server.start_server()
    discovery_url = str(discovery_server.make_url(""))

    # ---- workers
    workers: list[WorkerAgent] = []
    worker_servers: list[TestServer] = []
    for i in range(N_WORKERS):
        provider = Wallet.from_seed(f"provider-{i}".encode())
        node = Wallet.from_seed(f"node-{i}".encode())
        ledger.mint(provider.address, 1000)
        agent = WorkerAgent(
            provider_wallet=provider,
            node_wallet=node,
            ledger=ledger,
            pool_id=pid,
            runtime=MockRuntime(),
            compute_specs=specs(),
            port=8091 + i,  # distinct endpoints: the duplicate-endpoint
            # defense (monitor rule 1) kills same-ip:port squatters
            http=session,
            known_orchestrators=[manager.address],
            known_validators=[validator_wallet.address],
        )
        assert agent.check_pool_requirements()
        agent.register_on_ledger()
        ledger.whitelist_provider(provider.address)  # admin onboarding step
        server = TestServer(agent.make_control_app())
        await server.start_server()
        control_url = str(server.make_url("/control"))
        agent.p2p_id = f"p2p-{i}"
        # advertise the real control URL in discovery
        agent.discovery_node_payload_orig = agent.discovery_node_payload
        agent.control_url = control_url
        workers.append(agent)
        worker_servers.append(server)

    # patch payloads to advertise live control URLs
    for agent in workers:
        orig = agent.discovery_node_payload_orig

        def payload(agent=agent, orig=orig):
            d = orig()
            d["worker_p2p_addresses"] = [agent.control_url]
            return d

        agent.discovery_node_payload = payload

    # ---- orchestrator
    store = StoreContext.new_test()
    matcher = TpuBatchMatcher(store, min_solve_interval=0.0)
    matcher.attach_observers()
    scheduler = Scheduler(store, batch_matcher=matcher)

    async def discovery_fetcher():
        headers, _ = sign_request(f"/api/pool/{pid}", manager)
        async with session.get(
            f"{discovery_url}/api/pool/{pid}", headers=headers
        ) as resp:
            data = await resp.json()
            return [DiscoveryNode.from_dict(d) for d in data.get("data", [])]

    async def invite_sender(node, payload):
        url = (node.p2p_addresses or [None])[0]
        if not url:
            return False
        headers, body = sign_request("/control/invite", manager, payload)
        async with session.post(f"{url}/invite", json=body, headers=headers) as resp:
            return resp.status == 200

    storage = MockStorageProvider()
    orchestrator = OrchestratorService(
        ledger,
        pid,
        manager,
        store=store,
        scheduler=scheduler,
        storage=storage,
        discovery_fetcher=discovery_fetcher,
        invite_sender=invite_sender,
    )
    orch_server = TestServer(orchestrator.make_app())
    await orch_server.start_server()
    orch_url = str(orch_server.make_url("")).rstrip("/")
    orchestrator.heartbeat_url = orch_url  # invites must carry the live URL

    # ---- validator
    toploc_server = TestServer(make_toploc_app(toploc_results))
    await toploc_server.start_server()

    async def validator_discovery_fetcher():
        headers, _ = sign_request("/api/validator", validator_wallet)
        async with session.get(
            f"{discovery_url}/api/validator", headers=headers
        ) as resp:
            data = await resp.json()
            return [DiscoveryNode.from_dict(d) for d in data.get("data", [])]

    synthetic = SyntheticDataValidator(
        ledger,
        pid,
        storage,
        [ToplocClient(str(toploc_server.make_url("")).rstrip("/"), session)],
    )
    validator = ValidatorService(
        validator_wallet,
        ledger,
        pid,
        synthetic=synthetic,
        discovery_fetcher=validator_discovery_fetcher,
        http=session,
        challenge_size=16,
    )

    servers = [discovery_server, orch_server, toploc_server] + worker_servers
    return {
        "ledger": ledger,
        "pid": pid,
        "manager": manager,
        "discovery": discovery,
        "discovery_url": discovery_url,
        "workers": workers,
        "orchestrator": orchestrator,
        "orch_url": orch_url,
        "validator": validator,
        "storage": storage,
        "servers": servers,
        "session": session,
    }


@pytest.fixture
def cluster_results():
    return {"out.parquet": {"status": "Accept", "output_flops": 777}}


def test_full_lifecycle(cluster_results):
    async def flow():
        async with aiohttp.ClientSession() as session:
            c = await build_cluster(session, cluster_results)
            ledger, pid = c["ledger"], c["pid"]
            workers, orchestrator, validator = (
                c["workers"],
                c["orchestrator"],
                c["validator"],
            )

            # 1. workers register with discovery (signed PUT, §3.1)
            for agent in workers:
                assert await agent.upload_to_discovery([c["discovery_url"]])

            # 2. validator: hardware-challenges unvalidated nodes (§3.6)
            stats = await validator.validation_loop_once()
            assert stats["validated_nodes"] == N_WORKERS

            # 3. discovery chain sync exposes validated nodes to the pool view
            assert c["discovery"].chain_sync_once() >= N_WORKERS

            # 4. orchestrator sees them, invites them (§3.2, §3.3)
            assert await orchestrator.discovery_monitor_once() == N_WORKERS
            assert await orchestrator.invite_once() == N_WORKERS
            for agent in workers:
                assert agent.heartbeat_active
                assert ledger.is_node_in_pool(pid, agent.node_wallet.address)

            # 5. operator submits a task (admin API)
            async with c["session"].post(
                f"{c['orch_url']}/tasks",
                json={"name": "synthesize", "image": "gen:latest"},
                headers={"Authorization": "Bearer admin"},
            ) as resp:
                assert resp.status == 201

            # 6. heartbeat loop (§3.4): first beats land, the status FSM
            # promotes WaitingForHeartbeat -> Healthy, and beats return the
            # scheduled task from the TPU batch matcher
            for agent in workers:
                await agent.heartbeat_once()
            await orchestrator.status_update_once()
            for agent in workers:
                node = orchestrator.store.node_store.get_node(
                    agent.node_wallet.address
                )
                assert node.status == NodeStatus.HEALTHY
                task = await agent.heartbeat_once()
                assert task is not None and task.name == "synthesize"
                assert agent.runtime.current.id == task.id

            # 7. a worker's workload reports output via the bridge path
            w0 = workers[0]
            w0.orchestrator_url = c["orch_url"]
            w0.metrics[("t", "loss")] = 0.5
            assert await w0.submit_output(sha="fa" * 32, flops=777, file_name="out.parquet")
            info = ledger.get_work_info(pid, "fa" * 32)
            assert info is not None and info.work_units == 777

            # 8. upload mapping exists; validator validates the work (§3.6)
            assert await c["storage"].resolve_mapping_for_sha("fa" * 32) == "out.parquet"
            await validator.validation_loop_once()  # trigger
            await validator.validation_loop_once()  # poll
            assert (
                validator.synthetic.get_status("fa" * 32) == ValidationResult.ACCEPT
            )
            assert not ledger.get_work_info(pid, "fa" * 32).invalidated

            # 9. metrics flowed through the heartbeat into the store
            for agent in workers:
                await agent.heartbeat_once()
            got = orchestrator.store.metrics_store.get_metrics_for_task("t")
            assert got == {"loss": {w0.node_wallet.address: 0.5}}

            # 10. health FSM: a worker stops beating -> Unhealthy -> Dead ->
            # ejected from the pool (§3.6 failure path)
            dead = workers[-1]
            orchestrator.store.heartbeat_store.clear_heartbeat(
                dead.node_wallet.address
            )
            for _ in range(3):
                await orchestrator.status_update_once()
                orchestrator.store.heartbeat_store.clear_heartbeat(
                    dead.node_wallet.address
                )
            node = orchestrator.store.node_store.get_node(dead.node_wallet.address)
            assert node.status == NodeStatus.DEAD
            assert not ledger.is_node_in_pool(pid, dead.node_wallet.address)

            for s in c["servers"]:
                await s.close()

    asyncio.new_event_loop().run_until_complete(flow())


def test_challenge_rejects_wrong_result(cluster_results):
    """A worker returning wrong matmul results must not be validated."""

    async def flow():
        async with aiohttp.ClientSession() as session:
            c = await build_cluster(session, cluster_results)
            agent = c["workers"][0]
            assert await agent.upload_to_discovery([c["discovery_url"]])

            # sabotage: worker answers the challenge with zeros
            from aiohttp import web

            async def bad_challenge(request):
                body = request.get("auth_body") or {}
                n = len(body["matrix_a"])
                return web.json_response(
                    {"success": True, "result": [[0.0] * n for _ in range(n)]}
                )

            agent_app = c["servers"][3].app  # first worker's control app
            # rebuild route table with the sabotaged handler
            agent.handle_challenge = bad_challenge
            ok = await c["validator"].challenge_node(agent.control_url)
            # direct call against the sabotaged handler:
            # validator must reject mismatched results
            stats_before = c["ledger"].is_node_validated(agent.node_wallet.address)
            assert not stats_before or ok is False

            for s in c["servers"]:
                await s.close()

    asyncio.new_event_loop().run_until_complete(flow())
