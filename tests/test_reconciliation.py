"""Discovery-monitor reconciliation rules (discovery/monitor.rs:236-420):
endpoint squatting, whitelist revocation/recovery, inactive grace."""

import pytest

# Environment guard: this module's import chain reaches
# protocol_tpu.security / protocol_tpu.utils.tls, which need the
# third-party `cryptography` package (wallet signing + TLS material).
# On hosts without it, report the whole module as SKIPPED instead of a
# collection error (tier-1 keeps an honest skip count; CI installs
# cryptography and runs everything).
pytest.importorskip(
    "cryptography", reason="cryptography not installed (signing/TLS dependency)"
)

import asyncio
import time

from protocol_tpu.models.node import DiscoveryNode, Node
from protocol_tpu.services.orchestrator import OrchestratorService
from protocol_tpu.store import NodeStatus, OrchestratorNode

from tests.test_services import make_world


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def dn(address, ip="1.1.1.1", port=80, validated=True, whitelisted=True,
       active=True, balance=100, last_updated=None):
    return DiscoveryNode(
        node=Node(id=address, ip_address=ip, port=port),
        is_validated=validated,
        is_provider_whitelisted=whitelisted,
        is_active=active,
        latest_balance=balance,
        last_updated=last_updated or time.time(),
    )


def svc_with(nodes, discovered):
    ledger, creator, manager, provider, node, pid = make_world()
    svc = OrchestratorService(ledger, pid, manager)
    for n in nodes:
        svc.store.node_store.add_node(n)

    async def fetcher():
        return discovered

    svc.discovery_fetcher = fetcher
    return svc


def test_rule1_nonhealthy_node_sharing_healthy_endpoint_dies():
    svc = svc_with(
        [
            OrchestratorNode(address="0xhealthy", ip_address="9.9.9.9", port=80,
                             status=NodeStatus.HEALTHY),
            OrchestratorNode(address="0xsquat", ip_address="9.9.9.9", port=80,
                             status=NodeStatus.DISCOVERED),
        ],
        [dn("0xsquat", ip="9.9.9.9", port=80)],
    )
    run(svc.discovery_monitor_once())
    assert svc.store.node_store.get_node("0xsquat").status == NodeStatus.DEAD
    assert svc.store.node_store.get_node("0xhealthy").status == NodeStatus.HEALTHY


def test_rule2_whitelist_revoked_ejects():
    svc = svc_with(
        [OrchestratorNode(address="0xa", status=NodeStatus.HEALTHY)],
        [dn("0xa", whitelisted=False)],
    )
    run(svc.discovery_monitor_once())
    assert svc.store.node_store.get_node("0xa").status == NodeStatus.EJECTED


def test_rule3_rewhitelisted_ejected_becomes_dead_then_recovers():
    svc = svc_with(
        [OrchestratorNode(address="0xa", status=NodeStatus.EJECTED)],
        [dn("0xa", whitelisted=True, last_updated=time.time() + 10)],
    )
    run(svc.discovery_monitor_once())
    # ejected -> dead (recoverable); rule 6 then lifts dead -> discovered
    # because the discovery record is newer than the status change...
    status = svc.store.node_store.get_node("0xa").status
    assert status in (NodeStatus.DEAD, NodeStatus.DISCOVERED)
    # second tick with a fresh discovery update completes recovery
    run(svc.discovery_monitor_once())
    assert svc.store.node_store.get_node("0xa").status == NodeStatus.DISCOVERED


def test_rule4_inactive_grace():
    # recently-healthy node: grace protects it
    fresh = OrchestratorNode(address="0xa", status=NodeStatus.HEALTHY,
                             last_status_change=time.time())
    svc = svc_with([fresh], [dn("0xa", active=False)])
    run(svc.discovery_monitor_once())
    assert svc.store.node_store.get_node("0xa").status == NodeStatus.HEALTHY

    # past grace: whitelisted -> Dead
    stale = OrchestratorNode(address="0xb", status=NodeStatus.HEALTHY,
                             last_status_change=time.time() - 400)
    svc2 = svc_with([stale], [dn("0xb", active=False, whitelisted=True,
                                 last_updated=time.time() - 500)])
    run(svc2.discovery_monitor_once())
    assert svc2.store.node_store.get_node("0xb").status == NodeStatus.DEAD

    # past grace: not whitelisted -> Ejected
    stale2 = OrchestratorNode(address="0xc", status=NodeStatus.HEALTHY,
                              last_status_change=time.time() - 400)
    svc3 = svc_with([stale2], [dn("0xc", active=False, whitelisted=False)])
    run(svc3.discovery_monitor_once())
    assert svc3.store.node_store.get_node("0xc").status == NodeStatus.EJECTED


def test_rule6_dead_to_discovered_emits_webhook_and_refreshes_specs():
    """The Dead -> Discovered recovery must route through _set_status so
    webhook observers see it like every other transition (monitor.rs:359-383),
    and must absorb the refreshed compute specs from discovery."""
    from protocol_tpu.models import ComputeSpecs, CpuSpecs

    d = dn("0xa", last_updated=time.time() + 5)
    d.node.compute_specs = ComputeSpecs(cpu=CpuSpecs(cores=64), ram_mb=1)
    svc = svc_with(
        [OrchestratorNode(address="0xa", status=NodeStatus.DEAD,
                          last_status_change=time.time() - 30)],
        [d],
    )
    events = []

    class Hook:
        def handle_status_change(self, addr, old, new):
            events.append((addr, old, new))

    svc.webhook = Hook()
    run(svc.discovery_monitor_once())
    node = svc.store.node_store.get_node("0xa")
    assert node.status == NodeStatus.DISCOVERED
    assert node.compute_specs is not None and node.compute_specs.cpu.cores == 64
    assert (
        "0xa", NodeStatus.DEAD.value, NodeStatus.DISCOVERED.value
    ) in events


def test_rule8_new_node_skipped_when_endpoint_taken():
    svc = svc_with(
        [OrchestratorNode(address="0xhealthy", ip_address="9.9.9.9", port=80,
                          status=NodeStatus.HEALTHY)],
        [dn("0xnew", ip="9.9.9.9", port=80)],
    )
    run(svc.discovery_monitor_once())
    assert svc.store.node_store.get_node("0xnew") is None
