#!/usr/bin/env python3
"""Fake ``docker`` CLI for DockerRuntime tests (the role bollard fakes
play in the reference's worker tests — no dockerd in CI).

State lives in $FAKE_DOCKER_STATE (a JSON file). Supported subcommands:
ps -a, run -d, rm -f, restart, logs, inspect. Containers "run" until
stopped; an env var FAKE_EXIT=<n> on the container makes it exit
immediately with that code (simulating a crashing or completing task).
"""

import json
import os
import sys
import time


def load(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {"containers": {}, "calls": []}


def save(path, state):
    with open(path, "w") as f:
        json.dump(state, f, indent=1)


def main() -> int:
    path = os.environ["FAKE_DOCKER_STATE"]
    state = load(path)
    argv = sys.argv[1:]
    state["calls"].append(argv)
    cmd = argv[0] if argv else ""

    if cmd == "ps":
        for name in state["containers"]:
            print(name)
    elif cmd == "rm":
        name = argv[-1]
        state["containers"].pop(name, None)
    elif cmd == "restart":
        name = argv[-1]
        c = state["containers"].get(name)
        if c:
            c["status"] = "running"
            c["exit_code"] = 0
    elif cmd == "logs":
        name = argv[-1]
        c = state["containers"].get(name)
        if c:
            print(f"log line from {name}")
    elif cmd == "inspect":
        name = argv[-1]
        c = state["containers"].get(name)
        if c is None:
            print(f"Error: No such object: {name}", file=sys.stderr)
            save(path, state)
            return 1
        print(json.dumps({
            "status": c["status"],
            "exit_code": c["exit_code"],
            "id": c["id"],
            "image": c["image"],
        }))
    elif cmd == "run":
        # parse the docker run surface DockerRuntime emits
        it = iter(argv[1:])
        c = {"env": {}, "volumes": [], "flags": [], "cmd": [],
             "entrypoint": None, "status": "running", "exit_code": 0,
             "image": "", "id": f"cid-{int(time.time() * 1000) % 100000}"}
        name = ""
        positionals = []
        for a in it:
            if a == "--name":
                name = next(it)
            elif a == "-e":
                k, _, v = next(it).partition("=")
                c["env"][k] = v
            elif a == "-v":
                c["volumes"].append(next(it))
            elif a in ("--network", "--shm-size", "--gpus", "--entrypoint"):
                c["flags"].append((a, next(it)))
                if a == "--entrypoint":
                    c["entrypoint"] = c["flags"][-1][1]
            elif a == "-d":
                continue
            else:
                positionals.append(a)
        c["image"] = positionals[0] if positionals else ""
        c["cmd"] = positionals[1:]
        if "FAKE_EXIT" in c["env"]:
            c["status"] = "exited"
            c["exit_code"] = int(c["env"]["FAKE_EXIT"])
        state["containers"][name] = c
        print(c["id"])
    else:
        print(f"fake docker: unknown command {cmd}", file=sys.stderr)
        save(path, state)
        return 1

    save(path, state)
    return 0


if __name__ == "__main__":
    sys.exit(main())
