"""Native CPU engine: build + exact parity with the JAX kernels and the
numpy oracles, plus quality vs the optimal assignment."""

import numpy as np
import pytest
from scipy.optimize import linear_sum_assignment

import jax.numpy as jnp

from protocol_tpu import native
from protocol_tpu.ops.assign import assign_greedy
from protocol_tpu.ops.cost import INFEASIBLE

from tests.test_assign import greedy_oracle, matching_cost, random_cost
from tests.test_sparse import jittered_cost

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no native toolchain"
)


class TestGreedyNative:
    @pytest.mark.parametrize("seed,P,T", [(0, 16, 16), (1, 64, 256), (2, 256, 64)])
    def test_parity_with_oracle_and_jax(self, seed, P, T):
        rng = np.random.default_rng(seed)
        cost = random_cost(rng, P, T)
        got = native.greedy_assign(cost)
        np.testing.assert_array_equal(got, greedy_oracle(cost))
        jax_res = assign_greedy(jnp.asarray(cost))
        np.testing.assert_array_equal(got, np.asarray(jax_res.provider_for_task))

    def test_task_order(self):
        rng = np.random.default_rng(3)
        cost = random_cost(rng, 32, 48)
        order = rng.permutation(48).astype(np.int32)
        got = native.greedy_assign(cost, task_order=order)
        np.testing.assert_array_equal(got, greedy_oracle(cost, order=list(order)))


class TestTopkNative:
    def test_matches_jax_candidates(self):
        from protocol_tpu.ops.sparse import candidates_topk
        from protocol_tpu.ops.cost import CostWeights, cost_matrix
        from tests.test_sparse import encode_random_marketplace

        ep, er = encode_random_marketplace(5, 32, 16)
        cost = np.asarray(cost_matrix(ep, er, CostWeights())[0])
        jp, jc = candidates_topk(ep, er, k=8, tile=8)
        cp, cc = native.topk_candidates(cost, k=8)
        np.testing.assert_array_equal(cp, np.asarray(jp))
        np.testing.assert_allclose(cc, np.asarray(jc), rtol=1e-6)


class TestAuctionNative:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_near_optimal(self, seed):
        rng = np.random.default_rng(seed)
        n = 64
        cost = rng.uniform(0, 10, size=(n, n)).astype(np.float32)
        cand_p, cand_c = native.topk_candidates(cost, k=n)
        p4t = native.auction_sparse(cand_p, cand_c, num_providers=n, eps_end=0.005)
        assert (p4t >= 0).all()
        used = set()
        for p in p4t:
            assert p not in used
            used.add(p)
        ri, ci = linear_sum_assignment(jittered_cost(cost))
        opt = jittered_cost(cost)[ri, ci].sum()
        got = sum(jittered_cost(cost)[p, t] for t, p in enumerate(p4t))
        assert got <= opt + n * 0.006, f"native auction {got} vs optimal {opt}"

    def test_infeasible_and_contention(self):
        rng = np.random.default_rng(7)
        cost = random_cost(rng, 16, 64, p_infeasible=0.3)  # oversubscribed
        cand_p, cand_c = native.topk_candidates(cost, k=16)
        p4t = native.auction_sparse(cand_p, cand_c, num_providers=16)
        # every assignment feasible + unique; at most P assigned
        used = set()
        n_assigned = 0
        for t, p in enumerate(p4t):
            if p >= 0:
                assert cost[p, t] < INFEASIBLE * 0.5
                assert p not in used
                used.add(p)
                n_assigned += 1
        assert n_assigned == 16  # full provider utilization under contention
