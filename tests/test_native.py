"""Native CPU engine: build + exact parity with the JAX kernels and the
numpy oracles, plus quality vs the optimal assignment."""

import numpy as np
import pytest
from scipy.optimize import linear_sum_assignment

import jax.numpy as jnp

from protocol_tpu import native
from protocol_tpu.ops.assign import assign_greedy
from protocol_tpu.ops.cost import INFEASIBLE

from tests.test_assign import greedy_oracle, random_cost
from tests.test_sparse import jittered_cost

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no native toolchain"
)


class TestGreedyNative:
    @pytest.mark.parametrize("seed,P,T", [(0, 16, 16), (1, 64, 256), (2, 256, 64)])
    def test_parity_with_oracle_and_jax(self, seed, P, T):
        rng = np.random.default_rng(seed)
        cost = random_cost(rng, P, T)
        got = native.greedy_assign(cost)
        np.testing.assert_array_equal(got, greedy_oracle(cost))
        jax_res = assign_greedy(jnp.asarray(cost))
        np.testing.assert_array_equal(got, np.asarray(jax_res.provider_for_task))

    def test_task_order(self):
        rng = np.random.default_rng(3)
        cost = random_cost(rng, 32, 48)
        order = rng.permutation(48).astype(np.int32)
        got = native.greedy_assign(cost, task_order=order)
        np.testing.assert_array_equal(got, greedy_oracle(cost, order=list(order)))


class TestTopkNative:
    def test_matches_jax_candidates(self):
        from protocol_tpu.ops.sparse import candidates_topk
        from protocol_tpu.ops.cost import CostWeights, cost_matrix
        from tests.test_sparse import encode_random_marketplace

        ep, er = encode_random_marketplace(5, 32, 16)
        cost = np.asarray(cost_matrix(ep, er, CostWeights())[0])
        jp, jc = candidates_topk(ep, er, k=8, tile=8)
        cp, cc = native.topk_candidates(cost, k=8)
        np.testing.assert_array_equal(cp, np.asarray(jp))
        np.testing.assert_allclose(cc, np.asarray(jc), rtol=1e-6)


class TestFusedNative:
    """fused_topk_candidates: cost + top-k straight from encoded features,
    no [P, T] tensor. Feasibility must match compat_mask EXACTLY (integer
    logic); costs may differ from XLA in the last ulp (trig), so candidate
    parity is checked against the dense native path built on XLA costs,
    allowing only near-tie slot swaps."""

    def test_compat_exact_and_candidates_agree(self):
        from protocol_tpu.ops.cost import CostWeights, cost_matrix
        from protocol_tpu.ops.encoding import compat_mask
        from tests.test_sparse import encode_random_marketplace

        for seed in (0, 1, 2):
            ep, er = encode_random_marketplace(seed, 48, 40)
            P = int(np.asarray(ep.gpu_count).shape[0])
            # k = P: the fused candidate set enumerates every feasible
            # provider per task -> direct feasibility comparison
            fp, fc = native.fused_topk_candidates(ep, er, CostWeights(), k=P)
            mask = np.asarray(compat_mask(ep, er))
            T = mask.shape[1]
            for t in range(T):
                got = {int(p) for p in fp[t] if p >= 0}
                want = {int(p) for p in np.flatnonzero(mask[:, t])}
                assert got == want, f"seed {seed} task {t}: {got} != {want}"
            # cost values match XLA's within float tolerance on feasible slots
            cost = np.asarray(cost_matrix(ep, er, CostWeights())[0])
            for t in range(T):
                for j in range(P):
                    p = fp[t, j]
                    if p >= 0:
                        assert abs(fc[t, j] - cost[p, t]) < 1e-3 + 1e-4 * abs(
                            cost[p, t]
                        )

    def test_topk_agreement_with_dense_path(self):
        from protocol_tpu.ops.cost import CostWeights, cost_matrix
        from tests.test_sparse import encode_random_marketplace

        ep, er = encode_random_marketplace(9, 128, 96)
        cost = np.asarray(cost_matrix(ep, er, CostWeights())[0])
        cp, cc = native.topk_candidates(cost, k=16)
        fp, fc = native.fused_topk_candidates(ep, er, CostWeights(), k=16)
        # forward region identical except where float drift swaps near-ties
        agree = (fp[:, :16] == cp).mean()
        assert agree > 0.99, f"slot agreement {agree}"
        # bidirectional extras: per ROW, no edge duplicates that task's
        # own forward list (a dup makes v1 == v2 in the bid math)
        for t in range(fp.shape[0]):
            fwd_row = {p for p in fp[t, :16] if p >= 0}
            for p in fp[t, 16:]:
                assert p < 0 or p not in fwd_row
        # and the auction on fused candidates matches or beats dense-path
        # quality (the repaired coverage can only help)
        p4t_f = native.auction_sparse(fp, fc, num_providers=128)
        p4t_d = native.auction_sparse(cp, cc, num_providers=128)
        assert int((p4t_f >= 0).sum()) >= int((p4t_d >= 0).sum())

    def test_matcher_native_fallback_routes_through_fused(self):
        """TpuBatchMatcher(native_fallback=True)'s bounded solve runs the
        fused engine (tpu_backend._bounded_t4p): weights and provider count
        must be plumbed correctly, and assignments must respect replica
        bounds. (Equivalence vs the jax path: test_memory_envelope.py.)"""
        import random

        from protocol_tpu.models.task import SchedulingConfig, Task, TaskRequest
        from protocol_tpu.sched.tpu_backend import TpuBatchMatcher
        from protocol_tpu.store import NodeStatus, OrchestratorNode, StoreContext
        from tests.test_encoding import random_specs

        rng = random.Random(5)
        store = StoreContext.new_test()
        for i in range(12):
            store.node_store.add_node(
                OrchestratorNode(
                    address=f"0xfu{i:02d}",
                    status=NodeStatus.HEALTHY,
                    compute_specs=random_specs(rng),
                )
            )
        store.task_store.add_task(
            Task.from_request(
                TaskRequest(
                    name="fused-b",
                    image="img",
                    scheduling_config=SchedulingConfig(
                        plugins={"tpu_scheduler": {"replicas": ["4"]}}
                    ),
                )
            )
        )
        m = TpuBatchMatcher(store, min_solve_interval=0.0, native_fallback=True)
        m.refresh()
        assert m.last_solve_stats["kernel"] == "native_cpu"
        by_task: dict = {}
        for addr, tid in m._assignment.items():
            by_task.setdefault(tid, []).append(addr)
        for addrs in by_task.values():
            assert len(addrs) <= 4


class TestAuctionNative:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_near_optimal(self, seed):
        rng = np.random.default_rng(seed)
        n = 64
        cost = rng.uniform(0, 10, size=(n, n)).astype(np.float32)
        cand_p, cand_c = native.topk_candidates(cost, k=n)
        p4t = native.auction_sparse(cand_p, cand_c, num_providers=n, eps_end=0.005)
        assert (p4t >= 0).all()
        used = set()
        for p in p4t:
            assert p not in used
            used.add(p)
        ri, ci = linear_sum_assignment(jittered_cost(cost))
        opt = jittered_cost(cost)[ri, ci].sum()
        got = sum(jittered_cost(cost)[p, t] for t, p in enumerate(p4t))
        assert got <= opt + n * 0.006, f"native auction {got} vs optimal {opt}"

    def test_infeasible_and_contention(self):
        rng = np.random.default_rng(7)
        cost = random_cost(rng, 16, 64, p_infeasible=0.3)  # oversubscribed
        cand_p, cand_c = native.topk_candidates(cost, k=16)
        p4t = native.auction_sparse(cand_p, cand_c, num_providers=16)
        # every assignment feasible + unique; at most P assigned
        used = set()
        n_assigned = 0
        for t, p in enumerate(p4t):
            if p >= 0:
                assert cost[p, t] < INFEASIBLE * 0.5
                assert p not in used
                used.add(p)
                n_assigned += 1
        assert n_assigned == 16  # full provider utilization under contention


class TestNativeCoverageRepair:
    """The degraded-mode completeness guarantee: forward-only top-k
    coverage-caps price-dominated fleets (measured 79% at 32k); the
    reverse-edge repair restores full coverage and the auction completes
    — the native twin of the JAX bidirectional path."""

    def _priced(self, P, T):
        from tests.test_sparse import TestBidirCandidates

        return TestBidirCandidates._priced_marketplace(P, T)

    def test_repair_restores_coverage_and_completeness(self):
        from protocol_tpu.ops.cost import CostWeights

        # production-sparse size: below ~1k the random reverse graph can
        # lack a perfect matching (same artifact the JAX bidir test
        # documents — those sizes take the dense solver in production)
        P = T = 1024
        ep, er = self._priced(P, T)
        fp0, fc0 = native.fused_topk_candidates(
            ep, er, CostWeights(), k=8, reverse_r=0, extra=0
        )
        p4t0 = native.auction_sparse(fp0, fc0, num_providers=P)
        capped = int((p4t0 >= 0).sum())
        assert capped < T * 0.75  # the coverage cap is real here

        fp, fc = native.fused_topk_candidates(
            ep, er, CostWeights(), k=8, reverse_r=8, extra=16
        )
        cov = np.unique(fp[fp >= 0]).size
        assert cov == P
        p4t = native.auction_sparse(fp, fc, num_providers=P)
        assigned = int((p4t >= 0).sum())
        assert assigned >= T * 0.99, f"{assigned}/{T} (capped run: {capped})"
        pos = p4t[p4t >= 0]
        assert np.unique(pos).size == pos.size
