"""Task-sharded sparse auction on the virtual 8-device CPU mesh: Jacobi
parity with the single-device kernel and feasibility under contention."""

import numpy as np
import pytest

import jax.numpy as jnp

from protocol_tpu.ops.cost import INFEASIBLE
from protocol_tpu.ops.sparse import assign_auction_sparse
from protocol_tpu.parallel import assign_auction_sparse_sharded, make_mesh

from tests.test_assign import check_feasible, random_cost


def build_candidates(cost: np.ndarray, k: int):
    order = np.argsort(cost, axis=0, kind="stable").T[:, :k]
    cand_c = np.take_along_axis(cost.T, order, axis=1).astype(np.float32)
    cand_p = np.where(cand_c < INFEASIBLE * 0.5, order.astype(np.int32), -1)
    return cand_p, cand_c


@pytest.mark.parametrize("seed,P,T,D", [(0, 48, 64, 8), (1, 64, 64, 4), (2, 32, 96, 2)])
def test_sharded_jacobi_parity(seed, P, T, D):
    rng = np.random.default_rng(seed)
    cost = random_cost(rng, P, T, p_infeasible=0.15)
    cand_p, cand_c = build_candidates(cost, k=min(16, P))
    mesh = make_mesh(D)
    # full frontier + no retirement = Jacobi schedule on both sides
    res_sharded = assign_auction_sparse_sharded(
        jnp.asarray(cand_p), jnp.asarray(cand_c), num_providers=P, mesh=mesh,
        eps=0.05, max_iters=4000, frontier=T, retire=False,
    )
    res_single = assign_auction_sparse(
        jnp.asarray(cand_p), jnp.asarray(cand_c), num_providers=P,
        eps=0.05, max_iters=4000, frontier=T, retire=False,
    )
    check_feasible(res_sharded, cost)
    np.testing.assert_array_equal(
        np.asarray(res_sharded.provider_for_task),
        np.asarray(res_single.provider_for_task),
    )


def test_sharded_contention_with_retirement():
    rng = np.random.default_rng(5)
    cost = random_cost(rng, 16, 64, p_infeasible=0.2)  # oversubscribed
    cand_p, cand_c = build_candidates(cost, k=16)
    mesh = make_mesh(8)
    res = assign_auction_sparse_sharded(
        jnp.asarray(cand_p), jnp.asarray(cand_c), num_providers=16, mesh=mesh,
        eps=0.05,
    )
    p4t = check_feasible(res, cost)
    assert (p4t >= 0).sum() > 0


def test_divisibility_enforced():
    mesh = make_mesh(8)
    with pytest.raises(ValueError):
        assign_auction_sparse_sharded(
            jnp.zeros((10, 4), jnp.int32), jnp.zeros((10, 4)), 4, mesh
        )


class TestScaledSharded:
    """The eps-scaling ladder + warm solve over the mesh (VERDICT r3
    item 3's sharded-parity leg): same phase discipline as the
    single-device twins, exact parity under the Jacobi schedule."""

    @pytest.mark.parametrize("seed,P,T,D", [(0, 64, 64, 8), (3, 96, 128, 4)])
    def test_scaled_jacobi_parity_with_single_device(self, seed, P, T, D):
        from protocol_tpu.ops.sparse import assign_auction_sparse_scaled
        from protocol_tpu.parallel import assign_auction_sparse_scaled_sharded

        rng = np.random.default_rng(seed)
        cost = random_cost(rng, P, T, p_infeasible=0.1)
        cand_p, cand_c = build_candidates(cost, k=min(16, P))
        mesh = make_mesh(D)
        kw = dict(
            num_providers=P, eps_start=2.0, eps_end=0.02,
            max_iters_per_phase=4000, frontier=T, with_prices=True,
        )
        res_sh, price_sh = assign_auction_sparse_scaled_sharded(
            jnp.asarray(cand_p), jnp.asarray(cand_c), mesh=mesh, **kw
        )
        # frontier_ladder off: exact-Jacobi comparison against the
        # fixed-frontier mesh kernel
        res_sg, price_sg = assign_auction_sparse_scaled(
            jnp.asarray(cand_p), jnp.asarray(cand_c),
            frontier_ladder=False, **kw
        )
        check_feasible(res_sh, cost)
        np.testing.assert_array_equal(
            np.asarray(res_sh.provider_for_task),
            np.asarray(res_sg.provider_for_task),
        )
        np.testing.assert_allclose(
            np.asarray(price_sh), np.asarray(price_sg), rtol=1e-6
        )

    def test_warm_jacobi_parity_with_single_device(self):
        from protocol_tpu.ops.sparse import (
            assign_auction_sparse_scaled,
            assign_auction_sparse_warm,
        )
        from protocol_tpu.parallel import assign_auction_sparse_warm_sharded

        rng = np.random.default_rng(7)
        P = T = 64
        cost = random_cost(rng, P, T, p_infeasible=0.1)
        cand_p, cand_c = build_candidates(cost, k=16)
        mesh = make_mesh(8)
        res0, price0 = assign_auction_sparse_scaled(
            jnp.asarray(cand_p), jnp.asarray(cand_c), num_providers=P,
            with_prices=True, frontier=T,
        )
        # 10% churn: first tasks re-open
        p4t0 = jnp.asarray(np.asarray(res0.provider_for_task)).at[:6].set(-1)
        kw = dict(
            num_providers=P, price0=price0, p4t0=p4t0,
            eps=0.02, max_iters=20000, frontier=T,
        )
        res_sh, price_sh = assign_auction_sparse_warm_sharded(
            jnp.asarray(cand_p), jnp.asarray(cand_c), mesh=mesh, **kw
        )
        res_sg, price_sg = assign_auction_sparse_warm(
            jnp.asarray(cand_p), jnp.asarray(cand_c),
            frontier_ladder=False, **kw
        )
        check_feasible(res_sh, cost)
        np.testing.assert_array_equal(
            np.asarray(res_sh.provider_for_task),
            np.asarray(res_sg.provider_for_task),
        )
        np.testing.assert_allclose(
            np.asarray(price_sh), np.asarray(price_sg), rtol=1e-6
        )

    def test_sharded_completeness_with_bidir_candidates(self):
        """Stage-B completeness composes with the mesh: bidir candidates +
        the sharded ladder assign every task at a production-sparse shape
        (the single-device 65k twin of this test is bench_scaling B2)."""
        from tests.test_sparse import TestBidirCandidates
        from protocol_tpu.ops.sparse import candidates_topk_bidir
        from protocol_tpu.parallel import assign_auction_sparse_scaled_sharded

        P = T = 1024
        ep, er = TestBidirCandidates._priced_marketplace(P, T)
        bp, bc = candidates_topk_bidir(
            ep, er, k=8, tile=256, reverse_r=8, extra=16
        )
        mesh = make_mesh(8)
        res = assign_auction_sparse_scaled_sharded(
            bp, bc, num_providers=P, mesh=mesh, frontier=1024,
        )
        p4t = np.asarray(res.provider_for_task)
        assigned = int((p4t >= 0).sum())
        assert assigned >= T * 0.99, f"sharded bidir assigned {assigned}/{T}"
        pos = p4t[p4t >= 0]
        assert np.unique(pos).size == pos.size

    def test_adaptive_ladder_sharded_matches_quality(self):
        """frontier_ladder=True on the mesh: same assignment count as the
        fixed-frontier schedule (a different, equally valid auction
        order), full completeness on the bidir graph."""
        from tests.test_sparse import TestBidirCandidates
        from protocol_tpu.ops.sparse import candidates_topk_bidir
        from protocol_tpu.parallel import assign_auction_sparse_scaled_sharded

        P = T = 1024
        ep, er = TestBidirCandidates._priced_marketplace(P, T)
        bp, bc = candidates_topk_bidir(
            ep, er, k=8, tile=256, reverse_r=8, extra=16
        )
        mesh = make_mesh(8)
        counts = {}
        for ladder in (False, True):
            res = assign_auction_sparse_scaled_sharded(
                bp, bc, num_providers=P, mesh=mesh, frontier=1024,
                frontier_ladder=ladder,
            )
            p4t = np.asarray(res.provider_for_task)
            counts[ladder] = int((p4t >= 0).sum())
            pos = p4t[p4t >= 0]
            assert np.unique(pos).size == pos.size
        assert counts[True] >= T * 0.99
        assert counts[True] >= counts[False] - 2
