"""Task-sharded sparse auction on the virtual 8-device CPU mesh: Jacobi
parity with the single-device kernel and feasibility under contention."""

import numpy as np
import pytest

import jax.numpy as jnp

from protocol_tpu.ops.cost import INFEASIBLE
from protocol_tpu.ops.sparse import assign_auction_sparse
from protocol_tpu.parallel import assign_auction_sparse_sharded, make_mesh

from tests.test_assign import check_feasible, random_cost


def build_candidates(cost: np.ndarray, k: int):
    order = np.argsort(cost, axis=0, kind="stable").T[:, :k]
    cand_c = np.take_along_axis(cost.T, order, axis=1).astype(np.float32)
    cand_p = np.where(cand_c < INFEASIBLE * 0.5, order.astype(np.int32), -1)
    return cand_p, cand_c


@pytest.mark.parametrize("seed,P,T,D", [(0, 48, 64, 8), (1, 64, 64, 4), (2, 32, 96, 2)])
def test_sharded_jacobi_parity(seed, P, T, D):
    rng = np.random.default_rng(seed)
    cost = random_cost(rng, P, T, p_infeasible=0.15)
    cand_p, cand_c = build_candidates(cost, k=min(16, P))
    mesh = make_mesh(D)
    # full frontier + no retirement = Jacobi schedule on both sides
    res_sharded = assign_auction_sparse_sharded(
        jnp.asarray(cand_p), jnp.asarray(cand_c), num_providers=P, mesh=mesh,
        eps=0.05, max_iters=4000, frontier=T, retire=False,
    )
    res_single = assign_auction_sparse(
        jnp.asarray(cand_p), jnp.asarray(cand_c), num_providers=P,
        eps=0.05, max_iters=4000, frontier=T, retire=False,
    )
    check_feasible(res_sharded, cost)
    np.testing.assert_array_equal(
        np.asarray(res_sharded.provider_for_task),
        np.asarray(res_single.provider_for_task),
    )


def test_sharded_contention_with_retirement():
    rng = np.random.default_rng(5)
    cost = random_cost(rng, 16, 64, p_infeasible=0.2)  # oversubscribed
    cand_p, cand_c = build_candidates(cost, k=16)
    mesh = make_mesh(8)
    res = assign_auction_sparse_sharded(
        jnp.asarray(cand_p), jnp.asarray(cand_c), num_providers=16, mesh=mesh,
        eps=0.05,
    )
    p4t = check_feasible(res, cost)
    assert (p4t >= 0).sum() > 0


def test_divisibility_enforced():
    mesh = make_mesh(8)
    with pytest.raises(ValueError):
        assign_auction_sparse_sharded(
            jnp.zeros((10, 4), jnp.int32), jnp.zeros((10, 4)), 4, mesh
        )
