"""Task-sharded sparse auction on the virtual 8-device CPU mesh: Jacobi
parity with the single-device kernel and feasibility under contention."""

import numpy as np
import pytest

import jax.numpy as jnp

from protocol_tpu.ops.cost import INFEASIBLE
from protocol_tpu.ops.sparse import assign_auction_sparse
from protocol_tpu.parallel import assign_auction_sparse_sharded, make_mesh

from tests.test_assign import check_feasible, random_cost


def build_candidates(cost: np.ndarray, k: int):
    order = np.argsort(cost, axis=0, kind="stable").T[:, :k]
    cand_c = np.take_along_axis(cost.T, order, axis=1).astype(np.float32)
    cand_p = np.where(cand_c < INFEASIBLE * 0.5, order.astype(np.int32), -1)
    return cand_p, cand_c


@pytest.mark.parametrize("seed,P,T,D", [(0, 48, 64, 8), (1, 64, 64, 4), (2, 32, 96, 2)])
def test_sharded_jacobi_parity(seed, P, T, D):
    rng = np.random.default_rng(seed)
    cost = random_cost(rng, P, T, p_infeasible=0.15)
    cand_p, cand_c = build_candidates(cost, k=min(16, P))
    mesh = make_mesh(D)
    # full frontier + no retirement = Jacobi schedule on both sides
    res_sharded = assign_auction_sparse_sharded(
        jnp.asarray(cand_p), jnp.asarray(cand_c), num_providers=P, mesh=mesh,
        eps=0.05, max_iters=4000, frontier=T, retire=False,
    )
    res_single = assign_auction_sparse(
        jnp.asarray(cand_p), jnp.asarray(cand_c), num_providers=P,
        eps=0.05, max_iters=4000, frontier=T, retire=False,
    )
    check_feasible(res_sharded, cost)
    np.testing.assert_array_equal(
        np.asarray(res_sharded.provider_for_task),
        np.asarray(res_single.provider_for_task),
    )


def test_sharded_contention_with_retirement():
    rng = np.random.default_rng(5)
    cost = random_cost(rng, 16, 64, p_infeasible=0.2)  # oversubscribed
    cand_p, cand_c = build_candidates(cost, k=16)
    mesh = make_mesh(8)
    res = assign_auction_sparse_sharded(
        jnp.asarray(cand_p), jnp.asarray(cand_c), num_providers=16, mesh=mesh,
        eps=0.05,
    )
    p4t = check_feasible(res, cost)
    assert (p4t >= 0).sum() > 0


def test_divisibility_enforced():
    mesh = make_mesh(8)
    with pytest.raises(ValueError):
        assign_auction_sparse_sharded(
            jnp.zeros((10, 4), jnp.int32), jnp.zeros((10, 4)), 4, mesh
        )


class TestScaledSharded:
    """The eps-scaling ladder + warm solve over the mesh (VERDICT r3
    item 3's sharded-parity leg): same phase discipline as the
    single-device twins, exact parity under the Jacobi schedule."""

    @pytest.mark.parametrize("seed,P,T,D", [(0, 64, 64, 8), (3, 96, 128, 4)])
    def test_scaled_jacobi_parity_with_single_device(self, seed, P, T, D):
        from protocol_tpu.ops.sparse import assign_auction_sparse_scaled
        from protocol_tpu.parallel import assign_auction_sparse_scaled_sharded

        rng = np.random.default_rng(seed)
        cost = random_cost(rng, P, T, p_infeasible=0.1)
        cand_p, cand_c = build_candidates(cost, k=min(16, P))
        mesh = make_mesh(D)
        kw = dict(
            num_providers=P, eps_start=2.0, eps_end=0.02,
            max_iters_per_phase=4000, frontier=T, with_prices=True,
        )
        res_sh, price_sh = assign_auction_sparse_scaled_sharded(
            jnp.asarray(cand_p), jnp.asarray(cand_c), mesh=mesh, **kw
        )
        # frontier_ladder off: exact-Jacobi comparison against the
        # fixed-frontier mesh kernel
        res_sg, price_sg = assign_auction_sparse_scaled(
            jnp.asarray(cand_p), jnp.asarray(cand_c),
            frontier_ladder=False, **kw
        )
        check_feasible(res_sh, cost)
        np.testing.assert_array_equal(
            np.asarray(res_sh.provider_for_task),
            np.asarray(res_sg.provider_for_task),
        )
        np.testing.assert_allclose(
            np.asarray(price_sh), np.asarray(price_sg), rtol=1e-6
        )

    def test_warm_jacobi_parity_with_single_device(self):
        from protocol_tpu.ops.sparse import (
            assign_auction_sparse_scaled,
            assign_auction_sparse_warm,
        )
        from protocol_tpu.parallel import assign_auction_sparse_warm_sharded

        rng = np.random.default_rng(7)
        P = T = 64
        cost = random_cost(rng, P, T, p_infeasible=0.1)
        cand_p, cand_c = build_candidates(cost, k=16)
        mesh = make_mesh(8)
        res0, price0 = assign_auction_sparse_scaled(
            jnp.asarray(cand_p), jnp.asarray(cand_c), num_providers=P,
            with_prices=True, frontier=T,
        )
        # 10% churn: first tasks re-open
        p4t0 = jnp.asarray(np.asarray(res0.provider_for_task)).at[:6].set(-1)
        kw = dict(
            num_providers=P, price0=price0, p4t0=p4t0,
            eps=0.02, max_iters=20000, frontier=T,
        )
        res_sh, price_sh = assign_auction_sparse_warm_sharded(
            jnp.asarray(cand_p), jnp.asarray(cand_c), mesh=mesh, **kw
        )
        res_sg, price_sg = assign_auction_sparse_warm(
            jnp.asarray(cand_p), jnp.asarray(cand_c),
            frontier_ladder=False, **kw
        )
        check_feasible(res_sh, cost)
        np.testing.assert_array_equal(
            np.asarray(res_sh.provider_for_task),
            np.asarray(res_sg.provider_for_task),
        )
        np.testing.assert_allclose(
            np.asarray(price_sh), np.asarray(price_sg), rtol=1e-6
        )

    def test_sharded_completeness_with_bidir_candidates(self):
        """Stage-B completeness composes with the mesh: bidir candidates +
        the sharded ladder assign every task at a production-sparse shape
        (the single-device 65k twin of this test is bench_scaling B2)."""
        from tests.test_sparse import TestBidirCandidates
        from protocol_tpu.ops.sparse import candidates_topk_bidir
        from protocol_tpu.parallel import assign_auction_sparse_scaled_sharded

        P = T = 1024
        ep, er = TestBidirCandidates._priced_marketplace(P, T)
        bp, bc = candidates_topk_bidir(
            ep, er, k=8, tile=256, reverse_r=8, extra=16
        )
        mesh = make_mesh(8)
        res = assign_auction_sparse_scaled_sharded(
            bp, bc, num_providers=P, mesh=mesh, frontier=1024,
        )
        p4t = np.asarray(res.provider_for_task)
        assigned = int((p4t >= 0).sum())
        assert assigned >= T * 0.99, f"sharded bidir assigned {assigned}/{T}"
        pos = p4t[p4t >= 0]
        assert np.unique(pos).size == pos.size

    def test_adaptive_ladder_sharded_matches_quality(self):
        """frontier_ladder=True on the mesh: same assignment count as the
        fixed-frontier schedule (a different, equally valid auction
        order), full completeness on the bidir graph."""
        from tests.test_sparse import TestBidirCandidates
        from protocol_tpu.ops.sparse import candidates_topk_bidir
        from protocol_tpu.parallel import assign_auction_sparse_scaled_sharded

        P = T = 1024
        ep, er = TestBidirCandidates._priced_marketplace(P, T)
        bp, bc = candidates_topk_bidir(
            ep, er, k=8, tile=256, reverse_r=8, extra=16
        )
        mesh = make_mesh(8)
        counts = {}
        for ladder in (False, True):
            res = assign_auction_sparse_scaled_sharded(
                bp, bc, num_providers=P, mesh=mesh, frontier=1024,
                frontier_ladder=ladder,
            )
            p4t = np.asarray(res.provider_for_task)
            counts[ladder] = int((p4t >= 0).sum())
            pos = p4t[p4t >= 0]
            assert np.unique(pos).size == pos.size
        assert counts[True] >= T * 0.99
        assert counts[True] >= counts[False] - 2


class TestShardedGeneration:
    """candidates_topk_bidir_sharded: bit-exact parity with the
    single-device generator (same global tiling, same jitter keys, same
    tile-pooled reverse contract) — the collective-free sharding of the
    measured wall-clock dominator."""

    def _marketplace(self, P, T, seed=5):
        import jax
        from tests.test_sparse import encode_random_marketplace

        ep, er = encode_random_marketplace(seed, P, T)
        return jax.tree.map(jnp.asarray, ep), jax.tree.map(jnp.asarray, er)

    @pytest.mark.parametrize(
        "P,T,D,tile,k,r,extra",
        [
            (512, 1024, 8, 64, 16, 8, 8),
            (256, 512, 4, 128, 8, 4, 4),   # rt > 1 branch (2 local tiles)
            (128, 256, 2, 128, 8, 1, 2),   # rt == 1 argmin branch
        ],
    )
    def test_bit_parity_with_single_device(self, P, T, D, tile, k, r, extra):
        from protocol_tpu.ops.cost import CostWeights
        from protocol_tpu.ops.sparse import candidates_topk_bidir
        from protocol_tpu.parallel import candidates_topk_bidir_sharded

        ep, er = self._marketplace(P, T)
        w = CostWeights()
        cp1, cc1 = candidates_topk_bidir(
            ep, er, w, k=k, tile=tile, reverse_r=r, extra=extra
        )
        cp2, cc2 = candidates_topk_bidir_sharded(
            ep, er, w, mesh=make_mesh(D), k=k, tile=tile, reverse_r=r,
            extra=extra,
        )
        np.testing.assert_array_equal(np.asarray(cp1), np.asarray(cp2))
        np.testing.assert_array_equal(np.asarray(cc1), np.asarray(cc2))

    def test_divisibility_enforced(self):
        from protocol_tpu.parallel import candidates_topk_bidir_sharded

        ep, er = self._marketplace(64, 96)  # 96 not divisible by 64-tile
        with pytest.raises(ValueError):
            candidates_topk_bidir_sharded(
                ep, er, mesh=make_mesh(8), k=8, tile=64
            )

    def test_feeds_sharded_solve_end_to_end(self):
        """The sharded pipeline composes: sharded generation -> sharded
        ladder, matching the fully single-device pipeline bit-for-bit
        under the Jacobi schedule."""
        from protocol_tpu.ops.cost import CostWeights
        from protocol_tpu.ops.sparse import (
            assign_auction_sparse_scaled,
            candidates_topk_bidir,
        )
        from protocol_tpu.parallel import (
            assign_auction_sparse_scaled_sharded,
            candidates_topk_bidir_sharded,
        )

        P = T = 512
        ep, er = self._marketplace(P, T, seed=9)
        w = CostWeights()
        mesh = make_mesh(8)
        bp_s, bc_s = candidates_topk_bidir_sharded(
            ep, er, w, mesh=mesh, k=8, tile=64, reverse_r=4, extra=8
        )
        bp_1, bc_1 = candidates_topk_bidir(
            ep, er, w, k=8, tile=64, reverse_r=4, extra=8
        )
        kw = dict(num_providers=P, frontier=T, with_prices=True)
        res_s, _ = assign_auction_sparse_scaled_sharded(
            bp_s, bc_s, mesh=mesh, **kw
        )
        res_1, _ = assign_auction_sparse_scaled(
            bp_1, bc_1, frontier_ladder=False, **kw
        )
        np.testing.assert_array_equal(
            np.asarray(res_s.provider_for_task),
            np.asarray(res_1.provider_for_task),
        )


class TestAdversarialParity:
    """VERDICT r4 item 8: the sharded-parity contract under the shapes
    that break naive SPMD ports — degenerate all-equal prices (every bid
    ties), churn mid-chain, uneven tails at several sizes, non-dividing
    mesh fallback, warm-after-rebuild."""

    def test_degenerate_all_equal_costs(self):
        """All-equal feasible costs: every round is a pure tie-break.
        Global win_task = pmin over shard-local minima must reproduce the
        single-device lowest-task-index rule exactly."""
        from protocol_tpu.ops.sparse import assign_auction_sparse

        P = T = 64
        cost = np.full((P, T), 3.0, np.float32)
        cand_p, cand_c = build_candidates(cost, k=16)
        mesh = make_mesh(8)
        res_sh = assign_auction_sparse_sharded(
            jnp.asarray(cand_p), jnp.asarray(cand_c), num_providers=P,
            mesh=mesh, eps=0.05, max_iters=4000, frontier=T, retire=False,
        )
        res_sg = assign_auction_sparse(
            jnp.asarray(cand_p), jnp.asarray(cand_c), num_providers=P,
            eps=0.05, max_iters=4000, frontier=T, retire=False,
        )
        np.testing.assert_array_equal(
            np.asarray(res_sh.provider_for_task),
            np.asarray(res_sg.provider_for_task),
        )
        # all-equal costs make every top-k window identical, so the
        # forward-only graph covers exactly k providers — the matching
        # caps there (the coverage phenomenon bidir candidates repair)
        assert int((np.asarray(res_sh.provider_for_task) >= 0).sum()) == 16

    @pytest.mark.parametrize("T_real,D", [(97, 8), (505, 8), (1000, 4)])
    def test_uneven_tail_padding(self, T_real, D):
        """Pow2/bucket padding with an uneven real tail: padded rows must
        never assign, real rows must match single-device exactly."""
        from protocol_tpu.ops.sparse import assign_auction_sparse_scaled
        from protocol_tpu.parallel import (
            assign_auction_sparse_scaled_sharded,
            pad_to_multiple,
        )

        rng = np.random.default_rng(T_real)
        P = 128
        T_pad = pad_to_multiple(T_real, D * 16)
        cost = random_cost(rng, P, T_real, p_infeasible=0.1)
        cand_p, cand_c = build_candidates(cost, k=16)
        cand_p = np.concatenate(
            [cand_p, np.full((T_pad - T_real, 16), -1, np.int32)]
        )
        cand_c = np.concatenate(
            [cand_c,
             np.full((T_pad - T_real, 16), np.float32(INFEASIBLE))]
        )
        mesh = make_mesh(D)
        kw = dict(
            num_providers=P, eps_start=2.0, eps_end=0.02,
            max_iters_per_phase=4000, frontier=T_pad,
        )
        res_sh = assign_auction_sparse_scaled_sharded(
            jnp.asarray(cand_p), jnp.asarray(cand_c), mesh=mesh, **kw
        )
        res_sg = assign_auction_sparse_scaled(
            jnp.asarray(cand_p), jnp.asarray(cand_c),
            frontier_ladder=False, **kw
        )
        got = np.asarray(res_sh.provider_for_task)
        np.testing.assert_array_equal(
            got, np.asarray(res_sg.provider_for_task)
        )
        assert not (got[T_real:] >= 0).any(), "padded tail must stay open"

    def test_non_dividing_mesh_rejected_everywhere(self):
        """Every sharded kernel must refuse a non-dividing T loudly (the
        matcher's fallback path depends on this contract, and a silent
        mis-shard would corrupt the matching)."""
        from protocol_tpu.parallel import (
            assign_auction_sparse_scaled_sharded,
            assign_auction_sparse_warm_sharded,
        )

        mesh = make_mesh(8)
        cp = jnp.zeros((12, 4), jnp.int32)
        cc = jnp.zeros((12, 4), jnp.float32)
        with pytest.raises(ValueError):
            assign_auction_sparse_scaled_sharded(cp, cc, 4, mesh)
        with pytest.raises(ValueError):
            assign_auction_sparse_warm_sharded(
                cp, cc, 4, mesh,
                price0=jnp.zeros(4), p4t0=jnp.full(12, -1, jnp.int32),
            )

    def test_warm_chain_with_churn_and_rebuild(self):
        """A 4-solve chain on the mesh: cold -> warm(churn) ->
        REBUILD (new candidate structure, seeds re-expressed, prices
        carried, retirement dropped) -> warm again. Every step must match
        the single-device twin bit-for-bit."""
        from protocol_tpu.ops.sparse import (
            assign_auction_sparse_scaled,
            assign_auction_sparse_warm,
        )
        from protocol_tpu.parallel import (
            assign_auction_sparse_scaled_sharded,
            assign_auction_sparse_warm_sharded,
        )

        rng = np.random.default_rng(11)
        P = T = 64
        cost = random_cost(rng, P, T, p_infeasible=0.1)
        cand_p, cand_c = build_candidates(cost, k=16)
        mesh = make_mesh(8)
        kw0 = dict(
            num_providers=P, eps_start=2.0, eps_end=0.02,
            max_iters_per_phase=4000, frontier=T, with_state=True,
        )
        res_sh, price_sh, ret_sh = assign_auction_sparse_scaled_sharded(
            jnp.asarray(cand_p), jnp.asarray(cand_c), mesh=mesh, **kw0
        )
        res_sg, price_sg, ret_sg = assign_auction_sparse_scaled(
            jnp.asarray(cand_p), jnp.asarray(cand_c),
            frontier_ladder=False, **kw0
        )
        np.testing.assert_array_equal(
            np.asarray(ret_sh), np.asarray(ret_sg)
        )

        # warm 1: 10% churn, retirement carried
        p4t1 = jnp.asarray(res_sh.provider_for_task).at[:6].set(-1)
        kw1 = dict(
            num_providers=P, price0=price_sh, p4t0=p4t1, eps=0.02,
            max_iters=20000, frontier=T, retired0=ret_sh, with_state=True,
        )
        w_sh, wp_sh, wret_sh = assign_auction_sparse_warm_sharded(
            jnp.asarray(cand_p), jnp.asarray(cand_c), mesh=mesh, **kw1
        )
        w_sg, wp_sg, wret_sg = assign_auction_sparse_warm(
            jnp.asarray(cand_p), jnp.asarray(cand_c),
            frontier_ladder=False, **kw1
        )
        np.testing.assert_array_equal(
            np.asarray(w_sh.provider_for_task),
            np.asarray(w_sg.provider_for_task),
        )
        np.testing.assert_array_equal(
            np.asarray(wret_sh), np.asarray(wret_sg)
        )

        # rebuild: costs drift, candidate structure regenerated; carried
        # prices survive, the retirement mask must NOT (stale w.r.t. the
        # new graph) — the caller drops it, kernels treat seeds as fresh
        cost2 = cost + rng.uniform(0, 0.2, cost.shape).astype(np.float32)
        cost2[cost >= INFEASIBLE * 0.5] = INFEASIBLE
        cand_p2, cand_c2 = build_candidates(cost2, k=16)
        p4t2 = jnp.asarray(w_sh.provider_for_task)
        kw2 = dict(
            num_providers=P, price0=wp_sh, p4t0=p4t2, eps=0.02,
            max_iters=20000, frontier=T,
        )
        f_sh, _ = assign_auction_sparse_warm_sharded(
            jnp.asarray(cand_p2), jnp.asarray(cand_c2), mesh=mesh, **kw2
        )
        f_sg, _ = assign_auction_sparse_warm(
            jnp.asarray(cand_p2), jnp.asarray(cand_c2),
            frontier_ladder=False, **kw2
        )
        np.testing.assert_array_equal(
            np.asarray(f_sh.provider_for_task),
            np.asarray(f_sg.provider_for_task),
        )
        check_feasible(f_sh, cost2)


class TestCandidateRepair:
    """repair_topk_bidir_sharded: the warm-path repaired==regen oracle
    contract — a churn-masked repair of the persistent parts lands the
    bit-identical structure a from-scratch bidirectional pass produces
    on the current features, at every device count (ISSUE 18)."""

    def _marketplace(self, P, T, seed=5):
        import jax
        from tests.test_sparse import encode_random_marketplace

        ep, er = encode_random_marketplace(seed, P, T)
        return jax.tree.map(jnp.asarray, ep), jax.tree.map(jnp.asarray, er)

    @staticmethod
    def _bump_price(ep, rows, delta=0.25):
        import dataclasses

        price = np.array(ep.price, copy=True)
        price[list(rows)] += delta
        return dataclasses.replace(ep, price=jnp.asarray(price))

    @staticmethod
    def _bump_req(er, rows, delta=1.0):
        import dataclasses

        cc = np.array(er.cpu_cores, copy=True)
        cc[list(rows)] = np.maximum(1.0, cc[list(rows)] + delta)
        return dataclasses.replace(er, cpu_cores=jnp.asarray(cc))

    def _full(self, ep, er, w, mesh, k, tile, r, extra):
        from protocol_tpu.ops.sparse import (
            candidates_topk_reverse,
            merge_reverse_candidates,
        )
        from protocol_tpu.parallel import candidates_topk_bidir_sharded

        if mesh is None:
            fp, fc, rt_, rc, pt, pc = candidates_topk_reverse(
                ep, er, w, k=k, tile=tile, reverse_r=r, with_pools=True
            )
            mp, mc = merge_reverse_candidates(fp, fc, rt_, rc, extra=extra)
            return [np.asarray(a) for a in (mp, mc, fp, fc, pt, pc)]
        return [
            np.asarray(a)
            for a in candidates_topk_bidir_sharded(
                ep, er, w, mesh=mesh, k=k, tile=tile, reverse_r=r,
                extra=extra, with_parts=True,
            )
        ]

    @pytest.mark.parametrize("D", [None, 1, 4])
    @pytest.mark.parametrize(
        "dirty_p,dirty_t",
        [
            ([5, 17, 40], []),            # provider-side churn only
            ([], [3, 60, 100, 101]),      # requirement-side churn only
            ([2, 90], [0, 127]),          # both sides
            ([], []),                     # empty event: repair is a no-op
        ],
    )
    def test_repair_matches_regen_bit_for_bit(self, D, dirty_p, dirty_t):
        from protocol_tpu.ops.cost import CostWeights
        from protocol_tpu.parallel.sparse import repair_topk_bidir_sharded

        P, T, k, tile, r, extra = 96, 128, 16, 16, 8, 8
        mesh = None if D is None else make_mesh(D)
        ep, er = self._marketplace(P, T)
        w = CostWeights()
        _, _, fwd_p, fwd_c, pool_t, pool_c = self._full(
            ep, er, w, mesh, k, tile, r, extra
        )
        ep2 = self._bump_price(ep, dirty_p) if dirty_p else ep
        er2 = self._bump_req(er, dirty_t) if dirty_t else er
        oracle = self._full(ep2, er2, w, mesh, k, tile, r, extra)
        got = repair_topk_bidir_sharded(
            ep2, er2, w, fwd_p=fwd_p, fwd_c=fwd_c, pool_t=pool_t,
            pool_c=pool_c, dirty_p=np.asarray(dirty_p, np.int64),
            dirty_t=np.asarray(dirty_t, np.int64), reverse_r=r,
            mesh=mesh, tile=tile, extra=extra,
        )
        stats = got[-1]
        order = ["cand_p", "cand_c", "fwd_p", "fwd_c", "pool_t", "pool_c"]
        for name, g, o in zip(order, got[:6], oracle):
            np.testing.assert_array_equal(g, o, err_msg=name)
        if not dirty_p and not dirty_t:
            assert stats["repair_rows"] == 0
            assert stats["repair_providers"] == 0
            assert stats["repair_blocks"] == 0
            assert stats["visited_cells_frac"] == 0.0
        else:
            # repair scope is honest churn-bounded work, not a rebuild
            assert stats["visited_cells_frac"] < 1.0

    def test_repair_scope_is_churn_bounded(self):
        """Requirement-side churn (the heartbeat steady state) repairs
        O(churned rows): the forward scope is exactly the dirty tasks
        and the visited-cell fraction stays near churn/T."""
        from protocol_tpu.ops.cost import CostWeights
        from protocol_tpu.parallel.sparse import repair_topk_bidir_sharded

        P, T, k, tile, r, extra = 96, 256, 16, 16, 8, 8
        ep, er = self._marketplace(P, T)
        w = CostWeights()
        _, _, fwd_p, fwd_c, pool_t, pool_c = self._full(
            ep, er, w, None, k, tile, r, extra
        )
        dirty_t = np.asarray([10, 77], np.int64)
        er2 = self._bump_req(er, dirty_t)
        *_, stats = repair_topk_bidir_sharded(
            ep, er2, w, fwd_p=fwd_p, fwd_c=fwd_c, pool_t=pool_t,
            pool_c=pool_c, dirty_p=np.zeros(0, np.int64),
            dirty_t=dirty_t, reverse_r=r, mesh=None, tile=tile,
            extra=extra,
        )
        assert stats["repair_rows"] == dirty_t.size
        assert stats["repair_enter_rows"] == 0  # no dirty providers
        assert stats["visited_cells_frac"] < 0.5

    def test_rt_one_and_clamped_k_branches(self):
        """The argmin reverse branch (rt == 1: many tiles) and k
        clamped at P both honor the oracle contract."""
        from protocol_tpu.ops.cost import CostWeights
        from protocol_tpu.parallel.sparse import repair_topk_bidir_sharded

        P, T, k, tile, r, extra = 24, 256, 64, 16, 4, 8
        ep, er = self._marketplace(P, T, seed=11)
        w = CostWeights()
        kk = min(k, P)
        _, _, fwd_p, fwd_c, pool_t, pool_c = self._full(
            ep, er, w, None, kk, tile, r, extra
        )
        ep2 = self._bump_price(ep, [1, 20])
        er2 = self._bump_req(er, [4, 200])
        oracle = self._full(ep2, er2, w, None, kk, tile, r, extra)
        got = repair_topk_bidir_sharded(
            ep2, er2, w, fwd_p=fwd_p, fwd_c=fwd_c, pool_t=pool_t,
            pool_c=pool_c, dirty_p=np.asarray([1, 20], np.int64),
            dirty_t=np.asarray([4, 200], np.int64), reverse_r=r,
            mesh=None, tile=tile, extra=extra,
        )
        for g, o in zip(got[:6], oracle):
            np.testing.assert_array_equal(g, o)
