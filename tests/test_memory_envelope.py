"""Memory-envelope pin for the dense assignment path.

ops/assign.py claims dense [P, T] kernels "cap out around ~30k x 30k on a
16 GB chip". This pins that claim to XLA's compile-time memory analysis
(platform-independent buffer assignment: argument + temp sizes) instead of
leaving it asserted: measure bytes/cell at two sizes, check the quadratic
scaling model holds, and extrapolate to the documented ceiling."""

import jax
import jax.numpy as jnp
import pytest

from protocol_tpu.ops.assign import assign_auction

HBM_BYTES = 16e9  # v5e chip HBM
CLAIMED_CEILING = 30_000


def _bytes_for(n: int) -> int:
    fn = lambda c: assign_auction(c, eps=0.05, max_iters=300).provider_for_task
    lowered = jax.jit(fn).lower(jax.ShapeDtypeStruct((n, n), jnp.float32))
    ma = lowered.compile().memory_analysis()
    return ma.temp_size_in_bytes + ma.argument_size_in_bytes


def test_dense_auction_memory_model_and_ceiling():
    b2, b4 = _bytes_for(2048), _bytes_for(4096)
    # quadratic scaling: 4x the cells -> ~4x the bytes (within 15%)
    ratio = b4 / b2
    assert 3.4 < ratio < 4.6, f"non-quadratic memory scaling: {ratio:.2f}"

    per_cell = b4 / (4096 * 4096)
    projected = per_cell * CLAIMED_CEILING**2
    # the documented ceiling must FIT 16 GB...
    assert projected < HBM_BYTES, (
        f"claimed {CLAIMED_CEILING}x{CLAIMED_CEILING} needs "
        f"{projected / 1e9:.1f} GB > 16 GB — ops/assign.py's envelope "
        "claim is wrong, update it"
    )
    # ...and be a real ceiling, not a loose one: the next pow2 bucket
    # (per the matcher's bucketing) must NOT fit, which is why the
    # blocked/sparse paths exist for the 100k-1M ladder
    next_bucket = per_cell * (2 * CLAIMED_CEILING) ** 2
    assert next_bucket > HBM_BYTES, (
        f"2x the claimed ceiling still fits ({next_bucket / 1e9:.1f} GB) — "
        "the documented envelope is too conservative"
    )


def test_matcher_reports_replica_slot_truncation():
    """The batch matcher must COUNT dropped replica slots (no silent caps
    in the core matcher) — VERDICT r1 weak point #4."""
    from protocol_tpu.models.task import SchedulingConfig, Task, TaskRequest
    from protocol_tpu.sched.tpu_backend import TpuBatchMatcher
    from protocol_tpu.store import NodeStatus, OrchestratorNode, StoreContext

    store = StoreContext.new_test()
    for i in range(4):
        store.node_store.add_node(
            OrchestratorNode(address=f"0xn{i}", status=NodeStatus.HEALTHY)
        )
    # demand 3 replicas x 2 tasks = 6 slots against a cap of 4
    for i in range(2):
        store.task_store.add_task(
            Task.from_request(
                TaskRequest(
                    name=f"t{i}",
                    image="img",
                    scheduling_config=SchedulingConfig(
                        plugins={"tpu_scheduler": {"replicas": ["3"]}}
                    ),
                )
            )
        )
    matcher = TpuBatchMatcher(store, min_solve_interval=0.0, max_replica_slots=4)
    matcher.refresh()
    assert matcher.last_solve_stats["truncated_replica_slots"] == 2

    # under the cap: zero truncation reported
    matcher2 = TpuBatchMatcher(store, min_solve_interval=0.0, max_replica_slots=64)
    matcher2.refresh()
    assert matcher2.last_solve_stats["truncated_replica_slots"] == 0


def test_native_fallback_matcher_assigns_equivalently():
    """TpuBatchMatcher(native_fallback=True) solves with the C++ engine
    (the framework's no-accelerator backend): assignments must respect
    replica bounds and compatibility exactly like the jax path."""
    import random

    from protocol_tpu import native

    if not native.available():
        pytest.skip("no native toolchain")

    from protocol_tpu.models.task import SchedulingConfig, Task, TaskRequest
    from protocol_tpu.sched.tpu_backend import TpuBatchMatcher
    from protocol_tpu.store import NodeStatus, OrchestratorNode, StoreContext
    from tests.test_encoding import random_specs

    rng = random.Random(3)
    store = StoreContext.new_test()
    for i in range(16):
        store.node_store.add_node(
            OrchestratorNode(
                address=f"0xnf{i:02d}",
                status=NodeStatus.HEALTHY,
                compute_specs=random_specs(rng),
            )
        )
    for i in range(4):
        cfg = SchedulingConfig(
            plugins={"tpu_scheduler": {"replicas": ["3"]}}
        ) if i % 2 == 0 else None
        store.task_store.add_task(
            Task.from_request(
                TaskRequest(name=f"nf-{i}", image="img", scheduling_config=cfg)
            )
        )

    jax_m = TpuBatchMatcher(store, min_solve_interval=0.0)
    nat_m = TpuBatchMatcher(store, min_solve_interval=0.0, native_fallback=True)
    jax_m.refresh()
    nat_m.refresh()

    assert nat_m.last_solve_stats["assigned"] > 0
    # replica bounds respected on the native path
    by_task: dict = {}
    for addr, tid in nat_m._assignment.items():
        by_task.setdefault(tid, []).append(addr)
    for tid, addrs in by_task.items():
        task = store.task_store.get_task(tid)
        if task.name.endswith(("0", "2")):  # bounded at 3
            assert len(addrs) <= 3, (task.name, addrs)
    # both backends achieve comparable coverage (auction tie-breaks may
    # differ between engines; coverage must not)
    assert (
        abs(nat_m.last_solve_stats["assigned"] - jax_m.last_solve_stats["assigned"])
        <= 2
    )
