"""Test configuration.

Force JAX onto a virtual 8-device CPU platform so multi-chip sharding logic
(mesh construction, shard_map kernels, collective layouts) is exercised
hermetically without TPU hardware.

The ambient environment registers a remote-TPU PJRT plugin via
sitecustomize and forces ``jax_platforms="axon,cpu"`` through
jax.config.update (which takes precedence over the JAX_PLATFORMS env var),
so we must override the config value after importing jax — env vars alone
are not enough.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

from protocol_tpu.utils.platform import force_host_cpu  # noqa: E402

force_host_cpu(8)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running (tier-1 runs with -m 'not slow')",
    )
