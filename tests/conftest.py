"""Test configuration.

Force JAX onto a virtual 8-device CPU platform so multi-chip sharding logic
(mesh construction, shard_map kernels, collective layouts) is exercised
hermetically without TPU hardware.

The ambient environment registers a remote-TPU PJRT plugin via
sitecustomize and forces ``jax_platforms="axon,cpu"`` through
jax.config.update (which takes precedence over the JAX_PLATFORMS env var),
so we must override the config value after importing jax — env vars alone
are not enough.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
