"""Parity: vectorized compat_mask vs the Python ComputeSpecs.meets() oracle
over randomized specs/requirements covering every branch of the algebra."""

import random

import numpy as np
import pytest

from protocol_tpu.models import (
    ComputeRequirements,
    ComputeSpecs,
    CpuSpecs,
    GpuRequirements,
    GpuSpecs,
    NodeLocation,
)
from protocol_tpu.ops.encoding import FeatureEncoder, compat_mask

MODELS = [
    "NVIDIA H100 80GB HBM3",
    "NVIDIA A100-SXM4-80GB",
    "NVIDIA GeForce RTX 4090",
    "NVIDIA GeForce RTX 3090",
    "H200",
    "Tesla V100-SXM2-16GB",
]
REQ_MODELS = ["H100", "A100", "RTX 4090", "H100, A100", "rtx_3090", "V100", "B200"]


def random_specs(rng: random.Random) -> ComputeSpecs:
    gpu = None
    if rng.random() < 0.8:
        gpu = GpuSpecs(
            count=rng.choice([None, 1, 2, 4, 8]),
            model=rng.choice([None] + MODELS),
            memory_mb=rng.choice([None, 16000, 24000, 40000, 80000]),
        )
    cpu = CpuSpecs(cores=rng.choice([None, 4, 16, 64])) if rng.random() < 0.8 else None
    return ComputeSpecs(
        gpu=gpu,
        cpu=cpu,
        ram_mb=rng.choice([None, 8192, 65536, 262144]),
        storage_gb=rng.choice([None, 100, 1000, 4000]),
    )


def random_gpu_req(rng: random.Random) -> GpuRequirements:
    g = GpuRequirements()
    g.count = rng.choice([None, 0, 1, 2, 4, 8])
    g.model = rng.choice([None] + REQ_MODELS)
    if rng.random() < 0.5:
        g.memory_mb = rng.choice([None, 16000, 40000, 80000])
    else:
        g.memory_mb_min = rng.choice([None, 16000, 40000])
        g.memory_mb_max = rng.choice([None, 80000, 100000])
        if (
            g.memory_mb_min is not None
            and g.memory_mb_max is not None
            and g.memory_mb_min > g.memory_mb_max
        ):
            g.memory_mb_max = None
    g.total_memory_min = rng.choice([None, 100000, 600000])
    g.total_memory_max = rng.choice([None, 700000])
    if (
        g.total_memory_min is not None
        and g.total_memory_max is not None
        and g.total_memory_min > g.total_memory_max
    ):
        g.total_memory_max = None
    return g


def random_requirements(rng: random.Random) -> ComputeRequirements:
    n_gpu = rng.choice([0, 1, 1, 2, 3])
    return ComputeRequirements(
        gpu=[random_gpu_req(rng) for _ in range(n_gpu)],
        cpu=CpuSpecs(cores=rng.choice([None, 2, 8, 32])) if rng.random() < 0.5 else None,
        ram_mb=rng.choice([None, 4096, 65536]),
        storage_gb=rng.choice([None, 50, 2000]),
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_compat_mask_parity(seed):
    rng = random.Random(seed)
    P, T = 40, 60
    specs = [random_specs(rng) for _ in range(P)]
    reqs = [random_requirements(rng) for _ in range(T)]

    enc = FeatureEncoder()
    ep = enc.encode_providers(specs)
    er = enc.encode_requirements(reqs)
    mask = np.asarray(compat_mask(ep, er))

    for i in range(P):
        for j in range(T):
            expected = specs[i].meets(reqs[j])
            assert mask[i, j] == expected, (
                f"mismatch p={i} t={j}: kernel={mask[i, j]} oracle={expected}\n"
                f"specs={specs[i]}\nreqs={reqs[j]}"
            )


def test_compat_mask_none_specs():
    enc = FeatureEncoder()
    ep = enc.encode_providers([None, ComputeSpecs()])
    er = enc.encode_requirements([ComputeRequirements(), ComputeRequirements.parse("ram_mb=1")])
    mask = np.asarray(compat_mask(ep, er))
    # empty requirements pass for anyone; ram req fails for spec-less nodes
    assert mask[:, 0].all()
    assert not mask[:, 1].any()


def test_padding_rows_invalid():
    enc = FeatureEncoder()
    ep = enc.encode_providers([ComputeSpecs()], pad_to=4)
    er = enc.encode_requirements([ComputeRequirements()], pad_to=6)
    mask = np.asarray(compat_mask(ep, er))
    assert mask[0, 0]
    assert not mask[1:, :].any()
    assert not mask[:, 1:].any()


def test_vocab_growth_and_overflow():
    enc = FeatureEncoder(model_words=1)  # capacity 32
    for i in range(32):
        enc.intern_model(f"model-{i}")
    with pytest.raises(ValueError):
        enc.intern_model("one-too-many")


def test_locations_encoded_in_radians():
    enc = FeatureEncoder()
    ep = enc.encode_providers(
        [ComputeSpecs()], locations=[NodeLocation(latitude=90.0, longitude=180.0)]
    )
    assert np.isclose(float(ep.lat[0]), np.pi / 2)
    assert np.isclose(float(ep.lon[0]), np.pi)
