"""Provider-sharded blocked Sinkhorn: potential parity with the
single-device blocked kernel on the 8-device CPU mesh."""

import numpy as np
import pytest

from protocol_tpu.ops.blocked import sinkhorn_potentials_blocked
from protocol_tpu.ops.cost import CostWeights
from protocol_tpu.parallel import make_mesh, sinkhorn_potentials_sharded

from tests.test_sparse import encode_random_marketplace


@pytest.mark.parametrize("seed,P,T,D", [(0, 32, 32, 8), (1, 64, 16, 4)])
def test_sharded_potentials_match_blocked(seed, P, T, D):
    ep, er = encode_random_marketplace(seed, P, T)
    mesh = make_mesh(D)
    u_s, v_s = sinkhorn_potentials_sharded(
        ep, er, mesh, CostWeights(), eps=0.1, num_iters=40, tile=8
    )
    u_b, v_b = sinkhorn_potentials_blocked(
        ep, er, CostWeights(), eps=0.1, num_iters=40, tile=8
    )
    np.testing.assert_allclose(np.asarray(u_s), np.asarray(u_b), atol=1e-4)
    np.testing.assert_allclose(np.asarray(v_s), np.asarray(v_b), atol=1e-4)


def test_divisibility_enforced():
    ep, er = encode_random_marketplace(2, 30, 16)
    mesh = make_mesh(8)
    with pytest.raises(ValueError):
        sinkhorn_potentials_sharded(ep, er, mesh, tile=8)
