"""Decision-quality plane: the per-task outcome taxonomy, winner
margins, the certified duality-gap bound, churn/starvation signals, and
the tick-indexed SLO burn-rate engine.

The taxonomy tests are ORACLE tests: each population is seeded so a
specific cause (no candidates at all, outbid under capacity pressure,
a carried stale retirement) is known by construction, and the engine's
code must name exactly that cause — at every thread count, for both
engines. The null-buffer tests pin the zero-overhead contract: passing
no outcome buffer must change nothing, bit for bit.
"""

import numpy as np
import pytest

from protocol_tpu import native, obs
from protocol_tpu.obs import quality
from protocol_tpu.obs.slo import SLOConfig, SLOEngine
from protocol_tpu.ops.cost import CostWeights

from tests.test_sparse import encode_random_marketplace

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no native toolchain"
)

INF = np.float32(1e9)


def _unique_candidates(seed, T, P, K):
    """[T, K] candidate rows with UNIQUE providers per row (margin
    oracles need an unambiguous seat slot)."""
    rng = np.random.default_rng(seed)
    cand_p = np.empty((T, K), np.int32)
    for t in range(T):
        cand_p[t] = rng.choice(P, size=K, replace=False)
    cand_c = rng.uniform(0.0, 10.0, size=(T, K)).astype(np.float32)
    return cand_p, cand_c


def _margin_oracle(cand_p, cand_c, p4t, price):
    """Reference winner margin at final prices: value(seat) minus the
    best value over the task's OTHER candidates (floored at -1e8)."""
    T, K = cand_p.shape
    out = np.zeros(T, np.float32)
    for t in range(T):
        seat = p4t[t]
        if seat < 0:
            continue
        vseat = vother = -np.inf
        for j in range(K):
            p = cand_p[t, j]
            if p < 0:
                continue
            v = -cand_c[t, j] - price[p]
            if p == seat:
                vseat = max(vseat, v)
            else:
                vother = max(vother, v)
        out[t] = vseat - max(vother, -1e8)
    return out


class TestAuctionTaxonomy:
    @pytest.mark.parametrize("threads", [1, 2, 4])
    def test_no_candidates_cause(self, threads):
        """Rows seeded with NO feasible candidate must come back
        unassigned:no_candidates — and only those rows."""
        cand_p, cand_c = _unique_candidates(0, 64, 128, 8)
        # candidate generation writes p = -1 for every infeasible slot
        # (cost is kInfeasible only on -1 slots) — the no-candidates
        # class is exactly the all-empty rows
        empty = [3, 9, 17, 40, 50]
        cand_p[empty] = -1
        cand_c[empty] = INF
        outs = {}
        p4t, _, _ = native.auction_sparse_mt(
            cand_p, cand_c, num_providers=128, threads=threads,
            outcomes=outs,
        )
        codes = outs["codes"]
        for t in empty:
            assert p4t[t] < 0
            assert codes[t] == native.OUTCOME_NO_CANDIDATES
        rest = np.setdiff1d(np.arange(64), empty)
        assert (codes[rest] == native.OUTCOME_ASSIGNED).all()
        assert (p4t[rest] >= 0).all()
        assert (outs["margin"][p4t < 0] == 0.0).all()

    @pytest.mark.parametrize("threads", [1, 2, 4])
    def test_outbid_under_capacity_pressure(self, threads):
        """T tasks fighting over P < T providers: exactly T - P tasks
        lose, and every loser's cause is outbid/give-up — capacity
        pressure, not a candidate problem."""
        T, P, K = 96, 64, 8
        rng = np.random.default_rng(1)
        cand_p = np.empty((T, K), np.int32)
        for t in range(T):
            cand_p[t] = rng.choice(P, size=K, replace=False)
        cand_c = rng.uniform(0.0, 10.0, size=(T, K)).astype(np.float32)
        outs = {}
        p4t, _, retired = native.auction_sparse_mt(
            cand_p, cand_c, num_providers=P, threads=threads,
            outcomes=outs,
        )
        codes = outs["codes"]
        lost = p4t < 0
        assert int(lost.sum()) == T - P
        assert (codes[lost] == native.OUTCOME_OUTBID).all()
        assert (codes[~lost] == native.OUTCOME_ASSIGNED).all()

    @pytest.mark.parametrize("threads", [1, 2, 4])
    def test_stale_retired_cause(self, threads):
        """A task that ENTERS a warm solve retired (carried flag,
        nothing re-opened it) must be named unassigned:retired — the
        stale class the PR 1 dirty-slot fix exists for — not lumped
        with the tick's fresh give-ups."""
        T, P, K = 96, 64, 8
        rng = np.random.default_rng(2)
        cand_p = np.empty((T, K), np.int32)
        for t in range(T):
            cand_p[t] = rng.choice(P, size=K, replace=False)
        cand_c = rng.uniform(0.0, 10.0, size=(T, K)).astype(np.float32)
        cold_p4t, price, cold_retired = native.auction_sparse_mt(
            cand_p, cand_c, num_providers=P, threads=threads,
        )
        # the carried flag stays set on cleanup-seated tasks by design
        # (PR 1): the stale-unassigned class is retired AND seatless
        stale = np.flatnonzero(cold_retired & (cold_p4t < 0))
        assert int((cold_p4t >= 0).sum()) == P  # saturated marketplace
        assert stale.size == T - P
        # warm re-solve, nothing churned: the carried flags stay set and
        # the losers are the STALE class this tick (cause recorded in a
        # PREVIOUS solve, not this one)
        outs = {}
        p4t, _, retired_out = native.auction_sparse_mt(
            cand_p, cand_c, num_providers=P, threads=threads,
            eps_start=0.02, eps_end=0.02, price=price.copy(),
            retired=cold_retired.copy(),
            seed_provider_for_task=cold_p4t,
            outcomes=outs,
        )
        codes = outs["codes"]
        np.testing.assert_array_equal(p4t, cold_p4t)
        for t in stale:
            assert p4t[t] < 0 and retired_out[t]
            assert codes[t] == native.OUTCOME_RETIRED
        seated = p4t >= 0
        assert (codes[seated] == native.OUTCOME_ASSIGNED).all()

    @pytest.mark.parametrize("threads", [1, 2, 4])
    def test_margins_match_oracle(self, threads):
        cand_p, cand_c = _unique_candidates(3, 128, 256, 8)
        outs = {}
        p4t, price, _ = native.auction_sparse_mt(
            cand_p, cand_c, num_providers=256, threads=threads,
            outcomes=outs,
        )
        oracle = _margin_oracle(cand_p, cand_c, p4t, price)
        np.testing.assert_allclose(
            outs["margin"], oracle, rtol=1e-5, atol=1e-5
        )
        # eps-CS at convergence: winner margins sit above -eps
        assert float(outs["margin"][p4t >= 0].min()) >= -0.02 - 1e-5

    @pytest.mark.parametrize("threads", [1, 2, 4])
    def test_outcomes_thread_invariant(self, threads):
        ep, er = encode_random_marketplace(11, 256, 256)
        cand_p, cand_c = native.fused_topk_candidates(
            ep, er, CostWeights(), k=16, reverse_r=8, extra=16
        )
        ref = {}
        native.auction_sparse_mt(
            cand_p, cand_c, num_providers=256, threads=1, outcomes=ref,
        )
        got = {}
        native.auction_sparse_mt(
            cand_p, cand_c, num_providers=256, threads=threads,
            outcomes=got,
        )
        np.testing.assert_array_equal(got["codes"], ref["codes"])
        np.testing.assert_array_equal(got["margin"], ref["margin"])

    def test_null_buffer_changes_nothing(self):
        """The zero-overhead contract: no outcome buffer, no stats dict
        — bit-identical matching, prices, and retirement either way."""
        ep, er = encode_random_marketplace(4, 256, 256)
        cand_p, cand_c = native.fused_topk_candidates(
            ep, er, CostWeights(), k=16, reverse_r=8, extra=16
        )
        bare = native.auction_sparse_mt(
            cand_p, cand_c, num_providers=256, threads=2,
        )
        outs, stats = {}, {}
        instrumented = native.auction_sparse_mt(
            cand_p, cand_c, num_providers=256, threads=2,
            outcomes=outs, stats=stats,
        )
        for a, b in zip(bare, instrumented):
            np.testing.assert_array_equal(a, b)
        assert "codes" in outs and "plan_cost" in stats


class TestSinkhornTaxonomy:
    def _candidates(self, seed=5, T=128, P=128, K=8):
        return _unique_candidates(seed, T, P, K)

    @pytest.mark.parametrize("threads", [1, 2, 4])
    def test_support_taxonomy_and_invariance(self, threads):
        cand_p, cand_c = self._candidates()
        unsupported = [2, 77]
        cand_p[unsupported] = -1
        ref_out = {}
        f1, g1, _, _ = native.sinkhorn_sparse_mt(
            cand_p, cand_c, num_providers=128, eps=0.05,
            max_iters=200, threads=1, outcomes=ref_out,
        )
        outs = {}
        f, g, _, _ = native.sinkhorn_sparse_mt(
            cand_p, cand_c, num_providers=128, eps=0.05,
            max_iters=200, threads=threads, outcomes=outs,
        )
        np.testing.assert_array_equal(f, f1)
        np.testing.assert_array_equal(g, g1)
        np.testing.assert_array_equal(outs["codes"], ref_out["codes"])
        np.testing.assert_array_equal(outs["margin"], ref_out["margin"])
        codes = outs["codes"]
        for t in unsupported:
            assert codes[t] == native.OUTCOME_NO_CANDIDATES
            assert outs["margin"][t] == 0.0
        supported = np.setdiff1d(np.arange(128), unsupported)
        assert (codes[supported] == native.OUTCOME_ASSIGNED).all()

    def test_margin_is_entropic_argmax_margin(self):
        cand_p, cand_c = self._candidates(seed=6)
        outs = {}
        f, _, _, _ = native.sinkhorn_sparse_mt(
            cand_p, cand_c, num_providers=128, eps=0.05,
            max_iters=200, threads=2, outcomes=outs,
        )
        for t in [0, 17, 99]:
            vals = np.sort(f[cand_p[t]] - cand_c[t])[::-1]
            assert outs["margin"][t] == pytest.approx(
                vals[0] - vals[1], rel=1e-5, abs=1e-5
            )

    def test_null_buffer_identity(self):
        cand_p, cand_c = self._candidates(seed=7)
        f0, g0, i0, e0 = native.sinkhorn_sparse_mt(
            cand_p, cand_c, num_providers=128, eps=0.05,
            max_iters=200, threads=2,
        )
        outs = {}
        f1, g1, i1, e1 = native.sinkhorn_sparse_mt(
            cand_p, cand_c, num_providers=128, eps=0.05,
            max_iters=200, threads=2, outcomes=outs,
        )
        np.testing.assert_array_equal(f0, f1)
        np.testing.assert_array_equal(g0, g1)
        assert (i0, e0) == (i1, e1)


class TestGapCertificate:
    def test_engine_certificate_matches_reference_scan(self):
        """gap_from_certificate (O(T) from the engine's margin pass)
        and duality_gap (the O(T*K) numpy reference) must agree — same
        certificate, two derivations."""
        cand_p, cand_c = _unique_candidates(8, 256, 256, 8)
        outs, stats = {}, {}
        p4t, price, _ = native.auction_sparse_mt(
            cand_p, cand_c, num_providers=256, threads=2,
            outcomes=outs, stats=stats,
        )
        ref = quality.duality_gap(cand_p, cand_c, p4t, price)
        cert = quality.gap_from_certificate(
            p4t, stats["plan_cost"], stats["cs_slack"],
            stats["idle_price"],
        )
        assert cert["plan_cost"] == pytest.approx(
            ref["plan_cost"], rel=1e-5
        )
        assert cert["gap_total"] == pytest.approx(
            ref["gap_total"], rel=1e-3, abs=1e-3
        )
        assert cert["idle_price"] == pytest.approx(
            ref["idle_price"], rel=1e-5, abs=1e-5
        )

    def test_gap_is_a_certificate(self):
        """The bound must be SOUND: plan cost minus the optimal
        assignment cost (brute-forced on a small instance) is <= the
        reported gap."""
        from scipy.optimize import linear_sum_assignment

        T = P = K = 16
        rng = np.random.default_rng(9)
        cost = rng.uniform(0.0, 10.0, size=(T, P)).astype(np.float32)
        cand_p = np.tile(np.arange(P, dtype=np.int32), (T, 1))
        cand_c = cost.copy()
        p4t, price, _ = native.auction_sparse_mt(
            cand_p, cand_c, num_providers=P, threads=1,
        )
        gap = quality.duality_gap(cand_p, cand_c, p4t, price)
        plan = sum(cost[t, p4t[t]] for t in range(T) if p4t[t] >= 0)
        rows, cols = linear_sum_assignment(cost)
        opt = float(cost[rows, cols].sum())
        assert plan - opt <= gap["gap_total"] + 1e-4
        assert gap["dual_bound"] <= opt + 1e-4

    def test_converged_gap_within_2eps(self):
        """The acceptance bound: on a saturated marketplace (the synth
        population the golden trace and the CI gate run) the certified
        per-task gap at auction convergence sits within 2x the engine
        eps."""
        from protocol_tpu.trace.synth import (
            synth_providers, synth_requirements,
        )

        ep = synth_providers(np.random.default_rng(10), 512)
        er = synth_requirements(np.random.default_rng(11), 512)
        cand_p, cand_c = native.fused_topk_candidates(
            ep, er, CostWeights(), k=16, reverse_r=8, extra=16
        )
        p4t, price, _ = native.auction_sparse_mt(
            cand_p, cand_c, num_providers=512, threads=2,
        )
        assert int((p4t >= 0).sum()) == 512
        gap = quality.duality_gap(cand_p, cand_c, p4t, price)
        assert gap["gap_per_task"] <= 2 * 0.02


class TestQualitySignals:
    def test_plan_churn(self):
        prev = np.array([0, 1, 2, -1, 4], np.int32)
        cur = np.array([0, 2, 2, 3, -1], np.int32)
        rows, ratio = quality.plan_churn(prev, cur, None)
        assert (rows, ratio) == (3, 0.6)
        valid = np.array([1, 1, 1, 1, 0], bool)
        rows, ratio = quality.plan_churn(prev, cur, valid)
        assert (rows, ratio) == (2, 0.5)

    def test_starvation_ages_and_hist(self):
        p4t = np.array([-1, 0, -1, 1], np.int32)
        age = quality.starvation_update(None, p4t, None)
        np.testing.assert_array_equal(age, [1, 0, 1, 0])
        age = quality.starvation_update(age, p4t, None)
        np.testing.assert_array_equal(age, [2, 0, 2, 0])
        p4t2 = np.array([-1, 0, 5, 1], np.int32)
        age = quality.starvation_update(age, p4t2, None)
        np.testing.assert_array_equal(age, [3, 0, 0, 0])
        hist = quality.starvation_hist(age)
        assert sum(hist) == 1
        assert hist[quality.STARVE_BUCKETS.index(4)] == 1  # bucket (2,4]
        # invalid rows never starve
        age = quality.starvation_update(
            None, np.array([-1, -1]), np.array([True, False])
        )
        np.testing.assert_array_equal(age, [1, 0])

    def test_tick_quality_unexplained_invariant(self):
        """An unassigned valid task whose code claims "assigned" is the
        one inconsistency the CI gate hunts — tick_quality must count
        it."""
        cand_p, cand_c = _unique_candidates(12, 8, 16, 4)
        p4t = np.array([0, 1, -1, 2, -1, 3, 4, 5], np.int32)
        codes = np.zeros(8, np.uint8)
        codes[2] = native.OUTCOME_OUTBID  # explained
        # task 4 unassigned but coded "assigned": unexplained
        stats, _ = quality.tick_quality(
            cand_p, cand_c, p4t, None,
            outcomes={"codes": codes, "margin": np.zeros(8, np.float32)},
        )
        assert stats["outcome_unexplained"] == 1
        assert stats["outcome_outbid"] == 1


class TestArenaQuality:
    def _solve_chain(self, engine="auction"):
        import dataclasses

        from protocol_tpu.native.arena import NativeSolveArena

        ep, er = encode_random_marketplace(13, 192, 256)  # tasks > slots
        arena = NativeSolveArena(threads=2, engine=engine)
        arena.solve(ep, er, CostWeights())
        stats = [dict(arena.last_stats)]
        for i in range(3):
            price = np.array(ep.price, copy=True)
            price[[i, i + 7]] += 0.25
            ep = dataclasses.replace(ep, price=price)
            arena.solve(ep, er, CostWeights())
            stats.append(dict(arena.last_stats))
        return stats

    @pytest.mark.parametrize("engine", ["auction", "sinkhorn"])
    def test_last_stats_carries_quality(self, engine):
        assert obs.enabled()
        stats = self._solve_chain(engine)
        for s in stats:
            assert "gap_per_task" in s
            assert s["outcome_unexplained"] == 0
            assert "starve_hist" in s
            total = sum(
                s[k] for _, k in quality.OUTCOME_STAT_KEYS
            )
            assert total == 256  # every valid task classified
        # warm ticks carry churn; the cold tick cannot
        assert "churn_ratio" not in stats[0]
        assert all("churn_ratio" in s for s in stats[1:])

    def test_starvation_persists_across_warm_ticks(self):
        stats = self._solve_chain()
        # 256 tasks / 192 providers: ~64 tasks starve every tick, and
        # the age of the persistent losers must climb tick over tick
        assert stats[0]["starving"] > 0
        assert stats[-1]["starve_max"] >= 3

    def test_short_circuit_tick_advances_starvation(self):
        from protocol_tpu.native.arena import NativeSolveArena

        ep, er = encode_random_marketplace(14, 192, 256)
        arena = NativeSolveArena(threads=2)
        arena.solve(ep, er, CostWeights())
        m0 = arena.last_stats["starve_max"]
        arena.solve(ep, er, CostWeights())  # byte-identical: short-circuit
        s = arena.last_stats
        assert s["changed_rows"] == 0
        assert s["churn_ratio"] == 0.0
        assert s["starve_max"] == m0 + 1  # ages advance, plan reused
        assert s["gap_per_task"] is not None  # carried certificate reused

    def test_obs_disabled_skips_quality(self):
        from protocol_tpu.native.arena import NativeSolveArena

        ep, er = encode_random_marketplace(15, 128, 128)
        obs.set_enabled(False)
        try:
            arena = NativeSolveArena(threads=2)
            arena.solve(ep, er, CostWeights())
            assert "gap_per_task" not in arena.last_stats
        finally:
            obs.set_enabled(True)


class TestSLOEngine:
    def _cfg(self, **kw):
        kw.setdefault("min_assigned_frac", 0.95)
        return SLOConfig(**kw)

    def test_inert_without_objectives(self):
        eng = SLOEngine(SLOConfig())
        assert eng.observe("s", "t", 0, {"assigned_frac": 0.0}) == []
        assert eng.snapshot()["fired_total"] == 0

    def test_multi_window_fire_and_clear(self):
        """Sustained badness fires once both windows fill and burn past
        the threshold; recovery clears the alert — and the whole
        sequence is a pure function of the tick-indexed inputs."""
        eng = SLOEngine(self._cfg())
        events = []
        for tick in range(32):
            events += eng.observe(
                "s", "ten", tick, {"assigned_frac": 0.5}
            )
        assert [e["state"] for e in events] == ["fire"]
        assert events[0]["slo"] == "assigned_frac"
        # the fast pair (8, 32) fires the moment its LONG window fills
        # (a half-filled window must not page); the slow pair's 128-tick
        # window never fills in 32 ticks
        assert events[0]["tick"] == 31
        assert events[0]["window"] == [8, 32]
        assert eng.fired_total == 1
        cleared = []
        for tick in range(32, 64):
            cleared += eng.observe(
                "s", "ten", tick, {"assigned_frac": 1.0}
            )
        assert {e["state"] for e in cleared} == {"clear"}
        assert eng.active_alerts() == []

    def test_one_tick_blip_does_not_page(self):
        eng = SLOEngine(self._cfg())
        events = []
        for tick in range(64):
            frac = 0.5 if tick == 10 else 1.0
            events += eng.observe("s", "t", tick, {"assigned_frac": frac})
        assert events == []

    def test_deterministic_replay(self):
        rng = np.random.default_rng(16)
        seq = rng.uniform(0.8, 1.0, size=200)
        runs = []
        for _ in range(2):
            eng = SLOEngine(self._cfg(min_assigned_frac=0.9))
            ev = []
            for tick, frac in enumerate(seq):
                ev += eng.observe("s", "t", tick, {"assigned_frac": float(frac)})
            runs.append(ev)
        assert runs[0] == runs[1]

    def test_cold_ticks_skip_latency_objective(self):
        eng = SLOEngine(SLOConfig(p99_warm_tick_ms=1.0))
        for tick in range(64):
            assert eng.observe(
                "s", "t", tick, {"wall_ms": 50.0}, cold=True
            ) == []

    def test_registry_integration_and_trace_events(self):
        """ObsRegistry feeds the SLO engine under its lock and returns
        the fired events; the snapshot carries config + recent alerts."""
        from protocol_tpu.obs.metrics import ObsRegistry

        reg = ObsRegistry(role="test")
        reg.attach(slo=SLOEngine(self._cfg()))
        fired = []
        for _ in range(32):  # fast pair: long window is 32 ticks
            fired += reg.observe_tick(
                "ten@sess", 1.0, 100, 10, arena_stats={"cold": False}
            )
        assert any(e["state"] == "fire" for e in fired)
        assert fired[0]["tenant"] == "ten"
        snap = reg.snapshot()
        assert snap["slo"]["fired_total"] >= 1
        assert snap["slo"]["recent"]
        assert snap["slo"]["config"]["min_assigned_frac"] == 0.95

    def test_slo_breach_lands_event_frames_in_trace(
        self, tmp_path, monkeypatch
    ):
        """End to end over a live wire-v2 session: an impossible
        assigned-frac objective must fire, the breach must land in the
        flight recorder as a tick-anchored EVENT frame, and the obs
        report must surface it — replay ignores the frame (events are
        observational, never solve inputs)."""
        import bench
        from protocol_tpu.obs import report as obs_report
        from protocol_tpu.obs.slo import SLOConfig
        from protocol_tpu.ops.cost import CostWeights
        from protocol_tpu.proto import scheduler_pb2 as pb
        from protocol_tpu.proto import wire
        from protocol_tpu.services.scheduler_grpc import (
            SchedulerBackendClient,
            serve,
        )
        from protocol_tpu.trace import format as tfmt

        path = str(tmp_path / "slo.trace")
        monkeypatch.setenv("PROTOCOL_TPU_TRACE", path)
        # assigned_frac > 1 is unsatisfiable: every tick is bad, so the
        # fast (8, 32) pair must fire the moment 32 ticks land
        server = serve(
            "127.0.0.1:50981", slo=SLOConfig(min_assigned_frac=1.1)
        )
        client = SchedulerBackendClient("127.0.0.1:50981")
        try:
            rng = np.random.default_rng(0)
            ep = bench.synth_providers(rng, 96)
            er = bench.synth_requirements(rng, 96)
            w = CostWeights()
            p_cols = wire.canon_columns(ep, wire.P_WIRE_DTYPES)
            r_cols = wire.canon_columns(er, wire.R_WIRE_DTYPES)
            fp = wire.epoch_fingerprint(
                p_cols, r_cols, w, "native-mt:1", 32, 0.02, 0
            )
            req = pb.AssignRequestV2(
                providers=wire.encode_providers_v2(ep),
                requirements=wire.encode_requirements_v2(er),
                weights=pb.CostWeights(
                    price=w.price, load=w.load, proximity=w.proximity,
                    priority=w.priority,
                ),
                kernel="native-mt:1", top_k=32, eps=0.02,
            )
            resp = client.open_session(wire.chunk_snapshot("ten@s", fp, req))
            assert resp.ok, resp.error
            churn = np.random.default_rng(1)
            for tick in range(1, 36):
                rows = np.sort(
                    churn.choice(96, 2, replace=False).astype(np.int32)
                )
                price = p_cols["price"].copy()
                price[rows] = churn.uniform(0.5, 4.0, rows.size).astype(
                    np.float32
                )
                p_cols["price"] = price
                d = pb.AssignDeltaRequest(
                    session_id="ten@s", epoch_fingerprint=fp, tick=tick
                )
                d.provider_rows.CopyFrom(wire.blob(rows, np.int32))
                d.providers.CopyFrom(
                    wire.encode_providers_v2(wire.take_rows(p_cols, rows))
                )
                dr = client.assign_delta(d)
                assert dr.session_ok, dr.error
            snap = server.servicer.obs.snapshot()
            assert snap["slo"]["fired_total"] >= 1
            assert snap["slo"]["fired_by_tenant"].get("ten") >= 1
        finally:
            client.close()
            server.stop(grace=None)
        t = tfmt.read_trace(path)
        fired = [
            e for frame in t.events for e in frame["events"]
            if e["kind"] == "slo" and e["state"] == "fire"
        ]
        assert fired and fired[0]["slo"] == "assigned_frac"
        assert fired[0]["tenant"] == "ten"
        rendered = "\n".join(
            obs_report.quality_table(t.outcomes, t.events)
        )
        assert "SLO alert events in trace" in rendered

    def test_env_config(self):
        cfg = SLOConfig.from_env({
            "PROTOCOL_TPU_SLO_MIN_ASSIGNED": "0.97",
            "PROTOCOL_TPU_SLO_MAX_GAP": "0.04",
        })
        assert cfg.min_assigned_frac == 0.97
        assert cfg.max_gap_per_task == 0.04
        assert cfg.p99_warm_tick_ms is None
        assert cfg.active()
