"""Distributed fleet-of-fleets (ISSUE 12).

Covers the multi-process contracts the ``--dfleet`` CI gate rests on,
at unit/in-process grain: consistent-hash endpoint routing (failover
order agrees with post-kill re-homing by construction), the
(proc id, session id) journal namespace with atomic rename handoff
(exclusive ownership asserted under concurrent loads), LIVE migration
over a real wire — a session mid-delta-stream is moved between two
servicers with plans bit-identical to fault-free single-process replay
and the retransmit dedup asserted ACROSS the process boundary — the
client ladder's moved-redirect / endpoint-failover / handoff-wait
rungs, and the eviction tombstone that keeps the PR 9 "eviction = one
counted reopen" contract intact next to lazy rehydration. The real
3-subprocess kill -9 drill lives in ``perf_gate.py --dfleet``; a
2-subprocess smoke is here but slow-marked.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from protocol_tpu import native
from protocol_tpu.dfleet.discovery import DiscoveryEndpoint, fetch_topology
from protocol_tpu.dfleet.topology import FleetTopology
from protocol_tpu.faults.checkpoint import (
    SessionCheckpointer,
    handoff_orphans,
    journal_session_id,
)
from protocol_tpu.faults.plan import ChaosConfig
from protocol_tpu.fleet.fabric import FleetConfig
from protocol_tpu.proto import scheduler_pb2 as pb
from protocol_tpu.proto import wire
from protocol_tpu.services.scheduler_grpc import (
    RemoteBatchMatcher,
    SchedulerBackendClient,
    serve,
)
from protocol_tpu.trace import format as tfmt

from tests.test_scheduler_grpc import _pool_world

NATIVE = native.available()


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------- topology: the endpoint ring ----------------


class TestTopology:
    def test_routing_is_deterministic_and_total(self):
        topo = FleetTopology(["a:1", "b:2", "c:3"], vnodes=32)
        homes = {f"t{i}@s{i}": topo.endpoint_for(f"t{i}@s{i}")
                 for i in range(64)}
        again = FleetTopology(["a:1", "b:2", "c:3"], vnodes=32)
        assert homes == {
            sid: again.endpoint_for(sid) for sid in homes
        }
        # all three endpoints get work at this scale
        assert set(homes.values()) == {"a:1", "b:2", "c:3"}

    def test_failover_order_matches_post_kill_rehoming(self):
        """The client's failover list and the ring's re-homing after a
        kill must agree: the session's new home IS the next entry in
        its failover order — journal re-routes and client failover can
        never disagree about where a session lands."""
        topo = FleetTopology(["a:1", "b:2", "c:3"])
        for i in range(48):
            sid = f"t{i % 3}@sess-{i}"
            order = topo.failover_order(sid)
            assert order[0] == topo.endpoint_for(sid)
            assert sorted(order) == sorted(topo.endpoints)
            survived = topo.without(order[0])
            assert survived.endpoint_for(sid) == order[1]

    def test_without_moves_only_the_dead_endpoints_sessions(self):
        topo = FleetTopology(["a:1", "b:2", "c:3"])
        gone = "b:2"
        survived = topo.without(gone)
        assert survived.generation == topo.generation + 1
        for i in range(64):
            sid = f"x@{i}"
            if topo.endpoint_for(sid) != gone:
                assert survived.endpoint_for(sid) == topo.endpoint_for(
                    sid
                )

    def test_duplicate_and_unknown_refused(self):
        with pytest.raises(ValueError):
            FleetTopology(["a:1", "a:1"])
        with pytest.raises(ValueError):
            FleetTopology([])
        with pytest.raises(ValueError):
            FleetTopology(["a:1"], procs={"b:2": "p0"})

    def test_dict_roundtrip(self):
        topo = FleetTopology(
            ["a:1", "b:2"], procs={"a:1": "p7", "b:2": "p9"},
            vnodes=16, generation=3,
        )
        rt = FleetTopology.from_dict(
            json.loads(json.dumps(topo.to_dict()))
        )
        assert rt.generation == 3 and rt.procs == topo.procs
        for i in range(32):
            assert rt.endpoint_for(f"s{i}") == topo.endpoint_for(f"s{i}")


class TestDiscovery:
    def test_fleet_json_and_route(self):
        topo_box = [FleetTopology(["a:1", "b:2", "c:3"])]
        disco = DiscoveryEndpoint(lambda: topo_box[0])
        try:
            fetched = fetch_topology(disco.url)
            assert fetched.endpoints == topo_box[0].endpoints
            sid = "t0@route-me"
            with urllib.request.urlopen(
                f"{disco.url}/route?session={sid}", timeout=10
            ) as r:
                route = json.loads(r.read().decode())
            assert route["endpoint"] == topo_box[0].endpoint_for(sid)
            assert route["failover"] == topo_box[0].failover_order(sid)
            # membership change is visible through the same endpoint
            topo_box[0] = topo_box[0].without("b:2")
            assert fetch_topology(disco.url).generation == 1
        finally:
            disco.stop()

    def test_bad_requests_are_answered_not_crashed(self):
        disco = DiscoveryEndpoint(lambda: FleetTopology(["a:1"]))
        try:
            for path, code in (("/route", 400), ("/nope", 404)):
                try:
                    urllib.request.urlopen(
                        f"{disco.url}{path}", timeout=10
                    )
                    assert False, "expected HTTPError"
                except urllib.error.HTTPError as e:
                    assert e.code == code
        finally:
            disco.stop()


class TestChaosProcessKnobs:
    def test_process_targets_parse_and_roundtrip(self):
        cfg = ChaosConfig.from_spec(
            "seed=5,drop=0.03,kill_proc_at_tick=3,kill_proc=2,"
            "migrate_at_tick=4,migrate_proc=0"
        )
        assert cfg.kill_proc_at_tick == 3 and cfg.kill_proc == 2
        assert cfg.migrate_at_tick == 4 and cfg.migrate_proc == 0
        assert cfg.active()
        assert ChaosConfig.from_spec(cfg.spec()) == cfg

    def test_proc_id_and_endpoint_ride_the_env(self, monkeypatch):
        monkeypatch.setenv("PROTOCOL_TPU_FLEET_PROC_ID", "p7")
        monkeypatch.setenv(
            "PROTOCOL_TPU_FLEET_ENDPOINT", "10.0.0.7:50061"
        )
        cfg = FleetConfig.from_env()
        assert cfg.proc_id == "p7"
        assert cfg.endpoint == "10.0.0.7:50061"


# ---------------- wire helpers (session driving) ----------------


def _synth(tmp_path, ticks=6, seed=3, n=64):
    from protocol_tpu.trace.synth import synth_trace

    path = str(tmp_path / "dfleet.trace")
    synth_trace(
        path, n_providers=n, n_tasks=n, ticks=ticks, churn=0.05,
        seed=seed, kernel="native-mt:1",
    )
    return path


def _open_session(client, snap, sid, p_cols, r_cols):
    w = tfmt._as_ns(dict(zip(
        ("price", "load", "proximity", "priority"), snap.weights
    )))
    fp = wire.epoch_fingerprint(
        p_cols, r_cols, w, "native-mt:1",
        max(int(snap.top_k) or 64, 1), snap.eps, snap.max_iters,
    )
    req = pb.AssignRequestV2(
        providers=wire.encode_providers_v2(tfmt._as_ns(p_cols)),
        requirements=wire.encode_requirements_v2(tfmt._as_ns(r_cols)),
        weights=pb.CostWeights(
            price=snap.weights[0], load=snap.weights[1],
            proximity=snap.weights[2], priority=snap.weights[3],
        ),
        kernel="native-mt:1", top_k=snap.top_k, eps=snap.eps,
        max_iters=snap.max_iters,
    )
    chunks = list(wire.chunk_snapshot(sid, fp, req))
    return fp, client.open_session(iter(chunks), timeout=120)


def _delta_request(sid, fp, tick, delta):
    req = pb.AssignDeltaRequest(
        session_id=sid, epoch_fingerprint=fp, tick=tick
    )
    if delta.provider_rows.size:
        req.provider_rows.CopyFrom(wire.blob(delta.provider_rows, np.int32))
        req.providers.CopyFrom(
            wire.encode_providers_v2(tfmt._as_ns(delta.p_cols))
        )
    if delta.task_rows.size:
        req.task_rows.CopyFrom(wire.blob(delta.task_rows, np.int32))
        req.requirements.CopyFrom(
            wire.encode_requirements_v2(tfmt._as_ns(delta.r_cols))
        )
    return req


def _serve_pair(root):
    """Two servicers sharing one journal root, distinct namespaces —
    the in-test stand-in for two fleet processes (same wire protocol,
    same checkpointers, one GIL)."""
    addr_a = f"127.0.0.1:{_free_port()}"
    addr_b = f"127.0.0.1:{_free_port()}"
    a = serve(addr_a, fleet=FleetConfig(
        shards=2, ckpt_dir=root, proc_id="p0", endpoint=addr_a))
    b = serve(addr_b, fleet=FleetConfig(
        shards=2, ckpt_dir=root, proc_id="p1", endpoint=addr_b))
    return (addr_a, a), (addr_b, b)


# ---------------- journal namespace + atomic handoff ----------------


@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
class TestJournalNamespace:
    @pytest.fixture()
    def flushed(self, tmp_path):
        """A real flushed journal in p0's namespace (driven over the
        wire so the journal is exactly what production writes)."""
        from protocol_tpu.trace.replay import iter_input_ticks

        root = str(tmp_path / "journals")
        (addr_a, a), (addr_b, b) = _serve_pair(root)
        trace = tfmt.read_trace(_synth(tmp_path, ticks=2))
        sid = "t0@ns-test"
        client = SchedulerBackendClient(addr_a)
        fp = None
        server_tick = 0
        try:
            for tick, p_cols, r_cols, delta in iter_input_ticks(trace):
                if tick == 0:
                    fp, resp = _open_session(
                        client, trace.snapshot, sid, p_cols, r_cols
                    )
                    assert resp.ok, resp.error
                else:
                    resp = client.assign_delta(_delta_request(
                        sid, fp, server_tick + 1, delta
                    ), timeout=120)
                    assert resp.session_ok, resp.error
                    server_tick += 1
            yield root, sid, server_tick
        finally:
            client.close()
            a.stop(grace=None)
            b.stop(grace=None)

    def test_namespace_is_exclusive(self, flushed):
        root, sid, tick = flushed
        p0 = SessionCheckpointer(root, proc_id="p0")
        p1 = SessionCheckpointer(root, proc_id="p1")
        assert journal_session_id(p0.path_for(sid)) == sid
        assert p1.load_one(sid) is None  # not p1's journal
        restored = p0.load_one(sid)
        assert restored is not None and restored.tick == tick

    def test_handoff_moves_ownership_atomically(self, flushed):
        root, sid, tick = flushed
        p0 = SessionCheckpointer(root, proc_id="p0")
        p1 = SessionCheckpointer(root, proc_id="p1")
        assert p0.handoff(sid, "p1") is True
        assert p0.handoff(sid, "p1") is False  # already gone
        assert p0.load_one(sid) is None
        restored = p1.load_one(sid)
        assert restored is not None
        assert restored.tick == tick
        assert restored.last_p4t is not None

    def test_concurrent_loads_never_break_exclusivity(self, flushed):
        """The satellite race test: ownership flips while the OTHER
        side is loading; after every handoff completes the source can
        never load the journal, and the target always can — a journal
        is rehydratable from exactly one namespace."""
        root, sid, _ = flushed
        p0 = SessionCheckpointer(root, proc_id="p0")
        p1 = SessionCheckpointer(root, proc_id="p1")
        for i in range(12):
            owner, other = (p0, p1) if i % 2 == 0 else (p1, p0)
            racer_result = []

            def _racer():
                # races the rename from the TARGET side: legal answers
                # are None (pre-rename) or the session (post-rename)
                racer_result.append(other.load_one(sid))

            th = threading.Thread(target=_racer)
            th.start()
            assert owner.handoff(sid, other.proc_id) is True
            th.join()
            assert owner.load_one(sid) is None
            got = other.load_one(sid)
            assert got is not None and got.session_id == sid
            for r in racer_result:
                assert r is None or r.session_id == sid

    def test_orphan_reroute_by_meta_session_id(self, flushed):
        root, sid, tick = flushed
        moved = handoff_orphans(root, "p0", lambda s: "p2")
        assert moved == [(sid, "p2")]
        p2 = SessionCheckpointer(root, proc_id="p2")
        restored = p2.load_one(sid)
        assert restored is not None and restored.tick == tick
        # route=None leaves journals in place
        assert handoff_orphans(root, "p2", lambda s: None) == []
        assert p2.load_one(sid) is not None


# ---------------- live migration over a real wire ----------------


@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
class TestLiveMigration:
    def test_mid_stream_migration_is_warm_and_bit_identical(
        self, tmp_path
    ):
        """The tentpole drill at unit grain: a session mid-delta-stream
        is checkpointed, migrated, and resumed on a second servicer;
        every plan must be bit-identical to fault-free single-process
        replay, and a retransmit of the last tick must be answered as
        the replayed twin ACROSS the process boundary."""
        from protocol_tpu.trace.replay import iter_input_ticks, replay

        trace_path = _synth(tmp_path, ticks=6)
        trace = tfmt.read_trace(trace_path)
        baseline = replay(
            trace_path, engine="native-mt:1", verify=False,
            keep_p4t=True,
        )["p4ts"]
        root = str(tmp_path / "journals")
        (addr_a, a), (addr_b, b) = _serve_pair(root)
        sid = "t0@mig"
        client = SchedulerBackendClient(addr_a)
        moved_redirects = 0
        server_tick = 0
        last_req = last_p4t = fp = None
        try:
            for tick, p_cols, r_cols, delta in iter_input_ticks(trace):
                if tick == 0:
                    fp, resp = _open_session(
                        client, trace.snapshot, sid, p_cols, r_cols
                    )
                    assert resp.ok, resp.error
                    p4t = wire.unblob(
                        resp.result.provider_for_task, np.int32
                    )
                else:
                    if tick == 3:
                        assert a.servicer.migrate_out(addr_b, "p1") == 1
                    req = _delta_request(
                        sid, fp, server_tick + 1, delta
                    )
                    resp = client.assign_delta(req, timeout=120)
                    if not resp.session_ok and resp.error.startswith(
                        "moved:"
                    ):
                        target = resp.error[len("moved:"):].strip()
                        assert target == addr_b
                        moved_redirects += 1
                        client.close()
                        client = SchedulerBackendClient(target)
                        resp = client.assign_delta(req, timeout=120)
                    assert resp.session_ok, f"tick {tick}: {resp.error}"
                    assert not resp.replayed
                    server_tick += 1
                    p4t = wire.unblob(
                        resp.result.provider_for_task, np.int32
                    )
                    last_req, last_p4t = req, p4t
                assert np.array_equal(p4t, baseline[tick]), (
                    f"tick {tick} diverged from fault-free replay"
                )
            assert moved_redirects == 1

            # retransmit dedup across the boundary: the SAME final tick
            # resent to the NEW home replays the cached twin
            resp = client.assign_delta(last_req, timeout=120)
            assert resp.session_ok and resp.replayed
            assert np.array_equal(
                wire.unblob(resp.result.provider_for_task, np.int32),
                last_p4t,
            )

            seam_a = a.servicer.seam.snapshot()
            seam_b = b.servicer.seam.snapshot()
            assert seam_a.get("session_session_migrated_out") == 1
            assert seam_a.get("session_moved_refused") == 1
            assert seam_b.get("session_session_rehydrated") == 1
            # zero reopens anywhere: exactly one session_open total
            assert seam_a.get("session_session_open") == 1
            assert "session_session_open" not in seam_b

            # the persistent candidate structure rode the journal: the
            # rehydrated session's post-handoff delta ticks REPAIRED the
            # carried structure warm — zero full-matrix candidate
            # passes — instead of regenerating cold on the new process
            session, _ = b.servicer.sessions.get(sid, fp)
            assert session is not None
            assert session.arena.last_stats["cold"] is False
            assert session.arena.last_stats["cand_cold_passes"] == 0
        finally:
            client.close()
            a.stop(grace=None)
            b.stop(grace=None)

    def test_rerouted_journal_clears_stale_redirect(self, tmp_path):
        """Migration target dies and the ring re-routes the journal
        BACK to the original home: the stale moved:<dead endpoint>
        entry must not blackhole the session — the journal's location
        is the authority, and the old home adopts the session back
        and serves it warm."""
        from protocol_tpu.trace.replay import iter_input_ticks

        trace = tfmt.read_trace(_synth(tmp_path, ticks=4))
        root = str(tmp_path / "journals")
        (addr_a, a), (addr_b, b) = _serve_pair(root)
        sid = "t0@boomerang"
        client = SchedulerBackendClient(addr_a)
        try:
            ticks = list(iter_input_ticks(trace))
            _t, p_cols, r_cols, _d = ticks[0]
            fp, resp = _open_session(
                client, trace.snapshot, sid, p_cols, r_cols
            )
            assert resp.ok
            assert a.servicer.migrate_out(addr_b, "p1") == 1
            # tick 1 lands at B (rehydrates there, flushes to p1)
            client_b = SchedulerBackendClient(addr_b)
            resp = client_b.assign_delta(
                _delta_request(sid, fp, 1, ticks[1][3]), timeout=120
            )
            assert resp.session_ok, resp.error
            client_b.close()
            # B dies; the ring re-routes its orphaned journal back to A
            b.stop(grace=None)
            assert handoff_orphans(root, "p1", lambda s: "p0") == [
                (sid, "p0")
            ]
            # the delta at A must ADOPT (journal is here), not bounce
            # at the corpse via the stale moved:addr_b entry
            resp = client.assign_delta(
                _delta_request(sid, fp, 2, ticks[2][3]), timeout=120
            )
            assert resp.session_ok, resp.error
            seam_a = a.servicer.seam.snapshot()
            assert seam_a.get("session_session_rehydrated") == 1
            assert "session_moved_refused" not in seam_a
        finally:
            client.close()
            a.stop(grace=None)
            b.stop(grace=None)

    def test_reopen_at_old_home_is_redirected(self, tmp_path):
        """A client that tries to RE-OPEN at the old home after a
        migration is bounced to the new one — opening there would fork
        ownership of the session's state."""
        from protocol_tpu.trace.replay import iter_input_ticks

        trace = tfmt.read_trace(_synth(tmp_path, ticks=1))
        root = str(tmp_path / "journals")
        (addr_a, a), (addr_b, b) = _serve_pair(root)
        sid = "t0@reopen"
        client = SchedulerBackendClient(addr_a)
        try:
            ticks = list(iter_input_ticks(trace))
            _tick, p_cols, r_cols, _d = ticks[0]
            fp, resp = _open_session(
                client, trace.snapshot, sid, p_cols, r_cols
            )
            assert resp.ok
            assert a.servicer.migrate_out(addr_b, "p1") == 1
            _fp, resp = _open_session(
                client, trace.snapshot, sid, p_cols, r_cols
            )
            assert not resp.ok
            assert resp.error == f"moved:{addr_b}"
        finally:
            client.close()
            a.stop(grace=None)
            b.stop(grace=None)

    def test_eviction_tombstone_preserves_reopen_contract(
        self, tmp_path
    ):
        """Lazy rehydration must NOT resurrect a session this process
        itself evicted for capacity — eviction releases memory, and the
        PR 9 contract (forced eviction = the ladder's counted reopen)
        still holds with journals on disk."""
        from protocol_tpu.trace.replay import iter_input_ticks

        trace = tfmt.read_trace(_synth(tmp_path, ticks=2))
        root = str(tmp_path / "journals")
        (addr_a, a), (_addr_b, b) = _serve_pair(root)
        sid = "t0@evict"
        client = SchedulerBackendClient(addr_a)
        try:
            ticks = list(iter_input_ticks(trace))
            _t, p_cols, r_cols, _d = ticks[0]
            fp, resp = _open_session(
                client, trace.snapshot, sid, p_cols, r_cols
            )
            assert resp.ok
            # forced eviction (chaos/pressure shape) — journal remains
            # on disk, but the tombstone forbids lazy resurrection
            assert a.servicer.sessions.shard_of(sid).evict(
                sid, "chaos"
            )
            _t1, _p, _r, delta = ticks[1]
            resp = client.assign_delta(
                _delta_request(sid, fp, 1, delta), timeout=120
            )
            assert not resp.session_ok
            assert "unknown session" in resp.error
            assert "session_session_rehydrated" not in (
                a.servicer.seam.snapshot()
            )
            # a fresh OPEN clears the tombstone (new incarnation)
            fp, resp = _open_session(
                client, trace.snapshot, sid, p_cols, r_cols
            )
            assert resp.ok
        finally:
            client.close()
            a.stop(grace=None)
            b.stop(grace=None)


# ---------------- the production client's dfleet rungs ----------------


@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
class TestRemoteMatcherFailover:
    def test_moved_redirect_resumes_warm_without_reopen(self, tmp_path):
        root = str(tmp_path / "journals")
        (addr_a, a), (addr_b, b) = _serve_pair(root)
        store = _pool_world()
        m = RemoteBatchMatcher(
            store, [addr_a, addr_b], min_solve_interval=0.0, wire="v2",
            native_fallback=True, native_engine="native-mt",
            native_threads=2, retry_base_s=0.01,
        )
        try:
            m.refresh()
            m.refresh()
            assert m._session["tick"] == 1
            moved = a.servicer.migrate_out(addr_b, "p1")
            assert moved == 1
            m.refresh()  # delta -> moved: -> rebind -> SAME delta warm
            snap = m.seam.snapshot()
            assert snap.get("session_moved_redirect") == 1
            assert "session_session_reopen" not in snap
            assert m._session["tick"] == 2
            assert m._assignment
            seam_b = b.servicer.seam.snapshot()
            assert seam_b.get("session_session_rehydrated") == 1
        finally:
            m.client.close()
            a.stop(grace=None)
            b.stop(grace=None)

    def test_kill_plus_handoff_fails_over_warm(self, tmp_path):
        """The crash drill at matcher grain: the session's home dies
        (hard stop), its orphaned journal is re-routed, and the next
        refresh fails over down the endpoint list and resumes WARM —
        zero reopens, the delta stream uninterrupted."""
        root = str(tmp_path / "journals")
        (addr_a, a), (addr_b, b) = _serve_pair(root)
        store = _pool_world()
        m = RemoteBatchMatcher(
            store, [addr_a, addr_b], min_solve_interval=0.0, wire="v2",
            native_fallback=True, native_engine="native-mt",
            native_threads=2, retry_base_s=0.01, retries=4,
        )
        try:
            m.refresh()
            m.refresh()
            assert m._session["tick"] == 1
            a.stop(grace=None)  # kill -9 stand-in
            moved = handoff_orphans(root, "p0", lambda s: "p1")
            assert [s for s, _ in moved] == [m._session["id"]]
            m.refresh()
            snap = m.seam.snapshot()
            assert snap.get("session_endpoint_failover", 0) >= 1
            assert "session_session_reopen" not in snap
            assert m._session["tick"] == 2
            assert m._assignment
            seam_b = b.servicer.seam.snapshot()
            assert seam_b.get("session_session_rehydrated") == 1
        finally:
            m.client.close()
            a.stop(grace=None)
            b.stop(grace=None)


# ---------------- real subprocesses (slow: spawn cost) ----------------


@pytest.mark.slow
@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
class TestProcessFleetSubprocess:
    def test_kill_one_of_two_processes_resumes_warm(self, tmp_path):
        from protocol_tpu.dfleet.manager import ProcessFleet
        from protocol_tpu.fleet.loadgen import run_load

        rep = run_load(
            sessions=2, tenants=2, providers=64, tasks=64, ticks=6,
            churn=0.05, kernel="native-mt:1", shards=2,
            seed=1, processes=2, restart_at_tick=2,
            restart_mode="crash",
            ckpt_dir=str(tmp_path / "journals"),
        )
        assert rep["errors"] == []
        assert rep["drill"].get("killed")
        mig = rep["migration"]
        assert mig["reopens_total"] == 0
        for t, agg in rep["tenants"].items():
            assert agg["min_assigned_frac"] >= 0.9
        # ProcessFleet API surface smoke (scrape/witness join shapes)
        assert set(rep["processes"].keys()) == {"p0", "p1"}
        del ProcessFleet  # imported to assert availability
