"""Distributed fleet-of-fleets (ISSUE 12).

Covers the multi-process contracts the ``--dfleet`` CI gate rests on,
at unit/in-process grain: consistent-hash endpoint routing (failover
order agrees with post-kill re-homing by construction), the
(proc id, session id) journal namespace with atomic rename handoff
(exclusive ownership asserted under concurrent loads), LIVE migration
over a real wire — a session mid-delta-stream is moved between two
servicers with plans bit-identical to fault-free single-process replay
and the retransmit dedup asserted ACROSS the process boundary — the
client ladder's moved-redirect / endpoint-failover / handoff-wait
rungs, and the eviction tombstone that keeps the PR 9 "eviction = one
counted reopen" contract intact next to lazy rehydration. The real
3-subprocess kill -9 drill lives in ``perf_gate.py --dfleet``; a
2-subprocess smoke is here but slow-marked.

ISSUE 14 adds the autonomous resilience tier: the deterministic
heartbeat failure detector (virtual-clock state-machine tests —
alive→suspect→dead, flap suppression, driver-kill exclusion), fenced
journal ownership (monotonic namespace epochs; a superseded process is
``moved:``-refused on delta/open and cannot flush), torn-journal
hardening (counted skip, never a failed re-route), generation-
monotonic topology adoption (manager, discovery poll, client ladder),
and the slow-marked 2-subprocess SIGSTOP zombie drill; the 3-process
CI bar lives in ``perf_gate.py --chaos`` phase C.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from protocol_tpu import native
from protocol_tpu.dfleet.discovery import DiscoveryEndpoint, fetch_topology
from protocol_tpu.dfleet.topology import FleetTopology
from protocol_tpu.faults.checkpoint import (
    SessionCheckpointer,
    handoff_orphans,
    journal_session_id,
)
from protocol_tpu.faults.plan import ChaosConfig
from protocol_tpu.fleet.fabric import FleetConfig
from protocol_tpu.proto import scheduler_pb2 as pb
from protocol_tpu.proto import wire
from protocol_tpu.services.scheduler_grpc import (
    RemoteBatchMatcher,
    SchedulerBackendClient,
    serve,
)
from protocol_tpu.trace import format as tfmt

from tests.test_scheduler_grpc import _pool_world

NATIVE = native.available()


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------- topology: the endpoint ring ----------------


class TestTopology:
    def test_routing_is_deterministic_and_total(self):
        topo = FleetTopology(["a:1", "b:2", "c:3"], vnodes=32)
        homes = {f"t{i}@s{i}": topo.endpoint_for(f"t{i}@s{i}")
                 for i in range(64)}
        again = FleetTopology(["a:1", "b:2", "c:3"], vnodes=32)
        assert homes == {
            sid: again.endpoint_for(sid) for sid in homes
        }
        # all three endpoints get work at this scale
        assert set(homes.values()) == {"a:1", "b:2", "c:3"}

    def test_failover_order_matches_post_kill_rehoming(self):
        """The client's failover list and the ring's re-homing after a
        kill must agree: the session's new home IS the next entry in
        its failover order — journal re-routes and client failover can
        never disagree about where a session lands."""
        topo = FleetTopology(["a:1", "b:2", "c:3"])
        for i in range(48):
            sid = f"t{i % 3}@sess-{i}"
            order = topo.failover_order(sid)
            assert order[0] == topo.endpoint_for(sid)
            assert sorted(order) == sorted(topo.endpoints)
            survived = topo.without(order[0])
            assert survived.endpoint_for(sid) == order[1]

    def test_without_moves_only_the_dead_endpoints_sessions(self):
        topo = FleetTopology(["a:1", "b:2", "c:3"])
        gone = "b:2"
        survived = topo.without(gone)
        assert survived.generation == topo.generation + 1
        for i in range(64):
            sid = f"x@{i}"
            if topo.endpoint_for(sid) != gone:
                assert survived.endpoint_for(sid) == topo.endpoint_for(
                    sid
                )

    def test_duplicate_and_unknown_refused(self):
        with pytest.raises(ValueError):
            FleetTopology(["a:1", "a:1"])
        with pytest.raises(ValueError):
            FleetTopology([])
        with pytest.raises(ValueError):
            FleetTopology(["a:1"], procs={"b:2": "p0"})

    def test_dict_roundtrip(self):
        topo = FleetTopology(
            ["a:1", "b:2"], procs={"a:1": "p7", "b:2": "p9"},
            vnodes=16, generation=3,
        )
        rt = FleetTopology.from_dict(
            json.loads(json.dumps(topo.to_dict()))
        )
        assert rt.generation == 3 and rt.procs == topo.procs
        for i in range(32):
            assert rt.endpoint_for(f"s{i}") == topo.endpoint_for(f"s{i}")


class TestDiscovery:
    def test_fleet_json_and_route(self):
        topo_box = [FleetTopology(["a:1", "b:2", "c:3"])]
        disco = DiscoveryEndpoint(lambda: topo_box[0])
        try:
            fetched = fetch_topology(disco.url)
            assert fetched.endpoints == topo_box[0].endpoints
            sid = "t0@route-me"
            with urllib.request.urlopen(
                f"{disco.url}/route?session={sid}", timeout=10
            ) as r:
                route = json.loads(r.read().decode())
            assert route["endpoint"] == topo_box[0].endpoint_for(sid)
            assert route["failover"] == topo_box[0].failover_order(sid)
            # membership change is visible through the same endpoint
            topo_box[0] = topo_box[0].without("b:2")
            assert fetch_topology(disco.url).generation == 1
        finally:
            disco.stop()

    def test_bad_requests_are_answered_not_crashed(self):
        disco = DiscoveryEndpoint(lambda: FleetTopology(["a:1"]))
        try:
            for path, code in (("/route", 400), ("/nope", 404)):
                try:
                    urllib.request.urlopen(
                        f"{disco.url}{path}", timeout=10
                    )
                    assert False, "expected HTTPError"
                except urllib.error.HTTPError as e:
                    assert e.code == code
        finally:
            disco.stop()


class TestChaosProcessKnobs:
    def test_process_targets_parse_and_roundtrip(self):
        cfg = ChaosConfig.from_spec(
            "seed=5,drop=0.03,kill_proc_at_tick=3,kill_proc=2,"
            "migrate_at_tick=4,migrate_proc=0"
        )
        assert cfg.kill_proc_at_tick == 3 and cfg.kill_proc == 2
        assert cfg.migrate_at_tick == 4 and cfg.migrate_proc == 0
        assert cfg.active()
        assert ChaosConfig.from_spec(cfg.spec()) == cfg

    def test_proc_id_and_endpoint_ride_the_env(self, monkeypatch):
        monkeypatch.setenv("PROTOCOL_TPU_FLEET_PROC_ID", "p7")
        monkeypatch.setenv(
            "PROTOCOL_TPU_FLEET_ENDPOINT", "10.0.0.7:50061"
        )
        cfg = FleetConfig.from_env()
        assert cfg.proc_id == "p7"
        assert cfg.endpoint == "10.0.0.7:50061"


# ---------------- wire helpers (session driving) ----------------


def _synth(tmp_path, ticks=6, seed=3, n=64):
    from protocol_tpu.trace.synth import synth_trace

    path = str(tmp_path / "dfleet.trace")
    synth_trace(
        path, n_providers=n, n_tasks=n, ticks=ticks, churn=0.05,
        seed=seed, kernel="native-mt:1",
    )
    return path


def _open_session(client, snap, sid, p_cols, r_cols):
    w = tfmt._as_ns(dict(zip(
        ("price", "load", "proximity", "priority"), snap.weights
    )))
    fp = wire.epoch_fingerprint(
        p_cols, r_cols, w, "native-mt:1",
        max(int(snap.top_k) or 64, 1), snap.eps, snap.max_iters,
    )
    req = pb.AssignRequestV2(
        providers=wire.encode_providers_v2(tfmt._as_ns(p_cols)),
        requirements=wire.encode_requirements_v2(tfmt._as_ns(r_cols)),
        weights=pb.CostWeights(
            price=snap.weights[0], load=snap.weights[1],
            proximity=snap.weights[2], priority=snap.weights[3],
        ),
        kernel="native-mt:1", top_k=snap.top_k, eps=snap.eps,
        max_iters=snap.max_iters,
    )
    chunks = list(wire.chunk_snapshot(sid, fp, req))
    return fp, client.open_session(iter(chunks), timeout=120)


def _delta_request(sid, fp, tick, delta):
    req = pb.AssignDeltaRequest(
        session_id=sid, epoch_fingerprint=fp, tick=tick
    )
    if delta.provider_rows.size:
        req.provider_rows.CopyFrom(wire.blob(delta.provider_rows, np.int32))
        req.providers.CopyFrom(
            wire.encode_providers_v2(tfmt._as_ns(delta.p_cols))
        )
    if delta.task_rows.size:
        req.task_rows.CopyFrom(wire.blob(delta.task_rows, np.int32))
        req.requirements.CopyFrom(
            wire.encode_requirements_v2(tfmt._as_ns(delta.r_cols))
        )
    return req


def _serve_pair(root):
    """Two servicers sharing one journal root, distinct namespaces —
    the in-test stand-in for two fleet processes (same wire protocol,
    same checkpointers, one GIL)."""
    addr_a = f"127.0.0.1:{_free_port()}"
    addr_b = f"127.0.0.1:{_free_port()}"
    a = serve(addr_a, fleet=FleetConfig(
        shards=2, ckpt_dir=root, proc_id="p0", endpoint=addr_a))
    b = serve(addr_b, fleet=FleetConfig(
        shards=2, ckpt_dir=root, proc_id="p1", endpoint=addr_b))
    return (addr_a, a), (addr_b, b)


# ---------------- journal namespace + atomic handoff ----------------


@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
class TestJournalNamespace:
    @pytest.fixture()
    def flushed(self, tmp_path):
        """A real flushed journal in p0's namespace (driven over the
        wire so the journal is exactly what production writes)."""
        from protocol_tpu.trace.replay import iter_input_ticks

        root = str(tmp_path / "journals")
        (addr_a, a), (addr_b, b) = _serve_pair(root)
        trace = tfmt.read_trace(_synth(tmp_path, ticks=2))
        sid = "t0@ns-test"
        client = SchedulerBackendClient(addr_a)
        fp = None
        server_tick = 0
        try:
            for tick, p_cols, r_cols, delta in iter_input_ticks(trace):
                if tick == 0:
                    fp, resp = _open_session(
                        client, trace.snapshot, sid, p_cols, r_cols
                    )
                    assert resp.ok, resp.error
                else:
                    resp = client.assign_delta(_delta_request(
                        sid, fp, server_tick + 1, delta
                    ), timeout=120)
                    assert resp.session_ok, resp.error
                    server_tick += 1
            yield root, sid, server_tick
        finally:
            client.close()
            a.stop(grace=None)
            b.stop(grace=None)

    def test_namespace_is_exclusive(self, flushed):
        root, sid, tick = flushed
        p0 = SessionCheckpointer(root, proc_id="p0")
        p1 = SessionCheckpointer(root, proc_id="p1")
        assert journal_session_id(p0.path_for(sid)) == sid
        assert p1.load_one(sid) is None  # not p1's journal
        restored = p0.load_one(sid)
        assert restored is not None and restored.tick == tick

    def test_handoff_moves_ownership_atomically(self, flushed):
        root, sid, tick = flushed
        p0 = SessionCheckpointer(root, proc_id="p0")
        p1 = SessionCheckpointer(root, proc_id="p1")
        assert p0.handoff(sid, "p1") is True
        assert p0.handoff(sid, "p1") is False  # already gone
        assert p0.load_one(sid) is None
        restored = p1.load_one(sid)
        assert restored is not None
        assert restored.tick == tick
        assert restored.last_p4t is not None

    def test_concurrent_loads_never_break_exclusivity(self, flushed):
        """The satellite race test: ownership flips while the OTHER
        side is loading; after every handoff completes the source can
        never load the journal, and the target always can — a journal
        is rehydratable from exactly one namespace."""
        root, sid, _ = flushed
        p0 = SessionCheckpointer(root, proc_id="p0")
        p1 = SessionCheckpointer(root, proc_id="p1")
        for i in range(12):
            owner, other = (p0, p1) if i % 2 == 0 else (p1, p0)
            racer_result = []

            def _racer():
                # races the rename from the TARGET side: legal answers
                # are None (pre-rename) or the session (post-rename)
                racer_result.append(other.load_one(sid))

            th = threading.Thread(target=_racer)
            th.start()
            assert owner.handoff(sid, other.proc_id) is True
            th.join()
            assert owner.load_one(sid) is None
            got = other.load_one(sid)
            assert got is not None and got.session_id == sid
            for r in racer_result:
                assert r is None or r.session_id == sid

    def test_orphan_reroute_by_meta_session_id(self, flushed):
        root, sid, tick = flushed
        moved = handoff_orphans(root, "p0", lambda s: "p2")
        assert moved == [(sid, "p2")]
        p2 = SessionCheckpointer(root, proc_id="p2")
        restored = p2.load_one(sid)
        assert restored is not None and restored.tick == tick
        # route=None leaves journals in place
        assert handoff_orphans(root, "p2", lambda s: None) == []
        assert p2.load_one(sid) is not None


# ---------------- live migration over a real wire ----------------


@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
class TestLiveMigration:
    def test_mid_stream_migration_is_warm_and_bit_identical(
        self, tmp_path
    ):
        """The tentpole drill at unit grain: a session mid-delta-stream
        is checkpointed, migrated, and resumed on a second servicer;
        every plan must be bit-identical to fault-free single-process
        replay, and a retransmit of the last tick must be answered as
        the replayed twin ACROSS the process boundary."""
        from protocol_tpu.trace.replay import iter_input_ticks, replay

        trace_path = _synth(tmp_path, ticks=6)
        trace = tfmt.read_trace(trace_path)
        baseline = replay(
            trace_path, engine="native-mt:1", verify=False,
            keep_p4t=True,
        )["p4ts"]
        root = str(tmp_path / "journals")
        (addr_a, a), (addr_b, b) = _serve_pair(root)
        sid = "t0@mig"
        client = SchedulerBackendClient(addr_a)
        moved_redirects = 0
        server_tick = 0
        last_req = last_p4t = fp = None
        try:
            for tick, p_cols, r_cols, delta in iter_input_ticks(trace):
                if tick == 0:
                    fp, resp = _open_session(
                        client, trace.snapshot, sid, p_cols, r_cols
                    )
                    assert resp.ok, resp.error
                    p4t = wire.unblob(
                        resp.result.provider_for_task, np.int32
                    )
                else:
                    if tick == 3:
                        assert a.servicer.migrate_out(addr_b, "p1") == 1
                    req = _delta_request(
                        sid, fp, server_tick + 1, delta
                    )
                    resp = client.assign_delta(req, timeout=120)
                    if not resp.session_ok and resp.error.startswith(
                        "moved:"
                    ):
                        target = resp.error[len("moved:"):].strip()
                        assert target == addr_b
                        moved_redirects += 1
                        client.close()
                        client = SchedulerBackendClient(target)
                        resp = client.assign_delta(req, timeout=120)
                    assert resp.session_ok, f"tick {tick}: {resp.error}"
                    assert not resp.replayed
                    server_tick += 1
                    p4t = wire.unblob(
                        resp.result.provider_for_task, np.int32
                    )
                    last_req, last_p4t = req, p4t
                assert np.array_equal(p4t, baseline[tick]), (
                    f"tick {tick} diverged from fault-free replay"
                )
            assert moved_redirects == 1

            # retransmit dedup across the boundary: the SAME final tick
            # resent to the NEW home replays the cached twin
            resp = client.assign_delta(last_req, timeout=120)
            assert resp.session_ok and resp.replayed
            assert np.array_equal(
                wire.unblob(resp.result.provider_for_task, np.int32),
                last_p4t,
            )

            seam_a = a.servicer.seam.snapshot()
            seam_b = b.servicer.seam.snapshot()
            assert seam_a.get("session_session_migrated_out") == 1
            assert seam_a.get("session_moved_refused") == 1
            assert seam_b.get("session_session_rehydrated") == 1
            # zero reopens anywhere: exactly one session_open total
            assert seam_a.get("session_session_open") == 1
            assert "session_session_open" not in seam_b

            # the persistent candidate structure rode the journal: the
            # rehydrated session's post-handoff delta ticks REPAIRED the
            # carried structure warm — zero full-matrix candidate
            # passes — instead of regenerating cold on the new process
            session, _ = b.servicer.sessions.get(sid, fp)
            assert session is not None
            assert session.arena.last_stats["cold"] is False
            assert session.arena.last_stats["cand_cold_passes"] == 0
        finally:
            client.close()
            a.stop(grace=None)
            b.stop(grace=None)

    def test_rerouted_journal_clears_stale_redirect(self, tmp_path):
        """Migration target dies and the ring re-routes the journal
        BACK to the original home: the stale moved:<dead endpoint>
        entry must not blackhole the session — the journal's location
        is the authority, and the old home adopts the session back
        and serves it warm."""
        from protocol_tpu.trace.replay import iter_input_ticks

        trace = tfmt.read_trace(_synth(tmp_path, ticks=4))
        root = str(tmp_path / "journals")
        (addr_a, a), (addr_b, b) = _serve_pair(root)
        sid = "t0@boomerang"
        client = SchedulerBackendClient(addr_a)
        try:
            ticks = list(iter_input_ticks(trace))
            _t, p_cols, r_cols, _d = ticks[0]
            fp, resp = _open_session(
                client, trace.snapshot, sid, p_cols, r_cols
            )
            assert resp.ok
            assert a.servicer.migrate_out(addr_b, "p1") == 1
            # tick 1 lands at B (rehydrates there, flushes to p1)
            client_b = SchedulerBackendClient(addr_b)
            resp = client_b.assign_delta(
                _delta_request(sid, fp, 1, ticks[1][3]), timeout=120
            )
            assert resp.session_ok, resp.error
            client_b.close()
            # B dies; the ring re-routes its orphaned journal back to A
            b.stop(grace=None)
            assert handoff_orphans(root, "p1", lambda s: "p0") == [
                (sid, "p0")
            ]
            # the delta at A must ADOPT (journal is here), not bounce
            # at the corpse via the stale moved:addr_b entry
            resp = client.assign_delta(
                _delta_request(sid, fp, 2, ticks[2][3]), timeout=120
            )
            assert resp.session_ok, resp.error
            seam_a = a.servicer.seam.snapshot()
            assert seam_a.get("session_session_rehydrated") == 1
            assert "session_moved_refused" not in seam_a
        finally:
            client.close()
            a.stop(grace=None)
            b.stop(grace=None)

    def test_reopen_at_old_home_is_redirected(self, tmp_path):
        """A client that tries to RE-OPEN at the old home after a
        migration is bounced to the new one — opening there would fork
        ownership of the session's state."""
        from protocol_tpu.trace.replay import iter_input_ticks

        trace = tfmt.read_trace(_synth(tmp_path, ticks=1))
        root = str(tmp_path / "journals")
        (addr_a, a), (addr_b, b) = _serve_pair(root)
        sid = "t0@reopen"
        client = SchedulerBackendClient(addr_a)
        try:
            ticks = list(iter_input_ticks(trace))
            _tick, p_cols, r_cols, _d = ticks[0]
            fp, resp = _open_session(
                client, trace.snapshot, sid, p_cols, r_cols
            )
            assert resp.ok
            assert a.servicer.migrate_out(addr_b, "p1") == 1
            _fp, resp = _open_session(
                client, trace.snapshot, sid, p_cols, r_cols
            )
            assert not resp.ok
            assert resp.error == f"moved:{addr_b}"
        finally:
            client.close()
            a.stop(grace=None)
            b.stop(grace=None)

    def test_eviction_tombstone_preserves_reopen_contract(
        self, tmp_path
    ):
        """Lazy rehydration must NOT resurrect a session this process
        itself evicted for capacity — eviction releases memory, and the
        PR 9 contract (forced eviction = the ladder's counted reopen)
        still holds with journals on disk."""
        from protocol_tpu.trace.replay import iter_input_ticks

        trace = tfmt.read_trace(_synth(tmp_path, ticks=2))
        root = str(tmp_path / "journals")
        (addr_a, a), (_addr_b, b) = _serve_pair(root)
        sid = "t0@evict"
        client = SchedulerBackendClient(addr_a)
        try:
            ticks = list(iter_input_ticks(trace))
            _t, p_cols, r_cols, _d = ticks[0]
            fp, resp = _open_session(
                client, trace.snapshot, sid, p_cols, r_cols
            )
            assert resp.ok
            # forced eviction (chaos/pressure shape) — journal remains
            # on disk, but the tombstone forbids lazy resurrection
            assert a.servicer.sessions.shard_of(sid).evict(
                sid, "chaos"
            )
            _t1, _p, _r, delta = ticks[1]
            resp = client.assign_delta(
                _delta_request(sid, fp, 1, delta), timeout=120
            )
            assert not resp.session_ok
            assert "unknown session" in resp.error
            assert "session_session_rehydrated" not in (
                a.servicer.seam.snapshot()
            )
            # a fresh OPEN clears the tombstone (new incarnation)
            fp, resp = _open_session(
                client, trace.snapshot, sid, p_cols, r_cols
            )
            assert resp.ok
        finally:
            client.close()
            a.stop(grace=None)
            b.stop(grace=None)


# ---------------- the production client's dfleet rungs ----------------


@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
class TestRemoteMatcherFailover:
    def test_moved_redirect_resumes_warm_without_reopen(self, tmp_path):
        root = str(tmp_path / "journals")
        (addr_a, a), (addr_b, b) = _serve_pair(root)
        store = _pool_world()
        m = RemoteBatchMatcher(
            store, [addr_a, addr_b], min_solve_interval=0.0, wire="v2",
            native_fallback=True, native_engine="native-mt",
            native_threads=2, retry_base_s=0.01,
        )
        try:
            m.refresh()
            m.refresh()
            assert m._session["tick"] == 1
            moved = a.servicer.migrate_out(addr_b, "p1")
            assert moved == 1
            m.refresh()  # delta -> moved: -> rebind -> SAME delta warm
            snap = m.seam.snapshot()
            assert snap.get("session_moved_redirect") == 1
            assert "session_session_reopen" not in snap
            assert m._session["tick"] == 2
            assert m._assignment
            seam_b = b.servicer.seam.snapshot()
            assert seam_b.get("session_session_rehydrated") == 1
        finally:
            m.client.close()
            a.stop(grace=None)
            b.stop(grace=None)

    def test_kill_plus_handoff_fails_over_warm(self, tmp_path):
        """The crash drill at matcher grain: the session's home dies
        (hard stop), its orphaned journal is re-routed, and the next
        refresh fails over down the endpoint list and resumes WARM —
        zero reopens, the delta stream uninterrupted."""
        root = str(tmp_path / "journals")
        (addr_a, a), (addr_b, b) = _serve_pair(root)
        store = _pool_world()
        m = RemoteBatchMatcher(
            store, [addr_a, addr_b], min_solve_interval=0.0, wire="v2",
            native_fallback=True, native_engine="native-mt",
            native_threads=2, retry_base_s=0.01, retries=4,
        )
        try:
            m.refresh()
            m.refresh()
            assert m._session["tick"] == 1
            a.stop(grace=None)  # kill -9 stand-in
            moved = handoff_orphans(root, "p0", lambda s: "p1")
            assert [s for s, _ in moved] == [m._session["id"]]
            m.refresh()
            snap = m.seam.snapshot()
            assert snap.get("session_endpoint_failover", 0) >= 1
            assert "session_session_reopen" not in snap
            assert m._session["tick"] == 2
            assert m._assignment
            seam_b = b.servicer.seam.snapshot()
            assert seam_b.get("session_session_rehydrated") == 1
        finally:
            m.client.close()
            a.stop(grace=None)
            b.stop(grace=None)


# ---------------- real subprocesses (slow: spawn cost) ----------------


@pytest.mark.slow
@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
class TestProcessFleetSubprocess:
    def test_kill_one_of_two_processes_resumes_warm(self, tmp_path):
        from protocol_tpu.dfleet.manager import ProcessFleet
        from protocol_tpu.fleet.loadgen import run_load

        rep = run_load(
            sessions=2, tenants=2, providers=64, tasks=64, ticks=6,
            churn=0.05, kernel="native-mt:1", shards=2,
            seed=1, processes=2, restart_at_tick=2,
            restart_mode="crash",
            ckpt_dir=str(tmp_path / "journals"),
        )
        assert rep["errors"] == []
        assert rep["drill"].get("killed")
        mig = rep["migration"]
        assert mig["reopens_total"] == 0
        for t, agg in rep["tenants"].items():
            assert agg["min_assigned_frac"] >= 0.9
        # ProcessFleet API surface smoke (scrape/witness join shapes)
        assert set(rep["processes"].keys()) == {"p0", "p1"}
        del ProcessFleet  # imported to assert availability


# ---------------- autonomous failure detection (ISSUE 14) ----------------


class TestFailureDetector:
    """Pure state-machine tests on a VIRTUAL clock — the detector never
    reads time itself (the determinism lint enforces it), so these
    drive the exact transition sequence the module promises."""

    CFG = None  # built per test; class attr keeps flake8 quiet

    @staticmethod
    def _cfg(**kw):
        from protocol_tpu.dfleet.detector import DetectorConfig

        base = dict(
            alpha=0.5, suspect_factor=3.0, dead_factor=6.0,
            min_interval_s=1.0, dead_misses=3, flap_penalty=1.0,
            flap_memory=4, flap_decay_beats=8, max_penalty=4.0,
        )
        base.update(kw)
        return DetectorConfig(**base)

    def test_alive_suspect_dead_progression(self):
        from protocol_tpu.dfleet.detector import (
            ALIVE, DEAD, SUSPECT, FailureDetector,
        )

        det = FailureDetector(["p0", "p1"], self._cfg())
        t = 0.0
        for _ in range(5):
            t += 1.0
            det.heartbeat("p0", t)
            det.heartbeat("p1", t)
        assert det.state_of("p1") == ALIVE
        # p1 goes dark; p0 keeps beating
        dark_from = t
        for _ in range(3):
            t += 1.0
            det.heartbeat("p0", t)
            det.probe_failed("p1", t)
        # elapsed == 3.0 is not > 3 x ewma(1.0): still alive
        assert det.evaluate(dark_from + 3.0) == []
        det.heartbeat("p0", dark_from + 3.5)
        assert det.evaluate(dark_from + 3.5) == []
        assert det.state_of("p1") == SUSPECT  # suspect != ejected
        # past the dead factor AND >= dead_misses consecutive misses
        det.heartbeat("p0", dark_from + 6.5)
        assert det.evaluate(dark_from + 6.5) == ["p1"]
        assert det.state_of("p1") == DEAD
        assert det.state_of("p0") == ALIVE
        # dead is terminal and reported exactly once
        assert det.evaluate(dark_from + 100.0) == []
        det.heartbeat("p1", dark_from + 7.0)  # the zombie's late beat
        assert det.state_of("p1") == DEAD
        assert det.snapshot()["procs"]["p1"]["zombie_beats"] == 1

    def test_dead_requires_sustained_misses_not_just_elapsed(self):
        from protocol_tpu.dfleet.detector import SUSPECT, FailureDetector

        det = FailureDetector(["p0"], self._cfg())
        det.heartbeat("p0", 1.0)
        det.heartbeat("p0", 2.0)
        # long silence but ZERO failed probes (e.g. the sampler itself
        # stalled): suspect, never dead — ejection needs evidence of
        # refusal, not just a gap
        assert det.evaluate(30.0) == []
        assert det.state_of("p0") == SUSPECT

    def test_flap_suppression_inflates_thresholds(self):
        from protocol_tpu.dfleet.detector import (
            ALIVE, SUSPECT, FailureDetector,
        )

        det = FailureDetector(["p0"], self._cfg())
        t = 0.0
        for _ in range(4):
            t += 1.0
            det.heartbeat("p0", t)
        # one flap: silence past the suspect threshold, then recover
        t += 3.5
        assert det.evaluate(t) == []
        assert det.state_of("p0") == SUSPECT
        det.heartbeat("p0", t)
        assert det.state_of("p0") == ALIVE
        snap = det.snapshot()
        assert snap["totals"]["flaps"] == 1
        assert snap["procs"]["p0"]["recent_flaps"] == 1
        # suppression: the SAME silence that suspected a clean process
        # no longer suspects the flapper — the flap penalty (1 +
        # flap_penalty * recent_flaps) AND the gap-adapted EWMA both
        # inflated its threshold, which is exactly how a slow-but-alive
        # node stays in the fleet instead of flap-cycling to ejection
        det.evaluate(t + 3.5)
        assert det.state_of("p0") == ALIVE  # the flapper does NOT
        # ...while the clean twin at the same cadence DOES suspect
        det2 = FailureDetector(["fresh"], self._cfg())
        u = 0.0
        for _ in range(4):
            u += 1.0
            det2.heartbeat("fresh", u)
        det2.evaluate(u + 3.5)
        assert det2.state_of("fresh") == SUSPECT

    def test_same_samples_replay_identical_transitions(self):
        from protocol_tpu.dfleet.detector import FailureDetector

        def run():
            det = FailureDetector(["a", "b"], self._cfg())
            t = 0.0
            for i in range(20):
                t += 1.0
                det.heartbeat("a", t)
                if i < 10:
                    det.heartbeat("b", t)
                else:
                    det.probe_failed("b", t)
                det.evaluate(t)
            return det.snapshot()

        one, two = run(), run()
        assert one["transitions"] == two["transitions"]
        assert one["procs"] == two["procs"]

    def test_driver_kill_is_removed_never_ejected(self):
        from protocol_tpu.dfleet.detector import FailureDetector

        det = FailureDetector(["p0", "p1"], self._cfg())
        det.heartbeat("p0", 1.0)
        det.heartbeat("p1", 1.0)
        det.remove("p1")  # the driver SIGKILLed it itself
        for t in (5.0, 9.0, 14.0):
            det.probe_failed("p1", t)
        assert det.evaluate(20.0) == []
        assert det.snapshot()["totals"]["ejections"] == 0


# ---------------- fenced journal ownership (ISSUE 14) ----------------


@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
class TestFencing:
    def test_fence_stamp_is_monotonic_and_adopted(self, tmp_path):
        from protocol_tpu.faults.checkpoint import (
            read_fence,
            stamp_fence,
        )

        root = str(tmp_path)
        assert read_fence(root, "p0")["epoch"] == 0  # unstamped: inert
        assert stamp_fence(root, "p0") == 1
        assert stamp_fence(root, "p0", topology={"g": 1}) == 2
        ck = SessionCheckpointer(root, proc_id="p0")
        assert ck.fence_epoch == 2
        assert not ck.fence_superseded()
        assert stamp_fence(root, "p0") == 3
        assert ck.fence_superseded()
        assert ck.fence_state()["epoch"] == 3

    def test_superseded_fence_refuses_flush(self, tmp_path):
        """flush_locked must refuse (counted) once the namespace fence
        moved past the adopted epoch — an ejected process can never
        resurrect a journal a survivor now owns."""
        from protocol_tpu.faults.checkpoint import stamp_fence

        root = str(tmp_path / "journals")
        (addr_a, a), (_addr_b, b) = _serve_pair(root)
        trace = tfmt.read_trace(_synth(tmp_path, ticks=1))
        sid = "t0@flushfence"
        client = SchedulerBackendClient(addr_a)
        try:
            from protocol_tpu.trace.replay import iter_input_ticks

            _t, p_cols, r_cols, _d = next(iter(iter_input_ticks(trace)))
            fp, resp = _open_session(
                client, trace.snapshot, sid, p_cols, r_cols
            )
            assert resp.ok, resp.error
            stamp_fence(root, "p0")
            assert a.servicer.finish_drain() == 0  # refused, not flushed
            assert a.servicer.ckpt.fence_refusals >= 1
        finally:
            client.close()
            a.stop(grace=None)
            b.stop(grace=None)

    def test_zombie_is_fence_refused_and_survivor_serves_warm(
        self, tmp_path
    ):
        """The zombie-resume contract at unit grain, over a real wire:
        A's namespace is superseded + its journal re-routed (what the
        detector's ejection does while a SIGSTOPped A is frozen); A —
        which never observed any of it, exactly like a resumed zombie —
        must answer ``moved:`` on delta AND re-open, ack nothing, and B
        must serve the SAME tick warm from the re-routed journal."""
        from protocol_tpu.trace.replay import iter_input_ticks

        trace = tfmt.read_trace(_synth(tmp_path, ticks=4))
        root = str(tmp_path / "journals")
        (addr_a, a), (addr_b, b) = _serve_pair(root)
        sid = "t0@zombie"
        client = SchedulerBackendClient(addr_a)
        try:
            ticks = list(iter_input_ticks(trace))
            _t, p_cols, r_cols, _d = ticks[0]
            fp, resp = _open_session(
                client, trace.snapshot, sid, p_cols, r_cols
            )
            assert resp.ok, resp.error
            resp = client.assign_delta(
                _delta_request(sid, fp, 1, ticks[1][3]), timeout=120
            )
            assert resp.session_ok, resp.error

            # the ejection, as the manager runs it against a frozen A:
            # fence superseded + journal re-routed in one call
            topo = FleetTopology(
                [addr_b], procs={addr_b: "p1"}, generation=1
            )
            stats: dict = {}
            moved = handoff_orphans(
                root, "p0", lambda s: "p1",
                topology=topo.to_dict(), stats=stats,
            )
            assert moved == [(sid, "p1")]
            assert stats["fence_epoch"] == 1

            # the zombie: delta moved:-refused, re-open moved:-refused
            resp = client.assign_delta(
                _delta_request(sid, fp, 2, ticks[2][3]), timeout=120
            )
            assert not resp.session_ok
            assert resp.error == f"moved:{addr_b}"
            _fp2, resp2 = _open_session(
                client, trace.snapshot, sid, p_cols, r_cols
            )
            assert not resp2.ok and resp2.error == f"moved:{addr_b}"
            assert a.servicer.seam.snapshot().get(
                "session_fence_refused"
            ) == 2
            # and it can never flush into the superseded namespace
            assert a.servicer.finish_drain() == 0

            # the survivor serves the SAME tick warm — zero reopens
            cb = SchedulerBackendClient(addr_b)
            try:
                resp = cb.assign_delta(
                    _delta_request(sid, fp, 2, ticks[2][3]), timeout=120
                )
                assert resp.session_ok, resp.error
                seam_b = b.servicer.seam.snapshot()
                assert seam_b.get("session_session_rehydrated") == 1
                assert "session_session_open" not in seam_b
            finally:
                cb.close()
        finally:
            client.close()
            a.stop(grace=None)
            b.stop(grace=None)


# ---------------- torn-journal hardening (ISSUE 14 satellite) ----------


@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
class TestTornJournalHardening:
    def test_torn_journal_skipped_counted_rest_rerouted(self, tmp_path):
        """A journal whose META frame is truncated (process killed
        mid-flush) must be SKIPPED with a counted warning — never raise
        out of the re-route loop — and the remaining journals must
        still move. load_all applies the same contract on restore."""
        import os
        import shutil

        from protocol_tpu.trace.replay import iter_input_ticks

        root = str(tmp_path / "journals")
        (addr_a, a), (_addr_b, b) = _serve_pair(root)
        trace = tfmt.read_trace(_synth(tmp_path, ticks=2))
        sids = ["t0@torn-x", "t0@torn-y"]
        clients = []
        try:
            for sid in sids:
                client = SchedulerBackendClient(addr_a)
                clients.append(client)
                server_tick = 0
                for tick, p_cols, r_cols, delta in iter_input_ticks(
                    trace
                ):
                    if tick == 0:
                        fp, resp = _open_session(
                            client, trace.snapshot, sid, p_cols, r_cols
                        )
                        assert resp.ok, resp.error
                    else:
                        resp = client.assign_delta(_delta_request(
                            sid, fp, server_tick + 1, delta
                        ), timeout=120)
                        assert resp.session_ok, resp.error
                        server_tick += 1
        finally:
            for c in clients:
                c.close()
            a.stop(grace=None)
            b.stop(grace=None)

        p0 = SessionCheckpointer(root, proc_id="p0")
        good = p0.path_for(sids[0])
        torn = os.path.join(p0.directory, "torn0000deadbeef.ckpt")
        with open(good, "rb") as fh:
            blob = fh.read()
        with open(torn, "wb") as fh:
            fh.write(blob[:16])  # magic + sheared META header

        stats: dict = {}
        moved = handoff_orphans(
            root, "p0", lambda s: "p9", stats=stats
        )
        assert sorted(s for s, _ in moved) == sorted(sids)
        assert stats["journals_moved"] == 2
        assert stats["journals_skipped"] == 1
        # the torn file stays behind; the good ones landed in p9
        assert os.path.exists(torn)

        # restore-side twin: load_all skips the torn file, counted
        p9 = SessionCheckpointer(root, proc_id="p9")
        shutil.copyfile(
            torn, os.path.join(p9.directory, "torn0000deadbeef.ckpt")
        )
        restored = p9.load_all()
        assert sorted(s.session_id for s in restored) == sorted(sids)
        assert p9.journals_skipped == 1


# ---------------- generation-monotonic adoption (ISSUE 14 satellite) ---


class TestGenerationMonotonicAdoption:
    def test_fetch_topology_refuses_stale_generation(self):
        """A stale /fleet.json poll racing a detector ejection must
        LOSE: fetch_topology keeps the newer held topology when the
        served one is not strictly newer."""
        served = [FleetTopology(["a:1", "b:2", "c:3"])]  # generation 0
        disco = DiscoveryEndpoint(lambda: served[0])
        try:
            held = FleetTopology(
                ["a:1", "c:3"], procs={"a:1": "p0", "c:3": "p2"},
                generation=1,
            )  # what the ejection already produced
            got = fetch_topology(disco.url, current=held)
            assert got is held  # the stale poll lost
            served[0] = served[0].without("b:2").without("a:1")  # gen 2
            got = fetch_topology(disco.url, current=held)
            assert got.generation == 2
            assert got is not held
        finally:
            disco.stop()

    def test_manager_adopt_guard_is_generation_monotonic(self):
        from protocol_tpu.dfleet.manager import ProcessFleet

        fleet = ProcessFleet(processes=2)  # built, never started
        try:
            current = fleet.topology
            stale = FleetTopology(
                current.endpoints, procs=current.procs,
                generation=current.generation,
            )
            assert fleet.adopt_topology(stale) is False
            newer = current.without(current.endpoints[0])
            assert fleet.adopt_topology(newer) is True
            assert fleet.topology.generation == newer.generation
            assert fleet.adopt_topology(current) is False  # now stale
        finally:
            fleet.stop()

    def test_matcher_adopt_guard_and_reladdering(self):
        store = _pool_world()
        m = RemoteBatchMatcher(
            store, ["a:1", "b:2"], min_solve_interval=0.0
        )
        try:
            topo1 = FleetTopology(
                ["a:1", "b:2", "c:3"],
                procs={"a:1": "p0", "b:2": "p1", "c:3": "p2"},
                generation=1,
            )
            assert m.adopt_topology(topo1, session_id="t0@adopt")
            assert sorted(m.endpoints) == ["a:1", "b:2", "c:3"]
            assert m.endpoints == topo1.failover_order("t0@adopt")
            # stale (same and lower generation) must be refused even
            # if it carries a different membership
            stale = FleetTopology(["z:9"], generation=1)
            assert m.adopt_topology(stale) is False
            assert "z:9" not in m.endpoints
            assert m.seam.snapshot().get(
                "session_stale_topology_refused"
            ) == 1
            # newer generation that ejected our bound endpoint: adopt
            # AND fail over off the corpse
            bound = m.endpoints[m._endpoint_i]
            topo2 = topo1.without(bound)
            assert m.adopt_topology(topo2, session_id="t0@adopt")
            assert bound not in m.endpoints
            assert m.client.address == m.endpoints[0]
        finally:
            m.client.close()


@pytest.mark.slow
@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
class TestZombieResumeSubprocess:
    def test_pause_zombie_is_ejected_fenced_and_warm(self, tmp_path):
        """The zombie-resume drill end to end on real subprocesses:
        SIGSTOP one of two processes mid-run — the detector must eject
        it with zero driver-owned kills, journals re-route, the resumed
        zombie is fence-refused, and every session resumes warm with
        plans bit-identical to the fault-free replay."""
        from protocol_tpu.fleet.loadgen import run_load

        rep = run_load(
            sessions=2, tenants=2, providers=64, tasks=64, ticks=8,
            churn=0.05, kernel="native-mt:1", shards=2, seed=1,
            processes=2,
            chaos="seed=7,pause_proc_at_tick=2,pause_proc=1",
            rpc_timeout_s=10.0, max_retries=60, verify_plans=True,
            ckpt_dir=str(tmp_path / "journals"),
        )
        assert rep["errors"] == []
        drill = rep["drill"]
        assert drill.get("paused") and drill.get("resumed")
        assert drill.get("ejected_by_detector")
        assert drill.get("zombie_fence_refused"), drill
        det = rep["detector"]
        assert det["time_to_detect_s"] is not None
        assert det["false_positive_ejections"] == []
        mig = rep["migration"]
        assert mig["reopens_total"] == 0
        assert mig["plan_mismatches_total"] == 0
