"""Signed-URL upload loop with the LocalDir provider: request-upload ->
HTTP PUT with the HMAC token -> artifact on disk -> validator mapping."""

import pytest

# Environment guard: this module's import chain reaches
# protocol_tpu.security / protocol_tpu.utils.tls, which need the
# third-party `cryptography` package (wallet signing + TLS material).
# On hosts without it, report the whole module as SKIPPED instead of a
# collection error (tier-1 keeps an honest skip count; CI installs
# cryptography and runs everything).
pytest.importorskip(
    "cryptography", reason="cryptography not installed (signing/TLS dependency)"
)

import asyncio
import tempfile
from urllib.parse import urlparse

from aiohttp.test_utils import TestClient, TestServer

from protocol_tpu.security import sign_request
from protocol_tpu.services.orchestrator import OrchestratorService
from protocol_tpu.store import NodeStatus, OrchestratorNode
from protocol_tpu.utils.storage import LocalDirStorageProvider

from tests.test_services import make_world


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def test_full_signed_upload_loop():
    ledger, creator, manager, provider, node, pid = make_world()

    async def flow():
        with tempfile.TemporaryDirectory() as root:
            storage = LocalDirStorageProvider(root, public_base_url="http://x")
            clock = [1000.0]
            svc = OrchestratorService(
                ledger, pid, manager, storage=storage, uploads_per_hour=100,
                time_fn=lambda: clock[0],
            )
            svc.store.node_store.add_node(
                OrchestratorNode(address=node.address, status=NodeStatus.HEALTHY)
            )
            async with TestClient(TestServer(svc.make_app())) as client:
                payload = {
                    "file_name": "artifact.bin",
                    "file_size": 11,
                    "file_type": "application/octet-stream",
                    "sha256": "de"*32,
                }
                headers, body = sign_request("/storage/request-upload", node, payload)
                r = await client.post(
                    "/storage/request-upload", json=body, headers=headers
                )
                assert r.status == 200, await r.text()
                url = (await r.json())["data"]["signed_url"]
                # PUT through the signed URL (token auth, no wallet signature)
                path_q = url.split("http://x", 1)[1]
                r2 = await client.put(path_q, data=b"hello world")
                assert r2.status == 200, await r2.text()

                # artifact landed; the validator can resolve the mapping
                assert await storage.file_exists("artifact.bin")
                assert await storage.resolve_mapping_for_sha("de"*32) == "artifact.bin"

                # tampered token rejected
                r3 = await client.put(path_q[:-4] + "beef", data=b"x")
                assert r3.status == 403

                # path traversal rejected
                parsed = urlparse(path_q)
                r4 = await client.put(
                    "/storage/upload/..%2Fescape?" + parsed.query, data=b"x"
                )
                assert r4.status in (400, 403)

                # uploads above aiohttp's 1 MiB default must pass (the
                # advertised cap is 100 MB; regression for client_max_size)
                big_payload = {
                    "file_name": "big.bin",
                    "file_size": 5 * 1024 * 1024,
                    "file_type": "bin",
                    "sha256": "b1"*32,
                }
                h2, b2 = sign_request("/storage/request-upload", node, big_payload)
                r5 = await client.post(
                    "/storage/request-upload", json=b2, headers=h2
                )
                url5 = (await r5.json())["data"]["signed_url"]
                r6 = await client.put(
                    url5.split("http://x", 1)[1], data=b"z" * (5 * 1024 * 1024)
                )
                assert r6.status == 200, await r6.text()

                # the token binds the APPROVED size: a PUT larger than the
                # requested file_size is rejected even with a valid token
                r8 = await client.put(
                    url5.split("http://x", 1)[1],
                    data=b"z" * (6 * 1024 * 1024),  # approved 5 MiB
                )
                assert r8.status == 413, await r8.text()
                # overflow must not leave a partial artifact behind
                assert not await storage.file_exists("big.bin.part")

                # escaping file_name rejected at ISSUE time
                bad = {
                    "file_name": "../../etc/passwd",
                    "file_size": 1,
                    "file_type": "bin",
                    "sha256": "ee"*32,
                }
                h3, b3 = sign_request("/storage/request-upload", node, bad)
                r7 = await client.post(
                    "/storage/request-upload", json=b3, headers=h3
                )
                assert r7.status == 400

                # a non-hex sha (e.g. path traversal aimed at the mapping
                # namespace) is rejected before any state is written
                for sha in ("x/../" + "de" * 32, "de" * 8, "zz" * 32):
                    h_s, b_s = sign_request(
                        "/storage/request-upload", node,
                        {"file_name": "n.bin", "file_size": 1,
                         "file_type": "bin", "sha256": sha},
                    )
                    r_s = await client.post(
                        "/storage/request-upload", json=b_s, headers=h_s
                    )
                    assert r_s.status == 400, sha

                # the validator's mapping/ namespace is write-protected:
                # a node must not mint signed URLs for resolution objects
                for name in ("mapping/deadbeef", "x/../mapping/deadbeef"):
                    h4, b4 = sign_request(
                        "/storage/request-upload", node,
                        {"file_name": name, "file_size": 1,
                         "file_type": "bin", "sha256": "aa"*32},
                    )
                    r9 = await client.post(
                        "/storage/request-upload", json=b4, headers=h4
                    )
                    assert r9.status == 400, name

                # one sha, one owner: a second node cannot re-map a sha
                # another node already claimed (would misdirect validation)
                from protocol_tpu.security import Wallet

                node2 = Wallet.from_seed(b"upload-node-2")
                svc.store.node_store.add_node(
                    OrchestratorNode(address=node2.address,
                                     status=NodeStatus.HEALTHY)
                )
                steal = {
                    "file_name": "steal.bin",
                    "file_size": 1,
                    "file_type": "bin",
                    "sha256": "de"*32,  # node-1's pending work sha
                }
                h5, b5 = sign_request("/storage/request-upload", node2, steal)
                r10 = await client.post(
                    "/storage/request-upload", json=b5, headers=h5
                )
                assert r10.status == 409
                # unchanged mapping
                assert await storage.resolve_mapping_for_sha("de"*32) == "artifact.bin"
                # ...but the owner may re-request its own sha
                h6, b6 = sign_request(
                    "/storage/request-upload", node,
                    {"file_name": "artifact-v2.bin", "file_size": 1,
                     "file_type": "bin", "sha256": "de"*32},
                )
                r11 = await client.post(
                    "/storage/request-upload", json=b6, headers=h6
                )
                assert r11.status == 200

                # a STALE claim (mapped object never uploaded — claimant
                # crashed before its PUT) may be taken over by another node,
                # but only once the claim has outlived the signed-URL window:
                # an in-flight first upload (claimed, object not yet PUT)
                # must not be seizable mid-PUT
                h7, b7 = sign_request(
                    "/storage/request-upload", node,
                    {"file_name": "ghost.bin", "file_size": 1,
                     "file_type": "bin", "sha256": "09" * 32},
                )
                assert (await client.post(
                    "/storage/request-upload", json=b7, headers=h7
                )).status == 200
                # node never PUTs ghost.bin; node2 tries immediately — the
                # claim is still inside the signed-URL window, so refused
                h8, b8 = sign_request(
                    "/storage/request-upload", node2,
                    {"file_name": "revived.bin", "file_size": 1,
                     "file_type": "bin", "sha256": "09" * 32},
                )
                assert (await client.post(
                    "/storage/request-upload", json=b8, headers=h8
                )).status == 409
                assert await storage.resolve_mapping_for_sha("09" * 32) == "ghost.bin"
                # ...after the grace window the claim is stale: takeover OK
                clock[0] += svc.upload_claim_grace + 1
                h8b, b8b = sign_request(
                    "/storage/request-upload", node2,
                    {"file_name": "revived.bin", "file_size": 1,
                     "file_type": "bin", "sha256": "09" * 32},
                )
                assert (await client.post(
                    "/storage/request-upload", json=b8b, headers=h8b
                )).status == 200
                assert await storage.resolve_mapping_for_sha("09" * 32) == "revived.bin"

                # refresh-squatting is bounded: a node re-requesting its own
                # never-uploaded sha keeps restarting the grace window, but
                # past 4x grace TOTAL age the claim falls anyway
                sha_sq = "0a" * 32
                async def rereq(w, name):
                    h, b = sign_request(
                        "/storage/request-upload", w,
                        {"file_name": name, "file_size": 1,
                         "file_type": "bin", "sha256": sha_sq},
                    )
                    return await client.post(
                        "/storage/request-upload", json=b, headers=h
                    )
                assert (await rereq(node, "squat.bin")).status == 200
                for _ in range(4):  # refresh just inside each window
                    clock[0] += svc.upload_claim_grace - 1
                    assert (await rereq(node, "squat.bin")).status == 200
                    # within the (refreshed) grace + total-age cap: refused
                    assert (await rereq(node2, "take.bin")).status == 409
                # total age now > 4x grace: the claim falls despite the
                # squatter's latest refresh still being inside its grace
                clock[0] += 5
                assert (await rereq(node2, "take.bin")).status == 200
                assert await storage.resolve_mapping_for_sha(sha_sq) == "take.bin"

    run(flow())
