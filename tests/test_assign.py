"""Assignment-kernel tests: feasibility invariants, greedy parity vs a numpy
oracle, and solution quality vs scipy's optimal linear_sum_assignment."""

import numpy as np
import pytest
from scipy.optimize import linear_sum_assignment

import jax.numpy as jnp

from protocol_tpu.ops.assign import (
    AssignResult,
    assign_auction,
    assign_auction_scaled,
    assign_greedy,
    assign_sinkhorn,
    ffd_order,
)
from protocol_tpu.ops.cost import INFEASIBLE


def random_cost(rng, P, T, p_infeasible=0.2):
    cost = rng.uniform(0.0, 10.0, size=(P, T)).astype(np.float32)
    infeas = rng.random(size=(P, T)) < p_infeasible
    cost[infeas] = float(INFEASIBLE)
    return cost


def check_feasible(res: AssignResult, cost: np.ndarray):
    p4t = np.asarray(res.provider_for_task)
    t4p = np.asarray(res.task_for_provider)
    P, T = cost.shape
    used = set()
    for t, p in enumerate(p4t):
        if p >= 0:
            assert cost[p, t] < INFEASIBLE * 0.5, f"infeasible pair t={t} p={p}"
            assert p not in used, f"provider {p} double-booked"
            used.add(p)
            assert t4p[p] == t
    for p, t in enumerate(t4p):
        if t >= 0:
            assert p4t[t] == p
    return p4t


def greedy_oracle(cost: np.ndarray, order=None):
    """Host-side reference: each task (in order) takes the cheapest free
    compatible provider, ties to lowest provider index."""
    P, T = cost.shape
    avail = np.ones(P, bool)
    out = np.full(T, -1, np.int64)
    order = range(T) if order is None else order
    for t in order:
        col = np.where(avail, cost[:, t], INFEASIBLE)
        p = int(np.argmin(col))
        if col[p] < INFEASIBLE * 0.5:
            out[t] = p
            avail[p] = False
    return out


def matching_cost(cost, p4t):
    return sum(cost[p, t] for t, p in enumerate(p4t) if p >= 0)


class TestGreedy:
    @pytest.mark.parametrize("seed,P,T", [(0, 16, 16), (1, 64, 256), (2, 256, 64)])
    def test_parity_with_oracle(self, seed, P, T):
        rng = np.random.default_rng(seed)
        cost = random_cost(rng, P, T)
        res = assign_greedy(jnp.asarray(cost))
        p4t = check_feasible(res, cost)
        np.testing.assert_array_equal(p4t, greedy_oracle(cost))

    def test_custom_order_parity(self):
        rng = np.random.default_rng(3)
        cost = random_cost(rng, 32, 48)
        order = rng.permutation(48).astype(np.int32)
        res = assign_greedy(jnp.asarray(cost), task_order=jnp.asarray(order))
        p4t = check_feasible(res, cost)
        np.testing.assert_array_equal(p4t, greedy_oracle(cost, order=list(order)))

    def test_ffd_order(self):
        demand = jnp.asarray([1.0, 5.0, 3.0, 5.0])
        order = np.asarray(ffd_order(demand))
        np.testing.assert_array_equal(order, [1, 3, 2, 0])

    def test_all_infeasible(self):
        cost = np.full((4, 4), float(INFEASIBLE), np.float32)
        res = assign_greedy(jnp.asarray(cost))
        assert (np.asarray(res.provider_for_task) == -1).all()


class TestAuction:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_near_optimal_square(self, seed):
        rng = np.random.default_rng(seed)
        n = 48
        cost = rng.uniform(0.0, 10.0, size=(n, n)).astype(np.float32)
        res = assign_auction(jnp.asarray(cost), eps=0.01, max_iters=5000)
        p4t = check_feasible(res, cost)
        assert (p4t >= 0).all(), "feasible square problem must fully match"
        ri, ci = linear_sum_assignment(cost)
        opt = cost[ri, ci].sum()
        got = matching_cost(cost, p4t)
        assert got <= opt + n * 0.011, f"auction {got} vs optimal {opt}"

    def test_with_infeasibility(self):
        rng = np.random.default_rng(7)
        cost = random_cost(rng, 40, 30, p_infeasible=0.3)
        res = assign_auction(jnp.asarray(cost), eps=0.05, max_iters=5000)
        check_feasible(res, cost)
        # every task with at least one feasible provider should be assigned
        # (more providers than tasks, so no contention shortage)
        p4t = np.asarray(res.provider_for_task)
        feasible_tasks = (cost < INFEASIBLE * 0.5).any(axis=0)
        assert (p4t[feasible_tasks] >= 0).all()

    def test_more_tasks_than_providers(self):
        rng = np.random.default_rng(11)
        cost = random_cost(rng, 8, 32, p_infeasible=0.0)
        res = assign_auction(jnp.asarray(cost), eps=0.05, max_iters=200)
        p4t = check_feasible(res, cost)
        assert (p4t >= 0).sum() == 8  # all providers consumed

    def test_eps_scaled(self):
        rng = np.random.default_rng(5)
        n = 32
        cost = rng.uniform(0.0, 10.0, size=(n, n)).astype(np.float32)
        res = assign_auction_scaled(jnp.asarray(cost), eps_start=1.0, eps_end=0.01)
        p4t = check_feasible(res, cost)
        ri, ci = linear_sum_assignment(cost)
        opt = cost[ri, ci].sum()
        assert matching_cost(cost, p4t) <= opt + n * 0.011


class TestSinkhorn:
    def test_identity_structure(self):
        # strongly diagonal cost => sinkhorn must recover the diagonal
        n = 16
        cost = np.full((n, n), 5.0, np.float32)
        np.fill_diagonal(cost, 0.1)
        res = assign_sinkhorn(jnp.asarray(cost), eps=0.05, num_iters=300)
        p4t = check_feasible(res, cost)
        np.testing.assert_array_equal(p4t, np.arange(n))

    @pytest.mark.parametrize("seed", [0, 1])
    def test_quality_vs_optimal(self, seed):
        rng = np.random.default_rng(seed)
        n = 32
        cost = rng.uniform(0.0, 10.0, size=(n, n)).astype(np.float32)
        res = assign_sinkhorn(jnp.asarray(cost), eps=0.02, num_iters=500)
        p4t = check_feasible(res, cost)
        assert (p4t >= 0).all()
        ri, ci = linear_sum_assignment(cost)
        opt = cost[ri, ci].sum()
        got = matching_cost(cost, p4t)
        # entropic + rounding: allow 15% slack over optimal
        assert got <= opt * 1.15 + 1.0, f"sinkhorn {got} vs optimal {opt}"

    def test_rectangular_with_infeasibility(self):
        rng = np.random.default_rng(9)
        cost = random_cost(rng, 48, 24, p_infeasible=0.2)
        res = assign_sinkhorn(jnp.asarray(cost), eps=0.05, num_iters=300)
        check_feasible(res, cost)
        p4t = np.asarray(res.provider_for_task)
        feasible_tasks = (cost < INFEASIBLE * 0.5).any(axis=0)
        assert (p4t[feasible_tasks] >= 0).all()
