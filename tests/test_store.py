"""KV engine + domain store tests (hermetic per-test stores, mirroring the
reference's embedded-redis fixtures)."""


from protocol_tpu.models import HeartbeatRequest, MetricEntry, MetricKey, Task
from protocol_tpu.store import (
    KVStore,
    NodeStatus,
    OrchestratorNode,
    StoreContext,
)


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class TestKV:
    def test_set_get_delete(self):
        kv = KVStore()
        assert kv.set("a", "1")
        assert kv.get("a") == "1"
        assert kv.delete("a") == 1
        assert kv.get("a") is None

    def test_set_nx(self):
        kv = KVStore()
        assert kv.set("k", "1", nx=True)
        assert not kv.set("k", "2", nx=True)
        assert kv.get("k") == "1"

    def test_ttl_expiry(self):
        clock = FakeClock()
        kv = KVStore(time_fn=clock)
        kv.set("k", "v", ex=60)
        assert kv.get("k") == "v"
        clock.advance(61)
        assert kv.get("k") is None
        assert not kv.exists("k")

    def test_set_clears_ttl(self):
        clock = FakeClock()
        kv = KVStore(time_fn=clock)
        kv.set("k", "v", ex=10)
        kv.set("k", "v2")
        clock.advance(100)
        assert kv.get("k") == "v2"

    def test_incr(self):
        kv = KVStore()
        assert kv.incr("c") == 1
        assert kv.incr("c") == 2

    def test_hash_ops(self):
        kv = KVStore()
        kv.hset("h", "f1", "a")
        kv.hset_mapping("h", {"f2": "b", "f3": "c"})
        assert kv.hget("h", "f2") == "b"
        assert kv.hgetall("h") == {"f1": "a", "f2": "b", "f3": "c"}
        assert kv.hdel("h", "f1", "nope") == 1
        assert kv.hincrby("h", "n", 5) == 5

    def test_set_ops(self):
        kv = KVStore()
        assert kv.sadd("s", "a", "b") == 2
        assert kv.sadd("s", "b", "c") == 1
        assert kv.smembers("s") == {"a", "b", "c"}
        assert kv.sismember("s", "a")
        assert kv.srem("s", "a") == 1
        assert kv.scard("s") == 2

    def test_zset_ops(self):
        kv = KVStore()
        kv.zadd("z", {"a": 3.0, "b": 1.0, "c": 2.0})
        assert kv.zrangebyscore("z", 1.5, 3.5) == [("c", 2.0), ("a", 3.0)]
        assert kv.zscore("z", "b") == 1.0
        assert kv.zremrangebyscore("z", 0, 2.0) == 2
        assert kv.zcard("z") == 1

    def test_list_ops(self):
        kv = KVStore()
        kv.rpush("l", "a", "b")
        kv.lpush("l", "z")
        assert kv.lrange("l") == ["z", "a", "b"]
        assert kv.lrange("l", 0, 1) == ["z", "a"]
        assert kv.lrem("l", 0, "a") == 1
        assert kv.llen("l") == 2

    def test_wrongtype(self):
        kv = KVStore()
        kv.set("k", "v")
        import pytest

        with pytest.raises(TypeError):
            kv.hset("k", "f", "v")

    def test_keys_pattern(self):
        kv = KVStore()
        kv.set("node:1", "a")
        kv.set("node:2", "b")
        kv.set("task:1", "c")
        assert sorted(kv.keys("node:*")) == ["node:1", "node:2"]


class TestNodeStore:
    def test_add_get_roundtrip(self):
        ctx = StoreContext.new_test()
        n = OrchestratorNode(address="0xa", ip_address="1.1.1.1", port=80)
        ctx.node_store.add_node(n)
        got = ctx.node_store.get_node("0xa")
        assert got.address == "0xa"
        assert got.status == NodeStatus.DISCOVERED
        assert len(ctx.node_store.get_nodes()) == 1

    def test_status_transition_stamps_time(self):
        ctx = StoreContext.new_test()
        ctx.node_store.add_node(OrchestratorNode(address="0xa"))
        ctx.node_store.update_node_status("0xa", NodeStatus.HEALTHY)
        got = ctx.node_store.get_node("0xa")
        assert got.status == NodeStatus.HEALTHY
        assert got.last_status_change is not None

    def test_uninvited(self):
        ctx = StoreContext.new_test()
        ctx.node_store.add_node(OrchestratorNode(address="0xa"))
        ctx.node_store.add_node(
            OrchestratorNode(address="0xb", status=NodeStatus.HEALTHY)
        )
        assert [n.address for n in ctx.node_store.get_uninvited_nodes()] == ["0xa"]

    def test_remove(self):
        ctx = StoreContext.new_test()
        ctx.node_store.add_node(OrchestratorNode(address="0xa"))
        ctx.node_store.remove_node("0xa")
        assert ctx.node_store.get_node("0xa") is None
        assert ctx.node_store.get_nodes() == []


class TestTaskStore:
    def test_crud_and_observers(self):
        ctx = StoreContext.new_test()
        created, deleted = [], []
        ctx.task_store.subscribe_created(lambda t: created.append(t.id))
        ctx.task_store.subscribe_deleted(lambda t: deleted.append(t.id))

        t = Task(name="t1", image="img")
        ctx.task_store.add_task(t)
        assert created == [t.id]
        assert ctx.task_store.name_exists("t1")
        assert ctx.task_store.get_task(t.id).name == "t1"
        assert len(ctx.task_store.get_all_tasks()) == 1

        ctx.task_store.delete_task(t.id)
        assert deleted == [t.id]
        assert ctx.task_store.get_task(t.id) is None
        assert not ctx.task_store.name_exists("t1")

    def test_ordering_preserved(self):
        ctx = StoreContext.new_test()
        ids = []
        for i in range(5):
            t = Task(name=f"t{i}", image="img", created_at=i)
            ctx.task_store.add_task(t)
            ids.append(t.id)
        assert [t.id for t in ctx.task_store.get_all_tasks()] == ids


class TestHeartbeatStore:
    def test_beat_ttl(self):
        clock = FakeClock()
        kv = KVStore(time_fn=clock)
        ctx = StoreContext(kv)
        hb = HeartbeatRequest(address="0xa", task_state="RUNNING")
        ctx.heartbeat_store.beat(hb)
        assert ctx.heartbeat_store.get_heartbeat("0xa").task_state == "RUNNING"
        clock.advance(91)
        assert ctx.heartbeat_store.get_heartbeat("0xa") is None

    def test_unhealthy_counter(self):
        ctx = StoreContext.new_test()
        assert ctx.heartbeat_store.increment_unhealthy_counter("0xa") == 1
        assert ctx.heartbeat_store.increment_unhealthy_counter("0xa") == 2
        assert ctx.heartbeat_store.get_unhealthy_counter("0xa") == 2
        ctx.heartbeat_store.clear_unhealthy_counter("0xa")
        assert ctx.heartbeat_store.get_unhealthy_counter("0xa") == 0


class TestMetricsStore:
    def test_store_and_fetch(self):
        ctx = StoreContext.new_test()
        e = MetricEntry(key=MetricKey(task_id="t1", label="loss"), value=0.5)
        ctx.metrics_store.store_metrics([e], "0xa")
        got = ctx.metrics_store.get_metrics_for_task("t1")
        assert got == {"loss": {"0xa": 0.5}}

    def test_delete_for_node(self):
        ctx = StoreContext.new_test()
        e = MetricEntry(key=MetricKey(task_id="t1", label="loss"), value=0.5)
        ctx.metrics_store.store_metrics([e], "0xa")
        ctx.metrics_store.store_metrics([e], "0xb")
        ctx.metrics_store.delete_metrics_for_node("0xa")
        assert ctx.metrics_store.get_metrics_for_task("t1") == {"loss": {"0xb": 0.5}}
        ctx.metrics_store.delete_metrics_for_node("0xb")
        assert ctx.metrics_store.get_all_metrics() == {}
