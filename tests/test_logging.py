"""Loki log shipping (reference worker/src/utils/logging.rs:39-60): push
API shape, batching, labels, and failure tolerance against a live fake
Loki endpoint."""

import http.server
import json
import logging
import threading

from protocol_tpu.utils.logging import LokiHandler, setup_logging


class _FakeLoki(http.server.BaseHTTPRequestHandler):
    pushes: list[dict] = []
    fail = False

    def do_POST(self):
        body = self.rfile.read(int(self.headers["Content-Length"]))
        if _FakeLoki.fail:
            self.send_response(500)
            self.end_headers()
            return
        _FakeLoki.pushes.append(
            {"path": self.path, "body": json.loads(body)}
        )
        self.send_response(204)
        self.end_headers()

    def log_message(self, *a):  # silence
        pass


def _serve():
    srv = http.server.HTTPServer(("127.0.0.1", 0), _FakeLoki)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_port}"


def test_loki_push_shape_and_labels():
    _FakeLoki.pushes = []
    srv, url = _serve()
    try:
        h = LokiHandler(url, labels={"service": "worker", "pool": "3"},
                        flush_interval=600)  # manual flush only
        log = logging.getLogger("loki-test")
        log.addHandler(h)
        log.setLevel(logging.INFO)
        h.setFormatter(logging.Formatter("%(levelname)s %(message)s"))
        log.info("hello loki")
        log.warning("watch out")
        h.flush()
        assert h.pushed == 2 and h.dropped == 0
        push = _FakeLoki.pushes[-1]
        assert push["path"] == "/loki/api/v1/push"
        stream = push["body"]["streams"][0]
        assert stream["stream"] == {
            "job": "protocol_tpu", "service": "worker", "pool": "3"
        }
        values = stream["values"]
        assert values[0][1] == "INFO hello loki"
        assert values[1][1] == "WARNING watch out"
        assert int(values[0][0]) > 1e18  # nanosecond timestamps
        h.close()
    finally:
        srv.shutdown()


def test_loki_failure_never_raises():
    srv, url = _serve()
    try:
        _FakeLoki.fail = True
        h = LokiHandler(url, flush_interval=600)
        log = logging.getLogger("loki-fail")
        log.addHandler(h)
        log.setLevel(logging.INFO)
        log.info("doomed")
        h.flush()  # 500 from the sink: swallowed, counted
        assert h.dropped == 1 and h.pushed == 0
        h.close()
    finally:
        _FakeLoki.fail = False
        srv.shutdown()


def test_setup_logging_wires_handler():
    _FakeLoki.pushes = []
    srv, url = _serve()
    root = logging.getLogger()
    before = list(root.handlers)
    try:
        h = setup_logging(level="info", loki_url=url,
                          labels={"service": "validator"})
        assert h is not None
        # WARNING: immune to whatever root level earlier tests configured
        logging.getLogger("anything").warning("via root")
        h.flush()
        assert h.pushed >= 1
        assert _FakeLoki.pushes[-1]["body"]["streams"][0]["stream"][
            "service"
        ] == "validator"
    finally:
        for extra in [x for x in root.handlers if x not in before]:
            root.removeHandler(extra)
            extra.close()
        srv.shutdown()
