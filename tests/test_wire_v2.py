"""Wire protocol v2: tensor frames, session epochs, streaming snapshots.

Covers the ISSUE 2 contract: v1<->v2 wire parity (bit-identical
matchings for the dense, sparse and warm kernels), a delta-session churn
sequence (add/remove/mutate provider rows across >= 3 AssignDelta ticks
checked against a full-snapshot reference arena), fingerprint-mismatch
fallback, snapshots larger than one stream chunk, transport retry, and
the session-loss recovery path. tests/test_scheduler_grpc.py stays
UNMODIFIED — old v1 clients against the new server are proven there.
"""

import numpy as np
import pytest

import grpc

import bench
from protocol_tpu import native
from protocol_tpu.ops.cost import CostWeights
from protocol_tpu.proto import scheduler_pb2 as pb
from protocol_tpu.proto import wire
from protocol_tpu.services.scheduler_grpc import (
    RemoteBatchMatcher,
    SchedulerBackendClient,
    encoded_to_proto,
    encoded_to_proto_v2,
    serve,
)

ADDR = "127.0.0.1:50975"
NATIVE = native.available()


@pytest.fixture(scope="module")
def backend():
    server = serve(address=ADDR)
    client = SchedulerBackendClient(ADDR)
    yield server, client
    client.close()
    server.stop(grace=None)


def _market(seed=0, P=96, T=64):
    rng = np.random.default_rng(seed)
    return bench.synth_providers(rng, P), bench.synth_requirements(rng, T)


# ---------------- tensor frames ----------------


def test_blob_roundtrip():
    for arr in (
        np.arange(7, dtype=np.int32),
        np.random.default_rng(0).normal(size=(3, 5)).astype(np.float32),
        np.array([True, False, True]),
        np.zeros((2, 3, 4), np.uint32),
    ):
        out = wire.unblob(wire.blob(arr))
        assert out.dtype == arr.dtype
        np.testing.assert_array_equal(out, arr)


def test_unblob_rejects_mismatch():
    b = wire.blob(np.arange(4, dtype=np.int32))
    with pytest.raises(ValueError, match="dtype mismatch"):
        wire.unblob(b, np.float32)
    b2 = wire.blob(np.arange(4, dtype=np.int32))
    b2.shape[:] = [5]
    with pytest.raises(ValueError, match="size mismatch"):
        wire.unblob(b2)


def test_encode_decode_batches_roundtrip():
    ep, er = _market()
    ep2 = wire.decode_providers_v2(wire.encode_providers_v2(ep))
    er2 = wire.decode_requirements_v2(wire.encode_requirements_v2(er))
    for name in wire.P_WIRE_DTYPES:
        np.testing.assert_array_equal(
            np.asarray(getattr(ep, name)), np.asarray(getattr(ep2, name)),
            err_msg=name,
        )
    for name in wire.R_WIRE_DTYPES:
        np.testing.assert_array_equal(
            np.asarray(getattr(er, name)), np.asarray(getattr(er2, name)),
            err_msg=name,
        )


# ---------------- v1 <-> v2 unary parity ----------------


@pytest.mark.parametrize(
    "kernel",
    ["greedy", "auction", "sinkhorn", "topk"]
    + (["native-mt:2"] if NATIVE else []),
)
def test_unary_wire_parity(backend, kernel):
    """The codec must be invisible: same kernel, same matching, bit for
    bit, whichever wire carried the batch."""
    _, client = backend
    ep, er = _market(seed=1)
    r1 = client.assign(encoded_to_proto(ep, er, kernel=kernel, top_k=16))
    r2 = client.assign_v2(
        encoded_to_proto_v2(ep, er, kernel=kernel, top_k=16)
    )
    np.testing.assert_array_equal(
        np.asarray(r1.provider_for_task, np.int32),
        wire.unblob(r2.provider_for_task, np.int32),
    )
    np.testing.assert_array_equal(
        np.asarray(r1.task_for_provider, np.int32),
        wire.unblob(r2.task_for_provider, np.int32),
    )
    assert r1.num_assigned == r2.num_assigned


def test_warm_topk_wire_parity(backend):
    """The stateless warm path (prices + seeds riding the wire) must be
    codec-independent too."""
    _, client = backend
    ep, er = _market(seed=2)
    cold1 = client.assign(encoded_to_proto(ep, er, kernel="topk", top_k=16))
    warm_price = np.asarray(cold1.price, np.float32)
    seeds = np.asarray(cold1.provider_for_task, np.int32)

    req1 = encoded_to_proto(ep, er, kernel="topk", top_k=16)
    req1.warm_price.extend(warm_price)
    req1.seed_provider_for_task.extend(seeds)
    warm1 = client.assign(req1)

    req2 = encoded_to_proto_v2(ep, er, kernel="topk", top_k=16)
    req2.warm_price.CopyFrom(wire.blob(warm_price, np.float32))
    req2.seed_provider_for_task.CopyFrom(wire.blob(seeds, np.int32))
    warm2 = client.assign_v2(req2)

    np.testing.assert_array_equal(
        np.asarray(warm1.provider_for_task, np.int32),
        wire.unblob(warm2.provider_for_task, np.int32),
    )


# ---------------- session epochs ----------------


def _open(client, p_cols, r_cols, kernel="native-mt:2", top_k=16,
          session_id="s-test", chunk_bytes=1 << 20, fp=None):
    w = CostWeights()
    if fp is None:
        fp = wire.epoch_fingerprint(p_cols, r_cols, w, kernel, top_k, 0.02, 0)
    req = encoded_to_proto_v2(
        wire.take_rows(p_cols, slice(None)),
        wire.take_rows(r_cols, slice(None)),
        w, kernel=kernel, top_k=top_k, eps=0.02,
    )
    chunks = list(
        wire.chunk_snapshot(session_id, fp, req, chunk_bytes=chunk_bytes)
    )
    return client.open_session(iter(chunks)), fp, chunks


@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
class TestSessionProtocol:
    def test_delta_churn_sequence_matches_full_snapshot_reference(
        self, backend
    ):
        """>= 3 AssignDelta ticks with add/remove/mutate provider rows:
        every tick's matching must be BIT-IDENTICAL to a reference warm
        arena fed the same sequence as full snapshots — the delta codec
        reconstructs the same server-side state, so the same solver sees
        the same inputs."""
        from protocol_tpu.native.arena import NativeSolveArena
        from protocol_tpu.services.session_store import _as_ns, _pad_cols

        _, client = backend
        P, T = 96, 64
        ep, er = _market(seed=3, P=P, T=T)
        p_cols = wire.canon_columns(ep, wire.P_WIRE_DTYPES)
        r_cols = wire.canon_columns(er, wire.R_WIRE_DTYPES)
        resp, fp, _ = _open(client, p_cols, r_cols, session_id="s-churn")
        assert resp.ok, resp.error

        ref = NativeSolveArena(k=16, threads=2)
        w = CostWeights()
        r_pad = _pad_cols(r_cols, T)
        ref_p4t = ref.solve(
            _as_ns(_pad_cols(p_cols, P)), _as_ns(r_pad), w
        )
        np.testing.assert_array_equal(
            np.asarray(ref_p4t)[:T],
            wire.unblob(resp.result.provider_for_task, np.int32),
        )

        rng = np.random.default_rng(7)
        cur = {k: v.copy() for k, v in p_cols.items()}
        for tick in range(1, 4):
            rows = [tick, 10 + tick, 40 + tick]
            # mutate: reprice one row; remove: invalidate one row;
            # add (rejoin): revalidate a previously-removed row with
            # fresh specs — the three churn classes of a live fleet
            cur["price"][rows[0]] = np.float32(rng.uniform(0.5, 4.0))
            cur["valid"][rows[1]] = False
            cur["valid"][rows[2]] = True
            cur["gpu_mem_mb"][rows[2]] = np.int32(80000)
            idx = np.asarray(rows, np.int32)
            dreq = pb.AssignDeltaRequest(
                session_id="s-churn", epoch_fingerprint=fp, tick=tick
            )
            dreq.provider_rows.CopyFrom(wire.blob(idx, np.int32))
            dreq.providers.CopyFrom(
                wire.encode_providers_v2(wire.take_rows(cur, idx))
            )
            dresp = client.assign_delta(dreq)
            assert dresp.session_ok, dresp.error
            got = wire.unblob(dresp.result.provider_for_task, np.int32)

            ref_pad = _pad_cols(cur, P)
            ref_p4t = ref.solve(
                _as_ns({k: v.copy() for k, v in ref_pad.items()}),
                _as_ns(r_pad), w,
            )
            np.testing.assert_array_equal(np.asarray(ref_p4t)[:T], got)
            # the matching must be injective and never seat a removed row
            pos = got[got >= 0]
            assert np.unique(pos).size == pos.size
            assert not np.isin(pos, np.flatnonzero(~cur["valid"])).any()

    def test_fingerprint_mismatch_refused(self, backend):
        _, client = backend
        ep, er = _market(seed=4)
        p_cols = wire.canon_columns(ep, wire.P_WIRE_DTYPES)
        r_cols = wire.canon_columns(er, wire.R_WIRE_DTYPES)
        resp, fp, _ = _open(client, p_cols, r_cols, session_id="s-fp")
        assert resp.ok
        bad = pb.AssignDeltaRequest(
            session_id="s-fp", epoch_fingerprint="deadbeef", tick=1
        )
        r = client.assign_delta(bad)
        assert not r.session_ok
        assert "fingerprint" in r.error

    def test_unknown_session_refused(self, backend):
        _, client = backend
        r = client.assign_delta(
            pb.AssignDeltaRequest(
                session_id="never-opened", epoch_fingerprint="x", tick=1
            )
        )
        assert not r.session_ok
        assert "unknown" in r.error

    def test_tick_replay_and_divergence(self, backend):
        """A byte-identical retransmit of the last applied tick is
        answered idempotently from the dedup cache (the ISSUE 9 crash
        protocol: the original response died on the wire); a same-tick
        request with DIFFERENT bytes is genuine divergence and refuses,
        as does a skipped tick."""
        _, client = backend
        ep, er = _market(seed=5)
        p_cols = wire.canon_columns(ep, wire.P_WIRE_DTYPES)
        r_cols = wire.canon_columns(er, wire.R_WIRE_DTYPES)
        resp, fp, _ = _open(client, p_cols, r_cols, session_id="s-tick")
        assert resp.ok
        ok = client.assign_delta(pb.AssignDeltaRequest(
            session_id="s-tick", epoch_fingerprint=fp, tick=1
        ))
        assert ok.session_ok and not ok.replayed
        # identical retransmit: replayed twin, applied exactly once
        replay = client.assign_delta(pb.AssignDeltaRequest(
            session_id="s-tick", epoch_fingerprint=fp, tick=1
        ))
        assert replay.session_ok and replay.replayed
        np.testing.assert_array_equal(
            wire.unblob(ok.result.provider_for_task, np.int32),
            wire.unblob(replay.result.provider_for_task, np.int32),
        )
        # same tick, different bytes: diverged shadow state — refused
        rows = np.array([0], np.int32)
        diverged = client.assign_delta(pb.AssignDeltaRequest(
            session_id="s-tick", epoch_fingerprint=fp, tick=1,
            provider_rows=wire.blob(rows, np.int32),
            providers=wire.encode_providers_v2(
                wire.take_rows(p_cols, rows)
            ),
        ))
        assert not diverged.session_ok
        assert "tick" in diverged.error
        # skipped tick: refused (the cursor is at 1, not 2)
        skipped = client.assign_delta(pb.AssignDeltaRequest(
            session_id="s-tick", epoch_fingerprint=fp, tick=3
        ))
        assert not skipped.session_ok
        assert "tick" in skipped.error

    def test_client_claimed_fingerprint_is_verified(self, backend):
        """A client whose codec disagrees with the server must be told at
        OPEN time, not drift silently."""
        _, client = backend
        ep, er = _market(seed=6)
        p_cols = wire.canon_columns(ep, wire.P_WIRE_DTYPES)
        r_cols = wire.canon_columns(er, wire.R_WIRE_DTYPES)
        resp, _, _ = _open(
            client, p_cols, r_cols, session_id="s-bad", fp="not-the-hash"
        )
        assert not resp.ok
        assert "fingerprint" in resp.error

    def test_non_native_kernel_refused_falls_to_unary(self, backend):
        _, client = backend
        ep, er = _market(seed=7)
        p_cols = wire.canon_columns(ep, wire.P_WIRE_DTYPES)
        r_cols = wire.canon_columns(er, wire.R_WIRE_DTYPES)
        resp, _, _ = _open(
            client, p_cols, r_cols, kernel="topk", session_id="s-topk"
        )
        assert not resp.ok
        assert "session-servable" in resp.error

    def test_snapshot_streams_in_multiple_chunks(self, backend):
        """A snapshot larger than one chunk must reassemble exactly
        (gzip on, 512-byte chunks -> many frames)."""
        _, client = backend
        ep, er = _market(seed=8, P=128, T=96)
        p_cols = wire.canon_columns(ep, wire.P_WIRE_DTYPES)
        r_cols = wire.canon_columns(er, wire.R_WIRE_DTYPES)
        resp, fp, chunks = _open(
            client, p_cols, r_cols, session_id="s-chunks", chunk_bytes=512
        )
        assert len(chunks) > 3
        assert chunks[0].codec in ("", "gzip")
        assert chunks[0].total_bytes == sum(len(c.payload) for c in chunks)
        assert resp.ok, resp.error
        assert resp.epoch_fingerprint == fp

    def test_truncated_snapshot_rejected(self, backend):
        _, client = backend
        ep, er = _market(seed=9)
        p_cols = wire.canon_columns(ep, wire.P_WIRE_DTYPES)
        r_cols = wire.canon_columns(er, wire.R_WIRE_DTYPES)
        w = CostWeights()
        fp = wire.epoch_fingerprint(
            p_cols, r_cols, w, "native-mt:2", 16, 0.02, 0
        )
        req = encoded_to_proto_v2(
            wire.take_rows(p_cols, slice(None)),
            wire.take_rows(r_cols, slice(None)),
            w, kernel="native-mt:2", top_k=16, eps=0.02,
        )
        chunks = list(wire.chunk_snapshot("s-trunc", fp, req, chunk_bytes=512))
        resp = client.open_session(iter(chunks[:-1]))  # drop the tail
        assert not resp.ok
        assert "truncated" in resp.error


# ---------------- the matcher client half ----------------


def _pool_world(n_nodes=12, n_tasks=5):
    import random

    from tests.test_encoding import random_specs
    from protocol_tpu.models.task import SchedulingConfig, Task, TaskRequest
    from protocol_tpu.store import NodeStatus, OrchestratorNode, StoreContext

    rng = random.Random(7)
    store = StoreContext.new_test()
    for i in range(n_nodes):
        store.node_store.add_node(
            OrchestratorNode(
                address=f"0xnode{i:02d}",
                status=NodeStatus.HEALTHY,
                ip_address=f"10.0.0.{i}",
                port=9000 + i,
                compute_specs=random_specs(rng),
            )
        )
    for i in range(n_tasks):
        cfg = None
        if i % 2 == 0:
            cfg = SchedulingConfig(plugins={"tpu_scheduler": {"replicas": ["2"]}})
        store.task_store.add_task(
            Task.from_request(
                TaskRequest(name=f"task-{i}", image="img", scheduling_config=cfg)
            )
        )
    return store


def test_remote_matcher_v2_parity_with_v1(backend):
    """wire=v2 is a codec change, not a scheduler change: the assignment
    must match wire=v1 exactly."""
    store = _pool_world()
    m1 = RemoteBatchMatcher(store, ADDR, min_solve_interval=0.0, wire="v1")
    m2 = RemoteBatchMatcher(store, ADDR, min_solve_interval=0.0, wire="v2")
    m1.refresh()
    m2.refresh()
    assert m1._assignment == m2._assignment
    assert m2._assignment, "v2 matcher assigned nothing"
    assert m2.last_solve_stats["wire"] == "v2"
    assert m2.last_solve_stats["remote_bytes_out"] > 0


@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
def test_remote_matcher_session_reuse_and_recovery(backend):
    """The native-mt matcher rides the session protocol: repeat
    refreshes advance the session tick (deltas, not snapshots), and a
    server-side session loss re-opens transparently."""
    server, _ = backend
    store = _pool_world()
    m = RemoteBatchMatcher(
        store, ADDR, min_solve_interval=0.0, wire="v2",
        native_fallback=True, native_engine="native-mt", native_threads=2,
    )
    m.refresh()
    assert m._session is not None and m._session["tick"] == 0
    m.refresh()
    assert m._session["tick"] == 1  # delta tick, not a new snapshot

    # evict server-side (replica restart / LRU): next refresh must
    # re-open from client state instead of erroring the scheduler tick
    server.servicer.sessions.drop(m._session["id"])
    m.refresh()
    assert m._session["tick"] == 0
    assert m.seam.snapshot().get("session_session_reopen", 0) >= 1
    assert m._assignment


class _FlakyClient:
    """Wraps a real client; fails the first N calls of each RPC with a
    retryable code."""

    def __init__(self, real, fail_n=1,
                 code=grpc.StatusCode.UNAVAILABLE, only=None):
        self._real = real
        self._fails = {"assign": fail_n, "assign_v2": fail_n,
                       "assign_delta": fail_n, "open_session": fail_n}
        self._code = code
        self._only = only
        self.address = real.address

    def _maybe_fail(self, name):
        if self._only is not None and name not in self._only:
            return
        if self._fails[name] > 0:
            self._fails[name] -= 1
            err = grpc.RpcError()
            err.code = lambda: self._code
            raise err

    def assign(self, *a, **k):
        self._maybe_fail("assign")
        return self._real.assign(*a, **k)

    def assign_v2(self, *a, **k):
        self._maybe_fail("assign_v2")
        return self._real.assign_v2(*a, **k)

    def assign_delta(self, *a, **k):
        self._maybe_fail("assign_delta")
        return self._real.assign_delta(*a, **k)

    def open_session(self, *a, **k):
        self._maybe_fail("open_session")
        return self._real.open_session(*a, **k)

    def health(self, *a, **k):
        return self._real.health(*a, **k)

    def close(self):
        pass


def test_transient_unavailable_is_retried(backend):
    """One flaky RPC must not fail a scheduler tick: bounded backoff +
    reconnect, then success."""
    store = _pool_world(n_nodes=6, n_tasks=2)
    m = RemoteBatchMatcher(
        store, ADDR, min_solve_interval=0.0, wire="v1",
        retries=2, retry_base_s=0.01,
    )
    real = m.client
    m.client = _FlakyClient(real, fail_n=1)
    m._reconnect = lambda **kw: None  # keep the flaky wrapper through retries
    m.refresh()
    assert m._assignment
    assert m.seam.snapshot().get("session_retry", 0) >= 1
    real.close()


def test_retry_budget_exhausted_raises(backend):
    store = _pool_world(n_nodes=4, n_tasks=2)
    m = RemoteBatchMatcher(
        store, ADDR, min_solve_interval=0.0, wire="v1",
        retries=1, retry_base_s=0.01,
    )
    real = m.client
    m.client = _FlakyClient(real, fail_n=5)
    m._reconnect = lambda **kw: None
    with pytest.raises(grpc.RpcError):
        m.refresh()
    real.close()


def test_unimplemented_v2_falls_back_to_v1(backend):
    """Against an old server (no v2 RPCs) the matcher must drop to the
    frozen v1 contract permanently, not error."""
    store = _pool_world(n_nodes=6, n_tasks=2)
    m = RemoteBatchMatcher(store, ADDR, min_solve_interval=0.0, wire="v2")
    real = m.client
    m.client = _FlakyClient(
        real, fail_n=99, code=grpc.StatusCode.UNIMPLEMENTED,
        only={"assign_v2", "assign_delta", "open_session"},
    )
    m._reconnect = lambda **kw: None
    m.refresh()
    assert m.wire == "v1"
    assert m._assignment
    assert m.seam.snapshot().get("session_fallback_v1", 0) >= 1
    real.close()


def test_health_exposes_seam_metrics(backend):
    _, client = backend
    h = client.health()
    assert h.status == "ok"
    names = {s.name for s in h.seam_metrics}
    assert "sessions_active" in names
    assert any(n.startswith("solve_") for n in names)
