"""RemoteLedger client against a live LedgerApiService over real HTTP —
the seam standalone service pods use instead of the in-process Ledger
(reference: alloy JSON-RPC contract wrappers, shared/src/web3/)."""

import pytest

# Environment guard: this module's import chain reaches
# protocol_tpu.security / protocol_tpu.utils.tls, which need the
# third-party `cryptography` package (wallet signing + TLS material).
# On hosts without it, report the whole module as SKIPPED instead of a
# collection error (tier-1 keeps an honest skip count; CI installs
# cryptography and runs everything).
pytest.importorskip(
    "cryptography", reason="cryptography not installed (signing/TLS dependency)"
)

import asyncio
import threading
import time

import pytest

from protocol_tpu.chain import Ledger, LedgerError
from protocol_tpu.chain.ledger import PoolStatus, invite_digest
from protocol_tpu.chain.remote import RemoteLedger
from protocol_tpu.security import Wallet
from protocol_tpu.services.ledger_api import LedgerApiService


@pytest.fixture(scope="module")
def ledger_api():
    """LedgerApiService on a real port in a background thread, so the
    synchronous RemoteLedger can call it from the test thread."""
    ledger = Ledger()
    ready = threading.Event()
    state = {}

    def run():
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        async def boot():
            svc = LedgerApiService(ledger, admin_api_key="adm")
            runner = web.AppRunner(svc.make_app())
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            state["port"] = runner.addresses[0][1]
            ready.set()

        loop.run_until_complete(boot())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert ready.wait(10)
    yield ledger, RemoteLedger(
        f"http://127.0.0.1:{state['port']}", admin_api_key="adm"
    )


def test_full_surface_round_trip(ledger_api):
    local, remote = ledger_api
    creator, manager = Wallet.from_seed(b"rc"), Wallet.from_seed(b"rm")
    provider, node = Wallet.from_seed(b"rp"), Wallet.from_seed(b"rn")

    remote.mint(provider.address, 1000)
    assert remote.balance_of(provider.address) == 1000
    did = remote.create_domain("remote-domain", validation_logic="toploc")
    assert remote.get_domain(did).name == "remote-domain"
    pid = remote.create_pool(did, creator.address, manager.address, "ram_mb=1")
    pool = remote.get_pool_info(pid)
    assert pool.status == PoolStatus.PENDING
    assert pool.compute_manager_key == manager.address
    remote.start_pool(pid, creator.address)
    assert remote.get_pool_info(pid).status == PoolStatus.ACTIVE

    remote.register_provider(provider.address, 100)
    assert remote.provider_exists(provider.address)
    remote.whitelist_provider(provider.address)
    assert remote.is_provider_whitelisted(provider.address)
    remote.add_compute_node(provider.address, node.address)
    assert remote.node_exists(node.address)
    assert remote.get_node(node.address).provider == provider.address
    assert remote.get_stake(provider.address) == 100
    assert remote.calculate_stake(1) == local.calculate_stake(1)

    remote.grant_validator_role("0xval")
    assert remote.get_validator_role() == ["0xval"]
    remote.validate_node(node.address)
    assert remote.is_node_validated(node.address)

    # signed invite join through the remote client
    exp = time.time() + 60
    sig = manager.sign_message(invite_digest(did, pid, node.address, "n", exp))
    remote.join_compute_pool(pid, provider.address, node.address, "n", exp, sig)
    assert remote.is_node_in_pool(pid, node.address)

    remote.submit_work(pid, node.address, "fe" * 32, 42)
    info = remote.get_work_info(pid, "fe" * 32)
    assert info is not None and info.work_units == 42 and not info.invalidated
    assert len(remote.get_work_since(pid, time.time() - 60)) == 1
    remote.soft_invalidate_work(pid, "fe" * 32)
    assert remote.get_work_info(pid, "fe" * 32).soft_invalidated

    remote.leave_compute_pool(pid, node.address)
    assert not remote.is_node_in_pool(pid, node.address)

    # the remote client sees the same state the in-process ledger holds
    assert local.get_pool_info(pid).status == PoolStatus.ACTIVE


def test_errors_become_ledger_errors(ledger_api):
    _local, remote = ledger_api
    with pytest.raises(LedgerError):
        remote.get_pool_info(99999)
    # writes without the admin key are rejected
    anon = RemoteLedger(remote.base_url, admin_api_key="")
    with pytest.raises(LedgerError):
        anon.mint("0xx", 1)
    # unreachable API -> LedgerError, not a socket exception
    dead = RemoteLedger("http://127.0.0.1:1", timeout=0.3)
    with pytest.raises(LedgerError):
        dead.balance_of("0xx")


def test_cli_check_and_deregister(ledger_api, capsys):
    """Worker CLI parity (worker/src/cli/command.rs Check / Deregister)."""
    import json as _json

    from protocol_tpu import cli

    local, remote = ledger_api
    rc = cli.main(["check", "--storage-path", "/"])
    out = _json.loads(capsys.readouterr().out)
    assert "compute_specs" in out and isinstance(out["issues"], list)
    assert rc in (0, 1)

    provider, node = Wallet.from_seed(b"cli-p"), Wallet.from_seed(b"cli-n")
    remote.mint(provider.address, 1000)
    remote.register_provider(provider.address, 100)
    remote.add_compute_node(provider.address, node.address)
    assert remote.node_exists(node.address)
    rc = cli.main([
        "--ledger", remote.base_url, "--api-key", "adm",
        "deregister", "--provider", provider.address,
        "--node", node.address, "--reclaim", "50",
    ])
    assert rc == 0
    assert not remote.node_exists(node.address)
    assert remote.get_stake(provider.address) == 50


def test_services_accept_remote_ledger(ledger_api):
    """A DiscoveryService wired to the RemoteLedger behaves like one wired
    to the in-process ledger (the pod deployment shape)."""
    from aiohttp.test_utils import TestClient, TestServer

    from protocol_tpu.models import ComputeSpecs, CpuSpecs, Node
    from protocol_tpu.security import sign_request
    from protocol_tpu.services.discovery import DiscoveryService

    local, remote = ledger_api
    creator, manager = Wallet.from_seed(b"dc2"), Wallet.from_seed(b"dm2")
    provider, node = Wallet.from_seed(b"dp2"), Wallet.from_seed(b"dn2")
    remote.mint(provider.address, 1000)
    did = remote.create_domain("d2")
    pid = remote.create_pool(did, creator.address, manager.address, "")
    remote.register_provider(provider.address, 100)
    remote.add_compute_node(provider.address, node.address)

    svc = DiscoveryService(remote, pid)

    async def flow():
        async with TestClient(TestServer(svc.make_app())) as client:
            payload = Node(
                id=node.address,
                provider_address=provider.address,
                ip_address="3.3.3.3",
                port=1,
                compute_pool_id=pid,
                compute_specs=ComputeSpecs(cpu=CpuSpecs(cores=4), ram_mb=1),
            ).to_dict()
            headers, body = sign_request("/api/nodes", node, payload)
            # the remote ledger round-trip happens inside the handler; the
            # aiohttp loop must tolerate it (urllib call runs sync, small)
            r = await client.put("/api/nodes", json=body, headers=headers)
            return r.status

    assert asyncio.new_event_loop().run_until_complete(flow()) == 200


class TestWriteRetry:
    """The reference's retry_call semantics over HTTP
    (web3/contracts/helpers/utils.rs:22-70): transport failures retry,
    and the tx_id dedup guarantees a lost-response resend cannot
    double-apply the write."""

    def _flaky_proxy(self, upstream_port, fail_plan):
        """A TCP proxy that, per connection index in ``fail_plan``,
        forwards the request to the real ledger API but KILLS the client
        connection before relaying the response — the applied-but-
        response-lost failure mode."""
        import socket

        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(8)
        port = srv.getsockname()[1]
        seen = {"n": 0}

        def pump():
            while True:
                try:
                    cli, _ = srv.accept()
                except OSError:
                    return
                i = seen["n"]
                seen["n"] += 1
                try:
                    cli.settimeout(5)
                    data = b""
                    while b"\r\n\r\n" not in data:
                        data += cli.recv(65536)
                    head, _, body = data.partition(b"\r\n\r\n")
                    length = 0
                    for line in head.split(b"\r\n"):
                        if line.lower().startswith(b"content-length:"):
                            length = int(line.split(b":")[1])
                    while len(body) < length:
                        body += cli.recv(65536)
                    up = socket.create_connection(("127.0.0.1", upstream_port), 5)
                    up.sendall(data)
                    resp = b""
                    up.settimeout(5)
                    try:
                        while True:
                            chunk = up.recv(65536)
                            if not chunk:
                                break
                            resp += chunk
                            if b"\r\n\r\n" in resp:
                                # headers in; our API responds in one shot
                                break
                    except TimeoutError:
                        pass
                    up.close()
                    if i in fail_plan:
                        cli.close()  # response lost
                    else:
                        cli.sendall(resp)
                        cli.close()
                except Exception:
                    cli.close()

        t = threading.Thread(target=pump, daemon=True)
        t.start()
        return port, srv

    def test_lost_response_retry_applies_once(self, ledger_api):
        local, remote = ledger_api
        upstream_port = int(remote.base_url.rsplit(":", 1)[1])
        # fail the FIRST proxied connection's response (after forwarding)
        port, srv = self._flaky_proxy(upstream_port, fail_plan={0})
        try:
            flaky = RemoteLedger(
                f"http://127.0.0.1:{port}", admin_api_key="adm",
                retry_delay=0.05,
            )
            addr = "0xretry-once"
            before = local.balance_of(addr)
            flaky.mint(addr, 250)  # attempt 1 applies, response dies; retry dedups
            assert local.balance_of(addr) == before + 250, (
                "lost-response retry must apply the write exactly once"
            )
        finally:
            srv.close()

    def test_app_errors_do_not_retry(self, ledger_api):
        _local, remote = ledger_api
        calls = {"n": 0}
        orig = remote._http.post

        def counting(path, payload, **kw):
            calls["n"] += 1
            return orig(path, payload, **kw)

        remote._http.post = counting
        try:
            with pytest.raises(LedgerError):
                # transferring from an empty account is an APPLICATION
                # error: exactly one wire call, no retries
                remote.transfer("0xempty-src", "0xdst", 10**9)
        finally:
            remote._http.post = orig
        assert calls["n"] == 1


def test_read_path_ignores_tx_id_and_bad_bodies(ledger_api):
    """tx_id dedup is a write-path facility: reads are unauthenticated,
    so accepting tx_id there would hand strangers a memory lever. And
    non-object bodies get a clean 400, not a 500."""
    import json as _json
    import urllib.request

    _local, remote = ledger_api
    base = remote.base_url

    def post(path, body):
        req = urllib.request.Request(
            base + path, data=_json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        try:
            with urllib.request.urlopen(req) as r:
                return r.status, _json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, _json.loads(e.read())

    s1, d1 = post("/ledger/read/balance_of", {"address": "0xa", "tx_id": "x1"})
    # tx_id is NOT stripped on reads -> unknown kwarg -> clean 400
    assert s1 == 400 and "bad params" in d1["error"]
    s2, d2 = post("/ledger/read/balance_of", [1, 2])
    assert s2 == 400 and "object" in d2["error"]
    s3, d3 = post("/ledger/read/balance_of", {"address": "0xa"})
    assert s3 == 200 and d3["success"]
