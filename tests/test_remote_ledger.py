"""RemoteLedger client against a live LedgerApiService over real HTTP —
the seam standalone service pods use instead of the in-process Ledger
(reference: alloy JSON-RPC contract wrappers, shared/src/web3/)."""

import asyncio
import threading
import time

import pytest

from protocol_tpu.chain import Ledger, LedgerError
from protocol_tpu.chain.ledger import PoolStatus, invite_digest
from protocol_tpu.chain.remote import RemoteLedger
from protocol_tpu.security import Wallet
from protocol_tpu.services.ledger_api import LedgerApiService


@pytest.fixture(scope="module")
def ledger_api():
    """LedgerApiService on a real port in a background thread, so the
    synchronous RemoteLedger can call it from the test thread."""
    ledger = Ledger()
    ready = threading.Event()
    state = {}

    def run():
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        async def boot():
            svc = LedgerApiService(ledger, admin_api_key="adm")
            runner = web.AppRunner(svc.make_app())
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            state["port"] = runner.addresses[0][1]
            ready.set()

        loop.run_until_complete(boot())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert ready.wait(10)
    yield ledger, RemoteLedger(
        f"http://127.0.0.1:{state['port']}", admin_api_key="adm"
    )


def test_full_surface_round_trip(ledger_api):
    local, remote = ledger_api
    creator, manager = Wallet.from_seed(b"rc"), Wallet.from_seed(b"rm")
    provider, node = Wallet.from_seed(b"rp"), Wallet.from_seed(b"rn")

    remote.mint(provider.address, 1000)
    assert remote.balance_of(provider.address) == 1000
    did = remote.create_domain("remote-domain", validation_logic="toploc")
    assert remote.get_domain(did).name == "remote-domain"
    pid = remote.create_pool(did, creator.address, manager.address, "ram_mb=1")
    pool = remote.get_pool_info(pid)
    assert pool.status == PoolStatus.PENDING
    assert pool.compute_manager_key == manager.address
    remote.start_pool(pid, creator.address)
    assert remote.get_pool_info(pid).status == PoolStatus.ACTIVE

    remote.register_provider(provider.address, 100)
    assert remote.provider_exists(provider.address)
    remote.whitelist_provider(provider.address)
    assert remote.is_provider_whitelisted(provider.address)
    remote.add_compute_node(provider.address, node.address)
    assert remote.node_exists(node.address)
    assert remote.get_node(node.address).provider == provider.address
    assert remote.get_stake(provider.address) == 100
    assert remote.calculate_stake(1) == local.calculate_stake(1)

    remote.grant_validator_role("0xval")
    assert remote.get_validator_role() == ["0xval"]
    remote.validate_node(node.address)
    assert remote.is_node_validated(node.address)

    # signed invite join through the remote client
    exp = time.time() + 60
    sig = manager.sign_message(invite_digest(did, pid, node.address, "n", exp))
    remote.join_compute_pool(pid, provider.address, node.address, "n", exp, sig)
    assert remote.is_node_in_pool(pid, node.address)

    remote.submit_work(pid, node.address, "fe" * 32, 42)
    info = remote.get_work_info(pid, "fe" * 32)
    assert info is not None and info.work_units == 42 and not info.invalidated
    assert len(remote.get_work_since(pid, time.time() - 60)) == 1
    remote.soft_invalidate_work(pid, "fe" * 32)
    assert remote.get_work_info(pid, "fe" * 32).soft_invalidated

    remote.leave_compute_pool(pid, node.address)
    assert not remote.is_node_in_pool(pid, node.address)

    # the remote client sees the same state the in-process ledger holds
    assert local.get_pool_info(pid).status == PoolStatus.ACTIVE


def test_errors_become_ledger_errors(ledger_api):
    _local, remote = ledger_api
    with pytest.raises(LedgerError):
        remote.get_pool_info(99999)
    # writes without the admin key are rejected
    anon = RemoteLedger(remote.base_url, admin_api_key="")
    with pytest.raises(LedgerError):
        anon.mint("0xx", 1)
    # unreachable API -> LedgerError, not a socket exception
    dead = RemoteLedger("http://127.0.0.1:1", timeout=0.3)
    with pytest.raises(LedgerError):
        dead.balance_of("0xx")


def test_cli_check_and_deregister(ledger_api, capsys):
    """Worker CLI parity (worker/src/cli/command.rs Check / Deregister)."""
    import json as _json

    from protocol_tpu import cli

    local, remote = ledger_api
    rc = cli.main(["check", "--storage-path", "/"])
    out = _json.loads(capsys.readouterr().out)
    assert "compute_specs" in out and isinstance(out["issues"], list)
    assert rc in (0, 1)

    provider, node = Wallet.from_seed(b"cli-p"), Wallet.from_seed(b"cli-n")
    remote.mint(provider.address, 1000)
    remote.register_provider(provider.address, 100)
    remote.add_compute_node(provider.address, node.address)
    assert remote.node_exists(node.address)
    rc = cli.main([
        "--ledger", remote.base_url, "--api-key", "adm",
        "deregister", "--provider", provider.address,
        "--node", node.address, "--reclaim", "50",
    ])
    assert rc == 0
    assert not remote.node_exists(node.address)
    assert remote.get_stake(provider.address) == 50


def test_services_accept_remote_ledger(ledger_api):
    """A DiscoveryService wired to the RemoteLedger behaves like one wired
    to the in-process ledger (the pod deployment shape)."""
    from aiohttp.test_utils import TestClient, TestServer

    from protocol_tpu.models import ComputeSpecs, CpuSpecs, Node
    from protocol_tpu.security import sign_request
    from protocol_tpu.services.discovery import DiscoveryService

    local, remote = ledger_api
    creator, manager = Wallet.from_seed(b"dc2"), Wallet.from_seed(b"dm2")
    provider, node = Wallet.from_seed(b"dp2"), Wallet.from_seed(b"dn2")
    remote.mint(provider.address, 1000)
    did = remote.create_domain("d2")
    pid = remote.create_pool(did, creator.address, manager.address, "")
    remote.register_provider(provider.address, 100)
    remote.add_compute_node(provider.address, node.address)

    svc = DiscoveryService(remote, pid)

    async def flow():
        async with TestClient(TestServer(svc.make_app())) as client:
            payload = Node(
                id=node.address,
                provider_address=provider.address,
                ip_address="3.3.3.3",
                port=1,
                compute_pool_id=pid,
                compute_specs=ComputeSpecs(cpu=CpuSpecs(cores=4), ram_mb=1),
            ).to_dict()
            headers, body = sign_request("/api/nodes", node, payload)
            # the remote ledger round-trip happens inside the handler; the
            # aiohttp loop must tolerate it (urllib call runs sync, small)
            r = await client.put("/api/nodes", json=body, headers=headers)
            return r.status

    assert asyncio.new_event_loop().run_until_complete(flow()) == 200
