"""Ladder #5 capacity sharing, live in the matcher (VERDICT r3 item 5).

BASELINE.md config #5's core semantics: several tasks land on ONE
provider while its multi-resource capacity (GPU count, total VRAM, cpu,
ram, storage) holds. Colocate-flagged tasks route through the vector
bin-pack (ops/binpack.py) with the providers' REAL capacity vectors in
TpuBatchMatcher phase 0.5 — not the one-task-per-provider auction. The
reference cannot express this at all (one node, one task:
crates/orchestrator/src/scheduler/mod.rs:26-74).
"""

import importlib.util
import pytest

from protocol_tpu.models import (
    ComputeSpecs,
    CpuSpecs,
    GpuSpecs,
    SchedulingConfig,
    Task,
    TaskState,
)
from protocol_tpu.sched import Scheduler, TpuBatchMatcher
from protocol_tpu.sched.tpu_backend import (
    task_colocate,
    validate_tpu_scheduler_config,
)
from protocol_tpu.store import NodeStatus, OrchestratorNode, StoreContext


def mk_node(addr, gpu_count=2, cores=32, ram_mb=65536, model="H100"):
    return OrchestratorNode(
        address=addr,
        status=NodeStatus.HEALTHY,
        compute_specs=ComputeSpecs(
            gpu=GpuSpecs(count=gpu_count, model=model, memory_mb=80000),
            cpu=CpuSpecs(cores=cores),
            ram_mb=ram_mb,
            storage_gb=1000,
        ),
    )


def mk_colo_task(name, created_at, replicas, requirements, colocate=True):
    plugins = {
        "tpu_scheduler": {
            "replicas": [str(replicas)],
            "compute_requirements": [requirements],
        }
    }
    if colocate:
        plugins["tpu_scheduler"]["colocate"] = ["true"]
    return Task(
        name=name,
        image="img",
        created_at=created_at,
        state=TaskState.PENDING,
        scheduling_config=SchedulingConfig(plugins=plugins),
    )


ONE_GPU = "gpu:count=1;gpu:model=H100"


class TestColocationSolve:
    def test_two_one_gpu_tasks_share_a_two_gpu_provider(self):
        """THE ladder-#5 done-bar: a 2-GPU provider holds two 1-GPU tasks
        concurrently through the real solve path."""
        ctx = StoreContext.new_test()
        ctx.node_store.add_node(mk_node("0xprov"))
        t1 = mk_colo_task("a", 1.0, 1, ONE_GPU)
        t2 = mk_colo_task("b", 2.0, 1, ONE_GPU)
        ctx.task_store.add_task(t1)
        ctx.task_store.add_task(t2)
        m = TpuBatchMatcher(ctx, min_solve_interval=0.0)
        m.mark_dirty()

        node = ctx.node_store.get_node("0xprov")
        got = m.tasks_for_node(node)
        assert {t.id for t in got} == {t1.id, t2.id}
        assert m.last_solve_stats["colocated_slots"] == 2
        # the one-task surface stays coherent: first of the list
        assert m.task_for_node(node).id == got[0].id

    def test_capacity_respected_across_providers(self):
        """8 one-GPU replicas over a 2-GPU + 4-GPU fleet: exactly 6 seats
        exist; GPU capacity bounds every provider's load."""
        ctx = StoreContext.new_test()
        ctx.node_store.add_node(mk_node("0xsmall", gpu_count=2))
        ctx.node_store.add_node(mk_node("0xbig", gpu_count=4))
        t = mk_colo_task("many", 1.0, 8, ONE_GPU)
        ctx.task_store.add_task(t)
        m = TpuBatchMatcher(ctx, min_solve_interval=0.0)
        m.mark_dirty()
        m._ensure_fresh()

        # capacity accounting: 2 + 4 = 6 slots reserved; the wire lists
        # dedup to one instance per distinct task per node
        small = m.tasks_for_node(ctx.node_store.get_node("0xsmall"))
        big = m.tasks_for_node(ctx.node_store.get_node("0xbig"))
        assert [x.id for x in small] == [t.id] and [x.id for x in big] == [t.id]
        assert m.last_solve_stats["colocated_slots"] == 6
        assert m.last_solve_stats["colocated_unplaced"] == 2

    def test_vram_demand_bounds_stacking(self):
        """Per-GPU memory demand 80 GB: total VRAM (2 x 80 GB) admits two
        replicas even when gpu:count would admit more nominal slots."""
        ctx = StoreContext.new_test()
        ctx.node_store.add_node(mk_node("0xprov", gpu_count=2))
        t = mk_colo_task(
            "vram", 1.0, 4, "gpu:count=1;gpu:model=H100;gpu:memory_mb=80000"
        )
        ctx.task_store.add_task(t)
        m = TpuBatchMatcher(ctx, min_solve_interval=0.0)
        m.mark_dirty()
        m._ensure_fresh()
        # VRAM bounds the reservation at 2 of 4 requested slots
        assert m.last_solve_stats["colocated_slots"] == 2
        assert m.last_solve_stats["colocated_unplaced"] == 2

    def test_colocated_provider_excluded_from_auction(self):
        """A provider consumed by phase 0.5 must not also win a phase-1
        auction task (one capacity model at a time)."""
        ctx = StoreContext.new_test()
        ctx.node_store.add_node(mk_node("0xprov", gpu_count=2))
        ctx.node_store.add_node(mk_node("0xother", gpu_count=8, model="A100"))
        colo = mk_colo_task("colo", 1.0, 2, ONE_GPU)  # H100-only slices
        plain = mk_colo_task(
            "plain", 2.0, 1, "gpu:count=8;gpu:model=A100", colocate=False
        )
        ctx.task_store.add_task(colo)
        ctx.task_store.add_task(plain)
        m = TpuBatchMatcher(ctx, min_solve_interval=0.0)
        m.mark_dirty()
        m._ensure_fresh()

        prov_tasks = m.tasks_for_node(ctx.node_store.get_node("0xprov"))
        assert {t.id for t in prov_tasks} == {colo.id}
        assert m.last_solve_stats["colocated_slots"] == 2  # both replicas
        other = m.tasks_for_node(ctx.node_store.get_node("0xother"))
        assert [t.id for t in other] == [plain.id]

    def test_scheduler_and_heartbeat_surface(self):
        """get_tasks_for_node serves the full list; get_task_for_node the
        first — the legacy one-task surface stays intact."""
        ctx = StoreContext.new_test()
        ctx.node_store.add_node(mk_node("0xprov"))
        t1 = mk_colo_task("a", 1.0, 1, ONE_GPU)
        t2 = mk_colo_task("b", 2.0, 1, ONE_GPU)
        ctx.task_store.add_task(t1)
        ctx.task_store.add_task(t2)
        m = TpuBatchMatcher(ctx, min_solve_interval=0.0)
        sched = Scheduler(ctx, batch_matcher=m)
        m.mark_dirty()

        multi = sched.get_tasks_for_node("0xprov")
        assert {t.id for t in multi} == {t1.id, t2.id}
        one = sched.get_task_for_node("0xprov")
        assert one.id == multi[0].id

    def test_unassigned_capacity_goes_to_phase2(self):
        """Providers the bin-pack leaves untouched still flow to the
        unbounded phase as before (no phase-0.5 over-exclusion)."""
        ctx = StoreContext.new_test()
        ctx.node_store.add_node(mk_node("0xprov", gpu_count=2))
        ctx.node_store.add_node(mk_node("0xfree", gpu_count=8, model="A100"))
        colo = mk_colo_task("colo", 1.0, 2, ONE_GPU)  # H100-only slices
        swarm = Task(
            name="swarm", image="img", created_at=2.0, state=TaskState.PENDING
        )
        ctx.task_store.add_task(colo)
        ctx.task_store.add_task(swarm)
        m = TpuBatchMatcher(ctx, min_solve_interval=0.0)
        m.mark_dirty()
        m._ensure_fresh()
        free = m.tasks_for_node(ctx.node_store.get_node("0xfree"))
        assert [t.id for t in free] == [swarm.id]


class TestColocationConfig:
    def test_colocate_requires_replicas(self):
        t = Task(
            name="x", image="img", created_at=1.0, state=TaskState.PENDING,
            scheduling_config=SchedulingConfig(
                plugins={"tpu_scheduler": {"colocate": ["true"]}}
            ),
        )
        with pytest.raises(ValueError, match="replicas"):
            validate_tpu_scheduler_config(t)

    def test_colocate_excludes_anti_affinity(self):
        t = Task(
            name="x", image="img", created_at=1.0, state=TaskState.PENDING,
            scheduling_config=SchedulingConfig(
                plugins={"tpu_scheduler": {
                    "colocate": ["true"],
                    "replicas": ["2"],
                    "anti_affinity": ["task"],
                }}
            ),
        )
        with pytest.raises(ValueError, match="mutually exclusive"):
            validate_tpu_scheduler_config(t)

    def test_malformed_colocate_rejected(self):
        t = Task(
            name="x", image="img", created_at=1.0, state=TaskState.PENDING,
            scheduling_config=SchedulingConfig(
                plugins={"tpu_scheduler": {
                    "colocate": ["maybe"], "replicas": ["2"],
                }}
            ),
        )
        with pytest.raises(ValueError, match="colocate"):
            validate_tpu_scheduler_config(t)
        assert task_colocate(
            mk_colo_task("y", 1.0, 1, ONE_GPU, colocate=False)
        ) is False


class RecordingRuntime:
    """TaskRuntime test double: records applies, reports RUNNING."""

    def __init__(self, log):
        self.log = log
        self.task = None

    async def apply(self, task, node_address):
        self.log.append((id(self), task.id if task else None))
        self.task = task

    def state(self):
        if self.task is None:
            return None, None, None
        return self.task.id, TaskState.RUNNING, None



# Environment guard for the marked tests below: their code paths reach
# protocol_tpu.chain / protocol_tpu.security (wallet signing), which
# need the third-party `cryptography` package. Without it they skip —
# the rest of this module runs everywhere.
_HAS_CRYPTO = importlib.util.find_spec("cryptography") is not None
requires_crypto = pytest.mark.skipif(
    not _HAS_CRYPTO,
    reason="cryptography not installed (signing/TLS dependency)",
)

@requires_crypto
class TestWorkerConcurrentExecution:
    """The worker half of ladder #5: every colocated assignment beyond
    the primary runs CONCURRENTLY in its own runtime, reconciled per
    heartbeat (new task -> new runtime; departed -> apply(None))."""

    def _agent(self, log):
        import asyncio

        from protocol_tpu.chain import Ledger
        from protocol_tpu.security import Wallet
        from protocol_tpu.services.worker import WorkerAgent

        agent = WorkerAgent(
            Wallet.from_seed(b"colo-p"), Wallet.from_seed(b"colo-n"),
            Ledger(), 0,
            runtime=RecordingRuntime(log),
            runtime_factory=lambda slot=None: RecordingRuntime(log),
        )
        return agent, asyncio.new_event_loop()

    def test_extras_start_and_reconcile(self):
        log: list = []
        agent, loop = self._agent(log)
        t1 = mk_colo_task("a", 1.0, 1, ONE_GPU)
        t2 = mk_colo_task("b", 2.0, 1, ONE_GPU)
        t3 = mk_colo_task("c", 3.0, 1, ONE_GPU)

        loop.run_until_complete(agent._apply_extra_tasks([t2, t3]))
        assert set(agent.extra_runtimes) == {t2.id, t3.id}
        running = {rt.task.id for rt in agent.extra_runtimes.values()}
        assert running == {t2.id, t3.id}

        # t3 departs, t1 arrives: t3's runtime stopped AND dropped
        gone_rt = agent.extra_runtimes[t3.id]
        loop.run_until_complete(agent._apply_extra_tasks([t2, t1]))
        assert set(agent.extra_runtimes) == {t2.id, t1.id}
        assert gone_rt.task is None  # apply(None) stopped it

        # heartbeat payload reports every extra's state
        states = {
            tid: rt.state()[1] for tid, rt in agent.extra_runtimes.items()
        }
        assert all(s == TaskState.RUNNING for s in states.values())

    def test_heartbeat_reply_drives_concurrent_runtimes(self):
        """End to end through the REAL orchestrator heartbeat route: a
        colocated 2-GPU node receives assigned_tasks and runs both."""
        import asyncio

        import aiohttp
        from aiohttp.test_utils import TestServer

        from protocol_tpu.chain import Ledger
        from protocol_tpu.security import Wallet
        from protocol_tpu.services.orchestrator import OrchestratorService
        from protocol_tpu.services.worker import WorkerAgent
        from protocol_tpu.utils.storage import MockStorageProvider

        async def flow():
            ledger = Ledger()
            creator = Wallet.from_seed(b"cw")
            manager = Wallet.from_seed(b"cm")
            provider = Wallet.from_seed(b"cp")
            nodew = Wallet.from_seed(b"cn")
            ledger.mint(provider.address, 1000)
            did = ledger.create_domain("d")
            pid = ledger.create_pool(did, creator.address, manager.address, "")
            ledger.start_pool(pid, creator.address)
            ledger.register_provider(provider.address, 100)
            ledger.whitelist_provider(provider.address)
            ledger.add_compute_node(provider.address, nodew.address)

            ctx = StoreContext.new_test()
            m = TpuBatchMatcher(ctx, min_solve_interval=0.0)
            svc = OrchestratorService(
                ledger, pid, manager, store=ctx,
                scheduler=Scheduler(ctx, batch_matcher=m),
                storage=MockStorageProvider(),
            )
            ctx.node_store.add_node(mk_node(nodew.address, gpu_count=2))
            t1 = mk_colo_task("a", 1.0, 1, ONE_GPU)
            t2 = mk_colo_task("b", 2.0, 1, ONE_GPU)
            ctx.task_store.add_task(t1)
            ctx.task_store.add_task(t2)
            m.mark_dirty()

            server = TestServer(svc.make_app())
            await server.start_server()
            log: list = []
            async with aiohttp.ClientSession() as session:
                agent = WorkerAgent(
                    provider, nodew, ledger, pid,
                    runtime=RecordingRuntime(log),
                    runtime_factory=lambda slot=None: RecordingRuntime(log),
                    http=session,
                )
                agent.orchestrator_url = str(server.make_url("")).rstrip("/")
                agent.heartbeat_active = True
                got = await agent.heartbeat_once()
                assert got is not None
                primary = agent.runtime.task
                assert primary is not None
                assert len(agent.extra_runtimes) == 1
                extra = next(iter(agent.extra_runtimes.values())).task
                assert {primary.id, extra.id} == {t1.id, t2.id}
            await server.close()

        asyncio.new_event_loop().run_until_complete(flow())
