"""Worker crash recovery: persisted endpoint resumes heartbeating."""

import pytest

# Environment guard: this module's import chain reaches
# protocol_tpu.security / protocol_tpu.utils.tls, which need the
# third-party `cryptography` package (wallet signing + TLS material).
# On hosts without it, report the whole module as SKIPPED instead of a
# collection error (tier-1 keeps an honest skip count; CI installs
# cryptography and runs everything).
pytest.importorskip(
    "cryptography", reason="cryptography not installed (signing/TLS dependency)"
)

import tempfile

from protocol_tpu.chain import Ledger
from protocol_tpu.security import Wallet
from protocol_tpu.services.worker import SystemState, WorkerAgent


def test_state_roundtrip_and_recovery():
    with tempfile.TemporaryDirectory() as d:
        import os
        import stat

        node = Wallet.from_seed(b"n")
        state = SystemState(d)
        state.save("http://orch:8090", node.private_key_hex())
        assert state.load()["orchestrator_url"] == "http://orch:8090"
        # the file holds a private key: owner-only permissions
        mode = stat.S_IMODE(os.stat(state.path).st_mode)
        assert mode == 0o600, oct(mode)

        ledger = Ledger()
        agent = WorkerAgent(
            provider_wallet=Wallet.from_seed(b"p"),
            node_wallet=node,
            ledger=ledger,
            pool_id=0,
            state=SystemState(d),
        )
        # restart: resumes the persisted endpoint without a fresh invite
        assert agent.heartbeat_active
        assert agent.orchestrator_url == "http://orch:8090"


def test_recovery_refused_for_foreign_identity():
    """Stale state written by a DIFFERENT node wallet must not be resumed —
    the worker would sign beats the orchestrator never invited."""
    with tempfile.TemporaryDirectory() as d:
        SystemState(d).save("http://orch:8090", Wallet.from_seed(b"other").private_key_hex())
        agent = WorkerAgent(
            provider_wallet=Wallet.from_seed(b"p"),
            node_wallet=Wallet.from_seed(b"n"),
            ledger=Ledger(),
            pool_id=0,
            state=SystemState(d),
        )
        assert not agent.heartbeat_active


def test_no_auto_recover_flag():
    with tempfile.TemporaryDirectory() as d:
        SystemState(d).save("http://orch:8090", "ab" * 32)
        agent = WorkerAgent(
            provider_wallet=Wallet.from_seed(b"p"),
            node_wallet=Wallet.from_seed(b"n"),
            ledger=Ledger(),
            pool_id=0,
            state=SystemState(d),
            auto_recover=False,
        )
        assert not agent.heartbeat_active


def test_missing_state_is_clean():
    with tempfile.TemporaryDirectory() as d:
        assert SystemState(d).load() is None
        agent = WorkerAgent(
            provider_wallet=Wallet.from_seed(b"p"),
            node_wallet=Wallet.from_seed(b"n"),
            ledger=Ledger(),
            pool_id=0,
            state=SystemState(d),
        )
        assert not agent.heartbeat_active

        state = SystemState(d)
        state.save("u", "k")
        state.clear()
        assert state.load() is None
