"""Worker hardware/software checks (VERDICT r2 item 6).

Reference parity bar: crates/worker/src/checks/ — GPU probing (fake
nvidia-smi binary, same pattern as the fake-docker runtime tests),
storage mount detection, docker-daemon/NVIDIA-runtime/port checks, and
the composed boot gate.
"""

import json
import socket
import stat
import textwrap


from protocol_tpu.services.checks import (
    best_storage_path,
    check_docker,
    check_port_available,
    detect_gpus,
    memory_check,
    run_all_checks,
    scan_mount_points,
)


def fake_bin(tmp_path, name, body):
    p = tmp_path / name
    p.write_text("#!/bin/sh\n" + body)
    p.chmod(p.stat().st_mode | stat.S_IEXEC)
    return str(p)


class TestGpuDetection:
    def test_parses_nvidia_smi_csv(self, tmp_path):
        smi = fake_bin(
            tmp_path,
            "nvidia-smi",
            textwrap.dedent(
                """\
                cat <<'EOF'
                0, NVIDIA H100 80GB HBM3, 81559
                1, NVIDIA H100 80GB HBM3, 81559
                2, NVIDIA H100 80GB HBM3, 81559
                3, NVIDIA H100 80GB HBM3, 81559
                EOF
                """
            ),
        )
        gpus = detect_gpus(smi)
        assert len(gpus) == 1
        g = gpus[0]
        assert g.count == 4
        assert g.model == "nvidia h100 80gb hbm3"
        assert g.memory_mb == 81559
        assert g.indices == [0, 1, 2, 3]

    def test_visible_devices_filter(self, tmp_path, monkeypatch):
        smi = fake_bin(
            tmp_path,
            "nvidia-smi",
            'printf "0, A100, 40000\\n1, A100, 40000\\n2, A100, 40000\\n"',
        )
        monkeypatch.setenv("WORKER_VISIBLE_DEVICES", "0,2")
        gpus = detect_gpus(smi)
        assert gpus[0].count == 2
        assert gpus[0].indices == [0, 2]

    def test_heterogeneous_models_grouped(self, tmp_path):
        smi = fake_bin(
            tmp_path,
            "nvidia-smi",
            'printf "0, H100, 80000\\n1, RTX 4090, 24000\\n"',
        )
        gpus = detect_gpus(smi)
        assert {g.model for g in gpus} == {"h100", "rtx 4090"}

    def test_no_nvidia_stack(self):
        assert detect_gpus("/nonexistent/nvidia-smi") == []

    def test_failing_binary(self, tmp_path):
        smi = fake_bin(tmp_path, "nvidia-smi", "exit 9")
        assert detect_gpus(smi) == []


class TestStorage:
    def test_scan_mount_points_filters_pseudo(self, tmp_path):
        mounts = tmp_path / "mounts"
        mounts.write_text(
            "proc /proc proc rw 0 0\n"
            "sysfs /sys sysfs rw 0 0\n"
            "/dev/sda1 / ext4 rw 0 0\n"
            "tmpfs /dev/shm tmpfs rw 0 0\n"
        )
        points = scan_mount_points(str(mounts))
        assert [m.path for m in points] == ["/"]
        assert points[0].fs_type == "ext4"
        assert points[0].total_gb > 0

    def test_best_storage_path_fallback(self, tmp_path):
        path, avail = best_storage_path(str(tmp_path / "missing"))
        assert avail > 0

    def test_memory_check(self, tmp_path):
        mi = tmp_path / "meminfo"
        mi.write_text("MemTotal: 16384000 kB\nMemAvailable: 8192000 kB\n")
        total, avail = memory_check(str(mi))
        assert total == 16000 and avail == 8000


class TestDocker:
    def test_daemon_up_with_nvidia(self, tmp_path):
        docker = fake_bin(
            tmp_path,
            "docker",
            "echo '" + json.dumps({"Runtimes": {"nvidia": {}, "runc": {}}}) + "'",
        )
        up, nvidia, err = check_docker(docker)
        assert up and nvidia and err is None

    def test_daemon_up_no_nvidia(self, tmp_path):
        docker = fake_bin(
            tmp_path, "docker", "echo '" + json.dumps({"Runtimes": {"runc": {}}}) + "'"
        )
        up, nvidia, err = check_docker(docker)
        assert up and not nvidia

    def test_daemon_down(self, tmp_path):
        docker = fake_bin(
            tmp_path, "docker", "echo 'Cannot connect to the Docker daemon' >&2; exit 1"
        )
        up, nvidia, err = check_docker(docker)
        assert not up
        assert err

    def test_not_installed(self):
        up, nvidia, err = check_docker("definitely-not-docker-bin")
        assert not up and "not installed" in err


class TestPort:
    def test_available(self):
        assert check_port_available(0) is None  # ephemeral always binds

    def test_taken(self):
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.bind(("127.0.0.1", 0))
        s.listen(1)
        port = s.getsockname()[1]
        try:
            err = check_port_available(port, host="127.0.0.1")
            assert err is not None
        finally:
            s.close()



import importlib.util

import pytest

# Environment guard for the marked tests below: their code paths reach
# protocol_tpu.chain / protocol_tpu.security (wallet signing), which
# need the third-party `cryptography` package. Without it they skip —
# the rest of this module runs everywhere.
_HAS_CRYPTO = importlib.util.find_spec("cryptography") is not None
requires_crypto = pytest.mark.skipif(
    not _HAS_CRYPTO,
    reason="cryptography not installed (signing/TLS dependency)",
)

@requires_crypto
class TestComposedGate:
    def test_run_all_checks_with_fakes(self, tmp_path):
        smi = fake_bin(tmp_path, "nvidia-smi", 'printf "0, H100, 80000\\n"')
        docker = fake_bin(
            tmp_path,
            "docker",
            "echo '" + json.dumps({"Runtimes": {"nvidia": {}}}) + "'",
        )
        specs, report = run_all_checks(
            "/",
            nvidia_smi=smi,
            docker_bin=docker,
            probe_accelerator=False,
        )
        assert specs.gpu is not None and specs.gpu.model == "h100"
        assert not report.critical

    def test_docker_down_is_critical_when_required(self, tmp_path):
        docker = fake_bin(tmp_path, "docker", "exit 1")
        specs, report = run_all_checks(
            "/",
            nvidia_smi="/nonexistent",
            docker_bin=docker,
            require_docker=True,
            probe_accelerator=False,
        )
        assert report.critical

    def test_port_conflict_is_critical(self, tmp_path):
        docker = fake_bin(tmp_path, "docker", "echo '{}'")
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("0.0.0.0", 0))
        s.listen(1)
        port = s.getsockname()[1]
        try:
            specs, report = run_all_checks(
                "/",
                port=port,
                nvidia_smi="/nonexistent",
                docker_bin=docker,
                probe_accelerator=False,
            )
            assert any("port" in i.message for i in report.critical)
        finally:
            s.close()

    def test_missing_nvidia_runtime_warns_with_gpu(self, tmp_path):
        smi = fake_bin(tmp_path, "nvidia-smi", 'printf "0, H100, 80000\\n"')
        docker = fake_bin(tmp_path, "docker", "echo '{}'")
        specs, report = run_all_checks(
            "/", nvidia_smi=smi, docker_bin=docker, probe_accelerator=False
        )
        assert any("NVIDIA runtime" in i.message for i in report.issues)


class TestInterconnect:
    def test_probe_via_local_server(self):
        import http.server
        import threading

        from protocol_tpu.services.checks import interconnect_check

        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                payload = b"x" * (1 << 20)
                self.send_response(200)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *a):
                pass

        srv = http.server.HTTPServer(("127.0.0.1", 0), H)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            mbps = interconnect_check(
                f"http://127.0.0.1:{srv.server_port}/file"
            )
            assert mbps is not None and mbps > 0
        finally:
            srv.shutdown()

    def test_no_url_skips(self):
        from protocol_tpu.services.checks import interconnect_check

        assert interconnect_check(None) is None


class TestFixedF64:
    """Deterministic challenge wire format (hardware_challenge.rs:8-54):
    encode/decode must be exact after one quantization, and values must
    survive a JSON round-trip bit-for-bit."""

    def test_roundtrip_exact_after_quantization(self):
        import numpy as np

        from protocol_tpu.utils import fixedf64

        rng = np.random.default_rng(0)
        x = rng.standard_normal((16, 16))
        q = fixedf64.roundtrip(x)
        # quantization error bounded by half an lsb of Q31.32
        assert np.abs(q - x).max() <= 0.5 / (1 << 32)
        # re-encoding quantized values is EXACT (the validator quantizes
        # before computing, so both sides hold identical float64s)
        np.testing.assert_array_equal(fixedf64.roundtrip(q), q)

    def test_json_wire_is_bit_exact(self):
        import json

        import numpy as np

        from protocol_tpu.utils import fixedf64

        rng = np.random.default_rng(1)
        x = fixedf64.roundtrip(rng.standard_normal((8, 8)))
        wire = json.loads(json.dumps(fixedf64.encode_array(x)))
        np.testing.assert_array_equal(fixedf64.decode_array(wire), x)

    def test_large_values_do_not_wrap(self):
        import numpy as np

        from protocol_tpu.utils import fixedf64

        x = np.asarray([[1e12, -1e12]])
        got = fixedf64.roundtrip(x)
        np.testing.assert_allclose(got, x, rtol=0, atol=0.5 / (1 << 32))
