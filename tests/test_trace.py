"""Flight recorder (protocol_tpu/trace/): format round-trip, truncated
tails, deterministic synth, replay bit-identity across engines, threads
and transports, divergence localization, seam capture hooks, CLI smoke.

The acceptance bar this file proves at test scale (CI proves it on the
committed golden trace): replaying a recorded trace through native-mt at
threads {1, 2, 4} and through the v2 wire loopback reproduces the
recorded assignments bit-for-bit, and a synthetic trace recorded then
replayed round-trips identically.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from protocol_tpu import native
from protocol_tpu.trace import format as tfmt
from protocol_tpu.trace.replay import compare, iter_input_ticks, replay
from protocol_tpu.trace.synth import (
    synth_trace,
    synth_uniform_candidates,
)

NATIVE = native.available()
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _synth(tmp_path, name="in.trace", **kw):
    kw.setdefault("n_providers", 128)
    kw.setdefault("n_tasks", 128)
    kw.setdefault("ticks", 4)
    kw.setdefault("churn", 0.03)
    kw.setdefault("seed", 3)
    return synth_trace(str(tmp_path / name), **kw)


# ---------------- format ----------------


def test_format_roundtrip(tmp_path):
    path = _synth(tmp_path, task_churn=0.02, hotspot_every=2)
    t = tfmt.read_trace(path)
    assert not t.truncated
    assert t.meta["version"] == tfmt.VERSION
    assert t.snapshot is not None
    assert t.snapshot.n_providers == 128 and t.snapshot.n_tasks == 128
    assert t.snapshot.kernel == "native-mt"
    assert len(t.deltas) == 4 and t.ticks == 5
    # delta frames carry exactly the churned rows + their column values
    for d in t.deltas:
        for rows, cols, spec in (
            (d.provider_rows, d.p_cols, tfmt.P_TRACE_DTYPES),
            (d.task_rows, d.r_cols, tfmt.R_TRACE_DTYPES),
        ):
            if rows.size:
                assert set(cols) == set(spec)
                for name, dt in spec.items():
                    assert cols[name].dtype == dt
                    assert cols[name].shape[0] == rows.size
    # events ride the delta frames
    kinds = {e["kind"] for d in t.deltas for e in d.events}
    assert "heartbeat_drift" in kinds
    assert "hotspot_burst" in kinds
    assert "task_churn" in kinds


def test_outcome_roundtrip(tmp_path):
    path = str(tmp_path / "o.trace")
    p4t = np.array([2, -1, 0, 5], np.int32)
    price = np.array([0.5, 1.5, 0.0], np.float32)
    with tfmt.TraceWriter(path, meta={"who": "test"}) as w:
        w.write_outcome(0, p4t, price=price,
                        metrics={"solve_ms": 1.5, "bytes_in": 42})
    t = tfmt.read_trace(path)
    assert len(t.outcomes) == 1
    o = t.outcomes[0]
    assert o.tick == 0 and o.num_assigned == 3
    np.testing.assert_array_equal(o.provider_for_task, p4t)
    np.testing.assert_array_equal(o.price, price)
    assert o.metrics == {"solve_ms": 1.5, "bytes_in": 42}
    assert t.meta["who"] == "test"


def test_truncated_tail_recovery(tmp_path):
    path = _synth(tmp_path)
    data = open(path, "rb").read()
    full = tfmt.read_trace(path)
    assert not full.truncated
    # chop at several byte offsets: every prefix parses without raising,
    # yields a (possibly shorter) valid tick sequence, flags the tear
    for cut in (len(data) - 3, len(data) - 40, len(data) // 2,
                len(tfmt.MAGIC) + 5):
        p = str(tmp_path / f"cut{cut}.trace")
        with open(p, "wb") as fh:
            fh.write(data[:cut])
        t = tfmt.read_trace(p)
        assert t.truncated
        assert t.ticks <= full.ticks
    # a cut exactly on a frame boundary is a CLEAN (untruncated) prefix
    hdr = len(tfmt.MAGIC)
    import struct

    kind_len = struct.Struct("<BBII")
    off = hdr
    boundaries = []
    while off < len(data):
        _k, _f, ln, _c = kind_len.unpack_from(data, off)
        off += kind_len.size + ln
        boundaries.append(off)
    p = str(tmp_path / "clean_prefix.trace")
    with open(p, "wb") as fh:
        fh.write(data[:boundaries[1]])
    assert not tfmt.read_trace(p).truncated


def test_corrupt_payload_stops_cleanly(tmp_path):
    path = _synth(tmp_path)
    data = bytearray(open(path, "rb").read())
    data[-10] ^= 0xFF  # flip a byte inside the final frame's payload
    p = str(tmp_path / "corrupt.trace")
    open(p, "wb").write(bytes(data))
    t = tfmt.read_trace(p)  # CRC mismatch -> torn tail, not an exception
    assert t.truncated


def test_synth_is_deterministic(tmp_path):
    a = _synth(tmp_path, name="a.trace", seed=11)
    b = _synth(tmp_path, name="b.trace", seed=11)
    assert open(a, "rb").read() == open(b, "rb").read()
    c = _synth(tmp_path, name="c.trace", seed=12)
    assert open(a, "rb").read() != open(c, "rb").read()


def test_synth_lifecycle_knobs(tmp_path):
    path = _synth(
        tmp_path, ticks=6, headroom=0.25, growth=0.2,
        disconnect_at=3, disconnect_frac=0.5, reconnect_after=2,
    )
    t = tfmt.read_trace(path)
    kinds = [e["kind"] for d in t.deltas for e in d.events]
    assert "node_join" in kinds
    assert "mass_disconnect" in kinds
    assert "mass_reconnect" in kinds
    # validity lifecycle is real column churn: replaying the tick stream
    # shows the live count dip at the disconnect and recover after
    live = [
        int(p_cols["valid"].sum())
        for _tick, p_cols, _r, _d in iter_input_ticks(t)
    ]
    assert live[3] < live[2]  # the mass disconnect
    assert live[5] > live[3]  # the reconnect


def test_uniform_candidates_shape():
    cand_p, cand_c = synth_uniform_candidates(
        np.random.default_rng(0), 64, 128, k=8
    )
    assert cand_p.shape == (64, 8) and cand_p.dtype == np.int32
    assert cand_c.shape == (64, 8) and cand_c.dtype == np.float32
    assert cand_p.min() >= 0 and cand_p.max() < 128


# ---------------- replay ----------------


@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
class TestReplay:
    def _golden(self, tmp_path, engine="native-mt", **synth_kw):
        src = _synth(tmp_path, **synth_kw)
        golden = str(tmp_path / "golden.trace")
        rep = replay(src, engine=engine, threads=1, record_path=golden)
        assert rep["divergence"] is None  # no outcomes yet: vacuous
        return golden

    def test_record_then_replay_roundtrips(self, tmp_path):
        golden = self._golden(tmp_path)
        rep = replay(golden, engine="native-mt", threads=1)
        assert rep["verified_ticks"] == rep["ticks"] == 5
        assert rep["divergence"] is None

    def test_thread_invariance_1_2_4(self, tmp_path):
        golden = self._golden(tmp_path)
        for threads in (1, 2, 4):
            rep = replay(golden, engine="native-mt", threads=threads)
            assert rep["divergence"] is None, (threads, rep["divergence"])
            assert rep["verified_ticks"] == 5

    def test_sinkhorn_engine_roundtrips(self, tmp_path):
        golden = self._golden(
            tmp_path, engine="sinkhorn-mt", kernel="sinkhorn-mt"
        )
        for threads in (1, 2):
            rep = replay(golden, engine="sinkhorn-mt", threads=threads)
            assert rep["divergence"] is None
            assert rep["verified_ticks"] == 5

    def test_wire_v2_loopback_bit_identity(self, tmp_path):
        golden = self._golden(tmp_path)
        rep = replay(golden, engine="native-mt", threads=2,
                     transport="wire-v2")
        assert rep["divergence"] is None
        assert rep["verified_ticks"] == 5
        assert rep["wire_bytes_out"] > 0

    def test_wire_v1_loopback_bit_identity(self, tmp_path):
        golden = self._golden(tmp_path)
        rep = replay(golden, engine="native-mt", threads=1,
                     transport="wire-v1")
        assert rep["divergence"] is None
        assert rep["verified_ticks"] == 5

    def test_divergence_localization(self, tmp_path):
        """A perturbed recorded outcome must localize to exactly the
        perturbed tick and row set."""
        golden = self._golden(tmp_path)
        t = tfmt.read_trace(golden)
        perturbed = str(tmp_path / "perturbed.trace")
        rows_hit = [3, 7, 11]
        with tfmt.TraceWriter(perturbed, meta={}) as w:
            w.write_snapshot(
                t.snapshot.trace_id, t.snapshot.fingerprint,
                t.snapshot.request_v2(),
            )
            for tick in range(t.ticks):
                o = t.outcome_for(tick)
                p4t = o.provider_for_task.copy()
                if tick == 2:
                    p4t[rows_hit] = -7  # a value no solve produces
                if tick > 0:
                    d = t.deltas[tick - 1]
                    w.write_delta_cols(
                        tick, d.provider_rows, d.p_cols, d.task_rows,
                        d.r_cols, events=d.events,
                    )
                w.write_outcome(tick, p4t, price=o.price,
                                metrics=o.metrics)
        rep = replay(perturbed, engine="native-mt", threads=1)
        assert rep["divergence"] is not None
        assert rep["divergence"]["tick"] == 2
        assert rep["divergence"]["rows"] == rows_hit
        assert rep["divergence"]["n_rows"] == len(rows_hit)
        # localization stops at the first divergent tick
        assert rep["ticks"] == 3

    def test_non_replayable_recorded_kernel_refused_with_direction(
        self, tmp_path
    ):
        """A trace captured from a kernel with no replay engine (the jax
        unary "auction") must refuse with direction, not a parse crash —
        and must replay when an explicit engine is passed."""
        src = _synth(tmp_path, kernel="auction")
        with pytest.raises(ValueError, match="pass engine="):
            replay(src)
        rep = replay(src, engine="native-mt", threads=1)
        assert rep["ticks"] == 5  # explicit engine: replays (unverified)

    def test_compare_ab(self, tmp_path):
        golden = self._golden(tmp_path)
        c = compare(
            golden,
            {"engine": "native-mt", "threads": 1, "transport": "inproc"},
            {"engine": "native-mt", "threads": 4, "transport": "inproc"},
        )
        # the -mt determinism contract, through the A/B harness
        assert c["identical"] is True
        assert c["first_divergent_tick"] is None
        cx = compare(
            golden,
            {"engine": "native-mt", "threads": 1, "transport": "inproc"},
            {"engine": "sinkhorn-mt", "threads": 1, "transport": "inproc"},
            max_ticks=2,
        )
        assert "warm_speedup_b_over_a" not in cx or cx["identical"] in (
            True, False,
        )  # cross-engine: report exists either way
        assert cx["a"]["engine"] == "native-mt"
        assert cx["b"]["engine"] == "sinkhorn-mt"

    @pytest.mark.slow
    def test_16k_tick_roundtrip(self, tmp_path):
        """The acceptance-criteria scale point: a synthetic 16k-tick
        trace recorded then replayed round-trips identically."""
        src = synth_trace(
            str(tmp_path / "long.trace"), n_providers=64, n_tasks=64,
            ticks=16384, churn=0.05, seed=5,
        )
        golden = str(tmp_path / "long_golden.trace")
        replay(src, engine="native-mt", threads=2, record_path=golden)
        rep = replay(golden, engine="native-mt", threads=1)
        assert rep["divergence"] is None
        assert rep["verified_ticks"] == 16385


# ---------------- capture hooks ----------------


@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
class TestCapture:
    def test_matcher_capture_replays(self, tmp_path, monkeypatch):
        """PROTOCOL_TPU_TRACE on a live TpuBatchMatcher captures the
        native-arena solves; the captured trace replays bit-for-bit."""
        import random

        from protocol_tpu.models.task import (
            SchedulingConfig,
            Task,
            TaskRequest,
        )
        from protocol_tpu.sched.tpu_backend import TpuBatchMatcher
        from protocol_tpu.store import (
            NodeStatus,
            OrchestratorNode,
            StoreContext,
        )
        from tests.test_encoding import random_specs

        path = str(tmp_path / "matcher.trace")
        monkeypatch.setenv("PROTOCOL_TPU_TRACE", path)
        rng = random.Random(5)
        store = StoreContext.new_test()
        for i in range(12):
            store.node_store.add_node(
                OrchestratorNode(
                    address=f"0xtr{i:02d}",
                    status=NodeStatus.HEALTHY,
                    compute_specs=random_specs(rng),
                )
            )
        store.task_store.add_task(
            Task.from_request(
                TaskRequest(
                    name="tr-b", image="img",
                    scheduling_config=SchedulingConfig(
                        plugins={"tpu_scheduler": {"replicas": ["4"]}}
                    ),
                )
            )
        )
        m = TpuBatchMatcher(
            store, min_solve_interval=0.0, native_fallback=True,
            native_engine="native-mt", native_threads=2,
        )
        assert m.trace_recorder is not None
        m.refresh()
        # churn one node's price and solve again -> a delta frame
        node = store.node_store.get_nodes()[0]
        node.price = 9.75
        m.mark_dirty()
        m.refresh()
        m.trace_recorder.close()
        t = tfmt.read_trace(path)
        assert t.snapshot is not None
        assert t.snapshot.kernel == "native-mt:2"
        assert t.ticks == 2 and len(t.outcomes) == 2
        assert t.outcomes[1].metrics.get("arena_cold") is False
        rep = replay(path, engine="native-mt", threads=1)
        assert rep["divergence"] is None
        assert rep["verified_ticks"] == 2

    def test_session_capture_replays(self, tmp_path, monkeypatch):
        """The session-protocol capture path (OpenSession snapshot +
        SessionStore delta application) yields a replayable trace with
        SeamMetrics-derived per-tick provenance."""
        import bench
        from protocol_tpu.ops.cost import CostWeights
        from protocol_tpu.proto import scheduler_pb2 as pb
        from protocol_tpu.proto import wire
        from protocol_tpu.services.scheduler_grpc import (
            SchedulerBackendClient,
            serve,
        )

        path = str(tmp_path / "session.trace")
        monkeypatch.setenv("PROTOCOL_TPU_TRACE", path)
        server = serve("127.0.0.1:50978")
        client = SchedulerBackendClient("127.0.0.1:50978")
        try:
            rng = np.random.default_rng(0)
            ep = bench.synth_providers(rng, 96)
            er = bench.synth_requirements(rng, 96)
            w = CostWeights()
            p_cols = wire.canon_columns(ep, wire.P_WIRE_DTYPES)
            r_cols = wire.canon_columns(er, wire.R_WIRE_DTYPES)
            fp = wire.epoch_fingerprint(
                p_cols, r_cols, w, "native-mt:1", 32, 0.02, 0
            )
            req = pb.AssignRequestV2(
                providers=wire.encode_providers_v2(ep),
                requirements=wire.encode_requirements_v2(er),
                weights=pb.CostWeights(
                    price=w.price, load=w.load, proximity=w.proximity,
                    priority=w.priority,
                ),
                kernel="native-mt:1", top_k=32, eps=0.02,
            )
            resp = client.open_session(
                wire.chunk_snapshot("cap", fp, req)
            )
            assert resp.ok, resp.error
            churn = np.random.default_rng(1)
            for tick in range(1, 4):
                rows = np.sort(
                    churn.choice(96, 3, replace=False).astype(np.int32)
                )
                price = p_cols["price"].copy()
                price[rows] = churn.uniform(
                    0.5, 4.0, rows.size
                ).astype(np.float32)
                p_cols["price"] = price
                d = pb.AssignDeltaRequest(
                    session_id="cap", epoch_fingerprint=fp, tick=tick
                )
                d.provider_rows.CopyFrom(wire.blob(rows, np.int32))
                d.providers.CopyFrom(
                    wire.encode_providers_v2(wire.take_rows(p_cols, rows))
                )
                dr = client.assign_delta(d)
                assert dr.session_ok, dr.error
        finally:
            client.close()
            server.stop(grace=None)
        t = tfmt.read_trace(path)
        assert t.ticks == 4 and len(t.outcomes) == 4
        # outcome frames carry the seam's per-tick provenance
        assert t.outcomes[1].metrics["wire"] == "v2-session"
        assert t.outcomes[1].metrics["bytes_in"] > 0
        assert t.outcomes[1].metrics["solve_ms"] >= 0
        # delta frames hold the exact wire rows the session applied
        np.testing.assert_array_equal(
            t.deltas[0].provider_rows,
            np.sort(t.deltas[0].provider_rows),
        )
        rep = replay(path, transport="inproc")
        assert rep["divergence"] is None
        assert rep["verified_ticks"] == 4
        rep = replay(path, transport="wire-v2")
        assert rep["divergence"] is None


# ---------------- CLI ----------------


def test_cli_smoke(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    path = str(tmp_path / "cli.trace")
    out = subprocess.run(
        [sys.executable, "-m", "protocol_tpu.trace", "synth", path,
         "--providers", "64", "--tasks", "64", "--ticks", "2",
         "--churn", "0.05"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300,
    )
    assert out.returncode == 0, out.stderr
    info = json.loads(out.stdout)
    assert info["providers"] == 64 and info["ticks"] == 3
    if not NATIVE:
        pytest.skip("no native toolchain for the replay half")
    golden = str(tmp_path / "cli_golden.trace")
    out = subprocess.run(
        [sys.executable, "-m", "protocol_tpu.trace", "record", path,
         "--engine", "native-mt", "--threads", "1", "--out", golden],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300,
    )
    assert out.returncode == 0, out.stderr
    out = subprocess.run(
        [sys.executable, "-m", "protocol_tpu.trace", "replay", golden,
         "--engine", "native-mt", "--threads", "2"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300,
    )
    assert out.returncode == 0, out.stderr
    rep = json.loads(out.stdout)
    assert rep["divergence"] is None and rep["verified_ticks"] == 3
