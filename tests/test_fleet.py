"""Multi-tenant fleet layer: sharded session fabric, arena memory
budget + cross-shard eviction pressure, token-bucket admission,
weighted-fair thread budget, delta backpressure, the deterministic TTL
sweep hook, the jittered client backoff, and the adversarial
multi-tenant race suite (concurrent OpenSession vs fleet-pressure
eviction vs in-flight AssignDelta across >= 2 shards: the PR 3 "session
evicted" refusal contract must hold and no solve may run against a
disowned arena).
"""

import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from protocol_tpu import native
from protocol_tpu.fleet import (
    FairThreadBudget,
    FleetConfig,
    SessionFabric,
    TenantAdmission,
    TokenBucket,
    estimate_arena_bytes,
)
from protocol_tpu.fleet.loadgen import jain_index, run_load
from protocol_tpu.obs.metrics import ObsRegistry
from protocol_tpu.services.session_store import SessionStore, SolveSession

NATIVE = native.available()


def mk(sid, nbytes=1000, **kw):
    return SolveSession(
        session_id=sid, fingerprint="fp", weights=None,
        kernel="native-mt", threads=1, top_k=16, p_cols={}, r_cols={},
        n_providers=0, n_tasks=0, arena=None, arena_bytes=nbytes, **kw,
    )


# ---------------------------------------------------------------- fabric


class TestShardMap:
    def test_deterministic_and_spread(self):
        f = SessionFabric(shards=4, max_sessions=256)
        ids = [f"ten{i % 7}@s{i}" for i in range(512)]
        first = [f.shard_index(s) for s in ids]
        assert first == [f.shard_index(s) for s in ids]  # stable
        counts = np.bincount(first, minlength=4)
        assert counts.min() > 0.1 * len(ids) / 4  # no empty/starved shard

    def test_single_shard_is_a_plain_store(self):
        f = SessionFabric(shards=1, max_sessions=2)
        a, b, c = mk("a"), mk("b"), mk("c")
        f.put(a)
        f.put(b)
        f.put(c)
        assert len(f) == 2 and a.evicted and not c.evicted

    def test_store_api_surface(self):
        f = SessionFabric(shards=3, max_sessions=8)
        s = mk("ten@x")
        f.put(s)
        got, reason = f.get("ten@x", "fp")
        assert got is s and reason == ""
        none, reason = f.get("ten@x", "other-fp")
        assert none is None and "fingerprint" in reason
        f.drop("ten@x")
        assert len(f) == 0 and s.evicted

    def test_global_lru_count_pressure_is_cross_shard(self):
        """The fleet-wide max_sessions cap must evict the globally
        least-recently-used session no matter which shard holds it —
        single-store LRU semantics preserved at any shard count."""
        f = SessionFabric(shards=4, max_sessions=3)
        sessions = [mk(f"s{i}") for i in range(4)]
        for s in sessions[:3]:
            f.put(s)
        # touch s0 so s1 becomes the global LRU
        f.get("s0", "fp")
        f.put(sessions[3])
        assert len(f) == 3
        assert sessions[1].evicted
        assert not sessions[0].evicted and not sessions[3].evicted


class TestArenaBudget:
    def test_accounting_rollup_and_release(self):
        f = SessionFabric(shards=2, max_sessions=64)
        f.put(mk("a@1", nbytes=1000))
        f.put(mk("a@2", nbytes=500))
        f.put(mk("b@1", nbytes=2000))
        assert f.total_bytes == 3500
        assert f.tenant_bytes("a") == 1500
        assert f.tenant_bytes("b") == 2000
        f.drop("a@1")
        assert f.total_bytes == 2500 and f.tenant_bytes("a") == 500
        f.drop("a@2")
        assert f.tenant_bytes("a") == 0
        # zeroed tenant keys are pruned (uuid "tenants" would otherwise
        # grow the dict by one per client ever connected), and a
        # client-initiated drop is NOT an eviction
        snap = f.snapshot()
        assert "a" not in snap["tenant_bytes"]
        assert snap["evictions_by_tenant"] == {}

    def test_fleet_budget_pressure_evicts_global_lru(self):
        f = SessionFabric(shards=2, max_sessions=64, max_bytes=2500)
        first = mk("a@1", nbytes=1000)
        f.put(first)
        f.put(mk("a@2", nbytes=1000))
        assert f.total_bytes == 2000
        newest = mk("b@1", nbytes=1000)
        f.put(newest)  # 3000 > 2500: pressure evicts the global LRU
        assert f.total_bytes == 2000
        assert first.evicted  # oldest anywhere, regardless of shard
        assert not newest.evicted  # the session whose open triggered it
        snap = f.snapshot()
        assert snap["pressure_evictions"] == 1
        assert snap["evictions_by_tenant"] == {"a": 1}

    def test_tenant_budget_pressure_targets_that_tenant(self):
        f = SessionFabric(
            shards=2, max_sessions=64, tenant_max_bytes=1500
        )
        a1, b1 = mk("a@1", nbytes=1000), mk("b@1", nbytes=1000)
        f.put(a1)
        f.put(b1)
        a2 = mk("a@2", nbytes=1000)
        f.put(a2)  # tenant a at 2000 > 1500
        assert a1.evicted  # a's LRU, not b's
        assert not b1.evicted and not a2.evicted
        assert f.tenant_bytes("a") == 1000

    def test_estimate_tracks_rows_and_dtype_widths(self):
        from protocol_tpu.proto.wire import P_WIRE_DTYPES, R_WIRE_DTYPES

        def cols(spec, n):
            return {
                name: np.zeros(n, dt) for name, dt in spec.items()
            }

        small = estimate_arena_bytes(
            cols(P_WIRE_DTYPES, 64), cols(R_WIRE_DTYPES, 64), 16
        )
        big = estimate_arena_bytes(
            cols(P_WIRE_DTYPES, 1024), cols(R_WIRE_DTYPES, 1024), 16
        )
        assert small > 0 and big == small * 16  # linear in rows


class TestSweepHook:
    """Satellite regression: TTL eviction used to run only on access
    paths (put/get), so an idle expired session pinned its arena bytes
    until unrelated traffic happened to touch its shard. The fleet
    layer's deterministic sweep() releases it with no access at all."""

    def test_store_sweep_releases_without_access(self):
        released = []
        store = SessionStore(
            max_sessions=8, ttl_s=900.0,
            on_evict=lambda s, reason: released.append(
                (s.session_id, reason)
            ),
        )
        s = mk("idle")
        store.put(s)
        s.last_used -= 10_000.0  # idle past the TTL
        # NO put/get: the sweep alone must release it
        assert store.sweep() == 1
        assert s.evicted and len(store) == 0
        assert released[-1] == ("idle", "ttl")
        assert store.expirations == 1
        assert store.sweep() == 0  # idempotent

    def test_fabric_sweep_releases_arena_bytes(self):
        f = SessionFabric(shards=4, max_sessions=64)
        live, idle = mk("live@1", nbytes=700), mk("idle@1", nbytes=900)
        f.put(live)
        f.put(idle)
        idle.last_used -= 10_000.0
        assert f.total_bytes == 1600
        assert f.sweep() == 1
        assert idle.evicted and not live.evicted
        assert f.total_bytes == 700  # the bytes came back immediately
        assert f.tenant_bytes("idle") == 0


# ------------------------------------------------------------- admission


class TestTokenBucketAdmission:
    def test_bucket_refills_at_rate(self):
        now = [0.0]
        b = TokenBucket(rate=2.0, burst=2.0, clock=lambda: now[0])
        assert b.try_take() and b.try_take()
        assert not b.try_take()  # burst drained
        now[0] += 0.5  # refills 1 token
        assert b.try_take()
        assert not b.try_take()

    def test_admission_per_tenant_isolation_and_counters(self):
        now = [0.0]
        adm = TenantAdmission(rate=1.0, burst=1.0, clock=lambda: now[0])
        assert adm.admit("a")
        assert not adm.admit("a")  # a drained its bucket
        assert adm.admit("b")  # b unaffected
        snap = adm.snapshot()["tenants"]
        assert snap["a"] == {"admitted": 1, "refused": 1}
        assert snap["b"] == {"admitted": 1, "refused": 0}

    def test_rate_none_admits_everything(self):
        adm = TenantAdmission(rate=None)
        assert all(adm.admit("t") for _ in range(100))
        assert adm.snapshot()["tenants"]["t"]["refused"] == 0

    def test_registry_is_lru_bounded(self):
        """Tenant keys derive from client-minted session ids (a bare
        uuid's tenant is the whole uuid), so the registry must be
        bounded or a long-running server leaks one entry per session
        ever seen — and the per-tenant /metrics cardinality with it."""
        adm = TenantAdmission(rate=None, max_tenants=64)
        for i in range(500):
            adm.admit(f"uuid-{i:04d}")
        assert len(adm.snapshot()["tenants"]) == 64


class TestFairThreadBudget:
    def test_sole_tenant_matches_base_budget(self):
        b = FairThreadBudget(total=4)
        g1 = b.acquire(0, "a")  # "all threads"
        assert g1 == 4 and b.available == 0
        g2 = b.acquire(0, "a")  # drained: floor grant, NO blocking
        assert g2 == 1 and b.available == -1
        b.release(g1, "a")
        b.release(g2, "a")
        assert b.available == 4

    def test_contention_caps_at_weighted_share(self):
        b = FairThreadBudget(total=8)
        ga = b.acquire(0, "a")  # sole tenant: all 8
        assert ga == 8
        b.release(ga, "a")
        ga = b.acquire(4, "a")
        gb = b.acquire(0, "b")  # a holds 4: b capped at ceil(8/2)=4
        assert gb == 4
        gc = b.acquire(0, "c")  # three active: share ceil(8/3)=3 but
        assert gc == 1          # the pool is drained -> floor
        for g, t in ((ga, "a"), (gb, "b"), (gc, "c")):
            b.release(g, t)
        assert b.available == 8

    def test_heavy_tenant_cannot_take_the_whole_pool_under_contention(self):
        b = FairThreadBudget(total=8)
        ga = b.acquire(2, "light")
        gb = b.acquire(0, "heavy")  # wants all 8; capped at its share
        assert gb <= 4  # ceil(8/2) = 4, never the remaining 6
        b.release(ga, "light")
        b.release(gb, "heavy")

    def test_weights_shift_the_share(self):
        b = FairThreadBudget(total=8, weights={"gold": 3.0})
        g1 = b.acquire(1, "bronze")
        g2 = b.acquire(0, "gold")  # share = ceil(8 * 3/4) = 6
        assert g2 == 6
        b.release(g1, "bronze")
        b.release(g2, "gold")

    def test_fairness_index_range(self):
        b = FairThreadBudget(total=4)
        assert b.fairness_index() == 1.0  # vacuous
        for t in ("a", "b"):
            g = b.acquire(2, t)
            b.release(g, t)
        assert b.fairness_index() == 1.0  # even service
        for _ in range(8):
            g = b.acquire(2, "a")
            b.release(g, "a")
        assert 0.0 < b.fairness_index() < 1.0  # skewed service shows

    def test_books_are_lru_bounded_but_holders_survive(self):
        b = FairThreadBudget(total=4, max_tenants=16)
        held = b.acquire(1, "holder")
        for i in range(200):
            g = b.acquire(1, f"uuid-{i:04d}")
            b.release(g, f"uuid-{i:04d}")
        snap = b.tenant_snapshot()
        assert len(snap) <= 17  # 16 idle + the holder
        assert "holder" in snap  # a tenant holding threads never pruned
        b.release(held, "holder")
        assert b.available == 4

    def test_jain_index_helper(self):
        assert jain_index([1, 1, 1, 1]) == 1.0
        assert jain_index([]) == 1.0
        assert jain_index([0, 0, 0]) == 1.0  # vacuous: no demand at all
        assert jain_index([4, 0.0001, 0.0001, 0.0001]) < 0.3
        # a fully-starved participant MUST drag the index down — the
        # starvation signal the fleet gate floors on
        assert jain_index([1, 1, 1, 0]) == 0.75


# ---------------------------------------------------- backpressure (unit)


class TestDeltaBackpressure:
    def test_enter_tick_bounds_depth(self):
        s = mk("x")
        assert s.enter_tick(2) and s.enter_tick(2)
        assert not s.enter_tick(2)  # over depth: refuse
        s.exit_tick()
        assert s.enter_tick(2)  # slot freed

    def test_zero_depth_disables(self):
        s = mk("x")
        assert all(s.enter_tick(0) for _ in range(64))


# ------------------------------------------------------- client backoff


class TestBackoffJitter:
    """Satellite: bounded exponential backoff with deterministic jitter
    — H reconnecting clients must not thundering-herd a restarted
    servicer in lockstep, and the schedule must be replayable."""

    @staticmethod
    def _backoff(uid, base=0.05, cap=2.0):
        from protocol_tpu.services.scheduler_grpc import RemoteBatchMatcher

        fake = SimpleNamespace(
            retry_base_s=base, retry_max_s=cap, _session_uid=uid
        )
        return [
            RemoteBatchMatcher._backoff_s(fake, a) for a in range(8)
        ]

    def test_deterministic_per_client(self):
        assert self._backoff("client-1") == self._backoff("client-1")

    def test_clients_desynchronize(self):
        a, b = self._backoff("client-1"), self._backoff("client-2")
        assert a != b  # different jitter schedules

    def test_bounded_and_growing(self):
        seq = self._backoff("client-3", base=0.05, cap=2.0)
        assert all(0.025 <= d <= 2.0 for d in seq)  # [0.5x base, cap]
        # exponential envelope: late delays sit at the cap's magnitude
        assert max(seq[4:]) > max(seq[:2])


# ----------------------------------------------------- obs aggregation


class TestObsTenantAggregation:
    def test_tenant_rollup_merges_sessions(self):
        reg = ObsRegistry(role="test")
        for sid, ms in (("a@1", 10), ("a@2", 30), ("b@1", 20)):
            reg.observe_tick(sid, ms, 100, 97, cold=False)
        snap = reg.snapshot()
        assert set(snap["tenants"]) == {"a", "b"}
        assert snap["tenants"]["a"]["tick"]["count"] == 2
        assert snap["tenants"]["b"]["tick"]["count"] == 1
        assert snap["sessions"]["a@1"]["tenant"] == "a"


# ------------------------------------------------- wire-level behavior


@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
class TestFleetOverWire:
    """gRPC-level fleet behavior: admission refusals, delta
    backpressure, and the adversarial multi-tenant race suite."""

    @pytest.fixture(autouse=True)
    def _lock_witness(self, monkeypatch):
        """Arm the runtime lock-order witness (ISSUE 10) for every
        wire-level fleet test: each lock the in-process server creates
        asserts the committed acquisition order live, and a test that
        completed its races with ANY recorded violation fails — the
        dynamic twin of scripts/analysis/lockorder.py, run under the
        adversarial interleavings this suite exists to produce."""
        from protocol_tpu.utils import lockwitness

        monkeypatch.setenv("PROTOCOL_TPU_LOCK_WITNESS", "1")
        lockwitness.reset()
        yield
        assert lockwitness.violations() == [], (
            "lock-order witness violations under the fleet race suite: "
            f"{lockwitness.violations()[:5]}"
        )

    def _serve(self, **fleet_kw):
        from protocol_tpu.services.scheduler_grpc import (
            SchedulerBackendClient,
            serve,
        )
        from protocol_tpu.fleet.loadgen import _free_port

        port = _free_port()
        addr = f"127.0.0.1:{port}"
        server = serve(
            addr,
            max_workers=8,
            max_sessions=fleet_kw.pop("max_sessions", 16),
            fleet=FleetConfig(**fleet_kw),
        )
        return server, SchedulerBackendClient(addr), addr

    @staticmethod
    def _open(client, sid, seed, kernel="native-mt:1"):
        from protocol_tpu.ops.cost import CostWeights
        from protocol_tpu.proto import wire
        from protocol_tpu.services.scheduler_grpc import (
            encoded_to_proto_v2,
        )
        from tests.test_sparse import encode_random_marketplace

        ep, er = encode_random_marketplace(seed, 96, 64)
        p_cols = wire.canon_columns(ep, wire.P_WIRE_DTYPES)
        r_cols = wire.canon_columns(er, wire.R_WIRE_DTYPES)
        w = CostWeights()
        fp = wire.epoch_fingerprint(
            p_cols, r_cols, w, kernel, 16, 0.02, 0
        )
        req = encoded_to_proto_v2(
            wire.take_rows(p_cols, slice(None)),
            wire.take_rows(r_cols, slice(None)),
            w, kernel=kernel, top_k=16, eps=0.02,
        )
        chunks = list(wire.chunk_snapshot(sid, fp, req))
        return client.open_session(iter(chunks)), p_cols, fp

    @staticmethod
    def _delta(client, sid, fp, tick, p_cols, rows, price):
        from protocol_tpu.proto import scheduler_pb2 as pb
        from protocol_tpu.proto import wire

        idx = np.asarray(rows, np.int32)
        p_cols["price"] = p_cols["price"].copy()
        p_cols["price"][idx] = np.float32(price)
        req = pb.AssignDeltaRequest(
            session_id=sid, epoch_fingerprint=fp, tick=tick
        )
        req.provider_rows.CopyFrom(wire.blob(idx, np.int32))
        req.providers.CopyFrom(
            wire.encode_providers_v2(wire.take_rows(p_cols, idx))
        )
        return client.assign_delta(req)

    def test_admission_refuses_with_resource_exhausted(self):
        server, client, _ = self._serve(
            shards=2, admit_rate=0.001, admit_burst=2.0
        )
        try:
            oks, refusals = 0, []
            for i in range(4):
                resp, _, _ = self._open(client, f"ten@s{i}", seed=40 + i)
                if resp.ok:
                    oks += 1
                else:
                    refusals.append(resp.error)
            # burst=2: two sessions admitted, the rest refused with the
            # RESOURCE_EXHAUSTED shape on the protocol surface
            assert oks == 2
            assert len(refusals) == 2
            assert all("RESOURCE_EXHAUSTED" in e for e in refusals)
            adm = server.servicer.admission.snapshot()["tenants"]["ten"]
            assert adm == {"admitted": 2, "refused": 2}
        finally:
            client.close()
            server.stop(grace=None)

    def test_delta_backpressure_refuses_over_depth(self):
        server, client, addr = self._serve(
            shards=2, delta_queue_depth=1
        )
        from protocol_tpu.services.scheduler_grpc import (
            SchedulerBackendClient,
        )

        try:
            resp, p_cols, fp = self._open(client, "bp@s0", seed=50)
            assert resp.ok
            session, _ = server.servicer.sessions.get("bp@s0", fp)
            # hold the session lock: the first delta parks on it
            # (inflight=1), the second must be REFUSED at the depth
            # check without ever touching the lock queue
            session.lock.acquire()
            results = []

            def tick(tick_no):
                c = SchedulerBackendClient(addr)
                try:
                    results.append(self._delta(
                        c, "bp@s0", fp, tick_no, dict(p_cols), [3], 2.5
                    ))
                finally:
                    c.close()

            t1 = threading.Thread(target=tick, args=(1,))
            t1.start()
            time.sleep(0.3)  # t1 is parked on the session lock
            t2 = threading.Thread(target=tick, args=(2,))
            t2.start()
            t2.join(timeout=30)
            assert len(results) == 1  # t2 finished while t1 is parked
            assert results[0].session_ok is False
            assert "RESOURCE_EXHAUSTED" in results[0].error
            session.lock.release()
            t1.join(timeout=30)
            assert len(results) == 2
            assert results[1].session_ok, results[1].error
            snap = server.servicer.seam.snapshot()
            assert snap.get("session_backpressure_refused", 0) >= 1
        finally:
            client.close()
            server.stop(grace=None)

    def test_throttled_delta_retries_instead_of_reopening(self):
        """The production client's ladder under admission throttle: a
        RESOURCE_EXHAUSTED delta refusal must be retried in place (the
        bucket refills), NOT amplified into a full snapshot re-open,
        and a throttled OpenSession must not permanently demote the
        client to the unthrottled unary rung."""
        from protocol_tpu.services.scheduler_grpc import (
            RemoteBatchMatcher,
        )
        from tests.test_wire_v2 import _pool_world

        class ScriptedAdmission:
            """Deterministic admit() outcomes, then always-admit."""

            def __init__(self, script):
                self.script = list(script)

            def admit(self, tenant):
                return self.script.pop(0) if self.script else True

            def snapshot(self):
                return {"rate": None, "burst": 0.0, "tenants": {}}

        server, client, addr = self._serve(shards=2)
        client.close()
        try:
            store = _pool_world()
            m = RemoteBatchMatcher(
                store, addr, min_solve_interval=0.0, wire="v2",
                native_fallback=True, native_engine="native-mt",
                native_threads=1, retry_base_s=0.01,
            )
            # open admitted, delta 1 admitted, delta 2 refused ONCE
            # then admitted on the client's in-place retry
            server.servicer.admission = ScriptedAdmission(
                [True, True, False, True]
            )
            m.refresh()
            assert m._session is not None and m._session["tick"] == 0
            m.refresh()
            assert m._session["tick"] == 1
            m.refresh()  # throttled once, retried, SAME session
            assert m._session["tick"] == 2, "retry must stay in-session"
            assert m.seam.snapshot().get(
                "session_throttled_retry", 0
            ) == 1
            assert m.seam.snapshot().get("session_session_reopen", 0) == 0
            assert m._session_refused is False
            m.client.close()

            # throttled OpenSession: this tick degrades to unary, but
            # the session protocol must stay available afterwards
            m2 = RemoteBatchMatcher(
                store, addr, min_solve_interval=0.0, wire="v2",
                native_fallback=True, native_engine="native-mt",
                native_threads=1,
            )
            server.servicer.admission = ScriptedAdmission([False])
            m2.refresh()  # open refused -> unary rung for THIS tick
            assert m2._session is None
            assert m2._session_refused is False  # NOT permanent
            assert m2.seam.snapshot().get(
                "session_session_throttled", 0
            ) == 1
            m2.refresh()  # bucket "refilled": back on the session rung
            assert m2._session is not None and m2._assignment
            m2.client.close()
        finally:
            server.stop(grace=None)

    def test_throttled_unary_rung_retries_instead_of_raising(self):
        """The degrade rung must not throw: a RESOURCE_EXHAUSTED abort
        on the unary path is the fleet's throttle answer, so the client
        backs off and retries in place (no reconnect) instead of
        erroring the whole scheduler tick."""
        from protocol_tpu.services.scheduler_grpc import (
            RemoteBatchMatcher,
        )
        from tests.test_wire_v2 import _pool_world

        class ScriptedAdmission:
            def __init__(self, script):
                self.script = list(script)

            def admit(self, tenant):
                return self.script.pop(0) if self.script else True

            def snapshot(self):
                return {"rate": None, "burst": 0.0, "tenants": {}}

        server, client, addr = self._serve(shards=2)
        client.close()
        try:
            store = _pool_world()
            m = RemoteBatchMatcher(
                store, addr, min_solve_interval=0.0, wire="v1",
                retry_base_s=0.01,
            )
            # first unary admission refused, retry admitted
            server.servicer.admission = ScriptedAdmission([False])
            m.refresh()  # must NOT raise
            assert m._assignment
            assert m.seam.snapshot().get(
                "session_throttled_retry", 0
            ) >= 1
            m.client.close()
        finally:
            server.stop(grace=None)

    def test_adversarial_races_across_shards(self):
        """Concurrent OpenSession vs fleet-pressure eviction vs
        in-flight AssignDelta across >= 2 shards. Contract: every
        refusal is one of the protocol's honest answers (the PR 3
        "session evicted" contract included), no solve runs against a
        disowned arena (an acked tick implies the session was live —
        asserted via the servicer's own evicted-in-flight counter
        accounting), threads never deadlock, and the byte accounting
        balances exactly against the live sessions at the end."""
        # max_bytes sized so ~2 of these ~52KB 96x64 sessions fit:
        # every open beyond that pressure-evicts the global LRU while
        # other threads are mid-delta on it
        server, client, addr = self._serve(
            shards=2, max_bytes=120_000, max_sessions=16
        )
        from protocol_tpu.services.scheduler_grpc import (
            SchedulerBackendClient,
        )

        client.close()
        known_refusals = (
            "session evicted", "unknown session",
            "epoch fingerprint mismatch", "tick cursor mismatch",
            "RESOURCE_EXHAUSTED",
        )
        errors: list = []
        completed: dict = {}

        def run(worker: int):
            c = SchedulerBackendClient(addr)
            sid = f"t{worker % 3}@w{worker}"
            try:
                resp, p_cols, fp = self._open(
                    c, sid, seed=60 + worker
                )
                if not resp.ok:
                    errors.append((sid, f"open: {resp.error}"))
                    return
                tick = 0
                done = 0
                for step in range(6):
                    resp2 = self._delta(
                        c, sid, fp, tick + 1, p_cols, [step], 1.5 + step
                    )
                    if resp2.session_ok:
                        tick += 1
                        done += 1
                        continue
                    if not any(
                        k in resp2.error for k in known_refusals
                    ):
                        errors.append((sid, f"delta: {resp2.error}"))
                        return
                    # the ladder: re-open from authoritative columns
                    resp, p_cols, fp = self._open(
                        c, sid, seed=60 + worker
                    )
                    if not resp.ok:
                        errors.append((sid, f"reopen: {resp.error}"))
                        return
                    tick = 0
                completed[sid] = done
            except Exception as e:
                errors.append((sid, f"{type(e).__name__}: {e}"))
            finally:
                c.close()

        threads = [
            threading.Thread(target=run, args=(i,)) for i in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert not any(t.is_alive() for t in threads), "deadlocked"
        try:
            assert not errors, errors
            assert len(completed) == 6
            fabric = server.servicer.sessions
            # byte accounting must balance exactly against the live
            # sessions once the dust settles (leaked accounting would
            # wedge the budget into permanent pressure)
            live_bytes = 0
            for shard in fabric.shards:
                with shard._lock:
                    live_bytes += sum(
                        s.arena_bytes for s in shard._sessions.values()
                    )
            assert fabric.total_bytes == live_bytes
            assert (
                server.servicer._engine_budget.available
                == server.servicer._engine_budget.total
            )
            snap = server.servicer.seam.snapshot()
            # the drill actually exercised eviction pressure
            assert fabric.snapshot()["pressure_evictions"] > 0
            # and any in-flight loser was refused, never solved: the
            # servicer counts exactly the races it refused
            assert snap.get("session_session_miss", 0) >= 0
        finally:
            server.stop(grace=None)


# ------------------------------------------------------------- loadgen


@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
class TestLoadgen:
    def test_small_concurrent_run_holds_quality(self):
        rep = run_load(
            sessions=4, tenants=2, providers=128, tasks=128, ticks=3,
            churn=0.02, shards=2, max_workers=4, check_endpoint=True,
        )
        assert rep["errors"] == []
        assert set(rep["tenants"]) == {"t0", "t1"}
        for t, a in rep["tenants"].items():
            assert a["min_assigned_frac"] >= 0.97, (t, a)
            assert a["ticks_done"] == 2 * 4  # (1 cold + 3 warm) x 2
        assert rep["fairness_index_sessions"] > 0.5
        assert rep["metrics_endpoint_ok"]
        # the server-side obs plane saw the same tenants
        assert set(rep["server_obs"]["tenants"]) >= {"t0", "t1"}
        assert rep["server_obs"]["fleet"]["sessions"] == 4
        assert rep["scaling"]["projected_warm_ticks_per_s"]["8"] > 0
