"""Scheduler-seam concurrency: sharded session locking (two delta
sessions must progress concurrently without serializing on any global
lock), the shared engine-thread budget, and the eviction-vs-in-flight-
delta race (a delta that loses the race to LRU/TTL eviction must be
REFUSED, never solved against an arena the store no longer owns).
"""

import threading

import numpy as np
import pytest

from protocol_tpu import native
from protocol_tpu.ops.cost import CostWeights
from protocol_tpu.proto import scheduler_pb2 as pb
from protocol_tpu.proto import wire
from protocol_tpu.services.scheduler_grpc import (
    SchedulerBackendClient,
    encoded_to_proto_v2,
    serve,
)
from protocol_tpu.services.session_store import EngineThreadBudget

from tests.test_sparse import encode_random_marketplace

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no native toolchain"
)

ADDR = "127.0.0.1:50078"


@pytest.fixture()
def backend():
    server = serve(address=ADDR)
    client = SchedulerBackendClient(ADDR)
    yield server.servicer, client
    client.close()
    server.stop(grace=None)


def _cols(seed, P=96, T=64):
    ep, er = encode_random_marketplace(seed, P, T)
    return (
        wire.canon_columns(ep, wire.P_WIRE_DTYPES),
        wire.canon_columns(er, wire.R_WIRE_DTYPES),
    )


def _open(client, p_cols, r_cols, session_id, kernel="native-mt:2",
          top_k=16):
    w = CostWeights()
    fp = wire.epoch_fingerprint(p_cols, r_cols, w, kernel, top_k, 0.02, 0)
    req = encoded_to_proto_v2(
        wire.take_rows(p_cols, slice(None)),
        wire.take_rows(r_cols, slice(None)),
        w, kernel=kernel, top_k=top_k, eps=0.02,
    )
    chunks = list(wire.chunk_snapshot(session_id, fp, req))
    resp = client.open_session(iter(chunks))
    assert resp.ok, resp.error
    return fp


def _delta(client, session_id, fp, tick, p_cols, rows):
    idx = np.asarray(rows, np.int32)
    dreq = pb.AssignDeltaRequest(
        session_id=session_id, epoch_fingerprint=fp, tick=tick
    )
    dreq.provider_rows.CopyFrom(wire.blob(idx, np.int32))
    dreq.providers.CopyFrom(
        wire.encode_providers_v2(wire.take_rows(p_cols, idx))
    )
    return client.assign_delta(dreq)


def _run_session_ticks(client, sid, seed, n_ticks=3, kernel="native-mt:2"):
    """Open a session and run ``n_ticks`` churn deltas; returns the
    per-tick matchings. The churn sequence is a pure function of
    ``seed``, so a serialized rerun reproduces the identical inputs."""
    p_cols, r_cols = _cols(seed)
    fp = _open(client, p_cols, r_cols, sid, kernel=kernel)
    rng = np.random.default_rng(seed + 100)
    results = []
    for tick in range(1, n_ticks + 1):
        rows = [int(tick), int(10 + tick)]
        p_cols["price"] = p_cols["price"].copy()
        p_cols["price"][rows] = rng.uniform(0.5, 4.0, 2).astype(np.float32)
        dresp = _delta(client, sid, fp, tick, p_cols, rows)
        assert dresp.session_ok, dresp.error
        results.append(
            wire.unblob(dresp.result.provider_for_task, np.int32)
        )
    return results


class TestConcurrentSessions:
    @pytest.mark.parametrize("kernel", ["native-mt:2", "sinkhorn-mt:2"])
    def test_two_sessions_progress_and_match_serialized(
        self, backend, kernel
    ):
        """Two delta sessions ticking CONCURRENTLY (separate threads,
        separate session locks, shared thread budget) must both make
        progress and produce tick-for-tick the same matchings as the
        same sequences run serially on a fresh server — per-session
        arena state is isolated, and the budget only changes who
        computes, never what (the engines' thread-invariance
        contract)."""
        servicer, client = backend
        out: dict = {}
        errs: list = []

        def run(sid, seed):
            try:
                # each thread gets its own channel: gRPC channels are
                # thread-safe, but separate channels remove any client-
                # side serialization from the measurement
                c = SchedulerBackendClient(ADDR)
                try:
                    out[sid] = _run_session_ticks(
                        c, sid, seed, n_ticks=3, kernel=kernel
                    )
                finally:
                    c.close()
            except Exception as e:  # surfaced below
                errs.append(e)

        threads = [
            threading.Thread(target=run, args=("s-a", 21)),
            threading.Thread(target=run, args=("s-b", 22)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errs, errs
        assert set(out) == {"s-a", "s-b"}
        assert all(len(v) == 3 for v in out.values())
        # the budget must be fully returned once the dust settles
        assert (
            servicer._engine_budget.available
            == servicer._engine_budget.total
        )

        # serialized reference on a fresh server: bit-identical ticks
        ref_server = serve(address="127.0.0.1:50079")
        ref_client = SchedulerBackendClient("127.0.0.1:50079")
        try:
            for sid, seed in (("s-a", 21), ("s-b", 22)):
                ref = _run_session_ticks(
                    ref_client, sid, seed, n_ticks=3, kernel=kernel
                )
                for got, want in zip(out[sid], ref):
                    np.testing.assert_array_equal(got, want)
        finally:
            ref_client.close()
            ref_server.stop(grace=None)


class TestEvictionRace:
    def test_inflight_delta_refused_after_eviction(self, backend):
        """An AssignDelta that looked its session up, then lost the race
        to eviction before acquiring the session lock, must be REFUSED
        (fallback ladder) — solving would advance the tick of an arena
        the store no longer owns, silently diverging the client's shadow
        columns from a solve nobody can replay."""
        servicer, client = backend
        p_cols, r_cols = _cols(31)
        fp = _open(client, p_cols, r_cols, "s-race")
        session, reason = servicer.sessions.get("s-race", fp)
        assert session is not None, reason

        # hold the session lock (simulating another in-flight solve) so
        # the delta blocks between its store lookup and its solve
        session.lock.acquire()
        result: dict = {}

        def delta():
            p_cols["price"] = p_cols["price"].copy()
            p_cols["price"][3] = np.float32(2.5)
            result["resp"] = _delta(client, "s-race", fp, 1, p_cols, [3])

        t = threading.Thread(target=delta)
        t.start()
        # evict while the delta is parked on the lock
        import time as _time

        _time.sleep(0.2)
        servicer.sessions.drop("s-race")
        assert session.evicted is True
        session.lock.release()
        t.join(timeout=30)
        resp = result["resp"]
        assert resp.session_ok is False
        assert "evicted" in resp.error
        assert session.tick == 0  # the arena was never advanced

    def test_lru_and_ttl_eviction_mark_sessions(self, backend):
        from protocol_tpu.services.session_store import (
            SessionStore,
            SolveSession,
        )

        def mk(sid):
            return SolveSession(
                session_id=sid, fingerprint="fp", weights=None,
                kernel="native-mt", threads=1, top_k=16, p_cols={},
                r_cols={}, n_providers=0, n_tasks=0, arena=None,
            )

        store = SessionStore(max_sessions=2, ttl_s=900.0)
        a, b, c = mk("a"), mk("b"), mk("c")
        store.put(a)
        store.put(b)
        store.put(c)  # LRU-evicts a
        assert a.evicted and not b.evicted and not c.evicted
        # same-id replacement marks the replaced object
        b2 = mk("b")
        store.put(b2)
        assert b.evicted and not b2.evicted
        # TTL expiry
        c.last_used -= 10_000.0
        store.put(mk("d"))  # triggers _expire_locked
        assert c.evicted


class TestEngineThreadBudget:
    def test_drained_pool_degrades_instead_of_blocking(self):
        """The anti-serialization contract: a want=all request (threads=0,
        the DEFAULT kernel string) must not park concurrent solves behind
        the first — a drained pool hands out a 1-thread floor grant
        (bounded oversubscription) and the books balance after release."""
        budget = EngineThreadBudget(total=4)
        g1 = budget.acquire(0)  # "all threads"
        assert g1 == 4 and budget.available == 0
        g2 = budget.acquire(0)  # drained: floor grant, NO blocking
        assert g2 == 1 and budget.available == -1
        budget.release(g1)
        budget.release(g2)
        assert budget.available == 4

    def test_partial_grant_under_contention(self):
        budget = EngineThreadBudget(total=4)
        g1 = budget.acquire(3)
        g2 = budget.acquire(4)  # only 1 left: partial grant
        assert (g1, g2) == (3, 1)
        budget.release(g1)
        budget.release(g2)
        assert budget.available == 4
