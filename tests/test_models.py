"""L0 model tests: requirements DSL, capability matching, task model.

Edge cases mirror the reference's in-crate tests for
crates/shared/src/models/node.rs and task.rs.
"""

import pytest

from protocol_tpu.models import (
    ComputeRequirements,
    ComputeSpecs,
    CpuSpecs,
    GpuSpecs,
    Node,
    Task,
    TaskRequest,
    TaskState,
    VolumeMount,
    StorageConfig,
)
from protocol_tpu.models.node import RequirementsParseError


def specs(gpu_count=None, gpu_model=None, gpu_mem=None, cores=None, ram=None, storage=None):
    gpu = None
    if gpu_count is not None or gpu_model is not None or gpu_mem is not None:
        gpu = GpuSpecs(count=gpu_count, model=gpu_model, memory_mb=gpu_mem)
    cpu = CpuSpecs(cores=cores) if cores is not None else None
    return ComputeSpecs(gpu=gpu, cpu=cpu, ram_mb=ram, storage_gb=storage)


class TestRequirementsDSL:
    def test_basic_parse(self):
        r = ComputeRequirements.parse(
            "gpu:count=8;gpu:model=H100;gpu:memory_mb=80000;cpu:cores=32;ram_mb=65536;storage_gb=1000"
        )
        assert len(r.gpu) == 1
        assert r.gpu[0].count == 8
        assert r.gpu[0].model == "H100"
        assert r.gpu[0].memory_mb == 80000
        assert r.cpu.cores == 32
        assert r.ram_mb == 65536
        assert r.storage_gb == 1000

    def test_or_alternatives(self):
        r = ComputeRequirements.parse(
            "gpu:count=8;gpu:model=H100;gpu:count=4;gpu:model=A100"
        )
        assert len(r.gpu) == 2
        assert r.gpu[0].model == "H100"
        assert r.gpu[1].count == 4

    def test_empty_string(self):
        r = ComputeRequirements.parse("")
        assert r.gpu == [] and r.cpu is None

    def test_whitespace_and_empty_parts(self):
        r = ComputeRequirements.parse(" gpu:count=2 ; ; ram_mb=1024 ")
        assert r.gpu[0].count == 2
        assert r.ram_mb == 1024

    def test_exact_and_range_memory_conflict(self):
        with pytest.raises(RequirementsParseError):
            ComputeRequirements.parse("gpu:memory_mb=100;gpu:memory_mb_min=50")
        with pytest.raises(RequirementsParseError):
            ComputeRequirements.parse("gpu:memory_mb_max=100;gpu:memory_mb=50")

    def test_min_greater_than_max_rejected(self):
        with pytest.raises(RequirementsParseError):
            ComputeRequirements.parse("gpu:memory_mb_max=100;gpu:memory_mb_min=200")
        with pytest.raises(RequirementsParseError):
            ComputeRequirements.parse("gpu:total_memory_max=10;gpu:total_memory_min=20")

    def test_unknown_key(self):
        with pytest.raises(RequirementsParseError):
            ComputeRequirements.parse("bogus=1")

    def test_invalid_pair(self):
        with pytest.raises(RequirementsParseError):
            ComputeRequirements.parse("gpu:count")

    def test_invalid_int(self):
        with pytest.raises(RequirementsParseError):
            ComputeRequirements.parse("gpu:count=abc")

    def test_roundtrip_dict(self):
        r = ComputeRequirements.parse("gpu:count=8;gpu:model=H100;ram_mb=1")
        r2 = ComputeRequirements.from_dict(r.to_dict())
        assert r2 == r


class TestMeets:
    def test_simple_pass(self):
        s = specs(gpu_count=8, gpu_model="NVIDIA H100 80GB HBM3", gpu_mem=81000,
                  cores=64, ram=131072, storage=2000)
        r = ComputeRequirements.parse(
            "gpu:count=8;gpu:model=H100;gpu:memory_mb=80000;cpu:cores=32;ram_mb=65536"
        )
        assert s.meets(r)

    def test_gpu_count_exact(self):
        s = specs(gpu_count=4)
        assert not s.meets(ComputeRequirements.parse("gpu:count=8"))
        assert s.meets(ComputeRequirements.parse("gpu:count=4"))
        # more GPUs than required still fails: exact-count semantics
        assert not specs(gpu_count=16).meets(ComputeRequirements.parse("gpu:count=8"))

    def test_gpu_or_logic(self):
        s = specs(gpu_count=4, gpu_model="A100")
        r = ComputeRequirements.parse("gpu:count=8;gpu:model=H100;gpu:count=4;gpu:model=A100")
        assert s.meets(r)
        s2 = specs(gpu_count=2, gpu_model="A100")
        assert not s2.meets(r)

    def test_no_gpu_but_required(self):
        assert not specs(cores=8).meets(ComputeRequirements.parse("gpu:count=1"))

    def test_gpu_not_required(self):
        assert specs(cores=8).meets(ComputeRequirements.parse("cpu:cores=4"))

    def test_model_fuzzy_match(self):
        s = specs(gpu_count=1, gpu_model="NVIDIA GeForce RTX 4090")
        assert s.meets(ComputeRequirements.parse("gpu:count=1;gpu:model=RTX 4090"))
        assert s.meets(ComputeRequirements.parse("gpu:count=1;gpu:model=rtx_4090"))
        assert not s.meets(ComputeRequirements.parse("gpu:count=1;gpu:model=H100"))

    def test_model_csv_alternatives(self):
        s = specs(gpu_count=1, gpu_model="A100-SXM4-80GB")
        assert s.meets(ComputeRequirements.parse("gpu:count=1;gpu:model=H100, A100"))

    def test_memory_ranges(self):
        s = specs(gpu_count=1, gpu_mem=24000)
        assert s.meets(ComputeRequirements.parse("gpu:count=1;gpu:memory_mb_min=20000"))
        assert not s.meets(ComputeRequirements.parse("gpu:count=1;gpu:memory_mb_min=30000"))
        assert s.meets(ComputeRequirements.parse("gpu:count=1;gpu:memory_mb_max=30000"))
        assert not s.meets(ComputeRequirements.parse("gpu:count=1;gpu:memory_mb_max=20000"))

    def test_total_memory(self):
        s = specs(gpu_count=8, gpu_mem=80000)
        assert s.meets(ComputeRequirements.parse("gpu:count=8;gpu:total_memory_min=600000"))
        assert not s.meets(ComputeRequirements.parse("gpu:count=8;gpu:total_memory_min=700000"))
        assert not s.meets(ComputeRequirements.parse("gpu:count=8;gpu:total_memory_max=600000"))

    def test_total_memory_skipped_without_count(self):
        # total-memory constraints only bind when count AND memory present
        s = ComputeSpecs(gpu=GpuSpecs(memory_mb=80000))
        assert s.meets(ComputeRequirements.parse("gpu:total_memory_min=700000"))

    def test_ram_storage(self):
        s = specs(ram=1024, storage=10)
        assert s.meets(ComputeRequirements.parse("ram_mb=1024;storage_gb=10"))
        assert not s.meets(ComputeRequirements.parse("ram_mb=2048"))
        assert not s.meets(ComputeRequirements.parse("storage_gb=20"))
        assert not ComputeSpecs().meets(ComputeRequirements.parse("ram_mb=1"))

    def test_cpu_missing(self):
        assert not ComputeSpecs().meets(ComputeRequirements.parse("cpu:cores=1"))


class TestTask:
    def test_state_parse(self):
        assert TaskState.parse("RUNNING") is TaskState.RUNNING
        assert TaskState.parse("garbage") is TaskState.UNKNOWN

    def test_from_request(self):
        t = Task.from_request(TaskRequest(image="img", name="n"))
        assert t.state is TaskState.PENDING
        assert t.created_at > 0
        assert t.id

    def test_volume_mount_validation(self):
        VolumeMount("/data/${TASK_ID}", "/mnt").validate()
        with pytest.raises(ValueError):
            VolumeMount("/data/${BAD_VAR}", "/mnt").validate()
        with pytest.raises(ValueError):
            VolumeMount("", "/mnt").validate()

    def test_volume_mount_expansion(self):
        vm = VolumeMount("/d/${TASK_ID}/${NODE_ADDRESS}", "/m/${TASK_ID}")
        out = vm.replace_labels("tid", "0xabc")
        assert out.host_path == "/d/tid/0xabc"
        assert out.container_path == "/m/tid"

    def test_storage_config_validation(self):
        StorageConfig("${ORIGINAL_NAME}-${NODE_GROUP_INDEX}").validate()
        with pytest.raises(ValueError):
            StorageConfig("${NOPE}").validate()

    def test_config_hash_stability(self):
        t1 = Task(image="i", env_vars={"a": "1", "b": "2"})
        t2 = Task(image="i", env_vars={"b": "2", "a": "1"})
        assert t1.generate_config_hash() == t2.generate_config_hash()
        t3 = Task(image="i", env_vars={"a": "1", "b": "3"})
        assert t1.generate_config_hash() != t3.generate_config_hash()

    def test_json_roundtrip(self):
        t = Task.from_request(
            TaskRequest(
                image="img", name="n", env_vars={"K": "V"}, cmd=["run"],
                volume_mounts=[VolumeMount("/h", "/c")],
            )
        )
        t2 = Task.from_json(t.to_json())
        assert t2.to_dict() == t.to_dict()


class TestNode:
    def test_json_roundtrip(self):
        n = Node(
            id="0x1", provider_address="0x2", ip_address="1.2.3.4", port=8091,
            compute_pool_id=0,
            compute_specs=specs(gpu_count=2, gpu_model="H100", gpu_mem=80000, cores=8, ram=1024),
        )
        n2 = Node.from_json(n.to_json())
        assert n2.to_dict() == n.to_dict()
