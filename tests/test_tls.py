"""TLS on the wire (VERDICT r2 item 7).

The reference's transport is encrypted (libp2p Noise,
p2p/src/lib.rs:324-335); this framework's signed-HTTP redesign now has
the confidentiality half too. Done-bar: an e2e test running one
signed+TLS hop — here the worker's signed discovery registration over
HTTPS with CA verification, plus the keep-alive JSON client (remote
KV) against a TLS kv-api pod.
"""

import asyncio
import ssl

import pytest

# Environment guard: every test here mints TLS material via
# protocol_tpu.utils.tls.generate_self_signed, which needs the
# third-party `cryptography` package at call time (the module itself
# imports lazily, so collection succeeds and the failure would otherwise
# surface as per-test fixture errors). Skip the module honestly instead.
pytest.importorskip(
    "cryptography", reason="cryptography not installed (signing/TLS dependency)"
)

from protocol_tpu.utils.tls import (
    client_ssl_context,
    generate_self_signed,
    server_ssl_context,
)


@pytest.fixture(scope="module")
def pki(tmp_path_factory):
    return generate_self_signed(str(tmp_path_factory.mktemp("pki")))


class TestPki:
    def test_generates_verifiable_chain(self, pki):
        ctx = client_ssl_context(pki["ca"])
        assert ctx.verify_mode == ssl.CERT_REQUIRED
        srv = server_ssl_context(pki["cert"], pki["key"])
        assert srv is not None

    def test_key_is_owner_only(self, pki):
        import os
        import stat

        mode = stat.S_IMODE(os.stat(pki["key"]).st_mode)
        assert mode == 0o600


class TestSignedTlsHop:
    def test_worker_registration_over_https(self, pki):
        """Full signed+TLS hop: WorkerAgent -> HTTPS discovery with a
        pinned CA; a client without the CA must fail verification."""
        import aiohttp
        from aiohttp import web

        from protocol_tpu.chain.ledger import Ledger
        from protocol_tpu.models import ComputeSpecs, CpuSpecs
        from protocol_tpu.security.wallet import Wallet
        from protocol_tpu.services.discovery import DiscoveryService
        from protocol_tpu.services.worker import WorkerAgent

        async def run():
            ledger = Ledger()
            creator, manager = Wallet.from_seed(b"c"), Wallet.from_seed(b"m")
            did = ledger.create_domain("d", validation_logic="any")
            pid = ledger.create_pool(did, creator.address, manager.address, "")
            ledger.start_pool(pid, creator.address)

            svc = DiscoveryService(ledger, pid)
            runner = web.AppRunner(svc.make_app())
            await runner.setup()
            site = web.TCPSite(
                runner,
                "127.0.0.1",
                0,
                ssl_context=server_ssl_context(pki["cert"], pki["key"]),
            )
            await site.start()
            port = runner.addresses[0][1]
            url = f"https://127.0.0.1:{port}"

            provider, node = Wallet.from_seed(b"p"), Wallet.from_seed(b"n")
            ledger.mint(provider.address, 1000)

            # verified session: the signed PUT lands
            ctx = client_ssl_context(pki["ca"])
            async with aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(ssl=ctx)
            ) as session:
                agent = WorkerAgent(
                    provider_wallet=provider,
                    node_wallet=node,
                    ledger=ledger,
                    pool_id=pid,
                    compute_specs=ComputeSpecs(
                        cpu=CpuSpecs(cores=8), ram_mb=16384, storage_gb=100
                    ),
                    http=session,
                )
                agent.register_on_ledger()
                assert await agent.upload_to_discovery([url]) is True
            assert svc.store.get(node.address) is not None

            # unverified session: TLS handshake must REJECT (the signed
            # payload never leaves the client in the clear)
            async with aiohttp.ClientSession() as bare:
                agent2 = WorkerAgent(
                    provider_wallet=provider,
                    node_wallet=node,
                    ledger=ledger,
                    pool_id=pid,
                    compute_specs=ComputeSpecs(
                        cpu=CpuSpecs(cores=8), ram_mb=16384, storage_gb=100
                    ),
                    http=bare,
                )
                assert await agent2.upload_to_discovery([url]) is False

            await runner.cleanup()

        asyncio.run(run())

    def test_keepalive_client_over_https(self, pki, monkeypatch):
        """RemoteKVStore's keep-alive transport verifies the kv-api pod's
        cert against PROTOCOL_TPU_TLS_CA."""
        from aiohttp import web

        from protocol_tpu.services.kv_api import KvApiService
        from protocol_tpu.store.kv import KVStore
        from protocol_tpu.store.remote_kv import RemoteKVStore

        async def run():
            svc = KvApiService(KVStore(), api_key="k")
            runner = web.AppRunner(svc.make_app())
            await runner.setup()
            site = web.TCPSite(
                runner,
                "127.0.0.1",
                0,
                ssl_context=server_ssl_context(pki["cert"], pki["key"]),
            )
            await site.start()
            port = runner.addresses[0][1]

            monkeypatch.setenv("PROTOCOL_TPU_TLS_CA", pki["ca"])
            store = RemoteKVStore(f"https://127.0.0.1:{port}", api_key="k")

            def ops():
                store.set("tls-key", "v")
                return store.get("tls-key")

            got = await asyncio.to_thread(ops)
            assert got == "v"

            # without the CA the handshake fails closed
            monkeypatch.delenv("PROTOCOL_TPU_TLS_CA")
            bare = RemoteKVStore(f"https://127.0.0.1:{port}", api_key="k")
            with pytest.raises(Exception):
                await asyncio.to_thread(bare.get, "tls-key")

            await runner.cleanup()

        asyncio.run(run())


class TestReviewRegressions:
    def test_pinned_ca_replaces_system_trust(self, pki):
        """Pinning must be exclusive: if system roots stayed loaded, any
        public CA could mint a cert the control plane accepts, defeating
        the pin. Public endpoints use public_client_session() instead."""
        pinned = client_ssl_context(pki["ca"]).cert_store_stats()["x509_ca"]
        assert pinned == 1

    def test_public_session_ignores_pinned_ca(self, pki, monkeypatch):
        """GCS/S3/geolocation sessions must keep system trust even when a
        deployment CA is pinned, or every public HTTPS call fails."""
        import asyncio as _asyncio

        from protocol_tpu.utils.tls import (
            env_client_session,
            public_client_session,
        )

        async def run():
            monkeypatch.setenv("PROTOCOL_TPU_TLS_CA", pki["ca"])
            internal, public = env_client_session(), public_client_session()
            try:
                assert isinstance(internal.connector._ssl, ssl.SSLContext)
                assert not isinstance(
                    getattr(public.connector, "_ssl", None), ssl.SSLContext
                )
            finally:
                await internal.close()
                await public.close()

        _asyncio.run(run())

    def test_worker_advertises_control_scheme(self):
        """A TLS-serving worker must advertise https:// control URLs, or
        every orchestrator/validator dial fails at the handshake."""
        from protocol_tpu.chain.ledger import Ledger
        from protocol_tpu.models import ComputeSpecs, CpuSpecs
        from protocol_tpu.security.wallet import Wallet
        from protocol_tpu.services.worker import WorkerAgent

        def make(scheme):
            return WorkerAgent(
                provider_wallet=Wallet.from_seed(b"p"),
                node_wallet=Wallet.from_seed(b"n"),
                ledger=Ledger(),
                pool_id=0,
                compute_specs=ComputeSpecs(
                    cpu=CpuSpecs(cores=8), ram_mb=16384, storage_gb=100
                ),
                control_scheme=scheme,
            )

        plain = make("http").discovery_node_payload()
        tls = make("https").discovery_node_payload()
        assert plain["worker_p2p_addresses"][0].startswith("http://")
        assert tls["worker_p2p_addresses"][0].startswith("https://")
        with pytest.raises(ValueError):
            make("h2")

    def test_cli_session_honors_tls_ca(self, pki, monkeypatch):
        """The operator CLI must be able to reach TLS-enabled admin
        endpoints via PROTOCOL_TPU_TLS_CA."""
        import asyncio as _asyncio

        from protocol_tpu.cli import _session

        async def run():
            monkeypatch.setenv("PROTOCOL_TPU_TLS_CA", pki["ca"])
            s = _session()
            try:
                ctx = s.connector._ssl
                assert isinstance(ctx, ssl.SSLContext)
            finally:
                await s.close()
            monkeypatch.delenv("PROTOCOL_TPU_TLS_CA")
            s2 = _session()
            try:
                assert not isinstance(
                    getattr(s2.connector, "_ssl", None), ssl.SSLContext
                )
            finally:
                await s2.close()

        _asyncio.run(run())

    def test_worker_upload_session_routing(self):
        """Signed-URL PUTs pick the trust root by destination: orchestrator
        -origin URLs (LocalDir storage route) use the pinned control-plane
        session; external URLs use the public (system-trust) session."""
        from protocol_tpu.chain.ledger import Ledger
        from protocol_tpu.models import ComputeSpecs, CpuSpecs
        from protocol_tpu.security.wallet import Wallet
        from protocol_tpu.services.worker import WorkerAgent

        internal = object()
        agent = WorkerAgent(
            provider_wallet=Wallet.from_seed(b"p"),
            node_wallet=Wallet.from_seed(b"n"),
            ledger=Ledger(),
            pool_id=0,
            compute_specs=ComputeSpecs(
                cpu=CpuSpecs(cores=8), ram_mb=16384, storage_gb=100
            ),
            http=internal,
        )
        agent.orchestrator_url = "https://orch:8090"
        # orchestrator-origin -> control-plane session
        assert agent._upload_session(
            "https://orch:8090/storage/upload/x"
        ) is internal
        # external, no public session configured -> falls back to http
        # (tests / plaintext devnets)
        assert agent._upload_session(
            "https://storage.googleapis.com/b/o"
        ) is internal
        # external with an injected public session -> uses it
        public = object()
        agent.public_http = public
        assert agent._upload_session(
            "https://storage.googleapis.com/b/o"
        ) is public
        assert agent._upload_session(
            "https://orch:8090/storage/upload/x"
        ) is internal
