"""Live marketplace cost inputs (VERDICT r2 item 9).

The cost model's price and load terms must be fed from real state —
provider-advertised ask price (worker -> discovery -> orchestrator) and
worker-reported host load (heartbeat) — not the identically-zero
placeholders of round 2. Done-bar: a price change flips an assignment.
"""


from protocol_tpu.models import (
    ComputeSpecs,
    CpuSpecs,
    GpuSpecs,
    Node,
    SchedulingConfig,
    Task,
    TaskState,
)
from protocol_tpu.models.heartbeat import HeartbeatRequest
from protocol_tpu.sched import TpuBatchMatcher
from protocol_tpu.store import NodeStatus, OrchestratorNode, StoreContext


def specs():
    return ComputeSpecs(
        gpu=GpuSpecs(count=8, model="H100", memory_mb=80000),
        cpu=CpuSpecs(cores=32),
        ram_mb=65536,
        storage_gb=1000,
    )


def node(addr, price=None, load=0.0):
    return OrchestratorNode(
        address=addr,
        status=NodeStatus.HEALTHY,
        compute_specs=specs(),
        price=price,
        load=load,
    )


def one_slot_task():
    return Task(
        name="t",
        image="img",
        created_at=100,
        state=TaskState.PENDING,
        scheduling_config=SchedulingConfig(
            plugins={"tpu_scheduler": {"replicas": ["1"]}}
        ),
    )


class TestPriceFlipsAssignment:
    def _solve(self, price_a, price_b, **matcher_kw):
        ctx = StoreContext.new_test()
        ctx.node_store.add_node(node("0xa", price=price_a))
        ctx.node_store.add_node(node("0xb", price=price_b))
        ctx.task_store.add_task(one_slot_task())
        m = TpuBatchMatcher(ctx, min_solve_interval=0, **matcher_kw)
        m.refresh()
        assert m.last_solve_stats["assigned"] == 1
        return next(iter(m._assignment))

    def test_cheaper_node_wins_dense(self):
        assert self._solve(5.0, 1.0) == "0xb"
        assert self._solve(1.0, 5.0) == "0xa"

    def test_cheaper_node_wins_sparse(self):
        assert self._solve(5.0, 1.0, dense_cell_budget=0) == "0xb"
        assert self._solve(1.0, 5.0, dense_cell_budget=0) == "0xa"

    def test_price_change_flips_on_resolve(self):
        ctx = StoreContext.new_test()
        a, b = node("0xa", price=1.0), node("0xb", price=5.0)
        ctx.node_store.add_node(a)
        ctx.node_store.add_node(b)
        ctx.task_store.add_task(one_slot_task())
        m = TpuBatchMatcher(ctx, min_solve_interval=0, dense_cell_budget=0)
        m.refresh()
        assert "0xa" in m._assignment
        # the provider raises its ask above the competitor's
        a.price = 9.0
        ctx.node_store.update_node(a)
        m.mark_dirty()
        m.refresh()
        assert "0xb" in m._assignment and "0xa" not in m._assignment

    def test_load_steers_unbounded_swarm(self):
        ctx = StoreContext.new_test()
        ctx.node_store.add_node(node("0xbusy", load=1.0))
        ctx.node_store.add_node(node("0xidle", load=0.0))
        # one bounded slot: contention resolved by load when prices equal
        ctx.task_store.add_task(one_slot_task())
        m = TpuBatchMatcher(ctx, min_solve_interval=0)
        m.refresh()
        assert "0xidle" in m._assignment



import importlib.util

import pytest

# Environment guard for the marked tests below: their code paths reach
# protocol_tpu.chain / protocol_tpu.security (wallet signing), which
# need the third-party `cryptography` package. Without it they skip —
# the rest of this module runs everywhere.
_HAS_CRYPTO = importlib.util.find_spec("cryptography") is not None
requires_crypto = pytest.mark.skipif(
    not _HAS_CRYPTO,
    reason="cryptography not installed (signing/TLS dependency)",
)

@requires_crypto
class TestPropagation:
    def test_node_price_survives_discovery_payload(self):
        n = Node(id="0xw", price=2.5, compute_specs=specs())
        assert Node.from_dict(n.to_dict()).price == 2.5

    def test_orchestrator_node_round_trip(self):
        n = node("0xa", price=1.25, load=0.75)
        back = OrchestratorNode.from_dict(n.to_dict())
        assert back.price == 1.25 and back.load == 0.75

    def test_heartbeat_load_round_trip(self):
        hb = HeartbeatRequest(address="0xa", load=0.4)
        assert HeartbeatRequest.from_dict(hb.to_dict()).load == 0.4

    def test_price_flows_worker_to_orchestrator(self):
        """Full hop: WorkerAgent(price=..) -> signed discovery registration
        -> DiscoveryMonitor sync -> orchestrator node store."""
        import asyncio

        import aiohttp
        from aiohttp.test_utils import TestServer

        from protocol_tpu.chain.ledger import Ledger
        from protocol_tpu.models import DiscoveryNode
        from protocol_tpu.security.signer import sign_request
        from protocol_tpu.security.wallet import Wallet
        from protocol_tpu.sched import Scheduler
        from protocol_tpu.services.discovery import DiscoveryService
        from protocol_tpu.services.orchestrator import OrchestratorService
        from protocol_tpu.services.worker import WorkerAgent

        async def run():
            ledger = Ledger()
            creator = Wallet.from_seed(b"creator")
            manager = Wallet.from_seed(b"manager")
            did = ledger.create_domain("d", validation_logic="any")
            pid = ledger.create_pool(did, creator.address, manager.address, "")
            ledger.start_pool(pid, creator.address)
            async with aiohttp.ClientSession() as session:
                discovery = DiscoveryService(ledger, pid)
                dserver = TestServer(discovery.make_app())
                await dserver.start_server()
                durl = str(dserver.make_url(""))

                provider = Wallet.from_seed(b"p")
                nodew = Wallet.from_seed(b"n")
                ledger.mint(provider.address, 1000)
                agent = WorkerAgent(
                    provider_wallet=provider,
                    node_wallet=nodew,
                    ledger=ledger,
                    pool_id=pid,
                    compute_specs=specs(),
                    http=session,
                    price=3.75,
                )
                agent.register_on_ledger()
                ledger.whitelist_provider(provider.address)
                assert await agent.upload_to_discovery([durl])
                # the pool view exposes only validated nodes: attest as the
                # hardware validator would, then sync the ledger flags
                ledger.validate_node(nodew.address)
                discovery.chain_sync_once()

                ctx = StoreContext.new_test()
                sched = Scheduler(ctx)

                async def fetcher():
                    headers, _ = sign_request(f"/api/pool/{pid}", manager)
                    async with session.get(
                        f"{durl}/api/pool/{pid}", headers=headers
                    ) as resp:
                        data = await resp.json()
                        return [
                            DiscoveryNode.from_dict(d)
                            for d in data.get("data", [])
                        ]

                orch = OrchestratorService(
                    ledger, pid, manager, store=ctx, scheduler=sched,
                    discovery_fetcher=fetcher,
                )
                assert await orch.discovery_monitor_once() == 1
                stored = ctx.node_store.get_node(nodew.address)
                assert stored is not None and stored.price == 3.75
                await dserver.close()

        asyncio.run(run())

    def test_heartbeat_updates_node_load(self):
        """The orchestrator's heartbeat store section persists reported
        load onto the node (services/orchestrator.py heartbeat ops)."""
        from protocol_tpu.services.orchestrator import OrchestratorService

        ctx = StoreContext.new_test()
        ctx.node_store.add_node(node("0xa"))
        svc = OrchestratorService.__new__(OrchestratorService)
        svc.store = ctx
        hb = HeartbeatRequest(address="0xa", load=0.6)
        banned = svc._heartbeat_store_ops(hb, "0xa")
        assert banned is False
        assert ctx.node_store.get_node("0xa").load == 0.6
