"""Fixture-driven tests for the project lint engine (scripts/lints).

Contract (the tentpole's acceptance bar): each rule catches 100% of the
violations seeded in its fixture (`# SEED: <rule>` marks the expected
finding lines — the fixture is its own oracle), flags NOTHING in the
clean twin fixture, honors its escape annotation, and the whole engine
exits clean on the real tree (the fail-the-build gate CI runs)."""

import pathlib
import subprocess
import sys

import pytest

from scripts.lints import RULES, run_rules
from scripts.lints.base import Source, iter_files
from scripts.lints.densealloc import DenseAllocRule
from scripts.lints.determinism import SCOPES, DeterminismRule
from scripts.lints.dtype_contract import DtypeContractRule
from scripts.lints.isa_dispatch import IsaDispatchRule
from scripts.lints.lockdiscipline import LockDisciplineRule

REPO = pathlib.Path(__file__).resolve().parents[1]
FIXTURES = REPO / "scripts" / "lints" / "fixtures"

# determinism fixture harness DERIVED from the rule's own scope table
# (one source of truth: a new scope added to SCOPES automatically
# demands its fixture twins here — it cannot silently fall out of
# coverage)
_DET_CASES, _seen = [], set()
for _scope in SCOPES:
    if _scope.fixture_prefix in _seen:
        continue
    _seen.add(_scope.fixture_prefix)
    _DET_CASES.append((
        DeterminismRule,
        f"{_scope.fixture_prefix}determinism_bad.py",
        f"{_scope.fixture_prefix}determinism_ok.py",
        f"determinism-{_scope.name}",
    ))


def seeded_lines(path: pathlib.Path, rule_name: str) -> set[int]:
    return {
        i
        for i, line in enumerate(path.read_text().splitlines(), 1)
        if f"SEED: {rule_name}" in line
    }


def run_on(rule, fname: str):
    return rule.check(Source(FIXTURES / fname))


class TestRulesFireExactlyOnSeeds:
    @pytest.mark.parametrize(
        "rule_cls,bad,ok",
        [c[:3] for c in _DET_CASES] + [
            (LockDisciplineRule, "lock_bad.py", "lock_ok.py"),
            (LockDisciplineRule, "fleet_lock_bad.py", "fleet_lock_ok.py"),
            (LockDisciplineRule, "ckpt_lock_bad.py", "ckpt_lock_ok.py"),
            (DenseAllocRule, "dense_bad.py", "dense_ok.py"),
        ],
        ids=[c[3] for c in _DET_CASES] + [
            "lock-discipline", "lock-discipline-fleet",
            "lock-discipline-ckpt", "dense-alloc",
        ],
    )
    def test_seeds_and_clean_twin(self, rule_cls, bad, ok):
        rule = rule_cls()
        expected = seeded_lines(FIXTURES / bad, rule.name)
        assert expected, f"fixture {bad} has no SEED markers"
        findings = run_on(rule, bad)
        assert {f.line for f in findings} == expected
        # exactly one finding per seeded line — a rule double-reporting
        # the same violation would bury real findings in noise
        assert len(findings) == len(expected)
        assert all(f.rule == rule.name for f in findings)
        assert run_on(rule, ok) == []

    def test_every_determinism_scope_has_fixture_twins_and_coverage(self):
        """The anti-drift guarantee: each SCOPES entry must own fixture
        twins, and the rule's path filter must cover its declared
        paths — a new package added to the table cannot silently skip
        either half."""
        rule = DeterminismRule()
        for scope in SCOPES:
            bad = FIXTURES / f"{scope.fixture_prefix}determinism_bad.py"
            ok = FIXTURES / f"{scope.fixture_prefix}determinism_ok.py"
            assert bad.exists() and ok.exists(), scope.name
            for prefix in scope.prefixes:
                assert rule.applies(prefix + "x.py"), scope.name
            for suffix in scope.suffixes:
                assert rule.applies(suffix), scope.name
                assert rule._is_strict(suffix) == scope.strict, scope.name

    def test_dtype_call_sites(self):
        rule = DtypeContractRule()
        bad = FIXTURES / "dtype_sites_bad.py"
        findings = rule.check(Source(bad))
        assert {f.line for f in findings} == seeded_lines(bad, rule.name)


class TestDtypeCrossCheck:
    def test_seeded_trio_yields_all_mismatch_classes(self):
        rule = DtypeContractRule(
            wire=str(FIXTURES / "dtype_wire_bad.py"),
            arena=str(FIXTURES / "dtype_arena_bad.py"),
            encoding=str(FIXTURES / "dtype_encoding_bad.py"),
            trace=str(FIXTURES / "dtype_trace_bad.py"),
        )
        findings = rule.check_repo()
        msgs = "\n".join(f.message for f in findings)
        assert len(findings) == 5
        assert "'price'" in msgs  # width clash wire float32 vs arena int32
        assert "ram_mb" in msgs  # column dropped from the arena spec
        assert "extra_col" in msgs  # encoding field the wire never carries
        # the trace codec (third site): a recorded-width drift and a
        # dropped column, each its own finding
        trace_msgs = [f for f in findings if "trace" in f.message.lower()]
        assert len(trace_msgs) == 2

    def test_consistent_trio_is_clean(self):
        rule = DtypeContractRule(
            wire=str(FIXTURES / "dtype_wire_ok.py"),
            arena=str(FIXTURES / "dtype_arena_ok.py"),
            encoding=str(FIXTURES / "dtype_encoding_ok.py"),
            trace=str(FIXTURES / "dtype_trace_ok.py"),
        )
        assert rule.check_repo() == []

    def test_persisted_candidate_table_mismatch_is_a_finding(self):
        """Fourth dtype site: export_state's cand_* keys must be covered
        by _CAND_STATE_DTYPES exactly (the persisted candidate
        structure's widths are an on-disk journal contract)."""
        rule = DtypeContractRule(
            wire=str(FIXTURES / "dtype_wire_ok.py"),
            arena=str(FIXTURES / "dtype_cand_bad.py"),
            encoding=str(FIXTURES / "dtype_encoding_ok.py"),
            trace=str(FIXTURES / "dtype_trace_ok.py"),
        )
        findings = rule.check_repo()
        assert len(findings) == 1
        assert "cand_rev" in findings[0].message

    def test_real_arena_candidate_table_is_consistent(self):
        """The shipped arena's declared table covers its export exactly
        (mutation coverage rides the seeded fixture above)."""
        findings = [
            f for f in DtypeContractRule().check_repo()
            if "_CAND_STATE_DTYPES" in f.message or "cand_" in f.message
        ]
        assert findings == []

    def test_missing_table_is_a_finding_not_a_crash(self):
        rule = DtypeContractRule(
            wire=str(FIXTURES / "dtype_encoding_ok.py"),  # no dtype dicts
            arena=str(FIXTURES / "dtype_arena_ok.py"),
            trace=str(FIXTURES / "dtype_trace_ok.py"),
        )
        findings = rule.check_repo()
        assert findings and all(f.rule == "dtype-contract" for f in findings)

    def test_missing_trace_table_is_a_finding(self):
        rule = DtypeContractRule(
            wire=str(FIXTURES / "dtype_wire_ok.py"),
            arena=str(FIXTURES / "dtype_arena_ok.py"),
            encoding=str(FIXTURES / "dtype_encoding_ok.py"),
            trace=str(FIXTURES / "dtype_encoding_ok.py"),  # no trace dicts
        )
        findings = rule.check_repo()
        assert findings
        assert all("TRACE_DTYPES" in f.message for f in findings)


class TestIsaDispatch:
    """The vector-code boundary (ISSUE 16): intrinsics live only inside
    the engine's delimited PER-ISA section and are reached through the
    kIsaOps dispatch table. Fixture-seeded both ways and
    mutation-verified against the real engine source."""

    ENGINE = REPO / "native" / "assign_engine.cpp"

    def test_seeds_and_clean_twin(self):
        bad = FIXTURES / "isa_dispatch_bad.cpp"
        rule = IsaDispatchRule(native_glob=str(bad))
        expected = seeded_lines(bad, rule.name)
        assert expected, "fixture has no SEED markers"
        findings = rule.check_repo()
        assert {f.line for f in findings} == expected
        assert len(findings) == len(expected)
        assert all(f.rule == rule.name for f in findings)
        ok_rule = IsaDispatchRule(
            native_glob=str(FIXTURES / "isa_dispatch_ok.cpp")
        )
        assert ok_rule.check_repo() == []

    def test_real_engine_source_is_clean(self):
        assert IsaDispatchRule().check_repo() == []

    def test_mutated_engine_is_caught(self, tmp_path):
        """Injecting a raw intrinsic into an entry point OUTSIDE the
        section must be a finding — the boundary is load-bearing, not
        decorative."""
        src = self.ENGINE.read_text()
        needle = 'extern "C" {\n'
        assert needle in src
        mutated = tmp_path / "assign_engine.cpp"
        mutated.write_text(src.replace(
            needle,
            needle + "static float sneak(const float* x) "
            "{ return _mm256_cvtss_f32(_mm256_loadu_ps(x)); }\n",
            1,
        ))
        findings = IsaDispatchRule(native_glob=str(mutated)).check_repo()
        assert findings, "intrinsic outside the section not caught"
        assert all(f.rule == "isa-dispatch" for f in findings)

    def test_unclosed_section_is_a_finding(self, tmp_path):
        src = self.ENGINE.read_text()
        mutated = tmp_path / "assign_engine.cpp"
        mutated.write_text(src.replace(
            "// ==== END PER-ISA KERNELS (isa-dispatch)", "// ====", 1
        ))
        findings = IsaDispatchRule(native_glob=str(mutated)).check_repo()
        assert any("never closed" in f.message for f in findings)


class TestEngine:
    def test_real_tree_is_clean(self):
        """The acceptance bar: `python -m scripts.lints` exits 0 on the
        repo. Any finding here is either a real contract violation (fix
        it) or a rule false positive (fix the rule — never loosen the
        fixture)."""
        assert run_rules() == []

    def test_fixtures_are_excluded_from_the_default_walk(self):
        files = iter_files()
        assert not any("fixtures" in f.parts for f in files)

    def test_rule_registry_covers_the_catalog(self):
        names = {r.name for r in RULES}
        assert {
            "determinism", "lock-discipline", "dtype-contract",
            "dense-alloc", "isa-dispatch",
        } <= names

    def test_cli_exit_codes(self):
        ok = subprocess.run(
            [sys.executable, "-m", "scripts.lints", "--list"],
            cwd=REPO, capture_output=True, text=True,
        )
        assert ok.returncode == 0 and "determinism" in ok.stdout
        bad = subprocess.run(
            [sys.executable, "-m", "scripts.lints",
             str(FIXTURES / "dense_bad.py")],
            cwd=REPO, capture_output=True, text=True,
        )
        assert bad.returncode == 1
        assert "dense-alloc" in bad.stdout


class TestSLOStrictMode:
    """The strict tick-indexed mode covers the REAL SLO engine:
    mutation-verified — injecting a clock read into obs/slo.py must be
    caught, and the unmutated module must be clean."""

    REAL = REPO / "protocol_tpu" / "obs" / "slo.py"

    def test_real_slo_module_is_strict_and_clean(self):
        rule = DeterminismRule()
        assert rule.applies("protocol_tpu/obs/slo.py")
        assert rule._is_strict("protocol_tpu/obs/slo.py")
        assert rule.check(Source(self.REAL)) == []

    def test_quality_module_covered_and_clean(self):
        rule = DeterminismRule()
        assert rule.applies("protocol_tpu/obs/quality.py")
        assert not rule._is_strict("protocol_tpu/obs/quality.py")
        assert rule.check(
            Source(REPO / "protocol_tpu" / "obs" / "quality.py")
        ) == []

    @pytest.mark.parametrize(
        "mutation",
        [
            "        import time\n        _t0 = time.monotonic()\n",
            "        import time\n        _t0 = time.perf_counter()\n",
            "        from datetime import datetime\n"
            "        _now = datetime.now()\n",
        ],
        ids=["monotonic", "perf_counter", "datetime"],
    )
    def test_mutated_slo_engine_is_caught(self, tmp_path, mutation):
        src = self.REAL.read_text()
        needle = "        cfg = self.config\n"
        assert needle in src  # observe() body anchor
        mutated = tmp_path / "slo_mutated.py"  # slo_ prefix: strict
        mutated.write_text(src.replace(needle, needle + mutation, 1))
        findings = DeterminismRule().check(Source(mutated))
        assert findings, "clock read injected into observe() not caught"
        assert all(f.rule == "determinism" for f in findings)


class TestChaosPlaneCoverage:
    """The chaos-plane lint extension (ISSUE 9): the determinism rule
    covers ``protocol_tpu/faults/`` (a schedule that consulted
    ``random`` or a wall clock would be unreplayable) and the
    lock-discipline rule covers the checkpoint layer (a flush outside
    the session lock persists a torn tick). Mutation-verified both
    ways: the real modules are clean, and an injected violation is
    caught."""

    FAULTS = REPO / "protocol_tpu" / "faults"

    def test_determinism_rule_covers_the_fault_plane(self):
        rule = DeterminismRule()
        assert rule.applies("protocol_tpu/faults/plan.py")
        assert rule.applies("protocol_tpu/faults/inject.py")
        assert rule.applies("protocol_tpu/faults/harness.py")
        assert not rule._is_strict("protocol_tpu/faults/plan.py")
        for mod in ("plan.py", "inject.py", "harness.py",
                    "checkpoint.py"):
            assert rule.check(Source(self.FAULTS / mod)) == [], mod

    def test_lock_rule_covers_the_checkpoint_layer(self):
        rule = LockDisciplineRule()
        assert rule.applies("protocol_tpu/faults/checkpoint.py")
        assert rule.check(Source(self.FAULTS / "checkpoint.py")) == []

    def test_mutated_fault_schedule_is_caught(self, tmp_path):
        src = (self.FAULTS / "plan.py").read_text()
        needle = "        f = self._frac\n"
        assert needle in src  # decide() body anchor
        mutated = tmp_path / "plan_mutated.py"
        mutated.write_text(src.replace(
            needle,
            needle + "        import random\n"
            "        _jitter = random.random()\n",
            1,
        ))
        findings = DeterminismRule().check(Source(mutated))
        assert findings, "random draw injected into decide() not caught"
        assert all(f.rule == "determinism" for f in findings)

    def test_mutated_checkpoint_flush_is_caught(self, tmp_path):
        src = (self.FAULTS / "checkpoint.py").read_text()
        mutated = tmp_path / "checkpoint_mutated.py"
        mutated.write_text(
            src + "\n\ndef torn_peek(session):\n"
            "    return session.last_p4t, session.tick\n"
        )
        findings = LockDisciplineRule().check(Source(mutated))
        assert len(findings) == 2, (
            "unlocked resilience-cursor reads not caught"
        )
        assert all(f.rule == "lock-discipline" for f in findings)


class TestSuppression:
    def test_escape_annotation_drops_the_finding(self, tmp_path):
        f = tmp_path / "mod.py"
        f.write_text(
            "import numpy as np\n"
            "def solve(P, T):\n"
            "    return np.zeros((P, T))  # lint: dense-ok\n"
        )
        assert DenseAllocRule().check(Source(f)) == []

    def test_blanket_ok_also_escapes(self, tmp_path):
        f = tmp_path / "mod.py"
        f.write_text(
            "import time\n"
            "def solve():\n"
            "    return time.time()  # lint: ok\n"
        )
        assert DeterminismRule().check(Source(f)) == []
