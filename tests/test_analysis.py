"""Mutation-verified tests for the whole-program analyzer
(scripts/analysis) and the runtime lock witness.

The acceptance contract mirrors the lint engine's: every seeded
violation in the fixture corpus is caught (the `# SEED: <rule>` lines
are the oracle), the clean twins come back silent, the REAL tree is
clean, and each analyzer catches a realistic mutation injected into the
real modules — a reordered acquisition, a dropped lock, an
apply-before-deadline handler, a host-sync-in-jit, a dropped
static_argname, a renamed collective axis, and a device-count-derived
tile policy."""

import json
import pathlib
import subprocess
import sys

import pytest

from scripts.analysis import lockorder, protocolsm, purity, spmd, staging
from scripts.analysis.spec import load_spec, parse_toml_subset
from scripts.lints.base import (
    EXTERNAL_SUPPRESS_TOKENS,
    run_rules,
    stale_escapes,
)

REPO = pathlib.Path(__file__).resolve().parents[1]
FIXTURES = REPO / "scripts" / "analysis" / "fixtures"
SPEC = load_spec()


def seeded_lines(path: pathlib.Path, rule_name: str) -> set:
    return {
        i
        for i, line in enumerate(path.read_text().splitlines(), 1)
        if f"SEED: {rule_name}" in line
    }


# --------------------------------------------------------------------
# spec / toml
# --------------------------------------------------------------------


class TestSpec:
    def test_real_spec_loads_and_is_total(self):
        assert SPEC.ranks["shard"] == SPEC.ranks["session"], (
            "shard and session share a rank: neither may nest the other"
        )
        for key, dom in SPEC.classify_attr.items():
            assert dom in SPEC.ranks, key
        for key, dom in SPEC.classify_class.items():
            assert dom in SPEC.ranks, key
        assert set(SPEC.reentrant) <= set(SPEC.ranks)
        assert SPEC.ladder_markers, "ladder marker table must be committed"

    def test_documented_seam_order_is_encoded(self):
        r = SPEC.ranks
        # the ISSUE 10 ordering contract, as ranks
        assert r["shard"] < r["budget"]          # shard -> budget leaf
        assert r["session"] < r["arena"]         # session -> arena
        assert r["session"] < r["threadpool"]    # locked solve borrows
        assert r["session"] < r["trace"]         # recorder under session
        assert r["registry"] > r["budget"]       # registry is a leaf

    def test_ladder_markers_cover_the_client_contract(self):
        from protocol_tpu.services.scheduler_grpc import (
            _PERMANENT_REFUSALS,
        )

        for marker in _PERMANENT_REFUSALS:
            assert any(
                marker in m or m in marker for m in SPEC.ladder_markers
            ), marker
        assert "RESOURCE_EXHAUSTED" in SPEC.ladder_markers

    def test_toml_subset_parser_matches_shapes(self):
        doc = parse_toml_subset(
            '[a]\nx = 1\n"q.k" = "v"\nflag = true\n'
            '[b]\nitems = ["p", "q"]\nmulti = [\n  "r",\n  "s",\n]\n'
        )
        assert doc == {
            "a": {"x": 1, "q.k": "v", "flag": True},
            "b": {"items": ["p", "q"], "multi": ["r", "s"]},
        }

    def test_external_tokens_stay_in_sync_with_the_analyzer(self):
        from scripts.lints.base import EXTERNAL_SUPPRESS_SCOPES

        assert set(EXTERNAL_SUPPRESS_TOKENS) == {
            lockorder.SUPPRESS, protocolsm.SUPPRESS, purity.SUPPRESS,
            staging.SUPPRESS, spmd.SUPPRESS,
        }
        # the lint engine's scope table must mirror each analyzer's
        # actual roots, or the out-of-scope staleness check drifts
        assert EXTERNAL_SUPPRESS_SCOPES[protocolsm.SUPPRESS] == (
            protocolsm.DEFAULT_ROOTS
        )
        assert EXTERNAL_SUPPRESS_SCOPES[purity.SUPPRESS] == (
            purity.DEFAULT_ROOTS
        )
        # the jax passes share roots AND scope: one Index, one scan set
        assert EXTERNAL_SUPPRESS_SCOPES[staging.SUPPRESS] == (
            staging.DEFAULT_ROOTS
        )
        assert EXTERNAL_SUPPRESS_SCOPES[spmd.SUPPRESS] == (
            spmd.DEFAULT_ROOTS
        )
        assert staging.DEFAULT_ROOTS == purity.DEFAULT_ROOTS
        assert spmd.DEFAULT_ROOTS == purity.DEFAULT_ROOTS
        # the lock pass scans the whole walk: empty scope = everywhere
        assert EXTERNAL_SUPPRESS_SCOPES[lockorder.SUPPRESS] == ()

    def test_spmd_spec_loads_and_is_total(self):
        spec = spmd.load_spmd_spec()
        assert spec.axes == ("p",)
        assert spec.rank == 1
        # the conventional axis carrier names the builders thread
        assert "axis" in spec.axis_aliases
        assert "PROVIDER_AXIS" in spec.axis_aliases
        # the communication surface the sharded kernels actually use
        for op in ("psum", "pmax", "pmin", "all_gather", "axis_index"):
            assert op in spec.collectives, op
        # the D-invariance contract: tile policy guarded, jitter NOT
        # (the sharded gen rebuilds global ids from axis_index*Tl)
        assert "pick_tile" in spec.d_guarded
        assert "tie_jitter_ids" not in spec.d_guarded
        assert "jax.device_count" in spec.d_sources
        # the retrace pass's laundering set matches the real helpers
        from protocol_tpu.parallel import sparse as psparse
        from protocol_tpu.parallel.mesh import pad_to_multiple  # noqa: F401
        from protocol_tpu.ops.sparse import pick_tile  # noqa: F401

        assert hasattr(psparse, "_pow2_pad")
        assert "_pow2_pad" in spec.quantizers
        assert "pick_tile" in spec.quantizers
        assert "pad_to_multiple" in spec.quantizers


# --------------------------------------------------------------------
# fixture corpus: seeds caught exactly, clean twins silent
# --------------------------------------------------------------------


class TestSeededFixtures:
    @pytest.mark.parametrize(
        "runner,rule,bad,ok",
        [
            (
                lambda f: lockorder.run(roots=(str(f),), spec=SPEC),
                "lock-order", "lock_reorder_bad.py", "lock_reorder_ok.py",
            ),
            (
                lambda f: lockorder.run(roots=(str(f),), spec=SPEC),
                "lock-order", "lock_dropped_bad.py", "lock_reorder_ok.py",
            ),
            (
                lambda f: protocolsm.run(roots=(str(f),), spec=SPEC),
                "protocol-sm", "protocol_handler_bad.py",
                "protocol_handler_ok.py",
            ),
            (
                lambda f: purity.run(roots=(str(f),)),
                "jax-purity", "purity_bad.py", "purity_ok.py",
            ),
            (
                lambda f: purity.run(roots=(str(f),)),
                "jax-purity", "purity_calljit_bad.py",
                "purity_calljit_ok.py",
            ),
            (
                lambda f: purity.run(roots=(str(f),)),
                "jax-purity", "purity_repair_bad.py",
                "purity_repair_ok.py",
            ),
            (
                lambda f: staging.run(roots=(str(f),)),
                "jax-retrace", "staging_bad.py", "staging_ok.py",
            ),
            (
                lambda f: spmd.run(roots=(str(f),)),
                "spmd-contract", "spmd_bad.py", "spmd_ok.py",
            ),
        ],
        ids=[
            "lock-reorder", "lock-dropped", "protocol-sm", "jax-purity",
            "jax-purity-callform", "jax-purity-repair", "jax-retrace",
            "spmd-contract",
        ],
    )
    def test_seeds_and_clean_twin(self, runner, rule, bad, ok):
        expected = seeded_lines(FIXTURES / bad, rule)
        assert expected, f"fixture {bad} has no SEED markers"
        findings = runner(FIXTURES / bad)
        assert {f.line for f in findings} == expected
        assert len(findings) == len(expected)  # one finding per seed
        assert all(f.rule == rule for f in findings)
        assert runner(FIXTURES / ok) == []


# --------------------------------------------------------------------
# the real tree: clean, and every pass actually covers it
# --------------------------------------------------------------------


class TestRealTree:
    def test_lock_order_clean_and_graph_nonempty(self):
        an = lockorder.LockOrderAnalyzer(spec=SPEC)
        assert an.run() == []
        graph = set()
        for line in an.graph_lines():
            held, rest = line.split("->")
            graph.add((held.strip(), rest.split("(")[0].strip()))
        # the load-bearing seam edges must be OBSERVED (an empty graph
        # would mean the extractor went blind, not that the tree is
        # clean)
        assert ("shard", "budget") in graph
        assert ("session", "threadpool") in graph
        assert ("session", "trace") in graph

    def test_protocol_clean_on_the_servicer(self):
        ck = protocolsm.ProtocolChecker(spec=SPEC)
        assert ck.run() == []

    def test_purity_clean_and_closure_covers_the_kernels(self):
        pc = purity.PurityChecker()
        assert pc.run() == []
        entries = pc.jit_entries()
        assert len(entries) >= 10, "jit entry discovery went blind"
        reach = pc.closure(entries)
        rels = {pc.index.functions[q].rel for q in reach}
        assert any("ops/assign.py" in r for r in rels)
        assert any("ops/sparse.py" in r for r in rels)
        assert any("sched/tpu_backend.py" in r for r in rels)
        # the jax engine's sharded builders (nested jitted closures in
        # parallel/sparse.py) are trace roots the closure must reach —
        # the mesh kernels the JaxSolveArena solves through
        assert any(
            "parallel/sparse.py" in q and ".<locals>." in q
            for q in entries
        ), "sharded-builder jit entries went blind"
        assert any("parallel/sparse.py" in r for r in rels)
        # the warm-path repair kernels (ISSUE 18) are call-form jit
        # entries — forward rows, the enter scan (plain + shard_map
        # twin), the per-tile contribution recompute, and the fold
        # replay. A scan that stops seeing them stops guarding the warm
        # hot path.
        for want in (
            "_build_repair_enter.<locals>",
            "_build_repair_enter_sharded.<locals>",
            "_build_repair_forward.<locals>",
            "_build_repair_tile.<locals>",
            "_build_repair_refold.<locals>",
        ):
            assert any(
                "parallel/sparse.py" in q and want in q for q in entries
            ), f"repair jit entry {want} went blind"

    def test_retrace_clean_and_sees_the_compile_keys(self):
        st = staging.StagingChecker()
        assert st.run() == []
        # discovery sanity: the pass saw the same entry set purity does
        entries = st.purity.jit_entries()
        assert len(entries) >= 10
        # the lru_cached sharded builders are compile-key surfaces —
        # an empty builder map would mean R3 went blind
        builders = st._builders(entries)
        assert any(
            "parallel/sparse.py" in q and "_build_sharded" in q
            for q in builders
        ), "sharded-builder compile keys went blind"

    def test_spmd_clean_and_sees_the_sharded_kernels(self):
        sm = spmd.SpmdChecker()
        assert sm.run() == []
        sharded = sm._sharded_functions()
        # every sharded kernel family must be discovered (decorator
        # form in the builders, call form for the repair enter twin)
        rels = {sm.index.functions[q].rel for q in sharded}
        assert "protocol_tpu/parallel/sparse.py" in rels
        assert "protocol_tpu/parallel/auction.py" in rels
        assert "protocol_tpu/parallel/sinkhorn.py" in rels
        assert any("_build_repair_enter_sharded" in q for q in sharded)
        # and the region closure must reach the collective-bearing
        # helpers, or the placement rule (S4) stops meaning anything
        region = sm._sharded_region(sharded)
        assert len(region) > len(sharded)

    def test_cli_clean_and_exit_codes(self):
        ok = subprocess.run(
            [sys.executable, "-m", "scripts.analysis"],
            cwd=REPO, capture_output=True, text=True,
        )
        assert ok.returncode == 0, ok.stdout + ok.stderr
        assert "analysis clean" in ok.stdout
        bad = subprocess.run(
            [sys.executable, "-m", "scripts.analysis", "--graph"],
            cwd=REPO, capture_output=True, text=True,
        )
        assert bad.returncode == 0
        assert "shard" in bad.stdout


# --------------------------------------------------------------------
# mutation verification against the REAL modules
# --------------------------------------------------------------------


class TestRealModuleMutations:
    def test_reordered_acquisition_in_the_fabric_is_caught(self, tmp_path):
        src = (REPO / "protocol_tpu/fleet/fabric.py").read_text()
        mutated = tmp_path / "fabric_mutated.py"
        mutated.write_text(
            src + "\n\nclass RogueFabric(SessionFabric):\n"
            "    def bad_pressure(self):\n"
            "        with self._budget_lock:\n"
            "            self.shards[0].evict('x', reason='pressure')\n"
        )
        findings = lockorder.run(
            roots=(
                str(mutated),
                "protocol_tpu/services/session_store.py",
            ),
            spec=SPEC,
        )
        assert findings, "budget->shard reorder not caught"
        assert any(
            "'shard'" in f.message and "'budget'" in f.message
            for f in findings
        ), findings

    def test_dropped_lock_in_the_store_is_caught(self, tmp_path):
        src = (
            REPO / "protocol_tpu/services/session_store.py"
        ).read_text()
        mutated = tmp_path / "session_store_mutated.py"
        mutated.write_text(
            src + "\n\nclass RogueStore(SessionStore):\n"
            "    def sweep_fast(self):\n"
            "        self._expire_locked()\n"
        )
        findings = lockorder.run(roots=(str(mutated),), spec=SPEC)
        assert any(
            "_expire_locked" in f.message and "no lock held" in f.message
            for f in findings
        ), findings

    def test_apply_before_deadline_in_the_servicer_is_caught(
        self, tmp_path
    ):
        src = (
            REPO / "protocol_tpu/services/scheduler_grpc.py"
        ).read_text()
        deadline = '            self._check_deadline(context, "delta")\n'
        apply_block = (
            "                try:\n"
            "                    session.apply_delta(\n"
            "                        prow, p_delta, trow, r_delta,\n"
            "                        events=(\n"
            "                            [{\n"
            '                                "kind": '
            'request.event_kind or "event",\n'
            '                                "source": '
            "request.event_source,\n"
            '                                "seq": '
            "int(request.event_seq),\n"
            "                            }]\n"
            "                            if is_event else None\n"
            "                        ),\n"
            "                    )\n"
            "                except ValueError as e:\n"
            "                    context.abort(\n"
            "                        grpc.StatusCode.INVALID_ARGUMENT, "
            "str(e)\n"
            "                    )\n"
        )
        assert deadline in src and apply_block in src
        # the PR 9 mutation: deadline honored after the delta applied
        # (the stream-era handler routes events between the check and
        # the apply, so the mutation MOVES the check past the apply
        # rather than swapping adjacent lines)
        mutated_src = src.replace(deadline, "").replace(
            apply_block,
            apply_block
            + '                self._check_deadline(context, "delta")\n',
        )
        assert mutated_src != src
        mutated = tmp_path / "scheduler_grpc_mutated.py"
        mutated.write_text(mutated_src)
        findings = protocolsm.run(roots=(str(mutated),), spec=SPEC)
        assert any(
            "deadline honored AFTER" in f.message for f in findings
        ), findings
        # the unmutated servicer is clean (re-checked here so this test
        # fails loudly if the needle anchors drift)
        assert protocolsm.run(spec=SPEC) == []

    def test_host_sync_in_jit_is_caught(self, tmp_path):
        src = (REPO / "protocol_tpu/ops/assign.py").read_text()
        needle = "    _, _, owner, p4t = lax.while_loop(cond, body, state0)\n"
        assert needle in src  # assign_auction body anchor
        mutated = tmp_path / "assign_mutated.py"
        mutated.write_text(src.replace(
            needle, needle + "    _host = float(p4t.sum().item())\n", 1
        ))
        findings = purity.run(roots=(str(mutated),))
        assert any(".item()" in f.message for f in findings), findings

    def test_dropped_static_argname_is_caught(self, tmp_path):
        src = (REPO / "protocol_tpu/ops/sparse.py").read_text()
        needle = 'static_argnames=("k", "tile", "approx_recall")'
        assert needle in src  # candidates_topk anchor
        mutated = tmp_path / "sparse_mutated.py"
        mutated.write_text(src.replace(
            needle, 'static_argnames=("tile", "approx_recall")', 1
        ))
        findings = staging.run(roots=(str(mutated),))
        assert any(
            "'k' outside static_argnames" in f.message
            for f in findings
        ), findings
        # the unmutated module is clean (anchor-drift tripwire)
        assert staging.run(
            roots=("protocol_tpu/ops/sparse.py",)
        ) == []

    def test_renamed_collective_axis_is_caught(self, tmp_path):
        src = (REPO / "protocol_tpu/parallel/sparse.py").read_text()
        i = src.index("lax.psum(")
        j = src.index("axis)", i)
        assert j > i  # first psum passes the threaded axis carrier
        mutated = tmp_path / "parallel_sparse_mutated.py"
        mutated.write_text(src[:j] + '"q")' + src[j + len("axis)"):])
        findings = spmd.run(roots=(str(mutated),))
        assert any(
            "axis 'q'" in f.message and "psum" in f.message
            for f in findings
        ), findings

    def test_device_count_in_tile_policy_is_caught(self, tmp_path):
        src = (REPO / "protocol_tpu/parallel/jax_arena.py").read_text()
        needle = "tile = pick_tile(T, cap=min(1024, max(1, T // 8)))"
        assert needle in src  # _gen_plan computes tile before D
        mutated = tmp_path / "jax_arena_mutated.py"
        mutated.write_text(src.replace(
            needle,
            "D = self._ensure_devices()\n        "
            "tile = pick_tile(T, cap=min(1024, max(1, T // D)))",
            1,
        ))
        findings = spmd.run(roots=(str(mutated),))
        assert any(
            "derives from the device count" in f.message
            for f in findings
        ), findings
        # the unmutated arena is clean
        assert spmd.run(
            roots=("protocol_tpu/parallel/jax_arena.py",)
        ) == []


# --------------------------------------------------------------------
# runtime lock witness
# --------------------------------------------------------------------


class TestLockWitness:
    @pytest.fixture(autouse=True)
    def _armed(self, monkeypatch):
        from protocol_tpu.utils import lockwitness

        monkeypatch.setenv("PROTOCOL_TPU_LOCK_WITNESS", "1")
        lockwitness.reset()
        yield
        lockwitness.reset()

    def test_disabled_returns_plain_lock(self, monkeypatch):
        import threading

        from protocol_tpu.utils import lockwitness

        monkeypatch.delenv("PROTOCOL_TPU_LOCK_WITNESS", raising=False)
        lock = lockwitness.make_lock("shard")
        assert isinstance(lock, type(threading.Lock()))

    def test_spec_order_passes_reverse_order_records(self):
        from protocol_tpu.utils import lockwitness as lw

        shard = lw.make_lock("shard")
        budget = lw.make_lock("budget")
        with shard:
            with budget:
                pass
        assert lw.violations() == []
        with budget:
            with shard:
                pass
        v = lw.violations()
        assert len(v) == 1
        assert v[0]["acquiring"] == "shard"
        assert ("budget", SPEC.ranks["budget"]) in v[0]["held"]

    def test_same_rank_never_nests(self):
        from protocol_tpu.utils import lockwitness as lw

        a, b = lw.make_lock("shard"), lw.make_lock("shard")
        with a:
            with b:
                pass
        assert len(lw.violations()) == 1

    def test_reentrant_domain_may_reenter_itself(self):
        from protocol_tpu.utils import lockwitness as lw

        ledger = lw.make_rlock("ledger")
        with ledger:
            with ledger:  # RLock semantics: same instance, fine
                pass
        assert lw.violations() == []

    def test_bare_acquire_release_and_locked(self):
        from protocol_tpu.utils import lockwitness as lw

        lock = lw.make_lock("session")
        assert lock.acquire()
        assert lock.locked()
        lock.release()
        assert not lock.locked()
        assert lw.violations() == []

    def test_strict_mode_raises(self, monkeypatch):
        from protocol_tpu.utils import lockwitness as lw

        monkeypatch.setenv("PROTOCOL_TPU_LOCK_WITNESS", "strict")
        budget, shard = lw.make_lock("budget"), lw.make_lock("shard")
        with budget:
            with pytest.raises(lw.LockOrderViolation):
                with shard:
                    pass

    def test_fleet_locks_are_witnessed_under_env(self):
        from protocol_tpu.fleet.fabric import SessionFabric
        from protocol_tpu.utils.lockwitness import WitnessedLock

        fabric = SessionFabric(shards=2, max_sessions=4)
        assert isinstance(fabric._budget_lock, WitnessedLock)
        assert isinstance(fabric.shards[0]._lock, WitnessedLock)

    def test_lazy_module_lock_decides_at_first_use(self, monkeypatch):
        """Module-global locks (trace _claim_lock, _PROFILE_LOCK) are
        created at import time — before any fixture can arm the
        witness. LazyLock defers the decision to first acquisition, so
        arming the env AFTER import still witnesses them."""
        from protocol_tpu.utils import lockwitness as lw

        monkeypatch.delenv("PROTOCOL_TPU_LOCK_WITNESS", raising=False)
        lazy = lw.LazyLock("trace-claim")  # "import time": disarmed
        monkeypatch.setenv("PROTOCOL_TPU_LOCK_WITNESS", "1")
        with lazy:
            pass  # first use: resolves to a WitnessedLock
        assert isinstance(lazy._lock, lw.WitnessedLock)
        # and the order is asserted through the lazy shim: trace-claim
        # (38) acquired while holding tracer (52) violates
        tracer = lw.make_lock("tracer")
        with tracer:
            with lazy:
                pass
        assert len(lw.violations()) == 1

    def test_reentrant_runtime_sites_are_witnessed(self):
        # KVStore is the reentrant-domain site importable without the
        # optional cryptography dependency (the ledger mirrors it)
        from protocol_tpu.store.kv import KVStore
        from protocol_tpu.utils.lockwitness import WitnessedLock

        store = KVStore()
        assert isinstance(store._lock, WitnessedLock)
        assert store._lock.reentrant


# --------------------------------------------------------------------
# stale-escape audit + SARIF (satellites)
# --------------------------------------------------------------------


class TestStaleEscapeAudit:
    def test_stale_escape_is_reported(self, tmp_path):
        f = tmp_path / "mod.py"
        f.write_text(
            "import numpy as np\n"
            "def solve(P, T):\n"
            "    return P + T  # lint: dense-ok\n"
        )
        findings = run_rules(roots=(str(f),))
        assert [x.rule for x in findings] == ["stale-escape"]
        assert "suppresses no finding" in findings[0].message
        assert findings[0].line == 3

    def test_consumed_escape_is_not_reported(self, tmp_path):
        f = tmp_path / "mod.py"
        f.write_text(
            "import numpy as np\n"
            "def solve(P, T):\n"
            "    return np.zeros((P, T))  # lint: dense-ok\n"
        )
        assert run_rules(roots=(str(f),)) == []

    def test_unknown_token_is_reported(self, tmp_path):
        f = tmp_path / "mod.py"
        f.write_text("x = 1  # lint: bogus-ok\n")
        findings = run_rules(roots=(str(f),))
        assert [x.rule for x in findings] == ["stale-escape"]
        assert "unknown escape token" in findings[0].message

    def test_analyzer_tokens_are_not_the_lint_engines_business(self):
        lines = ["x = 1  # lint: lock-order-ok"]
        assert stale_escapes("mod.py", lines, set()) == []

    def test_out_of_scope_analyzer_token_is_stale(self):
        # a purity escape in a file the purity pass never scans: no
        # engine could ever consume it, so the lint audit reports it
        lines = ["x = 1  # lint: purity-ok"]
        findings = stale_escapes(
            "protocol_tpu/services/session_store.py", lines, set()
        )
        assert [f.rule for f in findings] == ["stale-escape"]
        assert "outside the owning analyzer's scan scope" in (
            findings[0].message
        )
        # the same escape inside the purity scope is the analyzer's
        # business, not the lint engine's
        assert stale_escapes(
            "protocol_tpu/ops/assign.py", lines, set()
        ) == []

    def test_analyzer_audits_its_own_stale_escape(self, tmp_path):
        from scripts.analysis.__main__ import _audit_own_escapes

        f = tmp_path / "mod.py"
        f.write_text("x = 1  # lint: purity-ok\n")
        rel = str(f.relative_to(f.anchor))
        # absolute path trick: _audit_own_escapes joins REPO/rel, so
        # feed it a file INSIDE the repo instead
        target = REPO / "scripts" / "analysis" / "fixtures"
        probe = target / "_stale_probe_tmp.py"
        probe.write_text("x = 1  # lint: purity-ok\n")
        try:
            rel = str(probe.relative_to(REPO))
            findings = _audit_own_escapes({rel}, "purity-ok", set())
            assert [x.rule for x in findings] == ["stale-escape"]
            consumed = {(rel, 1)}
            assert _audit_own_escapes({rel}, "purity-ok", consumed) == []
        finally:
            probe.unlink()

    def test_real_tree_audit_is_clean(self):
        # every committed escape still suppresses something — the audit
        # rides the full engine run
        assert [
            f for f in run_rules() if f.rule == "stale-escape"
        ] == []


class TestSarif:
    def test_shared_emitter_shape(self):
        from scripts.lints.base import Finding
        from scripts.lints.sarif import to_sarif

        doc = to_sarif(
            [Finding("lock-order", "a/b.py", 7, "boom")],
            "scripts.analysis",
        )
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        assert run["tool"]["driver"]["name"] == "scripts.analysis"
        assert run["tool"]["driver"]["rules"][0]["id"] == "lock-order"
        res = run["results"][0]
        assert res["ruleId"] == "lock-order"
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == "a/b.py"
        assert loc["region"]["startLine"] == 7

    def test_lints_cli_writes_sarif(self, tmp_path):
        out = tmp_path / "lints.sarif"
        proc = subprocess.run(
            [sys.executable, "-m", "scripts.lints", "--sarif", str(out)],
            cwd=REPO, capture_output=True, text=True,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        doc = json.loads(out.read_text())
        assert doc["runs"][0]["tool"]["driver"]["name"] == "scripts.lints"
        assert doc["runs"][0]["results"] == []

    def test_analysis_cli_writes_sarif_with_findings(self, tmp_path):
        out = tmp_path / "analysis.sarif"
        proc = subprocess.run(
            [sys.executable, "-m", "scripts.analysis",
             "--sarif", str(out)],
            cwd=REPO, capture_output=True, text=True,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        doc = json.loads(out.read_text())
        assert doc["runs"][0]["tool"]["driver"]["name"] == (
            "scripts.analysis"
        )
