"""Fake GCS/S3 bucket: an aiohttp app that VERIFIES V4 signed URLs
(signature reconstruction, expiry, signed content-length enforcement)
and stores objects in memory — the integration target for the cloud
storage providers (the reference tests against a real bucket via env
creds, google_cloud.rs:184-233; this fake keeps the same checks
hermetic)."""

from __future__ import annotations

import datetime
import hashlib
import hmac
import urllib.parse

from aiohttp import web

from protocol_tpu.utils.cloud_storage import _canonical_request


class FakeBucket:
    """Verifies GOOG4-RSA-SHA256 (with the SA public key) or
    AWS4-HMAC-SHA256 (with the secret key) query-signed requests."""

    def __init__(self, rsa_public_key=None, hmac_secret: str = "", region="auto"):
        self.rsa_public_key = rsa_public_key
        self.hmac_secret = hmac_secret
        self.region = region
        self.objects: dict[str, bytes] = {}
        self.rejections: list[str] = []

    def _reject(self, reason: str):
        self.rejections.append(reason)
        return web.Response(status=403, text=reason)

    def _verify(self, request: web.Request, prefix: str, algorithm: str):
        q = dict(request.query)
        for want in ("Algorithm", "Credential", "Date", "Expires",
                     "SignedHeaders", "Signature"):
            if f"{prefix}{want}" not in q:
                return f"missing {prefix}{want}"
        if q[f"{prefix}Algorithm"] != algorithm:
            return "wrong algorithm"
        sig = q.pop(f"{prefix}Signature")

        # expiry
        stamp = q[f"{prefix}Date"]
        t = datetime.datetime.strptime(stamp, "%Y%m%dT%H%M%SZ").replace(
            tzinfo=datetime.timezone.utc
        )
        age = (datetime.datetime.now(datetime.timezone.utc) - t).total_seconds()
        if age > int(q[f"{prefix}Expires"]):
            return "expired"

        # reconstruct the canonical request from what actually arrived
        signed_headers = q[f"{prefix}SignedHeaders"].split(";")
        headers = {}
        for h in signed_headers:
            if h == "host":
                headers["host"] = request.headers.get("Host", "")
            else:
                v = request.headers.get(h)
                if v is None:
                    return f"signed header {h} missing from request"
                headers[h] = v
        # raw_path keeps the client's percent-encoding — request.path is
        # already decoded, and re-quoting it would corrupt names that
        # legitimately contain encoded sequences
        canonical, _ = _canonical_request(
            request.method, request.raw_path.split("?", 1)[0], q, headers
        )
        scope = q[f"{prefix}Credential"].split("/", 1)[1]
        string_to_sign = "\n".join(
            [algorithm, stamp, scope,
             hashlib.sha256(canonical.encode()).hexdigest()]
        ).encode()

        if algorithm == "GOOG4-RSA-SHA256":
            from cryptography.exceptions import InvalidSignature
            from cryptography.hazmat.primitives import hashes
            from cryptography.hazmat.primitives.asymmetric import padding

            try:
                self.rsa_public_key.verify(
                    bytes.fromhex(sig), string_to_sign,
                    padding.PKCS1v15(), hashes.SHA256(),
                )
            except (InvalidSignature, ValueError):
                return "bad signature"
        else:
            def kd(key: bytes, msg: str) -> bytes:
                return hmac.new(key, msg.encode(), hashlib.sha256).digest()

            datestamp = stamp[:8]
            k = kd(f"AWS4{self.hmac_secret}".encode(), datestamp)
            k = kd(k, self.region)
            k = kd(k, "s3")
            k = kd(k, "aws4_request")
            want = hmac.new(k, string_to_sign, hashlib.sha256).hexdigest()
            if not hmac.compare_digest(want, sig):
                return "bad signature"

        # a signed content-length binds the upload size
        if "content-length" in headers and request.method == "PUT":
            if str(request.content_length) != headers["content-length"]:
                return "content-length mismatch"
        return None

    async def handle(self, request: web.Request) -> web.Response:
        prefix = "X-Goog-" if "X-Goog-Algorithm" in request.query else "X-Amz-"
        algorithm = (
            "GOOG4-RSA-SHA256" if prefix == "X-Goog-" else "AWS4-HMAC-SHA256"
        )
        err = self._verify(request, prefix, algorithm)
        if err:
            return self._reject(err)
        key = request.path.lstrip("/")
        if request.method == "PUT":
            body = await request.read()
            cl = request.headers.get("content-length")
            if cl is not None and int(cl) != len(body):
                return self._reject("body length lies about content-length")
            self.objects[key] = body
            return web.Response(status=200)
        if key not in self.objects:
            return web.Response(status=404)
        if request.method == "HEAD":
            return web.Response(status=200)
        return web.Response(body=self.objects[key])

    def make_app(self) -> web.Application:
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self.handle)
        return app
