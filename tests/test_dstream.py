"""Distributed event firehose (ISSUE 20).

Unit/in-process coverage for the ``dstream`` subsystem the
``perf_gate.py --dstream`` CI bar rests on:

  * **Sentinel-seq fan-out determinism.** A fleet-level event (mass
    blackout, ejection storm) decomposes into per-source leave events
    whose seq sits ABOVE every workload seq — under the stream engine's
    per-source latest-wins supersession the converged columns (and so
    the final reconciled plan) are independent of where the fan-out
    interleaves each session's firehose. Asserted here by applying the
    same event multiset in two hostile interleavings and comparing the
    reconciled plans bit-for-bit.
  * **Stream state travel.** ``StreamEngine.export_state`` /
    ``from_state`` round-trips the dedup cursors (a retransmit that
    straddles a process boundary must dedup at the target exactly as it
    would have at the origin), the reconcile-cadence cursor (migrated
    boundaries stay aligned with the fault-free replay), and the obs
    counters — all JSON-serializable for the checkpoint META frame.
  * **Cross-process live migration.** A stream session mid-firehose is
    Migrate'd between two servicers: the client follows the ``moved:``
    redirect with ZERO reopens, the target re-arms warm, a
    byte-identical retransmitted tick replays (CRC twin), an old
    (source, seq) at a fresh tick dedup-ACKs (cursor twin), and the
    reconciled plans across the boundary stay bit-identical to a
    fault-free single-process replay.
  * **Blackout composition + scrape rollup.** ``SessionFabric.blackout``
    arms a seeded leave-storm schedule drained exactly once by the
    drill driver; ``stream_rollup`` joins per-process ``/metrics.json``
    stream sections fleet-wide (dead procs listed, never dropped).

The real 3-subprocess SIGKILL/ejection-storm drill lives in
``perf_gate.py --dstream`` phase B.
"""

import json
import socket

import numpy as np
import pytest

from protocol_tpu import native
from protocol_tpu.dfleet.topology import FleetTopology
from protocol_tpu.dstream.fanout import (
    MASS_SEQ_BASE,
    PAD_SEQ_BASE,
    PAD_SOURCE,
    STORM_SEQ_BASE,
    affected_rows,
    blackout_storm_schedule,
    ejection_leave_events,
    leave_events,
    mass_leave_events,
    pad_event,
    source_home,
    storm_rows,
)
from protocol_tpu.dstream.rollup import events_per_second, stream_rollup
from protocol_tpu.fleet.fabric import FleetConfig, SessionFabric
from protocol_tpu.proto import scheduler_pb2 as pb
from protocol_tpu.proto import wire
from protocol_tpu.stream.engine import StreamEngine
from protocol_tpu.stream.replay import _events_of, _open_arena, stream_replay
from protocol_tpu.trace import format as tfmt
from protocol_tpu.trace.synth import synth_event_trace

NATIVE = native.available()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------- fan-out planning (pure) ----------------


class TestFanout:
    def test_sentinel_tiers_dominate_workload_and_each_other(self):
        """Workload seqs are per-source counters (thousands at most);
        the pad tier sits above them, mass above pads, storm above mass
        — so 'the process died' beats 'the region blacked out' for a
        doubly-affected source, and both beat every workload event."""
        workload_seq_max = 1 << 20
        assert workload_seq_max < PAD_SEQ_BASE < MASS_SEQ_BASE
        # tiers stay ordered across any plausible index/generation
        for k in (0, 1, 1000):
            for g in (0, 1, 1000):
                assert MASS_SEQ_BASE + k < STORM_SEQ_BASE + g

    def test_storm_rows_deterministic_and_bounded(self):
        a = storm_rows(7, "blackout-shard1", 256, 0.1)
        b = storm_rows(7, "blackout-shard1", 256, 0.1)
        np.testing.assert_array_equal(a, b)
        assert a.dtype == np.int32
        assert len(a) == round(256 * 0.1)
        assert sorted(a.tolist()) == a.tolist()
        assert a.min() >= 0 and a.max() < 256
        # different seed/tag pick different membership
        c = storm_rows(8, "blackout-shard1", 256, 0.1)
        assert a.tolist() != c.tolist()
        # never a no-op, never out of range
        assert len(storm_rows(1, "t", 64, 0.0001)) == 1
        assert len(storm_rows(1, "t", 64, 5.0)) == 64

    def test_leave_events_pin_snapshot_payload_invalid(self):
        rng = np.random.default_rng(3)
        p_cols = {
            "price": rng.random(16).astype(np.float32),
            "valid": np.ones(16, np.bool_),
        }
        evs = leave_events([2, 5], 1234, p_cols)
        assert [e.source for e in evs] == ["p2", "p5"]
        assert all(e.seq == 1234 and e.kind == "leave" for e in evs)
        for e, r in zip(evs, (2, 5)):
            np.testing.assert_array_equal(
                e.provider_rows, np.asarray([r], np.int32)
            )
            np.testing.assert_array_equal(
                e.p_cols["price"], p_cols["price"][[r]]
            )
            assert not e.p_cols["valid"][0]
            assert e.task_rows.size == 0 and e.r_cols == {}

    def test_mass_and_ejection_tiers(self):
        p_cols = {"valid": np.ones(4, np.bool_)}
        assert mass_leave_events(3, [0], p_cols)[0].seq == (
            MASS_SEQ_BASE + 3
        )
        assert ejection_leave_events(5, [0], p_cols)[0].seq == (
            STORM_SEQ_BASE + 5
        )

    def test_pad_event_is_a_distinct_seq_noop(self):
        p0, p1 = pad_event(0), pad_event(1)
        assert p0.source == PAD_SOURCE and p0.kind == "heartbeat"
        assert p0.seq == PAD_SEQ_BASE and p1.seq == PAD_SEQ_BASE + 1
        assert p0.provider_rows.size == 0 and p0.task_rows.size == 0

    def test_blackout_schedule_json_roundtrip(self):
        sched = blackout_storm_schedule(7, 1, 256, frac=0.1, mass_index=2)
        rt = json.loads(json.dumps(sched))
        assert rt == sched
        assert rt["kind"] == "blackout" and rt["mass_index"] == 2
        np.testing.assert_array_equal(
            np.asarray(rt["rows"], np.int32),
            storm_rows(7, "blackout-shard1", 256, 0.1),
        )

    def test_ejection_rows_partition_by_home(self):
        """Every source is homed on exactly one process, so the
        per-process affected sets partition the row space: each
        driver's storm membership is disjoint and complete — two
        processes can never both claim a source, and none is orphaned."""
        topo = FleetTopology(
            ["a:1", "b:2", "c:3"],
            procs={"a:1": "p0", "b:2": "p1", "c:3": "p2"},
        )
        sid, n = "t0@es1", 128
        sets = [
            affected_rows(topo, sid, pid, n).tolist()
            for pid in ("p0", "p1", "p2")
        ]
        assert all(len(s) > 0 for s in sets)  # ring spreads at n=128
        flat = sorted(r for s in sets for r in s)
        assert flat == list(range(n))
        for r in sets[0]:
            assert source_home(topo, sid, f"p{r}") == "p0"
        # membership is session-keyed: a second session storms its own set
        other = affected_rows(topo, "t1@es2", "p0", n).tolist()
        assert other != sets[0]


# ---------------- scrape rollup (pure) ----------------


class TestRollup:
    def _snap(self, nested: bool, streams: dict) -> dict:
        sessions = {
            sid: {"tick": {"count": 1}, "stream": st}
            for sid, st in streams.items()
        }
        if nested:  # scraped /metrics.json shape
            return {"seam": {}, "obs": {"sessions": sessions}}
        return {"sessions": sessions}  # raw ObsRegistry.snapshot()

    def test_rollup_joins_both_shapes_and_lists_dead(self):
        st_a = {
            "event": {"count": 10, "p99_us": 50.0, "max_us": 80.0},
            "deduped": 2, "reconciled": 3,
            "divergence_rows_max": 4, "repair_rows": 7,
        }
        st_b = {
            "event": {"count": 5, "p99_us": 90.0, "max_us": 95.0},
            "deduped": 0, "reconciled": 1,
            "divergence_rows_max": 9, "repair_rows": 2,
        }
        scrapes = {
            "p0": self._snap(True, {"t0@a": st_a}),
            "p1": self._snap(False, {"t1@b": st_b}),
            "p2": None,  # SIGKILL'd mid-drill
        }
        r = stream_rollup(scrapes)
        assert r["events"] == 15 and r["sessions"] == 2
        assert r["deduped"] == 2 and r["reconciled"] == 4
        assert r["repair_rows"] == 9
        assert r["divergence_rows_max"] == 9
        assert r["p99_us_max"] == 90.0 and r["max_us"] == 95.0
        assert r["dead_procs"] == ["p2"]
        assert r["procs"]["p0"]["events"] == 10
        assert r["procs"]["p1"]["events"] == 5

    def test_sessions_without_stream_sections_ignored(self):
        scrapes = {"p0": self._snap(True, {})}
        scrapes["p0"]["obs"]["sessions"]["t0@batch"] = {"tick": {}}
        r = stream_rollup(scrapes)
        assert r["events"] == 0 and r["sessions"] == 0
        assert r["dead_procs"] == []

    def test_events_per_second(self):
        assert events_per_second({"events": 100}, 4.0) == 25.0
        assert events_per_second({"events": 100}, 0.0) == 0.0
        assert events_per_second({}, None) == 0.0


# ---------------- blackout x stream composition ----------------


class TestBlackoutStorm:
    def test_armed_schedule_drains_exactly_once(self):
        fab = SessionFabric(shards=2, max_sessions=4)
        sched = blackout_storm_schedule(5, 1, 64, frac=0.2)
        fab.blackout(1, 2, storm=sched)
        assert fab.snapshot()["blackout_storms_armed"] == 1
        drained = fab.drain_storms()
        assert drained == [sched]
        assert fab.drain_storms() == []  # fanned out exactly once
        # counter is cumulative (obs plane), not a queue depth
        assert fab.snapshot()["blackout_storms_armed"] == 1

    def test_blackout_without_storm_stays_refusal_only(self):
        fab = SessionFabric(shards=2, max_sessions=4)
        fab.blackout(0, 1)
        assert fab.snapshot()["blackout_storms_armed"] == 0
        assert fab.drain_storms() == []


# ---------------- stream state travel ----------------


@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
class TestStreamStateTravel:
    @pytest.fixture(scope="class")
    def travel_trace(self, tmp_path_factory):
        path = str(tmp_path_factory.mktemp("travel") / "ev.trace")
        synth_event_trace(
            path, n_providers=96, n_tasks=96, events=12, seed=5,
            reconcile_every=6,
        )
        return tfmt.read_trace(path)

    def test_state_roundtrips_through_json(self, travel_trace):
        snap = travel_trace.snapshot
        events = _events_of(travel_trace)
        arena, w, _, _ = _open_arena(snap, "native-mt", 1)
        # gap_ceiling far above any real gap: config travel is under
        # test, not inline breach reconciles (those reset the cadence)
        eng = StreamEngine(arena, w, reconcile_every=6, gap_ceiling=1e9)
        for ev in events[:5]:
            assert not eng.apply(ev).deduped
        assert eng.events_since_reconcile == 5
        state = json.loads(json.dumps(eng.export_state()))

        arena2, w2, _, _ = _open_arena(snap, "native-mt", 1)
        eng2 = StreamEngine.from_state(arena2, w2, state)
        assert eng2.reconcile_every == 6 and eng2.gap_ceiling == 1e9
        assert eng2.events_since_reconcile == 5
        assert eng2.events_applied == 5
        # the traveled cursors enforce staleness: a retransmit of an
        # already-committed (source, seq) dedups at the re-armed engine
        assert eng2.apply(events[0]).deduped
        # ...and a genuinely fresh event still applies
        assert not eng2.apply(events[5]).deduped

    def test_cadence_cursor_rearms_the_due_flag(self, travel_trace):
        snap = travel_trace.snapshot
        arena, w, _, _ = _open_arena(snap, "native-mt", 1)
        eng = StreamEngine(arena, w, reconcile_every=4)
        state = eng.export_state()
        state["events_since_reconcile"] = 4  # flush raced the reconcile
        arena2, w2, _, _ = _open_arena(snap, "native-mt", 1)
        eng2 = StreamEngine.from_state(arena2, w2, state)
        assert eng2.reconcile_due and eng2.due_reason == "cadence"

    def test_cursor_export_cap_is_newest_and_counted(self, travel_trace):
        snap = travel_trace.snapshot
        arena, w, _, _ = _open_arena(snap, "native-mt", 1)
        eng = StreamEngine(arena, w, reconcile_every=1000)
        for i, ev in enumerate(_events_of(travel_trace)[:6]):
            eng.apply(ev)
        full = eng.export_state()["dedup"]
        capped = eng.export_state(max_cursor_sources=2)["dedup"]
        assert capped["truncated"] == len(full["sources"]) - 2
        assert capped["sources"] == full["sources"][-2:]


# ---------------- mass fan-out determinism ----------------


@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
class TestMassFanoutDeterminism:
    def test_hostile_interleavings_converge_bit_identical(
        self, tmp_path
    ):
        """The phase-A contract at unit grain: the same workload-event
        multiset plus the same mass storm, applied in two hostile
        interleavings (storm last vs storm FIRST, so every later
        workload event for a stormed source arrives superseded), must
        reconcile to bit-identical plans."""
        path = str(tmp_path / "mass.trace")
        synth_event_trace(
            path, n_providers=128, n_tasks=128, events=24, seed=11,
            reconcile_every=1000,
        )
        trace = tfmt.read_trace(path)
        snap = trace.snapshot
        events = _events_of(trace)
        rows = storm_rows(7, "blackout-shard1", snap.n_providers, 0.15)
        storm = mass_leave_events(0, rows, snap.p_cols)

        arena_a, w_a, _, _ = _open_arena(snap, "native-mt", 1)
        eng_a = StreamEngine(arena_a, w_a, reconcile_every=1000)
        for ev in events + storm:
            eng_a.apply(ev)
        plan_a = eng_a.reconcile().plan

        arena_b, w_b, _, _ = _open_arena(snap, "native-mt", 1)
        eng_b = StreamEngine(arena_b, w_b, reconcile_every=1000)
        deduped = 0
        for ev in storm + events:  # storm first: reordered delivery
            deduped += int(eng_b.apply(ev).deduped)
        plan_b = eng_b.reconcile().plan
        # stormed sources' workload events arrived superseded...
        stormed = {f"p{r}" for r in rows.tolist()}
        assert deduped == sum(
            1 for ev in events if ev.source in stormed
        )
        assert deduped > 0  # the interleaving was actually hostile
        # ...and the reconciled plans are bit-identical anyway
        np.testing.assert_array_equal(plan_a, plan_b)

    def test_storm_matches_extra_events_baseline(self, tmp_path):
        """The driver-side baseline (stream_replay extra_events) and a
        live engine fed the chaos'd order agree — the exact comparison
        the loadgen bit-identity gate performs."""
        path = str(tmp_path / "base.trace")
        synth_event_trace(
            path, n_providers=96, n_tasks=96, events=16, seed=3,
            reconcile_every=8,
        )
        trace = tfmt.read_trace(path)
        snap = trace.snapshot
        events = _events_of(trace)
        rows = storm_rows(2, "blackout-shard1", snap.n_providers, 0.1)
        storm = mass_leave_events(0, rows, snap.p_cols)
        rep = stream_replay(
            path, engine="native-mt", threads=1, reconcile_every=8,
            verify=False, final_reconcile=True, keep_recon_p4ts=True,
            extra_events=storm,
        )
        baseline = rep["recon_p4ts"][-1]

        arena, w, _, _ = _open_arena(snap, "native-mt", 1)
        eng = StreamEngine(arena, w, reconcile_every=8)
        # hostile: storm injected mid-stream, duplicates sprinkled in
        order = events[:5] + storm + events[3:] + storm[:1]
        for ev in order:
            eng.apply(ev)
        # the live arena answers the padded pow2 plan; the replay
        # reports real-row slices — compare on the real rows
        np.testing.assert_array_equal(
            eng.reconcile().plan[: snap.n_tasks], np.asarray(baseline)
        )


# ---------------- cross-process live migration ----------------


@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
class TestCrossProcessMigration:
    def _serve_pair(self, root):
        from protocol_tpu.services.scheduler_grpc import serve

        addr_a = f"127.0.0.1:{_free_port()}"
        addr_b = f"127.0.0.1:{_free_port()}"
        a = serve(addr_a, fleet=FleetConfig(
            shards=2, ckpt_dir=root, proc_id="p0", endpoint=addr_a))
        b = serve(addr_b, fleet=FleetConfig(
            shards=2, ckpt_dir=root, proc_id="p1", endpoint=addr_b))
        return (addr_a, a), (addr_b, b)

    def _open_stream(self, client, snap, sid, reconcile_every):
        req = snap.request_v2()
        req.stream_mode = True
        req.reconcile_every = reconcile_every
        w = tfmt._as_ns(dict(zip(
            ("price", "load", "proximity", "priority"), snap.weights
        )))
        fp = wire.epoch_fingerprint(
            snap.p_cols, snap.r_cols, w, snap.kernel, snap.top_k,
            snap.eps, snap.max_iters,
        )
        resp = client.open_session(
            iter(wire.chunk_snapshot(sid, fp, req)), timeout=60
        )
        assert resp.ok, resp.error
        return fp

    def _event_req(self, sid, fp, tick, ev):
        req = pb.AssignDeltaRequest(
            session_id=sid, epoch_fingerprint=fp, tick=tick,
            event_source=ev.source, event_seq=int(ev.seq),
            event_kind=ev.kind,
        )
        if ev.provider_rows.size:
            req.provider_rows.CopyFrom(
                wire.blob(ev.provider_rows, np.int32)
            )
            req.providers.CopyFrom(
                wire.encode_providers_v2(tfmt._as_ns(ev.p_cols))
            )
        if ev.task_rows.size:
            req.task_rows.CopyFrom(wire.blob(ev.task_rows, np.int32))
            req.requirements.CopyFrom(
                wire.encode_requirements_v2(tfmt._as_ns(ev.r_cols))
            )
        return req

    def test_stream_session_migrates_warm_with_dedup_twins(
        self, tmp_path
    ):
        """The satellite contract end to end on a real wire: Migrate
        mid-firehose, moved: redirect, warm re-arm at the target (zero
        reopens — the snapshot is never resent), a byte-identical
        retransmitted tick replays (CRC twin), an OLD (source, seq) at
        a fresh tick dedup-ACKs (traveled-cursor twin), and the
        reconcile boundaries land bit-identical to the fault-free
        single-process replay — including the boundary that fires AT
        THE TARGET, which is only aligned because the cadence cursor
        traveled."""
        from protocol_tpu.services.scheduler_grpc import (
            SchedulerBackendClient,
        )

        root = str(tmp_path / "journal")
        path = str(tmp_path / "mig.trace")
        synth_event_trace(
            path, n_providers=96, n_tasks=96, events=12, seed=8,
            reconcile_every=4,
        )
        trace = tfmt.read_trace(path)
        snap = trace.snapshot
        events = _events_of(trace)
        baseline = stream_replay(
            path, engine="native-mt", threads=1, reconcile_every=4,
            verify=False, final_reconcile=False, keep_recon_p4ts=True,
        )["recon_p4ts"]
        sid = "tenM@mig1"
        (addr_a, a), (addr_b, b) = self._serve_pair(root)
        ca = SchedulerBackendClient(addr_a)
        cb = SchedulerBackendClient(addr_b)
        try:
            fp = self._open_stream(ca, snap, sid, reconcile_every=4)
            recon_plans = []
            tick = 0
            for ev in events[:5]:
                tick += 1
                r = ca.assign_delta(
                    self._event_req(sid, fp, tick, ev), timeout=60
                )
                assert r.session_ok, r.error
                if r.reconciled:
                    recon_plans.append(np.frombuffer(
                        r.result.provider_for_task.data, np.int32
                    ))
            last_req = self._event_req(sid, fp, tick, events[4])

            # live migration mid-stream
            mig = ca.migrate(pb.MigrateRequest(
                target_endpoint=addr_b, target_proc_id="p1",
            ))
            assert mig.ok and mig.moved == 1
            # the origin answers moved:, never unknown
            r = ca.assign_delta(
                self._event_req(sid, fp, tick + 1, events[5]),
                timeout=60,
            )
            assert not r.session_ok
            assert r.error.startswith("moved:")
            assert addr_b in r.error

            # CRC twin: the byte-identical LAST tick resent at the
            # target replays from the rehydrated journal cursor
            r = cb.assign_delta(last_req, timeout=60)
            assert r.session_ok, r.error
            assert r.replayed

            # cursor twin: an OLD (source, seq) arriving as a FRESH
            # tick dedup-ACKs — only possible because the dedup
            # cursors traveled in the checkpoint META frame
            tick += 1
            r = cb.assign_delta(
                self._event_req(sid, fp, tick, events[1]), timeout=60
            )
            assert r.session_ok, r.error
            assert r.event_deduped

            # the target re-armed WARM: stream config + counters are
            # the origin's, not a fresh engine's
            session, _ = b.servicer.sessions.get(sid, fp)
            assert session is not None
            assert session.stream is not None
            assert session.stream.reconcile_every == 4
            assert session.stream.events_applied == 5

            # the rest of the firehose applies at the target; the
            # event-8 reconcile boundary fires HERE, aligned with the
            # fault-free replay by the traveled cadence cursor
            for ev in events[5:]:
                tick += 1
                r = cb.assign_delta(
                    self._event_req(sid, fp, tick, ev), timeout=60
                )
                assert r.session_ok, r.error
                assert not r.event_deduped
                if r.reconciled:
                    recon_plans.append(np.frombuffer(
                        r.result.provider_for_task.data, np.int32
                    ))

            assert len(recon_plans) == len(baseline) == 3
            for got, want in zip(recon_plans, baseline):
                np.testing.assert_array_equal(got, np.asarray(want))
            # zero reopens: the only open_session was the first one
            assert b.servicer.seam.snapshot().get(
                "session_session_migrated_out", 0
            ) == 0
            assert a.servicer.seam.snapshot().get(
                "session_session_migrated_out", 0
            ) == 1
        finally:
            ca.close()
            cb.close()
            a.stop(grace=None)
            b.stop(grace=None)
