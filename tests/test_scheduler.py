"""Scheduler tests: greedy-chain parity with the reference's semantics and
TPU batch-matcher behavior (bounded replicas + unbounded swarm tasks)."""


from protocol_tpu.models import (
    ComputeSpecs,
    CpuSpecs,
    GpuSpecs,
    SchedulingConfig,
    Task,
    TaskState,
    VolumeMount,
)
from protocol_tpu.sched import Scheduler, TpuBatchMatcher, expand_task_for_node
from protocol_tpu.store import NodeStatus, OrchestratorNode, StoreContext


def mk_node(addr, status=NodeStatus.HEALTHY, gpu_model=None, gpu_count=None):
    specs = None
    if gpu_model is not None:
        specs = ComputeSpecs(
            gpu=GpuSpecs(count=gpu_count, model=gpu_model, memory_mb=80000),
            cpu=CpuSpecs(cores=32),
            ram_mb=65536,
            storage_gb=1000,
        )
    return OrchestratorNode(address=addr, status=status, compute_specs=specs)


def mk_task(name, created_at, sched_plugins=None):
    return Task(
        name=name,
        image="img",
        created_at=created_at,
        state=TaskState.PENDING,
        scheduling_config=SchedulingConfig(plugins=sched_plugins) if sched_plugins else None,
    )


class TestGreedyChain:
    def test_newest_task_wins(self):
        ctx = StoreContext.new_test()
        ctx.node_store.add_node(mk_node("0xa"))
        old = mk_task("old", created_at=100)
        new = mk_task("new", created_at=200)
        ctx.task_store.add_task(old)
        ctx.task_store.add_task(new)
        sched = Scheduler(ctx)
        got = sched.get_task_for_node("0xa")
        assert got.name == "new"

    def test_no_tasks(self):
        ctx = StoreContext.new_test()
        ctx.node_store.add_node(mk_node("0xa"))
        assert Scheduler(ctx).get_task_for_node("0xa") is None

    def test_unknown_node(self):
        ctx = StoreContext.new_test()
        assert Scheduler(ctx).get_task_for_node("0xmissing") is None

    def test_env_cmd_volume_expansion(self):
        t = Task(
            name="t",
            image="img",
            env_vars={"OUT": "/data/${TASK_ID}/${NODE_ADDRESS}"},
            cmd=["run", "--id=${TASK_ID}"],
            volume_mounts=[VolumeMount("/h/${TASK_ID}", "/c")],
        )
        out = expand_task_for_node(t, "0xabc")
        assert out.env_vars["OUT"] == f"/data/{t.id}/0xabc"
        assert out.cmd[1] == f"--id={t.id}"
        assert out.volume_mounts[0].host_path == f"/h/{t.id}"
        # original untouched
        assert "${TASK_ID}" in t.env_vars["OUT"]


class TestTpuBatchMatcher:
    def test_unbounded_newest_parity(self):
        """With default weights (priority-dominant) the batch matcher gives
        every node the newest compatible task — the reference's behavior."""
        ctx = StoreContext.new_test()
        for i in range(4):
            ctx.node_store.add_node(mk_node(f"0x{i}", gpu_model="H100", gpu_count=8))
        ctx.task_store.add_task(mk_task("old", created_at=100))
        newest = mk_task("new", created_at=200)
        ctx.task_store.add_task(newest)

        matcher = TpuBatchMatcher(ctx)
        sched = Scheduler(ctx, batch_matcher=matcher)
        for i in range(4):
            got = sched.get_task_for_node(f"0x{i}")
            assert got is not None and got.name == "new"

    def test_compute_requirements_gate(self):
        ctx = StoreContext.new_test()
        ctx.node_store.add_node(mk_node("0xh", gpu_model="H100", gpu_count=8))
        ctx.node_store.add_node(mk_node("0xa", gpu_model="A100", gpu_count=8))
        h100_task = mk_task(
            "h100-only",
            created_at=300,
            sched_plugins={
                "tpu_scheduler": {"compute_requirements": ["gpu:count=8;gpu:model=H100"]}
            },
        )
        any_task = mk_task("any", created_at=100)
        ctx.task_store.add_task(any_task)
        ctx.task_store.add_task(h100_task)

        matcher = TpuBatchMatcher(ctx)
        sched = Scheduler(ctx, batch_matcher=matcher)
        assert sched.get_task_for_node("0xh").name == "h100-only"
        assert sched.get_task_for_node("0xa").name == "any"

    def test_bounded_replicas(self):
        """A 2-replica task absorbs exactly 2 nodes; the rest fall to the
        unbounded task."""
        ctx = StoreContext.new_test()
        for i in range(5):
            ctx.node_store.add_node(mk_node(f"0x{i}", gpu_model="H100", gpu_count=8))
        bounded = mk_task(
            "bounded",
            created_at=300,
            sched_plugins={"tpu_scheduler": {"replicas": ["2"]}},
        )
        swarm = mk_task("swarm", created_at=100)
        ctx.task_store.add_task(swarm)
        ctx.task_store.add_task(bounded)

        matcher = TpuBatchMatcher(ctx)
        matcher.refresh()
        names = []
        for i in range(5):
            node = ctx.node_store.get_node(f"0x{i}")
            names.append(matcher.task_for_node(node).name)
        assert names.count("bounded") == 2
        assert names.count("swarm") == 3

    def test_identical_nodes_fill_all_replicas(self):
        """Regression: with identically-specced nodes, exact cost ties made
        every open slot bid the SAME provider each auction round — one
        assignment per round, so a replica bound above max_iters seated
        exactly max_iters nodes (observed 300/400 live). tie_jitter in the
        dense solve decorrelates the targets."""
        ctx = StoreContext.new_test()
        n_nodes, replicas = 450, 350  # > the solve's 300-iteration cap
        for i in range(n_nodes):
            ctx.node_store.add_node(
                mk_node(f"0x{i:03d}", gpu_model="H100", gpu_count=8)
            )
        ctx.task_store.add_task(
            mk_task(
                "wide",
                created_at=100,
                sched_plugins={"tpu_scheduler": {"replicas": [str(replicas)]}},
            )
        )
        matcher = TpuBatchMatcher(ctx)
        matcher.refresh()
        seated = sum(
            1
            for i in range(n_nodes)
            if matcher.task_for_node(ctx.node_store.get_node(f"0x{i:03d}"))
            is not None
        )
        assert seated == replicas, seated

    def test_dirty_on_task_change(self):
        ctx = StoreContext.new_test()
        ctx.node_store.add_node(mk_node("0xa", gpu_model="H100", gpu_count=8))
        matcher = TpuBatchMatcher(ctx, min_solve_interval=0.0)
        matcher.attach_observers()
        sched = Scheduler(ctx, batch_matcher=matcher)
        assert sched.get_task_for_node("0xa") is None
        t = mk_task("late", created_at=100)
        ctx.task_store.add_task(t)
        got = sched.get_task_for_node("0xa")
        assert got is not None and got.name == "late"

    def test_no_schedulable_nodes(self):
        ctx = StoreContext.new_test()
        ctx.node_store.add_node(mk_node("0xa", status=NodeStatus.DEAD))
        ctx.task_store.add_task(mk_task("t", created_at=1))
        matcher = TpuBatchMatcher(ctx)
        matcher.refresh()
        assert matcher.last_solve_stats["nodes"] == 0
