"""Event-driven streaming assignment (ISSUE 15): the reconciliation
contract, certified-gap soundness, event idempotence under chaos, the
bounded-staleness watchdog, and the wire surface.

The load-bearing claims pinned here:

  * **Reconciliation bit-identity.** The stream engine's periodic full
    solve must equal a batch replay of the same event trace — a fresh
    always-cold arena solving the accumulated columns at the same
    boundaries — bit for bit, at threads {1, 2, 4}, on BOTH engines.
  * **Certified gap soundness.** The incremental tracker's bound must
    dominate the exact O(T*K) certificate at every event (an upper
    bound that ever dipped below the exact gap would be a lie with a
    CI gate built on it), and a ceiling-armed engine must never SERVE
    an answer above the ceiling (breach reconciles inline).
  * **Idempotence.** A duplicated or reordered (superseded) event must
    coalesce/dedup — acked, never double-applied — and a chaos'd
    (drop/dup/reorder) delivery of a whole stream must converge to the
    fault-free reconciled plan on both engines.
  * **Bounded staleness.** A starved reconcile (auto_reconcile off,
    cadence ignored) must flag and count every overdue answer.
"""

import socket

import numpy as np
import pytest

from protocol_tpu import native
from protocol_tpu.faults.plan import (
    ChaosConfig,
    FaultSchedule,
    event_delivery_order,
)
from protocol_tpu.obs.quality import duality_gap
from protocol_tpu.ops.cost import CostWeights
from protocol_tpu.stream.engine import StreamEngine
from protocol_tpu.stream.events import (
    SourceDedup,
    StreamEvent,
    coalesce,
    event_from_delta,
)
from protocol_tpu.stream.replay import (
    _events_of,
    _open_arena,
    batch_shadow_replay,
    stream_replay,
)
from protocol_tpu.trace import format as tfmt
from protocol_tpu.trace.synth import synth_event_trace

NATIVE = native.available()
pytestmark = pytest.mark.skipif(not NATIVE, reason="no native toolchain")


@pytest.fixture(scope="module")
def small_trace(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("stream") / "ev.trace")
    return synth_event_trace(
        path, n_providers=192, n_tasks=192, events=48, seed=9,
        heartbeat_w=0.6, join_w=0.15, leave_w=0.15, task_w=0.1,
        headroom=0.15, reconcile_every=16,
    )


# ---------------- event model ----------------


class TestEvents:
    def test_source_dedup_monotonic(self):
        d = SourceDedup()
        assert d.admit("p1", 0)
        assert d.admit("p1", 2)       # gaps fine: monotonic, not dense
        assert not d.admit("p1", 2)   # duplicate
        assert not d.admit("p1", 1)   # reordered (superseded)
        assert d.admit("p1", 3)
        assert d.admit("p2", 0)       # sources independent
        assert d.deduped == 2

    def test_source_dedup_lru_bound(self):
        d = SourceDedup(max_sources=4)
        for i in range(10):
            assert d.admit(f"s{i}", 0)
        assert len(d._seq) == 4

    def test_coalesce_latest_wins(self):
        def ev(seq, row, price):
            return StreamEvent(
                kind="heartbeat", source=f"p{row}", seq=seq,
                provider_rows=np.asarray([row], np.int32),
                p_cols={"price": np.asarray([price], np.float32)},
                task_rows=np.zeros(0, np.int32), r_cols={},
            )

        merged = coalesce([ev(0, 3, 1.0), ev(0, 5, 2.0), ev(1, 3, 9.0)])
        np.testing.assert_array_equal(
            merged.provider_rows, np.asarray([3, 5], np.int32)
        )
        # row 3's later event wins; row 5 keeps its only value
        np.testing.assert_array_equal(
            merged.p_cols["price"], np.asarray([9.0, 2.0], np.float32)
        )
        assert coalesce([]) is None

    def test_event_trace_roundtrip(self, small_trace):
        trace = tfmt.read_trace(small_trace)
        events = _events_of(trace)
        assert len(events) == 48
        seqs: dict = {}
        for ev in events:
            assert ev.kind in ("heartbeat", "join", "leave", "task")
            last = seqs.get(ev.source, -1)
            assert ev.seq == last + 1  # per-source strictly monotonic
            seqs[ev.source] = ev.seq
        at = [ev.at_us for ev in events]
        assert at == sorted(at) and at[0] > 0

    def test_event_trace_deterministic(self, tmp_path):
        a = synth_event_trace(
            str(tmp_path / "a.trace"), n_providers=64, n_tasks=64,
            events=12, seed=3,
        )
        b = synth_event_trace(
            str(tmp_path / "b.trace"), n_providers=64, n_tasks=64,
            events=12, seed=3,
        )
        with open(a, "rb") as fa, open(b, "rb") as fb:
            assert fa.read() == fb.read()


class TestEventChaos:
    def test_delivery_order_deterministic_and_complete(self):
        cfg = ChaosConfig.from_spec(
            "seed=7,drop=0.15,dup=0.15,reorder=0.2"
        )
        order1 = event_delivery_order(FaultSchedule(cfg), 40)
        order2 = event_delivery_order(FaultSchedule(cfg), 40)
        assert order1 == order2  # pure function of the seeded schedule
        # every event delivers at least once (convergence by
        # construction), duplicates appear exactly twice
        counts = {i: order1.count(i) for i in range(40)}
        assert all(c >= 1 for c in counts.values())
        assert any(c == 2 for c in counts.values())
        assert order1 != list(range(40))  # chaos actually reorders

    def test_inert_config_is_identity(self):
        cfg = ChaosConfig()
        assert event_delivery_order(FaultSchedule(cfg), 10) == list(
            range(10)
        )


# ---------------- the single-event arena entry ----------------


class TestApplyRows:
    def _primed(self, engine="auction", threads=0, n=128):
        import bench

        rng = np.random.default_rng(1)
        ep = bench.synth_providers(rng, n)
        er = bench.synth_requirements(rng, n)
        from protocol_tpu.native.arena import NativeSolveArena

        arena = NativeSolveArena(threads=threads, engine=engine)
        w = CostWeights()
        arena.solve(ep, er, w)
        return arena, w, ep, er

    def test_unprimed_refuses(self):
        from protocol_tpu.native.arena import NativeSolveArena

        arena = NativeSolveArena()
        with pytest.raises(RuntimeError, match="not primed"):
            arena.apply_rows(
                np.asarray([0], np.int32), {}, None, None, CostWeights()
            )

    def test_weights_mismatch_refuses(self):
        arena, w, ep, er = self._primed()
        other = CostWeights(price=2.0)
        with pytest.raises(ValueError, match="different weights"):
            arena.apply_rows(
                np.asarray([0], np.int32),
                {n_: np.asarray(getattr(ep, n_))[:1] for n_ in (
                    "gpu_count",)},
                None, None, other,
            )

    def test_noop_event_returns_carried_plan(self):
        arena, w, ep, er = self._primed()
        before = arena._p4t.copy()
        rr = np.asarray([3], np.int32)
        p_vals = {
            name: np.asarray(getattr(ep, name))[rr]
            for name in (
                "gpu_count", "gpu_mem_mb", "gpu_model_id", "has_gpu",
                "has_cpu", "cpu_cores", "ram_mb", "storage_gb", "lat",
                "lon", "has_location", "price", "load", "valid",
            )
        }
        out = arena.apply_rows(rr, p_vals, None, None, w)
        np.testing.assert_array_equal(out, before)
        assert arena.last_stats["changed_rows"] == 0
        assert arena.last_stats["cand_cold_passes"] == 0

    def test_event_repair_keeps_structure_exact(self):
        """After a churn event, the persistent candidate structure must
        equal a from-scratch rebuild on the current columns — the
        invariant everything else (reconcile bit-identity above all)
        stands on."""
        arena, w, ep, er = self._primed()
        rng = np.random.default_rng(5)
        rr = np.asarray([7], np.int32)
        p_vals = {
            name: np.asarray(getattr(ep, name))[rr].copy()
            for name in (
                "gpu_count", "gpu_mem_mb", "gpu_model_id", "has_gpu",
                "has_cpu", "cpu_cores", "ram_mb", "storage_gb", "lat",
                "lon", "has_location", "price", "load", "valid",
            )
        }
        p_vals["price"] = np.asarray(
            [rng.uniform(0.5, 4.0)], np.float32
        )
        arena.apply_rows(rr, p_vals, None, None, w)
        import protocol_tpu.native.arena as A

        n_p = arena._p_fields["gpu_count"].shape[0]
        rev_ref = np.zeros((n_p, arena.reverse_r), np.uint64)
        ref_p, ref_c = native.fused_topk_candidates(
            A._as_ns(arena._p_fields, A._P_SPEC),
            A._as_ns(arena._r_fields, A._R_SPEC),
            w, k=arena.k, threads=arena.threads, rev_out=rev_ref,
        )
        np.testing.assert_array_equal(arena._cand_p, ref_p)
        np.testing.assert_array_equal(arena._cand_c, ref_c)
        np.testing.assert_array_equal(arena._rev, rev_ref)

    def test_reconcile_equals_cold_solve(self):
        """reconcile() over the repaired structure == a cold batch
        solve on the current columns, bit for bit, both engines."""
        for engine in ("auction", "sinkhorn"):
            arena, w, ep, er = self._primed(engine=engine)
            rng = np.random.default_rng(6)
            price = np.asarray(ep.price).copy()
            rows = rng.choice(price.shape[0], 5, replace=False)
            for r in rows.tolist():
                rr = np.asarray([r], np.int32)
                p_vals = {
                    name: np.asarray(getattr(ep, name))[rr].copy()
                    for name in (
                        "gpu_count", "gpu_mem_mb", "gpu_model_id",
                        "has_gpu", "has_cpu", "cpu_cores", "ram_mb",
                        "storage_gb", "lat", "lon", "has_location",
                        "price", "load", "valid",
                    )
                }
                p_vals["price"] = np.asarray(
                    [rng.uniform(0.5, 4.0)], np.float32
                )
                price[r] = p_vals["price"][0]
                arena.apply_rows(rr, p_vals, None, None, w)
            got = arena.reconcile()
            import dataclasses

            from protocol_tpu.native.arena import NativeSolveArena

            cold = NativeSolveArena(
                threads=arena.threads, engine=engine
            )
            want = cold.solve(
                dataclasses.replace(ep, price=price), er, w
            )
            np.testing.assert_array_equal(got, want, err_msg=engine)


# ---------------- the reconciliation contract ----------------


class TestReconciliation:
    @pytest.mark.parametrize("threads", [1, 2, 4])
    def test_stream_reconcile_bit_identical_to_batch_shadow(
        self, small_trace, threads
    ):
        rep = stream_replay(
            small_trace, threads=threads, reconcile_every=16,
            keep_recon_p4ts=True, verify=False,
        )
        assert rep["cand_cold_passes"] == 0
        assert rep["reconciles"] >= 3
        shadow = batch_shadow_replay(
            small_trace, rep["recon_ticks"], threads=threads
        )
        assert len(shadow["p4ts"]) == len(rep["recon_p4ts"])
        for i, (a, b) in enumerate(
            zip(rep["recon_p4ts"], shadow["p4ts"])
        ):
            np.testing.assert_array_equal(
                a, b, err_msg=f"reconcile window {i}"
            )

    def test_sinkhorn_engine_reconciles_bit_identical(self, small_trace):
        rep = stream_replay(
            small_trace, engine="sinkhorn-mt", reconcile_every=24,
            keep_recon_p4ts=True, verify=False,
        )
        shadow = batch_shadow_replay(
            small_trace, rep["recon_ticks"], engine="sinkhorn-mt"
        )
        for a, b in zip(rep["recon_p4ts"], shadow["p4ts"]):
            np.testing.assert_array_equal(a, b)

    def test_replay_thread_invariance_via_recording(
        self, small_trace, tmp_path
    ):
        rec = str(tmp_path / "rec.trace")
        stream_replay(
            small_trace, threads=1, reconcile_every=16,
            record_path=rec, verify=False,
        )
        for threads in (2, 4):
            rep = stream_replay(rec, threads=threads)
            assert rep["divergence"] is None, rep["divergence"]
            assert rep["verified_events"] > 0

    def test_divergence_localizes_to_first_event(
        self, small_trace, tmp_path
    ):
        rec = str(tmp_path / "rec.trace")
        stream_replay(
            small_trace, reconcile_every=16, record_path=rec,
            verify=False,
        )
        # replaying under a DIFFERENT reconcile cadence diverges; the
        # report must name the first divergent event, not just "differs"
        rep = stream_replay(rec, reconcile_every=7)
        assert rep["divergence"] is not None
        assert rep["divergence"]["event"] >= 1
        assert rep["divergence"]["n_rows"] > 0


# ---------------- certified gap ----------------


class TestCertifiedGap:
    def test_tracker_dominates_exact_certificate(self, small_trace):
        """Soundness: the incremental bound must never dip below the
        exact O(T*K) certificate, at any event."""
        trace = tfmt.read_trace(small_trace)
        arena, w, _pp, _rp = _open_arena(trace.snapshot, "native-mt", 0)
        se = StreamEngine(arena, w, reconcile_every=10 ** 9)
        for ev in _events_of(trace):
            res = se.apply(ev)
            exact = duality_gap(
                arena._cand_p, arena._cand_c, arena._p4t, arena._price
            )
            assert res.gap_per_task + 1e-9 >= exact["gap_per_task"], (
                f"tracker {res.gap_per_task} below exact "
                f"{exact['gap_per_task']} at source {ev.source}"
            )

    def test_ceiling_breach_reconciles_inline(self, tmp_path):
        # a drift-dominant workload whose FRESH solves certify small
        # (~0.01/task) while streamed drift spikes past the ceiling —
        # the regime the ceiling contract exists for. (On workloads
        # where even a fresh full solve certifies above the ceiling,
        # the engine serves the reconciled plan — it cannot beat its
        # own full solve — which is why the contract is "<= ceiling OR
        # a fresh inline reconcile".)
        path = synth_event_trace(
            str(tmp_path / "mix.trace"), n_providers=256, n_tasks=256,
            events=48, seed=5, reconcile_every=16,
        )
        ceiling = 0.15
        rep = stream_replay(
            path, gap_ceiling=ceiling, reconcile_every=10 ** 6,
            verify=False,
        )
        # the ceiling (not the disabled cadence) triggered reconciles,
        # and no served answer ever exceeded it
        assert rep["reconciles"] >= 2
        assert rep["gap_max"] > ceiling  # breaches were observed...
        assert rep["gap_served_max"] <= ceiling + 1e-9  # ...never served

    def test_reconcile_rebases_gap(self, small_trace):
        trace = tfmt.read_trace(small_trace)
        arena, w, _pp, _rp = _open_arena(trace.snapshot, "native-mt", 0)
        se = StreamEngine(arena, w, reconcile_every=10 ** 9)
        for ev in _events_of(trace)[:20]:
            se.apply(ev)
        res = se.reconcile()
        exact = duality_gap(
            arena._cand_p, arena._cand_c, arena._p4t, arena._price
        )
        assert res.gap_per_task == pytest.approx(
            exact["gap_per_task"], abs=1e-6
        )
        assert se.events_since_reconcile == 0


# ---------------- idempotence under chaos ----------------


class TestIdempotence:
    @pytest.mark.parametrize("engine", ["native-mt", "sinkhorn-mt"])
    def test_chaosd_stream_converges_bit_identical(
        self, small_trace, engine
    ):
        base = stream_replay(
            small_trace, engine=engine, reconcile_every=16,
            keep_recon_p4ts=True, verify=False,
        )
        chaos = ChaosConfig.from_spec(
            "seed=3,drop=0.1,dup=0.12,reorder=0.1"
        )
        ch = stream_replay(
            small_trace, engine=engine, reconcile_every=16,
            chaos=chaos, verify=False, keep_recon_p4ts=True,
        )
        assert ch["deduped"] > 0  # the ladder actually fired
        np.testing.assert_array_equal(
            base["recon_p4ts"][-1], ch["recon_p4ts"][-1],
            err_msg=f"{engine}: chaos'd stream did not converge",
        )

    def test_duplicate_event_never_double_applies(self, small_trace):
        trace = tfmt.read_trace(small_trace)
        arena, w, _pp, _rp = _open_arena(trace.snapshot, "native-mt", 0)
        se = StreamEngine(arena, w, reconcile_every=10 ** 9)
        events = _events_of(trace)
        for ev in events[:10]:
            se.apply(ev)
        plan = arena._p4t.copy()
        price = np.asarray(arena._price).copy()
        res = se.apply(events[3])  # exact duplicate
        assert res.deduped
        np.testing.assert_array_equal(arena._p4t, plan)
        np.testing.assert_array_equal(np.asarray(arena._price), price)
        assert se.dedup.deduped == 1

    def test_burst_coalesces_and_commits_seqs(self, small_trace):
        trace = tfmt.read_trace(small_trace)
        arena, w, _pp, _rp = _open_arena(trace.snapshot, "native-mt", 0)
        se = StreamEngine(arena, w, reconcile_every=10 ** 9)
        events = _events_of(trace)[:6]
        res = se.apply_burst(events)
        assert not res.deduped
        # every burst member's seq committed: replaying any of them
        # dedups
        for ev in events:
            assert se.apply(ev).deduped


# ---------------- bounded staleness ----------------


class TestStalenessWatchdog:
    def test_starved_reconcile_flags_and_counts(self, small_trace):
        trace = tfmt.read_trace(small_trace)
        arena, w, _pp, _rp = _open_arena(trace.snapshot, "native-mt", 0)
        se = StreamEngine(
            arena, w, reconcile_every=8, max_stale_events=12,
            auto_reconcile=False,
        )
        events = _events_of(trace)
        stale_seen = 0
        for ev in events[:20]:
            res = se.apply(ev)
            if res.stale:
                stale_seen += 1
        assert se.reconcile_due and se.due_reason == "cadence"
        assert stale_seen == se.events_stale > 0
        # every answer past the bound was flagged
        assert stale_seen == 20 - 12
        se.reconcile()
        assert not se.reconcile_due
        res = se.apply(events[20])
        assert not res.stale


# ---------------- the wire surface ----------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestWireStream:
    @pytest.fixture(scope="class")
    def wire_setup(self, tmp_path_factory):
        from protocol_tpu.services.scheduler_grpc import (
            SchedulerBackendClient,
            serve,
        )

        path = str(tmp_path_factory.mktemp("wire") / "ev.trace")
        synth_event_trace(
            path, n_providers=128, n_tasks=128, events=20, seed=4,
            reconcile_every=8,
        )
        port = _free_port()
        server = serve(f"127.0.0.1:{port}")
        client = SchedulerBackendClient(f"127.0.0.1:{port}")
        yield client, tfmt.read_trace(path), server
        client.close()
        server.stop(grace=None)

    def _open_stream(self, client, snap, sid, reconcile_every=8):
        from protocol_tpu.proto import wire

        req = snap.request_v2()
        req.stream_mode = True
        req.reconcile_every = reconcile_every
        w = tfmt._as_ns(dict(zip(
            ("price", "load", "proximity", "priority"), snap.weights
        )))
        fp = wire.epoch_fingerprint(
            snap.p_cols, snap.r_cols, w, snap.kernel, snap.top_k,
            snap.eps, snap.max_iters,
        )
        chunks = list(wire.chunk_snapshot(sid, fp, req))
        resp = client.open_session(iter(chunks), timeout=60)
        assert resp.ok, resp.error
        return fp

    def _event_req(self, sid, fp, tick, ev):
        from protocol_tpu.proto import scheduler_pb2 as pb
        from protocol_tpu.proto import wire

        req = pb.AssignDeltaRequest(
            session_id=sid, epoch_fingerprint=fp, tick=tick,
            event_source=ev.source, event_seq=int(ev.seq),
            event_kind=ev.kind,
        )
        if ev.provider_rows.size:
            req.provider_rows.CopyFrom(
                wire.blob(ev.provider_rows, np.int32)
            )
            req.providers.CopyFrom(
                wire.encode_providers_v2(tfmt._as_ns(ev.p_cols))
            )
        if ev.task_rows.size:
            req.task_rows.CopyFrom(wire.blob(ev.task_rows, np.int32))
            req.requirements.CopyFrom(
                wire.encode_requirements_v2(tfmt._as_ns(ev.r_cols))
            )
        return req

    def test_stream_session_end_to_end(self, wire_setup):
        client, trace, server = wire_setup
        snap = trace.snapshot
        events = _events_of(trace)
        fp = self._open_stream(client, snap, "tenA@ws1")
        reconciles = 0
        tick = 0
        for ev in events:
            tick += 1
            r = client.assign_delta(
                self._event_req("tenA@ws1", fp, tick, ev), timeout=60
            )
            assert r.session_ok, r.error
            assert not r.event_deduped
            reconciles += int(r.reconciled)
            if r.reconciled:
                assert r.events_since_reconcile == 0
        assert reconciles == len(events) // 8

        # duplicate event as a NEW tick: acked deduped, never applied
        tick += 1
        r = client.assign_delta(
            self._event_req("tenA@ws1", fp, tick, events[0]), timeout=60
        )
        assert r.session_ok and r.event_deduped

        # per-event stream metrics landed in the obs registry
        snap_obs = server.servicer.obs.snapshot()
        stream_obs = snap_obs["sessions"]["tenA@ws1"].get("stream")
        assert stream_obs is not None
        assert stream_obs["event"]["count"] >= len(events)
        assert stream_obs["deduped"] == 1
        assert stream_obs["reconciled"] == reconciles

    def test_event_delta_on_batch_session_refused(self, wire_setup):
        client, trace, server = wire_setup
        snap = trace.snapshot
        from protocol_tpu.proto import wire

        req = snap.request_v2()  # no stream_mode
        w = tfmt._as_ns(dict(zip(
            ("price", "load", "proximity", "priority"), snap.weights
        )))
        fp = wire.epoch_fingerprint(
            snap.p_cols, snap.r_cols, w, snap.kernel, snap.top_k,
            snap.eps, snap.max_iters,
        )
        resp = client.open_session(
            iter(wire.chunk_snapshot("tenB@ws2", fp, req)), timeout=60
        )
        assert resp.ok
        ev = _events_of(trace)[0]
        r = client.assign_delta(
            self._event_req("tenB@ws2", fp, 1, ev), timeout=60
        )
        assert not r.session_ok
        assert "not stream-servable" in r.error

    def test_captured_stream_session_records_event_meta(
        self, tmp_path, monkeypatch
    ):
        """A flight-recorded stream session must land each event's
        {kind, source, seq} meta in its DELTA frames — so the capture
        replays as a STREAM trace (event_from_delta finds the meta),
        never as a meta-less batch trace that full-solves every tick."""
        from protocol_tpu.services.scheduler_grpc import (
            SchedulerBackendClient,
            serve,
        )

        trace_path = str(tmp_path / "capture.trace")
        monkeypatch.setenv("PROTOCOL_TPU_TRACE", trace_path)
        path = synth_event_trace(
            str(tmp_path / "src.trace"), n_providers=96, n_tasks=96,
            events=4, seed=6, reconcile_every=100,
        )
        trace = tfmt.read_trace(path)
        events = _events_of(trace)
        port = _free_port()
        server = serve(f"127.0.0.1:{port}")
        client = SchedulerBackendClient(f"127.0.0.1:{port}")
        try:
            fp = self._open_stream(
                client, trace.snapshot, "tenT@cap1",
                reconcile_every=100,
            )
            for tick, ev in enumerate(events, start=1):
                r = client.assign_delta(
                    self._event_req("tenT@cap1", fp, tick, ev),
                    timeout=60,
                )
                assert r.session_ok, r.error
            server.servicer.trace.close()
        finally:
            client.close()
            server.stop(grace=None)
        captured = tfmt.read_trace(trace_path)
        got = [event_from_delta(d) for d in captured.deltas]
        assert all(g is not None for g in got)
        assert [(g.kind, g.source, g.seq) for g in got] == [
            (ev.kind, ev.source, ev.seq) for ev in events
        ]

    def test_retransmitted_event_tick_replays(self, wire_setup):
        """Transport-level chaos: the SAME event tick resent (a dropped
        response) must hit the PR 9 retransmit dedup — replayed twin,
        applied exactly once — composing with the event-seq ladder."""
        client, trace, server = wire_setup
        snap = trace.snapshot
        events = _events_of(trace)
        fp = self._open_stream(
            client, snap, "tenC@ws3", reconcile_every=1000
        )
        req = self._event_req("tenC@ws3", fp, 1, events[0])
        r1 = client.assign_delta(req, timeout=60)
        assert r1.session_ok and not r1.replayed
        r2 = client.assign_delta(req, timeout=60)  # byte-identical resend
        assert r2.session_ok and r2.replayed
        np.testing.assert_array_equal(
            np.frombuffer(
                r1.result.provider_for_task.data, np.int32
            ),
            np.frombuffer(
                r2.result.provider_for_task.data, np.int32
            ),
        )


# ---------------- checkpoint re-arm ----------------


class TestStreamCheckpoint:
    def test_stream_config_survives_flush_and_load(self, tmp_path):
        import bench
        from protocol_tpu.faults.checkpoint import SessionCheckpointer
        from protocol_tpu.native.arena import NativeSolveArena
        from protocol_tpu.services.session_store import SolveSession
        from protocol_tpu.proto import wire as _wire

        rng = np.random.default_rng(2)
        ep = bench.synth_providers(rng, 64)
        er = bench.synth_requirements(rng, 64)
        w = CostWeights()
        arena = NativeSolveArena(threads=1)
        p_cols = _wire.canon_columns(ep, _wire.P_WIRE_DTYPES)
        r_cols = _wire.canon_columns(er, _wire.R_WIRE_DTYPES)
        p4t = arena.solve(
            tfmt._as_ns(p_cols), tfmt._as_ns(r_cols), w
        )
        session = SolveSession(
            session_id="t@ck1", fingerprint="fp", weights=w,
            kernel="native-mt:1", threads=1, top_k=64,
            p_cols=p_cols, r_cols=r_cols, n_providers=64, n_tasks=64,
            arena=arena, tick=3,
        )
        session.last_p4t = np.asarray(p4t, np.int32)
        session.stream = StreamEngine(
            arena, w, reconcile_every=17, gap_ceiling=0.5
        )
        ckpt = SessionCheckpointer(str(tmp_path), proc_id="p0")
        with session.lock:
            assert ckpt.flush_locked(session)
        loaded = ckpt.load_one("t@ck1")
        assert loaded is not None
        assert loaded.stream is not None
        assert loaded.stream.reconcile_every == 17
        assert loaded.stream.gap_ceiling == 0.5
        # the re-armed engine is live: an event applies
        ev_rows = np.asarray([1], np.int32)
        vals = {
            name: np.asarray(p_cols[name])[ev_rows].copy()
            for name in p_cols
        }
        vals["price"] = np.asarray([3.3], np.float32)
        res = loaded.stream.apply(StreamEvent(
            kind="heartbeat", source="p1", seq=0,
            provider_rows=ev_rows, p_cols=vals,
            task_rows=np.zeros(0, np.int32), r_cols={},
        ))
        assert not res.deduped
