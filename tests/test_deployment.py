"""Deployment artifacts: chart structure/values sanity and the serve
entry points (reference deployment/k8s + per-service binaries)."""

import os
import re
import subprocess
import sys

import importlib.util
import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
K8S = os.path.join(REPO, "deployment", "k8s")
CHARTS = ["discovery-chart", "orchestrator-chart", "validator-chart",
          "scheduler-chart", "kv-chart"]


@pytest.mark.parametrize("chart", CHARTS)
def test_chart_structure_and_values(chart):
    base = os.path.join(K8S, chart)
    meta = yaml.safe_load(open(os.path.join(base, "Chart.yaml")))
    assert meta["apiVersion"] == "v2" and meta["name"].startswith("protocol-tpu")
    values = yaml.safe_load(open(os.path.join(base, "values.yaml")))
    assert "image" in values
    templates = os.listdir(os.path.join(base, "templates"))
    assert "deployment.yaml" in templates and "service.yaml" in templates


@pytest.mark.parametrize("chart", CHARTS)
def test_templates_reference_defined_values(chart):
    """Every .Values.x.y referenced by a template must exist in
    values.yaml (the cheap half of `helm lint` without helm)."""
    base = os.path.join(K8S, chart)
    values = yaml.safe_load(open(os.path.join(base, "values.yaml")))
    for name in os.listdir(os.path.join(base, "templates")):
        text = open(os.path.join(base, "templates", name)).read()
        for m in re.finditer(r"\.Values\.([A-Za-z0-9_.]+)", text):
            node = values
            for part in m.group(1).split("."):
                assert isinstance(node, dict) and part in node, (
                    f"{chart}/templates/{name} references undefined "
                    f".Values.{m.group(1)}"
                )
                node = node[part]


def test_scheduler_chart_places_on_tpu_node_pool():
    text = open(
        os.path.join(K8S, "scheduler-chart", "templates", "deployment.yaml")
    ).read()
    assert "cloud.google.com/gke-tpu-accelerator" in text
    assert "google.com/tpu" in text


def test_serve_cli_surface():
    """Arg parsing + required env/flag validation, without booting."""
    env = dict(os.environ, PROTOCOL_TPU_VERSION="9.9-test")
    out = subprocess.run(
        [sys.executable, "-m", "protocol_tpu.serve", "--version"],
        capture_output=True, text=True, env=env, cwd=REPO,
    )
    assert "9.9-test" in out.stdout

    # missing ledger url fails loudly, not at first request
    out2 = subprocess.run(
        [sys.executable, "-m", "protocol_tpu.serve", "discovery",
         "--pool-id", "0"],
        capture_output=True, text=True, cwd=REPO,
        env={k: v for k, v in os.environ.items() if k != "LEDGER_URL"},
    )
    assert out2.returncode != 0
    assert "ledger-url" in out2.stderr.lower()



# Environment guard for the marked tests below: their code paths reach
# protocol_tpu.chain / protocol_tpu.security (wallet signing), which
# need the third-party `cryptography` package. Without it they skip —
# the rest of this module runs everywhere.
_HAS_CRYPTO = importlib.util.find_spec("cryptography") is not None
requires_crypto = pytest.mark.skipif(
    not _HAS_CRYPTO,
    reason="cryptography not installed (signing/TLS dependency)",
)

@requires_crypto
def test_serve_discovery_boots_against_live_ledger_api(tmp_path):
    """Multi-process shape: ledger API in-process, discovery booted via
    the serve entry point in a SUBPROCESS (the pod shape), health-checked
    over HTTP, then shut down."""
    import asyncio
    import json
    import threading
    import time
    import urllib.request

    from aiohttp import web

    from protocol_tpu.chain import Ledger
    from protocol_tpu.services.ledger_api import LedgerApiService

    ledger = Ledger()
    did = ledger.create_domain("d")
    pid = ledger.create_pool(did, "0xc", "0xm", "")
    ready = threading.Event()
    state = {}

    def run_api():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        async def boot():
            svc = LedgerApiService(ledger)
            runner = web.AppRunner(svc.make_app())
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            state["port"] = runner.addresses[0][1]
            ready.set()

        loop.run_until_complete(boot())
        loop.run_forever()

    threading.Thread(target=run_api, daemon=True).start()
    assert ready.wait(10)

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [sys.executable, "-m", "protocol_tpu.serve", "discovery",
         "--ledger-url", f"http://127.0.0.1:{state['port']}",
         "--pool-id", str(pid), "--port", str(port)],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        deadline = time.time() + 30
        last = None
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/health", timeout=1
                ) as resp:
                    last = json.loads(resp.read())
                    break
            except Exception:
                time.sleep(0.3)
        assert last == {"status": "ok"}, last
    finally:
        proc.terminate()
        proc.wait(timeout=10)
