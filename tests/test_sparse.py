"""Sparse top-K pipeline tests: candidate generation vs brute force, full-K
parity with the dense auction, restricted-graph quality vs scipy optimum."""

import numpy as np
import pytest
from scipy.optimize import linear_sum_assignment

import jax
import jax.numpy as jnp

from protocol_tpu.ops.assign import assign_auction
from protocol_tpu.ops.cost import INFEASIBLE, CostWeights, cost_matrix
from protocol_tpu.ops.sparse import assign_auction_sparse, assign_topk, candidates_topk
from protocol_tpu.ops.encoding import FeatureEncoder, compat_mask

from tests.test_assign import check_feasible, matching_cost, random_cost
from tests.test_encoding import random_requirements, random_specs


def encode_random_marketplace(seed, P, T):
    import random

    rng = random.Random(seed)
    enc = FeatureEncoder()
    ep = enc.encode_providers([random_specs(rng) for _ in range(P)])
    er = enc.encode_requirements([random_requirements(rng) for _ in range(T)])
    return ep, er


def jittered_cost(cost: np.ndarray) -> np.ndarray:
    """Replicates the kernel's deterministic tie-breaking jitter."""
    P, T = cost.shape
    p = np.arange(P, dtype=np.uint32)[:, None]
    t = np.arange(T, dtype=np.uint32)[None, :]
    h = (p * np.uint32(2654435761)) ^ (t * np.uint32(40503))
    jit = (h & np.uint32(1023)).astype(np.float32) * np.float32(1e-7)
    return np.where(cost < INFEASIBLE * 0.5, cost + jit, cost).astype(np.float32)


class TestCandidates:
    def test_matches_bruteforce_topk(self):
        ep, er = encode_random_marketplace(0, 32, 16)
        cand_p, cand_c = candidates_topk(ep, er, k=8, tile=8)
        cost = jittered_cost(np.asarray(cost_matrix(ep, er, CostWeights())[0]))
        for t in range(16):
            order = np.argsort(cost[:, t], kind="stable")[:8]
            expected = [int(p) if cost[p, t] < INFEASIBLE * 0.5 else -1 for p in order]
            got = list(np.asarray(cand_p)[t])
            assert got == expected, f"task {t}: {got} vs {expected}"
            feas = [i for i, p in enumerate(expected) if p >= 0]
            np.testing.assert_allclose(
                np.asarray(cand_c)[t][feas], cost[order, t][feas], rtol=1e-6
            )

    def test_identical_providers_not_capped_at_k(self):
        """Degenerate marketplace: N identical providers must not collapse
        every task's candidate list to the same k entries."""
        from protocol_tpu.models.node import ComputeRequirements, ComputeSpecs, CpuSpecs, GpuSpecs
        from protocol_tpu.ops.sparse import assign_topk

        enc = FeatureEncoder()
        spec = ComputeSpecs(
            gpu=GpuSpecs(count=8, model="H100", memory_mb=80000),
            cpu=CpuSpecs(cores=32), ram_mb=65536, storage_gb=1000,
        )
        ep = enc.encode_providers([spec] * 16)
        er = enc.encode_requirements(
            [ComputeRequirements.parse("gpu:count=8;gpu:model=H100")] * 8
        )
        res = assign_topk(ep, er, k=4, tile=8, eps=0.01)
        assert int(np.asarray(res.provider_for_task >= 0).sum()) == 8

    def test_tile_divisibility_enforced(self):
        ep, er = encode_random_marketplace(1, 8, 10)
        with pytest.raises(ValueError):
            candidates_topk(ep, er, k=4, tile=4)

    def test_approx_recall_selection(self):
        """approx_recall routes selection through lax.approx_max_k (the
        TPU-native PartialReduce targeting the measured stage-A top_k
        bottleneck). On CPU the lowering is exact, so the candidate sets
        must match lax.top_k's bit-for-bit; the real win is measured
        on-chip (SCALING.md)."""
        import jax

        if jax.devices()[0].platform != "cpu":
            pytest.skip("set-equality only holds on the exact CPU lowering")
        ep, er = encode_random_marketplace(7, 64, 32)
        exact_p, exact_c = candidates_topk(ep, er, k=8, tile=8)
        approx_p, approx_c = candidates_topk(
            ep, er, k=8, tile=8, approx_recall=0.95
        )
        # same candidate SETS per task (row order may differ between the
        # two reduction algorithms)
        for t in range(32):
            assert set(np.asarray(exact_p)[t].tolist()) == set(
                np.asarray(approx_p)[t].tolist()
            ), f"task {t}"
        # feasibility downstream: the approx sets drive a full solve
        res = assign_auction_sparse(
            approx_p, approx_c, num_providers=64, eps=0.05, max_iters=3000
        )
        assert int(np.asarray(res.provider_for_task >= 0).sum()) > 0


class TestSparseAuction:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_full_k_parity_with_dense(self, seed):
        rng = np.random.default_rng(seed)
        P, T = 32, 32
        cost = random_cost(rng, P, T, p_infeasible=0.2)
        # build full candidate lists (k = P) sorted by cost, as topk would
        order = np.argsort(cost, axis=0, kind="stable").T  # [T, P]
        cand_c = np.take_along_axis(cost.T, order, axis=1).astype(np.float32)
        cand_p = np.where(cand_c < INFEASIBLE * 0.5, order.astype(np.int32), -1)

        # frontier >= T + no retirement = the dense Jacobi schedule exactly
        res_sparse = assign_auction_sparse(
            jnp.asarray(cand_p), jnp.asarray(cand_c), num_providers=P,
            eps=0.05, max_iters=5000, frontier=T, retire=False,
        )
        res_dense = assign_auction(jnp.asarray(cost), eps=0.05, max_iters=5000)
        check_feasible(res_sparse, cost)
        np.testing.assert_array_equal(
            np.asarray(res_sparse.provider_for_task),
            np.asarray(res_dense.provider_for_task),
        )

    @pytest.mark.parametrize("seed", [0, 1])
    def test_restricted_quality(self, seed):
        """k=16 of 64 providers: matching cost within a few % of optimal."""
        rng = np.random.default_rng(seed)
        n = 64
        cost = rng.uniform(0.0, 10.0, size=(n, n)).astype(np.float32)
        order = np.argsort(cost, axis=0, kind="stable").T[:, :16]
        cand_c = np.take_along_axis(cost.T, order, axis=1).astype(np.float32)
        cand_p = order.astype(np.int32)
        res = assign_auction_sparse(
            jnp.asarray(cand_p), jnp.asarray(cand_c), num_providers=n,
            eps=0.01, max_iters=5000, frontier=16,
        )
        p4t = check_feasible(res, cost)
        assert (p4t >= 0).sum() >= n - 2  # near-perfect matching on 25% graph
        ri, ci = linear_sum_assignment(cost)
        opt = cost[ri, ci].sum()
        got = matching_cost(cost, p4t)
        assert got <= opt * 1.10 + n * 0.011, f"sparse {got} vs optimal {opt}"


class TestScaledAuction:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_near_optimal(self, seed):
        from protocol_tpu.ops.sparse import assign_auction_sparse_scaled

        rng = np.random.default_rng(seed)
        n = 64
        cost = rng.uniform(0, 10, size=(n, n)).astype(np.float32)
        order = np.argsort(cost, axis=0, kind="stable").T
        cand_c = np.take_along_axis(cost.T, order, axis=1).astype(np.float32)
        cand_p = order.astype(np.int32)
        res = assign_auction_sparse_scaled(
            jnp.asarray(cand_p), jnp.asarray(cand_c), num_providers=n,
            eps_end=0.005,
        )
        p4t = check_feasible(res, cost)
        assert (p4t >= 0).all()
        ri, ci = linear_sum_assignment(cost)
        opt = cost[ri, ci].sum()
        got = matching_cost(cost, p4t)
        assert got <= opt + n * 0.006, f"scaled auction {got} vs optimal {opt}"

    def test_contention_full_utilization(self):
        from protocol_tpu.ops.sparse import assign_auction_sparse_scaled

        rng = np.random.default_rng(7)
        cost = random_cost(rng, 16, 64, p_infeasible=0.3)  # oversubscribed
        order = np.argsort(cost, axis=0, kind="stable").T
        cand_c = np.take_along_axis(cost.T, order, axis=1).astype(np.float32)
        cand_p = np.where(cand_c < INFEASIBLE * 0.5, order.astype(np.int32), -1)
        res = assign_auction_sparse_scaled(
            jnp.asarray(cand_p), jnp.asarray(cand_c), num_providers=16,
        )
        p4t = check_feasible(res, cost)
        assert (p4t >= 0).sum() == 16  # every provider seated


class TestEndToEndTopk:
    def test_pipeline_feasibility_and_compat(self):
        ep, er = encode_random_marketplace(3, 48, 32)
        res = assign_topk(ep, er, k=8, tile=8, eps=0.05, max_iters=3000)
        mask = np.asarray(compat_mask(ep, er))
        p4t = np.asarray(res.provider_for_task)
        used = set()
        for t, p in enumerate(p4t):
            if p >= 0:
                assert mask[p, t], f"incompatible assignment t={t} p={p}"
                assert p not in used
                used.add(p)


class TestStallDetection:
    def test_unfillable_tail_ends_phase_early(self):
        """Per-task retirement cannot stop an unfillable tail (the open
        'hole' wanders the graph via eviction chains), so phases used to
        grind to max_iters with one open task. stall_limit ends the phase
        after N no-progress rounds instead."""
        from protocol_tpu.ops.sparse import _sparse_auction_phase

        # 3 tasks fighting over 2 providers: one permanent hole
        cand_p = jnp.asarray([[0, 1], [0, 1], [0, 1]], jnp.int32)
        cand_c = jnp.asarray([[1.0, 2.0], [1.1, 2.1], [1.2, 2.2]], jnp.float32)
        state, stall = _sparse_auction_phase(
            cand_p, cand_c, 2, None, eps=0.5, max_iters=5000,
            frontier=4, retire=False, stall_limit=16,
        )
        rounds = int(state[0])
        assigned = int(np.asarray(state[3] >= 0).sum())
        assert assigned == 2  # both providers seated
        assert rounds < 200, f"phase should stall out early, ran {rounds}"
        assert int(stall) >= 16  # the exit is observable, not silent

    def test_stall_disabled_by_default(self):
        """stall_limit=0 preserves the run-to-cap semantics the plain
        kernel's callers rely on."""
        from protocol_tpu.ops.sparse import _sparse_auction_phase

        cand_p = jnp.asarray([[0, 1], [0, 1], [0, 1]], jnp.int32)
        cand_c = jnp.asarray([[1.0, 2.0], [1.1, 2.1], [1.2, 2.2]], jnp.float32)
        state, _stall = _sparse_auction_phase(
            cand_p, cand_c, 2, None, eps=0.5, max_iters=300,
            frontier=4, retire=False, stall_limit=0,
        )
        assert int(state[0]) == 300  # ground to the cap, as before


class TestBidirCandidates:
    """Bidirectional candidate generation (stage-B completeness, VERDICT r3
    item 3): forward top-k alone coverage-caps the matching when costs are
    price-dominated — every task's window holds the same cheap providers
    and expensive rows get NO edges. Reverse (provider->task) edges
    guarantee every provider a path into the graph."""

    @staticmethod
    def _priced_marketplace(P, T, seed=0):
        """Identical specs, wide price spread: the adversarial shape for
        forward-only coverage (all tasks rank providers identically up to
        tie jitter)."""
        from protocol_tpu.models.node import (
            ComputeRequirements, ComputeSpecs, CpuSpecs, GpuSpecs,
        )

        enc = FeatureEncoder()
        spec = ComputeSpecs(
            gpu=GpuSpecs(count=8, model="H100", memory_mb=80000),
            cpu=CpuSpecs(cores=32), ram_mb=65536, storage_gb=1000,
        )
        rng = np.random.default_rng(seed)
        prices = rng.uniform(0.1, 10.0, size=P).tolist()
        ep = enc.encode_providers([spec] * P, prices=prices)
        er = enc.encode_requirements(
            [ComputeRequirements.parse("gpu:count=8;gpu:model=H100")] * T
        )
        return ep, er

    def test_reverse_edges_match_bruteforce(self):
        """Mirrors the tile-POOLED reverse semantics exactly: each tile
        contributes its per-provider top-ceil(r/n_tiles), the final edges
        are the best r of the pool (with the first edge therefore the
        true global best)."""
        from protocol_tpu.ops.sparse import candidates_topk_reverse

        P, T, tile, r = 24, 16, 8, 3
        ep, er = encode_random_marketplace(11, P, T)
        _, _, rev_t, rev_c = candidates_topk_reverse(
            ep, er, k=4, tile=tile, reverse_r=r
        )
        cost = jittered_cost(np.asarray(cost_matrix(ep, er, CostWeights())[0]))
        rev_t, rev_c = np.asarray(rev_t), np.asarray(rev_c)
        n_tiles = T // tile
        rt = -(-r // n_tiles)
        for p in range(P):
            pool = []
            for g in range(n_tiles):
                seg = cost[p, g * tile:(g + 1) * tile]
                for j in np.argsort(seg, kind="stable")[:rt]:
                    pool.append((float(seg[j]), g * tile + int(j)))
            pool.sort(key=lambda e: e[0])
            expected = [
                t if c < INFEASIBLE * 0.5 else -1 for c, t in pool[:r]
            ]
            assert rev_t[p].tolist() == expected, f"provider {p}"
            feas = [i for i, t in enumerate(expected) if t >= 0]
            np.testing.assert_allclose(
                rev_c[p][feas], [pool[i][0] for i in feas], rtol=1e-6
            )
            # the first edge is the true global best (exactness property
            # the pooling preserves)
            if expected and expected[0] >= 0:
                assert expected[0] == int(
                    np.argsort(cost[p], kind="stable")[0]
                )

    def test_merge_scatter_exact_and_deduped(self):
        """Per task, the merged extra columns hold the cheapest <=extra
        reverse edges targeting it — minus edges duplicating a forward
        candidate (a dup makes v1==v2 in the bid math, collapsing bid
        increments to +eps; measured slower AND worse at 4k)."""
        from protocol_tpu.ops.sparse import merge_reverse_candidates

        T, K, P, r, extra = 6, 2, 8, 4, 2
        rng = np.random.default_rng(3)
        cand_p = rng.integers(0, P, size=(T, K)).astype(np.int32)
        cand_c = rng.uniform(0, 1, size=(T, K)).astype(np.float32)
        rev_t = rng.integers(-1, T, size=(P, r)).astype(np.int32)
        rev_c = rng.uniform(0, 1, size=(P, r)).astype(np.float32)
        mp, mc = merge_reverse_candidates(
            jnp.asarray(cand_p), jnp.asarray(cand_c),
            jnp.asarray(rev_t), jnp.asarray(rev_c), extra=extra,
        )
        mp, mc = np.asarray(mp), np.asarray(mc)
        assert mp.shape == (T, K + extra)
        np.testing.assert_array_equal(mp[:, :K], cand_p)
        for t in range(T):
            edges = sorted(
                (float(rev_c[p, j]), int(p))
                for p in range(P)
                for j in range(r)
                if rev_t[p, j] == t and p not in cand_p[t]
            )[:extra]
            got = [
                (round(float(mc[t, K + i]), 6), int(mp[t, K + i]))
                for i in range(extra)
                if mp[t, K + i] >= 0
            ]
            expected = [(round(c, 6), p) for c, p in edges]
            assert got == expected, f"task {t}: {got} vs {expected}"

    def test_bidir_restores_coverage_and_completeness(self):
        """P=T with k<<P and price-dominated costs: forward-only coverage
        (and therefore assignment) caps at ~k; bidir restores full
        coverage AND the auction achieves the graph's maximum matching
        (100% here — production defaults at a production-sparse size;
        below ~1k the matcher routes through the dense solver anyway).
        Mirrors the measured 65k result: 99.98% vs forward-only 66.5%."""
        import scipy.sparse as _sp
        from scipy.sparse.csgraph import maximum_bipartite_matching

        from protocol_tpu.ops.sparse import (
            assign_auction_sparse_scaled,
            candidates_topk,
            candidates_topk_bidir,
        )

        P = T = 1024
        k = 8
        ep, er = self._priced_marketplace(P, T)
        fp, _ = candidates_topk(ep, er, k=k, tile=256)
        fwd_cov = np.unique(np.asarray(fp)[np.asarray(fp) >= 0]).size
        assert fwd_cov < P * 0.25, f"forward coverage {fwd_cov} not capped"

        bp, bc = candidates_topk_bidir(
            ep, er, k=k, tile=256, reverse_r=8, extra=16
        )
        bpn = np.asarray(bp)
        bidir_cov = np.unique(bpn[bpn >= 0]).size
        assert bidir_cov == P, f"bidir coverage {bidir_cov} != {P}"

        # graph capacity: the bidir candidate graph must admit a (near-)
        # perfect matching — this is what reverse_r buys
        rows, cols = np.nonzero(bpn >= 0)[0], bpn[bpn >= 0]
        g = _sp.csr_matrix(
            (np.ones(len(rows)), (rows, cols)), shape=(T, P)
        )
        maxm = int((maximum_bipartite_matching(g, perm_type="column") >= 0).sum())
        assert maxm >= T * 0.99, f"graph max matching only {maxm}/{T}"

        res = assign_auction_sparse_scaled(bp, bc, num_providers=P)
        p4t = np.asarray(res.provider_for_task)
        assigned = int((p4t >= 0).sum())
        # the auction must realize the graph's capacity, not just beat a bar
        assert assigned >= maxm - 2, f"auction {assigned} vs max {maxm}"
        assert assigned >= T * 0.99, f"bidir assigned only {assigned}/{T}"
        pos = p4t[p4t >= 0]
        assert np.unique(pos).size == pos.size  # injective matching


class TestAdaptiveFrontierLadder:
    """_phase_adaptive: segment-wise frontier shrink with host-side stall
    accounting (the per-segment stall_limit static would re-trace the
    kernel every boundary)."""

    def test_breaker_accumulates_across_segments(self):
        """With retirement off, an unfillable hole stalls forever; the
        host-side breaker must accumulate whole-segment stalls and trip
        at a limit LARGER than one segment (a single 256-round segment
        alone can never reach it), and report the ACCUMULATED count."""
        from protocol_tpu.ops.sparse import _phase_adaptive

        cand_p = jnp.asarray([[0, 1], [0, 1], [0, 1]], jnp.int32)
        cand_c = jnp.asarray(
            [[1.0, 2.0], [1.1, 2.1], [1.2, 2.2]], jnp.float32
        )
        state, stall = _phase_adaptive(
            cand_p, cand_c, 2, None, eps=0.5, max_iters=100_000,
            frontier=4, retire=False, stall_limit=600,
        )
        rounds = int(state[0])
        assert int(np.asarray(state[3] >= 0).sum()) == 2  # seated
        assert rounds < 100_000, "breaker must trip before the cap"
        assert int(stall) >= 600, "accumulated (not per-segment) stall"

    def test_quality_parity_with_fixed_frontier(self):
        """The ladder is a schedule change, not a semantics change: same
        near-optimal quality as the fixed-frontier path."""
        from scipy.optimize import linear_sum_assignment

        from protocol_tpu.ops.sparse import assign_auction_sparse_scaled

        rng = np.random.default_rng(3)
        n = 128
        cost = rng.uniform(0, 10, size=(n, n)).astype(np.float32)
        order = np.argsort(cost, axis=0, kind="stable").T
        cand_c = np.take_along_axis(cost.T, order, axis=1).astype(np.float32)
        cand_p = order.astype(np.int32)
        ri, ci = linear_sum_assignment(cost)
        opt = cost[ri, ci].sum()
        for ladder in (False, True):
            res = assign_auction_sparse_scaled(
                jnp.asarray(cand_p), jnp.asarray(cand_c), num_providers=n,
                eps_end=0.005, frontier_ladder=ladder,
            )
            p4t = np.asarray(res.provider_for_task)
            assert (p4t >= 0).all()
            got = sum(cost[p4t[t], t] for t in range(n))
            assert got <= opt + n * 0.006, f"ladder={ladder}: {got} vs {opt}"


class TestWarmColdRegression:
    """VERDICT r4 item 2: the warm (incremental) solve must actually be
    cheaper than the cold ladder in the contended T=P geometry — r4
    measured warm 5.5x SLOWER at 65k. Root causes, both pinned here:
    (a) the carried-price clamp flattened the top of the price
    distribution (65,535/65,536 prices clipped), so the eps-CS repair
    evicted ~60k seeds for 655 churned tasks — fixed by a uniform
    downshift that preserves every price difference; (b) auction winners
    sit EXACTLY on the eps-CS boundary (value = v2 - eps by bid
    construction), so a tolerance-free repair at the same eps evicted
    ~half the matching on float dust — fixed by a float-scale tolerance
    in _unassign_unhappy."""

    def _contended_instance(self, T=2048, k=8):
        from protocol_tpu.ops.sparse import candidates_topk_bidir

        ep, er = TestBidirCandidates._priced_marketplace(T, T)
        return candidates_topk_bidir(ep, er, k=k, tile=256, reverse_r=8, extra=16)

    def test_warm_chain_mechanisms_after_churn(self):
        """The three warm-chain mechanisms, each deterministic at CI size.
        The headline warm-vs-cold WALL bar (>= 2x at 16k/65k) lives in the
        gated scale suite (test_scale_matcher.py) and the per-round
        scaling artifact -- at T=2048 the cold ladder is only a few
        hundred rounds and the warm path's fixed stall budget dominates,
        so a wall comparison here would measure the breaker, not the
        incremental machinery."""
        from protocol_tpu.ops.sparse import (
            assign_auction_sparse_scaled,
            assign_auction_sparse_warm,
        )

        bp, bc = self._contended_instance()
        T = bc.shape[0]
        stats_cold: dict = {}
        res, price, retired = assign_auction_sparse_scaled(
            bp, bc, num_providers=T, with_state=True, stats_out=stats_cold
        )
        cold_assigned = int(np.asarray(res.provider_for_task >= 0).sum())
        # this instance has an unfillable tail -- the retired mask must be
        # non-trivial for the carry assertion below to mean anything
        assert int(np.asarray(retired).sum()) > 0

        p4t0 = jnp.asarray(res.provider_for_task).at[: T // 100].set(-1)

        def warm(**kw):
            stats: dict = {}
            r, _ = assign_auction_sparse_warm(
                bp, bc, num_providers=T, price0=price, p4t0=p4t0,
                stats_out=stats, **kw,
            )
            return int(np.asarray(r.provider_for_task >= 0).sum()), stats

        a_plain, s_plain = warm()
        a_carry, s_carry = warm(retired0=retired)

        # 1. retirement carry strictly cuts the re-fought tail
        assert s_carry["rounds_total"] < s_plain["rounds_total"], (
            f"carry {s_carry['rounds_total']} !< plain {s_plain['rounds_total']}"
        )
        # 2. quality parity: the incremental solve matches the cold ladder
        assert a_carry >= cold_assigned - 2
        assert a_plain >= cold_assigned - 2
        # 3. the warm cost is bounded by delta work + one stall budget --
        #    NOT by a from-scratch fine-eps solve (the r4 regression was
        #    11k+ rounds here-equivalent); segment granularity adds < 256
        assert s_carry["rounds_total"] <= stats_cold["rounds_total"] + 512 + 256, (
            f"warm {s_carry['rounds_total']} vs cold {stats_cold['rounds_total']}"
        )

    def test_repair_keeps_boundary_seeds_at_same_eps(self):
        """A converged solve re-admitted at the SAME eps must evict ZERO
        unchurned seeds: winners sit exactly on the eps-CS boundary, and
        only float dust separates them from 'unhappy'."""
        from protocol_tpu.ops.sparse import (
            _invert,
            _unassign_unhappy,
            assign_auction_sparse_scaled,
        )

        bp, bc = self._contended_instance(T=1024)
        T = bc.shape[0]
        res, price = assign_auction_sparse_scaled(
            bp, bc, num_providers=T, with_prices=True
        )
        p4t = jnp.asarray(res.provider_for_task)
        _, kept = _unassign_unhappy(bp, bc, price, _invert(p4t, T), p4t, 0.02)
        evicted = int((np.asarray(p4t) >= 0).sum()) - int(
            (np.asarray(kept) >= 0).sum()
        )
        assert evicted == 0, f"{evicted} seeds evicted at unchanged eps"

    def test_downshift_preserves_price_order(self):
        """Carried prices far above the retirement guard must arrive
        shifted, not clamped: relative order intact, max at the guard
        level."""
        from protocol_tpu.ops.sparse import assign_auction_sparse_warm

        cand_p = jnp.asarray([[0, 1], [1, 0], [2, -1]], jnp.int32)
        cand_c = jnp.asarray([[1.0, 2.0], [1.0, 2.0], [1.5, 0.0]], jnp.float32)
        # wildly ratcheted prices with distinct gaps, chosen so every
        # seed stays eps-CS happy in relative terms (nothing re-bids)
        price0 = jnp.asarray([1000.0, 1001.0, 1000.5], jnp.float32)
        p4t0 = jnp.asarray([0, 1, 2], jnp.int32)
        res, price = assign_auction_sparse_warm(
            cand_p, cand_c, num_providers=3, price0=price0, p4t0=p4t0
        )
        # seeds were eps-CS-consistent in RELATIVE terms; nothing re-bids,
        # so the returned prices are exactly the downshifted carries
        pr = np.asarray(price)
        np.testing.assert_allclose(pr[1] - pr[0], 1.0, atol=1e-4)
        np.testing.assert_allclose(pr[2] - pr[0], 0.5, atol=1e-4)
        assert pr.max() <= 2.0 + 5.0 + 1e-4  # finite_max + 5 guard
        assert (np.asarray(res.provider_for_task) == [0, 1, 2]).all()
