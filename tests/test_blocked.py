"""Blocked (matrix-free) Sinkhorn: potential parity with the dense kernel
and end-to-end matching quality."""

import numpy as np
import pytest
from scipy.optimize import linear_sum_assignment

import jax.numpy as jnp

from protocol_tpu.ops.assign import sinkhorn_plan
from protocol_tpu.ops.blocked import (
    assign_sinkhorn_blocked,
    sinkhorn_potentials_blocked,
)
from protocol_tpu.ops.cost import CostWeights, cost_matrix

from tests.test_assign import check_feasible, matching_cost
from tests.test_sparse import encode_random_marketplace


def test_plan_matches_dense_sinkhorn():
    """Blocked potentials reproduce the dense kernel's transport plan."""
    ep, er = encode_random_marketplace(0, 32, 32)
    cost, _ = cost_matrix(ep, er, CostWeights())
    eps, iters = 0.1, 80

    u, v = sinkhorn_potentials_blocked(
        ep, er, CostWeights(), eps=eps, num_iters=iters, tile=8
    )
    plan_blocked = np.asarray(
        jnp.exp(
            jnp.where(cost < 5e8, -cost / eps, -1e18)
            + u[:, None]
            + v[None, :]
        )
    )
    plan_dense = np.asarray(sinkhorn_plan(cost, eps=eps, num_iters=iters))
    np.testing.assert_allclose(plan_blocked, plan_dense, atol=1e-4)


def test_blocked_assignment_quality():
    rng = np.random.default_rng(1)
    ep, er = encode_random_marketplace(3, 48, 48)
    res = assign_sinkhorn_blocked(
        ep, er, eps=0.05, num_iters=100, tile=8, k=16
    )
    cost = np.asarray(cost_matrix(ep, er, CostWeights())[0])
    p4t = check_feasible(res, cost)
    # compare against the optimal on the feasible subproblem
    big = np.where(cost < 5e8, cost, 1e6).astype(np.float64)
    ri, ci = linear_sum_assignment(big)
    opt = sum(big[r, c] for r, c in zip(ri, ci) if big[r, c] < 1e5)
    got = matching_cost(cost, p4t)
    n_opt = sum(1 for r, c in zip(ri, ci) if big[r, c] < 1e5)
    assert (p4t >= 0).sum() >= n_opt - 2
    assert got <= opt * 1.25 + 2.0, f"blocked sinkhorn {got} vs optimal {opt}"


def test_tile_divisibility():
    ep, er = encode_random_marketplace(2, 8, 10)
    with pytest.raises(ValueError):
        sinkhorn_potentials_blocked(ep, er, tile=4)


class TestCostPairs:
    def test_matches_dense_cost_matrix(self):
        """cost_pairs must agree with the dense tensor entry-for-entry,
        including unassigned rows and the tail of a non-tile-multiple T
        (it is the quality instrument for shapes where [P, T] cannot
        exist)."""
        import numpy as np
        import jax.numpy as jnp

        from protocol_tpu.ops.cost import (
            INFEASIBLE,
            CostWeights,
            cost_matrix,
            cost_pairs,
        )
        from tests.test_sparse import encode_random_marketplace

        ep, er = encode_random_marketplace(3, 96, 100)
        w = CostWeights()
        dense, _ = cost_matrix(ep, er, w)
        rng = np.random.default_rng(0)
        p4t = rng.integers(-1, 96, size=100).astype(np.int32)
        got = np.asarray(cost_pairs(ep, er, jnp.asarray(p4t), w))
        want = np.where(
            p4t >= 0,
            np.asarray(dense)[np.maximum(p4t, 0), np.arange(100)],
            INFEASIBLE,
        )
        np.testing.assert_allclose(got, want, rtol=1e-6)
