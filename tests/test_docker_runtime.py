"""DockerRuntime lifecycle against the fake docker CLI: confighash
identity, env/volume injection, stale removal, state mapping, restart
backoff (reference worker/src/docker/service.rs:56-295,
docker_manager.rs)."""

import pytest

# Environment guard: this module's import chain reaches
# protocol_tpu.security / protocol_tpu.utils.tls, which need the
# third-party `cryptography` package (wallet signing + TLS material).
# On hosts without it, report the whole module as SKIPPED instead of a
# collection error (tier-1 keeps an honest skip count; CI installs
# cryptography and runs everything).
pytest.importorskip(
    "cryptography", reason="cryptography not installed (signing/TLS dependency)"
)

import asyncio
import json
import os
import stat
import sys

import pytest

from protocol_tpu.models.task import Task, TaskState, VolumeMount
from protocol_tpu.services.docker_runtime import DockerRuntime


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


@pytest.fixture()
def fake_docker(tmp_path):
    """Wrapper script invoking tests/fake_docker.py with a per-test state
    file; returns (docker_bin_path, state_loader)."""
    state_file = tmp_path / "docker_state.json"
    script = tmp_path / "docker"
    fake = os.path.join(os.path.dirname(__file__), "fake_docker.py")
    # -S skips site hooks: the ambient sitecustomize imports jax (~2 s),
    # which would otherwise tax every fake docker invocation
    script.write_text(
        "#!/bin/sh\n"
        f"FAKE_DOCKER_STATE={str(state_file)!r} "
        f"exec {sys.executable} -S {fake!r} \"$@\"\n"
    )
    script.chmod(script.stat().st_mode | stat.S_IEXEC)

    def state():
        return json.loads(state_file.read_text())

    state.path = str(state_file)
    return str(script), state


def make_task(tid="t1", image="busybox", cmd=None, env=None, volumes=None):
    return Task(
        id=tid,
        name=f"task-{tid}",
        image=image,
        cmd=cmd or ["echo", "hi"],
        env_vars=env or {},
        volume_mounts=volumes,
    )


def test_start_injects_identity_env_volumes(fake_docker, tmp_path):
    docker_bin, state = fake_docker
    rt = DockerRuntime(
        socket_path=str(tmp_path / "sock" / "metrics.sock"),
        docker_bin=docker_bin,
        system_memory_mb=1024,
    )
    task = make_task(
        env={"FOO": "x", "SOCK": "${SOCKET_PATH}"},
        cmd=["serve", "--sock", "${SOCKET_PATH}"],
        volumes=[VolumeMount(host_path="/data/in", container_path="/in")],
    )
    run(rt.apply(task, "0xnode"))

    name = rt.container_name(task)
    assert name.startswith("prime-task-") and "-t1-" in name
    c = state()["containers"][name]
    sock = str(tmp_path / "sock" / "metrics.sock")
    # ${SOCKET_PATH} expanded in env values and cmd (service.rs:185-201)
    assert c["env"]["SOCK"] == sock
    assert c["cmd"] == ["serve", "--sock", sock]
    assert c["env"]["NODE_ADDRESS"] == "0xnode"
    assert c["env"]["PRIME_TASK_ID"] == "t1"
    assert c["env"]["PRIME_MONITOR__SOCKET__PATH"] == sock
    # socket dir + task volumes mounted (service.rs:203-221)
    sock_dir = os.path.dirname(sock)
    assert f"{sock_dir}:{sock_dir}" in c["volumes"]
    assert "/data/in:/in" in c["volumes"]
    # shm = RAM/2 (service.rs:222-228)
    assert ("--shm-size", str(1024 * 1024 * 1024 // 2)) in [
        tuple(f) for f in c["flags"]
    ]
    # host networking default (docker_manager.rs:397-401)
    assert ("--network", "host") in [tuple(f) for f in c["flags"]]

    tid, ts, details = rt.state()
    assert (tid, ts) == ("t1", TaskState.RUNNING)
    assert details.container_status == "running"
    assert run(rt.get_logs())  # logs fetched on demand, not per tick


def test_config_change_replaces_container(fake_docker):
    docker_bin, state = fake_docker
    rt = DockerRuntime(docker_bin=docker_bin)
    t1 = make_task(env={"V": "1"})
    run(rt.apply(t1, "0xn"))
    old_name = rt.container_name(t1)
    assert old_name in state()["containers"]

    # same task id, new env -> new confighash -> old container removed
    t2 = make_task(env={"V": "2"})
    rt.last_started = 0.0  # get past the restart backoff
    run(rt.apply(t2, "0xn"))
    new_name = rt.container_name(t2)
    assert new_name != old_name
    containers = state()["containers"]
    assert new_name in containers and old_name not in containers


def test_stale_containers_removed_and_none_clears(fake_docker):
    docker_bin, state = fake_docker
    rt = DockerRuntime(docker_bin=docker_bin)
    t1 = make_task(tid="a")
    run(rt.apply(t1, "0xn"))
    assert state()["containers"]
    run(rt.apply(None, "0xn"))
    assert state()["containers"] == {}
    assert rt.state() == (None, TaskState.UNKNOWN, None)


def test_exit_code_maps_to_completed_or_failed(fake_docker):
    docker_bin, state = fake_docker
    rt = DockerRuntime(docker_bin=docker_bin)

    done = make_task(tid="ok", env={"FAKE_EXIT": "0"})
    run(rt.apply(done, "0xn"))
    _, ts, details = rt.state()
    assert ts == TaskState.COMPLETED and details.exit_code == 0

    rt2 = DockerRuntime(docker_bin=docker_bin)
    bad = make_task(tid="bad", env={"FAKE_EXIT": "3"})
    run(rt2.apply(bad, "0xn"))
    _, ts2, details2 = rt2.state()
    assert ts2 == TaskState.FAILED and details2.exit_code == 3
    assert rt2.failures == 1
    # failure count rises only on state CHANGES (service.rs:283-295);
    # within the backoff window the crashed container is left in place
    run(rt2.apply(bad, "0xn"))
    assert rt2.failures == 1
    assert rt2.state()[1] == TaskState.FAILED

    # past the backoff, the crashed container is removed and restarted
    rt2.last_started = 0.0
    run(rt2.apply(bad, "0xn"))
    # fake docker restarts it with FAKE_EXIT again -> exited; the failure
    # transition FAILED->FAILED doesn't double count, but the restart
    # attempt happened (a fresh container id)
    _, ts3, details3 = rt2.state()
    assert ts3 == TaskState.FAILED
    assert details3.container_id != details2.container_id


def test_restart_backoff_blocks_immediate_restart(fake_docker):
    docker_bin, state = fake_docker
    rt = DockerRuntime(docker_bin=docker_bin)
    task = make_task(tid="r")
    run(rt.apply(task, "0xn"))
    name = rt.container_name(task)

    # container vanishes (e.g. external rm); within backoff -> PENDING,
    # no restart attempt
    s = state()
    del s["containers"][name]
    with open(state.path, "w") as f:
        json.dump(s, f)

    run(rt.apply(task, "0xn"))
    assert rt.state()[1] == TaskState.PENDING
    assert name not in state()["containers"]

    # past the backoff -> restarted
    rt.last_started = 0.0
    run(rt.apply(task, "0xn"))
    assert name in state()["containers"]
    assert rt.state()[1] == TaskState.RUNNING


def test_explicit_restart_and_gpu_flag(fake_docker):
    docker_bin, state = fake_docker
    rt = DockerRuntime(docker_bin=docker_bin, gpu_device_ids=["0", "1"])
    task = make_task(tid="g", env={"FAKE_EXIT": "1"})
    run(rt.apply(task, "0xn"))
    name = rt.container_name(task)
    c = state()["containers"][name]
    assert ("--gpus", "device=0,1") in [tuple(f) for f in c["flags"]]

    run(rt.restart_task())
    assert state()["containers"][name]["status"] == "running"


def test_two_workers_share_daemon_without_mutual_teardown(fake_docker):
    """Workers sharing one dockerd (devnet) must not reconcile away each
    other's containers: identity is scoped per node address."""
    docker_bin, state = fake_docker
    rt_a = DockerRuntime(docker_bin=docker_bin)
    rt_b = DockerRuntime(docker_bin=docker_bin)
    ta, tb = make_task(tid="a"), make_task(tid="b")
    run(rt_a.apply(ta, "0xaaaa1111"))
    run(rt_b.apply(tb, "0xbbbb2222"))
    # both containers alive after each side reconciles again
    run(rt_a.apply(ta, "0xaaaa1111"))
    run(rt_b.apply(tb, "0xbbbb2222"))
    names = set(state()["containers"])
    assert rt_a.container_name(ta) in names
    assert rt_b.container_name(tb) in names
    assert rt_a.state()[1] == TaskState.RUNNING
    assert rt_b.state()[1] == TaskState.RUNNING


def test_entrypoint_without_cmd_gets_no_sleep_fallback(fake_docker):
    docker_bin, state = fake_docker
    rt = DockerRuntime(docker_bin=docker_bin)
    task = make_task(tid="e", cmd=[])
    task.cmd = None
    task.entrypoint = ["/app/run.sh"]
    run(rt.apply(task, "0xn"))
    c = state()["containers"][rt.container_name(task)]
    assert c["entrypoint"] == "/app/run.sh"
    assert c["cmd"] == []  # no bogus "sleep infinity" args to the entrypoint


def test_docker_unavailable_reports_unknown_not_stale(fake_docker, tmp_path):
    docker_bin, state = fake_docker
    rt = DockerRuntime(docker_bin=docker_bin)
    t1 = make_task(tid="s1")
    run(rt.apply(t1, "0xn"))
    assert rt.state()[1] == TaskState.RUNNING

    # daemon dies; a new task is applied: state must not echo t1's RUNNING
    rt.cli.docker_bin = str(tmp_path / "missing-docker")
    t2 = make_task(tid="s2")
    run(rt.apply(t2, "0xn"))
    tid, ts, details = rt.state()
    assert (tid, ts) == ("s2", TaskState.UNKNOWN)
    assert any("docker unavailable" in line for line in rt.logs)


def test_worker_agent_heartbeat_with_docker_runtime(fake_docker):
    """DockerRuntime behind the real WorkerAgent heartbeat application
    path (the e2e seam MockRuntime covers elsewhere)."""
    from protocol_tpu.services.worker import WorkerAgent
    from protocol_tpu.security import Wallet
    from protocol_tpu.chain import Ledger

    docker_bin, state = fake_docker
    ledger = Ledger()
    provider, node = Wallet.from_seed(b"dp"), Wallet.from_seed(b"dn")
    ledger.mint(provider.address, 1000)
    did = ledger.create_domain("d")
    creator, manager = Wallet.from_seed(b"dc"), Wallet.from_seed(b"dm")
    pid = ledger.create_pool(did, creator.address, manager.address, "")
    ledger.register_provider(provider.address, 100)
    ledger.add_compute_node(provider.address, node.address)

    rt = DockerRuntime(docker_bin=docker_bin)
    agent = WorkerAgent(provider, node, ledger, pid, runtime=rt)
    task = make_task(tid="hb")
    run(agent.runtime.apply(task, agent.node_wallet.address))
    tid, ts, details = agent.runtime.state()
    assert (tid, ts) == ("hb", TaskState.RUNNING)
    assert details.container_id.startswith("cid-")


def test_colocated_slots_do_not_sweep_each_other(fake_docker):
    """Ladder #5 on docker: a node's primary (slotless) and colocated
    extra (slotted) runtimes share one scope; each one's stale-container
    reconcile must never remove the sibling's container, and a departing
    extra's apply(None) must clean ONLY its own slot."""
    docker_bin, state = fake_docker
    addr = "0xabcdef0123456789"
    primary = DockerRuntime(docker_bin=docker_bin)
    extra = DockerRuntime(docker_bin=docker_bin, slot="c0ffee12")
    ta, tb = make_task(tid="aaaa1111"), make_task(tid="bbbb2222")

    run(primary.apply(ta, addr))
    run(extra.apply(tb, addr))
    names = set(state()["containers"])
    assert primary.container_name(ta) in names
    assert extra.container_name(tb) in names
    assert "s" + extra.slot + "-" in extra.container_name(tb)

    # reconcile ticks on BOTH sides: nothing of the sibling's is removed
    run(primary.reconcile_once(addr))
    run(extra.reconcile_once(addr))
    names = set(state()["containers"])
    assert primary.container_name(ta) in names
    assert extra.container_name(tb) in names

    # departing extra: apply(None) sweeps its own slot only
    run(extra.apply(None, addr))
    names = set(state()["containers"])
    assert extra.container_name(tb) not in names
    assert primary.container_name(ta) in names

    # primary task switch: its own old container goes, the (readded)
    # extra's survives. Zero the restart backoff so the re-starts happen
    # on THIS tick (the deferral is orthogonal to slot isolation).
    extra.last_started = 0.0
    run(extra.apply(tb, addr))
    tc = make_task(tid="cccc3333")
    primary.last_started = 0.0
    run(primary.apply(tc, addr))
    names = set(state()["containers"])
    assert primary.container_name(ta) not in names
    assert primary.container_name(tc) in names
    assert extra.container_name(tb) in names
