"""Ladder #5: multi-resource vector bin-pack + anti-affinity.

VERDICT r2 item 4 done-bar: an assign_* variant passing a randomized
feasibility + optimality-gap test at 10k scale, with CPU-oracle parity
(SURVEY §4 test strategy). Demands/capacities are integer-valued floats
so f32 kernel arithmetic is exact against the f64 oracle.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from protocol_tpu.ops.binpack import (
    assign_binpack_ffd,
    binpack_oracle,
    ffd_demand_order,
)
from protocol_tpu.ops.cost import INFEASIBLE


def random_instance(rng, P, T, R=4, compat=0.7, group_frac=0.0, n_locs=None):
    cost = rng.uniform(1.0, 10.0, (P, T)).astype(np.float32)
    cost[rng.uniform(size=(P, T)) > compat] = INFEASIBLE
    demand = rng.integers(1, 4, (T, R)).astype(np.float32)
    # sized so total capacity ~= 1.4x total demand per resource: a loose
    # but contended instance (some providers/resources still bind)
    capacity = rng.integers(8, 21, (P, R)).astype(np.float32)
    if group_frac > 0:
        n_groups = max(T // 8, 1)
        anti = np.where(
            rng.uniform(size=T) < group_frac,
            rng.integers(0, n_groups, T),
            -1,
        ).astype(np.int32)
    else:
        n_groups, anti = 1, np.full(T, -1, np.int32)
    loc = (
        rng.integers(0, n_locs, P).astype(np.int32)
        if n_locs
        else np.arange(P, dtype=np.int32)
    )
    return cost, demand, capacity, anti, loc, n_groups, (n_locs or P)


def check_feasible(cost, demand, capacity, anti, loc, p4t):
    used_cap = np.zeros_like(capacity)
    seen = set()
    for t, p in enumerate(p4t):
        if p < 0:
            continue
        assert cost[p, t] < INFEASIBLE * 0.5, "incompatible assignment"
        used_cap[p] += demand[t]
        g = int(anti[t])
        if g >= 0:
            key = (int(loc[p]), g)
            assert key not in seen, "anti-affinity violated"
            seen.add(key)
    assert (used_cap <= capacity + 1e-6).all(), "capacity exceeded"


class TestOracleParity:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
    def test_parity_randomized(self, seed):
        rng = np.random.default_rng(seed)
        cost, demand, capacity, anti, loc, G, L = random_instance(
            rng, P=64, T=192, group_frac=0.4
        )
        res = assign_binpack_ffd(
            jnp.asarray(cost), jnp.asarray(demand), jnp.asarray(capacity),
            anti_group=jnp.asarray(anti), loc_id=jnp.asarray(loc),
            num_locations=L, num_groups=G,
        )
        got = np.asarray(res.provider_for_task)
        want, want_cap = binpack_oracle(
            cost, demand, capacity, anti_group=anti, loc_id=loc
        )
        np.testing.assert_array_equal(got, want)
        np.testing.assert_allclose(
            np.asarray(res.remaining_capacity), want_cap, atol=1e-5
        )

    def test_multiple_tasks_per_provider(self):
        # one provider, capacity for exactly 3 unit tasks
        cost = np.full((1, 4), 1.0, np.float32)
        demand = np.ones((4, 1), np.float32)
        capacity = np.array([[3.0]], np.float32)
        res = assign_binpack_ffd(
            jnp.asarray(cost), jnp.asarray(demand), jnp.asarray(capacity)
        )
        p4t = np.asarray(res.provider_for_task)
        assert (p4t >= 0).sum() == 3  # 4th task refused: capacity, not slots
        assert float(res.remaining_capacity[0, 0]) == 0.0


class TestAntiAffinity:
    def test_group_spreads_across_providers(self):
        # 3 providers with huge capacity; 3 same-group tasks must spread
        cost = np.full((3, 3), 1.0, np.float32)
        demand = np.ones((3, 2), np.float32)
        capacity = np.full((3, 2), 100.0, np.float32)
        anti = np.zeros(3, np.int32)
        res = assign_binpack_ffd(
            jnp.asarray(cost), jnp.asarray(demand), jnp.asarray(capacity),
            anti_group=jnp.asarray(anti), num_groups=1,
        )
        p4t = np.asarray(res.provider_for_task)
        assert sorted(p4t.tolist()) == [0, 1, 2]

    def test_group_larger_than_domains_leaves_surplus_unassigned(self):
        cost = np.full((2, 3), 1.0, np.float32)
        demand = np.ones((3, 1), np.float32)
        capacity = np.full((2, 1), 100.0, np.float32)
        anti = np.zeros(3, np.int32)
        res = assign_binpack_ffd(
            jnp.asarray(cost), jnp.asarray(demand), jnp.asarray(capacity),
            anti_group=jnp.asarray(anti), num_groups=1,
        )
        p4t = np.asarray(res.provider_for_task)
        assert (p4t >= 0).sum() == 2

    def test_location_level_exclusion(self):
        # 4 providers in 2 locations; a 2-task group lands in DISTINCT
        # locations even though 4 distinct providers exist
        cost = np.full((4, 2), 1.0, np.float32)
        cost[2:, :] = 0.5  # providers 2,3 cheaper — both in location 1
        demand = np.ones((2, 1), np.float32)
        capacity = np.full((4, 1), 100.0, np.float32)
        anti = np.zeros(2, np.int32)
        loc = np.array([0, 0, 1, 1], np.int32)
        res = assign_binpack_ffd(
            jnp.asarray(cost), jnp.asarray(demand), jnp.asarray(capacity),
            anti_group=jnp.asarray(anti), loc_id=jnp.asarray(loc),
            num_locations=2, num_groups=1,
        )
        p4t = np.asarray(res.provider_for_task)
        assert {int(loc[p]) for p in p4t} == {0, 1}


class TestScale10k:
    def test_feasibility_and_gap_at_10k(self):
        rng = np.random.default_rng(7)
        cost, demand, capacity, anti, loc, G, L = random_instance(
            rng, P=2048, T=10240, group_frac=0.2, n_locs=256
        )
        res = assign_binpack_ffd(
            jnp.asarray(cost), jnp.asarray(demand), jnp.asarray(capacity),
            anti_group=jnp.asarray(anti), loc_id=jnp.asarray(loc),
            num_locations=L, num_groups=G,
        )
        p4t = np.asarray(res.provider_for_task)
        check_feasible(cost, demand, capacity, anti, loc, p4t)
        assigned = p4t >= 0
        # capacity-utilization sanity: most tasks place on this loose
        # instance (total demand ~0.75x total capacity)
        assert assigned.mean() > 0.5
        # optimality gap vs the capacity-free lower bound: each assigned
        # task's cost >= its min compatible cost, so LB = sum of row minima
        # over assigned tasks. FFD must stay within 2x of LB here — the
        # greedy pick IS the row min until capacity interferes.
        lb = np.minimum.reduce(np.where(cost < INFEASIBLE * 0.5, cost, np.inf))
        total = cost[p4t[assigned], np.flatnonzero(assigned)].sum()
        assert total <= 2.0 * lb[assigned].sum()

    def test_ffd_order_is_demand_descending(self):
        demand = jnp.asarray(
            np.array([[1, 1], [5, 5], [3, 3]], np.float32)
        )
        order = np.asarray(ffd_demand_order(demand))
        assert order.tolist() == [1, 2, 0]
