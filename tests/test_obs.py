"""Observability plane (protocol_tpu/obs): span tracer semantics,
HDR-histogram quantiles, the per-session registry's prometheus-OPTIONAL
degradation contract (dict snapshot authoritative, scrape endpoint 503s
cleanly), span-ID propagation across a wire-v2 session, and the
trace-native flame/phase report."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import protocol_tpu.obs as obs
from protocol_tpu.obs import metrics as obs_metrics
from protocol_tpu.obs.endpoint import MetricsEndpoint
from protocol_tpu.obs.metrics import (
    LatencyHistogram,
    ObsRegistry,
    percentiles_ms,
    tenant_of,
)
from protocol_tpu.obs.spans import METADATA_KEY, SpanTracer


class TestLatencyHistogram:
    def test_quantiles_bounded_relative_error(self):
        h = LatencyHistogram()
        values = [float(v) for v in range(1000, 2_000_000, 1117)]
        for v in values:
            h.observe_ns(v)
        values.sort()
        for q in (0.5, 0.9, 0.99):
            exact = values[min(len(values) - 1, int(q * len(values)))]
            est = h.quantile_ns(q)
            assert abs(est - exact) / exact < 0.10, (q, est, exact)

    def test_empty_and_below_floor(self):
        h = LatencyHistogram()
        assert h.snapshot_ms() == {"count": 0}
        assert h.quantile_ns(0.99) == 0.0
        h.observe_ns(5)  # below the 1 µs resolution floor: bucket 0
        assert h.count == 1
        assert h.quantile_ns(0.5) > 0

    def test_merge(self):
        a, b = LatencyHistogram(), LatencyHistogram()
        for v in (1e6, 2e6, 3e6):
            a.observe_ns(v)
        for v in (10e6, 20e6):
            b.observe_ns(v)
        a.merge(b)
        assert a.count == 5
        assert a.snapshot_ms()["max_ms"] == 20.0

    def test_percentiles_ms_helper(self):
        p = percentiles_ms([1.0, 2.0, 3.0, 100.0])
        assert p["count"] == 4
        assert p["p99_ms"] > 50

    def test_tenant_of(self):
        assert tenant_of("acme@pool-7") == "acme"
        assert tenant_of("bare-session") == "bare-session"
        assert tenant_of("") == "unknown"


class TestSpanTracer:
    def test_nesting_and_explicit_ids(self):
        tr = SpanTracer()
        with tr.span("root") as root:
            with tr.span("child") as child:
                assert child["trace"] == root["trace"]
                assert child["parent"] == root["span"]
        spans = tr.drain()
        assert [s["name"] for s in spans] == ["child", "root"]
        # counter-allocated ids, no randomness
        assert spans[1]["span"] < spans[0]["span"]

    def test_ring_bounded(self):
        tr = SpanTracer(capacity=8)
        for i in range(50):
            with tr.span(f"s{i}"):
                pass
        assert len(tr.snapshot()) == 8
        assert tr.snapshot()[-1]["name"] == "s49"

    def test_since_mark_and_trace_filter(self):
        tr = SpanTracer()
        with tr.span("before"):
            pass
        mark = tr.mark()
        with tr.span("a") as a:
            pass
        with tr.span("b"):
            pass
        got = tr.since(mark, trace=a["trace"])
        assert [s["name"] for s in got] == ["a"]

    def test_header_inject_extract(self):
        tr = SpanTracer()
        assert tr.header() == ""
        assert tr.inject(None) is None  # no open span: nothing to inject
        with tr.span("tick") as f:
            h = tr.header()
            assert h == f"{f['trace']}/{f['span']}"
            md = tr.inject([("other", "1")])
            assert (METADATA_KEY, h) in md
        assert SpanTracer.extract(md) == h
        assert SpanTracer.extract([("x", "y")]) is None

    def test_remote_parent_adoption(self):
        tr = SpanTracer()
        with tr.span("client") as c:
            header = tr.header()
        with tr.span("server-rpc", remote_parent=header) as s:
            assert s["trace"] == c["trace"]
            assert s["parent"] == c["span"]

    def test_disabled_is_noop(self):
        tr = SpanTracer(enabled=False)
        with tr.span("x") as f:
            assert f is None
        tr.point("y")
        tr.record_span("z", 0, 10)
        assert tr.snapshot() == []

    def test_point_and_record_span(self):
        tr = SpanTracer()
        with tr.span("root") as r:
            tr.point("evict", reason="lru")
            tr.record_span("region", 100, 50, kind="gen")
        spans = {s["name"]: s for s in tr.drain()}
        assert spans["evict"]["dur_ns"] == 0
        assert spans["evict"]["parent"] == r["span"]
        assert spans["region"]["dur_ns"] == 50
        assert spans["region"]["trace"] == r["trace"]


class TestObsRegistry:
    def _filled(self):
        reg = ObsRegistry(role="server")
        reg.observe_tick(
            "t1@pool", 5.0, 100, 97,
            arena_stats={"cold": True, "changed_rows": 100},
        )
        reg.observe_tick(
            "t1@pool", 2.0, 100, 99,
            arena_stats={"cold": False, "changed_rows": 10},
            delta_rows=4,
        )
        return reg

    def test_snapshot_authoritative(self):
        snap = self._filled().snapshot()
        s = snap["sessions"]["t1@pool"]
        assert s["tenant"] == "t1"
        assert s["tick"]["count"] == 1  # one warm tick
        assert s["cold_tick"]["count"] == 1
        assert s["assigned_frac"] == 0.99
        assert s["min_assigned_frac"] == 0.97
        # reuse ratio: (200 - 110 changed) / 200 rows
        assert s["arena_reuse_ratio"] == pytest.approx(0.45)
        assert s["delta_rows"] == 4

    def test_render_with_prometheus(self):
        if not obs_metrics.prometheus_available():
            pytest.skip("prometheus_client not installed")
        text = self._filled().render().decode()
        assert "scheduler_obs_tick_latency_ms" in text
        assert 'tenant="t1"' in text

    def test_reuse_ratio_padded_rows_stay_in_range(self):
        """The arena reports row counts over its PADDED pow2 batch; the
        ratio must stay a fraction for non-pow2 real task counts."""
        reg = ObsRegistry()
        reg.observe_tick("s", 1.0, 100, 100, arena_stats={
            "cold": True, "rows": 128, "changed_rows": 128})
        reg.observe_tick("s", 1.0, 100, 100, arena_stats={
            "cold": False, "rows": 128, "changed_rows": 5})
        s = reg.snapshot()["sessions"]["s"]
        assert 0.0 <= s["arena_reuse_ratio"] <= 1.0
        assert s["arena_reuse_ratio"] == pytest.approx(
            1 - 133 / 256, abs=1e-4
        )

    def test_stateless_kernel_is_cold_with_no_reuse(self):
        """No arena_stats = a stateless kernel: classified cold, no
        reuse credit, assigned fraction clamped (the 'best' kernel
        counts assigned PROVIDERS, which can exceed the task count)."""
        reg = ObsRegistry()
        reg.observe_tick("unary:v1", 3.0, 100, 256)
        s = reg.snapshot()["sessions"]["unary:v1"]
        assert s["cold_tick"]["count"] == 1 and s["tick"] == {"count": 0}
        assert s["arena_reuse_ratio"] == 0.0
        assert s["assigned_frac"] == 1.0  # clamped, never > 1

    def test_lru_bounded_sessions(self):
        """Client-minted session ids churn (uuids per process): the
        registry must stay bounded and keep the RECENT sessions."""
        reg = ObsRegistry(max_sessions=4)
        for i in range(10):
            reg.observe_tick(f"s{i}", 1.0, 10, 10)
        sessions = reg.snapshot()["sessions"]
        assert len(sessions) == 4
        assert "s9" in sessions and "s0" not in sessions
        # re-observing an old-but-surviving session refreshes recency
        reg.observe_tick("s6", 1.0, 10, 10)
        reg.observe_tick("new", 1.0, 10, 10)
        sessions = reg.snapshot()["sessions"]
        assert "s6" in sessions and "s7" not in sessions

    def test_kill_switch_gates_servicer_registry(self):
        """PROTOCOL_TPU_OBS=0 must silence the per-session registry too,
        not just spans/engine stats (the documented whole-plane off)."""
        pytest.importorskip("grpc")
        pytest.importorskip("jax")
        from protocol_tpu.services.scheduler_grpc import (
            SchedulerBackendServicer,
        )

        servicer = SchedulerBackendServicer()
        try:
            obs.set_enabled(False)
            servicer._observe_tick("s", 0.0, 10, 10)
            assert servicer.obs.snapshot()["sessions"] == {}
        finally:
            obs.set_enabled(True)
        servicer._observe_tick("s", 0.0, 10, 10)
        assert "s" in servicer.obs.snapshot()["sessions"]

    def test_prometheus_absent_degradation(self, monkeypatch):
        """The new registries must keep the SeamMetrics contract: no
        prometheus_client => the dict snapshot stays authoritative and
        only the prometheus render degrades (ImportError)."""
        monkeypatch.setattr(obs_metrics, "CollectorRegistry", None)
        reg = self._filled()
        snap = reg.snapshot()  # still fully functional
        assert snap["sessions"]["t1@pool"]["tick"]["count"] == 1
        with pytest.raises(ImportError):
            reg.render()


class TestEndpointDegradation:
    def _get(self, url):
        try:
            r = urllib.request.urlopen(url, timeout=10)
            return r.status, r.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    def test_scrape_503s_cleanly_without_prometheus(self, monkeypatch):
        monkeypatch.setattr(obs_metrics, "CollectorRegistry", None)
        reg = ObsRegistry()
        reg.observe_tick("s", 1.0, 10, 10)
        ep = MetricsEndpoint(
            prom_sources=[reg], json_sources={"obs": reg}
        )
        try:
            code, text = self._get(
                f"http://127.0.0.1:{ep.port}/metrics"
            )
            assert code == 503
            assert "metrics.json" in text  # points at the snapshot
            # the authoritative snapshot stays served
            code, text = self._get(
                f"http://127.0.0.1:{ep.port}/metrics.json"
            )
            assert code == 200
            assert json.loads(text)["obs"]["sessions"]["s"]
        finally:
            ep.stop()

    def test_scrape_200_with_prometheus(self):
        if not obs_metrics.prometheus_available():
            pytest.skip("prometheus_client not installed")
        reg = ObsRegistry()
        reg.observe_tick("s", 1.0, 10, 10)
        ep = MetricsEndpoint(
            prom_sources=[reg], json_sources={"obs": reg}
        )
        try:
            code, text = self._get(f"http://127.0.0.1:{ep.port}/metrics")
            assert code == 200
            assert "scheduler_obs_assigned_frac" in text
            code, _ = self._get(f"http://127.0.0.1:{ep.port}/healthz")
            assert code == 200
        finally:
            ep.stop()


grpc = pytest.importorskip("grpc")


class TestSpanPropagationWireV2:
    """A client tick's span context must ride the gRPC metadata and
    stitch the servicer's spans (rpc root, decode, solve, session
    lookup, budget grant, arena) into ONE causal trace across a full
    wire-v2 session (open + delta)."""

    def test_wire_v2_session_stitches_one_trace(self, tmp_path):
        pytest.importorskip("jax")
        from protocol_tpu import native

        if not native.available():
            pytest.skip("no native toolchain")
        import socket

        from protocol_tpu.obs.spans import TRACER
        from protocol_tpu.ops.cost import CostWeights
        from protocol_tpu.proto import scheduler_pb2 as pbs
        from protocol_tpu.proto import wire as wirelib
        from protocol_tpu.services.scheduler_grpc import (
            SchedulerBackendClient,
            encoded_to_proto_v2,
            serve,
        )
        from protocol_tpu.trace.synth import (
            synth_providers,
            synth_requirements,
        )

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        server = serve(f"127.0.0.1:{port}")
        client = SchedulerBackendClient(f"127.0.0.1:{port}")
        try:
            rng = np.random.default_rng(0)
            ep = synth_providers(rng, 128)
            er = synth_requirements(rng, 128)
            w = CostWeights()
            p_cols = wirelib.canon_columns(ep, wirelib.P_WIRE_DTYPES)
            r_cols = wirelib.canon_columns(er, wirelib.R_WIRE_DTYPES)
            fp = wirelib.epoch_fingerprint(
                p_cols, r_cols, w, "native-mt:1", 32, 0.02, 0
            )
            req = encoded_to_proto_v2(
                ep, er, w, kernel="native-mt:1", top_k=32, eps=0.02
            )
            with TRACER.span("client-tick") as tick:
                resp = client.open_session(
                    wirelib.chunk_snapshot("prop@t", fp, req)
                )
                assert resp.ok, resp.error
                p_cols["price"][:3] = 7.5
                rows = np.arange(3, dtype=np.int32)
                dreq = pbs.AssignDeltaRequest(
                    session_id="prop@t", epoch_fingerprint=fp, tick=1
                )
                dreq.provider_rows.CopyFrom(wirelib.blob(rows, np.int32))
                dreq.providers.CopyFrom(
                    wirelib.encode_providers_v2(
                        wirelib.take_rows(p_cols, rows)
                    )
                )
                dresp = client.assign_delta(dreq)
                assert dresp.session_ok, dresp.error
            trace_id = tick["trace"]
            spans = [
                s for s in TRACER.snapshot() if s["trace"] == trace_id
            ]
            names = {s["name"] for s in spans}
            # servicer-side spans adopted the client's trace id
            assert {
                "rpc.OpenSession", "rpc.AssignDelta", "wire.decode",
                "engine.solve", "session.lookup", "budget.grant",
                "arena.solve",
            } <= names
            roots = [s for s in spans if s["name"].startswith("rpc.")]
            assert all(s["parent"] is not None for s in roots)
            # per-session metrics landed under the session id
            snap = server.servicer.obs.snapshot()
            sess = snap["sessions"]["prop@t"]
            assert sess["tenant"] == "prop"
            assert sess["tick"]["count"] >= 1  # the delta tick
            assert sess["cold_tick"]["count"] >= 1  # the open solve
            assert snap["budget"]["grants"] >= 2
        finally:
            client.close()
            server.stop(grace=None)


class TestReport:
    def _recorded_trace(self, tmp_path) -> str:
        pytest.importorskip("jax")
        from protocol_tpu import native

        if not native.available():
            pytest.skip("no native toolchain")
        from protocol_tpu.trace.replay import replay
        from protocol_tpu.trace.synth import synth_trace

        src = str(tmp_path / "in.trace")
        synth_trace(src, n_providers=128, n_tasks=128, ticks=3,
                    churn=0.05, kernel="native-mt")
        out = str(tmp_path / "golden.trace")
        rep = replay(src, engine="native-mt", threads=1, record_path=out)
        assert rep["divergence"] is None
        return out

    def test_report_renders_native_phases(self, tmp_path):
        from protocol_tpu.obs.report import render

        text = render(self._recorded_trace(tmp_path))
        # per-tick table with native-engine INTERNAL phases
        assert "per-tick phase breakdown" in text
        assert "rounds" in text and "bids" in text
        # percentile table + flame
        assert "p99" in text
        assert "arena.engine" in text

    def test_report_json(self, tmp_path):
        from protocol_tpu.obs.report import report_dict

        d = report_dict(self._recorded_trace(tmp_path))
        assert len(d["ticks"]) == 4  # snapshot + 3 deltas
        assert d["warm"]["count"] == 3
        assert d["ticks"][1]["eng_rounds"] > 0

    def test_report_cli_smoke(self, tmp_path, capsys):
        from protocol_tpu.obs.__main__ import main

        rc = main(["report", self._recorded_trace(tmp_path)])
        assert rc == 0
        outp = capsys.readouterr().out
        assert "obs report" in outp and "rounds" in outp


class TestObsToggle:
    def test_arena_stats_follow_toggle(self):
        pytest.importorskip("jax")
        from protocol_tpu import native

        if not native.available():
            pytest.skip("no native toolchain")
        from protocol_tpu.native.arena import NativeSolveArena
        from protocol_tpu.ops.cost import CostWeights
        from tests.test_sparse import encode_random_marketplace

        ep, er = encode_random_marketplace(2, 128, 128)
        on = NativeSolveArena(threads=1)
        p_on = on.solve(ep, er, CostWeights())
        assert any(k.startswith("eng_") for k in on.last_stats)
        assert obs.enabled()
        try:
            obs.set_enabled(False)
            off = NativeSolveArena(threads=1)
            p_off = off.solve(ep, er, CostWeights())
            assert not any(k.startswith("eng_") for k in off.last_stats)
        finally:
            obs.set_enabled(True)
        # observability must observe, never perturb
        np.testing.assert_array_equal(p_on, p_off)
