"""Orchestrator control proxies (node logs/restart, group log fan-out) and
location resolvers."""

import pytest

# Environment guard: this module's import chain reaches
# protocol_tpu.security / protocol_tpu.utils.tls, which need the
# third-party `cryptography` package (wallet signing + TLS material).
# On hosts without it, report the whole module as SKIPPED instead of a
# collection error (tier-1 keeps an honest skip count; CI installs
# cryptography and runs everything).
pytest.importorskip(
    "cryptography", reason="cryptography not installed (signing/TLS dependency)"
)

import asyncio

import aiohttp
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from protocol_tpu.models.node import NodeLocation
from protocol_tpu.sched.node_groups import NodeGroupConfiguration, NodeGroupsPlugin
from protocol_tpu.services.orchestrator import OrchestratorService
from protocol_tpu.services.worker import SubprocessRuntime, WorkerAgent
from protocol_tpu.store import NodeStatus, OrchestratorNode
from protocol_tpu.utils.location import HttpLocationResolver, StaticLocationResolver

from tests.test_services import make_world


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


class TestControlProxies:
    def test_node_logs_and_restart_proxy(self):
        ledger, creator, manager, provider, node, pid = make_world()

        async def flow():
            async with aiohttp.ClientSession() as session:
                agent = WorkerAgent(
                    provider, node, ledger, pid,
                    runtime=SubprocessRuntime(),
                    http=session,
                    known_orchestrators=[manager.address],
                )
                agent.runtime.logs.extend(["line-1", "line-2"])
                wsrv = TestServer(agent.make_control_app())
                await wsrv.start_server()
                control_url = str(wsrv.make_url("/control"))

                svc = OrchestratorService(
                    ledger, pid, manager, control_http=session
                )
                svc.store.node_store.add_node(
                    OrchestratorNode(
                        address=node.address,
                        status=NodeStatus.HEALTHY,
                        p2p_addresses=[control_url],
                    )
                )
                async with TestClient(TestServer(svc.make_app())) as client:
                    auth = {"Authorization": "Bearer admin"}
                    r1 = await client.get(f"/nodes/{node.address}/logs", headers=auth)
                    logs = (await r1.json())["data"]
                    r2 = await client.post(
                        f"/nodes/{node.address}/restart", headers=auth
                    )
                    r3 = await client.get("/nodes/0xmissing/logs", headers=auth)
                    await wsrv.close()
                    return r1.status, logs, r2.status, r3.status

        s1, logs, s2, s3 = run(flow())
        assert s1 == 200 and logs[-2:] == ["line-1", "line-2"]
        assert s2 == 200
        assert s3 == 404

    def test_group_logs_fanout(self):
        ledger, creator, manager, provider, node, pid = make_world()

        async def flow():
            async with aiohttp.ClientSession() as session:
                from protocol_tpu.store import StoreContext

                store = StoreContext.new_test()
                groups = NodeGroupsPlugin(
                    store,
                    [NodeGroupConfiguration(name="pair", min_group_size=1, max_group_size=2)],
                )
                agents, servers, urls = [], [], []
                from protocol_tpu.security import Wallet

                for i in range(2):
                    w = Wallet.from_seed(f"gl-{i}".encode())
                    a = WorkerAgent(
                        provider, w, ledger, pid,
                        runtime=SubprocessRuntime(),
                        http=session,
                        known_orchestrators=[manager.address],
                    )
                    a.runtime.logs.append(f"member-{i}")
                    s = TestServer(a.make_control_app())
                    await s.start_server()
                    urls.append(str(s.make_url("/control")))
                    store.node_store.add_node(
                        OrchestratorNode(
                            address=w.address,
                            status=NodeStatus.HEALTHY,
                            p2p_addresses=[urls[-1]],
                        )
                    )
                    agents.append(a)
                    servers.append(s)
                group = groups._create_group(
                    groups.configurations[0], [a.node_wallet.address for a in agents]
                )
                svc = OrchestratorService(
                    ledger, pid, manager, store=store,
                    groups_plugin=groups, control_http=session,
                )
                async with TestClient(TestServer(svc.make_app())) as client:
                    r = await client.get(
                        f"/groups/{group.id}/logs",
                        headers={"Authorization": "Bearer admin"},
                    )
                    data = (await r.json())["data"]
                for s in servers:
                    await s.close()
                return data, agents

        data, agents = run(flow())
        for i, a in enumerate(agents):
            assert data[a.node_wallet.address] == [f"member-{i}"]


class TestControlSurfaceAuth:
    def test_no_allowlist_fails_closed_to_pool_manager(self):
        """With no configured orchestrator/validator allowlist the /control
        surface must NOT accept arbitrary valid wallet signatures: it derives
        the allowlist from the pool on the ledger (creator + compute manager),
        mirroring worker/src/p2p/mod.rs:320-322."""
        from protocol_tpu.security import Wallet
        from protocol_tpu.security.signer import sign_request

        ledger, creator, manager, provider, node, pid = make_world()

        async def flow():
            async with aiohttp.ClientSession() as session:
                agent = WorkerAgent(
                    provider, node, ledger, pid,
                    runtime=SubprocessRuntime(),
                    http=session,
                    # no known_orchestrators / known_validators configured
                )
                agent.runtime.logs.append("secret")
                validator_w = Wallet.from_seed(b"roled-validator")
                ledger.grant_validator_role(validator_w.address)
                async with TestClient(TestServer(agent.make_control_app())) as c:
                    stranger = Wallet.from_seed(b"stranger")
                    h_bad, _ = sign_request("/control/logs", stranger)
                    r_bad = await c.get("/control/logs", headers=h_bad)
                    h_mgr, _ = sign_request("/control/logs", manager)
                    r_mgr = await c.get("/control/logs", headers=h_mgr)
                    # wallets holding the on-ledger validator role are allowed
                    # (reference cli/command.rs:717-734 get_validator_role)
                    h_val, _ = sign_request("/control/logs", validator_w)
                    r_val = await c.get("/control/logs", headers=h_val)
                    return r_bad.status, r_mgr.status, r_val.status

        bad, ok, val = run(flow())
        assert bad == 401
        assert ok == 200
        assert val == 200


class TestLocationResolvers:
    def test_static_table_and_prefix(self):
        paris = NodeLocation(latitude=48.85, longitude=2.35, city="Paris")
        dc = NodeLocation(latitude=38.9, longitude=-77.0, region="dc-east")
        r = StaticLocationResolver({"1.2.3.4": paris, "10.1.": dc})
        assert run(r("1.2.3.4")).city == "Paris"
        assert run(r("10.1.99.5")).region == "dc-east"
        assert run(r("8.8.8.8")) is None

    def test_http_resolver_caches(self):
        calls = []

        async def handler(request):
            calls.append(request.match_info["ip"])
            return web.json_response({"latitude": 1.0, "longitude": 2.0, "city": "X"})

        async def flow():
            app = web.Application()
            app.router.add_get("/{ip}", handler)
            async with TestClient(TestServer(app)) as client:
                r = HttpLocationResolver("", client)
                a = await r("9.9.9.9")
                b = await r("9.9.9.9")
                return a, b

        a, b = run(flow())
        assert a.city == "X" and b.city == "X"
        assert calls == ["9.9.9.9"]  # second hit served from cache
