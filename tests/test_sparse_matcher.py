"""The sparse top-K pipeline as the production matcher path.

VERDICT r2 item 2: above ``dense_cell_budget`` the live matcher must route
phase 1 through streaming candidate generation + the frontier auction
(ops/sparse.py) instead of the dense auction — locally and over the gRPC
seam — and item 3: consecutive solves must warm-start from carried prices
and the previous matching (the delta-frontier incremental path).
"""

import numpy as np
import pytest

from protocol_tpu.models import (
    ComputeSpecs,
    CpuSpecs,
    GpuSpecs,
    SchedulingConfig,
    Task,
    TaskState,
)
from protocol_tpu.sched import TpuBatchMatcher
from protocol_tpu.store import NodeStatus, OrchestratorNode, StoreContext


def mk_node(addr, gpu_model="H100", gpu_count=8):
    return OrchestratorNode(
        address=addr,
        status=NodeStatus.HEALTHY,
        compute_specs=ComputeSpecs(
            gpu=GpuSpecs(count=gpu_count, model=gpu_model, memory_mb=80000),
            cpu=CpuSpecs(cores=32),
            ram_mb=65536,
            storage_gb=1000,
        ),
    )


def mk_bounded_task(name, created_at, replicas, requirements=None):
    plugins = {"tpu_scheduler": {"replicas": [str(replicas)]}}
    if requirements:
        plugins["tpu_scheduler"]["compute_requirements"] = [requirements]
    return Task(
        name=name,
        image="img",
        created_at=created_at,
        state=TaskState.PENDING,
        scheduling_config=SchedulingConfig(plugins=plugins),
    )


def populate(ctx, n_nodes, tasks):
    for i in range(n_nodes):
        ctx.node_store.add_node(mk_node(f"0x{i:040x}"))
    for t in tasks:
        ctx.task_store.add_task(t)


class TestSparseProductionPath:
    def test_sparse_path_engages_above_budget(self):
        ctx = StoreContext.new_test()
        populate(ctx, 24, [mk_bounded_task("t", 100, replicas=16)])
        m = TpuBatchMatcher(ctx, dense_cell_budget=0, min_solve_interval=0)
        m.refresh()
        assert m.last_solve_stats["kernel"] == "sparse_topk"
        assert m.last_solve_stats["assigned"] == 16

    def test_dense_path_below_budget(self):
        ctx = StoreContext.new_test()
        populate(ctx, 24, [mk_bounded_task("t", 100, replicas=16)])
        m = TpuBatchMatcher(ctx, min_solve_interval=0)  # default budget
        m.refresh()
        assert m.last_solve_stats["kernel"] == "dense_auction"
        assert m.last_solve_stats["assigned"] == 16

    def test_sparse_dense_same_count(self):
        tasks = [
            mk_bounded_task("a", 100, replicas=10),
            mk_bounded_task("b", 200, replicas=7),
        ]
        counts = {}
        for label, budget in (("dense", 1 << 24), ("sparse", 0)):
            ctx = StoreContext.new_test()
            populate(ctx, 32, tasks)
            m = TpuBatchMatcher(
                ctx, dense_cell_budget=budget, min_solve_interval=0
            )
            m.refresh()
            counts[label] = m.last_solve_stats["assigned"]
        assert counts["dense"] == counts["sparse"] == 17

    def test_requirements_respected_on_sparse_path(self):
        ctx = StoreContext.new_test()
        for i in range(8):
            ctx.node_store.add_node(mk_node(f"0xa{i:039x}", gpu_model="H100"))
        for i in range(8):
            ctx.node_store.add_node(mk_node(f"0xb{i:039x}", gpu_model="RTX4090"))
        ctx.task_store.add_task(
            mk_bounded_task(
                "h100only", 100, replicas=12, requirements="gpu:model=H100"
            )
        )
        m = TpuBatchMatcher(ctx, dense_cell_budget=0, min_solve_interval=0)
        m.refresh()
        # only the 8 H100 nodes are eligible despite 12 requested replicas
        assert m.last_solve_stats["assigned"] == 8
        for addr in m._assignment:
            assert addr.startswith("0xa")


class TestWarmStart:
    def test_second_solve_is_warm_and_stable(self):
        ctx = StoreContext.new_test()
        populate(ctx, 24, [mk_bounded_task("t", 100, replicas=16)])
        m = TpuBatchMatcher(ctx, dense_cell_budget=0, min_solve_interval=0)
        m.refresh()
        first = dict(m._assignment)
        assert m.last_solve_stats["warm"] is False
        m.mark_dirty()
        m.refresh()
        assert m.last_solve_stats["warm"] is True
        assert m.last_solve_stats["warm_seeded_slots"] == 16
        # unchanged population: the warm solve keeps everyone seated
        assert m._assignment == first

    def test_warm_solve_after_churn_assigns_new_node(self):
        ctx = StoreContext.new_test()
        populate(ctx, 16, [mk_bounded_task("t", 100, replicas=17)])
        m = TpuBatchMatcher(ctx, dense_cell_budget=0, min_solve_interval=0)
        m.refresh()
        assert m.last_solve_stats["assigned"] == 16  # supply-capped
        ctx.node_store.add_node(mk_node("0x" + "f" * 40))
        m.mark_dirty()
        m.refresh()
        assert m.last_solve_stats["warm"] is True
        assert m.last_solve_stats["assigned"] == 17
        assert "0x" + "f" * 40 in m._assignment

    def test_warm_disabled(self):
        ctx = StoreContext.new_test()
        populate(ctx, 24, [mk_bounded_task("t", 100, replicas=16)])
        m = TpuBatchMatcher(
            ctx, dense_cell_budget=0, min_solve_interval=0, warm_start=False
        )
        m.refresh()
        m.mark_dirty()
        m.refresh()
        assert m.last_solve_stats["warm"] is False

    def test_task_deleted_frees_nodes_for_remaining_task(self):
        ctx = StoreContext.new_test()
        a = mk_bounded_task("a", 100, replicas=12)
        b = mk_bounded_task("b", 200, replicas=12)
        populate(ctx, 12, [a, b])
        m = TpuBatchMatcher(ctx, dense_cell_budget=0, min_solve_interval=0)
        m.attach_observers()
        m.refresh()
        ctx.task_store.delete_task(a.id)
        m.refresh()
        assert m.last_solve_stats["assigned"] == 12
        assert set(m._assignment.values()) == {b.id}


class TestRemoteSparsePath:
    @pytest.fixture()
    def backend(self):
        from protocol_tpu.services import scheduler_grpc

        server = scheduler_grpc.serve(address="127.0.0.1:50071")
        yield "127.0.0.1:50071"
        server.stop(grace=None)

    def test_remote_topk_and_warm(self, backend):
        from protocol_tpu.services.scheduler_grpc import RemoteBatchMatcher

        ctx = StoreContext.new_test()
        populate(ctx, 24, [mk_bounded_task("t", 100, replicas=16)])
        m = RemoteBatchMatcher(
            ctx, address=backend, dense_cell_budget=0, min_solve_interval=0
        )
        m.refresh()
        assert m.last_solve_stats["kernel"] == "sparse_topk"
        assert m.last_solve_stats["assigned"] == 16
        assert m.last_solve_stats["remote_calls"] >= 1
        first = dict(m._assignment)
        m.mark_dirty()
        m.refresh()
        assert m.last_solve_stats["warm"] is True
        assert m._assignment == first

    def test_remote_matches_local(self, backend):
        from protocol_tpu.services.scheduler_grpc import RemoteBatchMatcher

        tasks = [
            mk_bounded_task("a", 100, replicas=9),
            mk_bounded_task("b", 200, replicas=6),
        ]
        ctx_l = StoreContext.new_test()
        populate(ctx_l, 20, tasks)
        local = TpuBatchMatcher(ctx_l, dense_cell_budget=0, min_solve_interval=0)
        local.refresh()

        ctx_r = StoreContext.new_test()
        populate(ctx_r, 20, tasks)
        remote = RemoteBatchMatcher(
            ctx_r, address=backend, dense_cell_budget=0, min_solve_interval=0
        )
        remote.refresh()
        assert (
            remote.last_solve_stats["assigned"]
            == local.last_solve_stats["assigned"]
            == 15
        )


class TestAntiAffinityMatcher:
    def _nodes_with_locations(self, ctx, n_per_loc=4, locs=((10.0, 10.0), (50.0, 50.0))):
        from protocol_tpu.models import NodeLocation

        idx = 0
        for lat, lon in locs:
            for _ in range(n_per_loc):
                n = mk_node(f"0x{idx:040x}")
                n.location = NodeLocation(latitude=lat, longitude=lon)
                ctx.node_store.add_node(n)
                idx += 1

    def _aa_task(self, name, created_at, replicas, mode):
        t = mk_bounded_task(name, created_at, replicas)
        t.scheduling_config.plugins["tpu_scheduler"]["anti_affinity"] = [mode]
        return t

    def test_location_spread_caps_at_distinct_locations(self):
        from protocol_tpu.models import Task
        from protocol_tpu.store import StoreContext

        ctx = StoreContext.new_test()
        self._nodes_with_locations(ctx)  # 8 nodes, 2 locations
        ctx.task_store.add_task(self._aa_task("spread", 100, 5, "location"))
        m = TpuBatchMatcher(ctx, min_solve_interval=0)
        m.refresh()
        st = m.last_solve_stats
        # only 2 distinct locations exist: 5 replicas cap at 2
        assert st["anti_affinity_assigned"] == 2
        locs = set()
        for addr in m._assignment:
            n = ctx.node_store.get_node(addr)
            locs.add((n.location.latitude, n.location.longitude))
        assert len(locs) == 2

    def test_task_spread_uses_distinct_providers(self):
        from protocol_tpu.store import StoreContext

        ctx = StoreContext.new_test()
        populate(ctx, 6, [])
        ctx.task_store.add_task(self._aa_task("spread", 100, 4, "task"))
        m = TpuBatchMatcher(ctx, min_solve_interval=0)
        m.refresh()
        assert m.last_solve_stats["anti_affinity_assigned"] == 4
        assert len(m._assignment) == 4  # distinct providers by construction

    def test_claimed_providers_excluded_from_auction(self):
        from protocol_tpu.store import StoreContext

        ctx = StoreContext.new_test()
        populate(ctx, 6, [])
        ctx.task_store.add_task(self._aa_task("spread", 100, 3, "task"))
        ctx.task_store.add_task(mk_bounded_task("auction", 200, 6))
        m = TpuBatchMatcher(ctx, min_solve_interval=0)
        m.refresh()
        st = m.last_solve_stats
        assert st["anti_affinity_assigned"] == 3
        # 6 nodes total: 3 claimed by spread, auction takes the other 3;
        # no provider double-assigned (the dict can't express it — the
        # invariant is the auction filled exactly the free nodes)
        assert st["assigned"] == 6
        by_task = {}
        for addr, tid in m._assignment.items():
            by_task.setdefault(tid, []).append(addr)
        assert sorted(len(v) for v in by_task.values()) == [3, 3]

    def test_claimed_excluded_on_cached_sparse_path(self):
        from protocol_tpu.store import StoreContext

        ctx = StoreContext.new_test()
        populate(ctx, 8, [])
        ctx.task_store.add_task(self._aa_task("spread", 100, 4, "task"))
        ctx.task_store.add_task(mk_bounded_task("auction", 200, 8))
        m = TpuBatchMatcher(ctx, min_solve_interval=0, dense_cell_budget=0)
        m.refresh()
        st = m.last_solve_stats
        assert st["kernel"] == "sparse_topk"
        assert st["anti_affinity_assigned"] == 4
        assert st["assigned"] == 8
        # warm second solve stays consistent
        m.mark_dirty()
        m.refresh()
        assert m.last_solve_stats["assigned"] == 8

    def test_invalid_mode_rejected(self):
        import pytest

        from protocol_tpu.sched.tpu_backend import validate_tpu_scheduler_config

        t = self._aa_task("bad", 100, 2, "rack")
        with pytest.raises(ValueError):
            validate_tpu_scheduler_config(t)


class TestMeshMatcher:
    """use_mesh=True routes phase 1 through the task-sharded eps-ladder /
    warm kernels (the v5e-8 path) — the production matcher solving over
    the virtual 8-device mesh end to end."""

    def test_mesh_solve_seats_all_replicas_and_warms(self):
        ctx = StoreContext.new_test()
        n = 64
        populate(ctx, n, [
            mk_bounded_task("a", 1.0, 24, "gpu:count=8;gpu:model=H100"),
            mk_bounded_task("b", 2.0, 24, "gpu:count=8;gpu:model=H100"),
        ])
        m = TpuBatchMatcher(
            ctx, min_solve_interval=0.0, dense_cell_budget=1,
            use_mesh=True,
        )
        assert m._mesh is not None  # conftest provides 8 virtual devices
        m.mark_dirty()
        m._ensure_fresh()
        s = m.last_solve_stats
        assert s["kernel"] == "sparse_topk"
        assert s["mesh_sharded"] is True  # the mesh path ENGAGED
        assert s["assigned"] == 48  # every replica of both tasks seated
        # second solve warm-starts over the mesh (seeded from the first)
        m.mark_dirty()
        m._ensure_fresh()
        assert m.last_solve_stats["warm"] is True
        assert m.last_solve_stats["mesh_sharded"] is True
        assert m.last_solve_stats["assigned"] == 48

    def test_mesh_assignment_counts_match_unsharded(self):
        def solve(use_mesh):
            ctx = StoreContext.new_test()
            populate(ctx, 96, [
                mk_bounded_task("a", 1.0, 40, "gpu:count=8;gpu:model=H100"),
            ])
            m = TpuBatchMatcher(
                ctx, min_solve_interval=0.0, dense_cell_budget=1,
                use_mesh=use_mesh,
            )
            m.mark_dirty()
            m._ensure_fresh()
            assert m.last_solve_stats["mesh_sharded"] is use_mesh
            return m.last_solve_stats["assigned"]

        # the sharded frontier order is a different, equally valid auction
        # schedule: counts must match even where the matching may differ
        assert solve(True) == solve(False) == 40


    def test_mesh_wire_path_shards_generation(self):
        """warm_start=False disables the candidate cache, sending the
        solve down the wire path — with a mesh, candidate GENERATION
        itself shards (candidates_topk_bidir_sharded; bit-identical to
        the single-device generator, so counts must match exactly)."""
        def solve(use_mesh):
            ctx = StoreContext.new_test()
            populate(ctx, 96, [
                mk_bounded_task("a", 1.0, 40, "gpu:count=8;gpu:model=H100"),
            ])
            m = TpuBatchMatcher(
                ctx, min_solve_interval=0.0, dense_cell_budget=1,
                use_mesh=use_mesh, warm_start=False,
            )
            m.mark_dirty()
            m._ensure_fresh()
            s = m.last_solve_stats
            assert s["kernel"] == "sparse_topk"
            assert s["mesh_gen_sharded"] is use_mesh
            return s["assigned"]

        assert solve(True) == solve(False) == 40


class TestWarmRetirementInvalidation:
    """ADVICE r5 (tpu_backend warm-retirement carry): incremental churn
    updates cached candidate lists without renumbering slots, so the
    carried retirement mask used to survive with stale flags — a task
    stayed retired after a newly-feasible provider appeared, until the
    next cold solve. The CandidateCache's dirty_slots now clears exactly
    the churned rows. The carried mask is injected directly (organic
    give-up retirement needs a long price war; the kernel's own
    retirement behavior is covered by the sparse kernel tests) — what's
    under test is the carry/invalidation plumbing."""

    def _spy_retired0(self, m, captured):
        orig = m._sparse_solve

        def spy(*args, **kwargs):
            captured.append(kwargs.get("retired0"))
            return orig(*args, **kwargs)

        m._sparse_solve = spy

    def _cold_solved_matcher(self):
        ctx = StoreContext.new_test()
        # demand 4 replicas on a 2-node fleet: two slots stay unseated
        populate(ctx, 2, [mk_bounded_task("t", 100, replicas=4)])
        m = TpuBatchMatcher(ctx, dense_cell_budget=0, min_solve_interval=0)
        m.refresh()
        assert m.last_solve_stats["assigned"] == 2
        return ctx, m

    def test_unchanged_population_keeps_carried_retirement(self):
        ctx, m = self._cold_solved_matcher()
        m._warm_retired = np.ones_like(np.asarray(m._warm_retired))
        captured = []
        self._spy_retired0(m, captured)
        m.mark_dirty()
        m.refresh()
        assert m.last_solve_stats["warm"] is True
        # clean population: the carry is the whole point — flags survive
        retired0 = captured[0]
        assert retired0 is not None
        assert bool(np.asarray(retired0).all())

    def test_churn_clears_carried_retirement(self):
        ctx, m = self._cold_solved_matcher()
        m._warm_retired = np.ones_like(np.asarray(m._warm_retired))
        # a new node churns into every slot's candidate list (k > fleet)
        ctx.node_store.add_node(mk_node("0xnew"))
        captured = []
        self._spy_retired0(m, captured)
        m.mark_dirty()
        m.refresh()
        assert m.last_solve_stats["warm"] is True
        # the mask handed to the warm kernel must not carry flags over
        # slots whose candidates changed (here: all of them) — pre-fix,
        # slot_fp matched and the stale mask rode through unchanged
        assert len(captured) == 1
        retired0 = captured[0]
        assert retired0 is None or not bool(np.asarray(retired0).any())
        # and the newly-feasible node is assigned THIS solve, not after
        # the next cold one
        assert m.last_solve_stats["assigned"] == 3
