"""Webhook plugin tests: event flow from status changes and group lifecycle
to HTTP delivery, filters, and overflow behavior."""

import pytest

# Environment guard: this module's import chain reaches
# protocol_tpu.security / protocol_tpu.utils.tls, which need the
# third-party `cryptography` package (wallet signing + TLS material).
# On hosts without it, report the whole module as SKIPPED instead of a
# collection error (tier-1 keeps an honest skip count; CI installs
# cryptography and runs everything).
pytest.importorskip(
    "cryptography", reason="cryptography not installed (signing/TLS dependency)"
)

import asyncio
import json

from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from protocol_tpu.sched.node_groups import NodeGroupConfiguration, NodeGroupsPlugin
from protocol_tpu.sched.webhook import WebhookConfig, WebhookPlugin
from protocol_tpu.services.orchestrator import OrchestratorService
from protocol_tpu.store import NodeStatus, OrchestratorNode, StoreContext

from tests.test_services import make_world


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def make_sink():
    received = []

    async def hook(request):
        received.append(await request.json())
        return web.json_response({"ok": True})

    app = web.Application()
    app.router.add_post("/hook", hook)
    return app, received


class TestWebhookPlugin:
    def test_config_from_env_json(self):
        cfgs = WebhookConfig.from_json_env(
            json.dumps([{"url": "http://x/hook", "event_types": ["group_created"]}])
        )
        assert cfgs[0].url == "http://x/hook"
        assert cfgs[0].event_types == ["group_created"]

    def test_delivery_and_filter(self):
        async def flow():
            app, received = make_sink()
            async with TestClient(TestServer(app)) as client:
                wh = WebhookPlugin(
                    [WebhookConfig(url="/hook", event_types=["node_status_changed"])],
                    http=client,
                )
                wh.handle_status_change("0xa", "Healthy", "Dead")
                wh.handle_group_created({"id": "g1"})  # filtered out
                await wh.drain_once()
                return received

        received = run(flow())
        assert len(received) == 1
        assert received[0]["type"] == "node_status_changed"
        assert received[0]["new_status"] == "Dead"

    def test_overflow_drops_oldest(self):
        async def flow():
            wh = WebhookPlugin([], http=None, queue_size=2)
            for i in range(4):
                wh.emit("e", n=i)
            out = []
            while not wh.queue.empty():
                out.append(wh.queue.get_nowait()["n"])
            return out, wh.dropped

        out, dropped = run(flow())
        assert out == [2, 3] and dropped == 2

    def test_orchestrator_status_changes_emit(self):
        ledger, creator, manager, provider, node, pid = make_world()

        async def flow():
            app, received = make_sink()
            async with TestClient(TestServer(app)) as client:
                wh = WebhookPlugin([WebhookConfig(url="/hook")], http=client)
                svc = OrchestratorService(ledger, pid, manager, webhook=wh)
                svc.store.node_store.add_node(
                    OrchestratorNode(address=node.address, status=NodeStatus.HEALTHY)
                )
                await svc.status_update_once()  # no beat -> Unhealthy
                await wh.drain_once()
                return received

        received = run(flow())
        assert [e["type"] for e in received] == ["node_status_changed"]
        assert received[0]["old_status"] == "Healthy"
        assert received[0]["new_status"] == "Unhealthy"

    def test_group_lifecycle_events(self):
        async def flow():
            app, received = make_sink()
            async with TestClient(TestServer(app)) as client:
                wh = WebhookPlugin([WebhookConfig(url="/hook")], http=client)
                ctx = StoreContext.new_test()
                plugin = NodeGroupsPlugin(
                    ctx,
                    [NodeGroupConfiguration(name="pair", min_group_size=1, max_group_size=2)],
                )
                plugin.on_group_created = wh.handle_group_created
                plugin.on_group_dissolved = wh.handle_group_destroyed
                g = plugin._create_group(plugin.configurations[0], ["0xa"])
                plugin.dissolve_group(g.id)
                await wh.drain_once()
                return received

        received = run(flow())
        assert [e["type"] for e in received] == ["group_created", "group_destroyed"]
