"""kv-api store pod + RemoteKVStore client: the shared-state seam that
lets orchestrator api/processor replicas scale like the reference's over
external Redis (orchestrator/src/main.rs modes, store/core/redis.rs)."""

import pytest

# Environment guard: this module's import chain reaches
# protocol_tpu.security / protocol_tpu.utils.tls, which need the
# third-party `cryptography` package (wallet signing + TLS material).
# On hosts without it, report the whole module as SKIPPED instead of a
# collection error (tier-1 keeps an honest skip count; CI installs
# cryptography and runs everything).
pytest.importorskip(
    "cryptography", reason="cryptography not installed (signing/TLS dependency)"
)

import asyncio
import threading

import pytest

from protocol_tpu.services.kv_api import KvApiService
from protocol_tpu.store import NodeStatus, OrchestratorNode, StoreContext
from protocol_tpu.store.kv import KVStore
from protocol_tpu.store.remote_kv import (
    LockLostError,
    RemoteKVError,
    RemoteKVStore,
)


def _spawn_api(kv: KVStore, lock_ttl: float = 5.0) -> str:
    ready = threading.Event()
    state = {}

    def run():
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        async def boot():
            svc = KvApiService(kv, api_key="k", lock_ttl=lock_ttl)
            runner = web.AppRunner(svc.make_app())
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            state["port"] = runner.addresses[0][1]
            ready.set()

        loop.run_until_complete(boot())
        loop.run_forever()

    threading.Thread(target=run, daemon=True).start()
    assert ready.wait(10)
    return f"http://127.0.0.1:{state['port']}"


@pytest.fixture(scope="module")
def kv_api():
    kv = KVStore()
    yield kv, _spawn_api(kv)


def _client(url):
    return RemoteKVStore(url, api_key="k")


def test_full_surface_round_trip(kv_api):
    _local, url = kv_api
    r = _client(url)
    assert r.set("s", "v") is True
    assert r.get("s") == "v"
    assert r.set("s", "w", nx=True) is False
    assert r.mget(["s", "missing"]) == ["v", None]
    assert r.incr("ctr", 5) == 5
    r.hset("h", "f", "1")
    r.hset_mapping("h", {"g": "2"})
    assert r.hgetall("h") == {"f": "1", "g": "2"}
    assert r.hincrby("h", "n", 3) == 3
    assert r.hdel("h", "g") == 1
    r.sadd("set", "a", "b")
    assert r.smembers("set") == {"a", "b"}
    assert r.sismember("set", "a") and not r.sismember("set", "z")
    assert r.scard("set") == 2
    r.zadd("z", {"m": 1.5, "n": 9.0})
    assert r.zscore("z", "m") == 1.5
    assert r.zrangebyscore("z") == [("m", 1.5), ("n", 9.0)]
    assert r.zrangebyscore("z", 2.0, 10.0) == [("n", 9.0)]
    assert r.zremrangebyscore("z", 0, 2) == 1
    r.rpush("l", "x", "y")
    r.lpush("l", "w")
    assert r.lrange("l") == ["w", "x", "y"]
    assert r.lrem("l", 1, "x") == 1
    assert r.llen("l") == 2
    assert r.exists("s")
    r.expire("s", 100)
    assert 90 < r.ttl("s") <= 100
    assert "ctr" in r.keys("*")
    assert r.delete("ctr") == 1
    # the server-side store saw everything (one shared state)
    assert _local.get("s") == "v"


def test_atomic_serializes_read_modify_write_across_clients(kv_api):
    _local, url = kv_api
    clients = [_client(url) for _ in range(4)]
    _local.set("rmw", "0")
    barrier = threading.Barrier(4)

    def bump(c):
        barrier.wait()
        for _ in range(5):
            with c.atomic():
                v = int(c.get("rmw"))
                c.set("rmw", str(v + 1))

    threads = [threading.Thread(target=bump, args=(c,)) for c in clients]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # without the advisory lock, concurrent get+set would lose updates
    assert _local.get("rmw") == "20"


def test_writes_block_until_foreign_lock_frees(kv_api):
    """In-process RLock semantics over the wire: a write meeting a foreign
    atomic section WAITS for the lock (no 500s on first contention);
    reads pass through immediately."""
    import time

    _local, url = kv_api
    a, b = _client(url), _client(url)

    def hold():
        with a.atomic():
            time.sleep(0.5)

    th = threading.Thread(target=hold)
    th.start()
    time.sleep(0.1)  # let A take the lock
    assert b.get("rmw") is not None  # reads never block
    t0 = time.monotonic()
    b.set("blocked", "x")  # blocks until A releases, then succeeds
    waited = time.monotonic() - t0
    th.join()
    assert waited >= 0.25, waited
    assert _local.get("blocked") == "x"

    # a client that cannot ever get through still fails loudly (bounded)
    slowpoke = RemoteKVStore(url, api_key="k", timeout=0.3)
    with a.atomic():
        with pytest.raises(RemoteKVError):
            slowpoke.set("never", "x")


def test_lock_lost_is_detected_not_silent():
    """A holder that pauses past lock_ttl inside atomic() must get a
    distinct failure on its next op — not silently interleave with the
    client that meanwhile took the lock (advisor r2 finding)."""
    import time

    kv = KVStore()
    url = _spawn_api(kv, lock_ttl=1.0)
    a, b = _client(url), _client(url)

    with pytest.raises(LockLostError):
        with a.atomic():
            a.set("k", "a1")
            time.sleep(1.4)  # lock expires mid-section (e.g. a slow
            # remote-ledger call between KV ops)
            with b.atomic():  # another client takes the expired lock
                b.set("k", "b")
            a.set("k", "a2")  # stale token: 410, op must NOT execute
    assert kv.get("k") == "b"

    # the loser can retry the whole section and succeed
    with a.atomic():
        a.set("k", "a-retried")
    assert kv.get("k") == "a-retried"


def test_pipeline_batch_atomic_single_round_trip(kv_api):
    """_pipeline executes an op batch atomically with per-op results; the
    foreign-lock gate applies to the whole batch."""
    _local, url = kv_api
    r = _client(url)
    results = r.pipeline_execute([
        ("set", ["p1", "v1"], {}),
        ("hset", ["ph", "f", "x"], {}),
        ("incr", ["pc"], {"amount": 3}),
        ("get", ["p1"], {}),
    ])
    assert results == [True, 1, 3, "v1"]
    assert _local.get("p1") == "v1" and _local.hget("ph", "f") == "x"

    # unknown op in the batch is rejected wholesale
    with pytest.raises(RemoteKVError):
        r.pipeline_execute([("flushall_everything", [], {})])

    # a foreign atomic section blocks the batch until released
    import time

    a = _client(url)

    def hold():
        with a.atomic():
            time.sleep(0.4)

    th = threading.Thread(target=hold)
    th.start()
    time.sleep(0.1)
    t0 = time.monotonic()
    r.pipeline_execute([("set", ["p2", "v2"], {})])
    waited = time.monotonic() - t0
    th.join()
    assert waited >= 0.2 and _local.get("p2") == "v2"


def test_metrics_store_batches_over_remote_kv(kv_api):
    from protocol_tpu.models.metric import MetricEntry

    _local, url = kv_api
    store = StoreContext(_client(url))
    entries = [
        MetricEntry.from_dict(
            {"key": {"task_id": "t", "label": f"m{i}"}, "value": float(i)}
        )
        for i in range(5)
    ]
    store.metrics_store.store_metrics(entries, "0xnode")
    got = store.metrics_store.get_metrics_for_task("t")
    assert got == {f"m{i}": {"0xnode": float(i)} for i in range(5)}


def test_store_context_over_remote_kv(kv_api):
    """Domain stores (node store etc.) run unchanged over the remote
    client — the orchestrator-replica shape."""
    _local, url = kv_api
    store_a = StoreContext(_client(url))
    store_b = StoreContext(_client(url))
    store_a.node_store.add_node(
        OrchestratorNode(address="0xshared", status=NodeStatus.HEALTHY,
                         ip_address="4.4.4.4", port=9)
    )
    # replica B sees replica A's write immediately
    node = store_b.node_store.get_node("0xshared")
    assert node is not None and node.status == NodeStatus.HEALTHY
    store_b.node_store.update_node_status("0xshared", NodeStatus.UNHEALTHY)
    assert store_a.node_store.get_node("0xshared").status == NodeStatus.UNHEALTHY


def test_kv_api_prometheus_metrics(kv_api):
    """The store pod exposes op counters + latency histograms."""
    import urllib.request

    _local, url = kv_api
    r = _client(url)
    r.set("metered", "1")
    r.pipeline_execute([("incr", ["metered-ctr"], {})])
    with urllib.request.urlopen(f"{url}/metrics", timeout=5) as resp:
        text = resp.read().decode()
    assert 'kv_api_requests_total{op="set",outcome="ok"}' in text
    assert 'kv_api_requests_total{op="_pipeline",outcome="ok"}' in text
    assert 'kv_api_op_duration_seconds_bucket{le="0.001",op="set"}' in text
