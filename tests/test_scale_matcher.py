"""VERDICT r2 done-bars at full scale: 100k nodes x 10k replica slots
through the sparse production path, locally and over gRPC, plus the
warm >= 10x incremental-solve claim — measured, not asserted.

~3-4 min on the CI CPU (the cold candidate pass streams a ~2G-cell cost
tensor), so the suite gates it behind PROTOCOL_TPU_SCALE_TESTS=1:

    PROTOCOL_TPU_SCALE_TESTS=1 python -m pytest tests/test_scale_matcher.py

(`make scale-tests` runs exactly that.) The always-on reduced-scale
equivalents live in tests/test_sparse_matcher.py.
"""

import os
import time

import pytest

from protocol_tpu.sched import TpuBatchMatcher
from protocol_tpu.store import StoreContext

from tests.test_sparse_matcher import mk_bounded_task, mk_node

pytestmark = pytest.mark.skipif(
    os.environ.get("PROTOCOL_TPU_SCALE_TESTS") != "1",
    reason="scale test (~4 min CPU); set PROTOCOL_TPU_SCALE_TESTS=1",
)

N_NODES = 100_000
N_SLOTS = 10_000


def build_ctx():
    ctx = StoreContext.new_test()
    for i in range(N_NODES):
        ctx.node_store.add_node(mk_node(f"0x{i:040x}"))
    ctx.task_store.add_task(mk_bounded_task("big", 100, replicas=N_SLOTS))
    return ctx


def test_100k_nodes_10k_slots_sparse_local_and_warm_speedup():
    ctx = build_ctx()
    m = TpuBatchMatcher(ctx, min_solve_interval=0, top_k=16)
    t0 = time.perf_counter()
    m.refresh()
    cold = time.perf_counter() - t0
    st = m.last_solve_stats
    assert st["kernel"] == "sparse_topk"
    assert st["assigned"] == N_SLOTS
    assert st["truncated_replica_slots"] == 0

    # warm twice: the second excludes the one-time warm-kernel compile
    m.mark_dirty(); m.refresh()
    assert m.last_solve_stats["warm"] is True
    m.mark_dirty()
    t0 = time.perf_counter()
    m.refresh()
    warm = time.perf_counter() - t0
    assert m.last_solve_stats["assigned"] == N_SLOTS
    assert cold / warm >= 10.0, f"warm speedup only {cold / warm:.1f}x"


def test_100k_nodes_10k_slots_over_grpc():
    from protocol_tpu.services import scheduler_grpc

    server = scheduler_grpc.serve(address="127.0.0.1:50079")
    try:
        ctx = build_ctx()
        m = scheduler_grpc.RemoteBatchMatcher(
            ctx, address="127.0.0.1:50079", min_solve_interval=0, top_k=16
        )
        m.refresh()
        st = m.last_solve_stats
        assert st["kernel"] == "sparse_topk"
        assert st["assigned"] == N_SLOTS
        assert st["remote_calls"] >= 1
    finally:
        server.stop(grace=None)
