"""VERDICT r2 done-bars at full scale: 100k nodes x 10k replica slots
through the sparse production path, locally and over gRPC, plus the
warm >= 10x incremental-solve claim — measured, not asserted.

~3-4 min on the CI CPU (the cold candidate pass streams a ~2G-cell cost
tensor), so the suite gates it behind PROTOCOL_TPU_SCALE_TESTS=1:

    PROTOCOL_TPU_SCALE_TESTS=1 python -m pytest tests/test_scale_matcher.py

(`make scale-tests` runs exactly that.) The always-on reduced-scale
equivalents live in tests/test_sparse_matcher.py.
"""

import os
import time

import pytest

from protocol_tpu.sched import TpuBatchMatcher
from protocol_tpu.store import StoreContext

from tests.test_sparse_matcher import mk_bounded_task, mk_node

pytestmark = pytest.mark.skipif(
    os.environ.get("PROTOCOL_TPU_SCALE_TESTS") != "1",
    reason="scale test (~4 min CPU); set PROTOCOL_TPU_SCALE_TESTS=1",
)

N_NODES = 100_000
N_SLOTS = 10_000


def build_ctx():
    ctx = StoreContext.new_test()
    for i in range(N_NODES):
        ctx.node_store.add_node(mk_node(f"0x{i:040x}"))
    ctx.task_store.add_task(mk_bounded_task("big", 100, replicas=N_SLOTS))
    return ctx


def test_100k_nodes_10k_slots_sparse_local_and_warm_speedup():
    ctx = build_ctx()
    m = TpuBatchMatcher(ctx, min_solve_interval=0, top_k=16)
    t0 = time.perf_counter()
    m.refresh()
    cold = time.perf_counter() - t0
    st = m.last_solve_stats
    assert st["kernel"] == "sparse_topk"
    assert st["assigned"] == N_SLOTS
    assert st["truncated_replica_slots"] == 0

    # warm twice: the second excludes the one-time warm-kernel compile
    m.mark_dirty(); m.refresh()
    assert m.last_solve_stats["warm"] is True
    m.mark_dirty()
    t0 = time.perf_counter()
    m.refresh()
    warm = time.perf_counter() - t0
    assert m.last_solve_stats["assigned"] == N_SLOTS
    assert cold / warm >= 10.0, f"warm speedup only {cold / warm:.1f}x"


def test_100k_nodes_10k_slots_over_grpc():
    from protocol_tpu.services import scheduler_grpc

    server = scheduler_grpc.serve(address="127.0.0.1:50079")
    try:
        ctx = build_ctx()
        m = scheduler_grpc.RemoteBatchMatcher(
            ctx, address="127.0.0.1:50079", min_solve_interval=0, top_k=16
        )
        m.refresh()
        st = m.last_solve_stats
        assert st["kernel"] == "sparse_topk"
        assert st["assigned"] == N_SLOTS
        assert st["remote_calls"] >= 1
    finally:
        server.stop(grace=None)


def test_16k_warm_solve_at_least_2x_faster_than_cold():
    """VERDICT r4 item 2's done-bar at the kernel level: warm >= 2x faster
    than the cold ladder at a contended bench-shaped 16k instance (r4 had
    measured warm 5.5x SLOWER at 65k -- root causes and their always-on
    mechanism tests live in test_sparse.TestWarmColdRegression)."""
    import bench
    import jax
    import jax.numpy as jnp
    import numpy as np

    from protocol_tpu.ops.cost import CostWeights
    from protocol_tpu.ops.sparse import (
        assign_auction_sparse_scaled,
        assign_auction_sparse_warm,
        candidates_topk_bidir,
    )

    T = 16384
    rng = np.random.default_rng(0)
    ep = bench.synth_providers(rng, T)
    er = bench.synth_requirements(rng, T)
    bp, bc = candidates_topk_bidir(
        ep, er, CostWeights(), k=64, tile=2048, reverse_r=8, extra=16
    )
    jax.block_until_ready((bp, bc))

    def cold():
        out = assign_auction_sparse_scaled(
            bp, bc, num_providers=T, frontier=8192, with_state=True
        )
        jax.block_until_ready(out[1])
        return out

    res, price, retired = cold()  # compile
    t0 = time.perf_counter(); res, price, retired = cold()
    t_cold = time.perf_counter() - t0

    p4t0 = jnp.asarray(res.provider_for_task).at[: T // 100].set(-1)

    def warm():
        r, p = assign_auction_sparse_warm(
            bp, bc, num_providers=T, price0=price, p4t0=p4t0,
            retired0=retired, frontier=8192,
        )
        jax.block_until_ready(p)
        return r

    warm()  # compile
    t0 = time.perf_counter(); res_w = warm()
    t_warm = time.perf_counter() - t0

    a_cold = int(np.asarray(res.provider_for_task >= 0).sum())
    a_warm = int(np.asarray(res_w.provider_for_task >= 0).sum())
    assert a_warm >= a_cold - 2
    assert t_warm * 2.0 <= t_cold, (
        f"warm {t_warm:.2f}s not >= 2x faster than cold {t_cold:.2f}s"
    )
