"""First-class JAX engine (ISSUE 17): the warm-solve arena behind the
native engine interface.

Contracts under test, at unit grain:

  - the arena's native-parity surface (cold/warm/short-circuit flows,
    honest ``cand_cold_passes`` reporting, heavy-churn cold fallback,
    unprimed/weights-mismatch refusals);
  - the regen-exactness contract (a warm chain's candidate structure is
    bit-identical to a from-scratch rebuild on the current columns);
  - device-count INVARIANCE of sharded generation (D=1 == D=4 == D=8,
    bit for bit, through the ``parallel/_compat`` shard_map shim on the
    conftest's virtual 8-device CPU mesh) — the property that makes the
    warm carry sound across device-count changes;
  - degradation INSIDE the engine: over-asking for devices clamps with
    a counted, non-fatal provenance flag, never a silent native
    fallback;
  - export/restore of the warm chain (checkpoint + migration seam),
    including the honest cold re-ground on a foreign backend tag;
  - engine selection through every surface: the arena factory, the
    session kernel string, the matcher kwarg, golden-trace replay, and
    the gRPC drain/restart checkpoint cycle.

The CI-grade gates (full golden replay identity, warm-carry speedup
floor, assigned-fraction floor vs native) live in ``perf_gate.py
--jax``.
"""

import dataclasses
import os

import numpy as np
import pytest

import jax

from protocol_tpu.ops.cost import CostWeights
from protocol_tpu.parallel.jax_arena import JaxSolveArena, jax_isa

from tests.test_sparse import encode_random_marketplace

GOLDEN_JAX = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "artifacts", "golden_trace_512x512_jax.trace",
)


def _unique_seats(p4t: np.ndarray) -> None:
    pos = p4t[p4t >= 0]
    assert np.unique(pos).size == pos.size


def _marketplace(seed=3, P=96, T=64):
    return encode_random_marketplace(seed, P, T)


def _bump_price(ep, rows, delta=0.25):
    price = np.array(ep.price, copy=True)
    price[list(rows)] += delta
    return dataclasses.replace(ep, price=price)


class TestJaxArenaWarmChain:
    def test_cold_solve_contract(self):
        ep, er = _marketplace()
        arena = JaxSolveArena(k=16)
        p4t = arena.solve(ep, er, CostWeights())
        _unique_seats(p4t)
        s = arena.last_stats
        assert s["engine"] == "jax"
        assert s["cold"] is True
        assert s["cand_cold_passes"] == 1
        assert s["native_isa"] == jax_isa() == "jax:cpu"
        assert s["assigned"] == int((p4t >= 0).sum()) > 0

    def test_byte_identical_marketplace_short_circuits(self):
        ep, er = _marketplace()
        arena = JaxSolveArena(k=16)
        first = arena.solve(ep, er, CostWeights())
        again = arena.solve(ep, er, CostWeights())
        np.testing.assert_array_equal(first, again)
        s = arena.last_stats
        assert s["cold"] is False
        assert s["cand_cold_passes"] == 0
        assert s["changed_rows"] == 0
        assert s["warm_solves_since_cold"] == 1

    def test_warm_churn_repairs_without_cold_pass(self):
        """A dirty provider rides the warm REPAIR path: zero full gen
        passes, and the stats carry the honest repair-scope counters
        (recomputed forward rows / reverse pools, visited-cell
        fraction) instead of a regen claim."""
        ep, er = _marketplace()
        arena = JaxSolveArena(k=16)
        arena.solve(ep, er, CostWeights())
        p4t = arena.solve(_bump_price(ep, [5]), er, CostWeights())
        _unique_seats(p4t)
        s = arena.last_stats
        assert s["cold"] is False
        assert s["cand_cold_passes"] == 0  # churn-masked repair, not regen
        assert s["dirty_providers"] == 1
        assert s["dirty_tasks"] == 0
        assert s["repair_rows"] >= 0 and s["repair_providers"] >= 1
        assert 0.0 < s["visited_cells_frac"] < 1.0

    def test_approx_recall_keeps_honest_regen_path(self):
        """approx_max_k selection has no exactness contract, so approx
        arenas carry no repair parts and a dirty warm tick still pays
        (and reports) one full gen pass."""
        ep, er = _marketplace()
        arena = JaxSolveArena(k=16, approx_recall=0.95)
        arena.solve(ep, er, CostWeights())
        assert arena._fwd_p is None
        arena.solve(_bump_price(ep, [5]), er, CostWeights())
        s = arena.last_stats
        assert s["cold"] is False
        assert s["cand_cold_passes"] == 1
        assert "visited_cells_frac" not in s

    def test_regen_equals_cold_rebuild_bit_for_bit(self):
        """The regen-exactness contract: after a churned warm tick the
        carried candidate structure equals a fresh arena's cold build
        on the same columns — no drifting cache, ever."""
        ep, er = _marketplace()
        arena = JaxSolveArena(k=16)
        arena.solve(ep, er, CostWeights())
        ep2 = _bump_price(ep, [1, 7, 11])
        arena.solve(ep2, er, CostWeights())

        fresh = JaxSolveArena(k=16)
        fresh.solve(ep2, er, CostWeights())
        np.testing.assert_array_equal(arena._cand_p, fresh._cand_p)
        np.testing.assert_array_equal(arena._cand_c, fresh._cand_c)

    def test_reconcile_matches_cold_ladder(self):
        """reconcile() re-solves the current structure from scratch
        duals: bit-identical to a cold solve on the current columns
        (regen exactness means the structures agree), without paying
        the gen pass."""
        ep, er = _marketplace()
        arena = JaxSolveArena(k=16)
        arena.solve(ep, er, CostWeights())
        arena.solve(_bump_price(ep, [2]), er, CostWeights())
        p4t = arena.reconcile()
        s = arena.last_stats
        assert s["reconcile"] is True and s["cand_cold_passes"] == 0

        fresh = JaxSolveArena(k=16)
        ref = fresh.solve(_bump_price(ep, [2]), er, CostWeights())
        np.testing.assert_array_equal(p4t, ref)

    def test_heavy_churn_falls_back_to_cold(self):
        ep, er = _marketplace()
        arena = JaxSolveArena(k=16, max_dirty_frac=0.1)
        arena.solve(ep, er, CostWeights())
        arena.solve(_bump_price(ep, range(48)), er, CostWeights())
        assert arena.last_stats["cold"] is True

    def test_weights_change_regrounds_cold(self):
        ep, er = _marketplace()
        arena = JaxSolveArena(k=16)
        arena.solve(ep, er, CostWeights())
        arena.solve(ep, er, CostWeights(price=2.0))
        assert arena.last_stats["cold"] is True

    def test_apply_rows_refusals(self):
        ep, er = _marketplace()
        arena = JaxSolveArena(k=16)
        with pytest.raises(RuntimeError, match="not primed"):
            arena.apply_rows(None, None, None, None, CostWeights())
        arena.solve(ep, er, CostWeights())
        with pytest.raises(ValueError, match="different weights"):
            arena.apply_rows(
                None, None, None, None, CostWeights(price=3.0)
            )

    def test_apply_rows_event_flow(self):
        from protocol_tpu.native.arena import _canon, _P_SPEC

        ep, er = _marketplace()
        arena = JaxSolveArena(k=16)
        base = arena.solve(ep, er, CostWeights())

        # no-op event (values equal the current columns): short-circuit
        pf = _canon(ep, _P_SPEC)
        rows = np.array([4], np.int32)
        vals = {n: np.asarray(pf[n][rows]) for n, _ in _P_SPEC}
        p4t = arena.apply_rows(rows, vals, None, None, CostWeights())
        np.testing.assert_array_equal(p4t, base)
        assert arena.last_stats["dirty_providers"] == 0
        assert arena.last_stats["cand_cold_passes"] == 0

        # a real reprice: dirty, O(churned rows) structure repair +
        # warm solve — zero full gen passes, repair mask set
        vals["price"] = np.asarray(vals["price"]) + 0.5
        p4t = arena.apply_rows(rows, vals, None, None, CostWeights())
        _unique_seats(p4t)
        s = arena.last_stats
        assert s["event"] is True and s["dirty_providers"] == 1
        assert s["cand_cold_passes"] == 0
        assert s["repair_providers"] >= 1
        assert s["visited_cells_frac"] < 1.0
        assert arena.last_repair_mask is not None
        # the event's repaired structure equals a fresh cold build on
        # the updated columns (the repaired==regen oracle contract)
        ep2 = _bump_price(ep, [4], delta=0.5)
        fresh = JaxSolveArena(k=16)
        fresh.solve(ep2, er, CostWeights())
        np.testing.assert_array_equal(arena._cand_p, fresh._cand_p)
        np.testing.assert_array_equal(arena._cand_c, fresh._cand_c)


class TestJitCacheWitness:
    """Runtime twin of the jax-retrace static pass: compilations per
    jit entry, counted by the ``protocol_tpu.utils.jitwitness`` patch
    that ``protocol_tpu/ops/__init__.py`` installs before any kernel
    decorator runs. ``perf_gate --jax`` arms it and fails on ANY
    recompile after the warm chain's warm-up boundary."""

    def test_shape_churn_counts_a_recompile_cache_hit_does_not(self):
        import jax.numpy as jnp

        from protocol_tpu.utils import jitwitness

        mark = jitwitness.snapshot()

        @jax.jit
        def _witness_probe(x):
            return x * 2

        _witness_probe(jnp.zeros(8, jnp.float32))
        _witness_probe(jnp.zeros(8, jnp.float32))  # cache hit
        d = jitwitness.delta(mark)
        entries = [k for k in d if "_witness_probe" in k]
        assert len(entries) == 1, d
        assert d[entries[0]] == 1  # one trace, not two
        _witness_probe(jnp.zeros(16, jnp.float32))  # forced shape churn
        assert jitwitness.delta(mark)[entries[0]] == 2

    def test_warm_repair_tick_is_compile_free(self):
        """The warm-path economics the witness gates: the FIRST warm
        repair tick may engage lazily-built kernels; a repeat tick with
        the same churn profile must replay the compiled cache only."""
        from protocol_tpu.utils import jitwitness

        ep, er = _marketplace()
        arena = JaxSolveArena(k=16)
        arena.solve(ep, er, CostWeights())
        arena.solve(_bump_price(ep, [5]), er, CostWeights())  # warm-up
        mark = jitwitness.snapshot()
        arena.solve(_bump_price(ep, [9]), er, CostWeights())
        assert arena.last_stats["cand_cold_passes"] == 0
        assert jitwitness.delta(mark) == {}, (
            "a settled warm repair tick hit the tracer"
        )

    def test_gate_fails_under_injected_warm_tick_retrace(self):
        """The perf_gate assertion, demonstrated without paying a 4096
        chain: a deliberately shape-churned 'warm tick' is a counted
        recompile, and the gate's failure predicate trips on it."""
        import jax.numpy as jnp

        from protocol_tpu.utils import jitwitness
        from scripts.perf_gate import _warm_recompile_failures

        @jax.jit
        def _retrace_probe(x):
            return x + 1

        _retrace_probe(jnp.zeros(8, jnp.float32))  # warm-up compile
        mark = jitwitness.snapshot()
        _retrace_probe(jnp.zeros(32, jnp.float32))  # the injected retrace
        delta = jitwitness.delta(mark)
        assert delta, "witness missed the injected retrace"
        failures = _warm_recompile_failures(delta, budget=0)
        assert failures and "hit the tracer" in failures[0]
        assert "_retrace_probe" in failures[0]
        # and the green path: an empty delta produces no failure
        assert _warm_recompile_failures({}, budget=0) == []

    def test_last_stats_surface_is_env_gated(self, monkeypatch):
        from protocol_tpu.utils import jitwitness

        monkeypatch.delenv("PROTOCOL_TPU_JIT_WITNESS", raising=False)
        ep, er = _marketplace()
        arena = JaxSolveArena(k=16)
        arena.solve(ep, er, CostWeights())
        assert "jit_compiles" not in arena.last_stats

        monkeypatch.setenv("PROTOCOL_TPU_JIT_WITNESS", "1")
        assert jitwitness.enabled()
        armed = JaxSolveArena(k=16)
        armed.solve(ep, er, CostWeights())
        s = armed.last_stats
        assert s["jit_compiles"] >= 1  # this process traced SOMETHING
        assert isinstance(s["jit_compiles_delta"], dict)
        # a byte-identical re-solve short-circuits: no tracing at all
        armed.solve(ep, er, CostWeights())
        assert armed.last_stats["jit_compiles_delta"] == {}


class TestDeviceInvarianceAndDegradation:
    """Satellite 4: the shard_map shim's D-invariance at arena grain,
    and the degrade-inside-the-engine contract."""

    @pytest.mark.parametrize("D", [2, 4, 8])
    def test_sharded_gen_is_device_count_invariant(self, D):
        ep, er = _marketplace(seed=9, P=128, T=64)
        ref = JaxSolveArena(k=16, devices=1)
        sharded = JaxSolveArena(k=16, devices=D)
        p_ref = ref.solve(ep, er, CostWeights())
        p_d = sharded.solve(ep, er, CostWeights())
        assert ref.last_stats["gen_sharded"] is False
        assert sharded.last_stats["gen_sharded"] is True
        assert sharded.last_stats["jax_devices"] == D
        np.testing.assert_array_equal(ref._cand_p, sharded._cand_p)
        np.testing.assert_array_equal(ref._cand_c, sharded._cand_c)
        np.testing.assert_array_equal(p_ref, p_d)
        np.testing.assert_array_equal(ref.price, sharded.price)

        # the warm tick stays on the invariant too — and both sides
        # ride the repair path, not a regen
        ep2 = _bump_price(ep, [3])
        np.testing.assert_array_equal(
            ref.solve(ep2, er, CostWeights()),
            sharded.solve(ep2, er, CostWeights()),
        )
        assert ref.last_stats["cand_cold_passes"] == 0
        assert sharded.last_stats["cand_cold_passes"] == 0
        np.testing.assert_array_equal(ref._fwd_c, sharded._fwd_c)
        np.testing.assert_array_equal(ref._pool_t, sharded._pool_t)

    @pytest.mark.parametrize("D", [2, 4])
    def test_apply_rows_rides_repair_at_many_devices(self, D):
        """Stream events over the SHARDED repair path: a dirty event on
        a D-device arena patches the structure with the sharded repair
        kernels (zero cold passes) and lands exactly the structure a
        fresh cold build at the same D produces."""
        from protocol_tpu.native.arena import _P_SPEC, _canon

        ep, er = _marketplace(seed=9, P=128, T=64)
        arena = JaxSolveArena(k=16, devices=D)
        arena.solve(ep, er, CostWeights())
        assert arena.last_stats["gen_sharded"] is True

        pf = _canon(ep, _P_SPEC)
        rows = np.array([7], np.int32)
        vals = {n: np.asarray(pf[n][rows]) for n, _ in _P_SPEC}
        vals["price"] = np.asarray(vals["price"]) + 0.5
        p4t = arena.apply_rows(rows, vals, None, None, CostWeights())
        _unique_seats(p4t)
        s = arena.last_stats
        assert s["event"] is True and s["cand_cold_passes"] == 0
        assert s["gen_sharded"] is True and s["repair_providers"] >= 1

        fresh = JaxSolveArena(k=16, devices=D)
        fresh.solve(_bump_price(ep, [7], delta=0.5), er, CostWeights())
        np.testing.assert_array_equal(arena._cand_p, fresh._cand_p)
        np.testing.assert_array_equal(arena._cand_c, fresh._cand_c)
        np.testing.assert_array_equal(arena._pool_c, fresh._pool_c)

    @pytest.mark.slow
    def test_sharded_gen_invariant_at_16k(self):
        """The acceptance shape (ISSUE 17): D=1 and D=4 produce the
        identical candidate structure at 16k. Generation only — the
        solve's D-independence is pinned by the fast tests above and
        the tick is ~30 s per side at this scale."""
        import bench
        from protocol_tpu.native.arena import _P_SPEC, _R_SPEC, _canon

        n = 16384
        ep = bench.synth_providers(np.random.default_rng(2), n)
        er = bench.synth_requirements(np.random.default_rng(3), n)
        pf, rf = _canon(ep, _P_SPEC), _canon(er, _R_SPEC)
        g1 = JaxSolveArena(devices=1)
        cp1, cc1, sh1 = g1._gen(pf, rf, CostWeights())
        g4 = JaxSolveArena(devices=4)
        cp4, cc4, sh4 = g4._gen(pf, rf, CostWeights())
        assert sh1 is False and sh4 is True
        np.testing.assert_array_equal(cp1, cp4)
        np.testing.assert_array_equal(cc1, cc4)

    def test_indivisible_task_count_degrades_to_single_device(self):
        """T % D != 0: generation runs single-device (flagged), still
        the jax engine, still the same bit-exact structure."""
        ep, er = _marketplace(seed=9, P=96, T=63)
        arena = JaxSolveArena(k=16, devices=4)
        arena.solve(ep, er, CostWeights())
        assert arena.last_stats["engine"] == "jax"
        assert arena.last_stats["gen_sharded"] is False

        ref = JaxSolveArena(k=16, devices=1)
        ref.solve(ep, er, CostWeights())
        np.testing.assert_array_equal(ref._cand_p, arena._cand_p)

    def test_device_overask_clamps_counted_never_native(self):
        """Asking for more devices than the host exposes (the 'missing
        accelerator' shape: kernel jax:64 on an 8-device host) clamps
        to what exists with a counted non-fatal flag. The solve is
        still a jax solve — bit-identical to devices=all — NEVER a
        silent fallback to the native engine."""
        avail = jax.local_device_count()
        ep, er = _marketplace(seed=9, P=128, T=64)
        arena = JaxSolveArena(k=16, devices=avail * 8)
        p4t = arena.solve(ep, er, CostWeights())
        assert arena.device_degraded is True
        assert arena.device_degraded_events == 1
        s = arena.last_stats
        assert s["engine"] == "jax"  # degraded INSIDE the engine
        assert s["device_degraded"] is True
        assert s["jax_devices"] == avail

        ref = JaxSolveArena(k=16, devices=0)  # 0 = all visible
        np.testing.assert_array_equal(
            ref.solve(ep, er, CostWeights()), p4t
        )
        assert ref.device_degraded is False

    def test_compat_shim_exports_shard_map(self):
        """The parallel/_compat seam every mesh kernel imports through:
        present and callable on this runtime (promoted or experimental
        home — the shim hides which)."""
        from protocol_tpu.parallel import _compat

        assert callable(_compat.shard_map)


class TestExportRestore:
    def test_roundtrip_continues_bit_identically(self):
        ep, er = _marketplace()
        arena = JaxSolveArena(k=16)
        arena.solve(ep, er, CostWeights())
        ep2 = _bump_price(ep, [5])
        arena.solve(ep2, er, CostWeights())
        state = arena.export_state()
        assert state["native_isa"] == jax_isa()

        other = JaxSolveArena(k=16)
        other.restore_state(ep2, er, state)
        ep3 = _bump_price(ep, [5, 9])
        got = other.solve(ep3, er, CostWeights())
        want = arena.solve(ep3, er, CostWeights())
        np.testing.assert_array_equal(got, want)
        assert other.last_stats["cold"] is False  # warm chain continued
        np.testing.assert_array_equal(other.price, arena.price)

    def test_export_is_a_copy_not_an_alias(self):
        ep, er = _marketplace()
        arena = JaxSolveArena(k=16)
        arena.solve(ep, er, CostWeights())
        state = arena.export_state()
        state["price"][:] = -1
        assert not np.array_equal(state["price"], arena.price)

    def test_foreign_backend_tag_regrounds_cold(self):
        """A carry exported under another float pipeline (the native
        engine, or jax on a different XLA backend) is refused into an
        honest cold re-ground — never warm-continued on costs this
        engine didn't score."""
        ep, er = _marketplace()
        arena = JaxSolveArena(k=16)
        arena.solve(ep, er, CostWeights())
        state = arena.export_state()
        state["native_isa"] = "avx2"  # a native-arena export

        other = JaxSolveArena(k=16)
        other.restore_state(ep, er, state)
        other.solve(ep, er, CostWeights())
        assert other.last_stats["cold"] is True

    def test_candidate_width_mismatch_regrounds_cold(self):
        ep, er = _marketplace()
        arena = JaxSolveArena(k=16)
        arena.solve(ep, er, CostWeights())
        state = arena.export_state()

        other = JaxSolveArena(k=8)  # narrower structure: carry invalid
        other.restore_state(ep, er, state)
        other.solve(ep, er, CostWeights())
        assert other.last_stats["cold"] is True

    def test_restored_carry_continues_on_repair_path(self):
        """The persistent parts ride export/restore: a restored warm
        chain's next dirty tick runs the churn-masked repair (zero cold
        passes), not a regen — and lands the same structure the
        exporting arena reaches."""
        ep, er = _marketplace()
        arena = JaxSolveArena(k=16)
        arena.solve(ep, er, CostWeights())
        state = arena.export_state()
        for name in ("fwd_p", "fwd_c", "pool_t", "pool_c"):
            assert state[name] is not None

        other = JaxSolveArena(k=16)
        other.restore_state(ep, er, state)
        ep2 = _bump_price(ep, [3])
        got = other.solve(ep2, er, CostWeights())
        assert other.last_stats["cand_cold_passes"] == 0
        assert other.last_stats["repair_providers"] >= 1
        want = arena.solve(ep2, er, CostWeights())
        np.testing.assert_array_equal(got, want)
        np.testing.assert_array_equal(other._fwd_p, arena._fwd_p)
        np.testing.assert_array_equal(other._pool_c, arena._pool_c)

    def test_pre_repair_carry_regrounds_cold(self):
        """A carry exported before the repair parts existed (an old
        checkpoint: merged lists only) degrades to an honest cold
        re-ground — never a shape error, never a warm continuation
        that would regenerate parts against a stale merge."""
        ep, er = _marketplace()
        arena = JaxSolveArena(k=16)
        arena.solve(ep, er, CostWeights())
        state = arena.export_state()
        for name in ("fwd_p", "fwd_c", "pool_t", "pool_c"):
            del state[name]  # what a pre-repair export looks like

        other = JaxSolveArena(k=16)
        other.restore_state(ep, er, state)
        other.solve(ep, er, CostWeights())
        assert other.last_stats["cold"] is True

    def test_part_shape_skew_regrounds_cold(self):
        """Part-width skew (reverse_r config changed between export and
        restore) is refused like a foreign tag — cold, not a crash."""
        ep, er = _marketplace()
        arena = JaxSolveArena(k=16, reverse_r=8)
        arena.solve(ep, er, CostWeights())
        state = arena.export_state()

        other = JaxSolveArena(k=16, reverse_r=4)
        other.restore_state(ep, er, state)
        other.solve(ep, er, CostWeights())
        assert other.last_stats["cold"] is True


class TestEngineSelectionSurfaces:
    def test_arena_factory(self):
        from protocol_tpu.services.session_store import make_solve_arena

        arena = make_solve_arena("jax", k=16, threads=2)
        assert isinstance(arena, JaxSolveArena)
        assert arena.devices == 2  # the suffix is the DEVICE count
        assert arena.engine == "jax"

    def test_session_kernel_string(self):
        from protocol_tpu.services.session_store import (
            parse_session_kernel,
        )

        assert parse_session_kernel("jax") == ("jax", 0)
        assert parse_session_kernel("jax:4") == ("jax", 4)
        assert parse_session_kernel("jax:x") is None

    def test_replay_engine_string(self):
        from protocol_tpu.trace.replay import parse_engine

        assert parse_engine("jax") == ("jax", 0)
        assert parse_engine("jax:2") == ("jax", 2)

    def test_matcher_kwarg_bad_suffix_refused(self):
        from protocol_tpu.sched.tpu_backend import TpuBatchMatcher
        from protocol_tpu.store import StoreContext

        with pytest.raises(ValueError, match="jax device suffix"):
            TpuBatchMatcher(
                StoreContext.new_test(), native_engine="jax:x"
            )

    def test_matcher_engages_jax_arena(self):
        """TpuBatchMatcher(native_engine='jax') routes phase 1 through
        the jax arena as a first-class engine — no native_fallback
        required — and the steady state doesn't flap."""
        import random

        from protocol_tpu.models.task import (
            SchedulingConfig,
            Task,
            TaskRequest,
        )
        from protocol_tpu.sched.tpu_backend import TpuBatchMatcher
        from protocol_tpu.store import (
            NodeStatus,
            OrchestratorNode,
            StoreContext,
        )
        from tests.test_encoding import random_specs

        rng = random.Random(5)
        store = StoreContext.new_test()
        for i in range(12):
            store.node_store.add_node(
                OrchestratorNode(
                    address=f"0xjx{i:02d}",
                    status=NodeStatus.HEALTHY,
                    compute_specs=random_specs(rng),
                )
            )
        store.task_store.add_task(
            Task.from_request(
                TaskRequest(
                    name="jx-b",
                    image="img",
                    scheduling_config=SchedulingConfig(
                        plugins={"tpu_scheduler": {"replicas": ["4"]}}
                    ),
                )
            )
        )
        m = TpuBatchMatcher(
            store, min_solve_interval=0.0, native_engine="jax",
        )
        m.refresh()
        assert m.last_solve_stats["kernel"] == "jax_arena"
        assert m.last_solve_stats["arena_cold"] is True
        assert m.last_solve_stats["arena_engine"] == "jax"
        first = dict(m._assignment)
        m.mark_dirty()
        m.refresh()
        assert m.last_solve_stats["arena_cold"] is False
        assert m.last_solve_stats["arena_changed_rows"] == 0
        assert m._assignment == first

    @pytest.mark.skipif(
        not os.path.exists(GOLDEN_JAX), reason="no committed jax golden"
    )
    def test_golden_replay_identity_smoke(self):
        """The committed jax golden replays bit-identically under
        engine=jax (first ticks — the full 9-tick identity + floors
        run in ``perf_gate.py --jax`` and the CI replay job)."""
        from protocol_tpu.trace.replay import replay

        rep = replay(GOLDEN_JAX, engine="jax", max_ticks=3)
        assert rep["divergence"] is None
        assert rep["verified_ticks"] == rep["ticks"] == 3


class TestGrpcAndCheckpoint:
    """The gRPC kernel surface end to end: sessions solve on the jax
    arena, drain flushes its warm state through the engine-blind
    checkpoint frames, and a restarted servicer resumes the SAME warm
    chain (no cold reopen herd)."""

    def test_drain_restart_resumes_jax_warm(self, tmp_path):
        from protocol_tpu.fleet.fabric import FleetConfig
        from protocol_tpu.parallel.jax_arena import JaxSolveArena
        from protocol_tpu.services.scheduler_grpc import (
            RemoteBatchMatcher,
            drain,
            serve,
        )
        from tests.test_faults import (
            _assert_shadow_matches_server,
            _free_port,
        )
        from tests.test_scheduler_grpc import _pool_world

        port = _free_port()
        addr = f"127.0.0.1:{port}"
        fleet = FleetConfig(shards=2, ckpt_dir=str(tmp_path))
        server = serve(addr, fleet=fleet)
        store = _pool_world()
        m = RemoteBatchMatcher(
            store, addr, min_solve_interval=0.0, wire="v2",
            native_fallback=True, native_engine="jax",
            retry_base_s=0.01,
        )
        try:
            m.refresh()
            m.refresh()
            assert m._session["tick"] == 1
            sess = server.servicer.sessions.get(
                m._session["id"], m._session["fp"]
            )[0]
            assert isinstance(sess.arena, JaxSolveArena)

            flushed = drain(server)
            assert flushed == 1
            assert list(tmp_path.glob("**/*.ckpt"))

            server = serve(addr, fleet=fleet)
            seam = server.servicer.seam.snapshot()
            assert seam.get("session_session_restored") == 1

            m.refresh()
            snap = m.seam.snapshot()
            assert m._session["tick"] == 2
            assert "session_session_reopen" not in snap  # warm resume
            sess = server.servicer.sessions.get(
                m._session["id"], m._session["fp"]
            )[0]
            assert isinstance(sess.arena, JaxSolveArena)
            assert m._assignment
            _assert_shadow_matches_server(m, server)
        finally:
            m.client.close()
            server.stop(grace=None)
