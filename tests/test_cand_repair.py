"""Incremental candidate maintenance: the repair-vs-cold parity oracle.

The persistent structure's whole contract is ONE invariant: after any
churn tick, the repaired ``(cand_p, cand_c, rev)`` triple is bit-identical
to a from-scratch ``fused_topk_candidates(..., rev_out=...)`` build on the
current features — at every thread count, through either solve engine.
These tests drive randomized churn scripts (provider join/leave/mutate,
price/load drift, task churn, mass-disconnect — the trace/synth.py
workload vocabulary) against that oracle, plus the bucketed cold pruner's
own bit-identity and the export/restore carry of the reverse keys.
"""

import dataclasses

import numpy as np
import pytest

from protocol_tpu import native
from protocol_tpu.ops.cost import CostWeights

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no native toolchain"
)

W = CostWeights()
THREADS = (1, 2, 4)


def _pop(seed, n):
    from protocol_tpu.trace.synth import synth_providers, synth_requirements

    rng = np.random.default_rng(seed)
    return synth_providers(rng, n), synth_requirements(rng, n)


def _churn(rng, ep, er, P, T):
    """One randomized churn op in the trace/synth vocabulary; returns
    (ep, er, dirty_p idx, dirty_t idx)."""
    dp, dt = set(), set()
    kind = int(rng.integers(0, 5))
    if kind == 0:  # price/load drift (the per-heartbeat common case)
        rows = rng.choice(P, max(1, P // 50), replace=False)
        price = np.array(ep.price, copy=True)
        price[rows] = rng.uniform(0.5, 4.0, rows.size).astype(np.float32)
        load = np.array(ep.load, copy=True)
        load[rows] = rng.uniform(0, 1, rows.size).astype(np.float32)
        ep = dataclasses.replace(ep, price=price, load=load)
        dp.update(int(r) for r in rows)
    elif kind == 1:  # spec mutate (structural)
        rows = rng.choice(P, max(1, P // 100), replace=False)
        mem = np.array(ep.gpu_mem_mb, copy=True)
        mem[rows] = rng.choice([16000, 24000, 40000, 80000], rows.size)
        cores = np.array(ep.cpu_cores, copy=True)
        cores[rows] = rng.choice([8, 16, 32, 64], rows.size)
        ep = dataclasses.replace(ep, gpu_mem_mb=mem, cpu_cores=cores)
        dp.update(int(r) for r in rows)
    elif kind == 2:  # join/leave (validity flips both ways)
        rows = rng.choice(P, max(1, P // 50), replace=False)
        valid = np.array(ep.valid, copy=True)
        valid[rows] = ~valid[rows]
        ep = dataclasses.replace(ep, valid=valid)
        dp.update(int(r) for r in rows)
    elif kind == 3:  # task churn (requirement re-roll)
        rows = rng.choice(T, max(1, T // 100), replace=False)
        prio = np.array(er.priority, copy=True)
        prio[rows] += rng.uniform(0.1, 0.5, rows.size).astype(np.float32)
        ram = np.array(er.ram_mb, copy=True)
        ram[rows] = rng.choice([-1, 32768], rows.size)
        er = dataclasses.replace(er, priority=prio, ram_mb=ram)
        dt.update(int(r) for r in rows)
    else:  # mass-disconnect (the failure-domain drill)
        rows = rng.choice(P, P // 4, replace=False)
        valid = np.array(ep.valid, copy=True)
        valid[rows] = False
        ep = dataclasses.replace(ep, valid=valid)
        dp.update(int(r) for r in rows)
    return (
        ep, er,
        np.array(sorted(dp), np.int32), np.array(sorted(dt), np.int32),
    )


def _rebuild(ep, er, k, P):
    rev = np.zeros((P, 8), np.uint64)
    cp, cc = native.fused_topk_candidates(
        ep, er, W, k=k, reverse_r=8, extra=16, threads=2, rev_out=rev
    )
    return cp, cc, rev


class TestBucketedColdParity:
    @pytest.mark.parametrize("threads", THREADS)
    def test_bucketed_equals_full_scan(self, threads):
        """Bucketed == unbucketed within the v2 (persistent-structure)
        family: both dispatch through the same runtime ISA table
        (scalar/avx2/avx512, one fmaf-matched pipeline per ISA), so
        within a process the float pipeline is pinned and the pruner
        must reproduce the full scan bit-for-bit. The reference here
        is the v2 full scan (rev_out requested), not the legacy one."""
        ep, er = _pop(0, 384)
        rev_ref = np.zeros((384, 8), np.uint64)
        ref = native.fused_topk_candidates(
            ep, er, W, k=32, threads=1, rev_out=rev_ref
        )
        st: dict = {}
        got = native.fused_topk_candidates(
            ep, er, W, k=32, threads=threads, bucketed=True, stats=st
        )
        np.testing.assert_array_equal(got[0], ref[0])
        np.testing.assert_array_equal(got[1], ref[1])
        # the pruner genuinely pruned (synth GPU constraints are
        # selective) AND stayed exact
        assert st["gen_pruned_rows"] > 0
        assert st["gen_visited"] < 384 * 384

    def test_rev_export_matches_between_paths(self):
        ep, er = _pop(1, 256)
        rev_full = np.zeros((256, 8), np.uint64)
        rev_bkt = np.zeros((256, 8), np.uint64)
        native.fused_topk_candidates(
            ep, er, W, k=32, threads=2, rev_out=rev_full
        )
        native.fused_topk_candidates(
            ep, er, W, k=32, threads=1, bucketed=True, rev_out=rev_bkt
        )
        np.testing.assert_array_equal(rev_full, rev_bkt)


class TestRepairOracle:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_randomized_churn_scripts_repair_bit_identical(self, seed):
        """8 churn ticks, kernel-level: repaired structure == cold
        rebuild at threads {1, 2, 4}, every tick."""
        rng = np.random.default_rng(seed)
        P = T = int(rng.choice([192, 256]))
        k = int(rng.choice([16, 32]))
        ep, er = _pop(seed, P)
        structs = {}
        for thr in THREADS:
            rev = np.zeros((P, 8), np.uint64)
            cp, cc = native.fused_topk_candidates(
                ep, er, W, k=k, threads=thr, rev_out=rev, bucketed=True
            )
            structs[thr] = (cp, cc, rev)
        for tick in range(8):
            ep, er, dp, dt = _churn(rng, ep, er, P, T)
            masks = {}
            for thr in THREADS:
                cp, cc, rev = structs[thr]
                masks[thr] = native.repair_topk_candidates(
                    ep, er, W, cp, cc, rev, dp, dt, k=k, threads=thr
                )
            for thr in (2, 4):
                for a, b in zip(
                    structs[1] + masks[1], structs[thr] + masks[thr]
                ):
                    np.testing.assert_array_equal(
                        a, b,
                        err_msg=f"tick {tick} threads={thr} diverged",
                    )
            cp, cc, rev = structs[1]
            rp, rc, rrev = _rebuild(ep, er, k, P)
            np.testing.assert_array_equal(
                cp, rp, err_msg=f"tick {tick}: forward providers drifted"
            )
            np.testing.assert_array_equal(
                cc, rc, err_msg=f"tick {tick}: forward costs drifted"
            )
            np.testing.assert_array_equal(
                rev, rrev, err_msg=f"tick {tick}: reverse keys drifted"
            )

    def test_duplicate_dirty_ids_are_harmless(self):
        """The wrapper dedups dirty index sets: a duplicated provider id
        must not double-sweep its column (torn reverse list at
        threads>1, duplicated forward entrants in the merge pool)."""
        P = T = 192
        ep, er = _pop(5, P)
        rev = np.zeros((P, 8), np.uint64)
        cp, cc = native.fused_topk_candidates(
            ep, er, W, k=16, threads=2, rev_out=rev
        )
        price = np.array(ep.price, copy=True)
        price[7] *= 0.5
        ep2 = dataclasses.replace(ep, price=price)
        native.repair_topk_candidates(
            ep2, er, W, cp, cc, rev,
            np.array([7, 7, 7], np.int32), np.array([3, 3], np.int32),
            k=16, threads=4,
        )
        rp, rc, rrev = _rebuild(ep2, er, 16, P)
        np.testing.assert_array_equal(cp, rp)
        np.testing.assert_array_equal(cc, rc)
        np.testing.assert_array_equal(rev, rrev)

    def test_touched_covers_every_content_change(self):
        """The repair_mask contract: any row whose content moved must be
        flagged touched (a missed row would dodge the auction's eps-CS
        repair and the seat guard)."""
        rng = np.random.default_rng(7)
        P = T = 256
        ep, er = _pop(7, P)
        rev = np.zeros((P, 8), np.uint64)
        cp, cc = native.fused_topk_candidates(
            ep, er, W, k=16, threads=2, rev_out=rev
        )
        before_p, before_c = cp.copy(), cc.copy()
        ep2, er2, dp, dt = _churn(rng, ep, er, P, T)
        touched, changed = native.repair_topk_candidates(
            ep2, er2, W, cp, cc, rev, dp, dt, k=16, threads=2
        )
        moved = (cp != before_p).any(axis=1) | (cc != before_c).any(axis=1)
        assert not (moved & ~touched).any()
        assert not (changed & ~touched).any()  # changed implies touched


@pytest.mark.parametrize("engine", ["auction", "sinkhorn"])
class TestArenaStructureInvariant:
    def test_warm_chain_structure_equals_cold_rebuild(self, engine):
        """Arena-level oracle through both solve engines: after every
        warm tick the persistent structure matches a from-scratch build
        and the tick reports zero full-matrix passes."""
        from protocol_tpu.native.arena import NativeSolveArena

        rng = np.random.default_rng(11)
        P = T = 256
        ep, er = _pop(11, P)
        arena = NativeSolveArena(
            k=16, threads=2, engine=engine, cold_every=1_000_000
        )
        arena.solve(ep, er, W)
        assert arena.last_stats["cand_cold_passes"] == 1
        for tick in range(5):
            ep, er, _dp, _dt = _churn(rng, ep, er, P, T)
            p4t = arena.solve(ep, er, W)
            assert arena.last_stats["cold"] is False
            assert arena.last_stats["cand_cold_passes"] == 0
            pos = p4t[p4t >= 0]
            assert np.unique(pos).size == pos.size
            rp, rc, rrev = _rebuild(ep, er, 16, P)
            np.testing.assert_array_equal(arena._cand_p, rp)
            np.testing.assert_array_equal(arena._cand_c, rc)
            np.testing.assert_array_equal(arena._rev, rrev)

    def test_export_restore_carries_reverse_keys(self, engine):
        """A restored arena repairs warm on its first churn tick — the
        checkpoint/migration carry contract — and an OLD-format state
        dict (no cand_rev) degrades to an honest cold re-ground."""
        from protocol_tpu.native.arena import NativeSolveArena

        rng = np.random.default_rng(13)
        P = T = 192
        ep, er = _pop(13, P)
        src = NativeSolveArena(k=16, threads=2, engine=engine)
        src.solve(ep, er, W)
        state = src.export_state()
        assert state["cand_rev"] is not None

        dst = NativeSolveArena(k=16, threads=2, engine=engine)
        dst.restore_state(ep, er, state)
        ep2, er2, _dp, _dt = _churn(rng, ep, er, P, T)
        p4t_dst = dst.solve(ep2, er2, W)
        assert dst.last_stats["cold"] is False
        assert dst.last_stats["cand_cold_passes"] == 0
        p4t_src = src.solve(ep2, er2, W)
        np.testing.assert_array_equal(p4t_dst, p4t_src)

        legacy = {n: v for n, v in state.items() if n != "cand_rev"}
        old = NativeSolveArena(k=16, threads=2, engine=engine)
        old.restore_state(ep, er, legacy)
        old.solve(ep2, er2, W)
        assert old.last_stats["cold"] is True  # honest re-ground

        # config-skewed carry (exporter reverse_r != restorer's): the
        # same degrade contract — cold re-ground, never a mid-tick
        # shape error from the repair kernel
        skew = NativeSolveArena(
            k=16, threads=2, engine=engine, reverse_r=4
        )
        skew.restore_state(ep, er, state)
        skew.solve(ep2, er2, W)
        assert skew.last_stats["cold"] is True

        # half-present slack pair (partially written / version-skewed
        # checkpoint): the pair is dropped whole and the first churn
        # tick still repairs WARM (slack is an optimization) — never a
        # mid-tick wrapper error
        half = dict(state)
        half["cand_slack_c"] = None
        hl = NativeSolveArena(k=16, threads=2, engine=engine)
        hl.restore_state(ep, er, half)
        assert hl._slack_p is None and hl._slack_c is None
        hl.solve(ep2, er2, W)
        assert hl.last_stats["cold"] is False
        assert hl.last_stats["cand_cold_passes"] == 0
