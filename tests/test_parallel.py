"""Sharded auction on the virtual 8-device CPU mesh: exact parity with the
dense single-device kernel (same deterministic tie-breaking)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from protocol_tpu.ops.assign import assign_auction
from protocol_tpu.parallel import assign_auction_sharded, make_mesh

from tests.test_assign import check_feasible, random_cost


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("seed,P,T,D", [(0, 64, 48, 8), (1, 128, 64, 4), (2, 64, 96, 2)])
def test_sharded_matches_dense(seed, P, T, D):
    rng = np.random.default_rng(seed)
    cost = random_cost(rng, P, T, p_infeasible=0.15)
    mesh = make_mesh(D)
    res_sharded = assign_auction_sharded(jnp.asarray(cost), mesh, eps=0.05, max_iters=2000)
    res_dense = assign_auction(jnp.asarray(cost), eps=0.05, max_iters=2000)
    check_feasible(res_sharded, cost)
    np.testing.assert_array_equal(
        np.asarray(res_sharded.provider_for_task),
        np.asarray(res_dense.provider_for_task),
    )


def test_sharded_requires_divisible():
    mesh = make_mesh(8)
    with pytest.raises(ValueError):
        assign_auction_sharded(jnp.zeros((10, 4)), mesh)


def test_sharded_full_square_matching():
    rng = np.random.default_rng(3)
    n = 64
    cost = rng.uniform(0, 10, size=(n, n)).astype(np.float32)
    mesh = make_mesh(8)
    res = assign_auction_sharded(jnp.asarray(cost), mesh, eps=0.02, max_iters=5000)
    p4t = check_feasible(res, cost)
    assert (p4t >= 0).all()
