"""Service-level tests: discovery registration gates, orchestrator routes +
health FSM, validator synthetic-data pipeline against a mock toploc server."""

import pytest

# Environment guard: this module's import chain reaches
# protocol_tpu.security / protocol_tpu.utils.tls, which need the
# third-party `cryptography` package (wallet signing + TLS material).
# On hosts without it, report the whole module as SKIPPED instead of a
# collection error (tier-1 keeps an honest skip count; CI installs
# cryptography and runs everything).
pytest.importorskip(
    "cryptography", reason="cryptography not installed (signing/TLS dependency)"
)

import asyncio
import json
import time

from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from protocol_tpu.chain import Ledger
from protocol_tpu.models import ComputeSpecs, CpuSpecs, GpuSpecs, Node
from protocol_tpu.models.heartbeat import HeartbeatRequest
from protocol_tpu.security import Wallet, sign_request
from protocol_tpu.services.discovery import DiscoveryService
from protocol_tpu.services.orchestrator import OrchestratorService
from protocol_tpu.services.validator import (
    GroupKey,
    SyntheticDataValidator,
    ToplocClient,
    ValidationResult,
)
from protocol_tpu.store import NodeStatus, OrchestratorNode
from protocol_tpu.utils.storage import MockStorageProvider


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def specs():
    return ComputeSpecs(
        gpu=GpuSpecs(count=8, model="H100", memory_mb=80000),
        cpu=CpuSpecs(cores=32),
        ram_mb=65536,
        storage_gb=2000,
    )


def make_world(pool_requirements=""):
    ledger = Ledger()
    creator = Wallet.from_seed(b"creator")
    manager = Wallet.from_seed(b"manager")
    provider = Wallet.from_seed(b"provider-1")
    node = Wallet.from_seed(b"node-1")
    ledger.mint(provider.address, 1000)
    did = ledger.create_domain("synth")
    pid = ledger.create_pool(did, creator.address, manager.address, pool_requirements)
    ledger.start_pool(pid, creator.address)
    ledger.register_provider(provider.address, 100)
    ledger.whitelist_provider(provider.address)
    ledger.add_compute_node(provider.address, node.address)
    return ledger, creator, manager, provider, node, pid


class TestDiscovery:
    def _node_payload(self, node_wallet, provider_wallet, pid, with_specs=True):
        return Node(
            id=node_wallet.address,
            provider_address=provider_wallet.address,
            ip_address="10.0.0.1",
            port=8091,
            compute_pool_id=pid,
            compute_specs=specs() if with_specs else None,
        ).to_dict()

    def test_register_and_read(self):
        ledger, creator, manager, provider, node, pid = make_world()
        svc = DiscoveryService(ledger, pid)

        async def flow():
            async with TestClient(TestServer(svc.make_app())) as client:
                payload = self._node_payload(node, provider, pid)
                headers, body = sign_request("/api/nodes", node, payload)
                r = await client.put("/api/nodes", json=body, headers=headers)
                assert r.status == 200, await r.text()

                # unvalidated -> /api/validator view (signed)
                h2, _ = sign_request("/api/validator", manager)
                r2 = await client.get("/api/validator", headers=h2)
                data = await r2.json()
                assert len(data["data"]) == 1

                # validate on ledger -> chain sync -> pool view
                ledger.validate_node(node.address)
                svc.chain_sync_once()
                h3, _ = sign_request(f"/api/pool/{pid}", manager)
                r3 = await client.get(f"/api/pool/{pid}", headers=h3)
                pool_nodes = (await r3.json())["data"]
                assert [n["id"] for n in pool_nodes] == [node.address]

        run(flow())

    def test_register_rejects_wrong_address(self):
        ledger, creator, manager, provider, node, pid = make_world()
        svc = DiscoveryService(ledger, pid)
        rogue = Wallet.from_seed(b"rogue")

        async def flow():
            async with TestClient(TestServer(svc.make_app())) as client:
                payload = self._node_payload(node, provider, pid)
                headers, body = sign_request("/api/nodes", rogue, payload)
                r = await client.put("/api/nodes", json=body, headers=headers)
                assert r.status == 401

        run(flow())

    def test_register_requires_ledger_node(self):
        ledger, creator, manager, provider, node, pid = make_world()
        svc = DiscoveryService(ledger, pid)
        ghost = Wallet.from_seed(b"ghost")

        async def flow():
            async with TestClient(TestServer(svc.make_app())) as client:
                payload = self._node_payload(ghost, provider, pid)
                headers, body = sign_request("/api/nodes", ghost, payload)
                r = await client.put("/api/nodes", json=body, headers=headers)
                assert r.status == 400

        run(flow())

    def test_pool_requirements_gate(self):
        ledger, creator, manager, provider, node, pid = make_world(
            pool_requirements="gpu:count=8;gpu:model=B200"
        )
        svc = DiscoveryService(ledger, pid)

        async def flow():
            async with TestClient(TestServer(svc.make_app())) as client:
                payload = self._node_payload(node, provider, pid)  # H100 specs
                headers, body = sign_request("/api/nodes", node, payload)
                r = await client.put("/api/nodes", json=body, headers=headers)
                assert r.status == 400
                assert "requirements" in (await r.json())["error"]

        run(flow())

    def test_active_node_immutable_except_p2p(self):
        ledger, creator, manager, provider, node, pid = make_world()
        svc = DiscoveryService(ledger, pid)

        async def flow():
            async with TestClient(TestServer(svc.make_app())) as client:
                payload = self._node_payload(node, provider, pid)
                headers, body = sign_request("/api/nodes", node, payload)
                assert (await client.put("/api/nodes", json=body, headers=headers)).status == 200
                # mark active (as chain sync would after pool join)
                dn = svc.store.get(node.address)
                dn.is_active = True
                svc.store.put(dn)
                # re-register with different ip + p2p: only p2p sticks
                payload2 = dict(payload)
                payload2["ip_address"] = "99.9.9.9"
                payload2["worker_p2p_id"] = "p2p-new"
                h2, b2 = sign_request("/api/nodes", node, payload2)
                r = await client.put("/api/nodes", json=b2, headers=h2)
                assert r.status == 200
                dn2 = svc.store.get(node.address)
                assert dn2.node.ip_address == "10.0.0.1"
                assert dn2.node.worker_p2p_id == "p2p-new"

        run(flow())

    def _join_pool(self, ledger, manager, provider, node_addr, pid):
        from protocol_tpu.chain.ledger import invite_digest

        ledger.validate_node(node_addr)
        exp = time.time() + 60
        sig = manager.sign_message(invite_digest(0, pid, node_addr, "n", exp))
        ledger.join_compute_pool(pid, provider.address, node_addr, "n", exp, sig)

    def test_per_ip_cap_counts_only_active_nodes(self):
        """Reference semantics (node_store.rs:55-75): only pool-ACTIVE nodes
        consume the per-IP cap. Plain registrations never hit it; once the
        cap's worth of nodes on an IP are active, further registrations are
        rejected; and a node leaving the pool frees its slot."""
        ledger, creator, manager, provider, node, pid = make_world()
        for i in range(2, 6):
            w = Wallet.from_seed(f"node-{i}".encode())
            ledger.add_compute_node(provider.address, w.address)
        svc = DiscoveryService(ledger, pid, max_nodes_per_ip=2)

        def register(client, i):
            w = Wallet.from_seed(f"node-{i}".encode())
            payload = self._node_payload(w, provider, pid)
            headers, body = sign_request("/api/nodes", w, payload)
            return client.put("/api/nodes", json=body, headers=headers)

        async def flow():
            async with TestClient(TestServer(svc.make_app())) as client:
                # inactive registrations do NOT consume the cap
                first = [(await register(client, i)).status for i in [1, 2, 3]]
                # activate nodes 1+2 (join pool, then chain sync)
                for i in [1, 2]:
                    w = Wallet.from_seed(f"node-{i}".encode())
                    self._join_pool(ledger, manager, provider, w.address, pid)
                svc.chain_sync_once()
                # cap reached: a new registration on the same IP is rejected
                rejected = (await register(client, 4)).status
                # an ACTIVE node may still re-register (p2p fixups)
                rereg = (await register(client, 1)).status
                # node-1 leaves the pool -> slot freed
                ledger.leave_compute_pool(pid, Wallet.from_seed(b"node-1").address)
                svc.chain_sync_once()
                freed = (await register(client, 4)).status
                return first, rejected, rereg, freed

        first, rejected, rereg, freed = run(flow())
        assert first == [200, 200, 200]
        assert rejected == 429
        assert rereg == 200
        assert freed == 200

    def test_platform_requires_api_key(self):
        ledger, *_, pid = make_world()
        svc = DiscoveryService(ledger, pid, admin_api_key="k")

        async def flow():
            async with TestClient(TestServer(svc.make_app())) as client:
                r1 = await client.get("/api/platform")
                r2 = await client.get(
                    "/api/platform", headers={"Authorization": "Bearer k"}
                )
                return r1.status, r2.status

        assert run(flow()) == (401, 200)


class TestOrchestratorRoutes:
    def _svc(self, groups=None):
        ledger, creator, manager, provider, node, pid = make_world()
        svc = OrchestratorService(
            ledger, pid, manager, groups_plugin=groups, storage=MockStorageProvider()
        )
        return svc, node, manager

    def test_heartbeat_flow(self):
        svc, node, _ = self._svc()
        svc.store.node_store.add_node(
            OrchestratorNode(address=node.address, status=NodeStatus.HEALTHY)
        )
        from protocol_tpu.models.task import Task, TaskState

        svc.store.task_store.add_task(Task(name="t", image="i", created_at=1, state=TaskState.PENDING))

        async def flow():
            async with TestClient(TestServer(svc.make_app())) as client:
                payload = {
                    "address": node.address,
                    "task_state": "RUNNING",
                    "metrics": [
                        {"key": {"task_id": "t1", "label": "loss"}, "value": 0.7}
                    ],
                }
                headers, body = sign_request("/heartbeat", node, payload)
                r = await client.post("/heartbeat", json=body, headers=headers)
                assert r.status == 200, await r.text()
                data = await r.json()
                assert data["data"]["current_task"]["name"] == "t"

        run(flow())
        assert svc.store.heartbeat_store.get_heartbeat(node.address) is not None
        assert svc.store.metrics_store.get_metrics_for_task("t1") == {
            "loss": {node.address: 0.7}
        }

    def test_heartbeat_rejects_unknown_node(self):
        svc, node, _ = self._svc()

        async def flow():
            async with TestClient(TestServer(svc.make_app())) as client:
                headers, body = sign_request(
                    "/heartbeat", node, {"address": node.address}
                )
                return (await client.post("/heartbeat", json=body, headers=headers)).status

        assert run(flow()) == 401

    def test_banned_node_rejected(self):
        svc, node, _ = self._svc()
        svc.store.node_store.add_node(
            OrchestratorNode(address=node.address, status=NodeStatus.HEALTHY)
        )
        svc.store.kv.set(f"orchestrator:banned:{node.address}", "1")

        async def flow():
            async with TestClient(TestServer(svc.make_app())) as client:
                headers, body = sign_request(
                    "/heartbeat", node, {"address": node.address}
                )
                return (await client.post("/heartbeat", json=body, headers=headers)).status

        assert run(flow()) == 401

    def test_task_crud_and_name_uniqueness(self):
        svc, *_ = self._svc()

        async def flow():
            async with TestClient(TestServer(svc.make_app())) as client:
                auth = {"Authorization": "Bearer admin"}
                t = {"name": "a", "image": "img"}
                r1 = await client.post("/tasks", json=t, headers=auth)
                r2 = await client.post("/tasks", json=t, headers=auth)
                r3 = await client.get("/tasks", headers=auth)
                tid = (await r1.json())["data"]["id"]
                r4 = await client.delete(f"/tasks/{tid}", headers=auth)
                return r1.status, r2.status, len((await r3.json())["data"]), r4.status

        assert run(flow()) == (201, 409, 1, 200)

    def test_storage_upload_flow(self):
        svc, node, _ = self._svc()
        svc.store.node_store.add_node(
            OrchestratorNode(address=node.address, status=NodeStatus.HEALTHY)
        )

        async def flow():
            async with TestClient(TestServer(svc.make_app())) as client:
                payload = {
                    "file_name": "out.parquet",
                    "file_size": 1024,
                    "file_type": "application/octet-stream",
                    "sha256": "ab"*32,
                }
                headers, body = sign_request(
                    "/storage/request-upload", node, payload
                )
                r = await client.post(
                    "/storage/request-upload", json=body, headers=headers
                )
                assert r.status == 200, await r.text()
                return (await r.json())["data"]

        data = run(flow())
        assert data["signed_url"].startswith("mock://upload/")
        assert run(svc.storage.resolve_mapping_for_sha("ab"*32)) == "out.parquet"

    def test_storage_rate_limit(self):
        svc, node, _ = self._svc()
        svc.uploads_per_hour = 1
        svc.store.node_store.add_node(
            OrchestratorNode(address=node.address, status=NodeStatus.HEALTHY)
        )

        async def flow():
            async with TestClient(TestServer(svc.make_app())) as client:
                statuses = []
                for _ in range(2):
                    payload = {
                        "file_name": "f",
                        "file_size": 1,
                        "file_type": "x",
                        "sha256": "5a"*32,
                    }
                    headers, body = sign_request(
                        "/storage/request-upload", node, payload
                    )
                    r = await client.post(
                        "/storage/request-upload", json=body, headers=headers
                    )
                    statuses.append(r.status)
                return statuses

        assert run(flow()) == [200, 429]

    def test_prometheus_exposition(self):
        """Full metric-family parity surface (metrics/mod.rs:6-126):
        gauges rebuilt at scrape, heartbeat/upload counters, and the
        status-update + solve histograms."""
        svc, node, _ = self._svc()
        svc.store.node_store.add_node(
            OrchestratorNode(address=node.address, status=NodeStatus.HEALTHY)
        )

        async def flow():
            async with TestClient(TestServer(svc.make_app())) as client:
                # heartbeat increments the counter
                hb = HeartbeatRequest(address=node.address).to_dict()
                headers, body = sign_request("/heartbeat", node, hb)
                r0 = await client.post("/heartbeat", json=body, headers=headers)
                assert r0.status == 200, await r0.text()
                # upload request increments its counter
                up = {"file_name": "m.bin", "file_size": 1,
                      "file_type": "bin", "sha256": "cd" * 32}
                h2, b2 = sign_request("/storage/request-upload", node, up)
                await client.post("/storage/request-upload", json=b2, headers=h2)
                await svc.status_update_once()
                r = await client.get(
                    "/metrics/prometheus", headers={"Authorization": "Bearer admin"}
                )
                return await r.text()

        text = run(flow())
        pid = svc.pool_id
        # the FSM demoted the heartbeating-but-not-in-pool node to Unhealthy
        assert (
            f'orchestrator_nodes_total{{pool_id="{pid}",status="Unhealthy"}} 1.0'
            in text
        )
        assert "orchestrator_heartbeat_requests_total{" in text
        assert "orchestrator_file_upload_requests_total{" in text
        assert (
            "orchestrator_status_update_execution_time_seconds_bucket" in text
        )
        assert "orchestrator_tasks_total{" in text

    def test_openapi_document(self):
        svc, node, _ = self._svc()

        async def flow():
            async with TestClient(TestServer(svc.make_app())) as client:
                r = await client.get("/openapi.json")
                return await r.json()

        doc = run(flow())
        assert doc["openapi"].startswith("3.")
        assert "/heartbeat" in doc["paths"]
        assert "post" in doc["paths"]["/heartbeat"]
        assert "/tasks/{task_id}" in doc["paths"]
        params = doc["paths"]["/tasks/{task_id}"]["delete"]["parameters"]
        assert params[0]["name"] == "task_id"

    def test_docs_page(self):
        """Interactive explorer (the reference's Swagger UI analog,
        api/server.rs:46-97): self-contained HTML over /openapi.json."""
        svc, node, _ = self._svc()

        async def flow():
            async with TestClient(TestServer(svc.make_app())) as client:
                r = await client.get("/docs")
                return r.status, r.content_type, await r.text()

        status, ctype, html = run(flow())
        assert status == 200 and ctype == "text/html"
        assert "openapi.json" in html and "data-send" in html


class TestStatusFSM:
    def _world(self):
        ledger, creator, manager, provider, node, pid = make_world()
        svc = OrchestratorService(ledger, pid, manager)
        return svc, ledger, manager, provider, node, pid

    def test_heartbeat_present_in_pool_becomes_healthy(self):
        svc, ledger, manager, provider, node, pid = self._world()
        ledger.validate_node(node.address)
        # join pool via signed invite
        from protocol_tpu.chain.ledger import invite_digest

        exp = time.time() + 60
        sig = manager.sign_message(invite_digest(0, pid, node.address, "n", exp))
        ledger.join_compute_pool(pid, provider.address, node.address, "n", exp, sig)

        svc.store.node_store.add_node(
            OrchestratorNode(address=node.address, status=NodeStatus.WAITING_FOR_HEARTBEAT)
        )
        svc.store.heartbeat_store.beat(HeartbeatRequest(address=node.address))
        run(svc.status_update_once())
        assert svc.store.node_store.get_node(node.address).status == NodeStatus.HEALTHY

    def test_heartbeat_present_not_in_pool_unhealthy(self):
        svc, ledger, manager, provider, node, pid = self._world()
        svc.store.node_store.add_node(
            OrchestratorNode(address=node.address, status=NodeStatus.WAITING_FOR_HEARTBEAT)
        )
        svc.store.heartbeat_store.beat(HeartbeatRequest(address=node.address))
        run(svc.status_update_once())
        assert svc.store.node_store.get_node(node.address).status == NodeStatus.UNHEALTHY

    def test_missing_beats_healthy_to_dead(self):
        svc, ledger, manager, provider, node, pid = self._world()
        svc.store.node_store.add_node(
            OrchestratorNode(address=node.address, status=NodeStatus.HEALTHY)
        )
        run(svc.status_update_once())  # -> Unhealthy (miss 1)
        assert svc.store.node_store.get_node(node.address).status == NodeStatus.UNHEALTHY
        run(svc.status_update_once())  # miss 2
        run(svc.status_update_once())  # miss 3 -> Dead
        assert svc.store.node_store.get_node(node.address).status == NodeStatus.DEAD

    def test_dead_in_pool_gets_ejected(self):
        svc, ledger, manager, provider, node, pid = self._world()
        ledger.validate_node(node.address)
        from protocol_tpu.chain.ledger import invite_digest

        exp = time.time() + 60
        sig = manager.sign_message(invite_digest(0, pid, node.address, "n", exp))
        ledger.join_compute_pool(pid, provider.address, node.address, "n", exp, sig)
        svc.store.node_store.add_node(
            OrchestratorNode(address=node.address, status=NodeStatus.DEAD)
        )
        run(svc.status_update_once())
        assert not ledger.is_node_in_pool(pid, node.address)

    def test_discovery_monitor_adds_discovered(self):
        svc, ledger, manager, provider, node, pid = self._world()
        from protocol_tpu.models.node import DiscoveryNode

        async def fetcher():
            return [
                DiscoveryNode(
                    node=Node(id=node.address, ip_address="1.1.1.1", port=80),
                    is_validated=True,
                    last_updated=time.time(),
                )
            ]

        svc.discovery_fetcher = fetcher
        run(svc.discovery_monitor_once())
        got = svc.store.node_store.get_node(node.address)
        assert got is not None and got.status == NodeStatus.DISCOVERED

    def test_invite_flow_marks_waiting(self):
        svc, ledger, manager, provider, node, pid = self._world()
        svc.store.node_store.add_node(OrchestratorNode(address=node.address))
        sent = []

        async def sender(n, payload):
            sent.append((n.address, payload))
            return True

        svc.invite_sender = sender
        assert run(svc.invite_once()) == 1
        assert svc.store.node_store.get_node(node.address).status == NodeStatus.WAITING_FOR_HEARTBEAT
        # the invite payload must verify on the ledger
        ledger.validate_node(node.address)
        addr, payload = sent[0]
        ledger.join_compute_pool(
            pid, provider.address, node.address,
            payload["invite_nonce"], payload["expiration"], payload["invite_signature"],
        )
        assert ledger.is_node_in_pool(pid, node.address)


def make_toploc_app(results: dict):
    """Mock toploc server (the reference mocks it with mockito,
    toploc.rs:399-795)."""
    triggered = []

    async def validate(request):
        triggered.append(request.match_info["file"])
        return web.json_response({"status": "ok"})

    async def status(request):
        f = request.match_info["file"]
        if f not in results:
            return web.json_response({"status": "Pending"})
        return web.json_response(results[f])

    app = web.Application()
    app.router.add_post("/validate/{file}", validate)
    app.router.add_post("/validategroup/{file}", validate)
    app.router.add_get("/status/{file}", status)
    app.router.add_get("/statusgroup/{file}", status)
    app["triggered"] = triggered
    return app


class TestSyntheticValidation:
    def test_group_key_regex(self):
        gk = GroupKey.parse("out-abc123-4-0-2.parquet")
        assert gk == GroupKey("abc123", 4, 0, 2)
        assert GroupKey.parse("plain-file.parquet") is None

    def _submit(self, ledger, manager, provider, node, pid, sha, units=100):
        if not ledger.is_node_in_pool(pid, node.address):
            from protocol_tpu.chain.ledger import invite_digest

            ledger.validate_node(node.address)
            exp = time.time() + 60
            sig = manager.sign_message(invite_digest(0, pid, node.address, "n", exp))
            ledger.join_compute_pool(pid, provider.address, node.address, "n", exp, sig)
        ledger.submit_work(pid, node.address, sha, units)

    def test_single_file_accept_and_reject(self):
        ledger, creator, manager, provider, node, pid = make_world()
        storage = MockStorageProvider()
        results = {
            "good.parquet": {"status": "Accept", "output_flops": 100},
            "bad.parquet": {"status": "Reject"},
        }

        async def flow():
            app = make_toploc_app(results)
            async with TestClient(TestServer(app)) as client:
                toploc = ToplocClient("", client)
                sv = SyntheticDataValidator(ledger, pid, storage, [toploc])
                self._submit(ledger, manager, provider, node, pid, "sha-good")
                self._submit(ledger, manager, provider, node, pid, "sha-bad")
                await storage.generate_mapping_file("sha-good", "good.parquet")
                await storage.generate_mapping_file("sha-bad", "bad.parquet")
                await sv.validate_work_once()  # trigger
                await sv.validate_work_once()  # poll
                return sv

        sv = run(flow())
        assert sv.get_status("sha-good") == ValidationResult.ACCEPT
        assert sv.get_status("sha-bad") == ValidationResult.REJECT
        assert ledger.get_work_info(pid, "sha-bad").invalidated
        assert not ledger.get_work_info(pid, "sha-good").invalidated
        assert [k for k, _ in sv.rejections()] == ["sha-bad"]

    def test_work_unit_mismatch_soft_invalidates(self):
        ledger, creator, manager, provider, node, pid = make_world()
        storage = MockStorageProvider()
        results = {"f.parquet": {"status": "Accept", "output_flops": 42}}

        async def flow():
            app = make_toploc_app(results)
            async with TestClient(TestServer(app)) as client:
                toploc = ToplocClient("", client)
                sv = SyntheticDataValidator(ledger, pid, storage, [toploc])
                self._submit(ledger, manager, provider, node, pid, "sha-f", units=100)
                await storage.generate_mapping_file("sha-f", "f.parquet")
                await sv.validate_work_once()
                await sv.validate_work_once()
                return sv

        sv = run(flow())
        assert sv.get_status("sha-f") == ValidationResult.WORK_MISMATCH
        assert ledger.get_work_info(pid, "sha-f").soft_invalidated

    def test_group_failing_indices(self):
        ledger, creator, manager, provider, node, pid = make_world()
        storage = MockStorageProvider()
        results = {
            "out-g1-2-0-1.parquet": {"status": "Reject", "failing_indices": [1]},
            "out-g1-2-0-0.parquet": {"status": "Reject", "failing_indices": [1]},
        }

        async def flow():
            app = make_toploc_app(results)
            async with TestClient(TestServer(app)) as client:
                toploc = ToplocClient("", client)
                sv = SyntheticDataValidator(ledger, pid, storage, [toploc])
                self._submit(ledger, manager, provider, node, pid, "sha-0")
                self._submit(ledger, manager, provider, node, pid, "sha-1")
                await storage.generate_mapping_file("sha-0", "out-g1-2-0-0.parquet")
                await storage.generate_mapping_file("sha-1", "out-g1-2-0-1.parquet")
                await sv.validate_work_once()  # collect both, trigger group
                await sv.validate_work_once()  # poll
                return sv

        sv = run(flow())
        assert sv.get_status("sha-0") == ValidationResult.ACCEPT
        assert sv.get_status("sha-1") == ValidationResult.REJECT

    def _second_node(self, ledger, provider):
        node2 = Wallet.from_seed(b"node-2")
        ledger.add_compute_node(provider.address, node2.address)
        return node2

    def test_group_work_units_summed_accepts_honest_members(self):
        # Each member claims a FRACTION of the group total; the check must
        # sum claims across the group (mod.rs:972-1090), not compare each
        # member's claim to the group-level output_flops.
        ledger, creator, manager, provider, node, pid = make_world()
        node2 = self._second_node(ledger, provider)
        storage = MockStorageProvider()
        results = {
            f"out-g3-2-0-{i}.parquet": {"status": "Accept", "output_flops": 100}
            for i in range(2)
        }

        async def flow():
            app = make_toploc_app(results)
            async with TestClient(TestServer(app)) as client:
                toploc = ToplocClient("", client)
                sv = SyntheticDataValidator(ledger, pid, storage, [toploc])
                self._submit(ledger, manager, provider, node, pid, "sha-0", units=50)
                self._submit(ledger, manager, provider, node2, pid, "sha-1", units=50)
                await storage.generate_mapping_file("sha-0", "out-g3-2-0-0.parquet")
                await storage.generate_mapping_file("sha-1", "out-g3-2-0-1.parquet")
                await sv.validate_work_once()  # collect + trigger group
                await sv.validate_work_once()  # poll
                return sv

        sv = run(flow())
        assert sv.get_status("sha-0") == ValidationResult.ACCEPT
        assert sv.get_status("sha-1") == ValidationResult.ACCEPT
        assert not ledger.get_work_info(pid, "sha-0").soft_invalidated
        assert not ledger.get_work_info(pid, "sha-1").soft_invalidated

    def test_group_work_units_mismatch_penalizes_only_deviating_node(self):
        # total claimed 130 vs toploc 100 -> mismatch; expected per node is
        # 50, so only the node claiming 80 is soft-invalidated
        # (mod.rs:1059-1095, 1327-1343).
        ledger, creator, manager, provider, node, pid = make_world()
        node2 = self._second_node(ledger, provider)
        storage = MockStorageProvider()
        results = {
            f"out-g4-2-0-{i}.parquet": {"status": "Accept", "output_flops": 100}
            for i in range(2)
        }

        async def flow():
            app = make_toploc_app(results)
            async with TestClient(TestServer(app)) as client:
                toploc = ToplocClient("", client)
                sv = SyntheticDataValidator(ledger, pid, storage, [toploc])
                self._submit(ledger, manager, provider, node, pid, "sha-0", units=50)
                self._submit(ledger, manager, provider, node2, pid, "sha-1", units=80)
                await storage.generate_mapping_file("sha-0", "out-g4-2-0-0.parquet")
                await storage.generate_mapping_file("sha-1", "out-g4-2-0-1.parquet")
                await sv.validate_work_once()
                await sv.validate_work_once()
                return sv

        sv = run(flow())
        assert sv.get_status("sha-0") == ValidationResult.ACCEPT
        assert sv.get_status("sha-1") == ValidationResult.WORK_MISMATCH
        assert not ledger.get_work_info(pid, "sha-0").soft_invalidated
        assert ledger.get_work_info(pid, "sha-1").soft_invalidated

    def test_validator_metrics_families(self):
        """validator/src/metrics.rs parity: loop/api histograms, work-key
        counters, group work-units check results in the exposition."""
        from protocol_tpu.utils.metrics import ValidatorMetrics

        ledger, creator, manager, provider, node, pid = make_world()
        node2 = self._second_node(ledger, provider)
        storage = MockStorageProvider()
        vm = ValidatorMetrics("0xval", pid)
        results = {
            f"out-gm-2-0-{i}.parquet": {"status": "Accept", "output_flops": 100}
            for i in range(2)
        }

        async def flow():
            app = make_toploc_app(results)
            async with TestClient(TestServer(app)) as client:
                toploc = ToplocClient("", client)
                sv = SyntheticDataValidator(
                    ledger, pid, storage, [toploc], metrics=vm
                )
                self._submit(ledger, manager, provider, node, pid, "sha-0", units=50)
                self._submit(ledger, manager, provider, node2, pid, "sha-1", units=80)
                await storage.generate_mapping_file("sha-0", "out-gm-2-0-0.parquet")
                await storage.generate_mapping_file("sha-1", "out-gm-2-0-1.parquet")
                await sv.validate_work_once()
                await sv.validate_work_once()
                return sv

        run(flow())
        text = vm.render().decode()
        assert (
            'validator_group_work_units_check_total{group_id="gm",'
            f'pool_id="{pid}",result="mismatch",validator_id="0xval"}} 1.0'
            in text
        )
        assert "validator_work_keys_soft_invalidated_total{" in text
        assert "validator_api_requests_total{" in text
        assert "validator_api_duration_seconds_bucket{" in text
        assert "validator_work_keys_to_process{" in text

    def test_incomplete_group_grace_soft_invalidation(self):
        ledger, creator, manager, provider, node, pid = make_world()
        storage = MockStorageProvider()

        async def flow():
            app = make_toploc_app({})
            async with TestClient(TestServer(app)) as client:
                toploc = ToplocClient("", client)
                sv = SyntheticDataValidator(
                    ledger, pid, storage, [toploc], grace_period=0.0
                )
                self._submit(ledger, manager, provider, node, pid, "sha-0")
                await storage.generate_mapping_file("sha-0", "out-g2-3-0-0.parquet")
                await sv.validate_work_once()  # registers incomplete group
                await asyncio.sleep(0.01)
                await sv.validate_work_once()  # grace expired -> soft invalidate
                return sv

        sv = run(flow())
        assert sv.get_status("sha-0") == ValidationResult.WORK_MISMATCH
        assert ledger.get_work_info(pid, "sha-0").soft_invalidated

    def test_prefix_filter_routing(self):
        ledger, creator, manager, provider, node, pid = make_world()
        storage = MockStorageProvider()

        async def flow():
            app_a = make_toploc_app({})
            app_b = make_toploc_app({})
            async with TestClient(TestServer(app_a)) as ca, TestClient(
                TestServer(app_b)
            ) as cb:
                t_a = ToplocClient("", ca, file_prefix_filter="modelA-")
                t_b = ToplocClient("", cb, file_prefix_filter="modelB-")
                sv = SyntheticDataValidator(ledger, pid, storage, [t_a, t_b])
                self._submit(ledger, manager, provider, node, pid, "sha-b")
                await storage.generate_mapping_file("sha-b", "modelB-file.parquet")
                await sv.validate_work_once()
                return app_a["triggered"], app_b["triggered"]

        ta, tb = run(flow())
        assert ta == [] and tb == ["modelB-file.parquet"]
