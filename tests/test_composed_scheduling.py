"""Composed gang scheduling: batch matcher + NodeGroupsPlugin in ONE pool.

VERDICT r2 item 5 done-bar: grouped and ungrouped nodes both get
TPU-matched assignments honoring topology bounds — the two schedulers are
no longer mutually exclusive deployments, and group<->task selection goes
through the matcher's cost solve instead of rng.choice
(SURVEY §7 hard part 5; reference scheduler_impl.rs:11-210).
"""

from protocol_tpu.models import (
    ComputeSpecs,
    CpuSpecs,
    GpuSpecs,
    SchedulingConfig,
    Task,
    TaskState,
)
from protocol_tpu.sched import Scheduler, TpuBatchMatcher
from protocol_tpu.sched.node_groups import NodeGroupConfiguration, NodeGroupsPlugin
from protocol_tpu.store import NodeStatus, OrchestratorNode, StoreContext


def specs():
    return ComputeSpecs(
        gpu=GpuSpecs(count=8, model="H100", memory_mb=80000),
        cpu=CpuSpecs(cores=32),
        ram_mb=65536,
        storage_gb=1000,
    )


def mk_node(addr, p2p=True):
    return OrchestratorNode(
        address=addr,
        status=NodeStatus.HEALTHY,
        compute_specs=specs(),
        p2p_id=f"p2p-{addr}" if p2p else None,
    )


def topo_task(name, created_at, topology, replicas=None):
    plugins = {"node_groups": {"allowed_topologies": [topology]}}
    if replicas is not None:
        plugins["tpu_scheduler"] = {"replicas": [str(replicas)]}
    return Task(
        name=name, image="img", created_at=created_at, state=TaskState.PENDING,
        scheduling_config=SchedulingConfig(plugins=plugins),
    )


def plain_task(name, created_at, replicas=None):
    plugins = {}
    if replicas is not None:
        plugins["tpu_scheduler"] = {"replicas": [str(replicas)]}
    return Task(
        name=name, image="img", created_at=created_at, state=TaskState.PENDING,
        scheduling_config=SchedulingConfig(plugins=plugins) if plugins else None,
    )


def build(n_grouped=4, n_free=3):
    ctx = StoreContext.new_test()
    for i in range(n_grouped):
        ctx.node_store.add_node(mk_node(f"0xg{i:039x}"))
    for i in range(n_free):
        # ungrouped: no p2p id -> ineligible for formation
        ctx.node_store.add_node(mk_node(f"0xf{i:039x}", p2p=False))
    plugin = NodeGroupsPlugin(
        ctx,
        [NodeGroupConfiguration(name="pair", min_group_size=2, max_group_size=2)],
    )
    plugin.attach_observers()
    matcher = TpuBatchMatcher(ctx, min_solve_interval=0)
    matcher.attach_observers()
    matcher.attach_groups(plugin)
    sched = Scheduler(ctx, plugins=[plugin], batch_matcher=matcher)
    return ctx, plugin, matcher, sched


class TestComposedScheduling:
    def test_grouped_and_ungrouped_both_served(self):
        ctx, plugin, matcher, sched = build()
        ctx.task_store.add_task(topo_task("gang", 100, "pair"))
        ctx.task_store.add_task(plain_task("solo", 200, replicas=3))
        plugin.on_task_created(topo_task("gang", 100, "pair"))  # enable config
        assert plugin.try_form_new_groups() == 2  # 4 nodes -> 2 pairs

        # grouped node resolves through the plugin with matcher ranking
        gaddr = "0xg" + "0" * 39
        got = sched.get_task_for_node(gaddr)
        assert got is not None and got.name == "gang"
        assert "${GROUP_INDEX}" not in str(got.env_vars)  # expansion ran

        # ungrouped node resolves through the individual batch solve
        faddr = "0xf" + "0" * 39
        got_f = sched.get_task_for_node(faddr)
        assert got_f is not None and got_f.name == "solo"

        # topology task NEVER reaches an ungrouped node
        assert matcher.last_solve_stats["group_assignments"] >= 1
        for addr, tid in matcher._assignment.items():
            assert not ctx.task_store.get_task(tid).allowed_topologies()

    def test_bounded_topology_task_replica_bound_across_groups(self):
        ctx, plugin, matcher, sched = build(n_grouped=6, n_free=0)
        t = topo_task("gang1", 100, "pair", replicas=1)
        ctx.task_store.add_task(t)
        plugin.on_task_created(t)
        assert plugin.try_form_new_groups() == 3  # 3 pairs

        served = set()
        for g in plugin.get_groups():
            for addr in g.nodes:
                got = sched.get_task_for_node(addr)
                if got is not None:
                    served.add(g.id)
                    assert got.name == "gang1"
        # replicas=1: exactly ONE group runs the task; rng.choice would
        # have handed it to every group
        assert len(served) == 1

    def test_idle_groups_take_unrestricted_unbounded_task(self):
        ctx, plugin, matcher, sched = build(n_grouped=4, n_free=0)
        bounded = topo_task("gang1", 100, "pair", replicas=1)
        swarm = plain_task("swarm", 50)  # unbounded, unrestricted
        ctx.task_store.add_task(bounded)
        ctx.task_store.add_task(swarm)
        plugin.on_task_created(bounded)
        assert plugin.try_form_new_groups() == 2

        names = set()
        for g in plugin.get_groups():
            got = sched.get_task_for_node(g.nodes[0])
            if got is not None:
                names.add(got.name)
        # one group holds the bounded topo task, the other the swarm task
        assert names == {"gang1", "swarm"}

    def test_group_churn_marks_matcher_dirty(self):
        ctx, plugin, matcher, sched = build(n_grouped=2, n_free=0)
        t = topo_task("gang", 100, "pair")
        ctx.task_store.add_task(t)
        plugin.on_task_created(t)
        matcher.refresh()
        assert matcher._dirty is False
        assert plugin.try_form_new_groups() == 1
        assert matcher._dirty is True  # on_group_created chained

    def test_plugin_only_mode_unchanged(self):
        """Without a matcher the plugin chain behaves exactly as before
        (ungrouped nodes in a topology pool get nothing)."""
        ctx = StoreContext.new_test()
        for i in range(2):
            ctx.node_store.add_node(mk_node(f"0xg{i:039x}"))
        plugin = NodeGroupsPlugin(
            ctx,
            [NodeGroupConfiguration(name="pair", min_group_size=2, max_group_size=2)],
        )
        plugin.attach_observers()
        sched = Scheduler(ctx, plugins=[plugin])
        t = topo_task("gang", 100, "pair")
        ctx.task_store.add_task(t)
        plugin.on_task_created(t)
        plugin.try_form_new_groups()
        got = sched.get_task_for_node("0xg" + "0" * 39)
        assert got is not None and got.name == "gang"
