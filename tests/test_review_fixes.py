"""Regression tests for review findings: fallback gating, config validation,
solve throttling, wire-path memory bounds, (0,0) locations."""

import numpy as np
import pytest

from protocol_tpu.models import (
    ComputeSpecs,
    GpuSpecs,
    NodeLocation,
    SchedulingConfig,
    Task,
    TaskRequest,
)
from protocol_tpu.models.node import ComputeRequirements, GpuRequirements
from protocol_tpu.ops.encoding import FeatureEncoder, compat_mask
from protocol_tpu.sched import Scheduler, TpuBatchMatcher
from protocol_tpu.store import StoreContext

from tests.test_scheduler import mk_node, mk_task


def test_fallback_does_not_bypass_requirements():
    """A node the batch solve covered but left unassigned stays idle instead
    of receiving a requirement-gated task via the greedy fallback."""
    ctx = StoreContext.new_test()
    ctx.node_store.add_node(mk_node("0xa100", gpu_model="A100", gpu_count=8))
    gated = mk_task(
        "h100-only",
        created_at=100,
        sched_plugins={"tpu_scheduler": {"compute_requirements": ["gpu:model=H100"]}},
    )
    ctx.task_store.add_task(gated)
    sched = Scheduler(ctx, batch_matcher=TpuBatchMatcher(ctx))
    assert sched.get_task_for_node("0xa100") is None


def test_fallback_respects_replica_bound():
    ctx = StoreContext.new_test()
    for i in range(5):
        ctx.node_store.add_node(mk_node(f"0x{i}", gpu_model="H100", gpu_count=8))
    bounded = mk_task(
        "bounded", created_at=100, sched_plugins={"tpu_scheduler": {"replicas": ["2"]}}
    )
    ctx.task_store.add_task(bounded)
    sched = Scheduler(ctx, batch_matcher=TpuBatchMatcher(ctx))
    got = [sched.get_task_for_node(f"0x{i}") for i in range(5)]
    assert sum(1 for t in got if t is not None) == 2


def test_uncovered_node_triggers_resolve_never_greedy():
    """A node the batch never considered marks the matcher dirty and gets a
    fresh solve — it must NOT fall through to an ungated greedy pick."""
    ctx = StoreContext.new_test()
    ctx.node_store.add_node(mk_node("0xa", gpu_model="H100", gpu_count=8))
    ctx.task_store.add_task(mk_task("t", created_at=100))
    clock = [1000.0]
    matcher = TpuBatchMatcher(ctx, min_solve_interval=10.0, time_fn=lambda: clock[0])
    sched = Scheduler(ctx, batch_matcher=matcher)
    assert sched.get_task_for_node("0xa").name == "t"

    ctx.node_store.add_node(mk_node("0xlate", gpu_model="H100", gpu_count=8))
    # throttled: the new node waits for the next solve window, no fallback
    assert sched.get_task_for_node("0xlate") is None
    clock[0] += 11
    assert sched.get_task_for_node("0xlate").name == "t"


def test_uncovered_node_cannot_bypass_replica_bound():
    """The scenario from review: replicas=1 task fully assigned; a late
    node must not receive it via any fallback."""
    ctx = StoreContext.new_test()
    ctx.node_store.add_node(mk_node("0xa", gpu_model="H100", gpu_count=8))
    bounded = mk_task(
        "one-replica", created_at=100,
        sched_plugins={"tpu_scheduler": {"replicas": ["1"]}},
    )
    ctx.task_store.add_task(bounded)
    matcher = TpuBatchMatcher(ctx, min_solve_interval=0.0)
    sched = Scheduler(ctx, batch_matcher=matcher)
    assert sched.get_task_for_node("0xa").name == "one-replica"
    ctx.node_store.add_node(mk_node("0xlate", gpu_model="H100", gpu_count=8))
    assert sched.get_task_for_node("0xlate") is None


def test_malformed_plugin_config_rejected_at_creation():
    with pytest.raises(ValueError):
        Task.from_request(
            TaskRequest(
                image="x",
                name="bad-reqs",
                scheduling_config=SchedulingConfig(
                    plugins={"tpu_scheduler": {"compute_requirements": ["gpu:count=abc"]}}
                ),
            )
        )
    with pytest.raises(ValueError):
        Task.from_request(
            TaskRequest(
                image="x",
                name="bad-replicas",
                scheduling_config=SchedulingConfig(
                    plugins={"tpu_scheduler": {"replicas": ["two"]}}
                ),
            )
        )
    with pytest.raises(ValueError):
        Task.from_request(
            TaskRequest(
                image="x",
                name="zero-replicas",
                scheduling_config=SchedulingConfig(
                    plugins={"tpu_scheduler": {"replicas": ["0"]}}
                ),
            )
        )


def test_malformed_config_in_store_skipped_not_crashing():
    """Direct store writes bypassing from_request must not break refresh()."""
    ctx = StoreContext.new_test()
    ctx.node_store.add_node(mk_node("0xa", gpu_model="H100", gpu_count=8))
    bad = mk_task(
        "bad", created_at=200,
        sched_plugins={"tpu_scheduler": {"compute_requirements": ["gpu:count=abc"]}},
    )
    good = mk_task("good", created_at=100)
    ctx.task_store.add_task(bad)
    ctx.task_store.add_task(good)
    matcher = TpuBatchMatcher(ctx)
    matcher.refresh()  # must not raise
    node = ctx.node_store.get_node("0xa")
    assert matcher.task_for_node(node).name == "good"


def test_solve_throttle_bounds_refresh_rate():
    ctx = StoreContext.new_test()
    ctx.node_store.add_node(mk_node("0xa", gpu_model="H100", gpu_count=8))
    clock = [1000.0]
    matcher = TpuBatchMatcher(ctx, min_solve_interval=10.0, time_fn=lambda: clock[0])
    matcher.attach_observers()
    sched = Scheduler(ctx, batch_matcher=matcher)

    solves = []
    orig = matcher.refresh

    def counting_refresh():
        solves.append(clock[0])
        orig()

    matcher.refresh = counting_refresh
    for i in range(5):
        ctx.task_store.add_task(mk_task(f"t{i}", created_at=i))
        clock[0] += 0.01
        sched.get_task_for_node("0xa")
    assert len(solves) == 1  # throttled: one solve despite 5 dirty events
    clock[0] += 11
    sched.get_task_for_node("0xa")
    assert len(solves) == 2  # dirty + interval elapsed -> re-solve


def test_wire_path_memory_bounds_parity():
    """memory_mb and memory_mb_min both set via from_dict: the stricter bound
    wins on device, matching host meets()."""
    req = ComputeRequirements(
        gpu=[GpuRequirements.from_dict({"count": 1, "memory_mb": 16000, "memory_mb_min": 24000})]
    )
    spec = ComputeSpecs(gpu=GpuSpecs(count=1, memory_mb=20000))
    assert spec.meets(req) is False
    enc = FeatureEncoder()
    ep = enc.encode_providers([spec])
    er = enc.encode_requirements([req])
    assert not bool(np.asarray(compat_mask(ep, er))[0, 0])


def test_zero_zero_location_is_real():
    enc = FeatureEncoder()
    ep = enc.encode_providers(
        [ComputeSpecs(), ComputeSpecs()],
        locations=[NodeLocation(latitude=0.0, longitude=0.0), None],
    )
    assert bool(np.asarray(ep.has_location)[0])
    assert not bool(np.asarray(ep.has_location)[1])
