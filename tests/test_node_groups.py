"""Node-groups plugin tests: formation (batched eligibility + proximity),
merge, dissolve, task binding (SET-NX), ring-variable expansion — mirroring
the scenarios of the reference's node_groups test module."""

import random

from protocol_tpu.models import (
    ComputeSpecs,
    CpuSpecs,
    GpuSpecs,
    NodeLocation,
    SchedulingConfig,
    Task,
    TaskState,
)
from protocol_tpu.sched.node_groups import (
    ENABLED_CONFIGS,
    GROUP_TASK_KEY,
    NodeGroupConfiguration,
    NodeGroupsPlugin,
    TaskSwitchingPolicy,
)
from protocol_tpu.store import NodeStatus, OrchestratorNode, StoreContext


def mk_node(addr, gpu="H100", count=8, status=NodeStatus.HEALTHY, p2p=True, loc=None):
    return OrchestratorNode(
        address=addr,
        status=status,
        p2p_id=f"p2p-{addr}" if p2p else None,
        p2p_addresses=[f"/ip4/10.0.0.1/tcp/4001/p2p/{addr}"] if p2p else None,
        compute_specs=ComputeSpecs(
            gpu=GpuSpecs(count=count, model=gpu, memory_mb=80000),
            cpu=CpuSpecs(cores=32),
            ram_mb=65536,
            storage_gb=1000,
        ),
        location=loc,
    )


def mk_topo_task(name, topologies, created_at=100):
    return Task(
        name=name,
        image="img",
        created_at=created_at,
        state=TaskState.PENDING,
        scheduling_config=SchedulingConfig(
            plugins={"node_groups": {"allowed_topologies": topologies}}
        ),
    )


def make_plugin(ctx, configs, policy=TaskSwitchingPolicy.IF_SAME_TASK, seed=0):
    p = NodeGroupsPlugin(ctx, configs, merge_policy=policy, rng=random.Random(seed))
    p.attach_observers()
    return p


CFG2 = NodeGroupConfiguration(name="pair", min_group_size=2, max_group_size=2)
CFG4 = NodeGroupConfiguration(
    name="quad-h100",
    min_group_size=4,
    max_group_size=4,
    compute_requirements="gpu:count=8;gpu:model=H100",
)


class TestConfigOrdering:
    def test_sorted_larger_min_then_specific(self):
        ctx = StoreContext.new_test()
        loose4 = NodeGroupConfiguration(name="quad-any", min_group_size=4, max_group_size=8)
        p = make_plugin(ctx, [CFG2, loose4, CFG4])
        assert [c.name for c in p.configurations] == ["quad-h100", "quad-any", "pair"]

    def test_invalid_bounds_rejected(self):
        import pytest

        ctx = StoreContext.new_test()
        with pytest.raises(ValueError):
            make_plugin(ctx, [NodeGroupConfiguration(name="bad", min_group_size=3, max_group_size=2)])


class TestEnableDisable:
    def test_task_lifecycle_toggles_configs(self):
        ctx = StoreContext.new_test()
        make_plugin(ctx, [CFG2, CFG4])
        t = mk_topo_task("train", ["quad-h100"])
        ctx.task_store.add_task(t)
        assert ctx.kv.smembers(ENABLED_CONFIGS) == {"quad-h100"}
        ctx.task_store.delete_task(t.id)
        assert ctx.kv.smembers(ENABLED_CONFIGS) == set()


class TestFormation:
    def test_forms_group_when_enough_eligible(self):
        ctx = StoreContext.new_test()
        plugin = make_plugin(ctx, [CFG4])
        for i in range(5):
            ctx.node_store.add_node(mk_node(f"0x{i}"))
        ctx.task_store.add_task(mk_topo_task("train", ["quad-h100"]))
        stats = plugin.run_group_management()
        assert stats["formed"] == 1
        groups = plugin.get_groups()
        assert len(groups) == 1 and len(groups[0].nodes) == 4

    def test_requirements_gate_formation(self):
        ctx = StoreContext.new_test()
        plugin = make_plugin(ctx, [CFG4])
        for i in range(3):
            ctx.node_store.add_node(mk_node(f"0xh{i}", gpu="H100"))
        for i in range(4):
            ctx.node_store.add_node(mk_node(f"0xa{i}", gpu="A100"))
        ctx.task_store.add_task(mk_topo_task("train", ["quad-h100"]))
        assert plugin.run_group_management()["formed"] == 0  # only 3 H100s

        ctx.node_store.add_node(mk_node("0xh3", gpu="H100"))
        assert plugin.run_group_management()["formed"] == 1
        group = plugin.get_groups()[0]
        assert all(a.startswith("0xh") for a in group.nodes)

    def test_unhealthy_or_no_p2p_excluded(self):
        ctx = StoreContext.new_test()
        plugin = make_plugin(ctx, [CFG2])
        ctx.node_store.add_node(mk_node("0xa"))
        ctx.node_store.add_node(mk_node("0xb", status=NodeStatus.UNHEALTHY))
        ctx.node_store.add_node(mk_node("0xc", p2p=False))
        ctx.task_store.add_task(mk_topo_task("t", ["pair"]))
        assert plugin.run_group_management()["formed"] == 0

    def test_proximity_seeding(self):
        """Nearest nodes group together: 2 in Paris + 2 in Tokyo + config
        max=2 -> the Paris pair forms one group, Tokyo pair the other."""
        ctx = StoreContext.new_test()
        plugin = make_plugin(ctx, [CFG2])
        paris = NodeLocation(latitude=48.85, longitude=2.35)
        paris2 = NodeLocation(latitude=48.80, longitude=2.30)
        tokyo = NodeLocation(latitude=35.68, longitude=139.69)
        tokyo2 = NodeLocation(latitude=35.60, longitude=139.60)
        ctx.node_store.add_node(mk_node("0xp1", loc=paris))
        ctx.node_store.add_node(mk_node("0xt1", loc=tokyo))
        ctx.node_store.add_node(mk_node("0xp2", loc=paris2))
        ctx.node_store.add_node(mk_node("0xt2", loc=tokyo2))
        ctx.task_store.add_task(mk_topo_task("t", ["pair"]))
        assert plugin.run_group_management()["formed"] == 2
        memberships = [set(g.nodes) for g in plugin.get_groups()]
        assert {"0xp1", "0xp2"} in memberships
        assert {"0xt1", "0xt2"} in memberships

    def test_nodes_not_double_grouped(self):
        ctx = StoreContext.new_test()
        plugin = make_plugin(ctx, [CFG2])
        for i in range(4):
            ctx.node_store.add_node(mk_node(f"0x{i}"))
        ctx.task_store.add_task(mk_topo_task("t", ["pair"]))
        assert plugin.run_group_management()["formed"] == 2
        assert plugin.run_group_management()["formed"] == 0  # all grouped


class TestDissolve:
    def test_status_change_dissolves_group(self):
        ctx = StoreContext.new_test()
        plugin = make_plugin(ctx, [CFG2])
        ctx.node_store.add_node(mk_node("0xa"))
        ctx.node_store.add_node(mk_node("0xb"))
        ctx.task_store.add_task(mk_topo_task("t", ["pair"]))
        plugin.run_group_management()
        assert len(plugin.get_groups()) == 1

        node = ctx.node_store.get_node("0xa")
        node.status = NodeStatus.DEAD
        ctx.node_store.update_node(node)
        plugin.handle_status_change(node)
        assert plugin.get_groups() == []
        assert plugin.group_for_node("0xb") is None

    def test_task_delete_dissolves_its_groups(self):
        ctx = StoreContext.new_test()
        plugin = make_plugin(ctx, [CFG2])
        ctx.node_store.add_node(mk_node("0xa"))
        ctx.node_store.add_node(mk_node("0xb"))
        t = mk_topo_task("t", ["pair"])
        ctx.task_store.add_task(t)
        plugin.run_group_management()
        group = plugin.get_groups()[0]
        # bind the group to the task via the scheduler path
        node = ctx.node_store.get_node("0xa")
        assert plugin.filter_tasks([t], node)
        ctx.task_store.delete_task(t.id)
        assert plugin.get_groups() == []

    def test_stale_mapping_recovered(self):
        ctx = StoreContext.new_test()
        plugin = make_plugin(ctx, [CFG2])
        ctx.kv.hset("node_to_group", "0xa", "ghost-group")
        assert plugin.group_for_node("0xa") is None
        assert ctx.kv.hget("node_to_group", "0xa") is None


class TestMerge:
    def _solo(self, plugin, ctx, addr):
        ctx.node_store.add_node(mk_node(addr))
        return plugin._create_group(
            NodeGroupConfiguration(name="elastic", min_group_size=1, max_group_size=4),
            [addr],
        )

    def test_merge_solo_groups(self):
        ctx = StoreContext.new_test()
        cfg = NodeGroupConfiguration(name="elastic", min_group_size=1, max_group_size=4)
        plugin = make_plugin(ctx, [cfg])
        g1 = self._solo(plugin, ctx, "0xa")
        g2 = self._solo(plugin, ctx, "0xb")
        g3 = self._solo(plugin, ctx, "0xc")
        assert plugin.try_merge_solo_groups() == 1
        groups = plugin.get_groups()
        assert len(groups) == 1 and len(groups[0].nodes) == 3

    def test_merge_respects_never_policy(self):
        ctx = StoreContext.new_test()
        cfg = NodeGroupConfiguration(name="elastic", min_group_size=1, max_group_size=4)
        plugin = make_plugin(ctx, [cfg], policy=TaskSwitchingPolicy.NEVER)
        self._solo(plugin, ctx, "0xa")
        self._solo(plugin, ctx, "0xb")
        assert plugin.try_merge_solo_groups() == 0

    def test_if_same_task_policy_buckets(self):
        ctx = StoreContext.new_test()
        cfg = NodeGroupConfiguration(name="elastic", min_group_size=1, max_group_size=4)
        plugin = make_plugin(ctx, [cfg])
        g1 = self._solo(plugin, ctx, "0xa")
        g2 = self._solo(plugin, ctx, "0xb")
        g3 = self._solo(plugin, ctx, "0xc")
        ctx.kv.set(GROUP_TASK_KEY.format(g1.id), "task-1")
        ctx.kv.set(GROUP_TASK_KEY.format(g2.id), "task-1")
        ctx.kv.set(GROUP_TASK_KEY.format(g3.id), "task-2")
        assert plugin.try_merge_solo_groups() == 1  # only the task-1 pair
        merged = [g for g in plugin.get_groups() if len(g.nodes) == 2][0]
        assert ctx.kv.get(GROUP_TASK_KEY.format(merged.id)) == "task-1"


class TestSchedulerFilter:
    def _grouped_pair(self):
        ctx = StoreContext.new_test()
        plugin = make_plugin(ctx, [CFG2])
        ctx.node_store.add_node(mk_node("0xa"))
        ctx.node_store.add_node(mk_node("0xb"))
        task = mk_topo_task("ring-train", ["pair"])
        task.env_vars = {
            "RANK": "${GROUP_INDEX}",
            "WORLD": "${GROUP_SIZE}",
            "NEXT": "${NEXT_P2P_ADDRESS}",
            "GID": "${GROUP_ID}",
        }
        ctx.task_store.add_task(task)
        plugin.run_group_management()
        return ctx, plugin, task

    def test_ungrouped_node_gets_nothing(self):
        ctx, plugin, task = self._grouped_pair()
        ctx.node_store.add_node(mk_node("0xc"))
        node = ctx.node_store.get_node("0xc")
        assert plugin.filter_tasks([task], node) == []

    def test_group_task_binding_is_stable(self):
        ctx, plugin, task = self._grouped_pair()
        other = mk_topo_task("other", ["pair"], created_at=200)
        na = ctx.node_store.get_node("0xa")
        nb = ctx.node_store.get_node("0xb")
        first = plugin.filter_tasks([task, other], na)[0]
        second = plugin.filter_tasks([task, other], nb)[0]
        assert first.id == second.id  # SET NX: both members see one task

    def test_ring_variable_expansion(self):
        ctx, plugin, task = self._grouped_pair()
        group = plugin.get_groups()[0]
        a_idx = group.nodes.index("0xa")
        na = ctx.node_store.get_node("0xa")
        got = plugin.filter_tasks([task], na)[0]
        assert got.env_vars["RANK"] == str(a_idx)
        assert got.env_vars["WORLD"] == "2"
        assert got.env_vars["GID"] == group.id
        # ring neighbor of a 2-group is the other member
        other = group.nodes[(a_idx + 1) % 2]
        assert other in got.env_vars["NEXT"]
        # original task untouched
        assert task.env_vars["RANK"] == "${GROUP_INDEX}"

    def test_deleted_bound_task_rebinds(self):
        ctx, plugin, task = self._grouped_pair()
        na = ctx.node_store.get_node("0xa")
        plugin.filter_tasks([task], na)
        other = mk_topo_task("other", ["pair"], created_at=200)
        # bound task vanishes from the task list -> rebind to applicable one
        got = plugin.filter_tasks([other], na)
        assert got and got[0].name == "other"


class TestLongTail:
    """The reference test module's long tail (node_groups/tests.rs):
    atomic-pipeline races, task-switching merge ordering, stale-task
    compare-and-delete."""

    def _solo(self, plugin, ctx, addr, loc=None):
        ctx.node_store.add_node(mk_node(addr, loc=loc))
        cfg = plugin.configurations[0]
        return plugin._create_group(cfg, [addr])

    def test_concurrent_setnx_assignment_single_winner(self):
        """Two schedulers race to bind a group's task: exactly one task id
        wins and both observe it (SET-NX semantics, mod.rs:471-476)."""
        import threading

        ctx = StoreContext.new_test()
        cfg = NodeGroupConfiguration(name="g", min_group_size=1, max_group_size=2)
        plugin = make_plugin(ctx, [cfg])
        group = self._solo(plugin, ctx, "0xr1")
        tasks = [mk_topo_task(f"t{i}", ["g"]) for i in range(8)]
        for t in tasks:
            ctx.task_store.add_task(t)

        results: list[str] = []
        barrier = threading.Barrier(8)

        def assign(seed):
            rng = random.Random(seed)
            p2 = NodeGroupsPlugin(ctx, [cfg], rng=rng)
            barrier.wait()
            got = p2._task_for_group(group, tasks)
            results.append(got.id if got else None)

        threads = [threading.Thread(target=assign, args=(s,)) for s in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(set(results)) == 1 and results[0] is not None

    def test_stale_task_compare_and_delete_preserves_fresh_assignment(self):
        """The stale-task cleanup must not clobber a FRESH assignment that
        landed between the read and the delete (the reference's Lua
        compare-and-delete, mod.rs:447-467)."""
        ctx = StoreContext.new_test()
        cfg = NodeGroupConfiguration(name="g", min_group_size=1, max_group_size=2)
        plugin = make_plugin(ctx, [cfg])
        group = self._solo(plugin, ctx, "0xs1")
        key = GROUP_TASK_KEY.format(group.id)

        live = mk_topo_task("live", ["g"])
        ctx.task_store.add_task(live)
        # group points at a deleted task; another scheduler swaps in a
        # fresh one between our read and cleanup — simulate by hooking get
        ctx.kv.set(key, "deleted-task-id")
        real_get = ctx.kv.get
        swapped = {"done": False}

        def racy_get(k):
            v = real_get(k)
            if k == key and not swapped["done"]:
                swapped["done"] = True
                ctx.kv.set(key, live.id)  # the racing fresh assignment
                return v  # caller still sees the stale value it read
            return v

        ctx.kv.get = racy_get
        try:
            got = plugin._task_for_group(group, [live])
        finally:
            ctx.kv.get = real_get
        # the fresh assignment survived the cleanup and was returned
        assert ctx.kv.get(key) == live.id
        assert got is not None and got.id == live.id

    def test_merge_proximity_orders_batch(self):
        """Merged batch is seeded by a located solo and filled nearest
        first (mod.rs:760-850): the far-away solo is left out."""
        ctx = StoreContext.new_test()
        cfg = NodeGroupConfiguration(name="g", min_group_size=1, max_group_size=2)
        plugin = make_plugin(ctx, [cfg], policy=TaskSwitchingPolicy.ALWAYS)
        paris = NodeLocation(latitude=48.85, longitude=2.35)
        lyon = NodeLocation(latitude=45.76, longitude=4.84)
        tokyo = NodeLocation(latitude=35.68, longitude=139.69)
        self._solo(plugin, ctx, "0xparis", loc=paris)
        self._solo(plugin, ctx, "0xtokyo", loc=tokyo)
        self._solo(plugin, ctx, "0xlyon", loc=lyon)
        assert plugin.try_merge_solo_groups() >= 1
        groups = plugin.get_groups()
        merged = next(g for g in groups if len(g.nodes) == 2)
        assert set(merged.nodes) == {"0xparis", "0xlyon"}

    def test_if_unassigned_policy_blocks_on_any_task(self):
        """IF_UNASSIGNED (the reference's prefer_larger_groups=false): one
        held task in the batch blocks the merge (mod.rs:277-287)."""
        ctx = StoreContext.new_test()
        cfg = NodeGroupConfiguration(name="g", min_group_size=2, max_group_size=4)
        plugin = make_plugin(ctx, [cfg], policy=TaskSwitchingPolicy.IF_UNASSIGNED)
        g1 = self._solo(plugin, ctx, "0xu1")
        self._solo(plugin, ctx, "0xu2")
        ctx.kv.set(GROUP_TASK_KEY.format(g1.id), "task-held")
        assert plugin.try_merge_solo_groups() == 0
        # free the task -> merge proceeds
        ctx.kv.delete(GROUP_TASK_KEY.format(g1.id))
        assert plugin.try_merge_solo_groups() == 1

    def test_if_unassigned_merges_around_task_holder(self):
        """A task-holding solo must not poison the batch: the unassigned
        solos still merge (no livelock)."""
        ctx = StoreContext.new_test()
        cfg = NodeGroupConfiguration(name="g", min_group_size=2, max_group_size=2)
        plugin = make_plugin(ctx, [cfg], policy=TaskSwitchingPolicy.IF_UNASSIGNED)
        held = self._solo(plugin, ctx, "0xh")
        self._solo(plugin, ctx, "0xf1")
        self._solo(plugin, ctx, "0xf2")
        ctx.kv.set(GROUP_TASK_KEY.format(held.id), "task-held")
        assert plugin.try_merge_solo_groups() == 1
        merged = next(g for g in plugin.get_groups() if len(g.nodes) == 2)
        assert set(merged.nodes) == {"0xf1", "0xf2"}
        assert plugin.get_group(held.id) is not None  # untouched

    def test_merged_group_gets_best_task_including_unrestricted(self):
        """find_best_task_for_group treats tasks with NO topology
        restriction as compatible with any group (mod.rs:1132-1164)."""
        ctx = StoreContext.new_test()
        cfg = NodeGroupConfiguration(name="g", min_group_size=1, max_group_size=2)
        plugin = make_plugin(ctx, [cfg], policy=TaskSwitchingPolicy.ALWAYS)
        self._solo(plugin, ctx, "0xb1")
        self._solo(plugin, ctx, "0xb2")
        unrestricted = Task(name="anywhere", image="img", state=TaskState.PENDING)
        ctx.task_store.add_task(unrestricted)
        assert plugin.try_merge_solo_groups() == 1
        merged = next(g for g in plugin.get_groups() if len(g.nodes) == 2)
        assert ctx.kv.get(GROUP_TASK_KEY.format(merged.id)) == unrestricted.id

    def test_concurrent_merge_and_dissolve_leave_consistent_state(self):
        """Atomic-pipeline race: a status-change dissolve racing the merge
        must never leave orphan node_to_group mappings or dangling
        group_task keys (the reference's pipe.atomic() invariants)."""
        import threading

        ctx = StoreContext.new_test()
        cfg = NodeGroupConfiguration(name="g", min_group_size=1, max_group_size=4)
        plugin = make_plugin(ctx, [cfg], policy=TaskSwitchingPolicy.ALWAYS)
        groups = [self._solo(plugin, ctx, f"0xc{i}") for i in range(6)]

        barrier = threading.Barrier(2)

        def merge():
            barrier.wait()
            plugin.try_merge_solo_groups()

        def dissolve():
            barrier.wait()
            for g in groups:
                plugin.dissolve_group(g.id)

        t1 = threading.Thread(target=merge)
        t2 = threading.Thread(target=dissolve)
        t1.start(); t2.start(); t1.join(); t2.join()

        # invariant: every node_to_group entry points at a live group, and
        # every live group's members point back at it
        live = {g.id: g for g in plugin.get_groups()}
        mapping = ctx.kv.hgetall("node_to_group")
        for addr, gid in mapping.items():
            assert gid in live, f"orphan mapping {addr} -> {gid}"
            assert addr in live[gid].nodes
        for gid, g in live.items():
            for addr in g.nodes:
                assert mapping.get(addr) == gid


class TestReferenceScenarios:
    """Direct ports of reference node_groups/tests.rs scenarios not yet
    covered by the suites above."""

    def _world(self, configs, nodes, policy=TaskSwitchingPolicy.ALWAYS):
        ctx = StoreContext.new_test()
        plugin = make_plugin(ctx, configs, policy=policy)
        for n in nodes:
            ctx.node_store.add_node(n)
        return ctx, plugin

    def test_group_formation_priority(self):
        """tests.rs test_group_formation_priority: with contested nodes,
        the larger-min / more-specific config forms first."""
        big = NodeGroupConfiguration(name="big", min_group_size=3, max_group_size=3)
        small = NodeGroupConfiguration(name="small", min_group_size=1, max_group_size=1)
        # registration order is small-first: the sort must still give 'big'
        # the nodes it needs
        ctx, plugin = self._world(
            [small, big], [mk_node(f"0xfp{i}") for i in range(3)]
        )
        for cfg in ("small", "big"):
            ctx.kv.sadd(ENABLED_CONFIGS, cfg)
        plugin.try_form_new_groups()
        by_config = {}
        for g in plugin.get_groups():
            by_config.setdefault(g.configuration_name, []).append(g)
        assert len(by_config.get("big", [])) == 1
        assert len(by_config["big"][0].nodes) == 3
        assert "small" not in by_config  # big consumed all three

    def test_building_largest_possible_groups(self):
        """tests.rs test_building_largest_possible_groups: formation fills
        to max_group_size when nodes allow."""
        cfg = NodeGroupConfiguration(name="g", min_group_size=2, max_group_size=4)
        ctx, plugin = self._world([cfg], [mk_node(f"0xlg{i}") for i in range(4)])
        ctx.kv.sadd(ENABLED_CONFIGS, "g")
        plugin.try_form_new_groups()
        groups = plugin.get_groups()
        assert len(groups) == 1 and len(groups[0].nodes) == 4

    def test_multiple_groups_same_configuration(self):
        """tests.rs test_multiple_groups_same_configuration: abundant nodes
        form several groups of one config."""
        cfg = NodeGroupConfiguration(name="g", min_group_size=2, max_group_size=2)
        ctx, plugin = self._world([cfg], [mk_node(f"0xmg{i}") for i in range(6)])
        ctx.kv.sadd(ENABLED_CONFIGS, "g")
        plugin.try_form_new_groups()
        groups = plugin.get_groups()
        assert len(groups) == 3
        assert all(len(g.nodes) == 2 for g in groups)
        grouped = [a for g in groups for a in g.nodes]
        assert len(set(grouped)) == 6  # no node in two groups

    def test_reformation_on_death(self):
        """tests.rs test_reformation_on_death: a member death dissolves the
        group; the next management tick re-forms from survivors + spares."""
        cfg = NodeGroupConfiguration(name="g", min_group_size=2, max_group_size=2)
        nodes = [mk_node(f"0xrd{i}") for i in range(3)]
        ctx, plugin = self._world([cfg], nodes)
        ctx.kv.sadd(ENABLED_CONFIGS, "g")
        plugin.try_form_new_groups()
        group = plugin.get_groups()[0]
        victim_addr = group.nodes[0]
        victim = ctx.node_store.get_node(victim_addr)
        victim.status = NodeStatus.DEAD
        ctx.node_store.update_node(victim)
        plugin.handle_status_change(victim)
        assert plugin.get_group(group.id) is None  # dissolved
        # next tick: survivor + the spare re-form
        plugin.try_form_new_groups()
        regrouped = plugin.get_groups()
        assert any(
            len(g.nodes) == 2 and victim_addr not in g.nodes for g in regrouped
        )

    def test_merge_only_compatible_groups(self):
        """tests.rs test_merge_only_compatible_groups: solos of different
        configurations never merge together."""
        a = NodeGroupConfiguration(name="a", min_group_size=1, max_group_size=4)
        b = NodeGroupConfiguration(name="b", min_group_size=1, max_group_size=4)
        ctx, plugin = self._world([a, b], [])
        for i, cfg in enumerate([a, a, b, b]):
            addr = f"0xmc{i}"
            ctx.node_store.add_node(mk_node(addr))
            plugin._create_group(cfg, [addr])
        plugin.try_merge_solo_groups()
        merged_a = [g for g in plugin.get_groups() if g.configuration_name == "a"]
        merged_b = [g for g in plugin.get_groups() if g.configuration_name == "b"]
        assert len(merged_a) == 1 and len(merged_b) == 1
        # membership must match the ORIGINATING config, not just be
        # disjoint with matching labels
        assert set(merged_a[0].nodes) == {"0xmc0", "0xmc1"}
        assert set(merged_b[0].nodes) == {"0xmc2", "0xmc3"}

    def test_task_assignment_during_merge(self):
        """tests.rs test_task_assignment_during_merge: a single shared task
        among merged solos carries to the merged group."""
        cfg = NodeGroupConfiguration(name="g", min_group_size=1, max_group_size=2)
        ctx, plugin = self._world([cfg], [])
        task = mk_topo_task("carry", ["g"])
        ctx.task_store.add_task(task)
        for i in range(2):
            addr = f"0xtm{i}"
            ctx.node_store.add_node(mk_node(addr))
            g = plugin._create_group(cfg, [addr])
            ctx.kv.set(GROUP_TASK_KEY.format(g.id), task.id)
        assert plugin.try_merge_solo_groups() == 1
        merged = next(g for g in plugin.get_groups() if len(g.nodes) == 2)
        assert ctx.kv.get(GROUP_TASK_KEY.format(merged.id)) == task.id
