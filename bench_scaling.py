"""Scaling table for the 1M x 1M provider-sharded configuration (ladder #4).

Produces BASELINE.md's missing evidence: MEASURED per-shard numbers for the
two stages of the sparse pipeline —

  stage A  candidates_topk   streaming top-K candidate generation,
                             peak memory O(P_shard * tile)
  stage B  sparse auction    frontier auction over [T, K] candidates
                             (single-device and mesh-sharded)

— plus compile-time HBM envelopes from XLA's buffer assignment at the FULL
ladder-#4 shapes (P_shard = 1M/8 per v5e-8 chip, T = 1M, K = 64), which do
not require executing at that scale.

Run on whatever backend is up (the axon TPU when healthy, the virtual CPU
mesh otherwise); every row is labeled with the platform it was measured on.
Usage: python bench_scaling.py [--full]  (--full uses ladder-#4 tile/K and
larger measurement shapes; default is a quick pass).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--full", action="store_true")
    parser.add_argument("--cpu", action="store_true", help="pin the CPU backend")
    parser.add_argument(
        "--artifact",
        default="artifacts/bench_scaling_rows.jsonl",
        help="JSONL file each stage row is APPENDED to as it completes — "
        "a timeout/kill preserves every finished stage's evidence "
        "(VERDICT r5 'what's weak' #4). Empty string disables.",
    )
    parser.add_argument(
        "--trace",
        default="",
        help="flight-recorder trace whose snapshot supplies the measured "
        "populations (sliced to each stage's shape) instead of the "
        "inline generator — the same captured fleet every run measures",
    )
    args = parser.parse_args()

    import os

    if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()

    import bench  # device_healthy probe + synth data (host-side)
    import jax

    if args.cpu or not bench.device_healthy(timeout=120):
        if not args.cpu:
            log("accelerator unreachable: measuring on the virtual CPU mesh")
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from protocol_tpu.ops.cost import CostWeights
    from protocol_tpu.ops.encoding import FeatureEncoder
    from protocol_tpu.ops.sparse import assign_auction_sparse, candidates_topk
    from protocol_tpu.parallel import assign_auction_sparse_sharded, make_mesh

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    log(f"platform={platform} devices={n_dev}")

    # ---- shapes
    K = 64
    TILE = 1024
    LADDER_P_SHARD = 1_000_000 // 8  # per-chip provider shard on v5e-8
    LADDER_T = 1_000_000
    if args.full:
        P_MEAS, T_MEAS = 131_072, 8_192  # measured stage-A shard
        T_AUCTION = 65_536  # measured stage-B frontier set
    else:
        P_MEAS, T_MEAS = 16_384, 2_048
        T_AUCTION = 8_192

    rng = np.random.default_rng(0)
    enc = FeatureEncoder()
    weights = CostWeights()

    # population source: the shared generators (trace/synth.py), or a
    # recorded trace's snapshot sliced to each stage's measurement shape
    if args.trace:
        from protocol_tpu.ops.encoding import (
            EncodedProviders,
            EncodedRequirements,
        )
        from protocol_tpu.trace import format as tfmt

        snap = tfmt.read_trace(args.trace).snapshot
        if snap is None:
            raise SystemExit(f"{args.trace}: no snapshot frame")

        def population(rng_, n_p, n_t):
            if n_p > snap.n_providers or n_t > snap.n_tasks:
                raise SystemExit(
                    f"{args.trace} holds {snap.n_providers}x{snap.n_tasks} "
                    f"rows; stage needs {n_p}x{n_t}"
                )
            return (
                EncodedProviders(
                    **{k: v[:n_p] for k, v in snap.p_cols.items()}
                ),
                EncodedRequirements(
                    **{k: v[:n_t] for k, v in snap.r_cols.items()}
                ),
            )
    else:
        def population(rng_, n_p, n_t):
            return (
                bench.synth_providers(rng_, n_p),
                bench.synth_requirements(rng_, n_t),
            )

    rows: list[dict] = []

    from protocol_tpu.utils.artifacts import append_jsonl

    def emit(row: dict) -> None:
        # kill-proof evidence: every completed stage lands on disk NOW
        rows.append(row)
        append_jsonl(args.artifact, row)

    # Identity-bust helper: the axon remote-TPU client memoizes executions
    # on (executable, input buffer ids) AND content-dedups uploads, so
    # repeat calls on the same (or re-uploaded identical) inputs replay
    # cached results in ~0 ms. Every timed lambda takes a per-call salt and
    # must thread it into one input via `x + salt * 0` ON DEVICE so each
    # iteration is a real execution (values stay bit-identical).
    _salt_counter = [0]

    def _force(out):
        # The axon client defers work: block_until_ready alone returns
        # without executing (measured 0.000 s for full 2000-round solves).
        # A SCALAR readback of the result is the only reliable completion
        # barrier — device-side slice first so only bytes, not the tensor,
        # cross the tunnel (large readbacks hang).
        leaf = jnp.ravel(jax.tree.leaves(out)[0])[:1]
        jax.device_get(leaf)

    # per-iteration walls of the most recent measure() call — the obs
    # histograms turn them into p50/p99 fields on the artifact rows
    # (distribution numbers instead of means only)
    last_walls_s: list[float] = []

    def measure(fn, warmup=1, iters=3):
        # the salt is passed with a DISTINCT value (content-dedup would
        # collapse identical 0.0 uploads); lambdas neutralize it on device
        # via `x + z * 0`
        for _ in range(warmup):
            _salt_counter[0] += 1
            _force(fn(jnp.float32(_salt_counter[0])))
        last_walls_s.clear()
        t_all = time.perf_counter()
        for _ in range(iters):
            _salt_counter[0] += 1
            t0 = time.perf_counter()
            out = fn(jnp.float32(_salt_counter[0]))
            _force(out)
            last_walls_s.append(time.perf_counter() - t0)
        return (time.perf_counter() - t_all) / iters, out

    def tick_pct() -> dict:
        """p50/p99 (seconds) of the most recent measure()'s iterations —
        exact (np.percentile over the retained walls; the obs histograms
        are for streams whose samples can't be kept)."""
        if not last_walls_s:
            return {"p50_s": 0.0, "p99_s": 0.0, "iters": 0}
        return {
            "p50_s": round(float(np.percentile(last_walls_s, 50)), 4),
            "p99_s": round(float(np.percentile(last_walls_s, 99)), 4),
            "iters": len(last_walls_s),
        }

    # ---------------- stage A: candidate generation ----------------
    log(f"stage A: candidates_topk P={P_MEAS} T={T_MEAS} K={K} tile={TILE}")
    ep_np, er_np = population(rng, P_MEAS, T_MEAS)
    ep_dev = jax.tree.map(jnp.asarray, ep_np)
    er_dev = jax.tree.map(jnp.asarray, er_np)
    secs, (cand_p, cand_c) = measure(
        lambda z: candidates_topk(
            bench.salt_providers(ep_dev, z), er_dev, weights, k=K, tile=TILE
        )
    )
    cells = P_MEAS * T_MEAS
    emit(
        {
            "stage": "A candidates_topk (measured)",
            "platform": platform,
            "shape": f"P={P_MEAS} T={T_MEAS} K={K} tile={TILE}",
            "wall_s": round(secs, 3),
            "cells_per_s": round(cells / secs / 1e9, 3),  # Gcell/s
            **tick_pct(),
        }
    )
    log(f"  {secs:.3f}s  ({cells / secs / 1e9:.2f} Gcells/s)")

    # full ladder-#4 stage-A cost model: (P_shard x T) cells per chip
    ladder_cells = LADDER_P_SHARD * LADDER_T
    emit(
        {
            "stage": "A candidates_topk (extrapolated per chip)",
            "platform": f"{platform} rate -> v5e-8 shard",
            "shape": f"P_shard={LADDER_P_SHARD} T={LADDER_T} K={K}",
            "wall_s": round(ladder_cells / (cells / secs), 1),
            "note": "linear in cells at fixed tile; v5e MXU rate is the "
            "open factor (measure on-chip when healthy)",
        }
    )

    # ---- stage-boundary overlap: stage B's BIDIRECTIONAL candidate
    # generation (the wire-path default's dominant cost-build) starts on a
    # worker thread NOW, while stage A's compile-time envelope analysis
    # runs — the generation wall is still timed inside the thread and
    # reported in its own row, but the artifact run's total wall-clock
    # (the thing timeouts kill) no longer pays the two stages in sequence.
    from concurrent.futures import ThreadPoolExecutor

    from protocol_tpu.ops.sparse import candidates_topk_bidir

    P_B = T_AUCTION
    epb, erb = population(rng, P_B, T_AUCTION)

    def _gen_bidir():
        t0 = time.perf_counter()
        cpb, ccb = candidates_topk_bidir(
            epb, erb, weights, k=K, tile=TILE, reverse_r=8, extra=16
        )
        jax.block_until_ready((cpb, ccb))
        return cpb, ccb, time.perf_counter() - t0

    overlap_pool = ThreadPoolExecutor(max_workers=1)
    bidir_future = overlap_pool.submit(_gen_bidir)

    # compile-time HBM envelope at FULL shard shape (no execution)
    log("stage A: HBM envelope via XLA buffer assignment at full shard shape")
    try:
        import dataclasses

        def _struct_like(obj, n):
            out = {}
            for f in dataclasses.fields(obj):
                a = np.asarray(getattr(obj, f.name))
                shape = (n,) + a.shape[1:]
                out[f.name] = jax.ShapeDtypeStruct(shape, a.dtype)
            return dataclasses.replace(obj, **out)

        ep_s = _struct_like(ep_np, LADDER_P_SHARD)
        # T enters via the tile scan; the envelope is dominated by P*tile
        lowered = jax.jit(
            lambda ep, er: candidates_topk(ep, er, weights, k=K, tile=TILE)
        ).lower(ep_s, _struct_like(er_np, TILE * 2))
        ma = lowered.compile().memory_analysis()
        hbm_gb = (ma.temp_size_in_bytes + ma.argument_size_in_bytes) / 1e9
        emit(
            {
                "stage": "A candidates_topk (HBM envelope, compile-time)",
                "platform": f"{platform} buffer assignment",
                "shape": f"P_shard={LADDER_P_SHARD} tile={TILE} K={K}",
                "hbm_gb": round(hbm_gb, 2),
                "fits_16gb": hbm_gb < 16,
            }
        )
        log(f"  {hbm_gb:.2f} GB (fits 16 GB: {hbm_gb < 16})")
    except Exception as e:
        log(f"  envelope analysis failed: {e}")

    # ---------------- stage B: sparse frontier auction ----------------
    # The BIDIRECTIONAL-candidate row comes first: it is the wire-path
    # default (every production matcher path generates bidir candidates),
    # so a run killed mid-stage-B leaves the row that matters on disk
    # (VERDICT r5 "what's weak" #4's ordering half).
    cpb, ccb, gen_bidir = bidir_future.result()
    overlap_pool.shutdown(wait=False)
    cov_bd = int(np.unique(np.asarray(cpb)[np.asarray(cpb) >= 0]).size)
    log(
        f"stage B: sparse auction T={T_AUCTION} K={K} single-device "
        f"(bidir wire-path default; gen overlapped stage A: {gen_bidir:.2f}s)"
    )
    secs_b, res = measure(
        lambda z: assign_auction_sparse(
            cpb, ccb + z * 0, num_providers=P_B, eps=0.05, max_iters=2000,
            frontier=min(T_AUCTION, 8192), retire=True,
        ).provider_for_task
    )
    assigned = int((np.asarray(res) >= 0).sum())
    emit(
        {
            "stage": "B sparse auction (measured, 1 device, bidir wire-path default)",
            "platform": platform,
            "shape": f"T={T_AUCTION} K={K} reverse_r=8 extra=16",
            "wall_s": round(secs_b, 3),
            "assignments_per_s": round(assigned / secs_b, 0),
            "assigned": assigned,
            "bidir_gen_s": round(gen_bidir, 2),
            "coverage": cov_bd,
        }
    )
    log(f"  {secs_b:.3f}s, {assigned}/{T_AUCTION} assigned "
        f"({assigned / secs_b:,.0f} assignments/s)")

    # stage B sharded over the mesh (same wire-path candidates)
    log(f"stage B: mesh-sharded auction over {n_dev} devices")
    mesh = make_mesh(n_dev)
    secs_s, res_s = measure(
        lambda z: assign_auction_sparse_sharded(
            cpb, ccb + z * 0, num_providers=P_B, mesh=mesh,
            eps=0.05, max_iters=2000, frontier=min(T_AUCTION, 8192),
            retire=True,
        ).provider_for_task
    )
    assigned_s = int((np.asarray(res_s) >= 0).sum())
    emit(
        {
            "stage": f"B sparse auction (measured, {n_dev}-device mesh, bidir)",
            "platform": platform,
            "shape": f"T={T_AUCTION} K={K} reverse_r=8 extra=16",
            "wall_s": round(secs_s, 3),
            "assignments_per_s": round(assigned_s / secs_s, 0),
        }
    )
    log(f"  {secs_s:.3f}s sharded ({assigned_s} assigned)")

    # stage B memory envelope at T=1M
    try:
        cp_s = jax.ShapeDtypeStruct((LADDER_T, K), jnp.int32)
        cc_s = jax.ShapeDtypeStruct((LADDER_T, K), jnp.float32)
        lowered = jax.jit(
            lambda p, c: assign_auction_sparse(
                p, c, num_providers=LADDER_P_SHARD, eps=0.05,
                max_iters=2000, frontier=8192, retire=True,
            ).provider_for_task
        ).lower(cp_s, cc_s)
        ma = lowered.compile().memory_analysis()
        hbm_gb = (ma.temp_size_in_bytes + ma.argument_size_in_bytes) / 1e9
        emit(
            {
                "stage": "B sparse auction (HBM envelope, compile-time)",
                "platform": f"{platform} buffer assignment",
                "shape": f"T={LADDER_T} K={K}",
                "hbm_gb": round(hbm_gb, 2),
                "fits_16gb": hbm_gb < 16,
            }
        )
        log(f"  T=1M envelope: {hbm_gb:.2f} GB (fits 16 GB: {hbm_gb < 16})")
    except Exception as e:
        log(f"  envelope analysis failed: {e}")

    # ---------------- stage B2: assignment completeness -------------------
    # VERDICT r3 item 3's done-bar: >=99% assignment at T>=65k in bounded
    # wall-clock. Forward-only top-k coverage-caps the matching (every
    # task's window holds the same cheap providers; at 65k only 49,813 of
    # 65,536 providers appear in ANY list -> 66.5% assigned no matter how
    # long the auction runs). Bidirectional candidates (per-provider
    # reverse edges, ops/sparse.candidates_topk_bidir) restore coverage
    # and the eps-scaled solve completes: 99.98% measured at 65k.
    from protocol_tpu.ops.sparse import assign_auction_sparse_scaled

    log(f"stage B2: completeness, forward vs bidir candidates T={T_AUCTION}")
    cp, cc = candidates_topk(epb, erb, weights, k=K, tile=TILE)
    jax.block_until_ready((cp, cc))
    cov_fwd = int(np.unique(np.asarray(cp)[np.asarray(cp) >= 0]).size)
    res_fwd = assign_auction_sparse_scaled(cp, cc, num_providers=P_B)
    a_fwd = int((np.asarray(res_fwd.provider_for_task) >= 0).sum())
    t0 = time.perf_counter()
    res_bd = assign_auction_sparse_scaled(cpb, ccb, num_providers=P_B)
    solve_bidir = time.perf_counter() - t0
    a_bd = int((np.asarray(res_bd.provider_for_task) >= 0).sum())
    emit(
        {
            "stage": "B2 completeness: forward vs bidir candidates",
            "platform": platform,
            "shape": f"T={T_AUCTION} K={K} reverse_r=8 extra=16",
            "fwd_assigned": a_fwd,
            "fwd_coverage": cov_fwd,
            "bidir_assigned": a_bd,
            "bidir_coverage": cov_bd,
            "bidir_gen_s": round(gen_bidir, 2),
            "bidir_solve_s": round(solve_bidir, 2),
            "complete_pct": round(100.0 * a_bd / T_AUCTION, 2),
        }
    )
    log(
        f"  forward: {a_fwd}/{T_AUCTION} assigned (coverage {cov_fwd}) -> "
        f"bidir: {a_bd}/{T_AUCTION} ({100.0 * a_bd / T_AUCTION:.2f}%, "
        f"coverage {cov_bd})"
    )

    # ---------------- stage C: incremental (warm) vs cold solve ----------
    # VERDICT r2 item 3's done-bar: the warm path re-bids only the delta
    # frontier from carried prices + the previous matching. At kernel level
    # the candidate structure is shared, so this isolates the auction's
    # warm win; the matcher-level win (which also skips candidate
    # regeneration via the CandidateCache) is larger — see
    # tests/test_scale_matcher.py.
    from protocol_tpu.ops.sparse import assign_auction_sparse_warm

    # bidir candidates from stage B2: the production path — forward-only
    # lists coverage-cap at scale and the cold ladder then "wins" by
    # stalling out at the wall, making warm-vs-cold meaningless
    log(f"stage C: warm vs cold sparse solve T={T_AUCTION} K={K} (bidir)")
    secs_cold, out_cold = measure(
        lambda z: assign_auction_sparse_scaled(
            cpb, ccb + z * 0, num_providers=P_B, frontier=min(T_AUCTION, 8192),
            with_state=True,
        )
    )
    cold_pct = tick_pct()
    res_cold, price_cold, retired_cold = out_cold
    # 1% churn: drop a contiguous 1% of the matching (freed providers /
    # re-opened tasks) and re-solve warm from the carried duals — prices
    # AND the retirement mask (the production chain shape; without the
    # mask the warm solve re-fights the priced-out tail every step)
    p4t0 = jnp.asarray(res_cold.provider_for_task)
    n_churn = max(T_AUCTION // 100, 1)
    p4t0 = p4t0.at[:n_churn].set(-1)
    secs_warm, _ = measure(
        lambda z: assign_auction_sparse_warm(
            cpb, ccb + z * 0, num_providers=P_B,
            price0=price_cold, p4t0=p4t0, retired0=retired_cold,
            frontier=min(T_AUCTION, 8192),
        )[0].provider_for_task
    )
    warm_pct = tick_pct()
    emit(
        {
            "stage": "C warm vs cold solve (measured)",
            "platform": platform,
            "shape": f"T={T_AUCTION} K={K}, 1% churn",
            "cold_s": round(secs_cold, 4),
            "warm_s": round(secs_warm, 4),
            "speedup": round(secs_cold / max(secs_warm, 1e-9), 1),
            "cold_p50_s": cold_pct["p50_s"],
            "cold_p99_s": cold_pct["p99_s"],
            "warm_p50_s": warm_pct["p50_s"],
            "warm_p99_s": warm_pct["p99_s"],
        }
    )
    log(
        f"  cold {secs_cold * 1e3:.1f} ms -> warm {secs_warm * 1e3:.1f} ms "
        f"({secs_cold / max(secs_warm, 1e-9):.1f}x)"
    )

    # ---------------- stage D: ladder #5 vector bin-pack ------------------
    # BASELINE.md config #5: multi-resource capacity vectors + anti-affinity
    # (ops/binpack.py). Measured at the 10k-task test scale.
    from protocol_tpu.ops.binpack import assign_binpack_ffd

    P_D, T_D, R_D = 2048, 10240, 4
    log(f"stage D: vector bin-pack P={P_D} T={T_D} R={R_D} + anti-affinity")
    rng_d = np.random.default_rng(5)
    cost_d = rng_d.uniform(1.0, 10.0, (P_D, T_D)).astype(np.float32)
    cost_d[rng_d.uniform(size=(P_D, T_D)) > 0.7] = 1e9
    demand = rng_d.integers(1, 4, (T_D, R_D)).astype(np.float32)
    capacity = rng_d.integers(8, 21, (P_D, R_D)).astype(np.float32)
    n_groups = T_D // 8
    anti = np.where(
        rng_d.uniform(size=T_D) < 0.2,
        rng_d.integers(0, n_groups, T_D),
        -1,
    ).astype(np.int32)
    loc = rng_d.integers(0, 256, P_D).astype(np.int32)
    cost_d_dev, demand_dev, capacity_dev = (
        jnp.asarray(cost_d), jnp.asarray(demand), jnp.asarray(capacity)
    )
    anti_dev, loc_dev = jnp.asarray(anti), jnp.asarray(loc)
    secs_d, res_d = measure(
        lambda z: assign_binpack_ffd(
            cost_d_dev + z * 0, demand_dev, capacity_dev,
            anti_group=anti_dev, loc_id=loc_dev,
            num_locations=256, num_groups=n_groups,
        ).provider_for_task
    )
    packed = int((np.asarray(res_d) >= 0).sum())
    emit(
        {
            "stage": "D vector bin-pack + anti-affinity (measured)",
            "platform": platform,
            "shape": f"P={P_D} T={T_D} R={R_D} groups={n_groups}",
            "wall_s": round(secs_d, 3),
            "tasks_per_s": round(packed / max(secs_d, 1e-9), 0),
            "packed": packed,
            **tick_pct(),
        }
    )
    log(f"  {secs_d:.3f}s, {packed}/{T_D} packed")

    # ---------------- stage S: ladder #3 Sinkhorn-OT ----------------
    # BASELINE config #3 (100k x 100k soft assignment, 1 chip): matrix-
    # free log-domain potentials (ops/blocked.py — O(P*tile) peak, never
    # [P, T]) + plan-guided candidate rounding.
    from protocol_tpu.ops.blocked import sinkhorn_potentials_blocked

    P_S = T_S = T_AUCTION
    # Each Sinkhorn iteration streams 2 full [P, T] logsumexp passes:
    # 20 iterations at 65k on the 1-core CPU host is ~8 h — the reason
    # the r4 artifact died before emitting a stage-S row. The iteration
    # budget is therefore platform-aware (overridable via
    # PROTOCOL_TPU_SINKHORN_ITERS) and recorded in the row's shape
    # string; quality at few iterations is measured separately against
    # the auction referee (scripts/stage_s_100k.py: mean cost within
    # 0.02% at iters=5).
    default_iters = 20 if platform != "cpu" else 4
    sink_iters = int(
        os.environ.get("PROTOCOL_TPU_SINKHORN_ITERS", default_iters)
    )
    log(
        f"stage S: sinkhorn potentials + rounding P=T={P_S} "
        f"(matrix-free, iters={sink_iters})"
    )
    eps_sink = 0.05
    # potentials are computed ONCE and fed into the plan-guided rounding
    # (assign_sinkhorn_blocked would recompute them, doubling the
    # dominant O(P*T*iters) stage — the r4/early-r5 artifact deaths)
    t0 = time.perf_counter()
    u_s, _v_s = sinkhorn_potentials_blocked(
        epb, erb, weights, eps=eps_sink, num_iters=sink_iters, tile=TILE
    )
    jax.block_until_ready(u_s)
    secs_pot = time.perf_counter() - t0
    t0 = time.perf_counter()
    offset_s = -eps_sink * jnp.where(u_s > -5e17, u_s, 0.0)
    cand_sp, cand_sc2 = candidates_topk(
        epb, erb, weights, k=32, tile=TILE, provider_offset=offset_s
    )
    res_s = assign_auction_sparse_scaled(
        cand_sp, cand_sc2, num_providers=P_S, eps_start=1.0, eps_end=0.02
    )
    sink_assigned = int((np.asarray(res_s.provider_for_task) >= 0).sum())
    secs_s_full = secs_pot + (time.perf_counter() - t0)
    emit(
        {
            "stage": "S sinkhorn-OT potentials + rounding (measured)",
            "platform": platform,
            "shape": f"P=T={P_S} iters={sink_iters} tile={TILE}",
            "potentials_s": round(secs_pot, 3),
            "end_to_end_s": round(secs_s_full, 3),
            "assigned": sink_assigned,
        }
    )
    log(
        f"  potentials {secs_pot:.3f}s; end-to-end {secs_s_full:.3f}s "
        f"({sink_assigned}/{T_S} assigned)"
    )
    # ladder-#3 HBM envelope at the full 100k shape (compile-time)
    try:
        import dataclasses as _dc2

        def _sds(obj, n):
            out = {}
            for f in _dc2.fields(obj):
                a = np.asarray(getattr(obj, f.name))
                out[f.name] = jax.ShapeDtypeStruct((n,) + a.shape[1:], a.dtype)
            return _dc2.replace(obj, **out)

        lowered = jax.jit(
            lambda e, r: sinkhorn_potentials_blocked(
                e, r, weights, eps=eps_sink, num_iters=sink_iters, tile=TILE
            )
        ).lower(_sds(epb, 100_000), _sds(erb, 100_000 // TILE * TILE))
        ma = lowered.compile().memory_analysis()
        hbm_gb = (ma.temp_size_in_bytes + ma.argument_size_in_bytes) / 1e9
        emit(
            {
                "stage": "S sinkhorn potentials (HBM envelope, compile-time)",
                "platform": f"{platform} buffer assignment",
                "shape": f"P=T~100k tile={TILE}",
                "hbm_gb": round(hbm_gb, 2),
                "fits_16gb": hbm_gb < 16,
            }
        )
        log(f"  100k envelope: {hbm_gb:.2f} GB (fits 16 GB: {hbm_gb < 16})")
    except Exception as e:
        log(f"  sinkhorn envelope failed: {e}")

    # ---------------- stage S (sparse): native O(nnz) sinkhorn-mt ---------
    # The ladder-#3 engine that actually completes at 100k x 100k
    # (scripts/stage_s_100k.py --engine sparse-mt): log-domain entropic OT
    # over the top-K candidate edges (nnz = T*K_eff per iteration, never
    # O(P*T)) + injective auction-referee rounding seeded from the duals.
    # Measured here at the bench shape on the SAME instance as the blocked
    # row above, so the two engines' wall-clocks are directly comparable.
    try:
        from protocol_tpu import native as native_mod

        if not native_mod.available():
            raise RuntimeError("no native toolchain")
        log(f"stage S (sparse): native sinkhorn-mt P=T={P_S}")
        t0 = time.perf_counter()
        cand_np, cand_nc = native_mod.fused_topk_candidates(
            epb, erb, weights, k=K, reverse_r=8, extra=16, threads=0
        )
        t_cand = time.perf_counter() - t0
        phase_stats: list = []
        t0 = time.perf_counter()
        f_s, _g_s = native_mod.sinkhorn_sparse_anneal(
            cand_np, cand_nc, P_S, eps_start=1.0, eps_end=0.05,
            iters_per_phase=50, tol=1e-2, threads=0,
            phase_stats=phase_stats,
        )
        t_pot_sp = time.perf_counter() - t0
        from protocol_tpu.ops.cost import INFEASIBLE as _INF

        feas = (cand_np >= 0) & (cand_nc < _INF * 0.5)
        price0 = native_mod.sinkhorn_referee_prices(f_s, cand_np, cand_nc)
        t0 = time.perf_counter()
        p4t_sp, _, _ = native_mod.auction_sparse_mt(
            cand_np, cand_nc, num_providers=P_S,
            eps_start=0.32, eps_end=0.02, threads=0, price=price0,
        )
        t_round = time.perf_counter() - t0
        emit(
            {
                "stage": "S sparse sinkhorn-mt + auction-referee rounding (measured)",
                "platform": "native_cpu",
                "shape": f"P=T={P_S} K_eff={cand_np.shape[1]} "
                         f"nnz={int(feas.sum())}",
                "cand_s": round(t_cand, 3),
                "potentials_s": round(t_pot_sp, 3),
                "rounding_s": round(t_round, 3),
                "end_to_end_s": round(t_cand + t_pot_sp + t_round, 3),
                "assigned": int((p4t_sp >= 0).sum()),
                "phases": phase_stats,
            }
        )
        log(
            f"  cand {t_cand:.2f}s + potentials {t_pot_sp:.2f}s + rounding "
            f"{t_round:.2f}s = {t_cand + t_pot_sp + t_round:.2f}s "
            f"({int((p4t_sp >= 0).sum())}/{T_S} assigned)"
        )
    except Exception as e:
        log(f"  sparse sinkhorn-mt stage failed: {e}")

    # ---------------- stage J: first-class jax arena (engine=jax) ---------
    # The jax engine behind the native-arena interface: sharded candidate
    # generation over the FULL visible mesh + adaptive eps-ladder solve
    # with warm dual carry. Device-count provenance rides in the platform
    # field (PR 3 convention); the sharded-gen bits are D-invariant by
    # contract (perf_gate --jax proves it), so this row measures the ICI/
    # host-mesh scaling of an identical computation, not a different one.
    try:
        log(f"stage J: jax arena cold+warm, full {n_dev}-device mesh")
        res_j = bench.run_jax_arena_bench(n=4096, devices=0)
        emit(
            {
                "stage": "J jax arena cold+warm (engine=jax, measured)",
                "platform": f"{platform} d{res_j['devices']}"
                            + ("" if res_j["gen_sharded"] else " unsharded"),
                "shape": "P=T=4096 k=64",
                "cold_s": round(res_j["cold_ms"] / 1e3, 3),
                "cold_gen_s": round(res_j["cold_gen_ms"] / 1e3, 3),
                "cold_solve_s": round(res_j["cold_solve_ms"] / 1e3, 3),
                "warm_tick_s": round(res_j["warm_median_ms"] / 1e3, 3),
                "warm_wall_speedup": res_j["warm_wall_speedup"],
                "warm_solve_speedup": res_j["warm_solve_speedup"],
                "assigned_frac": res_j["assigned_frac"],
            }
        )
        log(
            f"  cold {res_j['cold_ms'] / 1e3:.2f}s -> warm "
            f"{res_j['warm_median_ms'] / 1e3:.2f}s "
            f"({res_j['warm_wall_speedup']}x wall, "
            f"{res_j['warm_solve_speedup']}x solve stage; "
            f"sharded={res_j['gen_sharded']})"
        )
    except Exception as e:
        log(f"  jax arena stage failed: {e}")

    print(json.dumps({"platform": platform, "devices": n_dev, "rows": rows}, indent=1))


if __name__ == "__main__":
    main()
