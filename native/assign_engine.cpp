// Native CPU assignment engine.
//
// The host-side counterpart of the JAX kernels in protocol_tpu/ops: the
// control plane's fallback scheduler backend when no accelerator is
// reachable, and the honest CPU baseline for bench.py. Implements the same
// contracts as ops/assign.py / ops/sparse.py:
//
//   greedy_assign       task-ordered greedy: each task takes the cheapest
//                       free compatible provider (ties -> lowest provider
//                       index) — bit-compatible with assign_greedy.
//   auction_sparse      Gauss-Seidel Bertsekas auction on top-K candidate
//                       lists with eps-scaling and give-up retirement —
//                       the CPU mirror of assign_auction_sparse_scaled.
//   topk_candidates     per-task top-k cheapest providers from a dense
//                       cost matrix (with the same deterministic hash
//                       jitter as candidates_topk).
//
// Exposed as a C ABI for ctypes (no pybind11 dependency). All matrices are
// row-major contiguous; cost is [P, T] f32 with INFEASIBLE = 1e9 marking
// incompatible pairs. Build: make native  (g++ -O3 -shared -fPIC).

#include <algorithm>
#include <cfloat>
#include <cstdint>
#include <cstring>
#include <queue>
#include <vector>

namespace {

constexpr float kInfeasible = 1e9f;
constexpr float kNeg = -1e18f;

inline float jitter(uint32_t p, uint32_t t) {
  // must match protocol_tpu/ops/sparse.py candidates_topk
  uint32_t h = (p * 2654435761u) ^ (t * 40503u);
  return static_cast<float>(h & 1023u) * 1e-7f;
}

}  // namespace

extern "C" {

// Greedy matching. cost: [P*T] row-major ([p*T + t]); task_order: length T
// (or null for 0..T-1); out_provider_for_task: length T (filled with -1 for
// unassigned).
void greedy_assign(const float* cost, int32_t P, int32_t T,
                   const int32_t* task_order, int32_t* out_provider_for_task) {
  std::vector<uint8_t> avail(P, 1);
  for (int32_t i = 0; i < T; ++i) {
    out_provider_for_task[i] = -1;
  }
  for (int32_t i = 0; i < T; ++i) {
    const int32_t t = task_order ? task_order[i] : i;
    float best = kInfeasible;
    int32_t best_p = -1;
    for (int32_t p = 0; p < P; ++p) {
      if (!avail[p]) continue;
      const float c = cost[static_cast<int64_t>(p) * T + t];
      if (c < best) {
        best = c;
        best_p = p;
      }
    }
    if (best_p >= 0 && best < kInfeasible * 0.5f) {
      out_provider_for_task[t] = best_p;
      avail[best_p] = 0;
    }
  }
}

// Per-task top-k candidates from a dense cost matrix, jittered for
// degenerate marketplaces. out_cand_provider/out_cand_cost: [T*k].
void topk_candidates(const float* cost, int32_t P, int32_t T, int32_t k,
                     int32_t* out_cand_provider, float* out_cand_cost) {
  if (k > P) k = P;
  std::vector<std::pair<float, int32_t>> row(P);
  for (int32_t t = 0; t < T; ++t) {
    for (int32_t p = 0; p < P; ++p) {
      float c = cost[static_cast<int64_t>(p) * T + t];
      if (c < kInfeasible * 0.5f) c += jitter(p, t);
      row[p] = {c, p};
    }
    std::partial_sort(row.begin(), row.begin() + k, row.end());
    for (int32_t j = 0; j < k; ++j) {
      const bool feas = row[j].first < kInfeasible * 0.5f;
      out_cand_provider[static_cast<int64_t>(t) * k + j] =
          feas ? row[j].second : -1;
      out_cand_cost[static_cast<int64_t>(t) * k + j] = row[j].first;
    }
  }
}

// Gauss-Seidel auction on candidate lists with eps-scaling.
// cand_provider/cand_cost: [T*K]; out_provider_for_task: length T.
// Returns the number of assigned tasks.
int32_t auction_sparse(const int32_t* cand_provider, const float* cand_cost,
                       int32_t P, int32_t T, int32_t K, float eps_start,
                       float eps_end, float scale, int64_t max_events,
                       int32_t* out_provider_for_task) {
  std::vector<float> price(P, 0.0f);
  std::vector<int32_t> owner(P, -1);  // task holding each provider
  std::vector<int32_t> p4t(T, -1);
  std::vector<uint8_t> retired(T, 0);

  float max_cost = 0.0f;
  for (int64_t i = 0; i < static_cast<int64_t>(T) * K; ++i) {
    if (cand_provider[i] >= 0 && cand_cost[i] > max_cost) {
      max_cost = cand_cost[i];
    }
  }
  const float give_up = -(2.0f * max_cost + 10.0f);

  std::vector<int32_t> open;
  open.reserve(T);
  int64_t events = 0;

  float eps = eps_start;
  while (true) {
    const bool final_phase = eps <= eps_end;
    // Retirement only in the final phase: at coarse eps, price overshoot
    // from an unfillable tail would push *viable* tasks past give-up.
    // Coarse phases instead get a bounded event budget and hand off.
    const int64_t phase_budget =
        final_phase ? max_events : events + 4 * static_cast<int64_t>(T);

    // collect open tasks for this eps phase
    open.clear();
    for (int32_t t = 0; t < T; ++t) {
      if (p4t[t] < 0 && !retired[t]) open.push_back(t);
    }
    // Gauss-Seidel sweeps until the phase stabilizes or exhausts its budget
    while (!open.empty() && events < phase_budget && events < max_events) {
      const int32_t t = open.back();
      open.pop_back();
      if (p4t[t] >= 0 || retired[t]) continue;
      // best + second-best value over candidates at current prices
      float v1 = kNeg, v2 = kNeg;
      int32_t p1 = -1;
      for (int32_t j = 0; j < K; ++j) {
        const int32_t p = cand_provider[static_cast<int64_t>(t) * K + j];
        if (p < 0) continue;
        const float v =
            -cand_cost[static_cast<int64_t>(t) * K + j] - price[p];
        if (v > v1) {
          v2 = v1;
          v1 = v;
          p1 = p;
        } else if (v > v2) {
          v2 = v;
        }
      }
      if (p1 < 0) {
        retired[t] = 1;  // no feasible candidates at all
        continue;
      }
      if (v1 < give_up) {
        if (final_phase) {
          retired[t] = 1;  // priced out everywhere: not worth it
        }
        continue;  // coarse phases: park it; the next phase re-collects
      }
      if (v2 < -1e8f) v2 = -1e8f;  // single-option floor
      ++events;
      price[p1] += (v1 - v2) + eps;
      const int32_t evicted = owner[p1];
      owner[p1] = t;
      p4t[t] = p1;
      if (evicted >= 0) {
        p4t[evicted] = -1;
        open.push_back(evicted);
      }
    }
    if (eps <= eps_end || events >= max_events) break;
    eps = std::max(eps * scale, eps_end);
    // eps-CS repair: holders whose assignment violates the tighter eps
    // re-enter the auction (keeping happy holders seated avoids both the
    // full-reset cost and the mass-retirement pathology of pumped prices)
    for (int32_t t = 0; t < T; ++t) {
      const int32_t held = p4t[t];
      if (held < 0 || retired[t]) continue;
      float v1 = kNeg;
      float vcur = kNeg;
      for (int32_t j = 0; j < K; ++j) {
        const int32_t p = cand_provider[static_cast<int64_t>(t) * K + j];
        if (p < 0) continue;
        const float v =
            -cand_cost[static_cast<int64_t>(t) * K + j] - price[p];
        if (v > v1) v1 = v;
        if (p == held) vcur = v;
      }
      if (vcur < v1 - eps) {
        owner[held] = -1;
        p4t[t] = -1;
      }
    }
  }

  // Cleanup pass: a forward auction never lowers prices, so an unfillable
  // tail can leave providers stranded at pumped prices while feasible tasks
  // sit retired. Sweep the leftover graph greedily (cheapest free candidate
  // per remaining task) — the reference's matcher semantics on the tail,
  // guaranteeing no provider stays idle while a compatible task waits.
  for (int32_t t = 0; t < T; ++t) {
    if (p4t[t] >= 0) continue;
    float best = kInfeasible;
    int32_t best_p = -1;
    for (int32_t j = 0; j < K; ++j) {
      const int32_t p = cand_provider[static_cast<int64_t>(t) * K + j];
      if (p < 0 || owner[p] >= 0) continue;
      const float c = cand_cost[static_cast<int64_t>(t) * K + j];
      if (c < best) {
        best = c;
        best_p = p;
      }
    }
    if (best_p >= 0 && best < kInfeasible * 0.5f) {
      owner[best_p] = t;
      p4t[t] = best_p;
    }
  }

  int32_t assigned = 0;
  for (int32_t t = 0; t < T; ++t) {
    out_provider_for_task[t] = p4t[t];
    if (p4t[t] >= 0) ++assigned;
  }
  return assigned;
}

}  // extern "C"
