// Native CPU assignment engine.
//
// The host-side counterpart of the JAX kernels in protocol_tpu/ops: the
// control plane's fallback scheduler backend when no accelerator is
// reachable, and the honest CPU baseline for bench.py. Implements the same
// contracts as ops/assign.py / ops/sparse.py:
//
//   greedy_assign       task-ordered greedy: each task takes the cheapest
//                       free compatible provider (ties -> lowest provider
//                       index) — bit-compatible with assign_greedy.
//   auction_sparse      Gauss-Seidel Bertsekas auction on top-K candidate
//                       lists with eps-scaling and give-up retirement —
//                       the CPU mirror of assign_auction_sparse_scaled.
//   topk_candidates     per-task top-k cheapest providers from a dense
//                       cost matrix (with the same deterministic hash
//                       jitter as candidates_topk).
//
// Exposed as a C ABI for ctypes (no pybind11 dependency). All matrices are
// row-major contiguous; cost is [P, T] f32 with INFEASIBLE = 1e9 marking
// incompatible pairs. Build: make native  (g++ -O3 -shared -fPIC).

#include <algorithm>
#include <atomic>
#include <cfloat>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <memory>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

#if defined(__x86_64__) || defined(_M_X64)
#include <immintrin.h>  // lint: isa-dispatch-include
#define ENGINE_HAVE_X86 1
#endif

namespace {

constexpr float kInfeasible = 1e9f;
constexpr float kNeg = -1e18f;

// ---- ISA runtime dispatch (the per-ISA determinism seam) -------------------
//
// One baseline .so (built -march=x86-64-v2, no AVX anywhere in common
// code) carries scalar + AVX2 + AVX-512 kernels via GCC target
// attributes; which pipeline runs is a RUNTIME choice, never a build
// fact. The contract: results are bit-identical *within* an ISA across
// thread counts and builds (the scalar pipeline is additionally
// bit-identical across ISAs of the same request — it IS the referee).
// scalar == the historical score_cell pipeline, so every committed
// golden is the scalar-ISA golden. avx2/avx512 share ONE fmaf-matched
// float pipeline (score_cell_fma below is provably lane-equal to both
// vector kernels), so the two vector ISAs also agree bit-for-bit with
// each other — only scalar-vs-vector differs, in ULPs of the proximity
// term.
constexpr int32_t kIsaScalar = 0;
constexpr int32_t kIsaAvx2 = 1;
constexpr int32_t kIsaAvx512 = 2;

#ifndef ENGINE_DEFAULT_ISA
#define ENGINE_DEFAULT_ISA 0
#endif

// best supported ISA <= want: the graceful-fallback primitive (a host
// without AVX2 serves any request with scalar; "auto" is a request for
// avx512 that clamps to whatever the host has)
inline int32_t clamp_isa(int32_t want) {
#if defined(ENGINE_HAVE_X86)
  __builtin_cpu_init();
  int32_t best = kIsaScalar;
  if (want >= kIsaAvx2 && __builtin_cpu_supports("avx2") &&
      __builtin_cpu_supports("fma")) {
    best = kIsaAvx2;
  }
  if (want >= kIsaAvx512 && best == kIsaAvx2 &&
      __builtin_cpu_supports("avx512f") &&
      __builtin_cpu_supports("avx512dq") &&
      __builtin_cpu_supports("avx512bw") &&
      __builtin_cpu_supports("avx512vl")) {
    best = kIsaAvx512;
  }
  return best;
#else
  (void)want;
  return kIsaScalar;
#endif
}

// relaxed atomic: solves snapshot the value once at entry; setting the
// ISA concurrently with a running solve changes the NEXT solve only
std::atomic<int32_t> g_isa{clamp_isa(ENGINE_DEFAULT_ISA)};

inline float jitter(uint32_t p, uint32_t t) {
  // must match protocol_tpu/ops/sparse.py candidates_topk
  uint32_t h = (p * 2654435761u) ^ (t * 40503u);
  return static_cast<float>(h & 1023u) * 1e-7f;
}

// ---- engine phase stats (the observability plane's native layer) ----------
//
// Every -mt kernel takes a trailing nullable `int64_t* stats_out` pointing
// at ENGINE_STATS_SLOTS i64 slots (the ctypes wrapper documents the per-
// kernel slot layout). Stats are counters + steady_clock phase timings
// accumulated ON THE CALLING THREAD ONLY (helper threads never touch the
// array — no new shared state, TSan-clean by construction), and a null
// stats_out skips every clock read, so the uninstrumented path is
// byte-for-byte the historical one. Stats NEVER feed solver state: the
// matching is bit-identical with or without them (the replay-identity CI
// job runs with instrumentation on).
constexpr int kEngineStatsSlots = 16;

inline int64_t now_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// ---- threading primitives for the -mt engine variants ----------------------
//
// The engine stays DETERMINISTIC under any thread count: parallel regions
// only ever compute thread-private results from a shared read-only
// snapshot, and every cross-thread combination step is a value-based
// reduction (set selection / max-with-tie-rule) whose result is
// independent of chunk boundaries. threads=1 runs the identical code path.

inline int resolve_threads(int32_t threads, int64_t work_items) {
  int n = threads;
  if (n <= 0) {
    unsigned hw = std::thread::hardware_concurrency();
    n = hw ? static_cast<int>(hw) : 1;
  }
  if (work_items < n) n = work_items > 0 ? static_cast<int>(work_items) : 1;
  return n;
}

// Fork-join: fn(tid) on `threads` threads; the caller runs tid 0.
inline void run_threads(int threads, const std::function<void(int)>& fn) {
  if (threads <= 1) {
    fn(0);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(threads - 1);
  for (int i = 1; i < threads; ++i) pool.emplace_back(fn, i);
  fn(0);
  for (auto& t : pool) t.join();
}

// On-demand helper pool for round-synchronous loops (the -mt auction):
// workers are spawned once per solve and engaged ONLY when a round is
// large enough to amortize the wakeup (a condvar round-trip costs ~10 us;
// late auction rounds have a handful of open tasks and run inline on the
// caller, where the same code costs nanoseconds). Which thread computes a
// bid never affects its value, so engagement thresholds cannot change
// results.
class HelperPool {
 public:
  explicit HelperPool(int helpers) {
    threads_.reserve(helpers);
    for (int i = 0; i < helpers; ++i) {
      threads_.emplace_back([this, tid = i + 1] { worker(tid); });
    }
  }
  ~HelperPool() {
    {
      std::lock_guard<std::mutex> lk(m_);
      exit_ = true;
    }
    cv_work_.notify_all();
    for (auto& t : threads_) t.join();
  }
  // fn(tid) runs on every thread (caller = tid 0); returns when all done.
  void run(const std::function<void(int)>& fn) {
    {
      std::lock_guard<std::mutex> lk(m_);
      job_ = &fn;
      remaining_ = static_cast<int>(threads_.size());
      ++gen_;
    }
    cv_work_.notify_all();
    fn(0);
    std::unique_lock<std::mutex> lk(m_);
    cv_done_.wait(lk, [&] { return remaining_ == 0; });
  }

 private:
  void worker(int tid) {
    uint64_t seen = 0;
    while (true) {
      const std::function<void(int)>* job;
      {
        std::unique_lock<std::mutex> lk(m_);
        cv_work_.wait(lk, [&] { return exit_ || gen_ != seen; });
        if (exit_) return;
        seen = gen_;
        job = job_;
      }
      (*job)(tid);
      {
        std::lock_guard<std::mutex> lk(m_);
        if (--remaining_ == 0) cv_done_.notify_one();
      }
    }
  }

  std::mutex m_;
  std::condition_variable cv_work_, cv_done_;
  std::vector<std::thread> threads_;
  const std::function<void(int)>* job_ = nullptr;
  uint64_t gen_ = 0;
  int remaining_ = 0;
  bool exit_ = false;
};

}  // namespace

extern "C" {

// Greedy matching. cost: [P*T] row-major ([p*T + t]); task_order: length T
// (or null for 0..T-1); out_provider_for_task: length T (filled with -1 for
// unassigned).
void greedy_assign(const float* cost, int32_t P, int32_t T,
                   const int32_t* task_order, int32_t* out_provider_for_task) {
  std::vector<uint8_t> avail(P, 1);
  for (int32_t i = 0; i < T; ++i) {
    out_provider_for_task[i] = -1;
  }
  for (int32_t i = 0; i < T; ++i) {
    const int32_t t = task_order ? task_order[i] : i;
    float best = kInfeasible;
    int32_t best_p = -1;
    for (int32_t p = 0; p < P; ++p) {
      if (!avail[p]) continue;
      const float c = cost[static_cast<int64_t>(p) * T + t];
      if (c < best) {
        best = c;
        best_p = p;
      }
    }
    if (best_p >= 0 && best < kInfeasible * 0.5f) {
      out_provider_for_task[t] = best_p;
      avail[best_p] = 0;
    }
  }
}

// ---- ISA provenance ABI ----------------------------------------------------
// isa codes: 0 = scalar, 1 = avx2, 2 = avx512 (the ctypes wrapper maps
// names). engine_set_isa clamps to the best SUPPORTED isa <= the request
// and returns the effective value — dispatch can never crash on a host
// that lacks the ISA, it degrades (the graceful-fallback contract).
int32_t engine_isa_supported(int32_t isa) {
  if (isa < kIsaScalar || isa > kIsaAvx512) return 0;
  return clamp_isa(isa) == isa ? 1 : 0;
}

int32_t engine_set_isa(int32_t isa) {
  if (isa < kIsaScalar) isa = kIsaScalar;
  if (isa > kIsaAvx512) isa = kIsaAvx512;
  const int32_t eff = clamp_isa(isa);
  g_isa.store(eff, std::memory_order_relaxed);
  return eff;
}

int32_t engine_get_isa() { return g_isa.load(std::memory_order_relaxed); }

namespace {

// (cost, provider) lexicographic order packed into one u64: the f32 cost
// bits go through the standard total-order transform (sign-flip for
// nonneg, full flip for neg) so unsigned integer comparison == (cost,
// provider) pair comparison with ties broken by lower provider index.
inline uint64_t pack_key(float c, int32_t p) {
  uint32_t b;
  std::memcpy(&b, &c, 4);
  b ^= static_cast<uint32_t>(static_cast<int32_t>(b) >> 31) | 0x80000000u;
  return (static_cast<uint64_t>(b) << 32) | static_cast<uint32_t>(p);
}

inline float unpack_key_cost(uint64_t key) {
  uint32_t b = static_cast<uint32_t>(key >> 32);
  b ^= ~static_cast<uint32_t>(static_cast<int32_t>(b) >> 31) | 0x80000000u;
  float c;
  std::memcpy(&c, &b, 4);
  return c;
}

// Insert key into the sorted length-k array buf, dropping the current max
// (caller guarantees key < buf[k-1]). Position found branchlessly.
inline void sorted_insert(uint64_t* buf, int32_t k, uint64_t key) {
  const int32_t pos =
      static_cast<int32_t>(std::lower_bound(buf, buf + k, key) - buf);
  std::memmove(buf + pos + 1, buf + pos,
               static_cast<size_t>(k - 1 - pos) * 8);
  buf[pos] = key;
}

// Forward declarations for the runtime-dispatched lane helpers; the
// definitions (the only code in this file allowed to touch intrinsics)
// live in the PER-ISA KERNELS section below. The comparison helpers are
// value-only (no float arithmetic), so using them at any ISA can never
// change result bits — they gate which cells take the slow path, and
// the slow path re-checks exactly.
#if defined(ENGINE_HAVE_X86)
__attribute__((target("avx2"))) uint32_t lanes_le_arr_avx2(
    const float* v, const float* bound);
__attribute__((target("avx512f,avx512dq,avx512bw,avx512vl"))) uint32_t
lanes_le_arr_avx512(const float* v, const float* bound);
__attribute__((target("avx2"))) uint32_t lanes_le_bcast_avx2(const float* v,
                                                             float bound);
__attribute__((target("avx512f,avx512dq,avx512bw,avx512vl"))) uint32_t
lanes_le_bcast_avx512(const float* v, float bound);
#else
uint32_t lanes_le_arr_avx2(const float* v, const float* bound);
uint32_t lanes_le_arr_avx512(const float* v, const float* bound);
uint32_t lanes_le_bcast_avx2(const float* v, float bound);
uint32_t lanes_le_bcast_avx512(const float* v, float bound);
#endif

}  // namespace

// Per-task top-k candidates from a dense cost matrix, jittered for
// degenerate marketplaces. out_cand_provider/out_cand_cost: [T*k].
//
// Blocked for cache behavior: the matrix is [P, T] row-major, so a
// per-task column walk strides by T (one cache line per element). Instead
// we sweep provider rows once, visiting a tile of B tasks per pass —
// contiguous reads — and maintain a bounded max-heap of the k cheapest
// candidates per task in the tile. The heap root gives a fast reject:
// jitter >= 0, so an unjittered c > root can never enter.
void topk_candidates(const float* cost, int32_t P, int32_t T, int32_t k,
                     int32_t* out_cand_provider, float* out_cand_cost) {
  if (k > P) k = P;
  if (k <= 0 || T <= 0) return;  // empty marketplace: nothing to emit
  const int32_t isa = g_isa.load(std::memory_order_relaxed);
  const int32_t B = 2048;  // tile buffers: 2048*k*8 B = 1 MB (L2) at k=64
  std::vector<uint64_t> bufs(static_cast<size_t>(B) * k);  // sorted keys
  std::vector<float> root_c(B);  // worst kept cost per task (fast reject)
  const int32_t fill = std::min(k, P);
  for (int32_t t0 = 0; t0 < T; t0 += B) {
    const int32_t nb = std::min(B, T - t0);
    // Fill phase: the first k providers all enter every task's buffer.
    for (int32_t p = 0; p < fill; ++p) {
      const float* row = cost + static_cast<int64_t>(p) * T + t0;
      for (int32_t i = 0; i < nb; ++i) {
        const float c = row[i];
        const float cj = (c < kInfeasible * 0.5f) ? c + jitter(p, t0 + i) : c;
        bufs[static_cast<size_t>(i) * k + p] = pack_key(cj, p);
      }
    }
    for (int32_t i = 0; i < nb; ++i) {
      uint64_t* buf = bufs.data() + static_cast<size_t>(i) * k;
      std::sort(buf, buf + k);
      root_c[i] = unpack_key_cost(buf[k - 1]);
    }
    for (int32_t p = fill; p < P; ++p) {
      const float* row = cost + static_cast<int64_t>(p) * T + t0;
      const auto consider = [&](int32_t i) {
        const float c = row[i];
        const float cj = (c < kInfeasible * 0.5f) ? c + jitter(p, t0 + i) : c;
        const uint64_t key = pack_key(cj, p);
        uint64_t* buf = bufs.data() + static_cast<size_t>(i) * k;
        if (key >= buf[k - 1]) return;
        sorted_insert(buf, k, key);
        root_c[i] = unpack_key_cost(buf[k - 1]);
      };
      int32_t i = 0;
      // wide-lane reject (runtime dispatch): jitter >= 0, so an
      // unjittered c > root can never enter the buffer; survivors (rare
      // after warm-up) take the slow path. Comparison-only, so result
      // bits match the scalar loop at every ISA.
      if (isa == kIsaAvx512) {
        for (; i + 16 <= nb; i += 16) {
          uint32_t m = lanes_le_arr_avx512(row + i, root_c.data() + i);
          while (m) {
            const int32_t j = __builtin_ctz(m);
            m &= m - 1;
            consider(i + j);
          }
        }
      } else if (isa == kIsaAvx2) {
        for (; i + 8 <= nb; i += 8) {
          uint32_t m = lanes_le_arr_avx2(row + i, root_c.data() + i);
          while (m) {
            const int32_t j = __builtin_ctz(m);
            m &= m - 1;
            consider(i + j);
          }
        }
      }
      for (; i < nb; ++i) {
        if (row[i] <= root_c[i]) consider(i);
      }
    }
    // emit (buffers already sorted ascending by (cost, provider))
    for (int32_t i = 0; i < nb; ++i) {
      const uint64_t* buf = bufs.data() + static_cast<size_t>(i) * k;
      const int64_t base = static_cast<int64_t>(t0 + i) * k;
      for (int32_t j = 0; j < k; ++j) {
        const float c = unpack_key_cost(buf[j]);
        const bool feas = c < kInfeasible * 0.5f;
        out_cand_provider[base + j] =
            feas ? static_cast<int32_t>(buf[j] & 0xffffffffu) : -1;
        out_cand_cost[base + j] = c;
      }
    }
  }
}

// ---------------------------------------------------------------------------
// Fused cost + top-k: the degraded-mode hot path. Mirrors what the TPU
// pipeline does (ops/sparse.py candidates_topk streams tiles of the cost
// tensor without materializing [P, T]): compute each task's provider costs
// from the encoded features (ops/encoding.py compat_mask semantics,
// ops/cost.py cost terms) directly into an L2-resident scratch row, then
// select the top-k via the vectorized reject + sorted-insert kernel. The
// [P, T] tensor never exists, which is where the old fallback spent ~90%
// of its wall-clock (XLA cost build + strided re-read).
// ---------------------------------------------------------------------------

namespace {

// cephes-style asinf: |err| a few ulp on [0, 1] — candidate selection is
// jitter-decorrelated, so last-ulp drift vs XLA's asin only perturbs exact
// near-ties between backends, never feasibility.
inline float asin_poly(const float x) {
  const bool big = x > 0.5f;
  const float xx = big ? std::sqrt((1.0f - x) * 0.5f) : x;
  const float z = xx * xx;
  const float p =
      ((((4.2163199048e-2f * z + 2.4181311049e-2f) * z + 4.5470025998e-2f) * z +
        7.4953002686e-2f) *
           z +
       1.6666752422e-1f) *
          z * xx +
      xx;
  return big ? 1.5707963267948966f - 2.0f * p : p;
}

// Option semantics of encoding.py _ge_min/_le_max: no constraint passes;
// a constraint on an absent (-1) spec fails.
inline bool ge_min(int32_t spec, int32_t req) {
  return req < 0 || (spec >= 0 && spec >= req);
}
inline bool le_max(int32_t spec, int32_t req) {
  return req < 0 || (spec >= 0 && spec <= req);
}

}  // namespace

// Provider features, shape [P] each (i32 / u8 / f32 as in EncodedProviders).
struct ProviderFeatures {
  const int32_t* gpu_count;
  const int32_t* gpu_mem_mb;
  const int32_t* gpu_model_id;
  const uint8_t* has_gpu;
  const uint8_t* has_cpu;
  const int32_t* cpu_cores;
  const int32_t* ram_mb;
  const int32_t* storage_gb;
  const float* lat;
  const float* lon;
  const uint8_t* has_location;
  const float* price;
  const float* load;
  const uint8_t* valid;
};

// Requirement features: scalars [T]; GPU options [T*K]; model mask [T*K*W].
struct RequirementFeatures {
  const uint8_t* cpu_required;
  const int32_t* cpu_cores;
  const int32_t* ram_mb;
  const int32_t* storage_gb;
  const uint8_t* gpu_opt_valid;
  const int32_t* gpu_count;
  const int32_t* gpu_mem_min;
  const int32_t* gpu_mem_max;
  const int32_t* gpu_total_mem_min;
  const int32_t* gpu_total_mem_max;
  const uint32_t* gpu_model_mask;
  const uint8_t* gpu_model_constrained;
  const float* lat;
  const float* lon;
  const uint8_t* has_location;
  const float* priority;
  const uint8_t* valid;
};

namespace {

// Per-solve provider precomputes shared by every task chunk: base cost
// term + trig for the cos-product haversine form (sin^2(d/2) =
// (1-cos d)/2 expands into products of per-side sin/cos — no per-cell
// trig).
struct ProviderPrecomp {
  std::vector<float> base, slat, clat, slon, clon;
  explicit ProviderPrecomp(const ProviderFeatures* pf, int32_t P,
                           float w_price, float w_load)
      : base(P), slat(P), clat(P), slon(P), clon(P) {
    for (int32_t p = 0; p < P; ++p) {
      base[p] = w_price * pf->price[p] + w_load * pf->load[p];
      slat[p] = std::sin(pf->lat[p]);
      clat[p] = std::cos(pf->lat[p]);
      slon[p] = std::sin(pf->lon[p]);
      clon[p] = std::cos(pf->lon[p]);
    }
  }
};

// Per-task scalars hoisted once per row pass (and, for the repair
// kernel, once per SOLVE — every phase shares the same table).
struct TaskScore {
  uint8_t valid, cpu_req, has_loc;
  int32_t cores, ram, storage;
  float slat, clat, slon, clon, prio;
  bool any_opt;
};

inline TaskScore make_task_score(const RequirementFeatures* rf, int32_t t,
                                 int32_t K, float w_priority) {
  TaskScore ts;
  ts.valid = rf->valid[t];
  ts.cpu_req = rf->cpu_required[t];
  ts.cores = rf->cpu_cores[t];
  ts.ram = rf->ram_mb[t];
  ts.storage = rf->storage_gb[t];
  ts.slat = std::sin(rf->lat[t]);
  ts.clat = std::cos(rf->lat[t]);
  ts.slon = std::sin(rf->lon[t]);
  ts.clon = std::cos(rf->lon[t]);
  ts.has_loc = rf->has_location[t];
  ts.prio = w_priority * rf->priority[t];
  ts.any_opt = false;
  for (int32_t o = 0; o < K; ++o) {
    ts.any_opt =
        ts.any_opt || rf->gpu_opt_valid[static_cast<int64_t>(t) * K + o];
  }
  return ts;
}

// One GPU OR-alternative check — the exact expressions of the historical
// scalar pass, factored so the full-scan, bucket-pruned, and repair
// paths share ONE implementation (bit-identity across paths holds by
// construction, not by parallel maintenance of three copies).
inline bool gpu_option_ok(const ProviderFeatures* pf,
                          const RequirementFeatures* rf, int64_t tk,
                          int32_t W, int32_t p) {
  const int32_t pc = pf->gpu_count[p];
  const int32_t pm = pf->gpu_mem_mb[p];
  const int32_t rc = rf->gpu_count[tk];
  const bool count_ok = rc < 0 || (pc < 0 ? rc == 0 : pc == rc);
  const bool mem_ok =
      ge_min(pm, rf->gpu_mem_min[tk]) && le_max(pm, rf->gpu_mem_max[tk]);
  const int32_t rtot_min = rf->gpu_total_mem_min[tk];
  const int32_t rtot_max = rf->gpu_total_mem_max[tk];
  const int32_t total = pc * pm;
  const bool have_total = pc >= 0 && pm >= 0;
  const bool tot_ok = (rtot_min < 0 || !have_total || total >= rtot_min) &&
                      (rtot_max < 0 || !have_total || total <= rtot_max);
  const int32_t mid = pf->gpu_model_id[p];
  const int32_t mid0 = mid > 0 ? mid : 0;
  const uint32_t* mask = rf->gpu_model_mask + tk * W;
  const bool model_hit = (mask[mid0 >> 5] >> (mid0 & 31)) & 1u;
  const bool model_ok =
      !rf->gpu_model_constrained[tk] || (mid >= 0 && model_hit);
  return count_ok && mem_ok && tot_ok && model_ok;
}

// The per-(provider, task) cost cell: feasibility gates + cost terms,
// kInfeasible when any gate fails. Each cell is a pure function of its
// own features — identical expressions in every caller means identical
// float bits in every caller.
inline float score_cell(const ProviderFeatures* pf,
                        const RequirementFeatures* rf,
                        const ProviderPrecomp& pre, const TaskScore& ts,
                        int32_t t, int32_t K, int32_t W, int32_t p,
                        float w_proximity) {
  bool ok =
      !ts.cpu_req || (pf->has_cpu[p] && ge_min(pf->cpu_cores[p], ts.cores));
  ok = ok && ge_min(pf->ram_mb[p], ts.ram);
  ok = ok && ge_min(pf->storage_gb[p], ts.storage);
  ok = ok && pf->valid[p] && ts.valid;
  if (ok && ts.any_opt) {
    bool gany = false;
    for (int32_t o = 0; o < K && !gany; ++o) {
      const int64_t tk = static_cast<int64_t>(t) * K + o;
      if (!rf->gpu_opt_valid[tk]) continue;
      gany = gpu_option_ok(pf, rf, tk, W, p);
    }
    ok = pf->has_gpu[p] && gany;
  }
  if (!ok) return kInfeasible;
  float c = pre.base[p] - ts.prio;
  if (ts.has_loc && pf->has_location[p]) {
    const float cos_dlat = pre.clat[p] * ts.clat + pre.slat[p] * ts.slat;
    const float cos_dlon = pre.clon[p] * ts.clon + pre.slon[p] * ts.slon;
    float a = 0.5f * (1.0f - cos_dlat) +
              pre.clat[p] * ts.clat * 0.5f * (1.0f - cos_dlon);
    a = a < 0.0f ? 0.0f : (a > 1.0f ? 1.0f : a);
    const float dist = 2.0f * 6371.0f * asin_poly(std::sqrt(a));
    c += w_proximity * dist;
  }
  return c;
}

// A lane block of provider features: the SAME pointers serve the full
// scan (the pf arrays + ProviderPrecomp columns ARE provider-ordered
// SoA) and the bucket-ordered BucketSoA copies — one vector kernel,
// two layouts. Index i is a position INTO these arrays; mapping back
// to the original provider id is the caller's job.
struct ProviderBlockView {
  const uint8_t *valid, *has_cpu, *has_gpu, *has_location;
  const int32_t *cpu_cores, *ram_mb, *storage_gb;
  const int32_t *gpu_count, *gpu_mem_mb, *gpu_model_id;
  const float *base, *slat, *clat, *slon, *clon;
};

inline ProviderBlockView full_view(const ProviderFeatures* pf,
                                   const ProviderPrecomp& pre) {
  return {pf->valid,     pf->has_cpu,    pf->has_gpu,
          pf->has_location, pf->cpu_cores, pf->ram_mb,
          pf->storage_gb, pf->gpu_count,  pf->gpu_mem_mb,
          pf->gpu_model_id, pre.base.data(), pre.slat.data(),
          pre.clat.data(), pre.slon.data(), pre.clon.data()};
}

// ==== BEGIN PER-ISA KERNELS (isa-dispatch) =================================
// The ONLY code in this file allowed to touch intrinsics or per-ISA
// target attributes (enforced by the isa-dispatch lint rule). Every
// entry point routes through the kIsaOps dispatch table below; common
// code never branches on compile-time ISA macros.
//
// Determinism contract: score_cell_fma is the per-cell twin of BOTH
// vector kernels — every operation maps 1:1 onto a lane op with the
// same rounding (fmaf == vfmaddps lane, sqrtf == vsqrtps lane, the
// clamp mirrors maxps/minps operand order), so any mix of block and
// single-cell scoring at the same vector ISA produces identical bits.
// AVX2 and AVX-512 use the same op sequence at different widths, hence
// agree with each other too. The file is compiled -ffp-contract=off so
// no surrounding mul+add ever fuses behind the contract's back.
#if defined(ENGINE_HAVE_X86)

// fmaf-matched scalar scorer for the vector pipeline (isa != scalar):
// gates are the exact integer logic of score_cell; the cost path swaps
// each a*b+c for the single-rounded fmaf the vector lanes execute.
__attribute__((target("avx2,fma"))) float score_cell_fma(
    const ProviderFeatures* pf, const RequirementFeatures* rf,
    const ProviderPrecomp& pre, const TaskScore& ts, int32_t t, int32_t K,
    int32_t W, int32_t p, float w_proximity) {
  bool ok =
      !ts.cpu_req || (pf->has_cpu[p] && ge_min(pf->cpu_cores[p], ts.cores));
  ok = ok && ge_min(pf->ram_mb[p], ts.ram);
  ok = ok && ge_min(pf->storage_gb[p], ts.storage);
  ok = ok && pf->valid[p] && ts.valid;
  if (ok && ts.any_opt) {
    bool gany = false;
    for (int32_t o = 0; o < K && !gany; ++o) {
      const int64_t tk = static_cast<int64_t>(t) * K + o;
      if (!rf->gpu_opt_valid[tk]) continue;
      gany = gpu_option_ok(pf, rf, tk, W, p);
    }
    ok = pf->has_gpu[p] && gany;
  }
  if (!ok) return kInfeasible;
  float c = pre.base[p] - ts.prio;
  if (ts.has_loc && pf->has_location[p]) {
    const float cos_dlat =
        __builtin_fmaf(pre.clat[p], ts.clat, pre.slat[p] * ts.slat);
    const float cos_dlon =
        __builtin_fmaf(pre.clon[p], ts.clon, pre.slon[p] * ts.slon);
    float a = __builtin_fmaf(pre.clat[p] * ts.clat * 0.5f, 1.0f - cos_dlon,
                             0.5f * (1.0f - cos_dlat));
    a = a > 0.0f ? a : 0.0f;  // maxps operand order (second wins ties)
    a = a < 1.0f ? a : 1.0f;  // minps
    const float x = std::sqrt(a);
    const bool big = x > 0.5f;
    const float xx = big ? std::sqrt((1.0f - x) * 0.5f) : x;
    const float z = xx * xx;
    float poly = 4.2163199048e-2f;
    poly = __builtin_fmaf(poly, z, 2.4181311049e-2f);
    poly = __builtin_fmaf(poly, z, 4.5470025998e-2f);
    poly = __builtin_fmaf(poly, z, 7.4953002686e-2f);
    poly = __builtin_fmaf(poly, z, 1.6666752422e-1f);
    const float asin_small = __builtin_fmaf(poly * z, xx, xx);
    const float asin_x =
        big ? __builtin_fmaf(-2.0f, asin_small, 1.5707963267948966f)
            : asin_small;
    const float dist = (2.0f * 6371.0f) * asin_x;
    c += w_proximity * dist;  // separate mul + add, as the lanes do
  }
  return c;
}

// ---- comparison-only lane helpers (bit-safe at any ISA) ----

__attribute__((target("avx2"))) uint32_t lanes_le_arr_avx2(
    const float* v, const float* bound) {
  return static_cast<uint32_t>(_mm256_movemask_ps(_mm256_cmp_ps(
      _mm256_loadu_ps(v), _mm256_loadu_ps(bound), _CMP_LE_OQ)));
}

__attribute__((target("avx512f,avx512dq,avx512bw,avx512vl"))) uint32_t
lanes_le_arr_avx512(const float* v, const float* bound) {
  return _mm512_cmp_ps_mask(_mm512_loadu_ps(v), _mm512_loadu_ps(bound),
                            _CMP_LE_OQ);
}

__attribute__((target("avx2"))) uint32_t lanes_le_bcast_avx2(const float* v,
                                                             float bound) {
  return static_cast<uint32_t>(_mm256_movemask_ps(_mm256_cmp_ps(
      _mm256_loadu_ps(v), _mm256_set1_ps(bound), _CMP_LE_OQ)));
}

__attribute__((target("avx512f,avx512dq,avx512bw,avx512vl"))) uint32_t
lanes_le_bcast_avx512(const float* v, float bound) {
  return _mm512_cmp_ps_mask(_mm512_loadu_ps(v), _mm512_set1_ps(bound),
                            _CMP_LE_OQ);
}

// Block-skip survivors for the repair column sweeps, lanes over tasks:
// survive = (lb <= rev_worst_cost) | use_fwd & (not_full | lb <=
// theta_cost) with lb = base_p - prio[t] (the exact float the per-cell
// precheck packs). Conservative in the float domain — pack_key is
// monotone in cost with id 0 minimal, so key(lb,0) <= key(c,p) implies
// lb <= c; a lane this test retires could never pass the per-cell
// check, and every survivor re-runs that exact check. Prune-only: no
// float result ever changes.
__attribute__((target("avx2"))) uint32_t lb_survivors_avx2(
    float base_p, const float* prio, const float* theta_cost,
    const uint8_t* not_full, float rev_worst_cost, int use_fwd) {
  const __m256 lb =
      _mm256_sub_ps(_mm256_set1_ps(base_p), _mm256_loadu_ps(prio));
  __m256 surv =
      _mm256_cmp_ps(lb, _mm256_set1_ps(rev_worst_cost), _CMP_LE_OQ);
  if (use_fwd) {
    const __m256i nf = _mm256_cmpgt_epi32(
        _mm256_cvtepu8_epi32(
            _mm_loadl_epi64(reinterpret_cast<const __m128i*>(not_full))),
        _mm256_setzero_si256());
    surv = _mm256_or_ps(
        surv, _mm256_or_ps(_mm256_castsi256_ps(nf),
                           _mm256_cmp_ps(lb, _mm256_loadu_ps(theta_cost),
                                         _CMP_LE_OQ)));
  }
  return static_cast<uint32_t>(_mm256_movemask_ps(surv));
}

__attribute__((target("avx512f,avx512dq,avx512bw,avx512vl"))) uint32_t
lb_survivors_avx512(float base_p, const float* prio, const float* theta_cost,
                    const uint8_t* not_full, float rev_worst_cost,
                    int use_fwd) {
  const __m512 lb =
      _mm512_sub_ps(_mm512_set1_ps(base_p), _mm512_loadu_ps(prio));
  __mmask16 surv =
      _mm512_cmp_ps_mask(lb, _mm512_set1_ps(rev_worst_cost), _CMP_LE_OQ);
  if (use_fwd) {
    surv |= _mm512_cmpgt_epi32_mask(
                _mm512_cvtepu8_epi32(_mm_loadu_si128(
                    reinterpret_cast<const __m128i*>(not_full))),
                _mm512_setzero_si512()) |
            _mm512_cmp_ps_mask(lb, _mm512_loadu_ps(theta_cost), _CMP_LE_OQ);
  }
  return surv;
}

// ---- the vector scoring kernels ----
//
// Lane-for-lane ports of score_cell_fma over one block of the view
// (8 lanes AVX2, 16 lanes AVX-512): integer/byte gates fold into a
// lane mask, the cost pipeline is the fixed op sequence documented on
// score_cell_fma, and failed lanes blend to kInfeasible. Reduction
// over a row is NOT done here — callers fold the scored block through
// the same scalar insert sequence as the scalar path, in ascending
// lane order, so selection order is a pure function of the scores.

__attribute__((target("avx2"))) inline __m256i avx2_u8x8(const uint8_t* p) {
  return _mm256_cvtepu8_epi32(
      _mm_loadl_epi64(reinterpret_cast<const __m128i*>(p)));
}

__attribute__((target("avx2"))) inline __m256i avx2_ge(__m256i a, __m256i b) {
  return _mm256_or_si256(_mm256_cmpgt_epi32(a, b), _mm256_cmpeq_epi32(a, b));
}

__attribute__((target("avx2,fma"))) void score_block_avx2(
    const ProviderBlockView& pv, const RequirementFeatures* rf,
    const TaskScore& ts, int32_t t, int32_t K, int32_t W, int32_t i0,
    float w_proximity, float* out) {
  const __m256i zero = _mm256_setzero_si256();
  __m256i ok = ts.valid ? _mm256_set1_epi32(-1) : zero;
  ok = _mm256_and_si256(
      ok, _mm256_cmpgt_epi32(avx2_u8x8(pv.valid + i0), zero));
  if (ts.cpu_req) {
    __m256i cpu_ok = _mm256_cmpgt_epi32(avx2_u8x8(pv.has_cpu + i0), zero);
    if (ts.cores >= 0) {
      const __m256i cores = _mm256_loadu_si256(
          reinterpret_cast<const __m256i*>(pv.cpu_cores + i0));
      cpu_ok = _mm256_and_si256(
          cpu_ok,
          _mm256_and_si256(avx2_ge(cores, _mm256_set1_epi32(ts.cores)),
                           avx2_ge(cores, zero)));
    }
    ok = _mm256_and_si256(ok, cpu_ok);
  }
  if (ts.ram >= 0) {
    const __m256i ram = _mm256_loadu_si256(
        reinterpret_cast<const __m256i*>(pv.ram_mb + i0));
    ok = _mm256_and_si256(
        ok, _mm256_and_si256(avx2_ge(ram, _mm256_set1_epi32(ts.ram)),
                             avx2_ge(ram, zero)));
  }
  if (ts.storage >= 0) {
    const __m256i st = _mm256_loadu_si256(
        reinterpret_cast<const __m256i*>(pv.storage_gb + i0));
    ok = _mm256_and_si256(
        ok, _mm256_and_si256(avx2_ge(st, _mm256_set1_epi32(ts.storage)),
                             avx2_ge(st, zero)));
  }
  if (ts.any_opt && !_mm256_testz_si256(ok, ok)) {
    const __m256i pc = _mm256_loadu_si256(
        reinterpret_cast<const __m256i*>(pv.gpu_count + i0));
    const __m256i pm = _mm256_loadu_si256(
        reinterpret_cast<const __m256i*>(pv.gpu_mem_mb + i0));
    const __m256i mid = _mm256_loadu_si256(
        reinterpret_cast<const __m256i*>(pv.gpu_model_id + i0));
    const __m256i pc_abs = _mm256_cmpgt_epi32(zero, pc);
    const __m256i pm_abs = _mm256_cmpgt_epi32(zero, pm);
    __m256i gany = zero;
    for (int32_t o = 0; o < K; ++o) {
      const int64_t tk = static_cast<int64_t>(t) * K + o;
      if (!rf->gpu_opt_valid[tk]) continue;
      __m256i om = _mm256_set1_epi32(-1);
      const int32_t rc = rf->gpu_count[tk];
      if (rc == 0) {
        om = _mm256_and_si256(
            om, _mm256_or_si256(pc_abs, _mm256_cmpeq_epi32(pc, zero)));
      } else if (rc > 0) {
        om = _mm256_and_si256(om,
                              _mm256_cmpeq_epi32(pc, _mm256_set1_epi32(rc)));
      }
      const int32_t rmem_min = rf->gpu_mem_min[tk];
      if (rmem_min >= 0) {
        om = _mm256_and_si256(
            om, _mm256_andnot_si256(
                    pm_abs, avx2_ge(pm, _mm256_set1_epi32(rmem_min))));
      }
      const int32_t rmem_max = rf->gpu_mem_max[tk];
      if (rmem_max >= 0) {
        om = _mm256_and_si256(
            om, _mm256_andnot_si256(
                    pm_abs, avx2_ge(_mm256_set1_epi32(rmem_max), pm)));
      }
      const int32_t rtot_min = rf->gpu_total_mem_min[tk];
      const int32_t rtot_max = rf->gpu_total_mem_max[tk];
      if (rtot_min >= 0 || rtot_max >= 0) {
        const __m256i total = _mm256_mullo_epi32(pc, pm);
        const __m256i no_total = _mm256_or_si256(pc_abs, pm_abs);
        if (rtot_min >= 0) {
          om = _mm256_and_si256(
              om, _mm256_or_si256(
                      no_total,
                      avx2_ge(total, _mm256_set1_epi32(rtot_min))));
        }
        if (rtot_max >= 0) {
          om = _mm256_and_si256(
              om, _mm256_or_si256(
                      no_total,
                      avx2_ge(_mm256_set1_epi32(rtot_max), total)));
        }
      }
      if (rf->gpu_model_constrained[tk]) {
        const __m256i mid0 = _mm256_max_epi32(mid, zero);
        const __m256i word = _mm256_min_epi32(_mm256_srli_epi32(mid0, 5),
                                              _mm256_set1_epi32(W - 1));
        const __m256i bit = _mm256_and_si256(mid0, _mm256_set1_epi32(31));
        const __m256i words = _mm256_i32gather_epi32(
            reinterpret_cast<const int*>(rf->gpu_model_mask + tk * W), word,
            4);
        const __m256i hit = _mm256_and_si256(_mm256_srlv_epi32(words, bit),
                                             _mm256_set1_epi32(1));
        om = _mm256_and_si256(
            om, _mm256_and_si256(_mm256_cmpgt_epi32(hit, zero),
                                 avx2_ge(mid, zero)));
      }
      gany = _mm256_or_si256(gany, om);
    }
    const __m256i has_gpu =
        _mm256_cmpgt_epi32(avx2_u8x8(pv.has_gpu + i0), zero);
    ok = _mm256_and_si256(ok, _mm256_and_si256(has_gpu, gany));
  }
  __m256 c = _mm256_sub_ps(_mm256_loadu_ps(pv.base + i0),
                           _mm256_set1_ps(ts.prio));
  if (ts.has_loc) {
    const __m256 pclat = _mm256_loadu_ps(pv.clat + i0);
    const __m256 cos_dlat = _mm256_fmadd_ps(
        pclat, _mm256_set1_ps(ts.clat),
        _mm256_mul_ps(_mm256_loadu_ps(pv.slat + i0),
                      _mm256_set1_ps(ts.slat)));
    const __m256 cos_dlon = _mm256_fmadd_ps(
        _mm256_loadu_ps(pv.clon + i0), _mm256_set1_ps(ts.clon),
        _mm256_mul_ps(_mm256_loadu_ps(pv.slon + i0),
                      _mm256_set1_ps(ts.slon)));
    const __m256 one = _mm256_set1_ps(1.0f);
    const __m256 half = _mm256_set1_ps(0.5f);
    __m256 a = _mm256_fmadd_ps(
        _mm256_mul_ps(_mm256_mul_ps(pclat, _mm256_set1_ps(ts.clat)), half),
        _mm256_sub_ps(one, cos_dlon),
        _mm256_mul_ps(half, _mm256_sub_ps(one, cos_dlat)));
    a = _mm256_min_ps(_mm256_max_ps(a, _mm256_setzero_ps()), one);
    const __m256 x = _mm256_sqrt_ps(a);
    const __m256 big = _mm256_cmp_ps(x, half, _CMP_GT_OQ);
    const __m256 xx = _mm256_blendv_ps(
        x, _mm256_sqrt_ps(_mm256_mul_ps(_mm256_sub_ps(one, x), half)), big);
    const __m256 z = _mm256_mul_ps(xx, xx);
    __m256 poly = _mm256_set1_ps(4.2163199048e-2f);
    poly = _mm256_fmadd_ps(poly, z, _mm256_set1_ps(2.4181311049e-2f));
    poly = _mm256_fmadd_ps(poly, z, _mm256_set1_ps(4.5470025998e-2f));
    poly = _mm256_fmadd_ps(poly, z, _mm256_set1_ps(7.4953002686e-2f));
    poly = _mm256_fmadd_ps(poly, z, _mm256_set1_ps(1.6666752422e-1f));
    const __m256 asin_small =
        _mm256_fmadd_ps(_mm256_mul_ps(poly, z), xx, xx);
    const __m256 asin_x = _mm256_blendv_ps(
        asin_small,
        _mm256_fnmadd_ps(_mm256_set1_ps(2.0f), asin_small,
                         _mm256_set1_ps(1.5707963267948966f)),
        big);
    const __m256 dist =
        _mm256_mul_ps(_mm256_set1_ps(2.0f * 6371.0f), asin_x);
    const __m256i ploc =
        _mm256_cmpgt_epi32(avx2_u8x8(pv.has_location + i0), zero);
    c = _mm256_blendv_ps(
        c, _mm256_add_ps(c, _mm256_mul_ps(_mm256_set1_ps(w_proximity), dist)),
        _mm256_castsi256_ps(ploc));
  }
  _mm256_storeu_ps(
      out, _mm256_blendv_ps(_mm256_set1_ps(kInfeasible), c,
                            _mm256_castsi256_ps(ok)));
}

__attribute__((target("avx512f,avx512dq,avx512bw,avx512vl,fma"))) void
score_block_avx512(const ProviderBlockView& pv, const RequirementFeatures* rf,
                   const TaskScore& ts, int32_t t, int32_t K, int32_t W,
                   int32_t i0, float w_proximity, float* out) {
  const __m512i zero = _mm512_setzero_si512();
  const __m512 vinf = _mm512_set1_ps(kInfeasible);
  __mmask16 ok = ts.valid ? static_cast<__mmask16>(0xffff) : 0;
  ok &= _mm512_cmpgt_epi32_mask(
      _mm512_cvtepu8_epi32(_mm_loadu_si128(
          reinterpret_cast<const __m128i*>(pv.valid + i0))),
      zero);
  if (ts.cpu_req) {
    __mmask16 cpu_ok = _mm512_cmpgt_epi32_mask(
        _mm512_cvtepu8_epi32(_mm_loadu_si128(
            reinterpret_cast<const __m128i*>(pv.has_cpu + i0))),
        zero);
    if (ts.cores >= 0) {
      const __m512i cores = _mm512_loadu_si512(pv.cpu_cores + i0);
      cpu_ok &= _mm512_cmpge_epi32_mask(cores,
                                        _mm512_set1_epi32(ts.cores)) &
                _mm512_cmpge_epi32_mask(cores, zero);
    }
    ok &= cpu_ok;
  }
  if (ts.ram >= 0) {
    const __m512i ram = _mm512_loadu_si512(pv.ram_mb + i0);
    ok &= _mm512_cmpge_epi32_mask(ram, _mm512_set1_epi32(ts.ram)) &
          _mm512_cmpge_epi32_mask(ram, zero);
  }
  if (ts.storage >= 0) {
    const __m512i st = _mm512_loadu_si512(pv.storage_gb + i0);
    ok &= _mm512_cmpge_epi32_mask(st, _mm512_set1_epi32(ts.storage)) &
          _mm512_cmpge_epi32_mask(st, zero);
  }
  if (ts.any_opt && ok) {
    const __m512i pc = _mm512_loadu_si512(pv.gpu_count + i0);
    const __m512i pm = _mm512_loadu_si512(pv.gpu_mem_mb + i0);
    const __m512i mid = _mm512_loadu_si512(pv.gpu_model_id + i0);
    const __mmask16 pc_abs = _mm512_cmplt_epi32_mask(pc, zero);
    const __mmask16 pm_abs = _mm512_cmplt_epi32_mask(pm, zero);
    __mmask16 gany_m = 0;
    for (int32_t o = 0; o < K; ++o) {
      const int64_t tk = static_cast<int64_t>(t) * K + o;
      if (!rf->gpu_opt_valid[tk]) continue;
      __mmask16 om = 0xffff;
      const int32_t rc = rf->gpu_count[tk];
      if (rc == 0) {
        om &= pc_abs | _mm512_cmpeq_epi32_mask(pc, zero);
      } else if (rc > 0) {
        om &= _mm512_cmpeq_epi32_mask(pc, _mm512_set1_epi32(rc));
      }
      const int32_t rmem_min = rf->gpu_mem_min[tk];
      if (rmem_min >= 0) {
        om &= _mm512_cmpge_epi32_mask(pm, _mm512_set1_epi32(rmem_min)) &
              ~pm_abs;
      }
      const int32_t rmem_max = rf->gpu_mem_max[tk];
      if (rmem_max >= 0) {
        om &= _mm512_cmple_epi32_mask(pm, _mm512_set1_epi32(rmem_max)) &
              ~pm_abs;
      }
      const int32_t rtot_min = rf->gpu_total_mem_min[tk];
      const int32_t rtot_max = rf->gpu_total_mem_max[tk];
      if (rtot_min >= 0 || rtot_max >= 0) {
        const __m512i total = _mm512_mullo_epi32(pc, pm);
        const __mmask16 no_total = pc_abs | pm_abs;
        if (rtot_min >= 0) {
          om &= no_total | _mm512_cmpge_epi32_mask(
                               total, _mm512_set1_epi32(rtot_min));
        }
        if (rtot_max >= 0) {
          om &= no_total | _mm512_cmple_epi32_mask(
                               total, _mm512_set1_epi32(rtot_max));
        }
      }
      if (rf->gpu_model_constrained[tk]) {
        const __m512i mid0 = _mm512_max_epi32(mid, zero);
        const __m512i word = _mm512_min_epi32(_mm512_srli_epi32(mid0, 5),
                                              _mm512_set1_epi32(W - 1));
        const __m512i bit = _mm512_and_si512(mid0, _mm512_set1_epi32(31));
        const __m512i words = _mm512_i32gather_epi32(
            word, rf->gpu_model_mask + tk * W, 4);
        const __m512i hit = _mm512_and_si512(
            _mm512_srlv_epi32(words, bit), _mm512_set1_epi32(1));
        om &= _mm512_cmpgt_epi32_mask(hit, zero) &
              _mm512_cmpge_epi32_mask(mid, zero);
      }
      gany_m |= om;
    }
    const __mmask16 has_gpu = _mm512_cmpgt_epi32_mask(
        _mm512_cvtepu8_epi32(_mm_loadu_si128(
            reinterpret_cast<const __m128i*>(pv.has_gpu + i0))),
        zero);
    ok &= has_gpu & gany_m;
  }
  __m512 c = _mm512_sub_ps(_mm512_loadu_ps(pv.base + i0),
                           _mm512_set1_ps(ts.prio));
  if (ts.has_loc) {
    const __m512 pclat = _mm512_loadu_ps(pv.clat + i0);
    const __m512 cos_dlat = _mm512_fmadd_ps(
        pclat, _mm512_set1_ps(ts.clat),
        _mm512_mul_ps(_mm512_loadu_ps(pv.slat + i0),
                      _mm512_set1_ps(ts.slat)));
    const __m512 cos_dlon = _mm512_fmadd_ps(
        _mm512_loadu_ps(pv.clon + i0), _mm512_set1_ps(ts.clon),
        _mm512_mul_ps(_mm512_loadu_ps(pv.slon + i0),
                      _mm512_set1_ps(ts.slon)));
    const __m512 one = _mm512_set1_ps(1.0f);
    const __m512 half = _mm512_set1_ps(0.5f);
    __m512 a = _mm512_fmadd_ps(
        _mm512_mul_ps(_mm512_mul_ps(pclat, _mm512_set1_ps(ts.clat)), half),
        _mm512_sub_ps(one, cos_dlon),
        _mm512_mul_ps(half, _mm512_sub_ps(one, cos_dlat)));
    a = _mm512_min_ps(_mm512_max_ps(a, _mm512_setzero_ps()), one);
    // asin(sqrt(a)), cephes split at 0.5
    const __m512 x = _mm512_sqrt_ps(a);
    const __mmask16 big = _mm512_cmp_ps_mask(x, half, _CMP_GT_OQ);
    const __m512 xx = _mm512_mask_blend_ps(
        big, x,
        _mm512_sqrt_ps(_mm512_mul_ps(_mm512_sub_ps(one, x), half)));
    const __m512 z = _mm512_mul_ps(xx, xx);
    __m512 poly = _mm512_set1_ps(4.2163199048e-2f);
    poly = _mm512_fmadd_ps(poly, z, _mm512_set1_ps(2.4181311049e-2f));
    poly = _mm512_fmadd_ps(poly, z, _mm512_set1_ps(4.5470025998e-2f));
    poly = _mm512_fmadd_ps(poly, z, _mm512_set1_ps(7.4953002686e-2f));
    poly = _mm512_fmadd_ps(poly, z, _mm512_set1_ps(1.6666752422e-1f));
    const __m512 asin_small =
        _mm512_fmadd_ps(_mm512_mul_ps(poly, z), xx, xx);
    const __m512 asin_x = _mm512_mask_blend_ps(
        big, asin_small,
        _mm512_fnmadd_ps(_mm512_set1_ps(2.0f), asin_small,
                         _mm512_set1_ps(1.5707963267948966f)));
    const __m512 dist =
        _mm512_mul_ps(_mm512_set1_ps(2.0f * 6371.0f), asin_x);
    const __mmask16 ploc = _mm512_cmpgt_epi32_mask(
        _mm512_cvtepu8_epi32(_mm_loadu_si128(
            reinterpret_cast<const __m128i*>(pv.has_location + i0))),
        zero);
    c = _mm512_mask_add_ps(
        c, ploc, c, _mm512_mul_ps(_mm512_set1_ps(w_proximity), dist));
  }
  _mm512_storeu_ps(out, _mm512_mask_blend_ps(ok, vinf, c));
}

#endif  // ENGINE_HAVE_X86
// ==== END PER-ISA KERNELS (isa-dispatch) ===================================

#if !defined(ENGINE_HAVE_X86)
// non-x86 hosts: clamp_isa already pins scalar, so none of these can be
// reached — stubs keep the dispatch table well-formed.
inline float score_cell_fma(const ProviderFeatures* pf,
                            const RequirementFeatures* rf,
                            const ProviderPrecomp& pre, const TaskScore& ts,
                            int32_t t, int32_t K, int32_t W, int32_t p,
                            float w_proximity) {
  return score_cell(pf, rf, pre, ts, t, K, W, p, w_proximity);
}
inline uint32_t lanes_le_arr_avx2(const float*, const float*) { return 0; }
inline uint32_t lanes_le_arr_avx512(const float*, const float*) { return 0; }
inline uint32_t lanes_le_bcast_avx2(const float*, float) { return 0; }
inline uint32_t lanes_le_bcast_avx512(const float*, float) { return 0; }
inline uint32_t lb_survivors_avx2(float, const float*, const float*,
                                  const uint8_t*, float, int) {
  return 0;
}
inline uint32_t lb_survivors_avx512(float, const float*, const float*,
                                    const uint8_t*, float, int) {
  return 0;
}
inline void score_block_avx2(const ProviderBlockView&,
                             const RequirementFeatures*, const TaskScore&,
                             int32_t, int32_t, int32_t, int32_t, float,
                             float*) {}
inline void score_block_avx512(const ProviderBlockView&,
                               const RequirementFeatures*, const TaskScore&,
                               int32_t, int32_t, int32_t, int32_t, float,
                               float*) {}
#endif

// The dispatch table: every ISA-dependent operation routes through one
// of these rows (indexed by the engine isa code). New native entry
// points must use the table, never intrinsics directly — the
// isa-dispatch lint enforces the boundary textually.
struct IsaOps {
  int32_t width;  // scoring lanes per block
  void (*score_block)(const ProviderBlockView&, const RequirementFeatures*,
                      const TaskScore&, int32_t, int32_t, int32_t, int32_t,
                      float, float*);
  uint32_t (*le_bcast)(const float*, float);
  uint32_t (*lb_survivors)(float, const float*, const float*, const uint8_t*,
                           float, int);
};

const IsaOps kIsaOps[3] = {
    {1, nullptr, nullptr, nullptr},
    {8, score_block_avx2, lanes_le_bcast_avx2, lb_survivors_avx2},
    {16, score_block_avx512, lanes_le_bcast_avx512, lb_survivors_avx512},
};

// per-cell scorer behind the ISA seam: scalar keeps the historical
// pipeline (and its inlining); the vector ISAs score through the
// fmaf twin so single-cell and block scoring agree bit-for-bit
inline float score_cell_isa(int32_t isa, const ProviderFeatures* pf,
                            const RequirementFeatures* rf,
                            const ProviderPrecomp& pre, const TaskScore& ts,
                            int32_t t, int32_t K, int32_t W, int32_t p,
                            float w_proximity) {
  return isa == kIsaScalar
             ? score_cell(pf, rf, pre, ts, t, K, W, p, w_proximity)
             : score_cell_fma(pf, rf, pre, ts, t, K, W, p, w_proximity);
}

// ---- capability-signature buckets (the sub-quadratic cold pruner) ----
//
// Providers are grouped by the two EXACT-SEMANTICS discrete axes of the
// compat mask — GPU model id and GPU count (pc == rc, not >=) — plus
// validity and has_gpu. A task derives, per GPU OR-alternative, the set
// of (model, count) buckets that could possibly satisfy it; providers
// outside the union are PROVABLY infeasible (their model is accepted by
// no option, or their count matches none), so exact-scoring only the
// admissible buckets reproduces the full row scan bit-for-bit. The
// threshold gates (cpu/ram/storage/mem) are left to the exact-scoring
// verification pass — they prune cells, never correctness. Coverage
// fallback: when the admissible union is most of the fleet (no GPU
// options, or permissive ones), the row runs the historical full scan —
// also exact, so candidate sets are ALWAYS equal to the unpruned pass,
// never merely similar.
constexpr int32_t kModelBuckets = 64;   // mid<0 | 0..61 | >=62 pooled
constexpr int32_t kCountBuckets = 11;   // pc<0 | 0..8 | >8 pooled
constexpr int32_t kNumBuckets = 2 + kModelBuckets * kCountBuckets;

inline int32_t provider_bucket(const ProviderFeatures* pf, int32_t p) {
  if (!pf->valid[p]) return 0;   // infeasible for every task
  if (!pf->has_gpu[p]) return 1; // infeasible for any GPU-requiring task
  const int32_t mid = pf->gpu_model_id[p];
  const int32_t mb = mid < 0 ? 0 : 1 + (mid < kModelBuckets - 2
                                            ? mid : kModelBuckets - 2);
  const int32_t pc = pf->gpu_count[p];
  const int32_t cb = pc < 0 ? 0 : (pc <= 8 ? 1 + pc : kCountBuckets - 1);
  return 2 + mb * kCountBuckets + cb;
}

struct BucketIndex {
  std::vector<int32_t> start;  // [kNumBuckets + 1] prefix offsets
  std::vector<int32_t> ids;    // provider ids grouped by bucket,
                               // ascending within each bucket
  BucketIndex(const ProviderFeatures* pf, int32_t P)
      : start(kNumBuckets + 1, 0), ids(P) {
    for (int32_t p = 0; p < P; ++p) ++start[provider_bucket(pf, p) + 1];
    for (int32_t b = 0; b < kNumBuckets; ++b) start[b + 1] += start[b];
    std::vector<int32_t> fill(start.begin(), start.end() - 1);
    for (int32_t p = 0; p < P; ++p) ids[fill[provider_bucket(pf, p)]++] = p;
  }
};

// Bucket-ordered SoA feature copies for the vector pruner path: each
// bucket's providers become one CONTIGUOUS run of every feature column
// (the per-bucket id indirection in the scalar path costs a gather per
// feature per cell — the measured difference between vector parity and
// vector speedup at 16k). Built once per solve when the engine is on a
// vector ISA and the pruner is enabled; the copies hold the exact
// values the pf/pre arrays hold, so scoring through either layout is
// bit-identical. ids aliases bx.ids (position -> original provider).
struct BucketSoA {
  std::vector<uint8_t> valid, has_cpu, has_gpu, has_location;
  std::vector<int32_t> cpu_cores, ram_mb, storage_gb;
  std::vector<int32_t> gpu_count, gpu_mem_mb, gpu_model_id;
  std::vector<float> base, slat, clat, slon, clon;
  const int32_t* ids;
  BucketSoA(const ProviderFeatures* pf, const ProviderPrecomp& pre,
            const BucketIndex& bx, int32_t P)
      : valid(P), has_cpu(P), has_gpu(P), has_location(P), cpu_cores(P),
        ram_mb(P), storage_gb(P), gpu_count(P), gpu_mem_mb(P),
        gpu_model_id(P), base(P), slat(P), clat(P), slon(P), clon(P),
        ids(bx.ids.data()) {
    for (int32_t i = 0; i < P; ++i) {
      const int32_t p = bx.ids[i];
      valid[i] = pf->valid[p];
      has_cpu[i] = pf->has_cpu[p];
      has_gpu[i] = pf->has_gpu[p];
      has_location[i] = pf->has_location[p];
      cpu_cores[i] = pf->cpu_cores[p];
      ram_mb[i] = pf->ram_mb[p];
      storage_gb[i] = pf->storage_gb[p];
      gpu_count[i] = pf->gpu_count[p];
      gpu_mem_mb[i] = pf->gpu_mem_mb[p];
      gpu_model_id[i] = pf->gpu_model_id[p];
      base[i] = pre.base[p];
      slat[i] = pre.slat[p];
      clat[i] = pre.clat[p];
      slon[i] = pre.slon[p];
      clon[i] = pre.clon[p];
    }
  }
  ProviderBlockView view() const {
    return {valid.data(),      has_cpu.data(),   has_gpu.data(),
            has_location.data(), cpu_cores.data(), ram_mb.data(),
            storage_gb.data(), gpu_count.data(), gpu_mem_mb.data(),
            gpu_model_id.data(), base.data(),    slat.data(),
            clat.data(),       slon.data(),      clon.data()};
  }
};

// Fill adm[kNumBuckets] for one task; returns the admissible provider
// count. Clear bits are PROVABLY infeasible buckets; set bits are merely
// possible (the exact-scoring pass decides). Deterministic: a pure
// function of the requirement row.
inline int64_t task_admissible(const RequirementFeatures* rf, int32_t t,
                               int32_t K, int32_t W, const TaskScore& ts,
                               const BucketIndex& bx, uint8_t* adm) {
  std::memset(adm, 0, kNumBuckets);
  if (!ts.valid) return 0;
  if (!ts.any_opt) {
    // GPU-irrelevant task: every live bucket admissible (bucket 0 =
    // invalid providers stays pruned — valid=0 fails the scalar gate
    // for every task)
    std::memset(adm + 1, 1, kNumBuckets - 1);
  } else {
    for (int32_t o = 0; o < K; ++o) {
      const int64_t tk = static_cast<int64_t>(t) * K + o;
      if (!rf->gpu_opt_valid[tk]) continue;
      uint8_t cadm[kCountBuckets];
      const int32_t rc = rf->gpu_count[tk];
      for (int32_t cb = 0; cb < kCountBuckets; ++cb) {
        bool ok;
        if (rc < 0) ok = true;                       // any count
        else if (rc == 0) ok = cb <= 1;              // pc absent or 0
        else if (rc <= 8) ok = cb == 1 + rc;         // exact match
        else ok = cb == kCountBuckets - 1;           // pooled >8 bucket
        cadm[cb] = ok;
      }
      bool madm[kModelBuckets];
      if (!rf->gpu_model_constrained[tk]) {
        for (int32_t mb = 0; mb < kModelBuckets; ++mb) madm[mb] = true;
      } else {
        const uint32_t* mask = rf->gpu_model_mask + tk * W;
        madm[0] = false;  // mid < 0 fails a constrained option
        for (int32_t mb = 1; mb < kModelBuckets - 1; ++mb) {
          const int32_t mid = mb - 1;
          madm[mb] =
              mid < W * 32 && ((mask[mid >> 5] >> (mid & 31)) & 1u);
        }
        bool any_hi = false;  // pooled high bucket: any bit >= 62 set
        for (int32_t bit = kModelBuckets - 2; bit < W * 32 && !any_hi;
             ++bit) {
          any_hi = (mask[bit >> 5] >> (bit & 31)) & 1u;
        }
        madm[kModelBuckets - 1] = any_hi;
      }
      for (int32_t mb = 0; mb < kModelBuckets; ++mb) {
        if (!madm[mb]) continue;
        uint8_t* row = adm + 2 + mb * kCountBuckets;
        for (int32_t cb = 0; cb < kCountBuckets; ++cb) row[cb] |= cadm[cb];
      }
    }
  }
  int64_t n = 0;
  for (int32_t b = 0; b < kNumBuckets; ++b) {
    if (adm[b]) n += bx.start[b + 1] - bx.start[b];
  }
  return n;
}

// The transposed admissibility question — can a provider in model/count
// bucket (mb, cb) possibly satisfy task t? — for provider-major column
// sweeps (the repair kernel's dirty columns). Must answer true whenever
// task_admissible would set the bucket's bit: a false negative here
// would silently skip a feasible cell.
inline bool bucket_admits_task(const RequirementFeatures* rf, int32_t t,
                               int32_t K, int32_t W, const TaskScore& ts,
                               bool has_gpu, int32_t mb, int32_t cb) {
  if (!ts.valid) return false;
  if (!ts.any_opt) return true;
  if (!has_gpu) return false;
  for (int32_t o = 0; o < K; ++o) {
    const int64_t tk = static_cast<int64_t>(t) * K + o;
    if (!rf->gpu_opt_valid[tk]) continue;
    const int32_t rc = rf->gpu_count[tk];
    bool cok;
    if (rc < 0) cok = true;
    else if (rc == 0) cok = cb <= 1;
    else if (rc <= 8) cok = cb == 1 + rc;
    else cok = cb == kCountBuckets - 1;
    if (!cok) continue;
    if (!rf->gpu_model_constrained[tk]) return true;
    if (mb == 0) continue;
    const uint32_t* mask = rf->gpu_model_mask + tk * W;
    if (mb < kModelBuckets - 1) {
      const int32_t mid = mb - 1;
      if (mid < W * 32 && ((mask[mid >> 5] >> (mid & 31)) & 1u)) {
        return true;
      }
    } else {
      for (int32_t bit = kModelBuckets - 2; bit < W * 32; ++bit) {
        if ((mask[bit >> 5] >> (bit & 31)) & 1u) return true;
      }
    }
  }
  return false;
}

// The fused per-task pass over [t_begin, t_end): feature->cost into an
// L2-resident scratch row, vectorized top-k select, optional reverse
// (provider->task) tracking into caller-provided buffers. Tasks are
// independent, so chunking the range across threads reproduces the
// single-range outputs bit-for-bit; the reverse buffers hold each
// provider's best-r keys over the CHUNK — a set selection that a later
// merge combines into the global best-r (also order-independent).
// With ``bx`` non-null, rows whose admissible-bucket union is below
// ``coverage_frac`` of the fleet score only that union (bit-identical
// by the pruner's provable-infeasibility contract); other rows fall
// back to the full scan. ``probes`` (nullable, 3 slots per thread):
// [0] admissible providers visited, [1] full-scan fallback rows,
// [2] bucket-pruned rows.
void fused_process_tasks(const ProviderFeatures* pf,
                         const RequirementFeatures* rf, int32_t P,
                         int32_t t_begin, int32_t t_end, int32_t K, int32_t W,
                         int32_t k, int32_t k_out, float w_proximity,
                         float w_priority, const ProviderPrecomp& pre,
                         int32_t reverse_r, uint64_t* rev, float* rev_worst,
                         int32_t* out_cand_provider, float* out_cand_cost,
                         const BucketIndex* bx = nullptr,
                         float coverage_frac = 0.6f,
                         int64_t* probes = nullptr, int32_t slack_cap = 0,
                         int32_t* slack_p = nullptr,
                         float* slack_c = nullptr,
                         int32_t isa = kIsaScalar,
                         const BucketSoA* soa = nullptr) {
  const bool do_rev = rev != nullptr && reverse_r > 0;
  const IsaOps& ops = kIsaOps[isa];
  const ProviderBlockView fv = full_view(pf, pre);
  const ProviderBlockView sv =
      soa != nullptr ? soa->view() : ProviderBlockView{};
  float segbuf[16];  // one vector block of bucket-segment scores
  // selection width: top-(k + slack) keys are tracked so the emitted
  // slack tail (the repair kernel's deletion absorber) rides the same
  // pass; the first k of a top-(k+s) selection IS the top-k, so the
  // emitted candidate rows are bit-identical at every slack setting
  const int32_t k_sel =
      slack_p != nullptr ? std::min(k + slack_cap, P) : k;
  std::vector<float> scratch(P);
  std::vector<uint64_t> topbuf(k_sel);  // sorted packed (cost, provider)
  std::vector<uint8_t> adm(bx != nullptr ? kNumBuckets : 0);
  const uint64_t pad_key = pack_key(kInfeasible, 0xffffffffu);
  const auto emit_slack = [&](int32_t t, const uint64_t* buf) {
    if (slack_p == nullptr) return;
    const int64_t sbase = static_cast<int64_t>(t) * slack_cap;
    for (int32_t j = 0; j < slack_cap; ++j) {
      if (k + j < k_sel) {
        const float c = unpack_key_cost(buf[k + j]);
        const bool feas = c < kInfeasible * 0.5f;
        slack_p[sbase + j] =
            feas ? static_cast<int32_t>(buf[k + j] & 0xffffffffu) : -1;
        slack_c[sbase + j] = feas ? c : kInfeasible;
      } else {
        slack_p[sbase + j] = -1;
        slack_c[sbase + j] = kInfeasible;
      }
    }
  };

  for (int32_t t = t_begin; t < t_end; ++t) {
    // ONE construction of the per-task scalars (shared with the repair
    // kernel and the per-ISA kernels — every scoring path reads the
    // same hoists, so an edit here cannot split their bit-identity)
    const TaskScore ts = make_task_score(rf, t, K, w_priority);
    if (bx != nullptr) {
      const int64_t n_adm =
          task_admissible(rf, t, K, W, ts, *bx, adm.data());
      if (n_adm < static_cast<int64_t>(coverage_frac * P)) {
        // bucket-pruned row: exact-score only the admissible union.
        // Same keys, same jitter, same insert rule as the full scan —
        // pruned-out providers are provably infeasible, so the top-k
        // SET (and every reverse fold) is bit-identical.
        if (probes != nullptr) {
          probes[0] += n_adm;
          ++probes[2];
        }
        uint64_t* buf = topbuf.data();
        for (int32_t j = 0; j < k_sel; ++j) buf[j] = pad_key;
        // fold one scored cell, in the segment's ascending-id order —
        // the SAME insert sequence whichever layout scored it
        const auto fold = [&](int32_t p, float c) {
          if (c >= kInfeasible * 0.5f) return;
          const float cj = c + jitter(p, t);
          if (do_rev && c < rev_worst[p]) {
            uint64_t* rb = rev + static_cast<size_t>(p) * reverse_r;
            const uint64_t rkey = pack_key(cj, static_cast<uint32_t>(t));
            if (rkey < rb[reverse_r - 1]) {
              sorted_insert(rb, reverse_r, rkey);
              rev_worst[p] = unpack_key_cost(rb[reverse_r - 1]);
            }
          }
          const uint64_t key = pack_key(cj, p);
          if (key < buf[k_sel - 1]) sorted_insert(buf, k_sel, key);
        };
        if (isa != kIsaScalar && soa != nullptr) {
          // vector segments over the bucket-ordered SoA; sub-block
          // tails score the same cells through the fmaf twin (equal
          // bits by the per-ISA contract)
          for (int32_t b = 1; b < kNumBuckets; ++b) {
            if (!adm[b]) continue;
            const int32_t s1 = bx->start[b + 1];
            int32_t i = bx->start[b];
            for (; i + ops.width <= s1; i += ops.width) {
              ops.score_block(sv, rf, ts, t, K, W, i, w_proximity, segbuf);
              for (int32_t j = 0; j < ops.width; ++j) {
                fold(soa->ids[i + j], segbuf[j]);
              }
            }
            for (; i < s1; ++i) {
              const int32_t p = bx->ids[i];
              fold(p, score_cell_fma(pf, rf, pre, ts, t, K, W, p,
                                     w_proximity));
            }
          }
        } else {
          for (int32_t b = 1; b < kNumBuckets; ++b) {
            if (!adm[b]) continue;
            for (int32_t i = bx->start[b]; i < bx->start[b + 1]; ++i) {
              const int32_t p = bx->ids[i];
              fold(p, score_cell_isa(isa, pf, rf, pre, ts, t, K, W, p,
                                     w_proximity));
            }
          }
        }
        const int64_t out_base = static_cast<int64_t>(t) * k_out;
        for (int32_t j = 0; j < k; ++j) {
          const float c = unpack_key_cost(buf[j]);
          const bool feas = c < kInfeasible * 0.5f;
          out_cand_provider[out_base + j] =
              feas ? static_cast<int32_t>(buf[j] & 0xffffffffu) : -1;
          out_cand_cost[out_base + j] = c;
        }
        for (int32_t j = k; j < k_out; ++j) {
          out_cand_provider[out_base + j] = -1;
          out_cand_cost[out_base + j] = kInfeasible;
        }
        emit_slack(t, buf);
        continue;
      }
      if (probes != nullptr) {
        probes[0] += P;
        ++probes[1];
      }
    }
    int32_t p0 = 0;
    // Full scan through the dispatch table: one vector block kernel per
    // lane-width stride, fmaf-twin tail. At isa == scalar the loop
    // below runs score_cell over every cell — the historical pipeline,
    // bit-for-bit. The persistent-structure family no longer forces
    // scalar: within an ISA there is exactly ONE float pipeline, so the
    // repair kernel's bit-identical-to-rebuild promise holds at every
    // ISA (the tag is the provenance).
    if (isa != kIsaScalar) {
      for (; p0 + ops.width <= P; p0 += ops.width) {
        ops.score_block(fv, rf, ts, t, K, W, p0, w_proximity,
                        scratch.data() + p0);
      }
      for (; p0 < P; ++p0) {
        scratch[p0] =
            score_cell_fma(pf, rf, pre, ts, t, K, W, p0, w_proximity);
      }
    }
    if (p0 < P) {
      for (int32_t p = p0; p < P; ++p) {
        scratch[p] = score_cell(pf, rf, pre, ts, t, K, W, p, w_proximity);
      }
    }
    if (do_rev) {
      // reverse tracking: fold task t into each provider's best-r. Hot
      // path is one compare against the cached root; inserts are rare
      // once the buffers warm up.
      for (int32_t p = 0; p < P; ++p) {
        const float c = scratch[p];
        if (c >= rev_worst[p] || c >= kInfeasible * 0.5f) continue;
        const float cj = c + jitter(p, t);
        uint64_t* rb = rev + static_cast<size_t>(p) * reverse_r;
        const uint64_t key = pack_key(cj, static_cast<uint32_t>(t));
        if (key < rb[reverse_r - 1]) {
          sorted_insert(rb, reverse_r, key);
          rev_worst[p] = unpack_key_cost(rb[reverse_r - 1]);
        }
      }
    }
    // top-k_sel select: vectorized reject + sorted insertion (same
    // output contract as topk_candidates on a dense row; the emitted
    // first k is the top-k whatever the slack width)
    uint64_t* buf = topbuf.data();
    for (int32_t p = 0; p < k_sel; ++p) {
      const float c = scratch[p];
      const float cj = (c < kInfeasible * 0.5f) ? c + jitter(p, t) : c;
      buf[p] = pack_key(cj, p);
    }
    std::sort(buf, buf + k_sel);
    float root = unpack_key_cost(buf[k_sel - 1]);
    int32_t p = k_sel;
    if (isa != kIsaScalar) {
      // wide-lane reject via the dispatch table (comparison-only, so
      // this changes which cells take the slow path, never their bits)
      for (; p + ops.width <= P; p += ops.width) {
        uint32_t m = ops.le_bcast(scratch.data() + p, root);
        while (m) {
          const int32_t pp = p + __builtin_ctz(m);
          m &= m - 1;
          const float c = scratch[pp];
          const float cj = (c < kInfeasible * 0.5f) ? c + jitter(pp, t) : c;
          const uint64_t key = pack_key(cj, pp);
          if (key >= buf[k_sel - 1]) continue;
          sorted_insert(buf, k_sel, key);
          root = unpack_key_cost(buf[k_sel - 1]);
        }
      }
    }
    for (; p < P; ++p) {
      const float c = scratch[p];
      if (c > root) continue;
      const float cj = (c < kInfeasible * 0.5f) ? c + jitter(p, t) : c;
      const uint64_t key = pack_key(cj, p);
      if (key >= buf[k_sel - 1]) continue;
      sorted_insert(buf, k_sel, key);
      root = unpack_key_cost(buf[k_sel - 1]);
    }
    const int64_t out_base = static_cast<int64_t>(t) * k_out;
    for (int32_t j = 0; j < k; ++j) {
      const float c = unpack_key_cost(buf[j]);
      const bool feas = c < kInfeasible * 0.5f;
      out_cand_provider[out_base + j] =
          feas ? static_cast<int32_t>(buf[j] & 0xffffffffu) : -1;
      out_cand_cost[out_base + j] = c;
    }
    for (int32_t j = k; j < k_out; ++j) {
      out_cand_provider[out_base + j] = -1;
      out_cand_cost[out_base + j] = kInfeasible;
    }
    emit_slack(t, buf);
  }
}

// Scatter EVERY provider's reverse edges into the extra columns (same
// guarantee as the JAX bidirectional merge: r routes into the graph per
// provider — repairing only fully-uncovered providers leaves single-list
// providers stranded, measured 91.8% vs ~100% at 32k). Sort by
// (task, cost) so each task keeps its cheapest ``extra``; edges
// duplicating a forward candidate are dropped (a dup makes v1 == v2 in
// the bid math — measured slower AND worse).
void scatter_reverse_edges(int32_t P, int32_t T, int32_t k, int32_t k_out,
                           int32_t reverse_r, int32_t extra,
                           const uint64_t* rev, int32_t* out_cand_provider,
                           float* out_cand_cost) {
  struct Edge {
    int32_t t;
    float c;
    int32_t p;
  };
  std::vector<Edge> edges;
  edges.reserve(static_cast<size_t>(P) * reverse_r);
  for (int32_t p = 0; p < P; ++p) {
    const uint64_t* rb = rev + static_cast<size_t>(p) * reverse_r;
    for (int32_t j = 0; j < reverse_r; ++j) {
      const float c = unpack_key_cost(rb[j]);
      if (c >= kInfeasible * 0.5f) break;  // sorted: rest infeasible
      edges.push_back({static_cast<int32_t>(rb[j] & 0xffffffffu), c, p});
    }
  }
  // fully-ordered comparator (provider id breaks exact-cost ties): the
  // warm repair rebuilds SUBSETS of rows from the same edge universe, so
  // the fill order must be a pure function of edge VALUES, never of
  // std::sort's unstable tie handling
  std::sort(edges.begin(), edges.end(), [](const Edge& a, const Edge& b) {
    if (a.t != b.t) return a.t < b.t;
    if (a.c != b.c) return a.c < b.c;
    return a.p < b.p;
  });
  std::vector<int32_t> fill(T, 0);
  for (const Edge& e : edges) {
    if (fill[e.t] >= extra) continue;
    const int64_t row = static_cast<int64_t>(e.t) * k_out;
    bool dup = false;
    for (int32_t j = 0; j < k && !dup; ++j) {
      dup = out_cand_provider[row + j] == e.p;
    }
    if (dup) continue;
    const int32_t at = fill[e.t]++;
    out_cand_provider[row + k + at] = e.p;
    out_cand_cost[row + k + at] = e.c;
  }
}

// Shared driver: single-range when threads == 1 (bit-compatible with the
// historical single-threaded pass), contiguous task chunks + reverse-edge
// merge when threads > 1. The merged reverse structure equals the
// single-range one exactly: each chunk keeps its r smallest (cost, task)
// keys per provider, and the union's r smallest is the global best-r no
// matter how tasks were chunked.
void fused_topk_impl(const ProviderFeatures* pf, const RequirementFeatures* rf,
                     int32_t P, int32_t T, int32_t K, int32_t W, int32_t k,
                     float w_price, float w_load, float w_proximity,
                     float w_priority, int32_t* out_cand_provider,
                     float* out_cand_cost, int32_t reverse_r, int32_t extra,
                     int32_t threads, int64_t* stats_out = nullptr,
                     int32_t use_buckets = 0, float coverage_frac = 0.6f,
                     uint64_t* rev_out = nullptr, int32_t slack_cap = 0,
                     int32_t* slack_p_out = nullptr,
                     float* slack_c_out = nullptr) {
  // Bidirectional candidates (the degraded-mode twin of the JAX path's
  // ops/sparse.candidates_topk_bidir): on price-dominated fleets every
  // task's forward top-k holds the same cheap providers, capping the
  // matching at the covered fraction (measured 79% at 32k). With
  // reverse_r/extra > 0 the pass ALSO tracks EVERY provider's best-r
  // tasks (one compare per cell against a cached worst key) and scatters
  // them into ``extra`` appended candidate columns (cheapest-first per
  // task, forward dups dropped). Output stride becomes k + extra.
  if (k > P) k = P;
  if (k <= 0 || T <= 0) return;  // empty marketplace: nothing to emit
  if (reverse_r < 0) reverse_r = 0;
  if (extra < 0) extra = 0;
  const bool do_rev = reverse_r > 0 && extra > 0;
  const int32_t k_out = k + extra;
  const int nt = resolve_threads(threads, T);
  const ProviderPrecomp pre(pf, P, w_price, w_load);
  const uint64_t pad_key = pack_key(kInfeasible, 0xffffffffu);
  const bool st = stats_out != nullptr;
  if (st) {
    std::memset(stats_out, 0, kEngineStatsSlots * 8);
    stats_out[3] = nt;
  }
  // one float pipeline per ISA (snapshotted once per solve): scalar is
  // the historical score_cell pipeline, the vector ISAs the fmaf one —
  // see the per-ISA contract in fused_process_tasks
  const int32_t isa = g_isa.load(std::memory_order_relaxed);
  int64_t t0 = st ? now_ns() : 0;
  std::unique_ptr<BucketIndex> bx;
  std::unique_ptr<BucketSoA> soa;
  if (use_buckets) {
    bx.reset(new BucketIndex(pf, P));
    if (isa != kIsaScalar) soa.reset(new BucketSoA(pf, pre, *bx, P));
    if (st) {
      stats_out[7] = now_ns() - t0;
      t0 = now_ns();
    }
  }
  // per-thread pruner counters ([0] providers visited, [1] fallback
  // rows, [2] pruned rows), summed by the caller after the join — the
  // stats array itself stays calling-thread-only
  std::vector<int64_t> probes_all(st && use_buckets ? nt * 3 : 0, 0);
  const auto fold_probes = [&]() {
    if (probes_all.empty()) return;
    for (int i = 0; i < nt; ++i) {
      stats_out[4] += probes_all[static_cast<size_t>(i) * 3];
      stats_out[6] += probes_all[static_cast<size_t>(i) * 3 + 1];
      stats_out[5] += probes_all[static_cast<size_t>(i) * 3 + 2];
    }
  };

  if (nt <= 1) {
    std::vector<uint64_t> rev;
    std::vector<float> rev_worst;
    if (do_rev) {
      rev.assign(static_cast<size_t>(P) * reverse_r, pad_key);
      rev_worst.assign(P, kInfeasible);
    }
    fused_process_tasks(pf, rf, P, 0, T, K, W, k, k_out, w_proximity,
                        w_priority, pre, do_rev ? reverse_r : 0,
                        do_rev ? rev.data() : nullptr,
                        do_rev ? rev_worst.data() : nullptr,
                        out_cand_provider, out_cand_cost, bx.get(),
                        coverage_frac,
                        probes_all.empty() ? nullptr : probes_all.data(),
                        slack_cap, slack_p_out, slack_c_out, isa,
                        soa.get());
    if (st) {
      stats_out[0] = now_ns() - t0;
      t0 = now_ns();
      fold_probes();
    }
    if (do_rev) {
      if (rev_out != nullptr) {
        std::memcpy(rev_out, rev.data(),
                    static_cast<size_t>(P) * reverse_r * 8);
      }
      scatter_reverse_edges(P, T, k, k_out, reverse_r, extra, rev.data(),
                            out_cand_provider, out_cand_cost);
      if (st) stats_out[2] = now_ns() - t0;
    } else if (rev_out != nullptr) {
      for (size_t i = 0; i < static_cast<size_t>(P) * reverse_r; ++i) {
        rev_out[i] = pad_key;
      }
    }
    return;
  }

  // per-thread reverse buffers; forward outputs are disjoint by task row
  std::vector<uint64_t> rev_all;
  std::vector<float> rev_worst_all;
  if (do_rev) {
    rev_all.assign(static_cast<size_t>(nt) * P * reverse_r, pad_key);
    rev_worst_all.assign(static_cast<size_t>(nt) * P, kInfeasible);
  }
  const int32_t chunk = (T + nt - 1) / nt;
  run_threads(nt, [&](int tid) {
    const int32_t t0 = std::min<int32_t>(tid * chunk, T);
    const int32_t t1 = std::min<int32_t>(t0 + chunk, T);
    if (t0 >= t1) return;
    uint64_t* rev = do_rev
        ? rev_all.data() + static_cast<size_t>(tid) * P * reverse_r
        : nullptr;
    float* worst = do_rev
        ? rev_worst_all.data() + static_cast<size_t>(tid) * P
        : nullptr;
    fused_process_tasks(pf, rf, P, t0, t1, K, W, k, k_out, w_proximity,
                        w_priority, pre, do_rev ? reverse_r : 0, rev, worst,
                        out_cand_provider, out_cand_cost, bx.get(),
                        coverage_frac,
                        probes_all.empty()
                            ? nullptr
                            : probes_all.data() +
                                  static_cast<size_t>(tid) * 3,
                        slack_cap, slack_p_out, slack_c_out, isa,
                        soa.get());
  });
  if (st) {
    stats_out[0] = now_ns() - t0;
    t0 = now_ns();
    fold_probes();
  }
  if (!do_rev && rev_out != nullptr) {
    for (size_t i = 0; i < static_cast<size_t>(P) * reverse_r; ++i) {
      rev_out[i] = pad_key;
    }
  }
  if (do_rev) {
    // deterministic reduction: per provider, the r smallest keys of the
    // union of all chunks' best-r sets == the global best-r set
    std::vector<uint64_t> merged(static_cast<size_t>(P) * reverse_r);
    std::vector<uint64_t> tmp(static_cast<size_t>(nt) * reverse_r);
    for (int32_t p = 0; p < P; ++p) {
      for (int tid = 0; tid < nt; ++tid) {
        std::memcpy(
            tmp.data() + static_cast<size_t>(tid) * reverse_r,
            rev_all.data() +
                (static_cast<size_t>(tid) * P + p) * reverse_r,
            static_cast<size_t>(reverse_r) * 8);
      }
      std::sort(tmp.begin(), tmp.end());
      std::memcpy(merged.data() + static_cast<size_t>(p) * reverse_r,
                  tmp.data(), static_cast<size_t>(reverse_r) * 8);
    }
    if (st) {
      stats_out[1] = now_ns() - t0;
      t0 = now_ns();
    }
    if (rev_out != nullptr) {
      std::memcpy(rev_out, merged.data(),
                  static_cast<size_t>(P) * reverse_r * 8);
    }
    scatter_reverse_edges(P, T, k, k_out, reverse_r, extra, merged.data(),
                          out_cand_provider, out_cand_cost);
    if (st) stats_out[2] = now_ns() - t0;
  }
}

}  // namespace

void fused_topk_candidates(const ProviderFeatures* pf,
                           const RequirementFeatures* rf, int32_t P, int32_t T,
                           int32_t K, int32_t W, int32_t k, float w_price,
                           float w_load, float w_proximity, float w_priority,
                           int32_t* out_cand_provider, float* out_cand_cost,
                           int32_t reverse_r, int32_t extra) {
  fused_topk_impl(pf, rf, P, T, K, W, k, w_price, w_load, w_proximity,
                  w_priority, out_cand_provider, out_cand_cost, reverse_r,
                  extra, /*threads=*/1);
}

// Multi-threaded fused pass (engine=native-mt): contiguous task chunks in
// parallel + a deterministic reverse-edge merge. threads <= 0 means "all
// hardware threads". Output is bit-identical for every thread count.
// stats_out (nullable, kEngineStatsSlots i64): [0] fused-pass ns,
// [1] reverse-merge ns, [2] scatter ns, [3] threads used,
// [4] providers visited (pruner on), [5] bucket-pruned rows,
// [6] coverage-fallback rows, [7] bucket-index build ns.
void fused_topk_candidates_mt(const ProviderFeatures* pf,
                              const RequirementFeatures* rf, int32_t P,
                              int32_t T, int32_t K, int32_t W, int32_t k,
                              float w_price, float w_load, float w_proximity,
                              float w_priority, int32_t* out_cand_provider,
                              float* out_cand_cost, int32_t reverse_r,
                              int32_t extra, int32_t threads,
                              int64_t* stats_out) {
  fused_topk_impl(pf, rf, P, T, K, W, k, w_price, w_load, w_proximity,
                  w_priority, out_cand_provider, out_cand_cost, reverse_r,
                  extra, threads, stats_out);
}

// The v2 fused entry (the persistent-candidate seam): adds the
// capability-bucket pruner (``use_buckets`` — sub-quadratic cold
// generation whose output is bit-identical to the full scan, coverage
// fallback per row) and ``rev_out`` (nullable [P * reverse_r] u64) —
// the per-provider reverse-edge keys the pass computed, exported so the
// warm arena can persist them and repair incrementally instead of
// regenerating cold. Same determinism contract as _mt.
void fused_topk_candidates_v2(const ProviderFeatures* pf,
                              const RequirementFeatures* rf, int32_t P,
                              int32_t T, int32_t K, int32_t W, int32_t k,
                              float w_price, float w_load, float w_proximity,
                              float w_priority, int32_t* out_cand_provider,
                              float* out_cand_cost, int32_t reverse_r,
                              int32_t extra, int32_t threads,
                              int32_t use_buckets, float coverage_frac,
                              uint64_t* rev_out, int32_t slack_cap,
                              int32_t* slack_p_out, float* slack_c_out,
                              int64_t* stats_out) {
  fused_topk_impl(pf, rf, P, T, K, W, k, w_price, w_load, w_proximity,
                  w_priority, out_cand_provider, out_cand_cost, reverse_r,
                  extra, threads, stats_out, use_buckets, coverage_frac,
                  rev_out, slack_cap, slack_p_out, slack_c_out);
}

namespace {

struct Ent {  // forward entrant: dirty provider key into a clean row
  int32_t t;
  uint64_t key;  // pack_key(jittered cost, provider)
};

struct RevEdge {  // candidate reverse edge from a dirty-task row scan
  int32_t q;     // clean provider whose reverse list it may enter
  uint64_t key;  // pack_key(jittered cost, task)
};

}  // namespace

// ---------------------------------------------------------------------------
// Incremental candidate repair (the persistent-structure warm path).
//
// Given the CURRENT feature columns plus the candidate structure built on
// the previous tick's columns — which differ ONLY at the listed dirty
// provider/task rows — rewrite cand/rev IN PLACE to be bit-identical to a
// from-scratch fused_topk_candidates_v2 build on the current columns,
// touching O(dirty_P * T + dirty_T * admissible + touched_rows * K) cells
// instead of the full O(P * T) matrix. The exactness argument, per row:
//
//   * forward top-k: stripping the dirty entries from a row and folding
//     in every dirty provider whose NEW key is <= the row's old k-th key
//     (theta) yields a pool whose first k IS the exact top-k whenever the
//     pool still holds >= k keys (every excluded clean provider's key
//     exceeds theta, hence the pool's k-th). A row whose pool shrinks
//     below k (a top-k member churned out without replacement) is
//     re-scored through the bucket pruner — counted, never guessed.
//   * reverse lists: a dirty provider's list is rebuilt from its fresh
//     column (computed anyway); a clean provider's list only changes via
//     dirty-TASK edges — stripped then re-folded from the dirty rows'
//     scans, with the same pool argument per list (was-full lists that
//     lose more entries than re-enter below their old worst key are
//     rebuilt from one O(T) column scan).
//   * extras: re-scattered only for rows whose incoming reverse edges or
//     forward list changed — per-task fill is a pure function of exactly
//     those inputs, so untouched rows are bit-identical by construction.
//
// Every phase is either row/provider-parallel over disjoint outputs or a
// collect-then-sort reduction over value-ordered keys, so the result is
// bit-identical for every thread count — the fused pass's contract.
//
// touched_out [T] u8: rows whose candidate CONTENT moved (either
//   direction) — the warm auction's repair_mask / seat-guard input.
// changed_out [T] u8: rows whose membership changed (in EITHER
//   direction — the historical _merge_delta's dirty-membership compare
//   also fired on departures) or where a kept candidate got cheaper by
//   > cheaper_tol — the retirement-clearing contract. Pure cost
//   increases with unchanged membership cannot un-retire; a membership
//   loss clears the flag and the re-bid simply re-retires (harmless,
//   and plan-compatible with the pre-repair behavior the golden traces
//   were recorded under).
// stats_out (nullable, kEngineStatsSlots i64):
//   [0] merged rows   [1] rescanned rows   [2] dirty provider columns
//   [3] reverse-list column rescans        [4] providers visited
//   [5] exact-scored cells                 [6] coverage-fallback rows
//   [7] column-pass ns [8] merge ns [9] reverse ns [10] scatter ns
//   [11] compare ns    [12] threads used   [13] forward entrants
//   [14] changed rows  [15] touched rows
// Returns 0, or -1 on malformed shape arguments.
int32_t repair_topk_candidates_mt(
    const ProviderFeatures* pf, const RequirementFeatures* rf, int32_t P,
    int32_t T, int32_t K, int32_t W, int32_t k, float w_price, float w_load,
    float w_proximity, float w_priority, int32_t* cand_p_io, float* cand_c_io,
    uint64_t* rev_io, int32_t* slack_p_io, float* slack_c_io,
    int32_t slack_cap, const int32_t* dirty_p, int32_t n_dp,
    const int32_t* dirty_t, int32_t n_dt, int32_t reverse_r, int32_t extra,
    int32_t threads, float cheaper_tol, float coverage_frac,
    uint8_t* touched_out, uint8_t* changed_out, int64_t* stats_out) {
  if (P <= 0 || T <= 0 || k <= 0 || k > P || reverse_r <= 0 || extra < 0) {
    return -1;
  }
  if (slack_p_io == nullptr || slack_c_io == nullptr) slack_cap = 0;
  const int32_t k_out = k + extra;
  // repair-time selection width for rescans: the rebuilt row refills its
  // slack tail so the deletion absorber re-arms
  const int32_t k_sel = std::min(k + slack_cap, P);
  const bool st = stats_out != nullptr;
  if (st) std::memset(stats_out, 0, kEngineStatsSlots * 8);
  int64_t t_phase = st ? now_ns() : 0;
  const uint64_t pad_key = pack_key(kInfeasible, 0xffffffffu);
  // small instances run every phase inline: a repair at 512 rows is
  // microseconds of work, and spawning a helper pool would cost more
  // than the whole job (the engine's usual wakeup-amortization rule);
  // the result is identical either way — every phase is thread-count
  // invariant by construction
  constexpr int32_t kRepairParMin = 4096;
  const int nt = std::max(P, T) >= kRepairParMin
                     ? resolve_threads(threads, T)
                     : 1;
  if (st) stats_out[12] = nt;
  // ONE helper pool for every phase: the kernel is a pipeline of seven
  // short parallel regions, and per-region thread spawns (~100 us x
  // nt-1) would dominate the repair wall at high thread counts — the
  // exact wakeup-amortization argument of the -mt auction's pool
  std::unique_ptr<HelperPool> pool(
      nt > 1 ? new HelperPool(nt - 1) : nullptr);
  const auto par = [&](const std::function<void(int)>& fn) {
    if (pool != nullptr) {
      pool->run(fn);
    } else {
      fn(0);
    }
  };
  const ProviderPrecomp pre(pf, P, w_price, w_load);
  const BucketIndex bx(pf, P);
  // one float pipeline per ISA, snapshotted once — every phase of this
  // repair and the from-scratch rebuild it must match score through the
  // same per-cell function (the per-ISA determinism contract)
  const int32_t isa = g_isa.load(std::memory_order_relaxed);
  const IsaOps& ops = kIsaOps[isa];

  std::vector<uint8_t> in_dp(P, 0), in_dt(T, 0);
  for (int32_t i = 0; i < n_dp; ++i) {
    if (dirty_p[i] >= 0 && dirty_p[i] < P) in_dp[dirty_p[i]] = 1;
  }
  for (int32_t i = 0; i < n_dt; ++i) {
    if (dirty_t[i] >= 0 && dirty_t[i] < T) in_dt[dirty_t[i]] = 1;
  }
  std::memset(touched_out, 0, T);
  std::memset(changed_out, 0, T);

  // hoisted per-task scalars + each row's entrant bound tau (the key of
  // the LAST entry of forward+slack — every provider outside the
  // maintained list is provably beyond it, the pool argument's anchor;
  // an empty list means every clean provider is infeasible, so tau
  // opens to the pad key and every feasible dirty key enters) + each
  // reverse list's pre-repair worst key — every later phase reads
  // these as an immutable snapshot
  std::vector<TaskScore> ts_all(T);
  std::vector<uint64_t> theta(T);
  // forward-not-full rows carry a PROOF, not just a bound: a top-k with
  // an empty tail means fewer than k providers were feasible at the
  // last rebuild, so every clean provider outside the list is
  // infeasible — ANY newly-feasible dirty key must enter (the tau
  // filter only orders known-feasible competition)
  std::vector<uint8_t> not_full(T);
  // float-domain SoA shadows of the per-task bounds, for the vectorized
  // block-skip: prio feeds the lower bound lb = base[p] - prio[t], and
  // theta_cost is the cost component of theta (key-domain comparison is
  // relaxed to cost-domain — a conservative superset, see lb_survivors)
  std::vector<float> prio_all, theta_cost;
  if (isa != kIsaScalar) {
    prio_all.resize(T);
    theta_cost.resize(T);
  }
  const int32_t tchunk = (T + nt - 1) / nt;
  par([&](int tid) {
    const int32_t lo = std::min<int32_t>(tid * tchunk, T);
    const int32_t hi = std::min<int32_t>(lo + tchunk, T);
    for (int32_t t = lo; t < hi; ++t) {
      ts_all[t] = make_task_score(rf, t, K, w_priority);
      not_full[t] =
          cand_p_io[static_cast<int64_t>(t) * k_out + k - 1] < 0;
      uint64_t tau = pad_key;
      bool found = false;
      for (int32_t j = slack_cap - 1; j >= 0 && !found; --j) {
        const int64_t s = static_cast<int64_t>(t) * slack_cap + j;
        if (slack_p_io[s] >= 0) {
          tau = pack_key(slack_c_io[s], slack_p_io[s]);
          found = true;
        }
      }
      for (int32_t j = k - 1; j >= 0 && !found; --j) {
        const int64_t s = static_cast<int64_t>(t) * k_out + j;
        if (cand_p_io[s] >= 0) {
          tau = pack_key(cand_c_io[s], cand_p_io[s]);
          found = true;
        }
      }
      theta[t] = tau;
      if (isa != kIsaScalar) {
        prio_all[t] = ts_all[t].prio;
        theta_cost[t] = unpack_key_cost(tau);
      }
    }
  });
  std::vector<uint64_t> wkey(P);  // reverse worst-key snapshot
  for (int32_t p = 0; p < P; ++p) {
    wkey[p] = rev_io[static_cast<size_t>(p) * reverse_r + reverse_r - 1];
  }

  // ---- phase 1: dirty-provider columns. One gated sweep per dirty
  // provider: rebuilds its reverse list exactly (the column IS its edge
  // universe) and emits forward entrants (new key <= theta) per row.
  std::vector<std::vector<Ent>> ents(nt);
  std::vector<std::vector<int32_t>> aff(nt);  // affected task ids
  std::vector<int64_t> cells(nt, 0);

  // ONE gated column sweep shared by this phase and the phase-3
  // reverse-list rebuild: bucket-pruned exact scoring of provider p's
  // column into a reverse_r key buffer (cells the transposed bucket
  // predicate proves infeasible are skipped, never scored — the same
  // exactness contract as the task-side pruning), optionally
  // collecting forward entrants. One implementation, so the two
  // column-shaped passes cannot drift apart.
  // Cost lower-bound precheck for the column sweeps: for an admissible
  // cell, score_cell = base[p] - prio[t] + proximity with proximity and
  // jitter both >= 0 (when w_proximity >= 0, the production regime), so
  // lb = base[p] - prio[t] bounds every achievable key from below
  // (pack_key is monotone in cost with id 0 minimal). A row whose lb
  // can neither enter the reverse buffer (current worst only shrinks —
  // the standard streaming-top-k skip, exact) nor pass the entrant
  // theta/not-full test is SKIPPED without the proximity math — a
  // prune-only fast path, never a float change, so bit-identity with
  // the full sweep holds by construction.
  const bool lb_ok = w_proximity >= 0.0f;
  // Block-skip (the vectorized widening of the precheck above): one
  // lane-block lower-bound test in the FLOAT domain retires a whole
  // block of rows before any admissibility or scoring work. Soundness:
  // pack_key is monotone in cost with id 0 minimal, so the key-domain
  // tests above are implied by the cost-domain tests lb <= worst_cost /
  // lb <= theta_cost — the float test admits a conservative SUPERSET of
  // lanes. Survivor lanes fall through to the EXACT per-cell sequence
  // (bucket gate, key-domain precheck, score), so the set of scored
  // cells — and therefore every float, every key, and the cells[] stat
  // — is identical to the scalar sweep. The block test reads the
  // reverse worst from the block's entry; it only shrinks, so staleness
  // again only widens the survivor set.
  const auto sweep_column_range = [&](int32_t p, uint64_t* rb,
                                      std::vector<Ent>* ent_out, int tid,
                                      int32_t t0, int32_t t1) {
    if (!pf->valid[p]) return;
    const bool p_gpu = pf->has_gpu[p] != 0;
    const int32_t b = provider_bucket(pf, p);
    const int32_t mb = b >= 2 ? (b - 2) / kCountBuckets : 0;
    const int32_t cb = b >= 2 ? (b - 2) % kCountBuckets : 0;
    const auto cell = [&](int32_t t) {
      if (b >= 2 &&
          !bucket_admits_task(rf, t, K, W, ts_all[t], p_gpu, mb, cb)) {
        return;
      }
      if (b == 1 && ts_all[t].any_opt) return;  // no GPU
      if (lb_ok) {
        const uint64_t lbkey =
            pack_key(pre.base[p] - ts_all[t].prio, 0);
        const bool rev_possible = lbkey < rb[reverse_r - 1];
        const bool fwd_possible =
            ent_out != nullptr && !in_dt[t] &&
            (not_full[t] || lbkey <= theta[t]);
        if (!rev_possible && !fwd_possible) return;
      }
      const float c =
          score_cell_isa(isa, pf, rf, pre, ts_all[t], t, K, W, p,
                         w_proximity);
      ++cells[tid];
      if (c >= kInfeasible * 0.5f) return;
      const float cj = c + jitter(p, t);
      const uint64_t rkey = pack_key(cj, static_cast<uint32_t>(t));
      if (rkey < rb[reverse_r - 1]) {
        sorted_insert(rb, reverse_r, rkey);
      }
      if (ent_out != nullptr && !in_dt[t]) {
        const uint64_t fkey = pack_key(cj, p);
        if (fkey <= theta[t] || not_full[t]) {
          ent_out->push_back({t, fkey});
        }
      }
    };
    int32_t t = t0;
    if (isa != kIsaScalar && lb_ok) {
      const int use_fwd = ent_out != nullptr ? 1 : 0;
      const float base_p = pre.base[p];
      for (; t + ops.width <= t1; t += ops.width) {
        const float rw = unpack_key_cost(rb[reverse_r - 1]);
        uint32_t m = ops.lb_survivors(base_p, prio_all.data() + t,
                                      theta_cost.data() + t,
                                      not_full.data() + t, rw, use_fwd);
        while (m != 0) {
          const int32_t j = __builtin_ctz(m);
          m &= m - 1;
          cell(t + j);  // ctz walks lanes in ascending t: scalar order
        }
      }
    }
    for (; t < t1; ++t) cell(t);
  };
  const auto sweep_column = [&](int32_t p, uint64_t* rb,
                                std::vector<Ent>* ent_out, int tid) {
    for (int32_t j = 0; j < reverse_r; ++j) rb[j] = pad_key;
    sweep_column_range(p, rb, ent_out, tid, 0, T);
  };

  // Cache-blocked transposed pass: the naive loop sweeps each dirty
  // column over ALL T rows before moving on, streaming the full
  // per-task side arrays (ts_all/theta/not_full, ~50 B/row) through
  // cache once PER COLUMN. Tiling swaps the loops — a t-tile of side
  // arrays stays resident while every dirty column visits it. Each
  // column's reverse state lives in its own rev_io row (thread-owned:
  // providers are partitioned by chunk) and persists across tiles;
  // within a column t still ascends monotonically, so inserts happen in
  // the exact order of the untiled sweep — bit-identical lists, keys,
  // and cell counts. Entrant push order changes across tiles, which is
  // invisible: entrants are globally sorted before use.
  constexpr int32_t kSweepTile = 4096;
  const int32_t pchunk = (n_dp + nt - 1) / nt;
  par([&](int tid) {
    const int32_t lo = std::min<int32_t>(tid * pchunk, n_dp);
    const int32_t hi = std::min<int32_t>(lo + pchunk, n_dp);
    for (int32_t i = lo; i < hi; ++i) {
      const int32_t p = dirty_p[i];
      if (p < 0 || p >= P) continue;
      uint64_t* dst = rev_io + static_cast<size_t>(p) * reverse_r;
      for (int32_t j = 0; j < reverse_r; ++j) {  // old edges -> affected
        if (unpack_key_cost(dst[j]) >= kInfeasible * 0.5f) break;
        aff[tid].push_back(static_cast<int32_t>(dst[j] & 0xffffffffu));
      }
      for (int32_t j = 0; j < reverse_r; ++j) dst[j] = pad_key;
    }
    for (int32_t tt = 0; tt < T; tt += kSweepTile) {
      const int32_t te = std::min<int32_t>(tt + kSweepTile, T);
      for (int32_t i = lo; i < hi; ++i) {
        const int32_t p = dirty_p[i];
        if (p < 0 || p >= P) continue;
        sweep_column_range(p, rev_io + static_cast<size_t>(p) * reverse_r,
                           &ents[tid], tid, tt, te);
      }
    }
    for (int32_t i = lo; i < hi; ++i) {
      const int32_t p = dirty_p[i];
      if (p < 0 || p >= P) continue;
      const uint64_t* dst = rev_io + static_cast<size_t>(p) * reverse_r;
      for (int32_t j = 0; j < reverse_r; ++j) {  // new edges -> affected
        if (unpack_key_cost(dst[j]) >= kInfeasible * 0.5f) break;
        aff[tid].push_back(static_cast<int32_t>(dst[j] & 0xffffffffu));
      }
    }
  });
  if (st) {
    stats_out[2] = n_dp;
    stats_out[7] = now_ns() - t_phase;
    t_phase = now_ns();
  }

  // deterministic entrant order: sorted by (row, key) regardless of
  // which thread computed which dirty column
  std::vector<Ent> entrants;
  for (int i = 0; i < nt; ++i) {
    entrants.insert(entrants.end(), ents[i].begin(), ents[i].end());
    ents[i].clear();
  }
  std::sort(entrants.begin(), entrants.end(), [](const Ent& a, const Ent& b) {
    return a.t != b.t ? a.t < b.t : a.key < b.key;
  });
  if (st) stats_out[13] = static_cast<int64_t>(entrants.size());

  // forward work set: rows holding a dirty provider, or receiving an
  // entrant (dirty-task rows are rebuilt whole, below)
  std::vector<uint8_t> proc(T, 0);
  for (const Ent& e : entrants) proc[e.t] = 1;
  par([&](int tid) {
    const int32_t lo = std::min<int32_t>(tid * tchunk, T);
    const int32_t hi = std::min<int32_t>(lo + tchunk, T);
    for (int32_t t = lo; t < hi; ++t) {
      if (in_dt[t] || proc[t]) continue;
      const int64_t row = static_cast<int64_t>(t) * k_out;
      for (int32_t j = 0; j < k; ++j) {
        const int32_t p = cand_p_io[row + j];
        if (p >= 0 && in_dp[p]) {
          proc[t] = 1;
          break;
        }
      }
      if (proc[t]) continue;
      // a dirty provider parked in the SLACK tail must be stripped too
      // (its cached key is stale even though the auction never sees it)
      const int64_t srow = static_cast<int64_t>(t) * slack_cap;
      for (int32_t j = 0; j < slack_cap; ++j) {
        const int32_t p = slack_p_io[srow + j];
        if (p >= 0 && in_dp[p]) {
          proc[t] = 1;
          break;
        }
      }
    }
  });

  // old-row copies (pre-modification) for the touched/changed compare;
  // rows are appended once, in ascending task order per copy pass
  std::vector<int32_t> old_idx(T, -1);
  std::vector<int32_t> old_rows;
  std::vector<int32_t> old_p;
  std::vector<float> old_c;
  // row registration is a cheap sequential prefix; the bulk memcpy of
  // the registered rows runs on the pool
  const auto copy_rows = [&](const std::function<bool(int32_t)>& want) {
    const size_t first = old_rows.size();
    for (int32_t t = 0; t < T; ++t) {
      if (old_idx[t] < 0 && want(t)) {
        old_idx[t] = static_cast<int32_t>(old_rows.size());
        old_rows.push_back(t);
      }
    }
    const size_t n_new = old_rows.size() - first;
    if (n_new == 0) return;
    old_p.resize(old_rows.size() * static_cast<size_t>(k_out));
    old_c.resize(old_rows.size() * static_cast<size_t>(k_out));
    const int32_t cchunk =
        static_cast<int32_t>((n_new + nt - 1) / nt);
    par([&](int tid) {
      const size_t lo = first + std::min<size_t>(
          static_cast<size_t>(tid) * cchunk, n_new);
      const size_t hi = first + std::min<size_t>(
          static_cast<size_t>(tid) * cchunk + cchunk, n_new);
      for (size_t i = lo; i < hi; ++i) {
        const int64_t row = static_cast<int64_t>(old_rows[i]) * k_out;
        std::memcpy(old_p.data() + i * k_out, cand_p_io + row,
                    static_cast<size_t>(k_out) * 4);
        std::memcpy(old_c.data() + i * k_out, cand_c_io + row,
                    static_cast<size_t>(k_out) * 4);
      }
    });
  };
  copy_rows([&](int32_t t) { return proc[t] || in_dt[t]; });

  // a bucket-exact row scan shared by dirty-task rebuilds and merge
  // rescans: fills the row's k forward slots; optionally collects
  // reverse-edge candidates for clean providers (dirty-task rows only —
  // a rescan's cells did not change value, so its edges are already in
  // exactly the right reverse lists)
  std::vector<int64_t> fb_rows(nt, 0), scanned(nt, 0);
  const auto emit_row = [&](int32_t t, const uint64_t* keys, int32_t n) {
    // write a row's forward slots + slack tail from n ascending keys
    const int64_t row = static_cast<int64_t>(t) * k_out;
    for (int32_t j = 0; j < k; ++j) {
      const bool feas = j < n && unpack_key_cost(keys[j]) < kInfeasible * 0.5f;
      cand_p_io[row + j] =
          feas ? static_cast<int32_t>(keys[j] & 0xffffffffu) : -1;
      cand_c_io[row + j] = feas ? unpack_key_cost(keys[j]) : kInfeasible;
    }
    const int64_t srow = static_cast<int64_t>(t) * slack_cap;
    for (int32_t j = 0; j < slack_cap; ++j) {
      const int32_t at = k + j;
      const bool feas =
          at < n && unpack_key_cost(keys[at]) < kInfeasible * 0.5f;
      slack_p_io[srow + j] =
          feas ? static_cast<int32_t>(keys[at] & 0xffffffffu) : -1;
      slack_c_io[srow + j] = feas ? unpack_key_cost(keys[at]) : kInfeasible;
    }
  };
  const auto scan_row = [&](int32_t t, std::vector<uint64_t>& buf,
                            std::vector<uint8_t>& adm, int tid,
                            std::vector<RevEdge>* collect) {
    for (int32_t j = 0; j < k_sel; ++j) buf[j] = pad_key;
    const TaskScore& ts = ts_all[t];
    const int64_t n_adm = task_admissible(rf, t, K, W, ts, bx, adm.data());
    const bool full = n_adm >= static_cast<int64_t>(coverage_frac * P);
    if (full) ++fb_rows[tid];
    const auto visit = [&](int32_t p) {
      const float c = score_cell_isa(isa, pf, rf, pre, ts_all[t], t, K, W,
                                     p, w_proximity);
      ++cells[tid];
      if (c >= kInfeasible * 0.5f) return;
      const float cj = c + jitter(p, t);
      if (collect != nullptr && !in_dp[p]) {
        const uint64_t rkey = pack_key(cj, static_cast<uint32_t>(t));
        if (rkey <= wkey[p]) collect->push_back({p, rkey});
      }
      const uint64_t key = pack_key(cj, p);
      if (key < buf[k_sel - 1]) sorted_insert(buf.data(), k_sel, key);
    };
    if (full) {
      scanned[tid] += P;
      for (int32_t p = 0; p < P; ++p) visit(p);
    } else {
      scanned[tid] += n_adm;
      for (int32_t b = 1; b < kNumBuckets; ++b) {
        if (!adm[b]) continue;
        for (int32_t i = bx.start[b]; i < bx.start[b + 1]; ++i) {
          visit(bx.ids[i]);
        }
      }
    }
    emit_row(t, buf.data(), k_sel);
  };

  // ---- phase 2a: dirty-task rows — full exact rebuild via the pruner,
  // collecting their reverse-edge candidates for phase 3
  std::vector<std::vector<RevEdge>> redges(nt);
  const int32_t dtchunk = (n_dt + nt - 1) / nt;
  par([&](int tid) {
    const int32_t lo = std::min<int32_t>(tid * dtchunk, n_dt);
    const int32_t hi = std::min<int32_t>(lo + dtchunk, n_dt);
    std::vector<uint64_t> buf(k_sel);
    std::vector<uint8_t> adm(kNumBuckets);
    for (int32_t i = lo; i < hi; ++i) {
      const int32_t t = dirty_t[i];
      if (t < 0 || t >= T) continue;
      scan_row(t, buf, adm, tid, &redges[tid]);
      touched_out[t] = 1;
      changed_out[t] = 1;
    }
  });

  // ---- phase 2b: merges for rows the provider churn touched, over the
  // maintained list L = forward + slack (strip dirty entries, fold the
  // entrants admitted below tau, keep the best k+slack)
  std::vector<int64_t> merged_n(nt, 0), rescan_n(nt, 0);
  par([&](int tid) {
    const int32_t lo = std::min<int32_t>(tid * tchunk, T);
    const int32_t hi = std::min<int32_t>(lo + tchunk, T);
    std::vector<uint64_t> buf(k_sel);
    std::vector<uint64_t> pool(static_cast<size_t>(k_sel));
    std::vector<uint8_t> adm(kNumBuckets);
    for (int32_t t = lo; t < hi; ++t) {
      if (!proc[t] || in_dt[t]) continue;
      const int64_t row = static_cast<int64_t>(t) * k_out;
      const int64_t srow = static_cast<int64_t>(t) * slack_cap;
      // retained: the row's non-dirty keys, ascending (forward then
      // slack — both stored ascending, slack keys beyond forward's)
      pool.clear();
      for (int32_t j = 0; j < k; ++j) {
        const int32_t p = cand_p_io[row + j];
        if (p < 0) break;
        if (!in_dp[p]) pool.push_back(pack_key(cand_c_io[row + j], p));
      }
      for (int32_t j = 0; j < slack_cap; ++j) {
        const int32_t p = slack_p_io[srow + j];
        if (p < 0) break;
        if (!in_dp[p]) pool.push_back(pack_key(slack_c_io[srow + j], p));
      }
      const size_t n_ret = pool.size();
      const Ent probe{t, 0};
      auto e_lo = std::lower_bound(
          entrants.begin(), entrants.end(), probe,
          [](const Ent& a, const Ent& b) { return a.t < b.t; });
      for (auto it = e_lo; it != entrants.end() && it->t == t; ++it) {
        pool.push_back(it->key);
      }
      // merge the two ascending runs (retained, entrants)
      std::inplace_merge(pool.begin(), pool.begin() + n_ret, pool.end());
      // a forward-not-full row's pool is ALL feasible providers (clean
      // absentees are provably infeasible, every feasible dirty key was
      // admitted) — exact at any size, no rescan
      if (static_cast<int32_t>(pool.size()) >= k || not_full[t]) {
        // the pool covers the top-k exactly (every provider outside it
        // is beyond tau, hence beyond the pool's k-th key); the tail
        // re-arms the slack, trimmed at capacity (tau ratchets down)
        ++merged_n[tid];
        emit_row(t, pool.data(),
                 std::min<int32_t>(pool.size(), k_sel));
      } else {
        // the list lost more members than re-entered: the true
        // successor is outside the maintained structure — re-score the
        // row exactly (bucket-pruned, never the full matrix)
        ++rescan_n[tid];
        scan_row(t, buf, adm, tid, nullptr);
      }
    }
  });
  if (st) {
    for (int i = 0; i < nt; ++i) {
      stats_out[0] += merged_n[i];
      stats_out[1] += rescan_n[i];
      stats_out[6] += fb_rows[i];
      stats_out[4] += scanned[i];
    }
    stats_out[8] = now_ns() - t_phase;
    t_phase = now_ns();
  }

  // ---- phase 3: clean providers' reverse lists — strip dirty-task
  // entries, fold the dirty rows' fresh edges back in, rebuild from one
  // column scan when the pool argument no longer covers the list
  std::vector<RevEdge> edges;
  for (int i = 0; i < nt; ++i) {
    edges.insert(edges.end(), redges[i].begin(), redges[i].end());
    redges[i].clear();
  }
  std::sort(edges.begin(), edges.end(),
            [](const RevEdge& a, const RevEdge& b) {
              return a.q != b.q ? a.q < b.q : a.key < b.key;
            });
  std::vector<int64_t> rev_rescans(nt, 0);
  const int32_t qchunk = (P + nt - 1) / nt;
  par([&](int tid) {
    const int32_t lo = std::min<int32_t>(tid * qchunk, P);
    const int32_t hi = std::min<int32_t>(lo + qchunk, P);
    std::vector<uint64_t> keep(reverse_r);
    const RevEdge probe_lo{lo, 0};
    auto it = std::lower_bound(
        edges.begin(), edges.end(), probe_lo,
        [](const RevEdge& a, const RevEdge& b) { return a.q < b.q; });
    for (int32_t q = lo; q < hi; ++q) {
      if (in_dp[q]) {
        while (it != edges.end() && it->q == q) ++it;  // rebuilt in phase 1
        continue;
      }
      uint64_t* rb = rev_io + static_cast<size_t>(q) * reverse_r;
      int32_t d = 0, m = 0;
      for (int32_t j = 0; j < reverse_r; ++j) {
        const uint64_t key = rb[j];
        if (unpack_key_cost(key) >= kInfeasible * 0.5f) break;
        const int32_t task = static_cast<int32_t>(key & 0xffffffffu);
        if (in_dt[task]) {
          ++d;
        } else {
          keep[m++] = key;
        }
      }
      const auto e_begin = it;
      while (it != edges.end() && it->q == q) ++it;
      const auto e_end = it;
      if (d == 0 && e_begin == e_end) continue;  // untouched list
      // affected: the full old membership and (later) the new one
      for (int32_t j = 0; j < m + d; ++j) {
        if (unpack_key_cost(rb[j]) >= kInfeasible * 0.5f) break;
        aff[tid].push_back(static_cast<int32_t>(rb[j] & 0xffffffffu));
      }
      const bool was_full = unpack_key_cost(wkey[q]) < kInfeasible * 0.5f;
      const int64_t iprime = e_end - e_begin;  // all collected <= wkey
      if (was_full && iprime < d) {
        // the list lost more entries than re-entered below its old
        // worst: the true best-r includes unknown clean edges — one
        // exact gated column sweep (the phase-1 implementation,
        // entrants off) rebuilds it
        ++rev_rescans[tid];
        sweep_column(q, keep.data(), nullptr, tid);
        std::memcpy(rb, keep.data(), static_cast<size_t>(reverse_r) * 8);
      } else {
        // best-r of (kept ascending) U (edges ascending): two-pointer
        // merge into the list, pad the tail
        int32_t a = 0;
        auto b = e_begin;
        std::vector<uint64_t> out(reverse_r, pad_key);
        int32_t n = 0;
        while (n < reverse_r && (a < m || b != e_end)) {
          if (a < m && (b == e_end || keep[a] <= b->key)) {
            out[n++] = keep[a++];
          } else {
            out[n++] = (b++)->key;
          }
        }
        std::memcpy(rb, out.data(), static_cast<size_t>(reverse_r) * 8);
      }
      for (int32_t j = 0; j < reverse_r; ++j) {  // new membership
        if (unpack_key_cost(rb[j]) >= kInfeasible * 0.5f) break;
        aff[tid].push_back(static_cast<int32_t>(rb[j] & 0xffffffffu));
      }
    }
  });
  if (st) {
    for (int i = 0; i < nt; ++i) stats_out[3] += rev_rescans[i];
    stats_out[9] = now_ns() - t_phase;
    t_phase = now_ns();
  }

  // ---- phase 4: re-scatter extras for every affected row
  if (extra > 0) {
    std::vector<uint8_t> affected(T, 0);
    for (int32_t t = 0; t < T; ++t) {
      if (proc[t] || in_dt[t]) affected[t] = 1;
    }
    for (int i = 0; i < nt; ++i) {
      for (const int32_t t : aff[i]) {
        if (t >= 0 && t < T) affected[t] = 1;
      }
      aff[i].clear();
    }
    copy_rows([&](int32_t t) { return affected[t] != 0; });
    struct SEdge {
      int32_t t;
      float c;
      int32_t p;
    };
    std::vector<std::vector<SEdge>> sed(nt);
    par([&](int tid) {
      const int32_t lo = std::min<int32_t>(tid * qchunk, P);
      const int32_t hi = std::min<int32_t>(lo + qchunk, P);
      for (int32_t p = lo; p < hi; ++p) {
        const uint64_t* rb = rev_io + static_cast<size_t>(p) * reverse_r;
        for (int32_t j = 0; j < reverse_r; ++j) {
          const float c = unpack_key_cost(rb[j]);
          if (c >= kInfeasible * 0.5f) break;
          const int32_t t = static_cast<int32_t>(rb[j] & 0xffffffffu);
          if (affected[t]) sed[tid].push_back({t, c, p});
        }
      }
    });
    std::vector<SEdge> sedges;
    for (int i = 0; i < nt; ++i) {
      sedges.insert(sedges.end(), sed[i].begin(), sed[i].end());
      sed[i].clear();
    }
    // the cold scatter's exact (t, c, p) fill order, restricted to the
    // affected subset — per-task fill only ever reads a task's own edges
    std::sort(sedges.begin(), sedges.end(),
              [](const SEdge& a, const SEdge& b) {
                if (a.t != b.t) return a.t < b.t;
                if (a.c != b.c) return a.c < b.c;
                return a.p < b.p;
              });
    // reset + fill, task-parallel: each thread owns a contiguous span
    // of task ids and the matching (sorted) edge span — per-task fill
    // is a pure function of that task's own edges and forward list
    const int64_t n_se = static_cast<int64_t>(sedges.size());
    par([&](int tid) {
      const int32_t lo = std::min<int32_t>(tid * tchunk, T);
      const int32_t hi = std::min<int32_t>(lo + tchunk, T);
      for (int32_t t = lo; t < hi; ++t) {
        if (!affected[t]) continue;
        const int64_t row = static_cast<int64_t>(t) * k_out;
        for (int32_t j = k; j < k_out; ++j) {
          cand_p_io[row + j] = -1;
          cand_c_io[row + j] = kInfeasible;
        }
      }
      const SEdge probe{lo, 0.0f, 0};
      auto it = std::lower_bound(
          sedges.begin(), sedges.end(), probe,
          [](const SEdge& a, const SEdge& b) { return a.t < b.t; });
      for (int64_t i = it - sedges.begin(); i < n_se; ++i) {
        const SEdge& e = sedges[i];
        if (e.t >= hi) break;
        const int64_t row = static_cast<int64_t>(e.t) * k_out;
        int32_t fill = 0;
        while (fill < extra && cand_p_io[row + k + fill] >= 0) ++fill;
        if (fill >= extra) continue;
        bool dup = false;
        for (int32_t j = 0; j < k && !dup; ++j) {
          dup = cand_p_io[row + j] == e.p;
        }
        if (dup) continue;
        cand_p_io[row + k + fill] = e.p;
        cand_c_io[row + k + fill] = e.c;
      }
    });
  }
  if (st) {
    stats_out[10] = now_ns() - t_phase;
    t_phase = now_ns();
  }

  // ---- phase 5: touched/changed against the saved old rows
  const int32_t n_old = static_cast<int32_t>(old_rows.size());
  const int32_t ochunk = (n_old + nt - 1) / nt;
  par([&](int tid) {
    const int32_t lo = std::min<int32_t>(tid * ochunk, n_old);
    const int32_t hi = std::min<int32_t>(lo + ochunk, n_old);
    // epoch-tagged per-provider scratch: membership + aligned-cost
    // analysis in one O(k_out) pass per row, no per-row sorts (rows
    // hold each provider at most once — the extras dup-check invariant)
    std::vector<int32_t> seen(P, -1);
    std::vector<float> ocost(P, 0.0f);
    for (int32_t i = lo; i < hi; ++i) {
      const int32_t t = old_rows[i];
      if (in_dt[t]) continue;  // forced touched+changed above
      const int64_t row = static_cast<int64_t>(t) * k_out;
      const int32_t* op = old_p.data() + static_cast<int64_t>(i) * k_out;
      const float* oc = old_c.data() + static_cast<int64_t>(i) * k_out;
      if (std::memcmp(op, cand_p_io + row,
                      static_cast<size_t>(k_out) * 4) == 0 &&
          std::memcmp(oc, cand_c_io + row,
                      static_cast<size_t>(k_out) * 4) == 0) {
        continue;  // bit-identical row: untouched
      }
      touched_out[t] = 1;
      int32_t n_old_m = 0, n_new_m = 0;
      for (int32_t j = 0; j < k_out; ++j) {
        const int32_t p = op[j];
        if (p < 0) continue;
        seen[p] = i;
        ocost[p] = oc[j];
        ++n_old_m;
      }
      bool member_changed = false;
      bool cheaper = false;
      for (int32_t j = 0; j < k_out; ++j) {
        const int32_t p = cand_p_io[row + j];
        if (p < 0) continue;
        ++n_new_m;
        if (seen[p] != i) {
          member_changed = true;
          break;
        }
        if (ocost[p] - cand_c_io[row + j] > cheaper_tol) cheaper = true;
      }
      if (member_changed || cheaper || n_old_m != n_new_m) {
        changed_out[t] = 1;
      }
    }
  });
  if (st) {
    stats_out[11] = now_ns() - t_phase;
    int64_t total_cells = 0;
    for (int i = 0; i < nt; ++i) total_cells += cells[i];
    stats_out[5] = total_cells;
    for (int32_t t = 0; t < T; ++t) {
      stats_out[14] += changed_out[t];
      stats_out[15] += touched_out[t];
    }
  }
  return 0;
}

// Gauss-Seidel auction on candidate lists with eps-scaling.
// cand_provider/cand_cost: [T*K]; out_provider_for_task: length T.
// Returns the number of assigned tasks.
int32_t auction_sparse(const int32_t* cand_provider, const float* cand_cost,
                       int32_t P, int32_t T, int32_t K, float eps_start,
                       float eps_end, float scale, int64_t max_events,
                       int32_t* out_provider_for_task) {
  std::vector<float> price(P, 0.0f);
  std::vector<int32_t> owner(P, -1);  // task holding each provider
  std::vector<int32_t> p4t(T, -1);
  std::vector<uint8_t> retired(T, 0);

  float max_cost = 0.0f;
  for (int64_t i = 0; i < static_cast<int64_t>(T) * K; ++i) {
    if (cand_provider[i] >= 0 && cand_cost[i] > max_cost) {
      max_cost = cand_cost[i];
    }
  }
  const float give_up = -(2.0f * max_cost + 10.0f);

  std::vector<int32_t> open;
  open.reserve(T);
  int64_t events = 0;

  float eps = eps_start;
  while (true) {
    const bool final_phase = eps <= eps_end;
    // Retirement only in the final phase: at coarse eps, price overshoot
    // from an unfillable tail would push *viable* tasks past give-up.
    // Coarse phases instead get a bounded event budget and hand off.
    const int64_t phase_budget =
        final_phase ? max_events : events + 4 * static_cast<int64_t>(T);

    // collect open tasks for this eps phase
    open.clear();
    for (int32_t t = 0; t < T; ++t) {
      if (p4t[t] < 0 && !retired[t]) open.push_back(t);
    }
    // Gauss-Seidel sweeps until the phase stabilizes or exhausts its budget
    while (!open.empty() && events < phase_budget && events < max_events) {
      const int32_t t = open.back();
      open.pop_back();
      if (p4t[t] >= 0 || retired[t]) continue;
      // best + second-best value over candidates at current prices
      float v1 = kNeg, v2 = kNeg;
      int32_t p1 = -1;
      for (int32_t j = 0; j < K; ++j) {
        const int32_t p = cand_provider[static_cast<int64_t>(t) * K + j];
        if (p < 0) continue;
        const float v =
            -cand_cost[static_cast<int64_t>(t) * K + j] - price[p];
        if (v > v1) {
          v2 = v1;
          v1 = v;
          p1 = p;
        } else if (v > v2) {
          v2 = v;
        }
      }
      if (p1 < 0) {
        retired[t] = 1;  // no feasible candidates at all
        continue;
      }
      if (v1 < give_up) {
        if (final_phase) {
          retired[t] = 1;  // priced out everywhere: not worth it
        }
        continue;  // coarse phases: park it; the next phase re-collects
      }
      if (v2 < -1e8f) v2 = -1e8f;  // single-option floor
      ++events;
      price[p1] += (v1 - v2) + eps;
      const int32_t evicted = owner[p1];
      owner[p1] = t;
      p4t[t] = p1;
      if (evicted >= 0) {
        p4t[evicted] = -1;
        open.push_back(evicted);
      }
    }
    if (eps <= eps_end || events >= max_events) break;
    eps = std::max(eps * scale, eps_end);
    // eps-CS repair: holders whose assignment violates the tighter eps
    // re-enter the auction (keeping happy holders seated avoids both the
    // full-reset cost and the mass-retirement pathology of pumped prices)
    for (int32_t t = 0; t < T; ++t) {
      const int32_t held = p4t[t];
      if (held < 0 || retired[t]) continue;
      float v1 = kNeg;
      float vcur = kNeg;
      for (int32_t j = 0; j < K; ++j) {
        const int32_t p = cand_provider[static_cast<int64_t>(t) * K + j];
        if (p < 0) continue;
        const float v =
            -cand_cost[static_cast<int64_t>(t) * K + j] - price[p];
        if (v > v1) v1 = v;
        if (p == held) vcur = v;
      }
      if (vcur < v1 - eps) {
        owner[held] = -1;
        p4t[t] = -1;
      }
    }
  }

  // Cleanup pass: a forward auction never lowers prices, so an unfillable
  // tail can leave providers stranded at pumped prices while feasible tasks
  // sit retired. Sweep the leftover graph greedily (cheapest free candidate
  // per remaining task) — the reference's matcher semantics on the tail,
  // guaranteeing no provider stays idle while a compatible task waits.
  for (int32_t t = 0; t < T; ++t) {
    if (p4t[t] >= 0) continue;
    float best = kInfeasible;
    int32_t best_p = -1;
    for (int32_t j = 0; j < K; ++j) {
      const int32_t p = cand_provider[static_cast<int64_t>(t) * K + j];
      if (p < 0 || owner[p] >= 0) continue;
      const float c = cand_cost[static_cast<int64_t>(t) * K + j];
      if (c < best) {
        best = c;
        best_p = p;
      }
    }
    if (best_p >= 0 && best < kInfeasible * 0.5f) {
      owner[best_p] = t;
      p4t[t] = best_p;
    }
  }

  int32_t assigned = 0;
  for (int32_t t = 0; t < T; ++t) {
    out_provider_for_task[t] = p4t[t];
    if (p4t[t] >= 0) ++assigned;
  }
  return assigned;
}

// ---------------------------------------------------------------------------
// Multi-threaded auction (engine=native-mt): synchronous Jacobi bidding
// rounds with per-thread bid computation and a deterministic sequential
// merge. Unlike the Gauss-Seidel engine above (whose result depends on its
// serial processing order), every round here computes ALL open tasks' bids
// against the same price snapshot, then applies one winner per provider
// (highest increment, ties -> lowest task index) — so the matching is a
// pure function of the inputs, bit-identical for every thread count
// including threads=1. Carries the FULL dual state (prices + retirement
// mask + previous matching) in/out, which is what the persistent warm
// arena (protocol_tpu/native/arena.py) chains between solves.
//
// price_io:   [P] f32 in/out — pass zeros for a cold solve.
// retired_io: [T] u8 in/out  — pass zeros for a cold solve; the caller
//             must clear flags for tasks whose candidates changed.
// p4t_seed:   [T] i32 or null — previous matching to re-seat (must be
//             injective over >= 0); seeds violating eps-CS are evicted by
//             the repair pass at each phase start.
// max_release: > 0 caps how many seated tasks the eps-CS repair may evict
//             per repair pass — the WORST violators (largest eps-CS
//             margin, ties to the lowest task index) go first, the rest
//             keep their now-suboptimal seats until a later solve. This
//             bounds the warm re-bidding wave under heavy drift (a mass
//             eviction degenerates a warm solve into a fine-eps cold
//             auction); the matching stays feasible and injective, and
//             staleness is amortized: each repair re-ranks the
//             violations it SCANS (all rows, or the repair_mask subset)
//             and releases the current worst. A caller combining the cap
//             with repair_mask must evict infeasible seats itself (a
//             capped-out violator whose row stops churning leaves the
//             mask — see arena.py's feasibility guard). <= 0 releases
//             every violator (the historical behavior).
// repair_mask: [T] u8 or null — rows the eps-CS repair may consider.
//             Sound because forward-auction prices are monotone: a seat
//             that was eps-happy at the last convergence can only become
//             HAPPIER unless its own row's candidate costs changed (v1
//             falls as rival prices rise; vcur is fixed while held), so
//             warm callers pass the rows whose costs they touched and
//             the repair skips the rest of the [T x K] scan. null scans
//             everything (cold calls / callers without churn tracking).
// stats_out: nullable, kEngineStatsSlots i64 slots —
//   [0] bidding rounds   [1] bids placed     [2] seats evicted (repair)
//   [3] repair passes that evicted >= 1 seat [4] eps phases
//   [5] repair ns        [6] bid ns          [7] merge ns
//   [8] cleanup ns       [9] tasks retired at exit
//   [10] outcome/margin pass ns (the decision-observability layer)
//   [11] plan cost over the candidate support, 1e-6 cost units
//   [12] reachable-idle price mass and [13] eps-CS slack (the two
//        duality-gap certificate addends, prices capped at the give-up
//        magnitude), 1e-6 cost units — filled only with margin_out
// Accumulated on the calling thread only; null skips every clock read.
//
// outcome_out: nullable [T] u8 — the per-task DECISION taxonomy (the
//   quality plane's native layer, same null-means-zero-overhead contract
//   as stats_out):
//     0 assigned
//     1 unassigned: no feasible candidates at all
//     2 unassigned: outbid / priced past give-up this solve (or left
//       open when the event budget ran out)
//     3 unassigned: carried (stale) retirement — the task entered
//       retired and nothing re-opened it this solve
//   Causes are recorded in the SEQUENTIAL merge and the exit loop, both
//   on the calling thread; helper threads never touch the array.
// margin_out: nullable [T] f32 — for assigned tasks, the winner margin
//   at FINAL prices: value(seat) - best value over the task's OTHER
//   candidates (runner-up floored at -1e8, mirroring the bid math's
//   single-option floor). 0 for unassigned tasks. One O(T*K) post-pass
//   on the calling thread; prices/matching are bit-identical with or
//   without it.
// Returns the number of assigned tasks.
int32_t auction_sparse_mt(const int32_t* cand_provider, const float* cand_cost,
                          int32_t P, int32_t T, int32_t K, float eps_start,
                          float eps_end, float scale, int64_t max_events,
                          int32_t threads, float* price_io, uint8_t* retired_io,
                          const int32_t* p4t_seed, int32_t max_release,
                          const uint8_t* repair_mask,
                          int32_t* out_provider_for_task,
                          int64_t* stats_out, uint8_t* outcome_out,
                          float* margin_out) {
  const bool st = stats_out != nullptr;
  if (st) std::memset(stats_out, 0, kEngineStatsSlots * 8);
  int64_t t_phase = 0;
  const bool oc = outcome_out != nullptr;
  // per-task retirement cause recorded during THIS solve (0 = none):
  // only touched by the sequential merge / exit loop on the calling
  // thread, and only allocated when the caller asked for outcomes
  std::vector<uint8_t> cause;
  if (oc) cause.assign(T, 0);
  std::vector<float> price(price_io, price_io + P);
  std::vector<int32_t> owner(P, -1);
  std::vector<int32_t> p4t(T, -1);
  std::vector<uint8_t> retired(retired_io, retired_io + T);
  if (p4t_seed != nullptr) {
    // NOTE: a seed does NOT clear a carried retirement flag (unlike the
    // JAX warm kernel's retired0 & (p4t0 < 0)): a priced-out task that
    // the cleanup pass seated stays seated-and-inert until its
    // candidates change, instead of being evicted by the eps-CS repair
    // and re-fighting (then re-retiring, then re-seating — a persistent
    // ~13%-of-seats flap measured on an UNCHANGED marketplace).
    for (int32_t t = 0; t < T; ++t) {
      const int32_t p = p4t_seed[t];
      if (p >= 0 && p < P && owner[p] < 0) {
        owner[p] = t;
        p4t[t] = p;
      }
    }
  }

  float max_cost = 0.0f;
  for (int64_t i = 0; i < static_cast<int64_t>(T) * K; ++i) {
    if (cand_provider[i] >= 0 && cand_cost[i] > max_cost) {
      max_cost = cand_cost[i];
    }
  }
  const float give_up = -(2.0f * max_cost + 10.0f);

  const int nt = resolve_threads(threads, T);
  // a condvar wakeup costs ~10 us; below this many items the round runs
  // inline on the caller (same code, same values — only WHO computes
  // changes, so the threshold cannot affect the matching)
  constexpr int32_t kParMin = 8192;
  HelperPool* pool = nullptr;
  if (nt > 1 && T >= kParMin) pool = new HelperPool(nt - 1);

  std::vector<int32_t> open;
  open.reserve(T);
  std::vector<int32_t> bid_p(T);     // per-open-slot bid provider / sentinel
  std::vector<float> bid_inc(T);     // per-open-slot price increment
  std::vector<uint8_t> release(T);   // repair pass: evict flag per task
  std::vector<float> rel_margin(T);  // eps-CS violation margin (capped mode)
  std::vector<int32_t> rel_list;     // violator ids for the capped select
  rel_list.reserve(T);
  std::vector<float> win_inc(P, 0.0f);
  std::vector<int32_t> win_task(P, -1);
  std::vector<int32_t> touched;
  touched.reserve(P);
  std::vector<int32_t> next_open;
  next_open.reserve(T);

  // chunked parallel-for over [0, n): helpers engaged only when n is
  // large enough to amortize their wakeup
  const auto par_for = [&](int32_t n, const std::function<void(int32_t, int32_t)>& body) {
    if (pool == nullptr || n < kParMin) {
      body(0, n);
      return;
    }
    const int32_t chunk = (n + nt - 1) / nt;
    pool->run([&](int tid) {
      const int32_t lo = std::min<int32_t>(tid * chunk, n);
      const int32_t hi = std::min<int32_t>(lo + chunk, n);
      if (lo < hi) body(lo, hi);
    });
  };

  int64_t events = 0;
  float eps = eps_start;
  while (true) {
    const bool final_phase = eps <= eps_end;
    const int64_t phase_budget =
        final_phase ? max_events : events + 4 * static_cast<int64_t>(T);

    // eps-CS repair (parallel mark, sequential apply): holders whose seat
    // violates the phase eps re-enter the auction — keeps happy holders
    // seated, evicts stale warm seeds. No-op on a cold start.
    if (st) {
      ++stats_out[4];
      t_phase = now_ns();
    }
    par_for(T, [&](int32_t lo, int32_t hi) {
      for (int32_t t = lo; t < hi; ++t) {
        release[t] = 0;
        const int32_t held = p4t[t];
        if (held < 0 || retired[t]) continue;
        if (repair_mask != nullptr && repair_mask[t] == 0) continue;
        float v1 = kNeg, vcur = kNeg;
        const int64_t row = static_cast<int64_t>(t) * K;
        for (int32_t j = 0; j < K; ++j) {
          const int32_t p = cand_provider[row + j];
          if (p < 0) continue;
          const float v = -cand_cost[row + j] - price[p];
          if (v > v1) v1 = v;
          if (p == held) vcur = v;
        }
        release[t] = vcur < v1 - eps;
        rel_margin[t] = v1 - vcur;
      }
    });
    if (max_release > 0) {
      rel_list.clear();
      for (int32_t t = 0; t < T; ++t) {
        if (release[t]) rel_list.push_back(t);
      }
      if (static_cast<int32_t>(rel_list.size()) > max_release) {
        // strict weak order with an id tiebreak: the released SET is
        // deterministic regardless of nth_element's internal order
        std::nth_element(
            rel_list.begin(), rel_list.begin() + max_release,
            rel_list.end(), [&](int32_t a, int32_t b) {
              if (rel_margin[a] != rel_margin[b])
                return rel_margin[a] > rel_margin[b];
              return a < b;
            });
        for (size_t i = max_release; i < rel_list.size(); ++i)
          release[rel_list[i]] = 0;
      }
    }
    bool released_any = false;
    for (int32_t t = 0; t < T; ++t) {
      if (release[t]) {
        if (st) ++stats_out[2];
        released_any = true;
        owner[p4t[t]] = -1;
        p4t[t] = -1;
      }
    }
    if (st && released_any) ++stats_out[3];
    open.clear();
    for (int32_t t = 0; t < T; ++t) {
      if (p4t[t] < 0 && !retired[t]) open.push_back(t);
    }
    if (st) stats_out[5] += now_ns() - t_phase;

    // synchronous bidding rounds: all open tasks bid against the same
    // price snapshot; one winner per provider (highest increment, ties to
    // the lowest task index) — a pure function of the round state.
    while (!open.empty() && events < phase_budget && events < max_events) {
      const int32_t n_open = static_cast<int32_t>(open.size());
      if (st) {
        ++stats_out[0];
        t_phase = now_ns();
      }
      par_for(n_open, [&](int32_t lo, int32_t hi) {
        for (int32_t i = lo; i < hi; ++i) {
          const int32_t t = open[i];
          float v1 = kNeg, v2 = kNeg;
          int32_t p1 = -1;
          const int64_t row = static_cast<int64_t>(t) * K;
          for (int32_t j = 0; j < K; ++j) {
            const int32_t p = cand_provider[row + j];
            if (p < 0) continue;
            const float v = -cand_cost[row + j] - price[p];
            if (v > v1) {
              v2 = v1;
              v1 = v;
              p1 = p;
            } else if (v > v2) {
              v2 = v;
            }
          }
          if (p1 < 0) {
            bid_p[i] = -2;  // no feasible candidates at all: retire
          } else if (v1 < give_up) {
            bid_p[i] = -3;  // priced out: park (retire in final phase)
          } else {
            if (v2 < -1e8f) v2 = -1e8f;  // single-option floor
            bid_p[i] = p1;
            bid_inc[i] = (v1 - v2) + eps;
          }
        }
      });
      if (st) {
        stats_out[6] += now_ns() - t_phase;
        t_phase = now_ns();
      }
      // deterministic sequential merge
      touched.clear();
      for (int32_t i = 0; i < n_open; ++i) {
        const int32_t t = open[i];
        const int32_t p = bid_p[i];
        if (st && p >= 0) ++stats_out[1];
        if (p == -2) {
          retired[t] = 1;
          if (oc) cause[t] = 1;  // no feasible candidates at all
          continue;
        }
        if (p == -3) {
          if (final_phase) {
            retired[t] = 1;
            if (oc) cause[t] = 2;  // priced out past give-up
          }
          continue;  // parked: re-collected at the next phase
        }
        if (win_task[p] < 0) {
          touched.push_back(p);
          win_task[p] = t;
          win_inc[p] = bid_inc[i];
        } else if (bid_inc[i] > win_inc[p] ||
                   (bid_inc[i] == win_inc[p] && t < win_task[p])) {
          win_task[p] = t;
          win_inc[p] = bid_inc[i];
        }
      }
      next_open.clear();
      for (const int32_t p : touched) {
        const int32_t t = win_task[p];
        price[p] += win_inc[p];
        const int32_t evicted = owner[p];
        owner[p] = t;
        p4t[t] = p;
        if (evicted >= 0) {
          p4t[evicted] = -1;
          next_open.push_back(evicted);
        }
        ++events;
        win_task[p] = -1;  // reset for the next round
      }
      // losers (bid but did not win) stay open
      for (int32_t i = 0; i < n_open; ++i) {
        const int32_t t = open[i];
        if (bid_p[i] >= 0 && p4t[t] < 0) next_open.push_back(t);
      }
      open.swap(next_open);
      if (st) stats_out[7] += now_ns() - t_phase;
    }

    if (eps <= eps_end || events >= max_events) break;
    eps = std::max(eps * scale, eps_end);
  }
  // pool deliberately outlives the bid loop: the margin/certificate
  // post-pass below reuses it (helpers idle-wait in between)
  if (st) t_phase = now_ns();

  // Cleanup pass (same tail semantics as the Gauss-Seidel engine): a
  // forward auction never lowers prices, so an unfillable tail can strand
  // providers at pumped prices while feasible tasks sit retired. Seat the
  // leftovers greedily; deterministic by task order.
  for (int32_t t = 0; t < T; ++t) {
    if (p4t[t] >= 0) continue;
    float best = kInfeasible;
    int32_t best_p = -1;
    const int64_t row = static_cast<int64_t>(t) * K;
    for (int32_t j = 0; j < K; ++j) {
      const int32_t p = cand_provider[row + j];
      if (p < 0 || owner[p] >= 0) continue;
      const float c = cand_cost[row + j];
      if (c < best) {
        best = c;
        best_p = p;
      }
    }
    if (best_p >= 0 && best < kInfeasible * 0.5f) {
      owner[best_p] = t;
      p4t[t] = best_p;
    }
  }

  int32_t assigned = 0;
  for (int32_t t = 0; t < T; ++t) {
    out_provider_for_task[t] = p4t[t];
    if (p4t[t] >= 0) ++assigned;
    if (oc) {
      // carried-vs-fresh retirement is decided BEFORE retired_io is
      // overwritten below: a task that entered retired and recorded no
      // fresh cause this solve is the stale-retired class
      uint8_t code;
      if (p4t[t] >= 0) {
        code = 0;  // assigned (bid, seed carry, or cleanup seat)
      } else {
        // a row whose every slot is empty OR infeasible-cost has no
        // feasible candidates, whatever the bid loop called it (an
        // infeasible-cost edge parks as "priced out" there because the
        // classification would cost a compare per slot per round; here
        // it is one scan per UNASSIGNED task at exit)
        bool any_feas = false;
        const int64_t row = static_cast<int64_t>(t) * K;
        for (int32_t j = 0; j < K; ++j) {
          const int32_t p = cand_provider[row + j];
          if (p >= 0 && cand_cost[row + j] < kInfeasible * 0.5f) {
            any_feas = true;
            break;
          }
        }
        if (!any_feas) {
          code = 1;  // no feasible candidates at all
        } else if (cause[t] != 0) {
          code = cause[t];  // retired THIS solve: no_candidates / give-up
        } else if (retired_io[t]) {
          code = 3;  // carried (stale) retirement, untouched this solve
        } else {
          code = 2;  // open at exit: outbid / event budget exhausted
        }
      }
      outcome_out[t] = code;
    }
    // the RAW flag is carried (a cleanup-seated retired task stays
    // retired): masking by seat here would launder the flag away and
    // re-open the task every warm solve — see the seeding note above
    retired_io[t] = retired[t];
    if (st && retired[t]) ++stats_out[9];
  }
  std::memcpy(price_io, price.data(), static_cast<size_t>(P) * 4);
  if (st) stats_out[8] = now_ns() - t_phase;
  if (margin_out != nullptr) {
    // winner margin vs runner-up at FINAL prices, one O(T*K) post-pass
    // on the calling thread — reads only converged state, writes only
    // margin_out, so the matching/prices are untouched by construction.
    // The same walk accumulates the DUALITY-GAP certificate (stats
    // slots [11] plan cost, [12] reachable-idle price, [13] eps-CS
    // slack, all 1e-6 cost units):
    //   gap = cs_slack + idle_price
    // bounds the plan's distance from the optimal assignment of the
    // same task set on the same candidate support. The certificate's
    // dual point uses prices CAPPED at the give-up magnitude — any
    // nonnegative dual vector certifies, and the cap strips the
    // single-option bid floor's ~1e8 price spikes (real competitive
    // prices never exceed willingness-to-pay, which give_up bounds)
    // without loosening converged marketplaces, where every price is
    // already below it. Margins stay RAW: attribution reports the
    // price the economy actually charged.
    if (st) t_phase = now_ns();
    const float cert_cap = 2.0f * max_cost + 10.0f;
    // capped dual point hoisted to one min per PROVIDER (the per-edge
    // min was a measurable share of the serial pass at 16k)
    std::vector<float> capped(P);
    for (int32_t p = 0; p < P; ++p) capped[p] = std::min(price[p], cert_cap);
    // reach marks feed only the idle-price addend, so busy providers —
    // nearly every edge of a converged marketplace — never store;
    // relaxed atomics make the surviving same-value marks race-free
    std::unique_ptr<std::atomic<uint8_t>[]> reach;
    if (st) {
      reach.reset(new std::atomic<uint8_t>[P]);
      for (int32_t p = 0; p < P; ++p)
        reach[p].store(0, std::memory_order_relaxed);
    }
    // FIXED-size chunks, each writing its own double partials, summed in
    // chunk order by the caller: the certificate is bit-identical for
    // every thread count (which thread computes a chunk never affects
    // its value), exactly the bid loop's invariance argument
    constexpr int32_t kCertChunk = 2048;
    const int32_t n_chunks = (T + kCertChunk - 1) / kCertChunk;
    std::vector<double> chunk_cost(n_chunks, 0.0);
    std::vector<double> chunk_slack(n_chunks, 0.0);
    std::atomic<int32_t> next_chunk{0};
    const auto cert_body = [&](int) {
      for (;;) {
        const int32_t ci =
            next_chunk.fetch_add(1, std::memory_order_relaxed);
        if (ci >= n_chunks) break;
        const int32_t lo = ci * kCertChunk;
        const int32_t hi = std::min(lo + kCertChunk, T);
        double pc = 0.0, sl = 0.0;
        for (int32_t t = lo; t < hi; ++t) {
          const int32_t seat = p4t[t];
          if (seat < 0) {
            margin_out[t] = 0.0f;
            continue;
          }
          float vseat = kNeg, vother = kNeg;
          float seat_c = kInfeasible;
          double best_adj = kInfeasible;
          const int64_t row = static_cast<int64_t>(t) * K;
          for (int32_t j = 0; j < K; ++j) {
            const int32_t p = cand_provider[row + j];
            if (p < 0) continue;
            const float c = cand_cost[row + j];
            const float v = -c - price[p];
            if (p == seat) {
              if (v > vseat) {
                vseat = v;
                seat_c = c;  // cheapest seat slot (same price => min c)
              }
            } else if (v > vother) {
              vother = v;
            }
            if (st && c < kInfeasible * 0.5f) {
              const double adj = c + static_cast<double>(capped[p]);
              if (adj < best_adj) best_adj = adj;
              if (owner[p] < 0)
                reach[p].store(1, std::memory_order_relaxed);
            }
          }
          if (vother < -1e8f) vother = -1e8f;  // single-option floor
          margin_out[t] = vseat - vother;
          if (st && seat_c < kInfeasible * 0.5f) {
            pc += seat_c;
            const double seat_adj =
                seat_c + static_cast<double>(capped[seat]);
            if (seat_adj > best_adj) sl += seat_adj - best_adj;
          }
        }
        chunk_cost[ci] = pc;
        chunk_slack[ci] = sl;
      }
    };
    if (pool != nullptr)
      pool->run(cert_body);
    else
      cert_body(0);
    if (st) {
      double plan_cost = 0.0, cs_slack = 0.0;
      for (int32_t ci = 0; ci < n_chunks; ++ci) {
        plan_cost += chunk_cost[ci];
        cs_slack += chunk_slack[ci];
      }
      double idle = 0.0;
      for (int32_t p = 0; p < P; ++p) {
        if (reach[p].load(std::memory_order_relaxed) && owner[p] < 0)
          idle += capped[p];
      }
      stats_out[11] = llround(plan_cost * 1e6);
      stats_out[12] = llround(idle * 1e6);
      stats_out[13] = llround(cs_slack * 1e6);
      stats_out[10] = now_ns() - t_phase;
    }
  }
  delete pool;
  return assigned;
}

// ---------------------------------------------------------------------------
// Sparse multi-threaded Sinkhorn (engine=sinkhorn-mt): log-domain entropic
// OT restricted to the top-K candidate edges. The blocked JAX kernel
// (ops/blocked.py sinkhorn_potentials_blocked) pays O(P*T) dense tile work
// per iteration — ~10^10 cell updates per sweep at 100k x 100k, which is
// what killed the round-5 ladder-#3 attempt (rc=143). This engine iterates
// ONLY over the nnz = T*K candidate edges (~8M at 100k with K_eff=80):
//
//   row (task) update      g_t = eps*(log_b - lse_j((f_{p_tj} - c_tj)/eps))
//                          task-chunked across threads; each task's K-entry
//                          logsumexp is computed serially by one thread.
//   column (provider) update  over a CSR transpose (provider-major edge
//                          lists, built once per call by a counting sort in
//                          ascending edge order): provider-chunked across
//                          threads, each provider's reduction serial.
//
// DETERMINISM: every row/column is reduced start-to-finish by exactly one
// thread in a fixed (ascending-edge) order, so chunk boundaries — and
// therefore the thread count — cannot change a single bit of the result.
// Math is double internally with potentials stored f32 after each update
// (the same rounding schedule as the NumPy reference in ops/sparse.py, so
// parity is exact up to libm exp/log ulps).
//
// Potentials f[P] (providers), g[T] (tasks) are DUAL potentials in cost
// units: they carry unchanged across eps-annealing phases and across warm
// re-solves after churn (the plan exp((f+g-c)/eps) is invariant under the
// uniform shift (f-s, g+s), mirroring the warm auction's price-downshift
// soundness argument). Marginals are the balanced uniform marginals of
// ops/blocked.py: a_p = m/np_valid, b_t = m/nt_valid, m = min(np, nt)
// over rows/columns with at least one feasible edge.
//
// One eps phase per call: iterate until the provider-marginal drift
// max_p |sum_t pi_pt - a_p| / a_p falls below tol or max_iters runs out
// (task marginals are exact after every g update by construction). The
// caller loops the anneal schedule (native.sinkhorn_sparse_anneal), which
// also gives per-phase wall-clock for free. Returns iterations run.
// stats_out: nullable, kEngineStatsSlots i64 slots —
//   [0] iterations   [1] CSR-transpose build ns   [2] f-update ns
//   [3] g-update ns  [4] marginal-drift check ns  [5] nnz edges
//   [6] outcome/margin pass ns
// Accumulated on the calling thread only; null skips every clock read.
//
// outcome_out: nullable [T] u8 — per-task support taxonomy at the
//   ENTROPIC layer (the injective seat taxonomy comes from the auction
//   referee downstream): 0 = the task has feasible candidate support,
//   1 = no feasible candidates at all (the transport plan cannot touch
//   it). margin_out: nullable [T] f32 — the entropic argmax margin in
//   cost units, best vs runner-up f_p - c over the task's feasible
//   candidates at the FINAL potentials (runner-up floored at -1e8 like
//   the auction's single-option floor; 0 for unsupported tasks). One
//   O(T*K) post-pass on the calling thread; null means zero overhead
//   and the potentials are bit-identical either way.
int32_t sinkhorn_sparse_mt(const int32_t* cand_provider,
                           const float* cand_cost, int32_t P, int32_t T,
                           int32_t K, float eps, int32_t max_iters, float tol,
                           int32_t threads, float* f_io, float* g_io,
                           float* out_err, int64_t* stats_out,
                           uint8_t* outcome_out, float* margin_out) {
  const bool st = stats_out != nullptr;
  if (st) std::memset(stats_out, 0, kEngineStatsSlots * 8);
  int64_t t_phase = st ? now_ns() : 0;
  const int64_t slots = static_cast<int64_t>(T) * K;
  // CSR transpose: provider-major edge lists in ascending edge order
  // (counting sort with a sequential fill — the fill order is what makes
  // the per-provider reduction order thread-count independent).
  std::vector<int64_t> col_ptr(static_cast<size_t>(P) + 1, 0);
  std::vector<uint8_t> col_any(T, 0);
  for (int64_t e = 0; e < slots; ++e) {
    const int32_t p = cand_provider[e];
    if (p < 0 || p >= P || cand_cost[e] >= kInfeasible * 0.5f) continue;
    ++col_ptr[p + 1];
    col_any[e / K] = 1;
  }
  for (int32_t p = 0; p < P; ++p) col_ptr[p + 1] += col_ptr[p];
  std::vector<int64_t> col_edge(col_ptr[P]);
  std::vector<int32_t> col_task(col_ptr[P]);  // task id per CSR slot:
  // hoists the e / K division out of the O(nnz * iters) hot loops (the
  // counting sort visits every edge anyway)
  {
    std::vector<int64_t> fill(col_ptr.begin(), col_ptr.end() - 1);
    for (int64_t e = 0; e < slots; ++e) {
      const int32_t p = cand_provider[e];
      if (p < 0 || p >= P || cand_cost[e] >= kInfeasible * 0.5f) continue;
      col_task[fill[p]] = static_cast<int32_t>(e / K);
      col_edge[fill[p]++] = e;
    }
  }
  int64_t np_valid = 0, nt_valid = 0;
  for (int32_t p = 0; p < P; ++p) np_valid += col_ptr[p + 1] > col_ptr[p];
  for (int32_t t = 0; t < T; ++t) nt_valid += col_any[t];
  if (st) {
    stats_out[1] = now_ns() - t_phase;
    stats_out[5] = col_ptr[P];
  }
  if (np_valid == 0 || nt_valid == 0) {
    if (out_err != nullptr) *out_err = 0.0f;
    for (int32_t t = 0; t < T; ++t) {
      if (outcome_out != nullptr) outcome_out[t] = col_any[t] ? 0 : 1;
      if (margin_out != nullptr) margin_out[t] = 0.0f;
    }
    return 0;
  }
  const double m = static_cast<double>(std::min(np_valid, nt_valid));
  const double log_a = std::log(m / static_cast<double>(np_valid));
  const double log_b = std::log(m / static_cast<double>(nt_valid));
  const double a_mass = m / static_cast<double>(np_valid);
  const double inv_eps = 1.0 / static_cast<double>(eps);
  const double deps = static_cast<double>(eps);

  const int nt = resolve_threads(threads, std::max(P, T));
  // same wakeup-amortization threshold family as the -mt auction: tiny
  // instances run inline on the caller (identical values either way)
  constexpr int32_t kParMinRows = 4096;
  HelperPool* pool = nullptr;
  if (nt > 1 && std::max(P, T) >= kParMinRows) pool = new HelperPool(nt - 1);
  const auto par_rows = [&](int32_t n,
                            const std::function<void(int, int32_t, int32_t)>&
                                body) {
    if (pool == nullptr || n < kParMinRows) {
      body(0, 0, n);
      return;
    }
    const int32_t chunk = (n + nt - 1) / nt;
    pool->run([&](int tid) {
      const int32_t lo = std::min<int32_t>(tid * chunk, n);
      const int32_t hi = std::min<int32_t>(lo + chunk, n);
      if (lo < hi) body(tid, lo, hi);
    });
  };

  std::vector<double> err_tid(nt, 0.0);
  int32_t it = 0;
  double err = 0.0, prev_err = HUGE_VAL;
  int stall = 0;
  while (it < max_iters) {
    ++it;
    if (st) t_phase = now_ns();
    // ---- f (provider/column) update over the CSR transpose
    par_rows(P, [&](int, int32_t lo, int32_t hi) {
      for (int32_t p = lo; p < hi; ++p) {
        const int64_t b = col_ptr[p], e_end = col_ptr[p + 1];
        if (b == e_end) continue;  // no edges: potential untouched
        double mx = -HUGE_VAL;
        for (int64_t i = b; i < e_end; ++i) {
          const double v = (static_cast<double>(g_io[col_task[i]]) -
                            static_cast<double>(cand_cost[col_edge[i]])) *
                           inv_eps;
          if (v > mx) mx = v;
        }
        double s = 0.0;
        for (int64_t i = b; i < e_end; ++i) {
          const double v = (static_cast<double>(g_io[col_task[i]]) -
                            static_cast<double>(cand_cost[col_edge[i]])) *
                           inv_eps;
          s += std::exp(v - mx);
        }
        f_io[p] = static_cast<float>(deps * (log_a - (mx + std::log(s))));
      }
    });
    if (st) {
      stats_out[2] += now_ns() - t_phase;
      t_phase = now_ns();
    }
    // ---- g (task/row) update over the [T, K] slot layout
    par_rows(T, [&](int, int32_t lo, int32_t hi) {
      for (int32_t t = lo; t < hi; ++t) {
        if (!col_any[t]) continue;
        const int64_t row = static_cast<int64_t>(t) * K;
        double mx = -HUGE_VAL;
        for (int32_t j = 0; j < K; ++j) {
          const int32_t p = cand_provider[row + j];
          // same edge filter as the CSR build: p >= P guards the f_io
          // read against out-of-range provider ids (caller mismatch
          // between padded candidate lists and an unpadded P)
          if (p < 0 || p >= P ||
              cand_cost[row + j] >= kInfeasible * 0.5f) continue;
          const double v = (static_cast<double>(f_io[p]) -
                            static_cast<double>(cand_cost[row + j])) * inv_eps;
          if (v > mx) mx = v;
        }
        double s = 0.0;
        for (int32_t j = 0; j < K; ++j) {
          const int32_t p = cand_provider[row + j];
          if (p < 0 || p >= P ||
              cand_cost[row + j] >= kInfeasible * 0.5f) continue;
          const double v = (static_cast<double>(f_io[p]) -
                            static_cast<double>(cand_cost[row + j])) * inv_eps;
          s += std::exp(v - mx);
        }
        g_io[t] = static_cast<float>(deps * (log_b - (mx + std::log(s))));
      }
    });
    if (st) {
      stats_out[3] += now_ns() - t_phase;
      t_phase = now_ns();
    }
    // ---- provider-marginal drift (task marginals are exact after g):
    // per-thread maxima merged by max — order-independent, deterministic
    for (int i = 0; i < nt; ++i) err_tid[i] = 0.0;
    par_rows(P, [&](int tid, int32_t lo, int32_t hi) {
      double worst = 0.0;
      for (int32_t p = lo; p < hi; ++p) {
        const int64_t b = col_ptr[p], e_end = col_ptr[p + 1];
        if (b == e_end) continue;
        double s = 0.0;
        const double fp = static_cast<double>(f_io[p]);
        for (int64_t i = b; i < e_end; ++i) {
          s += std::exp((fp + static_cast<double>(g_io[col_task[i]]) -
                         static_cast<double>(cand_cost[col_edge[i]])) *
                        inv_eps);
        }
        const double d = std::fabs(s - a_mass) / a_mass;
        if (d > worst) worst = d;
      }
      if (worst > err_tid[tid]) err_tid[tid] = worst;
    });
    err = 0.0;
    for (int i = 0; i < nt; ++i) err = std::max(err, err_tid[i]);
    if (st) stats_out[4] += now_ns() - t_phase;
    if (err <= static_cast<double>(tol)) break;
    // Stagnation exit: on a candidate support whose uniform marginals are
    // INFEASIBLE (a provider pocket that cannot absorb its share — common
    // on sparse top-K graphs), the potentials drift without bound while
    // the marginal error plateaus above tol. Two consecutive <0.5%-
    // improvement checks (after a settling window — early iterations are
    // legitimately non-monotonic) stop the burn; the plan's argmax
    // structure has long stabilized by then, which is all the rounding
    // referee consumes. Deterministic: err is a pure function of the
    // iteration state.
    if (it >= 8 && err >= 0.995 * prev_err) {
      if (++stall >= 2) break;
    } else {
      stall = 0;
    }
    prev_err = err;
  }
  delete pool;
  if (out_err != nullptr) *out_err = static_cast<float>(err);
  if (outcome_out != nullptr || margin_out != nullptr) {
    // support taxonomy + entropic argmax margin at the final potentials:
    // one O(T*K) pass on the calling thread, results untouched
    if (st) t_phase = now_ns();
    for (int32_t t = 0; t < T; ++t) {
      const bool has = col_any[t] != 0;
      if (outcome_out != nullptr) outcome_out[t] = has ? 0 : 1;
      if (margin_out == nullptr) continue;
      if (!has) {
        margin_out[t] = 0.0f;
        continue;
      }
      const int64_t row = static_cast<int64_t>(t) * K;
      float v1 = kNeg, v2 = kNeg;
      for (int32_t j = 0; j < K; ++j) {
        const int32_t p = cand_provider[row + j];
        if (p < 0 || p >= P ||
            cand_cost[row + j] >= kInfeasible * 0.5f) continue;
        const float v = f_io[p] - cand_cost[row + j];
        if (v > v1) {
          v2 = v1;
          v1 = v;
        } else if (v > v2) {
          v2 = v;
        }
      }
      if (v2 < -1e8f) v2 = -1e8f;  // single-option floor
      margin_out[t] = v1 - v2;
    }
    if (st) stats_out[6] = now_ns() - t_phase;
  }
  if (st) stats_out[0] = it;
  return it;
}

}  // extern "C"
