#!/usr/bin/env python
"""Scheduler-kernel benchmark. Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Measures the batched job<->worker matching throughput on the live accelerator
(the orchestrator hot path: BASELINE.md ladder) against the reference's
algorithmic envelope — a host-side greedy first-fit matcher equivalent to
crates/orchestrator/src/scheduler/mod.rs:26-74 (numpy-vectorized per-task
argmin, which is *generous* to the baseline: the reference re-fetches and
filters all tasks per node heartbeat).

Problem: synthetic marketplace, P providers x T tasks, multi-resource
feature vectors (GPU class/count/memory, CPU, RAM, storage, geo, price),
~uniform compatibility structure from the real compat_mask encoding.

Degraded-mode engine selection (key=value args):

    python bench.py engine=native-mt threads=4

``engine=native`` (default) measures the historical single-threaded C++
fallback; ``engine=native-mt`` measures the multi-threaded engine with a
PIPELINED stage overlap — the next solve's fused cost-build runs on a
worker thread while the current solve's auction runs (ctypes releases the
GIL for the duration of each native call, so the overlap is real). The
reported matching is checked bit-identical against threads=1.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

from protocol_tpu.ops.assign import assign_auction, assign_greedy
from protocol_tpu.ops.cost import INFEASIBLE, CostWeights, cost_matrix
from protocol_tpu.ops.encoding import EncodedProviders, EncodedRequirements
from protocol_tpu.ops.sparse import (
    assign_auction_sparse_scaled,
    candidates_topk_bidir,
)

P, T = 32768, 32768
TOPK = 64
TILE = 2048

# The synthetic marketplace generators live in the flight-recorder
# subsystem (the single source of synthetic populations); re-exported
# here because every bench/script/test historically reaches them as
# ``bench.synth_providers``.
from protocol_tpu.trace.synth import (  # noqa: E402
    MAX_GPU_OPTS,
    MODEL_CLASSES,
    MODEL_WORDS,
    synth_providers,
    synth_requirements,
)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def tpu_match(ep: EncodedProviders, er: EncodedRequirements):
    """Full hot path: streaming BIDIRECTIONAL candidate generation over the
    featurized cost tensor (never materializing [P, T]) + eps-scaled sparse
    frontier auction with cleanup. Reverse (provider->task) edges guarantee
    every provider appears in the candidate graph — forward-only top-k left
    ~9% of providers unreachable at 32k (coverage-capped matching). Host
    loop over jitted phases — each phase executable is cached after warmup."""

    cand_p, cand_c = candidates_topk_bidir(
        ep, er, CostWeights(), k=TOPK, tile=TILE, reverse_r=8, extra=16
    )
    res = assign_auction_sparse_scaled(
        cand_p, cand_c, num_providers=ep.gpu_count.shape[0],
        eps_start=4.0, eps_end=0.05, max_iters_per_phase=400,
    )
    return res.provider_for_task, res.num_assigned()


def salt_providers(ep: EncodedProviders, salt) -> EncodedProviders:
    """Identity-bust one input leaf with a zero-valued on-device add.

    The axon remote-TPU client MEMOIZES executions keyed on (executable,
    input buffer identities) and replays the cached result without running
    anything — measured 0.0 ms for repeat same-buffer calls vs real wall
    for salted ones. A per-iteration distinct salt forces a fresh buffer
    identity (values are bit-identical: + salt*0.0), so every timed
    iteration is a REAL on-chip execution. Host-side uploads are
    content-deduplicated too, so re-device_putting identical bytes does
    NOT bust the cache — the add must happen on device."""
    import dataclasses

    return dataclasses.replace(ep, price=ep.price + jnp.float32(salt) * 0.0)


def cpu_greedy_baseline(cost: np.ndarray) -> tuple[np.ndarray, float]:
    """Reference-equivalent greedy: each task in arrival order takes the
    cheapest free compatible provider."""
    t0 = time.perf_counter()
    avail = np.ones(cost.shape[0], bool)
    out = np.full(cost.shape[1], -1, np.int64)
    for t in range(cost.shape[1]):
        col = np.where(avail, cost[:, t], INFEASIBLE)
        p = int(np.argmin(col))
        if col[p] < INFEASIBLE * 0.5:
            out[t] = p
            avail[p] = False
    return out, time.perf_counter() - t0


def bench_native_mt(ep, er, threads: int, iters: int, st_total: float) -> dict:
    """engine=native-mt: multi-threaded fused pass + deterministic Jacobi
    auction, with the stage boundary OVERLAPPED — iteration i+1's fused
    cost-build runs on a worker thread while iteration i's auction runs on
    the main thread (both native calls drop the GIL). Steady-state
    pipelined wall-clock per solve is the metric; the matching is checked
    bit-identical against the same engine at threads=1."""
    import os
    from concurrent.futures import ThreadPoolExecutor

    from protocol_tpu import native
    from protocol_tpu.ops.cost import CostWeights

    n_threads = threads or (os.cpu_count() or 1)
    w = CostWeights()

    def gen():
        return native.fused_topk_candidates(
            ep, er, w, k=TOPK, threads=n_threads
        )

    with ThreadPoolExecutor(max_workers=1) as ex:
        t0 = time.perf_counter()
        fut = ex.submit(gen)
        for i in range(iters):
            cand_p, cand_c = fut.result()
            if i + 1 < iters:
                fut = ex.submit(gen)  # next cost-build overlaps this auction
            p4t, _, _ = native.auction_sparse_mt(
                cand_p, cand_c, num_providers=P, threads=n_threads
            )
        wall = (time.perf_counter() - t0) / iters
    n_assigned = int((p4t >= 0).sum())
    # determinism referee: the same engine, single thread, must reproduce
    # the matching bit-for-bit (cand structure identity is covered by the
    # parity tests; the auction is the order-sensitive half)
    p4t_ref, _, _ = native.auction_sparse_mt(
        cand_p, cand_c, num_providers=P, threads=1
    )
    bit_identical = bool(np.array_equal(p4t, p4t_ref))
    log(
        f"native-mt pipelined end-to-end ({n_threads} threads): "
        f"{wall * 1e3:.1f} ms/solve ({n_assigned / wall:,.0f} assignments/s; "
        f"{st_total / wall:.2f}x single-threaded engine; "
        f"bit-identical to threads=1: {bit_identical})"
    )
    return {
        "wall_s": wall,
        "assigned": n_assigned,
        "threads": n_threads,
        "bit_identical": bit_identical,
    }


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _churn_providers(p_cols, rng, churn: float) -> None:
    """Mutate ~churn of the provider rows in place (price + load — the
    per-heartbeat drift every real fleet reports)."""
    n = p_cols["price"].shape[0]
    rows = rng.choice(n, max(1, int(n * churn)), replace=False)
    p_cols["price"][rows] = rng.uniform(0.5, 4.0, rows.size).astype(np.float32)
    p_cols["load"][rows] = rng.uniform(0, 1, rows.size).astype(np.float32)


def run_wire_bench(
    P: int = 16384,
    T: int = 16384,
    churn: float = 0.01,
    ticks: int = 5,
    warmup: int = 3,
    threads: int = 0,
    seed: int = 0,
    chunk_bytes: int = 1 << 20,
    modes: tuple = ("v1", "v2"),
    trace_path: str = "",
) -> dict:
    """Loopback wire-path benchmark: the scheduler seam end-to-end
    (client serialize + RPC + server decode + warm native-mt solve) under
    steady-state churn, v1 full-snapshot unary vs v2 delta sessions.

    Both modes run against a FRESH server with the same synthetic
    marketplace and the same churn sequence (same rng seeds): one untimed
    cold tick, then ``warmup`` untimed churn ticks (the post-cold
    adaptation transient, where contested near-tie seats price out), then
    ``ticks`` timed steady-state ticks. The difference between modes is
    pure wire protocol — the warm solve behind both is the same arena.
    Returns per-tick wall/bytes/assigned per mode plus the v1/v2 speedup
    and bytes ratio, and the server-side seam metrics scraped from
    Health.

    With ``trace_path`` set, the population AND the per-tick churn come
    from a recorded/synthetic flight-recorder trace instead of the
    inline generator — the same captured workload both modes (and every
    future bench run) consume, instead of an unshareable rng sequence."""
    from protocol_tpu.ops.cost import CostWeights
    from protocol_tpu.proto import scheduler_pb2 as pbs
    from protocol_tpu.proto import wire as wirelib
    from protocol_tpu.services.scheduler_grpc import (
        SchedulerBackendClient,
        encoded_to_proto,
        encoded_to_proto_v2,
        serve,
    )

    kernel = f"native-mt:{threads}" if threads else "native-mt"
    w = CostWeights()
    trace_deltas = None
    if trace_path:
        from protocol_tpu.trace import format as tfmt

        tr = tfmt.read_trace(trace_path)
        if tr.snapshot is None:
            raise SystemExit(f"{trace_path}: no snapshot frame")
        P, T = tr.snapshot.n_providers, tr.snapshot.n_tasks
        trace_deltas = tr.deltas
        if not trace_deltas:
            raise SystemExit(
                f"{trace_path} holds no delta ticks (snapshot only) — "
                "the wire bench measures steady-state ticks; synth a "
                "trace with --ticks >= 1"
            )
        if warmup + ticks > len(trace_deltas):
            ticks = max(len(trace_deltas) - warmup, 1)
            warmup = max(min(warmup, len(trace_deltas) - ticks), 0)
            log(
                f"trace holds {len(trace_deltas)} ticks: clamped to "
                f"warmup={warmup} ticks={ticks}"
            )
    out: dict = {
        "P": P, "T": T, "churn": churn, "ticks": ticks,
        "kernel": kernel, "modes": {},
    }
    if trace_path:
        out["trace"] = trace_path

    def _apply_tick(i: int, p_cols, r_cols, churn_rng) -> None:
        """Mutate the columns for tick i (1-based): the trace's recorded
        delta when one is loaded, else synthetic price/load churn."""
        if trace_deltas is not None:
            d = trace_deltas[i - 1]
            for rows, delta, cols in (
                (d.provider_rows, d.p_cols, p_cols),
                (d.task_rows, d.r_cols, r_cols),
            ):
                for name, vals in delta.items():
                    cols[name][rows] = vals
        else:
            _churn_providers(p_cols, churn_rng, churn)

    for mode in modes:
        port = _free_port()
        server = serve(f"127.0.0.1:{port}")
        client = SchedulerBackendClient(f"127.0.0.1:{port}")
        if trace_deltas is not None:
            p_cols = {k: v.copy() for k, v in tr.snapshot.p_cols.items()}
            r_cols = {k: v.copy() for k, v in tr.snapshot.r_cols.items()}
        else:
            rng = np.random.default_rng(seed)
            ep = synth_providers(rng, P)
            er = synth_requirements(rng, T)
            p_cols = wirelib.canon_columns(ep, wirelib.P_WIRE_DTYPES)
            r_cols = wirelib.canon_columns(er, wirelib.R_WIRE_DTYPES)
        full = wirelib.take_rows  # ns view over all rows
        churn_rng = np.random.default_rng(seed + 1)
        tick_ms: list[float] = []
        tick_bytes: list[int] = []
        tick_assigned: list[int] = []
        if mode == "v1":
            # untimed cold tick: arena build + jit-free native warmup
            req = encoded_to_proto(
                full(p_cols, slice(None)), full(r_cols, slice(None)), w,
                kernel=kernel, top_k=64, eps=0.02,
            )
            client.assign(req, timeout=600)
            for i in range(warmup + ticks):
                _apply_tick(i + 1, p_cols, r_cols, churn_rng)
                t0 = time.perf_counter()
                req = encoded_to_proto(
                    full(p_cols, slice(None)), full(r_cols, slice(None)),
                    w, kernel=kernel, top_k=64, eps=0.02,
                )
                resp = client.assign(req, timeout=600)
                if i < warmup:
                    continue
                tick_ms.append((time.perf_counter() - t0) * 1e3)
                tick_bytes.append(req.ByteSize() + resp.ByteSize())
                tick_assigned.append(int(resp.num_assigned))
        else:
            fp = wirelib.epoch_fingerprint(
                p_cols, r_cols, w, kernel, 64, 0.02, 0
            )
            reqv2 = encoded_to_proto_v2(
                full(p_cols, slice(None)), full(r_cols, slice(None)), w,
                kernel=kernel, top_k=64, eps=0.02,
            )
            resp = client.open_session(
                wirelib.chunk_snapshot(
                    "bench", fp, reqv2, chunk_bytes=chunk_bytes
                ),
                timeout=600,
            )
            assert resp.ok, resp.error
            prev = {k: v.copy() for k, v in p_cols.items()}
            prev_r = {k: v.copy() for k, v in r_cols.items()}
            for tick in range(1, warmup + ticks + 1):
                _apply_tick(tick, p_cols, r_cols, churn_rng)
                t0 = time.perf_counter()
                # the timed tick includes the client-side churn scan: the
                # column diff is part of what v2 pays that v1 does not
                rows = wirelib.dirty_rows(p_cols, prev)
                trows = wirelib.dirty_rows(r_cols, prev_r)
                dreq = pbs.AssignDeltaRequest(
                    session_id="bench", epoch_fingerprint=fp, tick=tick
                )
                if rows.size:
                    dreq.provider_rows.CopyFrom(wirelib.blob(rows, np.int32))
                    dreq.providers.CopyFrom(
                        wirelib.encode_providers_v2(
                            wirelib.take_rows(p_cols, rows)
                        )
                    )
                if trows.size:
                    dreq.task_rows.CopyFrom(wirelib.blob(trows, np.int32))
                    dreq.requirements.CopyFrom(
                        wirelib.encode_requirements_v2(
                            wirelib.take_rows(r_cols, trows)
                        )
                    )
                dresp = client.assign_delta(dreq, timeout=600)
                assert dresp.session_ok, dresp.error
                prev = {k: v.copy() for k, v in p_cols.items()}
                prev_r = {k: v.copy() for k, v in r_cols.items()}
                if tick <= warmup:
                    continue
                tick_ms.append((time.perf_counter() - t0) * 1e3)
                tick_bytes.append(dreq.ByteSize() + dresp.ByteSize())
                tick_assigned.append(int(dresp.result.num_assigned))
        h = client.health()
        seam = {s.name: s.value for s in h.seam_metrics}
        # latency DISTRIBUTION per tick, not just means: headline p50/p99
        # are exact (the raw walls are in hand — np.percentile), and the
        # obs LatencyHistogram snapshot rides alongside (the same
        # estimator the per-session registries use at fleet scale, where
        # raw samples can't be kept)
        from protocol_tpu.obs.metrics import percentiles_ms

        pct = percentiles_ms(tick_ms)
        p50 = round(float(np.percentile(tick_ms, 50)), 2)
        p99 = round(float(np.percentile(tick_ms, 99)), 2)
        out["modes"][mode] = {
            "tick_ms": [round(x, 2) for x in tick_ms],
            "mean_tick_ms": round(sum(tick_ms) / len(tick_ms), 2),
            "median_tick_ms": round(float(np.median(tick_ms)), 2),
            "min_tick_ms": round(min(tick_ms), 2),
            "p50_tick_ms": p50,
            "p99_tick_ms": p99,
            "tick_percentiles": pct,
            "mean_tick_bytes": int(sum(tick_bytes) / len(tick_bytes)),
            "tick_assigned": tick_assigned,
            "server_seam": seam,
        }
        log(
            f"wire={mode}: mean {out['modes'][mode]['mean_tick_ms']:.1f} "
            f"ms/tick (p50 {p50}, p99 {p99}), "
            f"{out['modes'][mode]['mean_tick_bytes']:,} B/tick"
        )
        client.close()
        server.stop(grace=None)
    if "v1" in out["modes"] and "v2" in out["modes"]:
        # the headline (and CI-gated) speedup is MEDIAN tick vs median
        # tick: the warm arena's dual-refresh cycle makes individual
        # ticks bimodal (fast shielded ticks vs post-refresh adaptation
        # ticks), and a mean over a short window is noisy about where
        # the cycle landed. The mean-based number rides along.
        v1md = out["modes"]["v1"]["median_tick_ms"]
        v2md = out["modes"]["v2"]["median_tick_ms"]
        out["v2_speedup"] = round(v1md / v2md, 2)
        out["v2_speedup_mean"] = round(
            out["modes"]["v1"]["mean_tick_ms"]
            / out["modes"]["v2"]["mean_tick_ms"],
            2,
        )
        out["v2_bytes_ratio"] = round(
            out["modes"]["v1"]["mean_tick_bytes"]
            / max(out["modes"]["v2"]["mean_tick_bytes"], 1),
            1,
        )
        log(
            f"wire v2 delta tick: {out['v2_speedup']}x faster (median; "
            f"mean {out['v2_speedup_mean']}x), "
            f"{out['v2_bytes_ratio']}x fewer bytes than v1 full snapshot"
        )
    return out


def device_healthy(timeout: float = 120.0) -> bool:
    """Probe the default backend with a wall-clock bound, in a SUBPROCESS:
    the remote-TPU tunnel can wedge (ops hang indefinitely), and a hung
    in-process probe would hold jax's global backend-init lock, blocking
    the CPU fallback too. A killed child leaves this process clean."""
    import subprocess

    code = (
        "import jax, jax.numpy as jnp;"
        "x = jnp.ones((8, 8)) @ jnp.ones((8, 8));"
        "jax.block_until_ready(x);"
        "print('DEVICE_OK')"
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        return "DEVICE_OK" in out.stdout
    except subprocess.TimeoutExpired:
        return False


def run_quality_bench(
    P: int = 4096,
    T: int = 4096,
    churn: float = 0.01,
    ticks: int = 12,
    warmup: int = 2,
    threads: int = 0,
    engine: str = "auction",
    seed: int = 0,
) -> dict:
    """Warm-chain arena bench WITH the decision-quality plane on: one
    cold solve, ``warmup`` untimed churn ticks, then ``ticks`` timed
    ticks at ``churn`` provider churn — reporting headline p50/p99 tick
    walls, assigned fraction, and the quality scalars (certified
    duality gap, plan churn ratio, starvation, unassigned causes) the
    r06 bench round joins on."""
    import dataclasses

    from protocol_tpu.native.arena import NativeSolveArena
    from protocol_tpu.obs.metrics import percentiles_ms

    rng = np.random.default_rng(seed)
    ep = synth_providers(rng, P)
    er = synth_requirements(rng, T)
    arena = NativeSolveArena(
        threads=threads, engine="sinkhorn" if engine == "sinkhorn" else
        "auction",
    )
    churn_rng = np.random.default_rng(seed + 1)

    def _tick(e):
        price = np.array(e.price, copy=True)
        load = np.array(e.load, copy=True)
        rows = churn_rng.choice(P, max(1, int(P * churn)), replace=False)
        price[rows] = np.round(
            np.clip(price[rows] + churn_rng.uniform(-0.5, 0.5, rows.size),
                    0.05, None), 4
        ).astype(price.dtype)
        load[rows] = np.clip(
            load[rows] + churn_rng.uniform(-0.2, 0.2, rows.size)
            .astype(load.dtype), 0.0, 1.0
        )
        return dataclasses.replace(e, price=price, load=load)

    t0 = time.perf_counter()
    p4t = arena.solve(ep, er, CostWeights())
    cold_ms = (time.perf_counter() - t0) * 1e3
    for _ in range(warmup):
        ep = _tick(ep)
        arena.solve(ep, er, CostWeights())
    walls, quality_ticks = [], []
    for _ in range(ticks):
        ep = _tick(ep)
        t0 = time.perf_counter()
        p4t = arena.solve(ep, er, CostWeights())
        walls.append((time.perf_counter() - t0) * 1e3)
        quality_ticks.append({
            k: v for k, v in arena.last_stats.items()
            if isinstance(v, (int, float, bool))
        })
    assigned = int((p4t[:T] >= 0).sum())
    from protocol_tpu.obs.quality import aggregate_quality

    pct = percentiles_ms(walls)
    return {
        "P": P, "T": T, "churn": churn, "ticks": ticks,
        "engine": engine, "threads": arena.threads,
        "cold_ms": round(cold_ms, 3),
        "p50_tick_ms": pct["p50_ms"],
        "p99_tick_ms": pct["p99_ms"],
        "mean_tick_ms": round(float(np.mean(walls)), 3),
        "assigned_frac": round(assigned / T, 6),
        # the shared canonical roll-up (same vocabulary as replay
        # reports and obs report — cross-round joins stay schema-stable)
        "quality": aggregate_quality(quality_ticks) or {},
    }


def run_jax_arena_bench(
    n: int = 16384,
    devices: int = 0,
    churn: float = 0.01,
    ticks: int = 3,
    seed: int = 0,
) -> dict:
    """``engine=jax[:D]`` bench: the first-class jax arena's cold solve
    (compiled — compile is paid once untimed, like every other row) and
    a warm chain at ``churn`` REQUIREMENT churn riding the churn-masked
    structure repair (ISSUE 18 — warm ticks pay O(churn) repair, never
    a regen: asserted via ``cand_cold_passes``). Requirement-side churn
    is the informative warm case for this engine: provider repricing at
    k=64 honestly dirties ~half the candidate rows (every row listing a
    repriced provider) — that case is covered by the ``--cand`` gate's
    native rows and the repair-parity tests. Every tick reports its
    gen/solve wall split (cold and warm) in the artifact JSON."""
    import dataclasses

    from protocol_tpu.parallel.jax_arena import JaxSolveArena

    rng = np.random.default_rng(seed)
    ep = synth_providers(rng, n)
    er = synth_requirements(rng, n)
    w = CostWeights()
    arena = JaxSolveArena(devices=devices)
    arena.solve(ep, er, w)  # compile pass, untimed
    arena.invalidate()
    t0 = time.perf_counter()
    p4t = arena.solve(ep, er, w)
    cold_s = time.perf_counter() - t0
    cold_solve_ms = arena.last_stats["solve_ms"]
    cold_gen_ms = arena.last_stats["gen_ms"]
    sharded = bool(arena.last_stats.get("gen_sharded"))
    churn_rng = np.random.default_rng(seed + 1)
    walls, gens, solves, tick_detail = [], [], [], []
    cold_passes_warm = 0
    for _ in range(ticks):
        rows = churn_rng.choice(n, max(1, int(n * churn)), replace=False)
        ram = np.array(er.ram_mb, copy=True)
        ram[rows] = np.maximum(
            256,
            (ram[rows] * churn_rng.uniform(0.8, 1.25, rows.size)).astype(
                ram.dtype
            ),
        )
        er = dataclasses.replace(er, ram_mb=ram)
        t0 = time.perf_counter()
        p4t = arena.solve(ep, er, w)
        walls.append((time.perf_counter() - t0) * 1e3)
        s = arena.last_stats
        gens.append(s["gen_ms"])
        solves.append(s["solve_ms"])
        cold_passes_warm += int(s.get("cand_cold_passes", 0))
        tick_detail.append({
            "wall_ms": round(walls[-1], 3),
            "gen_ms": s["gen_ms"],
            "solve_ms": s["solve_ms"],
            "cand_cold_passes": s.get("cand_cold_passes"),
            "repair_rows": s.get("repair_rows"),
            "repair_providers": s.get("repair_providers"),
            "visited_cells_frac": s.get("visited_cells_frac"),
            "changed_rows": s.get("changed_rows"),
        })
    warm_ms = float(np.median(walls))
    return {
        "n": n,
        "devices": arena._devices_effective,
        "gen_sharded": sharded,
        "cold_ms": round(cold_s * 1e3, 3),
        "cold_gen_ms": cold_gen_ms,
        "cold_solve_ms": cold_solve_ms,
        "warm_median_ms": round(warm_ms, 3),
        "warm_gen_median_ms": round(float(np.median(gens)), 3),
        "warm_solve_median_ms": round(float(np.median(solves)), 3),
        "warm_wall_speedup": round(cold_s * 1e3 / max(warm_ms, 1e-9), 2),
        "warm_gen_speedup": round(
            cold_gen_ms / max(float(np.median(gens)), 1e-9), 2
        ),
        "warm_solve_speedup": round(
            cold_solve_ms / max(float(np.median(solves)), 1e-9), 2
        ),
        "warm_cand_cold_passes": cold_passes_warm,
        "warm_ticks": tick_detail,
        "assigned_frac": round(int((p4t >= 0).sum()) / n, 6),
    }


def parse_kv_args(argv: list[str]) -> dict[str, str]:
    """``engine=native-mt threads=4``-style arguments (ignores flags)."""
    out: dict[str, str] = {}
    for a in argv:
        k, sep, v = a.partition("=")
        if sep:
            out[k] = v
    return out


def main() -> None:
    global P, T, TILE
    args = parse_kv_args(sys.argv[1:])
    if args.get("quality"):
        # quality=1 [p= t= churn= ticks= threads= engine= out=]: the
        # r06 bench round — warm-chain arena ticks with the decision-
        # quality plane on. Stable metric name, platform field per the
        # PR 3 convention, quality scalars nested so cross-round joins
        # (BENCH_r0*.json) survive schema growth.
        jax.config.update("jax_platforms", "cpu")
        res = run_quality_bench(
            P=int(args.get("p", "4096")),
            T=int(args.get("t", "4096")),
            churn=float(args.get("churn", "0.01")),
            ticks=int(args.get("ticks", "12")),
            threads=int(args.get("threads", "0") or 0),
            engine=args.get("engine", "auction"),
        )
        headline = {
            "metric": (
                f"warm_tick_quality_{res['P']}x{res['T']}_"
                f"churn{res['churn']}"
            ),
            "platform": "native_cpu_engine_requested",
            "value": res["p50_tick_ms"],
            "unit": "ms_per_warm_tick_p50",
            "p50_tick_ms": res["p50_tick_ms"],
            "p99_tick_ms": res["p99_tick_ms"],
            "assigned_frac": res["assigned_frac"],
            "quality": res["quality"],
        }
        out_path = args.get("out")
        if out_path:
            with open(out_path, "w") as fh:
                json.dump({**headline, "detail": res}, fh, indent=1)
                fh.write("\n")
            log(f"wrote {out_path}")
        print(json.dumps(headline))
        return
    wire = args.get("wire")
    if wire:
        # wire=v1|v2|both: loopback wire-path bench (the scheduler seam
        # itself, not the kernel) — steady-state churn ticks over gRPC
        if wire not in ("v1", "v2", "both"):
            raise SystemExit(f"unknown wire mode {wire!r} (want v1|v2|both)")
        jax.config.update("jax_platforms", "cpu")
        modes = ("v1", "v2") if wire == "both" else (wire,)
        res = run_wire_bench(
            P=int(args.get("p", "16384")),
            T=int(args.get("t", "16384")),
            churn=float(args.get("churn", "0.01")),
            ticks=int(args.get("ticks", "5")),
            warmup=int(args.get("warmup", "3")),
            threads=int(args.get("threads", "0") or 0),
            modes=modes,
            # trace=<path>: consume a flight-recorder trace (population +
            # churn sequence) instead of generating inline
            trace_path=args.get("trace", ""),
        )
        out_path = args.get("out")
        if out_path:
            with open(out_path, "w") as fh:
                json.dump(res, fh, indent=1)
            log(f"wrote {out_path}")
        if wire == "both":
            print(json.dumps({
                "metric": (
                    f"wire_v2_delta_tick_speedup_{res['P']}x{res['T']}_"
                    f"churn{res['churn']}"
                ),
                "value": res["v2_speedup"],
                "unit": "x_vs_v1_full_snapshot",
                "bytes_ratio": res["v2_bytes_ratio"],
                "v1_mean_tick_ms": res["modes"]["v1"]["mean_tick_ms"],
                "v2_mean_tick_ms": res["modes"]["v2"]["mean_tick_ms"],
                "v1_p50_tick_ms": res["modes"]["v1"]["p50_tick_ms"],
                "v1_p99_tick_ms": res["modes"]["v1"]["p99_tick_ms"],
                "v2_p50_tick_ms": res["modes"]["v2"]["p50_tick_ms"],
                "v2_p99_tick_ms": res["modes"]["v2"]["p99_tick_ms"],
            }))
        else:
            m = res["modes"][wire]
            print(json.dumps({
                "metric": (
                    f"wire_{wire}_tick_{res['P']}x{res['T']}_"
                    f"churn{res['churn']}"
                ),
                "value": m["mean_tick_ms"],
                "unit": "ms_per_tick",
                "p50_tick_ms": m["p50_tick_ms"],
                "p99_tick_ms": m["p99_tick_ms"],
                "mean_tick_bytes": m["mean_tick_bytes"],
            }))
        return
    engine = args.get("engine", "native")
    if engine.partition(":")[0] == "jax":
        # engine=jax[:D] [n= churn= ticks= out=]: the first-class jax
        # arena. Provenance (backend platform + effective device count)
        # rides in the "platform" field per the PR 3 convention; the
        # metric NAME stays stable across hosts and meshes.
        suffix = engine.partition(":")[2]
        if suffix and not suffix.isdigit():
            raise SystemExit(
                f"bad jax device suffix {suffix!r} (want jax[:D])"
            )
        if not device_healthy():
            log("accelerator unreachable: jax arena on the CPU backend")
            jax.config.update("jax_platforms", "cpu")
        churn = float(args.get("churn", "0.01"))
        res = run_jax_arena_bench(
            n=int(args.get("n", args.get("p", "16384"))),
            devices=int(suffix or 0),
            churn=churn,
            ticks=int(args.get("ticks", "3")),
        )
        headline = {
            "metric": f"jax_arena_cold_warm_{res['n']}x{res['n']}_"
                      f"churn{churn}_top{TOPK}",
            "platform": (
                f"jax {jax.devices()[0].platform} d{res['devices']}"
                + ("" if res["gen_sharded"] else " unsharded")
            ),
            "value": res["warm_median_ms"],
            "unit": "ms_per_warm_tick_median",
            **{k: v for k, v in res.items() if k != "n"},
        }
        out_path = args.get("out")
        if out_path:
            with open(out_path, "w") as fh:
                json.dump(headline, fh, indent=1)
                fh.write("\n")
            log(f"wrote {out_path}")
        print(json.dumps(headline))
        return
    if engine not in ("native", "native-mt"):
        raise SystemExit(
            f"unknown engine {engine!r} (want native|native-mt|jax[:D])"
        )
    threads = int(args.get("threads", "0") or 0)
    rng = np.random.default_rng(0)
    # engine=native-mt is an explicit request to measure the CPU engine:
    # skip the (120 s) accelerator probe and take the native path directly
    force_native = engine == "native-mt"
    fallback = force_native or not device_healthy()
    if fallback:
        if force_native:
            log("engine=native-mt requested: measuring the native CPU engine")
        else:
            log(
                "accelerator unreachable: falling back to CPU backend "
                "at reduced scale"
            )
        jax.config.update("jax_platforms", "cpu")
        # 16k: large enough that the greedy baseline's O(P*T) scan and
        # cost build bite, small enough that the whole fallback bench
        # stays ~1 min (the fused native engine solves it COMPLETE in ~1 s)
        P = T = 16384
        TILE = 1024
    log(f"devices: {jax.devices()}")
    log(f"building synthetic marketplace P={P} T={T}")
    ep = synth_providers(rng, P)  # numpy-backed, host-side
    er = synth_requirements(rng, T)

    # ---- CPU baseline FIRST (host backend, before the accelerator is
    # touched): cost matrix on the CPU backend, then the reference-equivalent
    # greedy matcher. Large device->host readbacks through the remote-TPU
    # tunnel are unreliable, so nothing below ever transfers more than a
    # scalar off the accelerator.
    log("computing cost matrix + greedy baseline on host CPU...")
    cpu = jax.devices("cpu")[0]
    cost_fn = jax.jit(lambda e, r: cost_matrix(e, r, CostWeights())[0])
    cost_build_time = 0.0
    with jax.default_device(cpu):
        cost_np = np.asarray(cost_fn(ep, er))
        if fallback:
            # timed second build (cheap at fallback scale) for the fair
            # end-to-end comparison; the healthy path never rebuilds the
            # multi-GB tensor just to decorate a log line
            t0 = time.perf_counter()
            cost_np = np.asarray(cost_fn(ep, er))
            cost_build_time = time.perf_counter() - t0
    _, cpu_time = cpu_greedy_baseline(cost_np)
    log(
        f"cpu greedy wall: {cpu_time * 1e3:.1f} ms "
        f"(+{cost_build_time * 1e3:.1f} ms cost build)"
    )

    # the native C++ engine: this framework's own CPU fallback backend
    # (TpuBatchMatcher(native_fallback=True) solves with it when the
    # accelerator is absent)
    native_time = None
    try:
        from protocol_tpu import native

        # the fused engine computes cost from the encoded features
        # internally — [P, T] never materializes (the degraded-mode twin of
        # the sparse TPU path's streaming candidates_topk)
        t0 = time.perf_counter()
        cand_p, cand_c = native.fused_topk_candidates(ep, er, CostWeights(), k=TOPK)
        p4t_native = native.auction_sparse(cand_p, cand_c, num_providers=P)
        native_time = time.perf_counter() - t0
        log(
            f"native C++ fused cost+topk+auction wall: {native_time * 1e3:.1f} ms "
            f"({int((p4t_native >= 0).sum())} assigned)"
        )
    except Exception as e:
        if force_native:
            # an explicit engine=native-mt request must never be silently
            # answered with a jax measurement labeled as something else
            raise SystemExit(f"engine=native-mt requested but the native "
                             f"engine is unavailable: {e}")
        log(f"native engine unavailable: {e}")

    if fallback and native_time is not None:
        # Degraded mode measures the path the framework ACTUALLY runs
        # without an accelerator: the fused native engine, end-to-end from
        # encoded features (its cost computation happens inside the kernel,
        # so each timed iteration pays the full cost+candidates+auction).
        iters = 5
        t0 = time.perf_counter()
        for _ in range(iters):
            cand_p, cand_c = native.fused_topk_candidates(
                ep, er, CostWeights(), k=TOPK
            )
            p4t_native = native.auction_sparse(cand_p, cand_c, num_providers=P)
        total = (time.perf_counter() - t0) / iters
        n_assigned = int((p4t_native >= 0).sum())
        # equal footing: both sides pay the cost-tensor build (the greedy
        # baseline above was handed a prebuilt matrix)
        baseline_total = cost_build_time + cpu_time
        log(
            f"native fallback end-to-end: {total * 1e3:.1f} ms/solve "
            f"({n_assigned / total:,.0f} assignments/s; greedy end-to-end "
            f"{baseline_total * 1e3:.1f} ms)"
        )
        # Platform provenance rides in a dedicated "platform" field, NOT
        # in the metric name: a provenance-suffixed name made the same
        # measurement land under different metric keys depending on the
        # host's accelerator health, corrupting cross-round joins over
        # the BENCH_r0*.json series. The metric NAME is stable.
        if engine == "native-mt":
            mt = bench_native_mt(ep, er, threads, iters, total)
            print(
                json.dumps(
                    {
                        "metric": (
                            f"sparse_top{TOPK}_{P}x{T}_native_mt_engine_"
                            "match_throughput"
                        ),
                        "platform": "native_cpu_engine_requested",
                        "value": round(mt["assigned"] / mt["wall_s"], 1),
                        "unit": "assignments/sec",
                        "vs_baseline": round(baseline_total / mt["wall_s"], 2),
                        "threads": mt["threads"],
                        "vs_single_thread": round(total / mt["wall_s"], 2),
                        "bit_identical_to_threads1": mt["bit_identical"],
                    }
                )
            )
            return
        print(
            json.dumps(
                {
                    "metric": (
                        f"sparse_top{TOPK}_{P}x{T}_native_engine_match_"
                        "throughput"
                    ),
                    "platform": "native_cpu_fallback_accelerator_unreachable",
                    "value": round(n_assigned / total, 1),
                    "unit": "assignments/sec",
                    "vs_baseline": round(baseline_total / total, 2),
                }
            )
        )
        return
    del cost_np

    # ---- TPU path: ship features (O(P+T) bytes), compile, time
    accel = jax.devices()[0]
    ep = jax.tree.map(lambda x: jax.device_put(x, accel), ep)
    er = jax.tree.map(lambda x: jax.device_put(x, accel), er)
    log("compiling + warmup...")
    p4t, n_assigned = tpu_match(ep, er)
    n_assigned = int(n_assigned)
    log(f"warmup done, assigned {n_assigned}/{T}")

    iters = 5
    t0 = time.perf_counter()
    for i in range(iters):
        # distinct salt per iteration: without it the axon client replays
        # memoized results and the "measurement" times nothing (see
        # salt_providers). int(na) is the completion barrier: the axon
        # client defers execution, and block_until_ready returns without
        # running anything — only a value readback forces the solve.
        p4t, na = tpu_match(salt_providers(ep, i + 1), er)
        n_assigned = int(na)
    tpu_time = (time.perf_counter() - t0) / iters
    log(f"tpu full-match wall: {tpu_time * 1e3:.1f} ms  ({n_assigned / tpu_time:,.0f} assignments/s)")

    value = n_assigned / tpu_time
    # stable metric name; provenance in the "platform" field (see the
    # degraded-mode emitters above for why)
    platform = jax.devices()[0].platform + (
        "_fallback_accelerator_unreachable" if fallback else ""
    )
    print(
        json.dumps(
            {
                "metric": f"sparse_top{TOPK}_{P}x{T}_auction_match_throughput",
                "platform": platform,
                "value": round(value, 1),
                "unit": "assignments/sec",
                "vs_baseline": round(cpu_time / tpu_time, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
