#!/usr/bin/env python
"""Ladder-#4 stage-B EXECUTION smoke at the full 1M x 1M shape.

Runs the task-sharded eps-ladder auction over an 8-device mesh on
[1M, 80] synthetic candidates — execution evidence (memory, collectives,
adaptive-frontier segments, wall at shape), complementing the
compile-time HBM envelope and the 65k real-feature completeness proof
(bench_scaling --full stage B2). Uniform-random candidates cover every
provider by construction, so near-complete assignment is expected; the
point is that the machinery RUNS at the north-star shape.

Measured 2026-07-30 (virtual 8-dev CPU mesh): 999,744/1,000,000
assigned, injective, 209 s wall.
"""
import sys; sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))
from protocol_tpu.utils.platform import force_host_cpu
force_host_cpu(8)
import numpy as np, time, jax
import jax.numpy as jnp
from protocol_tpu.parallel import assign_auction_sparse_scaled_sharded, make_mesh

# full ladder-#4 stage-B shape: T=1M tasks, K_eff=80 candidate columns,
# P=1M providers; synthetic (uniform-random) candidate structure — this
# exercises EXECUTION at shape (memory, collectives, segment machinery),
# not matching quality (bench_scaling B2 covers that at 65k with real
# features)
T = P = 1_000_000
K = 80
rng = np.random.default_rng(0)
t0 = time.time()
cand_p = rng.integers(0, P, size=(T, K), dtype=np.int32)
cand_c = rng.uniform(0.0, 10.0, size=(T, K)).astype(np.float32)
print(f"synth built {time.time()-t0:.1f}s ({cand_p.nbytes/1e6:.0f}+{cand_c.nbytes/1e6:.0f} MB)", flush=True)

mesh = make_mesh(8)
EPS_END = 1.0  # short ladder: execution proof, not matching quality
t0 = time.time()
res, price = assign_auction_sparse_scaled_sharded(
    jnp.asarray(cand_p), jnp.asarray(cand_c), num_providers=P, mesh=mesh,
    eps_start=4.0, eps_end=EPS_END,
    max_iters_per_phase=512,             # bounded rounds
    frontier=8192, frontier_ladder=True, with_prices=True,
)
wall = time.time() - t0
p4t = np.asarray(res.provider_for_task)
n = int((p4t >= 0).sum())
pos = p4t[p4t >= 0]
print(f"1M stage-B executed: {wall:.1f}s, {n}/{T} assigned in bounded rounds, "
      f"injective={np.unique(pos).size == pos.size}", flush=True)

# ---- the steady-state claim: 1% churn, warm re-solve from carried
# duals. The warm eps MUST match the cold ladder's end: carried prices
# are an eps_end-equilibrium, and a finer warm eps would unseat nearly
# every holder through the eps-CS repair (measured: a 0.02 warm against
# a 1.0 ladder ran as a near-cold fine solve, 1554 s).
from protocol_tpu.parallel import assign_auction_sparse_warm_sharded

p4t0 = jnp.asarray(p4t).at[: T // 100].set(-1)
t0 = time.time()
# bounded like the cold run (its unbounded default chases the last
# ~250 never-seatable-in-budget tasks for thousands of rounds — measured
# 856 s reaching 999,983; the steady-state question is the CHURN delta)
wres, _ = assign_auction_sparse_warm_sharded(
    jnp.asarray(cand_p), jnp.asarray(cand_c), num_providers=P, mesh=mesh,
    price0=price, p4t0=p4t0, eps=EPS_END, max_iters=1024,
    frontier=8192, frontier_ladder=True,
)
wall_w = time.time() - t0
wn = int((np.asarray(wres.provider_for_task) >= 0).sum())
print(f"1M WARM solve (1% churn, eps={EPS_END}): {wall_w:.1f}s, "
      f"{wn}/{T} assigned ({wall/max(wall_w,1e-9):.1f}x faster than cold)", flush=True)
