#!/usr/bin/env python
"""Regenerate protocol_tpu/proto/scheduler_pb2.py without protoc.

The container has no protoc / grpcio-tools, so the generated module is
produced from a programmatically-built FileDescriptorProto: this script
is the single source of truth for the wire contract (scheduler.proto is
the human-readable mirror — keep both in sync).

v1-compat invariant: the ProviderBatch / RequirementBatch / CostWeights /
AssignRequest / AssignResponse / HealthRequest / HealthResponse messages
and the Assign / Health methods must keep their field numbers, types and
names EXACTLY as shipped — old clients speak them against new servers.
New revisions may only append messages, fields, and RPCs.

Usage: python scripts/gen_scheduler_pb2.py   (writes the pb2 in place,
then import-checks it in a subprocess).
"""

import os
import subprocess
import sys

from google.protobuf import descriptor_pb2 as dp

F = dp.FieldDescriptorProto

OUT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "protocol_tpu", "proto", "scheduler_pb2.py",
)

PKG = "protocol_tpu.scheduler.v1"

# (name, number, type, repeated?, message type name)
_T = {
    "float": F.TYPE_FLOAT,
    "double": F.TYPE_DOUBLE,
    "int32": F.TYPE_INT32,
    "int64": F.TYPE_INT64,
    "uint32": F.TYPE_UINT32,
    "uint64": F.TYPE_UINT64,
    "bool": F.TYPE_BOOL,
    "string": F.TYPE_STRING,
    "bytes": F.TYPE_BYTES,
}


def _msg(fd, name, fields):
    m = fd.message_type.add()
    m.name = name
    for fname, num, ftype, rep in fields:
        f = m.field.add()
        f.name = fname
        f.number = num
        f.label = F.LABEL_REPEATED if rep else F.LABEL_OPTIONAL
        if ftype in _T:
            f.type = _T[ftype]
        else:  # message-typed field
            f.type = F.TYPE_MESSAGE
            f.type_name = f".{PKG}.{ftype}"
        # proto3 scalar repeated fields are packed by default; submessage
        # presence for optional message fields comes for free
    return m


def build_file() -> dp.FileDescriptorProto:
    fd = dp.FileDescriptorProto()
    fd.name = "protocol_tpu/proto/scheduler.proto"
    fd.package = PKG
    fd.syntax = "proto3"

    # ---------------- v1 (frozen: see module docstring) ----------------
    _msg(fd, "ProviderBatch", [
        ("gpu_count", 1, "int32", True),
        ("gpu_mem_mb", 2, "int32", True),
        ("gpu_model_id", 3, "int32", True),
        ("has_gpu", 4, "bool", True),
        ("has_cpu", 5, "bool", True),
        ("cpu_cores", 6, "int32", True),
        ("ram_mb", 7, "int32", True),
        ("storage_gb", 8, "int32", True),
        ("lat", 9, "float", True),
        ("lon", 10, "float", True),
        ("has_location", 11, "bool", True),
        ("price", 12, "float", True),
        ("load", 13, "float", True),
    ])
    _msg(fd, "RequirementBatch", [
        ("cpu_required", 1, "bool", True),
        ("cpu_cores", 2, "int32", True),
        ("ram_mb", 3, "int32", True),
        ("storage_gb", 4, "int32", True),
        ("max_gpu_options", 5, "uint32", False),
        ("model_words", 6, "uint32", False),
        ("gpu_opt_valid", 7, "bool", True),
        ("gpu_count", 8, "int32", True),
        ("gpu_mem_min", 9, "int32", True),
        ("gpu_mem_max", 10, "int32", True),
        ("gpu_total_mem_min", 11, "int32", True),
        ("gpu_total_mem_max", 12, "int32", True),
        ("gpu_model_mask", 13, "uint32", True),
        ("gpu_model_constrained", 14, "bool", True),
        ("lat", 15, "float", True),
        ("lon", 16, "float", True),
        ("has_location", 17, "bool", True),
        ("priority", 18, "float", True),
    ])
    _msg(fd, "CostWeights", [
        ("price", 1, "float", False),
        ("load", 2, "float", False),
        ("proximity", 3, "float", False),
        ("priority", 4, "float", False),
    ])
    _msg(fd, "AssignRequest", [
        ("providers", 1, "ProviderBatch", False),
        ("requirements", 2, "RequirementBatch", False),
        ("weights", 3, "CostWeights", False),
        ("kernel", 4, "string", False),
        ("top_k", 5, "uint32", False),
        ("eps", 6, "float", False),
        ("max_iters", 7, "uint32", False),
        ("warm_price", 8, "float", True),
        ("seed_provider_for_task", 9, "int32", True),
    ])
    _msg(fd, "AssignResponse", [
        ("provider_for_task", 1, "int32", True),
        ("task_for_provider", 2, "int32", True),
        ("num_assigned", 3, "uint32", False),
        ("solve_ms", 4, "float", False),
        ("price", 5, "float", True),
    ])
    _msg(fd, "HealthRequest", [])
    # v1 fields 1-3 frozen; 4 is a v2 addition old clients skip as unknown
    _msg(fd, "HealthResponse", [
        ("status", 1, "string", False),
        ("platform", 2, "string", False),
        ("device_count", 3, "uint32", False),
        ("seam_metrics", 4, "MetricSample", True),
    ])

    # ---------------- v2: tensor frames + session epochs ----------------
    _msg(fd, "TensorBlob", [
        ("data", 1, "bytes", False),      # C-order, little-endian
        ("dtype", 2, "string", False),    # numpy dtype name, e.g. "int32"
        ("shape", 3, "int64", True),
    ])
    _msg(fd, "NamedTensor", [
        ("name", 1, "string", False),
        ("tensor", 2, "TensorBlob", False),
    ])
    _msg(fd, "ProviderBatchV2", [
        ("columns", 1, "NamedTensor", True),
    ])
    _msg(fd, "RequirementBatchV2", [
        ("columns", 1, "NamedTensor", True),
    ])
    _msg(fd, "AssignRequestV2", [
        ("providers", 1, "ProviderBatchV2", False),
        ("requirements", 2, "RequirementBatchV2", False),
        ("weights", 3, "CostWeights", False),
        ("kernel", 4, "string", False),
        ("top_k", 5, "uint32", False),
        ("eps", 6, "float", False),
        ("max_iters", 7, "uint32", False),
        ("warm_price", 8, "TensorBlob", False),
        ("seed_provider_for_task", 9, "TensorBlob", False),
        # streaming sessions (appended — old servers skip them): a
        # session opened with stream_mode accepts event-typed
        # AssignDelta ticks (per-event localized repair instead of a
        # full warm solve) and reconciles with a full batch solve every
        # reconcile_every events (0 = server default)
        ("stream_mode", 10, "bool", False),
        ("reconcile_every", 11, "uint32", False),
    ])
    _msg(fd, "AssignResponseV2", [
        ("provider_for_task", 1, "TensorBlob", False),
        ("task_for_provider", 2, "TensorBlob", False),
        ("num_assigned", 3, "uint32", False),
        ("solve_ms", 4, "float", False),
        ("price", 5, "TensorBlob", False),
        ("decode_ms", 6, "float", False),
    ])
    # client-streamed snapshot: chunk 1 carries the header fields
    # (session_id, fingerprint, codec, total_bytes); every chunk carries a
    # byte range of the serialized (optionally gzipped) AssignRequestV2
    _msg(fd, "SnapshotChunk", [
        ("session_id", 1, "string", False),
        ("epoch_fingerprint", 2, "string", False),
        ("payload", 3, "bytes", False),
        ("codec", 4, "string", False),    # "" | "gzip"
        ("total_bytes", 5, "uint64", False),
    ])
    _msg(fd, "OpenSessionResponse", [
        ("ok", 1, "bool", False),
        ("error", 2, "string", False),
        ("session_id", 3, "string", False),
        ("epoch_fingerprint", 4, "string", False),
        ("result", 5, "AssignResponseV2", False),
    ])
    _msg(fd, "AssignDeltaRequest", [
        ("session_id", 1, "string", False),
        ("epoch_fingerprint", 2, "string", False),
        ("tick", 3, "uint64", False),
        ("provider_rows", 4, "TensorBlob", False),   # i32 row indices
        ("providers", 5, "ProviderBatchV2", False),  # churned rows only
        ("task_rows", 6, "TensorBlob", False),
        ("requirements", 7, "RequirementBatchV2", False),
        # event-typed delta rows (appended): a non-empty event_source
        # marks this delta as ONE churn event — full current row state
        # for its rows, with a per-source monotonic seq the server
        # dedups on (duplicate/superseded events ack without applying).
        # Only stream-mode sessions serve them.
        ("event_source", 8, "string", False),
        ("event_seq", 9, "uint64", False),
        ("event_kind", 10, "string", False),
    ])
    _msg(fd, "AssignDeltaResponse", [
        ("session_ok", 1, "bool", False),
        ("error", 2, "string", False),
        ("result", 3, "AssignResponseV2", False),
        # resilience surface (appended fields — old clients skip them):
        # stale=True marks a DEGRADED answer (the per-tick solve
        # deadline was burned, so the previous plan was served;
        # staleness_ticks counts how many ticks old it is), replayed=
        # True marks an idempotent retransmit answer (the delta was
        # already applied; this is the cached response, not a re-solve)
        ("stale", 4, "bool", False),
        ("staleness_ticks", 5, "uint32", False),
        ("replayed", 6, "bool", False),
        # streaming surface (appended): event_deduped=True acks a
        # duplicate/superseded event WITHOUT applying it (idempotence);
        # reconciled=True marks this answer as a fresh full-solve
        # reconciliation; gap_per_task is the certified optimality-gap
        # bound of the served plan; events_since_reconcile counts the
        # streamed divergence window
        ("event_deduped", 7, "bool", False),
        ("reconciled", 8, "bool", False),
        ("gap_per_task", 9, "float", False),
        ("events_since_reconcile", 10, "uint32", False),
    ])
    _msg(fd, "MetricSample", [
        ("name", 1, "string", False),
        ("value", 2, "double", False),
    ])

    # ---------------- dfleet: live session migration (admin) ----------
    # Drain this process's sessions onto another process: flush each
    # session's checkpoint journal, hand the journal off atomically to
    # the target's namespace, and answer subsequent deltas for the
    # moved sessions with a "moved:<endpoint>" redirect. Empty
    # session_ids = every live session (whole-process drain).
    _msg(fd, "MigrateRequest", [
        ("target_endpoint", 1, "string", False),
        ("target_proc_id", 2, "string", False),
        ("session_ids", 3, "string", True),
    ])
    _msg(fd, "MigrateResponse", [
        ("ok", 1, "bool", False),
        ("error", 2, "string", False),
        ("moved", 3, "uint32", False),
    ])

    svc = fd.service.add()
    svc.name = "SchedulerBackend"
    for name, inp, out, cstream in [
        ("Assign", "AssignRequest", "AssignResponse", False),
        ("Health", "HealthRequest", "HealthResponse", False),
        ("AssignV2", "AssignRequestV2", "AssignResponseV2", False),
        ("OpenSession", "SnapshotChunk", "OpenSessionResponse", True),
        ("AssignDelta", "AssignDeltaRequest", "AssignDeltaResponse", False),
        ("Migrate", "MigrateRequest", "MigrateResponse", False),
    ]:
        m = svc.method.add()
        m.name = name
        m.input_type = f".{PKG}.{inp}"
        m.output_type = f".{PKG}.{out}"
        m.client_streaming = cstream
    return fd


TEMPLATE = '''\
# -*- coding: utf-8 -*-
# Generated by scripts/gen_scheduler_pb2.py.  DO NOT EDIT BY HAND!
# source: protocol_tpu/proto/scheduler.proto
# (no protoc in the build environment: the serialized FileDescriptorProto
#  below is produced programmatically — regenerate with
#  `python scripts/gen_scheduler_pb2.py`)
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database

_sym_db = _symbol_database.Default()


DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile({blob})

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(
    DESCRIPTOR, 'protocol_tpu.proto.scheduler_pb2', globals()
)
'''


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--check", action="store_true",
        help="verify the committed pb2 matches this generator (CI drift "
        "gate): exit 1 without writing anything if they differ",
    )
    args = ap.parse_args()

    fd = build_file()
    blob = fd.SerializeToString()
    content = TEMPLATE.format(blob=repr(blob))
    if args.check:
        try:
            with open(OUT) as fh:
                committed = fh.read()
        except FileNotFoundError:
            committed = ""
        if committed != content:
            print(
                f"DRIFT: {OUT} does not match scripts/gen_scheduler_pb2.py "
                "— someone edited the generated file by hand, or changed "
                "the generator without regenerating. Run "
                "`python scripts/gen_scheduler_pb2.py` and commit.",
                file=sys.stderr,
            )
            return 1
        print(f"{OUT} is in sync with the generator")
        return 0
    with open(OUT, "w") as fh:
        fh.write(content)
    print(f"wrote {OUT} ({len(blob)} descriptor bytes)")
    # import-check in a clean interpreter (this process's descriptor pool
    # may already hold the previous revision of the file)
    code = (
        "from protocol_tpu.proto import scheduler_pb2 as pb;"
        "m = pb.AssignRequestV2();"
        "m.providers.columns.add().name = 'price';"
        "assert pb.AssignRequest().SerializeToString() == b'';"
        "print('pb2 import check OK:',"
        " len(pb.DESCRIPTOR.message_types_by_name), 'messages')"
    )
    return subprocess.run([sys.executable, "-c", code]).returncode


if __name__ == "__main__":
    sys.exit(main())
