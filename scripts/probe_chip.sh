#!/bin/bash
# Gentle chip-health probe: subprocess-guarded, timeout-and-abandon (SIGTERM at
# connect stage is safe: no kernel in flight until devices() returns).
LOG=${1:-/tmp/chip_health.log}
echo "=== probe $(date -u +%H:%M:%SZ) ===" >> "$LOG"
timeout 240 python -u /tmp/probe_chip.py >> "$LOG" 2>&1
echo "rc=$? at $(date -u +%H:%M:%SZ)" >> "$LOG"
tail -3 "$LOG"
