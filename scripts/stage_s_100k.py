"""BASELINE ladder #3 executed AT SHAPE: Sinkhorn-OT soft assignment at
P = T = 100,000, with assignment quality compared against the eps-scaled
auction on the SAME instance (VERDICT r4 item 5's done-bar).

Two engines:

  --engine blocked    matrix-free blocked JAX potentials (ops/blocked.py)
                      + plan-guided rounding. O(P*T) dense tile work per
                      iteration — ~10^10 cell updates per sweep at 100k,
                      which is what got the round-5 attempt killed at
                      rc=143 on the 1-core CPU host.
  --engine sparse-mt  the native O(nnz) sparse sinkhorn engine
                      (native.sinkhorn_sparse_mt): log-domain entropic OT
                      iterating ONLY over the top-K candidate edges
                      (nnz = T*K_eff ~ 8M at 100k vs 10^10 dense cells),
                      multi-threaded and bit-identical per thread count,
                      then INJECTIVE rounding by the sparse auction
                      referee seeded from the Sinkhorn duals. This is the
                      configuration that completes ladder #3 on the
                      declared CPU platform.

The [P, T] tensor would be 40 GB — every pipeline here is streaming /
sparse, and quality is measured pairwise via ops.cost.cost_pairs for the
same reason. Run:

    python scripts/stage_s_100k.py --cpu --engine sparse-mt \
        [--json-out artifacts/stage_s_100k_r08_sparse_mt.json]

Emits one JSON line per stage row (appended kill-proof to --artifact as
each completes), plus a summary JSON when --json-out is given.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true", help="force host CPU")
    ap.add_argument("--size", type=int, default=100_000)
    ap.add_argument("--tile", type=int, default=2500)
    ap.add_argument("--iters", type=int, default=20,
                    help="blocked engine: Sinkhorn iterations")
    ap.add_argument("--engine", choices=("blocked", "sparse-mt"),
                    default="blocked")
    ap.add_argument("--threads", type=int, default=0,
                    help="sparse-mt: native engine threads (0 = all)")
    ap.add_argument("--k", type=int, default=64,
                    help="sparse-mt: forward candidates per task")
    ap.add_argument("--sink-iters", type=int, default=50,
                    help="sparse-mt: iterations per anneal phase")
    ap.add_argument("--json-out", default="",
                    help="write the full summary dict here as JSON")
    ap.add_argument(
        "--artifact",
        default="artifacts/stage_s_rows.jsonl",
        help="JSONL file each stage row is APPENDED to as it completes "
        "(a timeout cannot erase finished stages — the r4/r5 artifact "
        "deaths left header-only logs). Empty string disables.",
    )
    args = ap.parse_args()

    from protocol_tpu.utils.artifacts import append_jsonl

    summary: dict = {"engine": args.engine, "size": args.size, "rows": []}

    def emit(row: dict) -> None:
        print(json.dumps(row), flush=True)
        append_jsonl(args.artifact, row)
        summary["rows"].append(row)

    if args.cpu:
        from protocol_tpu.utils.platform import force_host_cpu

        force_host_cpu(1)
    import jax
    import jax.numpy as jnp
    import numpy as np

    import bench
    from protocol_tpu.ops.cost import INFEASIBLE, CostWeights, cost_pairs

    P = T = args.size
    tile = args.tile
    assert T % tile == 0, f"tile {tile} must divide T {T}"
    platform = jax.devices()[0].platform
    weights = CostWeights()
    rng = np.random.default_rng(42)
    print(f"# stage S at shape: P=T={P} tile={tile} platform={platform} "
          f"engine={args.engine}",
          file=sys.stderr, flush=True)
    # numpy-backed encodings: the native sparse engine consumes them
    # directly; the jitted quality/blocked kernels accept them too
    ep = bench.synth_providers(rng, P)
    er = bench.synth_requirements(rng, T)

    def quality(p4t) -> dict:
        c = np.asarray(cost_pairs(ep, er, p4t, weights))
        p4t = np.asarray(p4t)
        ok = (p4t >= 0) & (c < INFEASIBLE * 0.5)
        pos = p4t[p4t >= 0]
        return {
            "assigned": int((p4t >= 0).sum()),
            "injective": bool(np.unique(pos).size == pos.size),
            "infeasible_pairs": int((p4t >= 0).sum() - ok.sum()),
            "mean_cost": round(float(c[ok].mean()), 4) if ok.any() else None,
        }

    if args.engine == "sparse-mt":
        from protocol_tpu import native

        # ---- candidate structure: fused feature->cost->top-k (bidir),
        # the same O(nnz) support every stage below iterates over
        t0 = time.perf_counter()
        cand_p, cand_c = native.fused_topk_candidates(
            ep, er, weights, k=args.k, reverse_r=8, extra=16,
            threads=args.threads,
        )
        t_cand = time.perf_counter() - t0
        feas = (cand_p >= 0) & (cand_c < INFEASIBLE * 0.5)
        nnz = int(feas.sum())
        print(f"# candidates done: {t_cand:.1f}s nnz={nnz}",
              file=sys.stderr, flush=True)
        emit({
            "stage": "S sparse-mt candidate generation (measured)",
            "platform": "native_cpu",
            "shape": f"P=T={P} k={args.k} K_eff={cand_p.shape[1]} nnz={nnz}",
            "wall_s": round(t_cand, 2),
        })

        # ---- entropic potentials: O(nnz) per iteration, eps-annealed,
        # per-phase wall-clock recorded (the acceptance evidence)
        phase_stats: list = []
        t0 = time.perf_counter()
        f, g = native.sinkhorn_sparse_anneal(
            cand_p, cand_c, P, eps_start=1.0, eps_end=0.05,
            iters_per_phase=args.sink_iters, tol=1e-2,
            threads=args.threads, phase_stats=phase_stats,
        )
        t_pot = time.perf_counter() - t0
        print(f"# potentials done: {t_pot:.1f}s "
              f"({sum(s['iters'] for s in phase_stats)} iters over "
              f"{len(phase_stats)} phases)", file=sys.stderr, flush=True)

        # ---- injective rounding: the sparse auction as referee, seeded
        # with the downshifted+capped dual prices (formula + soundness
        # argument live in native.sinkhorn_referee_prices)
        price0 = native.sinkhorn_referee_prices(f, cand_p, cand_c)
        t0 = time.perf_counter()
        p4t_s, _price, _retired = native.auction_sparse_mt(
            cand_p, cand_c, num_providers=P,
            eps_start=0.32, eps_end=0.02, threads=args.threads,
            price=price0,
        )
        t_round = time.perf_counter() - t0
        q_sink = quality(p4t_s)
        emit({
            "stage": "S sparse sinkhorn-mt at shape (measured)",
            "platform": "native_cpu",
            "shape": f"P=T={P} k={args.k} K_eff={cand_p.shape[1]} "
                     f"threads={args.threads or os.cpu_count()}",
            "cand_s": round(t_cand, 2),
            "potentials_s": round(t_pot, 2),
            "rounding_s": round(t_round, 2),
            "end_to_end_s": round(t_cand + t_pot + t_round, 2),
            "anneal_phases": phase_stats,
            **{f"sinkhorn_{k}": v for k, v in q_sink.items()},
        })

        # ---- the auction on the SAME candidates (quality referee):
        # candidate generation is shared, so this isolates the solver
        # comparison the mean-cost delta is measured over
        t0 = time.perf_counter()
        p4t_a, _, _ = native.auction_sparse_mt(
            cand_p, cand_c, num_providers=P, threads=args.threads,
        )
        t_auc = time.perf_counter() - t0
        q_auc = quality(p4t_a)
        delta_pct = (
            100.0 * (q_sink["mean_cost"] - q_auc["mean_cost"])
            / q_auc["mean_cost"]
            if q_sink["mean_cost"] and q_auc["mean_cost"] else None
        )
        emit({
            "stage": "S auction referee on the same candidates (measured)",
            "platform": "native_cpu",
            "shape": f"P=T={P} k={args.k} (shared candidate structure)",
            "solve_s": round(t_auc, 2),
            "sinkhorn_vs_auction_mean_cost_delta_pct": (
                round(delta_pct, 3) if delta_pct is not None else None
            ),
            "sinkhorn_assigned_frac": round(q_sink["assigned"] / T, 4),
            **{f"auction_{k}": v for k, v in q_auc.items()},
        })
        summary["sinkhorn_vs_auction_mean_cost_delta_pct"] = (
            round(delta_pct, 3) if delta_pct is not None else None
        )
        summary["assigned_frac"] = round(q_sink["assigned"] / T, 4)
        if args.json_out:
            with open(args.json_out, "w") as fh:
                json.dump(summary, fh, indent=1)
            print(f"# wrote {args.json_out}", file=sys.stderr, flush=True)
        return

    # ---------------- blocked JAX engine (the historical path) ----------
    from protocol_tpu.ops.blocked import sinkhorn_potentials_blocked
    from protocol_tpu.ops.sparse import (
        assign_auction_sparse_scaled,
        candidates_topk,
        candidates_topk_bidir,
    )

    ep = jax.tree.map(jnp.asarray, ep)
    er = jax.tree.map(jnp.asarray, er)

    # ---- Sinkhorn potentials (the OT solve), computed ONCE and fed
    # into the plan-guided rounding directly — assign_sinkhorn_blocked
    # would recompute them, doubling the dominant O(P*T*iters) stage
    # (each iteration is two full [P, T] logsumexp passes: ~1 h/iter at
    # 100k on this 1-core host)
    eps_sink = 0.05
    t0 = time.perf_counter()
    u, v = sinkhorn_potentials_blocked(
        ep, er, weights, eps=eps_sink, num_iters=args.iters, tile=tile
    )
    jax.block_until_ready((u, v))
    t_pot = time.perf_counter() - t0
    print(f"# potentials done: {t_pot:.1f}s", file=sys.stderr, flush=True)

    # plan-guided candidates + auction rounding (the body of
    # ops.blocked.assign_sinkhorn_blocked, with u reused)
    t0 = time.perf_counter()
    offset = -eps_sink * jnp.where(u > -5e17, u, 0.0)
    cand_su, cand_sc = candidates_topk(
        ep, er, weights, k=32, tile=tile, provider_offset=offset
    )
    res_s = assign_auction_sparse_scaled(
        cand_su, cand_sc, num_providers=P, eps_start=1.0, eps_end=0.02
    )
    jax.block_until_ready(res_s.provider_for_task)
    t_sink = t_pot + (time.perf_counter() - t0)
    q_sink = quality(res_s.provider_for_task)
    emit({
        "stage": "S sinkhorn-OT at shape (measured)",
        "platform": platform,
        "shape": f"P=T={P} iters={args.iters} tile={tile} (potentials reused for rounding)",
        "potentials_s": round(t_pot, 2),
        "end_to_end_s": round(t_sink, 2),
        **{f"sinkhorn_{k}": v for k, v in q_sink.items()},
    })

    # ---- the auction on the SAME instance (quality referee) ----
    t0 = time.perf_counter()
    cp, cc = candidates_topk_bidir(
        ep, er, weights, k=64, tile=tile, reverse_r=8, extra=16
    )
    jax.block_until_ready((cp, cc))
    t_gen = time.perf_counter() - t0
    t0 = time.perf_counter()
    res_a = assign_auction_sparse_scaled(
        cp, cc, num_providers=P, frontier=8192
    )
    jax.block_until_ready(res_a.provider_for_task)
    t_solve = time.perf_counter() - t0
    q_auc = quality(res_a.provider_for_task)
    emit({
        "stage": "S auction referee on the same instance (measured)",
        "platform": platform,
        "shape": f"P=T={P} k=64 bidir",
        "gen_s": round(t_gen, 2),
        "solve_s": round(t_solve, 2),
        **{f"auction_{k}": v for k, v in q_auc.items()},
    })
    if args.json_out:
        with open(args.json_out, "w") as fh:
            json.dump(summary, fh, indent=1)


if __name__ == "__main__":
    main()
